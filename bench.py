"""Benchmark: PH on farmer, wall-clock to 1% relative gap.

Reference comparator: the one hard number the reference repo contains is
the 1000-scenario farmer EF solved by Gurobi 9.0 barrier in 2939.1 s
(reference paperruns/scripts/farmer/ef_1000_1000.out; BASELINE.md).
That run used crops_multiplier=1000; we solve the 1000-scenario farmer
with crops_multiplier=10 via PH to a verified 1% outer/inner gap — a
smaller per-scenario LP, so `vs_baseline` here is a protocol-level
comparator (same model family, same scenario count, same gap target),
not a like-for-like machine/size match.  The headline metric is
wall-clock seconds to 1% verified gap.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import time

import numpy as np


def main():
    from mpisppy_tpu.utils.platform import ensure_cpu_backend
    ensure_cpu_backend()
    import jax

    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.opt.ph import PH

    S = int(os.environ.get("BENCH_SCENS", 1000))
    mult = int(os.environ.get("BENCH_MULT", 10))
    on_tpu = jax.devices()[0].platform != "cpu"
    eps = 1e-5 if on_tpu else 1e-6

    b = farmer.build_batch(S, crops_multiplier=mult,
                           dtype=np.float32 if on_tpu else np.float64)
    opts = {"defaultPHrho": 1.0, "PHIterLimit": 200, "convthresh": 0.0,
            "pdhg_eps": eps, "pdhg_max_iters": 30000}
    ph = PH(opts, [f"scen{i}" for i in range(S)], batch=b)

    # warm up compiles (excluded: reference baseline excludes Gurobi
    # license/startup too)
    ph.Iter0()
    ph.ph_iteration()

    t0 = time.time()
    ph.clear_warmstart()
    ph.Iter0()
    outer = ph.trivial_bound
    gap = np.inf
    iters = 0
    while gap > 0.01 and iters < 200:
        ph.ph_iteration()
        iters += 1
        if iters % 5 == 0 or ph.conv < 1e-4:
            # implementable inner bound: evaluate the consensus xhat
            # with nonants FIXED (not the nonanticipativity-violating
            # per-scenario objectives)
            inner, feas = ph.evaluate_xhat(ph.root_xbar())
            outer = max(outer, ph.lagrangian_bound())
            if feas:
                gap = abs(inner - outer) / max(abs(inner), 1e-9)
    jax.block_until_ready(ph.state.x)
    wall = time.time() - t0
    if gap > 0.01:
        print(json.dumps({
            "metric": "farmer1000_ph_seconds_to_1pct_gap",
            "value": -1, "unit": "s", "vs_baseline": 0,
            "note": f"gap {gap:.4f} not closed in {iters} iters"}))
        return

    baseline_s = 2939.1  # Gurobi barrier, farmer EF-1000 (BASELINE.md)
    print(json.dumps({
        "metric": "farmer1000_ph_seconds_to_1pct_gap",
        "value": round(wall, 3),
        "unit": "s",
        "vs_baseline": round(baseline_s / wall, 2),
    }))


if __name__ == "__main__":
    main()

"""Benchmark: PH on farmer, wall-clock to a verified 1% relative gap.

Reference comparator: the one hard number the reference repo contains is
the 1000-scenario farmer EF solved by Gurobi 9.0 barrier in 2939.1 s
(reference paperruns/scripts/farmer/ef_1000_1000.out; BASELINE.md).
That run used crops_multiplier=1000; we solve the 1000-scenario farmer
with crops_multiplier=10 via PH to a verified 1% outer/inner gap — a
smaller per-scenario LP, so `vs_baseline` here is a protocol-level
comparator (same model family, same scenario count, same gap target),
not a like-for-like machine/size match.  The headline metric is
wall-clock seconds to 1% verified gap.

Bound validity (the round-2 failure was publishing polluted bounds):
  * outer = max(iter0 trivial bound, per-iteration Lagrangian bound).
    Farmer's batch carries all-finite implied variable boxes
    (models/farmer.py), so the PDHG dual objective equals the
    Lagrangian g(y) exactly for ANY dual iterate — valid without a
    convergence certificate (phbase.lagrangian_bound certify="auto").
    Iter0 itself runs certified (f64 fallback for f32 stragglers), so
    feasible mass is 1.0 or the run aborts (phbase.Iter0 hard-stop).
  * inner = expected objective of the consensus candidate with nonants
    fixed, evaluated by the reduced second-stage solve
    (spopt.evaluate_xhat): the objective at a primal-feasible point
    upper-bounds each subproblem regardless of dual convergence
    (feasibility within xhat_feastol, the FeasibilityTol analog).

Prints ONE json line:
{"metric", "value", "unit", "vs_baseline", "mfu", "iters_per_sec", ...}.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np


def _accelerator_alive(timeout_s=90):
    """Probe the accelerator backend in a SUBPROCESS with a timeout.

    The TPU plugin's device tunnel can wedge so that the first
    jax.devices() call blocks forever (observed: a dead axon tunnel
    hangs backend init even under JAX_PLATFORMS=cpu unless the plugin
    is deregistered first).  A hung bench records nothing; a CPU
    fallback records an honest number with "device": "cpu"."""
    if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
        return False
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); "
             "print(d[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s)
        return r.returncode == 0 and "cpu" not in r.stdout
    except (subprocess.TimeoutExpired, OSError):
        return False


def main():
    from mpisppy_tpu.utils.platform import ensure_cpu_backend
    if not _accelerator_alive():
        ensure_cpu_backend(force=True)
    else:
        ensure_cpu_backend()
    import jax

    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.opt.ph import PH

    on_tpu = jax.devices()[0].platform != "cpu"
    # full size on the accelerator; a smaller default on the CPU
    # fallback so a dead tunnel still yields a finished run (explicit
    # BENCH_SCENS always wins)
    fallback_sized = not on_tpu and "BENCH_SCENS" not in os.environ
    S = int(os.environ.get("BENCH_SCENS", 1000 if on_tpu else 250))
    mult = int(os.environ.get("BENCH_MULT", 10))
    # the 2939.1 s Gurobi baseline is the S=1000, crops_multiplier=10
    # protocol; any other size is a different instance and must not
    # report under the baseline metric's name or ratio
    at_baseline_size = (S == 1000 and mult == 10)

    b = farmer.build_batch(S, crops_multiplier=mult,
                           dtype=np.float32 if on_tpu else np.float64)
    opts = {
        "defaultPHrho": 1.0,          # measured best for this instance
        "PHIterLimit": 200,
        "convthresh": 0.0,
        "pdhg_eps": 1e-5,             # certified-bound tolerance
        "superstep_eps": 1e-4,        # loose PH subproblem solves
        "lagrangian_eps": 1e-4,       # outer bound: valid at ANY eps
        "pdhg_max_iters": 30000,
    }
    ph = PH(opts, [f"scen{i}" for i in range(S)], batch=b)

    # warm up compiles (excluded: reference baseline excludes Gurobi
    # license/startup too)
    ph.Iter0()
    ph.ph_iteration()
    ph.evaluate_xhat(ph.root_xbar())
    ph.lagrangian_bound()

    ph.clear_warmstart()
    ph.reset_solve_stats()
    t0 = time.time()
    ph.Iter0()
    outer = ph.trivial_bound
    gap = np.inf
    iters = 0
    while gap > 0.01 and iters < int(opts["PHIterLimit"]):
        ph.ph_iteration()
        iters += 1
        if iters % 2 == 0 or ph.conv < 1e-4:
            inner, feas = ph.evaluate_xhat(ph.root_xbar())
            outer = max(outer, ph.lagrangian_bound())
            if feas:
                gap = abs(inner - outer) / max(abs(inner), 1e-9)
    jax.block_until_ready(ph.state.x)
    wall = time.time() - t0
    stats = ph.solve_stats()
    extra = {
        "iters": iters,
        "iters_per_sec": round(iters / wall, 3),
        "mfu": (round(stats["mfu"], 6) if stats["mfu"] is not None
                else None),
        "kernel_tflops": round(stats["flops"] / 1e12, 3),
        "device": stats["device"],
        "scens": S,
        "crops_multiplier": mult,
    }
    if fallback_sized:
        extra["note_size"] = (f"reduced size (S={S}): accelerator "
                              "unavailable, CPU fallback")
    metric = ("farmer1000_ph_seconds_to_1pct_gap" if at_baseline_size
              else "farmer_reduced_ph_seconds_to_1pct_gap")
    if gap > 0.01:
        print(json.dumps({
            "metric": metric,
            "value": -1, "unit": "s", "vs_baseline": 0,
            "note": f"gap {gap:.4f} not closed in {iters} iters",
            **extra}))
        return

    baseline_s = 2939.1  # Gurobi barrier, farmer EF-1000 (BASELINE.md)
    vs = round(baseline_s / wall, 2) if at_baseline_size else 0
    print(json.dumps({
        "metric": metric,
        "value": round(wall, 3),
        "unit": "s",
        "vs_baseline": vs,
        "gap": round(float(gap), 5),
        **extra}))


if __name__ == "__main__":
    main()

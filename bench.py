"""Benchmark: PH on farmer, wall-clock to a verified 1% relative gap.

Reference comparator: the one hard number the reference repo contains is
the 1000-scenario farmer EF solved by Gurobi 9.0 barrier in 2939.1 s
(reference paperruns/scripts/farmer/ef_1000_1000.out; BASELINE.md).
That run is S=1000 at crops_multiplier=1000 — 11,998,000 rows x
15,000,000 cols, ~12,000 rows x 15,000 vars PER SCENARIO.  Only a run
at that size (the split-native ir.SplitA batch; dense would be ~288 GB)
reports a nonzero `vs_baseline`.  Any smaller instance (the CPU
fallback's crops_multiplier=10, or a reduced-S landing) is a DIFFERENT
problem and reports under the `farmer_reduced_*` metric name with
vs_baseline 0 — dividing a small-instance wall-clock by Gurobi's
large-instance wall-clock is not a speedup.  The headline metric is
wall-clock seconds to 1% verified gap.

Bound validity (the round-2 failure was publishing polluted bounds):
  * outer = max(iter0 trivial bound, per-iteration Lagrangian bound).
    Farmer's batch carries all-finite implied variable boxes
    (models/farmer.py), so the PDHG dual objective equals the
    Lagrangian g(y) exactly for ANY dual iterate — valid without a
    convergence certificate (phbase.lagrangian_bound certify="auto").
    Iter0 itself runs certified (f64 fallback for f32 stragglers), so
    feasible mass is 1.0 or the run aborts (phbase.Iter0 hard-stop).
    (Exception: the UC bench path downgrades that hard stop to a
    warning plus an iter0_feas_mass JSON field — UC is structurally
    feasible by construction, its bounds are validated independently,
    and a PDHG stall on degenerate ramping rows must not forfeit the
    run; see worker_uc.)
  * inner = expected objective of the consensus candidate with nonants
    fixed, evaluated by the reduced second-stage solve
    (spopt.evaluate_xhat): the objective at a primal-feasible point
    upper-bounds each subproblem regardless of dual convergence
    (feasibility within xhat_feastol, the FeasibilityTol analog).

HANG-PROOFING (the accelerator tunnel is single-client and wedges
transiently — observed rounds 1-3; it can wedge BETWEEN a successful
probe and the next backend init):
  * the top-level process never initializes jax at all;
  * it probes the accelerator in fresh subprocesses, retrying every
    BENCH_PROBE_WAIT seconds until BENCH_PROBE_DEADLINE (default 40%
    of the TPU budget) — the r4 fixed-try window gave up on a
    transient wedge the chip later recovered from;
  * the measured run itself executes in a subprocess under a hard
    timeout (BENCH_TPU_TIMEOUT); if that subprocess hangs or dies
    without printing the JSON line, the bench falls back to a CPU run
    at reduced size — so ONE json line is always produced.

Prints ONE json line:
{"metric", "value", "unit", "vs_baseline", "mfu", "iters_per_sec",
 "certify_s", ...}.
"""

import json
import os
import subprocess
import sys
import time

_PROBE_SRC = """
import jax
d = jax.devices()
import jax.numpy as jnp
x = jnp.ones((256, 256), jnp.float32)
y = (x @ x).block_until_ready()   # the tunnel must carry real compute
print(d[0].platform, float(y[0, 0]))
"""


def _probe_once(timeout_s):
    """Probe the accelerator in a SUBPROCESS with a timeout.  The TPU
    plugin's device tunnel can wedge so the first jax.devices() call
    blocks forever; a subprocess hang dies alone.

    Tri-state verdict — the retry loop needs to tell a TRANSIENT wedge
    from a box that can never produce an accelerator:
      "up"    probe ran on an accelerator backend;
      "cpu"   probe ran FINE but only a CPU backend exists — retrying
              cannot change this (r05 burned 6 probes / ~15 min here);
      "down"  probe hung/crashed — transient, worth retrying."""
    try:
        r = subprocess.run([sys.executable, "-c", _PROBE_SRC],
                           capture_output=True, text=True,
                           timeout=timeout_s)
        lines = r.stdout.strip().splitlines()
        if r.returncode == 0 and lines:
            return "cpu" if lines[-1].startswith("cpu") else "up"
        return "down"
    except (subprocess.TimeoutExpired, OSError):
        return "down"


def _probe_cache_path():
    """Cache file for TERMINAL probe verdicts.  BENCH_PROBE_CACHE
    overrides the location; "0" (or empty) disables caching."""
    p = os.environ.get("BENCH_PROBE_CACHE")
    if p == "0" or p == "":
        return None
    if p:
        return p
    import tempfile
    return os.path.join(tempfile.gettempdir(),
                        "mpisppy_tpu_bench_probe.json")


def _probe_cache_key():
    """The backend-environment fingerprint a cached verdict is valid
    for: anything that could change which backend jax discovers."""
    keys = ("JAX_PLATFORMS", "PJRT_DEVICE", "TPU_NAME",
            "TPU_WORKER_ID", "CLOUD_TPU_TASK_ID")
    return "|".join(f"{k}={os.environ.get(k, '')}" for k in keys)


def _probe_cache_get():
    path = _probe_cache_path()
    if path is None:
        return None
    try:
        with open(path) as f:
            return json.load(f).get(_probe_cache_key())
    except (OSError, ValueError):
        return None


def _probe_cache_put(verdict):
    path = _probe_cache_path()
    if path is None:
        return
    try:
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            data = {}
        data[_probe_cache_key()] = {"verdict": verdict,
                                    "ts": time.time()}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, path)
    except OSError:
        pass


def _fight_for_chip(deadline):
    """Probe until `deadline` (time.time() value): the tunnel wedges
    TRANSIENTLY (round 2 got through; rounds 1/3 gave up after one
    probe; round 4's 4-try/8-min window also gave up while the tunnel
    came back later).  The bench fights for the chip for the whole
    probe budget — but ONLY against transient failures: a healthy
    probe that lands on CPU means no accelerator can ever appear, so
    the first such probe ends the fight (the r05 fix), and
    MPISPPY_TPU_BENCH_SKIP_PROBE=1 skips probing entirely (CI boxes
    that know they have no chip go straight to the CPU path).

    TERMINAL verdicts ("cpu": the box can never produce an
    accelerator; "up": a chip answered) are PERSISTED to a small cache
    file keyed on the backend environment, so repeated bench runs on
    the same box don't re-burn the ~930s probe budget re-discovering
    the same CPU fallback (r05 spent 6 failed probes there).  "down"
    (transient) is never cached.  BENCH_PROBE_CACHE=0 disables;
    MPISPPY_TPU_BENCH_SKIP_PROBE=1 still overrides everything.
    Returns (alive, attempts)."""
    if os.environ.get("MPISPPY_TPU_BENCH_SKIP_PROBE") == "1":
        return False, 0
    if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
        return False, 0
    cached = _probe_cache_get()
    if cached is not None and cached.get("verdict") in ("cpu", "up"):
        v = cached["verdict"]
        print(f"[bench] cached probe verdict '{v}' for this backend "
              f"env (BENCH_PROBE_CACHE=0 to re-probe)", file=sys.stderr)
        return v == "up", 0
    wait = float(os.environ.get("BENCH_PROBE_WAIT", 60))
    timeout_s = float(os.environ.get("BENCH_PROBE_TIMEOUT", 150))
    attempt = 0
    while True:
        attempt += 1
        verdict = _probe_once(
            min(timeout_s, max(deadline - time.time(), 5)))
        if verdict == "up":
            _probe_cache_put("up")
            return True, attempt
        if verdict == "cpu":
            print(f"[bench] probe {attempt} healthy but CPU-only: no "
                  f"accelerator on this box, skipping the remaining "
                  f"probe budget", file=sys.stderr)
            _probe_cache_put("cpu")
            return False, attempt
        remaining = deadline - time.time()
        print(f"[bench] accelerator probe {attempt} failed "
              f"({remaining:.0f}s of probe budget left)",
              file=sys.stderr)
        if remaining <= wait:
            return False, attempt
        time.sleep(wait)


def _run_worker(extra_env, timeout_s):
    """Run the measured bench body in a subprocess; return its JSON
    line (str) or None on hang/crash/no-output."""
    env = dict(os.environ, **extra_env)
    try:
        r = subprocess.run([sys.executable, os.path.abspath(__file__),
                            "--worker"],
                           capture_output=True, text=True,
                           timeout=timeout_s, env=env)
    except subprocess.TimeoutExpired:
        print("[bench] worker timed out", file=sys.stderr)
        return None
    except OSError as e:
        print(f"[bench] worker failed to start: {e}", file=sys.stderr)
        return None
    sys.stderr.write(r.stderr[-4000:])
    for ln in reversed(r.stdout.strip().splitlines()):
        if ln.startswith("{") and ln.endswith("}"):
            return ln
    return None


def _telemetry_extras(ph, profile_iters=2):
    """Phase-timing breakdown + window-traffic counters for the BENCH
    JSON (telemetry subsystem).  Runs OUTSIDE the timed window: after
    the measurement, a few extra PH iterations execute under the
    phased (unfused) superstep to attribute time to
    solve / xbar-psum / W-update / convergence.  BENCH_PHASES=0 skips
    the profile pass (e.g. when the phase-jit compiles would not fit
    the remaining budget); the traffic counters are reported either
    way (zeros for a bench run without a wheel)."""
    from mpisppy_tpu import telemetry

    out = {"window_traffic": telemetry.traffic_counters()}
    if os.environ.get("BENCH_PHASES", "1") == "0":
        return out
    prev = telemetry._active
    tel = telemetry.configure({"enabled": True, "phase_timing": True})
    saved_tel = ph._tel
    ph._tel = tel
    try:
        for _ in range(profile_iters):
            ph.ph_iteration()
        hists = ph._tel.registry.snapshot()["histograms"]
        out["phase_seconds"] = {
            k: round(hists[f"ph.phase.{k}_seconds"]["mean"], 6)
            for k in ("solve", "psum", "w_update", "conv")
            if hists.get(f"ph.phase.{k}_seconds", {}).get("mean")
            is not None}
    finally:
        ph._tel = saved_tel
        telemetry._active = prev
    return out


def worker_sslp():
    """BENCH_MODEL=sslp50: the BASELINE target row "sslp, 50-100 scen
    (LP relaxation) — same gap" (BASELINE.md; the reference publishes
    the protocol but no wall-clock, so vs_baseline is 0).  The
    PUBLISHED SIPLIB sslp_5_25_50 instance (50 scenarios — the
    instance's full scenario set), LP relaxation solved by ONE
    consensus-mode batched PDHG solve (opt/ef.ExtensiveForm — the
    native replacement for the reference's per-rank Gurobi cylinder
    stack) to a verified primal/dual gap.  PH on this family's LP
    stalls at mushy fractional consensus (the per-scenario optima are
    near-binary and disagree), so EF-mode IS the LP-relaxation
    protocol here; the integer story is the MIP-diving golden
    (tests/test_integer_goldens.py, SIPLIB optimum -121.6)."""
    import numpy as np

    from mpisppy_tpu.utils.platform import (enable_f64_if_cpu,
                                            ensure_cpu_backend)
    ensure_cpu_backend()
    import jax

    from mpisppy_tpu.models import sslp
    from mpisppy_tpu.opt.ef import ExtensiveForm

    on_tpu = not enable_f64_if_cpu()
    S = int(os.environ.get("BENCH_SCENS", 50))
    b = sslp.build_batch(S, instance="sslp_5_25",
                         dtype=np.float32 if on_tpu else np.float64)
    opts = {"pdhg_eps": 1e-5, "pdhg_max_iters": 200000}
    # compile warmup (excluded, same rule as the farmer worker)
    ExtensiveForm(opts, sslp.scenario_names_creator(S),
                  batch=b).solve_extensive_form()
    ef = ExtensiveForm(opts, sslp.scenario_names_creator(S), batch=b)
    t0 = time.time()
    ef.solve_extensive_form()
    jax.block_until_ready(ef._result.x)
    wall = time.time() - t0
    obj = ef.get_objective_value()
    dual = ef.get_dual_bound()
    gap = abs(obj - dual) / max(abs(obj), 1e-9)
    stats = ef.solve_stats()
    out = {
        "metric": f"sslp_5_25_{S}_lp_ef_seconds_to_1pct_gap",
        "value": round(wall, 3) if gap <= 0.01 else -1,
        "unit": "s", "vs_baseline": 0,
        "gap": round(float(gap), 6),
        "objective": round(float(obj), 3),
        "dual_bound": round(float(dual), 3),
        "mfu": (round(stats["mfu"], 6) if stats["mfu"] is not None
                else None),
        "kernel_dtype": stats["dtype"],
        "device": ("TPU" if on_tpu else "cpu"), "scens": S}
    if gap > 0.01:
        out["note"] = f"gap {gap:.4f} above 1%"
    print(json.dumps(out))


def worker_uc():
    """BENCH_MODEL=uc1000: the reference's larger_uc stretch instance —
    1000 wind scenarios, 21-unit fleet, 24 h — PH + Lagrangian +
    threshold-commitment recovery to a measured gap, riding the
    shared-A matmul path (ir.bmatvec; models/uc.py shared_A).  No
    reference wall-clock exists for this instance, so vs_baseline is 0;
    the JSON records gap, wall, MFU."""
    import numpy as np

    from mpisppy_tpu.utils.platform import (enable_f64_if_cpu,
                                            ensure_cpu_backend)
    ensure_cpu_backend()
    import jax

    from mpisppy_tpu.models import uc
    from mpisppy_tpu.opt.ph import PH

    on_tpu = not enable_f64_if_cpu()
    # CPU runs a smaller default (the metric name embeds S, same
    # honest-naming rule as the farmer fallback): the full-slot 1-opt
    # sweeps that close the commitment gap are stacked launches that
    # the single host core serializes
    S = int(os.environ.get("BENCH_SCENS", 1000 if on_tpu else 250))
    fm = int(os.environ.get("BENCH_UC_FLEET", 7 if on_tpu else 2))
    H = int(os.environ.get("BENCH_UC_HOURS", 24 if on_tpu else 6))
    iters = int(os.environ.get("BENCH_UC_ITERS", 25 if on_tpu else 10))
    sweeps = int(os.environ.get("BENCH_UC_SWEEPS", 8))

    t_start = time.time()

    def tic(msg):
        # phase trace on stderr (stdout carries only the JSON line);
        # the r4 first TPU attempt timed out opaquely at 45 min — this
        # is how the next one localizes the cost
        print(f"[uc +{time.time() - t_start:7.1f}s] {msg}",
              file=sys.stderr, flush=True)

    b = uc.build_batch(S, H=H, fleet_multiplier=fm,
                       dtype=np.float32 if on_tpu else np.float64)
    tic(f"batch built: S={S} units={3 * fm} H={H} "
        f"vars={b.num_vars} rows={b.num_rows}")
    # f32's KKT-residual floor on this instance sits ~1e-4 (degenerate
    # ramping/Pmin rows): demanding 1e-5 makes every solve ride to
    # max_iters and every scenario fail the 10*eps feasibility screen
    # (the first r4 TPU attempt reported feasible mass 0.009 for a
    # structurally-feasible model).  On f32 the protocol is eps=1e-4
    # with the 1e-3 feasibility screen — the xhat_feastol analog,
    # published in the JSON; the OUTER bound's validity never depends
    # on eps (dual objective valid at any iterate, all-finite boxes)
    eps0 = 1e-4 if on_tpu else 1e-5
    ph = PH({"defaultPHrho": 50.0, "PHIterLimit": iters,
             "convthresh": 0.0, "pdhg_eps": eps0,
             "superstep_eps": 1e-4, "lagrangian_eps": 1e-4,
             "pdhg_max_iters": 20000,
             # UC is structurally feasible by construction (load shed
             # absorbs any demand), so an iter0 straggler is solver
             # stall on degenerate ramping/Pmin rows, not an
             # infeasible scenario; the bench's published bounds are
             # validated independently (dual-side outer via all-finite
             # boxes, feasibility-checked xhat inner)
             "iter0_infeasibility_ok": True,
             # keep the f64 CPU fallback OFF the accelerator's critical
             # path: on TPU/f32 UC stalls a large straggler set at
             # iter0, and an uncapped host re-solve dominated (and
             # timed out) the first r4 TPU attempt.  Bounds stay valid
             # via the Ebound mask + the EF dual bound below.
             "iter0_certify": False,
             "certify_max_iters": 30000},
            [f"s{i}" for i in range(S)], batch=b)
    ph.Iter0()         # compile warmup
    ph.ph_iteration()
    ph.clear_warmstart()
    ph.reset_solve_stats()
    tic("warmup done (Iter0 + 1 iteration compiled)")
    t0 = time.time()
    ph.Iter0()
    tic("timed Iter0 done")
    outer = ph.trivial_bound
    for k in range(iters):
        ph.ph_iteration()
        if (k + 1) % 5 == 0:
            # the Lagrangian bound is valid at ANY dual iterate (UC's
            # boxes are all finite) and not monotone along the W path —
            # keep the best one seen, not just the final
            outer = max(outer, ph.lagrangian_bound())
            tic(f"PH iter {k + 1}/{iters} (+Lagrangian)")
    if iters == 0 or iters % 5:
        # final-W bound, unless the loop just computed it
        outer = max(outer, ph.lagrangian_bound())
    tic("PH loop done")
    xbar = np.asarray(ph.state.xbar)[0]
    cands = uc.commitment_candidates(b, xbar)
    objs, feas, mass = ph.evaluate_candidates(cands, return_mass=True)
    tic("threshold candidates screened; feas mass per candidate: "
        + " ".join(f"{m:.3f}" for m in mass))
    ok = np.flatnonzero(feas)
    inner, cfeas = (np.inf, False)
    if ok.size:
        best = cands[int(ok[np.argmin(objs[ok])])]
        # 1-opt local search over ALL commitment slots: full-slot
        # sweeps reach the S=50 oracle optimum (measured -0.03%),
        # while fractional-slot-only sweeps leave the incumbent at the
        # threshold value — the wrongly-committed slots are NOT the
        # fractional ones.  Sweeps launch bounded stacked chunks of
        # `chunk` flips x S scenarios (uc.one_opt_commitment; the CPU
        # size default keeps the serial host affordable).  This is the
        # slam/xhat-heuristic analog that pulls the recovered
        # commitment toward the MIP optimum.
        # screen/verify sweeps (uc.one_opt_commitment screen_*): rank
        # flips at loose eps under a bounded PDHG budget, certify the
        # top-ranked with the accurate evaluator.  Every acceptance is
        # gated by the accurate evaluator; termination is the bounded
        # criterion documented in one_opt_commitment (top 3*verify_k
        # ranks of a full sweep), ~10x cheaper per sweep at scale
        best, inner = uc.one_opt_commitment(
            ph, b, best, max_sweeps=sweeps,
            screen_eps=3e-3, screen_cap=2000)
        tic(f"one-opt sweeps done ({sweeps} max)")
        cfeas = bool(np.isfinite(inner))
    jax.block_until_ready(ph.state.x)
    wall = time.time() - t0
    stats = ph.solve_stats()
    if not cfeas:
        # an infeasible recovery must not report a gap/incumbent
        print(json.dumps({
            "metric": f"uc{S}_ph_seconds_to_recovered_commitment",
            "value": -1, "unit": "s", "vs_baseline": 0,
            "note": "no feasible commitment candidate",
            "device": stats["device"], "scens": S}))
        return
    # one consensus-EF LP solve, OUTSIDE the timed window (the metric
    # times commitment recovery; this solve only VERIFIES it) — most
    # of the first r4 artifact's 17.7% "gap" was bound slack, not
    # incumbent slack (the instance's true integrality gap is ~2.8%).
    # Its cost is reported as ef_bound_s.
    from mpisppy_tpu.opt.ef import ef_dual_bound
    from mpisppy_tpu.resilience import wheel_counters
    ef_b, ef_bound_s = ef_dual_bound(b, ph.all_scenario_names)
    tic(f"EF dual bound done ({ef_bound_s:.1f}s)")
    outer = max(outer, ef_b)
    gap = (inner - outer) / max(abs(inner), 1e-9)
    print(json.dumps({
        "metric": f"uc{S}_ph_seconds_to_recovered_commitment",
        "value": round(wall, 3), "unit": "s", "vs_baseline": 0,
        "gap": round(float(gap), 5), "inner": round(float(inner), 2),
        "outer": round(float(outer), 2),
        "ef_dual_bound": round(float(ef_b), 2),
        "ef_bound_s": round(ef_bound_s, 3),
        "mfu": (round(stats["mfu"], 6) if stats["mfu"] is not None
                else None),
        "kernel_dtype": stats["dtype"],
        "hot_dtype": ph.pdhg_stats()["hot_dtype"],
        "promotions_total": ph.pdhg_stats()["promotions_total"],
        "kernel_tflops": round(stats["flops"] / 1e12, 3),
        "device": stats["device"], "scens": S, "units": 3 * fm,
        "hours": H, "certify_s": round(stats["certify_wall_s"], 3),
        "pdhg_eps": eps0, "xhat_feastol": 10 * eps0,
        # <1.0 means PDHG stalled on some scenarios at iter0 (solver
        # stall, not structural infeasibility — see the options
        # comment); the bounds above are valid regardless
        "iter0_feas_mass": round(
            getattr(ph, "iter0_feas_mass", 1.0), 4),
        "shared_A": bool(b.shared_A),
        **wheel_counters(ph),
        **_telemetry_extras(ph)}))


def _serve_chaos_row(opts, S, dtype):
    """Chaos-on replica-set phase of the serve bench: a 2-replica
    Router under replica_crash + slow_replica + poison_request with an
    open request load.  Returns the resilience fields for the serve
    JSON row — p50/p99 latency, hedge/shed traffic, breaker opens and
    replica restarts — so the bench records what degradation under
    chaos actually costs, not just the sunny-day throughput."""
    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.serve.router import Router

    n_req = int(os.environ.get("BENCH_SERVE_CHAOS_REQUESTS", 8))
    router = Router({
        "serve_replicas": 2,
        "serve_max_batch": 1,
        "serve_restart_backoff": 0.01,
        "serve_restart_backoff_cap": 0.05,
        "router_tick": 0.01, "router_probe_interval": 0.02,
        "router_hedge_threshold": 1.0,
        "router_breaker_backoff": 0.05,
        "router_breaker_backoff_cap": 0.5,
        "router_drain_deadline": 0.3,
        "chaos": {"replica_crash": 1, "slow_replica": 0.02,
                  "poison_request": True, "chaos_replica": 0},
    }).start()
    try:
        batch = farmer.build_batch(S, dtype=dtype)
        handles = []
        for i in range(n_req):
            handles.append(router.submit(
                batch, opts, model="farmer",
                idempotency_key=f"bench{i}"))
            if i == n_req // 2:      # poison mid-stream
                handles.append(router.submit(
                    batch, dict(opts, chaos_poison=True),
                    model="farmer", idempotency_key="bench-poison"))
            time.sleep(0.05)
        results = [router.result(h, timeout=600) for h in handles]
        st = router.stats()
        counts = st["counts"]
        return {
            "chaos": "replica_crash+slow_replica+poison_request",
            "chaos_requests": len(handles),
            "chaos_ok": sum(r["status"] == "ok" for r in results),
            "chaos_quarantined": counts.get("quarantined", 0),
            "p50_latency_seconds": (round(st["p50"], 4)
                                    if st["p50"] is not None else -1),
            "p99_latency_seconds": (round(st["p99"], 4)
                                    if st["p99"] is not None else -1),
            "hedged_requests": counts.get("hedged_requests", 0),
            "shed_requests": (counts.get("shed_requests", 0)
                              + counts.get("shed_hedges", 0)),
            "breaker_opens": counts.get("breaker_opens", 0),
            "replica_restarts": st["replica_restarts"],
            "brownout_level_max": max(
                [lv for lv, _ in router.brownout_transitions],
                default=0),
        }
    finally:
        router.shutdown(timeout=10)


def worker_serve():
    """BENCH_MODEL=serve: replica-fleet throughput A/B, thread mode vs
    process mode (mpisppy_tpu/serve/procpool.py) on the same host and
    workload — concurrent same-bucket farmer requests through a Router
    with BENCH_SERVE_REPLICAS slots.  Thread replicas serialize device
    execution on the in-process `_BACKEND_LOCK`; process replicas each
    own a JAX runtime, so the fleet actually parallelizes — the
    headline `serve_throughput_req_per_sec` is the PROCESS-mode number
    and `vs_baseline`/`speedup_process_vs_thread` is the ratio over
    thread mode.  Both modes share one AOT artifact dir
    (MPISPPY_TPU_COMPILE_CACHE_DIR): the thread run populates it, the
    process workers `prewarm()` from it at boot — `proc_boot_seconds`
    and `aot_prewarm_hits` report that economics.  Each mode runs the
    full workload once untimed (warmup: compiles + AOT persistence
    excluded, same rule as the other workers), then once timed.
    The parallel win scales with `host_cpus`: process workers need
    cores to land on, so on a 1-core host both modes serialize on the
    one core and the ratio reflects only the wire overhead (~0.9-1.0);
    on an N-core host it approaches min(N, replicas).
    Unless BENCH_SERVE_CHAOS=0, a chaos-on phase runs the thread-mode
    Router under injected replica_crash/slow_replica/poison_request
    and merges its resilience counters into the same row."""
    import tempfile

    import numpy as np

    from mpisppy_tpu.utils.platform import (enable_f64_if_cpu,
                                            ensure_cpu_backend)
    ensure_cpu_backend()

    from mpisppy_tpu import telemetry
    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.serve.router import Router

    on_tpu = not enable_f64_if_cpu()
    S = int(os.environ.get("BENCH_SCENS", 3))
    n_req = int(os.environ.get("BENCH_SERVE_REQUESTS", 16))
    max_batch = int(os.environ.get("BENCH_SERVE_MAX_BATCH", 8))
    n_rep = int(os.environ.get("BENCH_SERVE_REPLICAS", 2))
    # convthresh 0 runs every request through the full PH schedule —
    # uniform, device-bound per-group cost, so the A/B measures
    # execution parallelism instead of early-convergence noise
    iters = int(os.environ.get("BENCH_SERVE_PH_ITERS", 200))
    opts = {"defaultPHrho": 1.0, "PHIterLimit": iters,
            "convthresh": 0.0, "pdhg_eps": 1e-6}
    chaos_opts = {"defaultPHrho": 1.0, "PHIterLimit": 50,
                  "convthresh": 1e-4, "pdhg_eps": 1e-6}
    dtype = np.float32 if on_tpu else np.float64
    aot_dir = tempfile.mkdtemp(prefix="bench_serve_aot_")
    prev_cache_dir = os.environ.get("MPISPPY_TPU_COMPILE_CACHE_DIR")
    os.environ["MPISPPY_TPU_COMPILE_CACHE_DIR"] = aot_dir

    batches = [farmer.build_batch(S, seedoffset=i, dtype=dtype)
               for i in range(n_req)]

    def run_mode(mode):
        router = Router({
            "serve_replicas": n_rep, "serve_replica_mode": mode,
            "serve_max_batch": max_batch,
            "serve_max_inflight": n_req + 8,
            # same batch-forming window in BOTH modes: without it,
            # wire submits trickle into the worker and dispatch as
            # odd-width groups, each width a fresh trace
            "serve_coalesce_window_s": 0.25,
            "router_hedge_threshold": None,
            "telemetry": True}).start()
        try:
            # untimed pass: trace/AOT-load every width this workload
            # hits, on every replica, so the timed pass is steady-state
            warm = [router.submit(b, opts, model="farmer",
                                  idempotency_key=f"warm-{mode}-{i}")
                    for i, b in enumerate(batches)]
            for h in warm:
                router.result(h, timeout=600)
            t0 = time.time()
            handles = [router.submit(b, opts, model="farmer",
                                     idempotency_key=f"run-{mode}-{i}")
                       for i, b in enumerate(batches)]
            results = [router.result(h, timeout=600) for h in handles]
            wall = time.time() - t0
            ok = sum(r["status"] == "ok" for r in results)
            return wall, ok, router.stats()
        finally:
            router.shutdown(timeout=30)

    try:
        wall_thr, ok_thr, st_thr = run_mode("thread")
        wall_proc, ok_proc, st_proc = run_mode("process")
    finally:
        if prev_cache_dir is None:
            os.environ.pop("MPISPPY_TPU_COMPILE_CACHE_DIR", None)
        else:
            os.environ["MPISPPY_TPU_COMPILE_CACHE_DIR"] = prev_cache_dir
    tput_thr = n_req / wall_thr
    tput_proc = n_req / wall_proc
    speedup = tput_proc / tput_thr
    cc_proc = st_proc["compile_cache"]
    hit_rate = st_thr["compile_cache"]["hits"] / max(
        st_thr["compile_cache"]["hits"]
        + st_thr["compile_cache"]["misses"], 1)
    boots = st_proc.get("proc_boot_seconds") or [0.0]
    counters = telemetry.serve_counters()
    out = {
        "metric": "serve_throughput_req_per_sec",
        "value": round(tput_proc, 3) if ok_proc == n_req else -1,
        "unit": "req/s", "vs_baseline": round(speedup, 3),
        "serve_throughput_req_per_sec": round(tput_proc, 3),
        "serve_throughput_req_per_sec_thread": round(tput_thr, 3),
        "speedup_process_vs_thread": round(speedup, 3),
        "replica_mode": "process", "replicas": n_rep,
        "proc_boot_seconds": round(max(boots), 3),
        "aot_prewarm_hits": int(cc_proc.get("aot_prewarm_hits", 0)),
        "proc_prewarm_loaded": int(st_proc.get("prewarm_loaded", 0)),
        "compile_cache_hit_rate": round(hit_rate, 4),
        "requests": n_req, "ok": ok_proc, "ok_thread": ok_thr,
        "wall_s": round(wall_proc, 3),
        "wall_s_thread": round(wall_thr, 3),
        "max_batch": max_batch, "scens": S,
        "device": ("TPU" if on_tpu else "cpu"),
        # the parallel win needs cores for the workers to land on: on
        # a 1-core host the A/B degenerates to serialized compute plus
        # wire overhead, and speedup_process_vs_thread sits near (or
        # below) 1.0 — read it against this field
        "host_cpus": len(os.sched_getaffinity(0)),
        **counters}
    if ok_proc != n_req or ok_thr != n_req:
        out["note"] = (f"{n_req - ok_proc} process / "
                       f"{n_req - ok_thr} thread request(s) not ok")
    if os.environ.get("BENCH_SERVE_CHAOS", "1") != "0":
        out.update(_serve_chaos_row(chaos_opts, S, dtype))
    print(json.dumps(out))


def worker_serve_net():
    """BENCH_MODEL=serve_net: the network front door end to end
    (mpisppy_tpu/serve/net/) — an A/B cold-start measurement of the
    disk-persisted AOT executables, then an open socket load through a
    real Gateway with concurrent wire clients.

    Phase 1 (AOT A/B): a fresh CompileCache traces + persists the
    batched superstep (`cold_start_seconds_trace`), then a second
    fresh cache — a process-restart stand-in — rebuilds the same
    bucket from the on-disk artifact (`cold_start_seconds`,
    `aot_cache_hits`).  Phase 2: BENCH_SERVE_NET_CLIENTS (default 8)
    threaded `Client`s solve over TCP against a 2-replica router
    (chaos-on unless BENCH_SERVE_CHAOS=0); the row records
    `p50/p99_latency_seconds` and `serve_throughput_req_per_sec` from
    the router's latency window plus the gateway byte/reject
    counters."""
    import tempfile
    import threading

    import numpy as np

    from mpisppy_tpu.utils.platform import (enable_f64_if_cpu,
                                            ensure_cpu_backend)
    ensure_cpu_backend()

    from mpisppy_tpu import telemetry
    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.opt.ph import PH
    from mpisppy_tpu.serve import compile_cache as cc
    from mpisppy_tpu.serve.net.client import Client
    from mpisppy_tpu.serve.net.gateway import Gateway
    from mpisppy_tpu.serve.router import Router
    from mpisppy_tpu.serve.service import stack_superstep_args

    on_tpu = not enable_f64_if_cpu()
    S = int(os.environ.get("BENCH_SCENS", 3))
    n_cli = int(os.environ.get("BENCH_SERVE_NET_CLIENTS", 8))
    opts = {"defaultPHrho": 1.0, "PHIterLimit": 50, "convthresh": 1e-4,
            "pdhg_eps": 1e-6}
    dtype = np.float32 if on_tpu else np.float64

    # -- phase 1: AOT persistence cold-start A/B ----------------------
    with tempfile.TemporaryDirectory(prefix="mtaot-bench-") as aot_dir:
        os.environ["MPISPPY_TPU_COMPILE_CACHE_DIR"] = aot_dir
        phs = []
        for _ in range(2):
            ph = PH(dict(opts), [f"s{i}" for i in range(S)],
                    batch=farmer.build_batch(S, dtype=dtype))
            ph.Iter0()
            phs.append(ph)
        args = stack_superstep_args(phs)

        import jax
        t0 = time.monotonic()
        exe = cc.CompileCache().get(
            phs[0].batch, opts, model="farmer").batched_superstep(args)
        jax.block_until_ready(exe(*args).conv)
        cold_trace = time.monotonic() - t0

        warm_cache = cc.CompileCache()
        t0 = time.monotonic()
        exe = warm_cache.get(
            phs[0].batch, opts, model="farmer").batched_superstep(args)
        jax.block_until_ready(exe(*args).conv)
        cold_warm = time.monotonic() - t0
        aot_hits = warm_cache.stats()["aot_loads"]
        del os.environ["MPISPPY_TPU_COMPILE_CACHE_DIR"]

    # -- phase 2: open socket load through the gateway ----------------
    chaos_on = os.environ.get("BENCH_SERVE_CHAOS", "1") != "0"
    r_opts = {
        "serve_replicas": 2, "serve_max_batch": 1,
        "serve_restart_backoff": 0.01,
        "serve_restart_backoff_cap": 0.05,
        "router_tick": 0.01, "router_probe_interval": 0.02,
        "router_hedge_threshold": 1.0,
        "router_breaker_backoff": 0.05,
        "router_breaker_backoff_cap": 0.5,
        "router_drain_deadline": 0.3,
        "telemetry": True,
    }
    if chaos_on:
        r_opts["chaos"] = {"replica_crash": 1, "slow_replica": 0.02,
                           "chaos_replica": 0}
    gw = Gateway({"telemetry": True}, router=Router(r_opts).start())
    gw.start()
    host, port = gw.address
    outcomes = [None] * n_cli

    def one(i):
        with Client(host, port, request_timeout=600.0) as cli:
            outcomes[i] = cli.solve(
                farmer.build_batch(S, seedoffset=i, dtype=dtype), opts,
                timeout=600, model="farmer",
                idempotency_key=f"bench-net-{i}")

    t0 = time.time()
    threads = [threading.Thread(target=one, args=(i,), daemon=True)
               for i in range(n_cli)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    wall = time.time() - t0
    ok = sum(1 for r in outcomes
             if r is not None and r.get("status") == "ok")
    st = gw.router.stats()
    gw_counters = telemetry.gateway_counters()
    gw.shutdown()
    gw.router.shutdown(timeout=10)

    out = {
        "metric": "serve_net_throughput_req_per_sec",
        "value": round(n_cli / wall, 3) if ok == n_cli else -1,
        "unit": "req/s", "vs_baseline": 0,
        "serve_throughput_req_per_sec": round(n_cli / wall, 3),
        "p50_latency_seconds": (round(st["p50"], 4)
                                if st["p50"] is not None else -1),
        "p99_latency_seconds": (round(st["p99"], 4)
                                if st["p99"] is not None else -1),
        "cold_start_seconds": round(cold_warm, 4),
        "cold_start_seconds_trace": round(cold_trace, 4),
        "aot_cache_hits": aot_hits,
        "clients": n_cli, "ok": ok, "wall_s": round(wall, 3),
        "scens": S, "chaos": chaos_on,
        "replica_restarts": st["replica_restarts"],
        "device": ("TPU" if on_tpu else "cpu"),
        **gw_counters}
    if ok != n_cli:
        out["note"] = f"{n_cli - ok} request(s) not ok"
    print(json.dumps(out))


def worker_farmer_stream():
    """BENCH_MODEL=farmer_stream: StreamingPH over the streamed farmer
    universe — default S=1,000,000 scenarios, which NEVER materialize:
    blocks of BENCH_BLOCK (default 256) scenarios are built on demand
    from their global indices (models/farmer.scenario_block), double-
    buffered host->device, and solved as randomized-PH supersteps with
    the full-S dual weights host-resident (mpisppy_tpu/streaming/).
    The run stops when the BM/BPL sequential rule certifies a CI on
    the optimality gap of the consensus candidate (measured by
    ciutils.gap_estimators on fresh estimator samples) — `value` is
    the wall-clock to that certificate, -1 if the superstep budget ran
    out uncertified.  No reference comparator exists (the reference
    cannot load 1e6 farmer scenarios), so vs_baseline is 0.  The JSON
    carries the streaming-specific fields: sampled_scenarios (final
    active sample), blocks_per_superstep, prefetch_wait_seconds (~0
    when block loads fully overlap solves), ci_gap, and the stream.*
    telemetry counters."""
    from mpisppy_tpu.utils.platform import (enable_f64_if_cpu,
                                            ensure_cpu_backend)
    ensure_cpu_backend()

    from mpisppy_tpu import telemetry
    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.streaming import source_for_module
    from mpisppy_tpu.streaming.streaming_ph import StreamingPH

    on_tpu = not enable_f64_if_cpu()
    S = int(os.environ.get("BENCH_SCENS", 1_000_000))
    mult = int(os.environ.get("BENCH_MULT", 1))
    block = int(os.environ.get("BENCH_BLOCK", 256))
    iters = int(os.environ.get("BENCH_STREAM_ITERS", 60))
    rule = os.environ.get("BENCH_STREAM_RULE", "BM")
    telemetry.configure(True)
    src = source_for_module(
        farmer, S, {"crops_multiplier": mult, "split": True})
    opts = {
        "defaultPHrho": 1.0, "PHIterLimit": iters,
        "solver_eps": 1e-5, "superstep_eps": 1e-4,
        "pdhg_max_iters": 30000,
        "stream_block_size": block,
        "stream_check_every": int(
            os.environ.get("BENCH_STREAM_CHECK", 5)),
        "stopping_criterion": rule,
        # BM stop: continue while G > hprime*s + eps_prime; the
        # s-relative term does the work at farmer's ~1e5 objective
        # scale (an absolute eps alone would never fire).  CI upper
        # is h*s + eps — ~1-2% of the objective at certification.
        "BM_h": float(os.environ.get("BENCH_BM_H", 2.0)),
        "BM_hprime": float(os.environ.get("BENCH_BM_HPRIME", 0.35)),
        "BM_eps": float(os.environ.get("BENCH_BM_EPS", 200.0)),
        "crops_multiplier": mult,
        "telemetry": True,
    }
    sph = StreamingPH(opts, src, module=farmer)
    t0 = time.time()
    conv, eobj, trivial = sph.stream_main()
    wall = time.time() - t0
    st = sph.stream_stats()
    counters = telemetry.stream_counters()
    stats = sph.solve_stats()
    certified = sph.certified is not None
    out = {
        "metric": f"farmer_stream{S}_ph_seconds_to_certified_ci",
        "value": round(wall, 3) if certified else -1,
        "unit": "s", "vs_baseline": 0,
        "sampled_scenarios": st["sampled_scenarios"],
        "blocks_per_superstep": round(st["blocks_per_superstep"], 3),
        "prefetch_wait_seconds": round(st["prefetch_wait_seconds"], 4),
        "ci_gap": st["ci_gap"],
        "certified": certified,
        "stopping_criterion": rule,
        "supersteps": st["supersteps"],
        "block_width": st["block_width"],
        "peak_block_scens": st["peak_block_scens"],
        "sample_growth_events": st["sample_growth_events"],
        "blocks_loaded": st["blocks_loaded"],
        "scenarios_streamed": st["scenarios_streamed"],
        "eobj": round(float(eobj), 3),
        "trivial_bound_estimate": round(float(trivial), 3),
        "conv": round(float(conv), 6),
        "mfu": (round(stats["mfu"], 6) if stats["mfu"] is not None
                else None),
        "kernel_dtype": stats["dtype"],
        "device": stats["device"], "scens": S,
        "crops_multiplier": mult,
        **counters}
    if not certified:
        out["note"] = (f"uncertified after {st['supersteps']} "
                       f"supersteps (rule {rule})")
    print(json.dumps(out))


def worker_farmer_shard():
    """BENCH_MODEL=farmer_shard: StreamingPH over a DURABLE on-disk
    shard corpus (mpisppy_tpu/streaming/store.py) instead of the
    in-process generator — export the farmer universe once as
    checksummed fixed-width shard files, then stream sampled blocks
    back through the bounded readahead prefetcher with every read
    CRC+header validated.  Default S=4096 scenarios in shards of
    BENCH_SHARD_WIDTH (default 64); BENCH_SHARD_CHAOS=1 (default)
    additionally runs the four storage chaos modes (io_delay,
    io_error, shard_corrupt, shard_missing) and reports the degraded
    run's quarantine accounting.  `value` is the wall-clock to the
    certified CI of the HEALTHY run, -1 if uncertified.  The JSON
    carries the storage-specific fields: readahead_hit_rate,
    read_wait_seconds (time the gather actually blocked on disk),
    shards_quarantined, quarantined_frac (chaos run), and
    source_retries_total."""
    import shutil
    import tempfile

    from mpisppy_tpu.utils.platform import (enable_f64_if_cpu,
                                            ensure_cpu_backend)
    ensure_cpu_backend()
    enable_f64_if_cpu()

    from mpisppy_tpu import telemetry
    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.streaming import ShardSource
    from mpisppy_tpu.streaming.streaming_ph import StreamingPH

    S = int(os.environ.get("BENCH_SCENS", 4096))
    width = int(os.environ.get("BENCH_SHARD_WIDTH", 64))
    block = int(os.environ.get("BENCH_BLOCK", 256))
    iters = int(os.environ.get("BENCH_STREAM_ITERS", 60))
    rule = os.environ.get("BENCH_STREAM_RULE", "BM")
    telemetry.configure(True)

    corpus = tempfile.mkdtemp(prefix="farmer_shard_")
    t_export0 = time.time()
    farmer.export_corpus(corpus, S, shard_width=width)
    export_s = time.time() - t_export0

    def opts(**kw):
        o = {"defaultPHrho": 1.0, "PHIterLimit": iters,
             "solver_eps": 1e-5, "superstep_eps": 1e-4,
             "pdhg_max_iters": 30000,
             "stream_block_size": block,
             "stream_check_every": int(
                 os.environ.get("BENCH_STREAM_CHECK", 5)),
             "stopping_criterion": rule,
             "BM_h": float(os.environ.get("BENCH_BM_H", 2.0)),
             "BM_hprime": float(os.environ.get("BENCH_BM_HPRIME",
                                               0.35)),
             "BM_eps": float(os.environ.get("BENCH_BM_EPS", 200.0)),
             "telemetry": True}
        o.update(kw)
        return o

    try:
        src = ShardSource(corpus, depth=int(
            os.environ.get("BENCH_SHARD_DEPTH", 4)))
        sph = StreamingPH(opts(), src, module=farmer)
        t0 = time.time()
        conv, eobj, trivial = sph.stream_main()
        wall = time.time() - t0
        st = sph.stream_stats()
        counters = telemetry.storage_counters()
        stream_ctr = telemetry.stream_counters()
        stats = sph.solve_stats()
        certified = sph.certified is not None
        storage = st.get("storage", {})
        out = {
            "metric": f"farmer_shard{S}_ph_seconds_to_certified_ci",
            "value": round(wall, 3) if certified else -1,
            "unit": "s", "vs_baseline": 0,
            "corpus_export_seconds": round(export_s, 3),
            "shard_width": width,
            "n_shards": src.store.n_shards,
            "readahead_hit_rate": round(
                storage.get("readahead_hit_rate", 0.0), 4),
            "read_wait_seconds": round(
                storage.get("read_wait_seconds", 0.0), 4),
            "shards_quarantined": storage.get("shards_quarantined", 0),
            "quarantined_frac": storage.get("quarantined_frac", 0.0),
            "source_retries_total": stream_ctr["stream_source_retries"],
            "sampled_scenarios": st["sampled_scenarios"],
            "prefetch_wait_seconds": round(
                st["prefetch_wait_seconds"], 4),
            "ci_gap": st["ci_gap"],
            "certified": certified,
            "stopping_criterion": rule,
            "supersteps": st["supersteps"],
            "block_width": st["block_width"],
            "blocks_loaded": st["blocks_loaded"],
            "eobj": round(float(eobj), 3),
            "trivial_bound_estimate": round(float(trivial), 3),
            "conv": round(float(conv), 6),
            "kernel_dtype": stats["dtype"],
            "device": stats["device"], "scens": S,
            **counters}
        if not certified:
            out["note"] = (f"uncertified after {st['supersteps']} "
                           f"supersteps (rule {rule})")

        if os.environ.get("BENCH_SHARD_CHAOS", "1") != "0":
            # degraded rerun: all four storage chaos modes against the
            # SAME corpus — transient io faults must recover, the
            # corrupt/missing shards must quarantine, and the certified
            # CI must carry the lost-mass debit
            # fault the LAST TWO shards of the Iter0 sweep prefix so
            # the run provably hits them (faulting shards the sampler
            # never touches would inject nothing)
            n0 = min(S, 4 * block)
            hi_sid = max((n0 - 1) // width, 1)
            telemetry.configure(True)
            csrc = ShardSource(
                corpus, depth=4, max_shard_retries=2, backoff=0.01,
                max_quarantined_frac=0.5,
                chaos={"io_delay": 0.001, "io_error": 2,
                       "shard_corrupt": [hi_sid - 1],
                       "shard_missing": hi_sid})
            csph = StreamingPH(opts(n0min=n0), csrc, module=farmer)
            t1 = time.time()
            csph.stream_main(finalize=False)
            cst = csph.stream_stats()
            cstor = cst.get("storage", {})
            cert = csph.certified
            out.update({
                "chaos_wall_seconds": round(time.time() - t1, 3),
                "chaos_certified": cert is not None,
                "chaos_shards_quarantined": cstor.get(
                    "shards_quarantined", 0),
                "chaos_quarantined_frac": cstor.get(
                    "quarantined_frac", 0.0),
                "chaos_gap_debit": (round(cert["gap_debit"], 3)
                                    if cert else None),
                "chaos_read_retries": cstor.get("read_retries", 0),
            })
    finally:
        shutil.rmtree(corpus, ignore_errors=True)
    print(json.dumps(out))


def worker_wheel_mpmd():
    """BENCH_MODEL=wheel_mpmd: the device-resident MPMD wheel
    (mpisppy_tpu/mpmd/) — hub + Lagrangian + xhat cylinders on
    DISJOINT mesh slices.  The measured run uses the "collective"
    exchange backend (one fused all-gather + broadcast per staged
    superstep, mpmd/collective.py); a second A/B run with the
    per-pair "device" mailbox backend quantifies the fusion win.  On
    a CPU landing the fleet is faked to BENCH_MPMD_DEVICES (default
    8) virtual devices; on a multi-chip accelerator the real device
    list is sliced.  `value` is the wall-clock to the hub's certified
    gap termination (rel_gap) on the collective run, -1 if the
    iteration budget ran out first.  The JSON carries the
    MPMD-specific fields: n_slices, exchange_backend,
    exchange_latency_seconds / exchange_latency_seconds_device (the
    A/B pair, total exchange transfer time per backend),
    exchange_bytes_per_superstep, hub_overlap_fraction (share of hub
    wall-clock covered by concurrent spoke work on other slices),
    per-slice phase_seconds, bound-parity fields for the two
    backends, and the wheel.* telemetry counters.  A box with too few
    devices for even 1-device slices degrades to a single-slice
    seqlock wheel (no A/B) and says so in `note`."""
    ndev = int(os.environ.get("BENCH_MPMD_DEVICES", 8))
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        # must land before the first jax import in this process
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={ndev}"
        ).strip()
    from mpisppy_tpu.utils.platform import (enable_f64_if_cpu,
                                            ensure_cpu_backend)
    ensure_cpu_backend()
    import jax
    import numpy as np

    from mpisppy_tpu import telemetry
    from mpisppy_tpu.cylinders.hub import PHHub
    from mpisppy_tpu.cylinders.lagrangian_bounder import (
        LagrangianOuterBound)
    from mpisppy_tpu.cylinders.xhatshufflelooper_bounder import (
        XhatShuffleInnerBound)
    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.mpmd import MPMDWheel
    from mpisppy_tpu.opt.ph import PH
    from mpisppy_tpu.spin_the_wheel import WheelSpinner
    from mpisppy_tpu.utils.xhat_eval import Xhat_Eval

    on_tpu = not enable_f64_if_cpu()
    S = int(os.environ.get("BENCH_SCENS", 100))
    iters = int(os.environ.get("BENCH_ITERS", 40))
    rel_gap = float(os.environ.get("BENCH_REL_GAP", 1e-4))
    names = [f"scen{i}" for i in range(S)]
    base_opts = {"defaultPHrho": 1.0, "PHIterLimit": iters,
                 "convthresh": 0.0, "pdhg_eps": 1e-7,
                 "pdhg_max_iters": 30000, "telemetry": True}
    batch = farmer.build_batch(S)

    def run(backend):
        """One full wheel spin with a forced exchange backend, fresh
        telemetry; returns (spinner, wall_seconds, wheel_counters)."""
        telemetry.reset()
        telemetry.configure(True)
        hub_opts = {"rel_gap": rel_gap, "abs_gap": 1.0}
        if backend is not None:
            hub_opts["window_backend"] = backend
        hub_dict = {
            "hub_class": PHHub,
            "hub_kwargs": {"options": hub_opts},
            "opt_class": PH,
            "opt_kwargs": {"options": dict(base_opts),
                           "all_scenario_names": names, "batch": batch},
        }
        spoke_dicts = [
            {"spoke_class": LagrangianOuterBound,
             "spoke_kwargs": {"options": {}},
             "opt_class": PH,
             "opt_kwargs": {"options": dict(base_opts),
                            "all_scenario_names": names}},
            {"spoke_class": XhatShuffleInnerBound,
             "spoke_kwargs": {"options": {}},
             "opt_class": Xhat_Eval,
             "opt_kwargs": {"options": dict(base_opts),
                            "all_scenario_names": names}},
        ]
        if len(jax.devices()) >= len(spoke_dicts) + 1:
            ws = MPMDWheel(hub_dict, spoke_dicts)
        else:
            ws = WheelSpinner(hub_dict, spoke_dicts, mode="threads",
                              exchange_backend="seqlock")
        t0 = time.time()
        ws.spin()
        return ws, time.time() - t0, telemetry.wheel_counters()

    note = None
    mpmd_capable = len(jax.devices()) >= 3
    if not mpmd_capable:
        note = (f"{len(jax.devices())} device(s): too few for disjoint "
                "slices; single-slice seqlock wheel, no A/B")
    # measured run: the fused collective fabric (auto-selected on an
    # MPMD fleet; explicit so a future default change can't skew the
    # metric); baseline run: per-pair device mailboxes
    ws, wall, counters = run("collective" if mpmd_capable else None)
    dev_latency = None
    ab = {}
    if mpmd_capable:
        ws_d, wall_d, counters_d = run("device")
        dev_latency = counters_d["wheel_exchange_latency_seconds"]
        ab = {
            "exchange_latency_seconds_device": round(dev_latency, 6),
            "wall_seconds_device": round(wall_d, 3),
            "device_best_outer": round(float(ws_d.BestOuterBound), 3),
            "device_best_inner": round(float(ws_d.BestInnerBound), 3),
        }
    ob = float(ws.BestOuterBound)
    ib = float(ws.BestInnerBound)
    gap = abs(ib - ob) / max(1.0, abs(ib))
    certified = gap <= rel_gap
    plan = getattr(ws, "plan", None)
    coll_latency = counters["wheel_exchange_latency_seconds"]
    n_supersteps = counters.get("wheel_collective_exchanges", 0)
    out = {
        "metric": f"farmer{S}_wheel_mpmd_seconds_to_certified_gap",
        "value": round(wall, 3) if certified else -1,
        "unit": "s", "vs_baseline": 0,
        "n_slices": plan.n_slices if plan is not None else 1,
        "exchange_backend": getattr(ws, "exchange_backend_used", None)
        or "seqlock",
        "exchange_latency_seconds": round(coll_latency, 6),
        "exchange_bytes_per_superstep": round(
            counters["wheel_exchange_bytes"] / n_supersteps, 1)
        if n_supersteps else 0,
        **ab,
        "exchange_latency_ratio": round(coll_latency / dev_latency, 4)
        if dev_latency else None,
        "hub_overlap_fraction": round(
            getattr(ws, "hub_overlap_fraction", 0.0), 4),
        "phase_seconds": {
            k: round(v, 4)
            for k, v in getattr(ws, "slice_phase_seconds", {}).items()},
        "best_outer": round(ob, 3), "best_inner": round(ib, 3),
        "rel_gap": round(gap, 8), "certified": certified,
        "slices": plan.describe() if plan is not None else [],
        # elastic recovery (PR 10): reslices applied, devices the hub
        # reclaimed, and integrity-rejected window reads
        "reslice_events": len(getattr(
            getattr(ws, "supervisor", None), "reslice_log", ())),
        "devices_reclaimed": getattr(
            getattr(ws, "supervisor", None), "devices_reclaimed", 0),
        "corrupt_reads_total": int(np.asarray(getattr(
            ws.spcomm, "corrupt_reads", 0)).sum()),
        "device": jax.devices()[0].platform, "on_tpu": on_tpu,
        "scens": S, "iters": iters,
        **counters}
    if not certified:
        out["note"] = (f"gap {gap:.2e} > {rel_gap:g} after {iters} "
                       "hub iterations")
    if note:
        out["note"] = note if "note" not in out \
            else out["note"] + "; " + note
    print(json.dumps(out))


def worker():
    """The measured run (executes on whatever backend the env gives)."""
    model = os.environ.get("BENCH_MODEL", "farmer")
    if model == "uc1000":
        return worker_uc()
    if model == "sslp50":
        return worker_sslp()
    if model == "serve":
        return worker_serve()
    if model == "serve_net":
        return worker_serve_net()
    if model == "farmer_stream":
        return worker_farmer_stream()
    if model == "farmer_shard":
        return worker_farmer_shard()
    if model == "wheel_mpmd":
        return worker_wheel_mpmd()
    import numpy as np

    from mpisppy_tpu.utils.platform import (enable_f64_if_cpu,
                                            ensure_cpu_backend)
    ensure_cpu_backend()
    import jax
    import jax.numpy as jnp

    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.opt.ph import PH

    # f64 wherever the worker lands on CPU — including off-nominal
    # landings where the parent didn't inject JAX_ENABLE_X64 (direct
    # --worker runs, plugin degradation)
    on_tpu = not enable_f64_if_cpu()
    # On the accelerator the default is the TRUE baseline instance:
    # S=1000 at crops_multiplier=1000 (11,998,000 rows x 15,000,000
    # cols in the reference's EF formulation — the exact instance
    # behind the 2939.1 s Gurobi number).  It exists only split-native
    # (ir.SplitA; dense would be ~288 GB).  The CPU fallback defaults
    # to crops_multiplier=10 — a ~10,000x smaller kernel workload that
    # one host core can finish — and reports as farmer_reduced with
    # vs_baseline 0 (flagged via BENCH_NOTE_FALLBACK when the
    # orchestrator shrank it further).
    fallback_sized = not on_tpu and (
        "BENCH_SCENS" not in os.environ
        or os.environ.get("BENCH_NOTE_FALLBACK") == "1")
    S = int(os.environ.get("BENCH_SCENS", 1000))
    mult = int(os.environ.get("BENCH_MULT", 1000 if on_tpu else 10))
    # the 2939.1 s Gurobi baseline is the S=1000 crops_multiplier=1000
    # instance (reference paperruns/scripts/farmer/ef_1000_1000.out:10
    # — 11,998,000 rows); any other size is a DIFFERENT instance and
    # must not report under the baseline metric's name or ratio
    at_baseline_size = (S == 1000 and mult == 1000)

    b = farmer.build_batch(S, crops_multiplier=mult,
                           dtype=np.float32 if on_tpu else np.float64)
    opts = {
        "defaultPHrho": 1.0,          # measured best for this instance
        "PHIterLimit": 200,
        "convthresh": 0.0,
        "pdhg_eps": 1e-5,             # certified-bound tolerance
        "superstep_eps": 1e-4,        # loose PH subproblem solves
        "lagrangian_eps": 1e-4,       # outer bound: valid at ANY eps
        "pdhg_max_iters": 30000,
        # the SplitA prep is measured 4x faster on CPU f64; on the TPU
        # it is UNMEASURED (the r4 78 s headline ran the dense prep),
        # so the accelerator defaults to the measured configuration —
        # BENCH_SPLIT=1 opts in for A/B runs
        "no_split_prep": on_tpu and os.environ.get("BENCH_SPLIT") != "1",
    }
    if int(os.environ.get("BENCH_LAG_CAP", 0) or 0) > 0:
        # A/B knob: budget the Lagrangian bound solves (valid at any
        # iterate for farmer's all-finite boxes — costs tightness
        # only).  0/unset = uncapped.  Measured S=250 CPU: cheaper
        # checks but +6 iterations — a wash; kept as a tuning lever.
        opts["lagrangian_iters_cap"] = int(os.environ["BENCH_LAG_CAP"])
    if os.environ.get("BENCH_EPS_LADDER", "1") != "0":
        # inexactness ladder: early PH supersteps solve loosely (1e-3)
        # and tighten with the PH convergence metric down to the r05
        # static 1e-4 — never past that floor, so the late iterations
        # (and the certified bounds, which use pdhg_eps) are unchanged.
        # BENCH_EPS_LADDER=0 reverts to the static superstep_eps for
        # A/B runs.
        opts["eps_ladder"] = {"start": 1e-3, "min": 1e-4, "couple": 0.1}
    if float(os.environ.get("BENCH_COMPACT", 0) or 0) > 0:
        # opt-in converged-scenario compaction for the solve_loop
        # callers (Iter0 / xhat / Lagrangian); the fused PH superstep
        # is unaffected.  e.g. BENCH_COMPACT=0.5 halves the slab when
        # at most half the scenarios are still active.
        opts["pdhg_compact_threshold"] = float(os.environ["BENCH_COMPACT"])
    hot = os.environ.get("BENCH_HOT_DTYPE", "f32")
    if hot not in ("", "0", "off", "none", "f64"):
        # mixed-precision hot loop (default ON: f32).  The certified
        # bound solves request pdhg_eps=1e-5, below the f32 eps floor
        # (~1.2e-5), so they auto-PROMOTE to the full-precision pair
        # while the supersteps (1e-4 and looser) stay hot; the f64
        # certified re-solve path is precision-pinned regardless.
        # BENCH_HOT_DTYPE=off reverts to the r05 full-precision run.
        opts["pdhg_hot_dtype"] = hot
    if float(os.environ.get("BENCH_SPARSE", 0) or 0) > 0:
        # opt-in BCOO sparse shared-block matvecs for split preps:
        # e.g. BENCH_SPARSE=0.3 routes through jax.experimental.sparse
        # when the shared block is under 30% dense
        opts["pdhg_sparse_threshold"] = float(os.environ["BENCH_SPARSE"])
    ph = PH(opts, [f"scen{i}" for i in range(S)], batch=b)

    # warm up compiles (excluded: reference baseline excludes Gurobi
    # license/startup too).  Warmup runs at a HUGE eps so every solve
    # converges at its first KKT check: compile cost is identical (eps
    # is a traced arg), kernel cost ~0 — at baseline size a
    # full-accuracy warmup would cost as much as the timed run
    warm_eps = 1e6
    saved_eps = ph.solver_eps
    saved_ss = ph._superstep_eps_opt
    saved_lad = ph._ladder
    ph.solver_eps = jnp.asarray(warm_eps, b.c.dtype)
    ph._superstep_eps_opt = warm_eps
    ph._ladder = None  # the ladder eps would shadow the warmup eps
    ph.Iter0()
    ph.ph_iteration()
    ph.evaluate_xhat(ph.root_xbar())
    ph.lagrangian_bound(eps=warm_eps)
    ph.solver_eps = saved_eps
    ph._superstep_eps_opt = saved_ss
    ph._ladder = saved_lad

    ph.clear_warmstart()
    ph.reset_solve_stats()
    t0 = time.time()
    ph.Iter0()
    outer = ph.trivial_bound
    gap = np.inf
    iters = 0
    while gap > 0.01 and iters < int(opts["PHIterLimit"]):
        ph.ph_iteration()
        iters += 1
        # bound-check cadence: the Lagrangian solve costs ~4x a PH
        # iteration (no prox term -> no strong convexity), so while
        # the gap is far from the 1% target the bounds are checked
        # every 4 iterations; near the target every 2 (a late closure
        # detection costs 2 cheap iterations, a wasted check costs
        # one expensive Lagrangian solve)
        cadence = 2 if gap < 0.03 else 4
        if iters % cadence == 0 or ph.conv < 1e-4:
            inner, feas = ph.evaluate_xhat(ph.root_xbar())
            outer = max(outer, ph.lagrangian_bound())
            if feas:
                gap = abs(inner - outer) / max(abs(inner), 1e-9)
    jax.block_until_ready(ph.state.x)
    wall = time.time() - t0
    stats = ph.solve_stats()
    from mpisppy_tpu.resilience import wheel_counters
    extra = {
        "iters": iters,
        # resilience counters: 0/0 on a healthy run; nonzero when the
        # spoke supervisor restarted or pruned cylinders mid-bench
        **wheel_counters(ph),
        "iters_per_sec": round(iters / wall, 3),
        "mfu": (round(stats["mfu"], 6) if stats["mfu"] is not None
                else None),
        "kernel_tflops": round(stats["flops"] / 1e12, 3),
        "device": stats["device"],
        "scens": S,
        "crops_multiplier": mult,
        # cost of f64 certified re-solves inside the timed region
        # (VERDICT r3 item 2: must stay <10% of wall on the TPU path)
        "certify_s": round(stats["certify_wall_s"], 3),
        "certify_frac": round(stats["certify_wall_s"] / max(wall, 1e-9),
                              4),
    }
    # adaptive-work counters (ops/pdhg adaptive restarts, compaction,
    # eps ladder) for the timed region — spopt.pdhg_stats().  The
    # trajectory is compressed to its (width, active) change points so
    # the JSON line stays one line.
    ps = ph.pdhg_stats()
    traj = [t for i, t in enumerate(ps["active_fraction_traj"])
            if i == 0 or (t["width"], t["active"]) !=
            (ps["active_fraction_traj"][i - 1]["width"],
             ps["active_fraction_traj"][i - 1]["active"])]
    extra.update({
        "inner_iters": ps["inner_iters"],
        "restarts_total": ps["restarts_total"],
        "active_fraction_final": round(ps["active_fraction_final"], 4),
        "active_fraction_traj": traj,
        "flops_saved_tflops": round(ps["flops_saved"] / 1e12, 4),
        # precision/sparsity state of the timed region (PR 6)
        "hot_dtype": ps["hot_dtype"],
        "promotions_total": ps["promotions_total"],
        "shared_nnz_frac": (round(ps["shared_nnz_frac"], 6)
                            if ps["shared_nnz_frac"] is not None
                            else None),
        "kernel_dtype": stats["dtype"],
    })
    extra.update(_telemetry_extras(ph))
    if fallback_sized:
        extra["note_size"] = ("accelerator unavailable: CPU fallback "
                              f"at S={S} (f64)")
    # the baseline-size metric name carries the instance (S x mult):
    # only the 1000x1000 instance is the Gurobi comparator's problem.
    # S=10000 x mult=100 is BASELINE.md's own farmer-10k target row
    # (the scaledlw strong-scaling protocol shape at 10k scenarios);
    # no reference wall-clock exists for it, so vs_baseline stays 0.
    if at_baseline_size:
        metric = "farmer1000x1000_ph_seconds_to_1pct_gap"
    elif S == 10000 and mult == 100:
        metric = "farmer10k_ph_seconds_to_1pct_gap"
    else:
        metric = "farmer_reduced_ph_seconds_to_1pct_gap"
    if gap > 0.01:
        print(json.dumps({
            "metric": metric,
            "value": -1, "unit": "s", "vs_baseline": 0,
            "note": f"gap {gap:.4f} not closed in {iters} iters",
            **extra}))
        return

    baseline_s = 2939.1  # Gurobi barrier, farmer EF-1000 (BASELINE.md)
    vs = round(baseline_s / wall, 2) if at_baseline_size else 0
    print(json.dumps({
        "metric": metric,
        "value": round(wall, 3),
        "unit": "s",
        "vs_baseline": vs,
        "gap": round(float(gap), 5),
        **extra}))


def main():
    t_start = time.time()
    tpu_budget = float(os.environ.get("BENCH_TPU_TIMEOUT", 2700))
    deadline = t_start + tpu_budget
    # probing may spend up to this fraction of the TPU budget before
    # the bench concedes the chip (r4 gave up after ~8 min against a
    # transient wedge; now it keeps fighting but still leaves the
    # worker a majority share of the budget)
    probe_deadline = t_start + float(os.environ.get(
        "BENCH_PROBE_DEADLINE", 0.4 * tpu_budget))
    alive, attempts = _fight_for_chip(probe_deadline)
    line = None
    if alive:
        model = os.environ.get("BENCH_MODEL", "farmer")
        line = _run_worker({}, deadline - time.time())
        if (line is None and model == "farmer"
                and "BENCH_MULT" not in os.environ
                and deadline - time.time() > 300):
            # the true-size instance didn't finish in budget: retry
            # REDUCED on the still-alive chip (honestly named — the
            # worker reports farmer_reduced/vs_baseline 0 for it)
            print("[bench] baseline-size run produced no result; "
                  "retrying reduced size on accelerator",
                  file=sys.stderr)
            line = _run_worker({"BENCH_MULT": "10"},
                               deadline - time.time())
        if line is None:
            print("[bench] accelerator run produced no result; "
                  "falling back to CPU", file=sys.stderr)
    if line is None:
        cpu_timeout = float(os.environ.get("BENCH_CPU_TIMEOUT", 5400))
        line = _run_worker({"JAX_PLATFORMS": "cpu",
                            "JAX_ENABLE_X64": "1"}, cpu_timeout)
    if line is None and "BENCH_SCENS" not in os.environ \
            and os.environ.get("BENCH_MODEL", "farmer") == "farmer":
        # last resort (farmer only — sslp's published instance has
        # exactly 50 scenarios and uc already sizes per-backend):
        # reduced size so a constrained box still yields an honest
        # (differently-named) number
        line = _run_worker({"JAX_PLATFORMS": "cpu",
                            "JAX_ENABLE_X64": "1",
                            "BENCH_SCENS": "250",
                            "BENCH_NOTE_FALLBACK": "1"},
                           float(os.environ.get("BENCH_CPU2_TIMEOUT",
                                                1800)))
    if line is None:
        line = json.dumps({
            "metric": "farmer_reduced_ph_seconds_to_1pct_gap",
            "value": -1, "unit": "s", "vs_baseline": 0,
            "note": "no worker produced a result (hang/crash)",
            "probe_attempts": attempts})
    else:
        d = json.loads(line)
        d["probe_attempts"] = attempts
        line = json.dumps(d)
    print(line)


if __name__ == "__main__":
    if "--worker" in sys.argv:
        worker()
    else:
        main()

"""Build the documentation tree: validate + render to HTML.

The image has no sphinx, so this is a dependency-free builder:
  1. validates that every chapter listed in src/index.md exists, that
     every relative .md link in every chapter resolves, and that every
     repo path mentioned in prose tables exists;
  2. renders each chapter to doc/build/<name>.html with a minimal
     markdown converter (headers, fenced code, inline code, links,
     tables, lists, emphasis) — enough to read in a browser.

Usage:  python doc/build.py        (exit 0 = build OK)
"""

import html
import re
import sys
from pathlib import Path

SRC = Path(__file__).parent / "src"
OUT = Path(__file__).parent / "build"
REPO = Path(__file__).parent.parent

_CSS = """body{max-width:48rem;margin:2rem auto;padding:0 1rem;
font:16px/1.55 system-ui,sans-serif;color:#222}
code{background:#f2f2f2;padding:.1em .3em;border-radius:3px;
font-size:.92em}
pre{background:#f6f6f6;padding: .8em;overflow-x:auto;border-radius:6px}
pre code{background:none;padding:0}
table{border-collapse:collapse}td,th{border:1px solid #ccc;
padding:.3em .6em;text-align:left}
a{color:#0b63ce}h1,h2,h3{line-height:1.25}"""


def _inline(s):
    s = html.escape(s, quote=False)
    s = re.sub(r"`([^`]+)`", r"<code>\1</code>", s)
    s = re.sub(r"\[([^\]]+)\]\(([^)]+)\)",
               lambda m: '<a href="%s">%s</a>' % (
                   m.group(2).replace(".md", ".html"), m.group(1)), s)
    s = re.sub(r"\*\*([^*]+)\*\*", r"<strong>\1</strong>", s)
    s = re.sub(r"(?<![\w*])\*([^*\n]+)\*(?![\w*])", r"<em>\1</em>", s)
    return s


def render(md_text, title):
    out = ["<!doctype html><meta charset='utf-8'>",
           f"<title>{html.escape(title)}</title>",
           f"<style>{_CSS}</style>"]
    lines = md_text.split("\n")
    i, in_code, in_list, in_table = 0, False, False, False
    while i < len(lines):
        ln = lines[i]
        if ln.startswith("```"):
            if in_code:
                out.append("</code></pre>")
            else:
                out.append("<pre><code>")
            in_code = not in_code
            i += 1
            continue
        if in_code:
            out.append(html.escape(ln))
            i += 1
            continue
        if in_list and not ln.lstrip().startswith(("-", "*")) \
                and not ln.startswith("  "):
            out.append("</ul>")
            in_list = False
        if in_table and not ln.startswith("|"):
            out.append("</table>")
            in_table = False
        m = re.match(r"^(#{1,4})\s+(.*)", ln)
        if m:
            n = len(m.group(1))
            out.append(f"<h{n}>{_inline(m.group(2))}</h{n}>")
        elif ln.startswith("|"):
            cells = [c.strip() for c in ln.strip("|").split("|")]
            if all(re.fullmatch(r":?-+:?", c) for c in cells if c):
                pass          # separator row
            else:
                if not in_table:
                    out.append("<table>")
                    in_table = True
                    tag = "th"
                else:
                    tag = "td"
                out.append("<tr>" + "".join(
                    f"<{tag}>{_inline(c)}</{tag}>" for c in cells)
                    + "</tr>")
        elif ln.lstrip().startswith(("- ", "* ")):
            if not in_list:
                out.append("<ul>")
                in_list = True
            out.append(f"<li>{_inline(ln.lstrip()[2:])}</li>")
        elif ln.strip() == "":
            out.append("")
        else:
            out.append(f"<p>{_inline(ln)}</p>")
        i += 1
    if in_list:
        out.append("</ul>")
    if in_table:
        out.append("</table>")
    return "\n".join(out)


def validate():
    errors = []
    chapters = sorted(SRC.glob("*.md"))
    names = {p.name for p in chapters}
    for p in chapters:
        text = p.read_text()
        for m in re.finditer(r"\]\(([^)#]+\.md)[^)]*\)", text):
            tgt = m.group(1)
            if "/" not in tgt and tgt not in names:
                errors.append(f"{p.name}: broken link -> {tgt}")
        # repo paths in backticks that look like files must exist
        for m in re.finditer(
                r"`((?:mpisppy_tpu|examples|tests|doc)/[\w/.]+?"
                r"\.(?:py|cpp|so|md|csv))`", text):
            if not (REPO / m.group(1)).exists():
                errors.append(f"{p.name}: missing repo path "
                              f"-> {m.group(1)}")
    index = (SRC / "index.md").read_text()
    linked = set(re.findall(r"\]\((\w+\.md)\)", index))
    for p in chapters:
        if p.name != "index.md" and p.name not in linked:
            errors.append(f"index.md does not link {p.name}")
    return errors, chapters


def main():
    errors, chapters = validate()
    if errors:
        for e in errors:
            print("DOC ERROR:", e, file=sys.stderr)
        return 1
    OUT.mkdir(exist_ok=True)
    wanted = {p.stem + ".html" for p in chapters}
    for stale in OUT.glob("*.html"):
        if stale.name not in wanted:
            stale.unlink()
    for p in chapters:
        text = p.read_text()
        m = re.search(r"^#\s+(.*)", text, re.M)
        title = m.group(1) if m else p.stem
        (OUT / (p.stem + ".html")).write_text(render(text, title))
    print(f"doc build OK: {len(chapters)} chapters -> {OUT}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

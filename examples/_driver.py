"""Shared scaffolding for the example drivers (analog of the repeated
cfg -> vanilla -> WheelSpinner preamble in every reference example,
e.g. reference examples/sizes/sizes_cylinders.py:20-70).

Each per-model driver declares the standard flag groups, delegates to
the Amalgamator (EF mode or cylinders mode), and prints the bounds —
so `run_all.py` can smoke every family with real command lines.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))          # repo root, for mpisppy_tpu

from mpisppy_tpu.utils.platform import (  # noqa: E402
    enable_compile_cache_if_cpu, enable_f64_if_cpu, ensure_cpu_backend)

ensure_cpu_backend()        # no-op unless JAX_PLATFORMS requests cpu
enable_f64_if_cpu()         # CPU runs follow the f64 protocol
enable_compile_cache_if_cpu()   # repeat runs skip ~30 s of compiles

from mpisppy_tpu.utils import amalgamator, config  # noqa: E402


def standard_cfg():
    cfg = config.Config()
    cfg.popular_args()
    cfg.ph_args()
    cfg.two_sided_args()
    cfg.fwph_args()
    cfg.lagrangian_args()
    cfg.lagranger_args()
    cfg.xhatlooper_args()
    cfg.xhatshuffle_args()
    cfg.xhatspecific_args()
    cfg.xhatxbar_args()
    cfg.xhatlshaped_args()
    cfg.slammax_args()
    cfg.slammin_args()
    cfg.fixer_args()
    cfg.gapper_args()
    cfg.converger_args()
    cfg.norm_rho_args()
    cfg.mult_rho_args()
    cfg.wtracker_args()
    cfg.ef_args()
    return cfg


def cylinders_main(module, progname, args=None, extraargs_fct=None):
    """Parse the standard flag surface and run the model through the
    Amalgamator.  Returns the Amalgamator (bounds on
    .best_inner_bound/.best_outer_bound, or .EF_Obj in --EF mode).

    Prints one machine-readable `DRIVER_WALL build=..s run=..s` line —
    run_all.py records the split so corpus timings separate problem
    construction from the solve loop (whose first iteration carries
    the jit compiles)."""
    cfg = standard_cfg()
    if extraargs_fct is not None:
        extraargs_fct(cfg)
    ama = amalgamator.from_module(module, cfg, use_command_line=True,
                                  args=args, progname=progname)
    ama.run()
    if ama.is_EF:
        print(f"EF objective = {ama.EF_Obj}")
    else:
        print(f"BestInnerBound = {ama.best_inner_bound}")
        print(f"BestOuterBound = {ama.best_outer_bound}")
    print(f"DRIVER_WALL build={ama.wall_build:.2f}s "
          f"run={ama.wall_run:.2f}s")
    return ama

"""acopf3_cylinders — multistage DC-OPF with line outages (analog of
the reference's examples/acopf3/ccopf_multistage.py driver).

    python examples/acopf3_cylinders.py --branching-factors 2,2 \\
        --lagrangian --xhatshuffle --max-iterations 30
"""

import sys

from _driver import cylinders_main
from mpisppy_tpu.models import acopf3


def main(args=None):
    return cylinders_main(acopf3, "acopf3_cylinders", args=args)


if __name__ == "__main__":
    main(sys.argv[1:])

"""acopf3_soc — AC fidelity via the Jabr SOC relaxation (the step from
the DC approximation toward the reference's AC formulation,
examples/acopf3/ccopf_multistage.py `convex_relaxation` mode).

Sequential outer approximation: each round solves the current LP/QP
relaxation with the batched consensus kernel (warm-started), then
linearizes the violated rotated cones cc^2 + ss^2 <= u_i u_j into a
fixed-capacity cut buffer.  Ends with PH on the refined batch — the
refined ScenarioBatch is an ordinary batch, so the whole cylinder /
extension stack applies unchanged.

    python examples/acopf3_soc.py --case ieee14 --rounds 8
    python examples/acopf3_soc.py --branching-factors 2,2 --rounds 6
"""

import argparse
import sys
import time

import numpy as np

from mpisppy_tpu.models import acopf3
from mpisppy_tpu.opt.ph import PH


def main(args=None):
    p = argparse.ArgumentParser()
    p.add_argument("--branching-factors", default="1")
    p.add_argument("--case", default="")
    p.add_argument("--rounds", type=int, default=6)
    p.add_argument("--max-iterations", type=int, default=10)
    p.add_argument("--default-rho", type=float, default=50.0)
    p.add_argument("--pdhg-eps", type=float, default=1e-5)
    p.add_argument("--pdhg-max-iters", type=int, default=40000)
    a = p.parse_args(args)
    bf = tuple(int(x) for x in a.branching_factors.split(","))

    t0 = time.time()
    b = acopf3.build_soc_batch(branching_factors=bf,
                               case=a.case or None)
    t_build = time.time() - t0

    t0 = time.time()
    opts = {"pdhg_eps": a.pdhg_eps, "pdhg_max_iters": a.pdhg_max_iters}
    b2, hist = acopf3.soc_refine(b, rounds=a.rounds, opts=dict(opts))
    for rd, obj, viol, n in hist:
        print(f"round {rd}: obj={obj:.2f} max_cone_viol={viol:.2e} "
              f"cuts={n}")

    ph = PH({"defaultPHrho": a.default_rho,
             "PHIterLimit": a.max_iterations,
             "convthresh": 1e-6, **opts},
            list(b2.tree.scen_names), batch=b2)
    conv, eobj, triv = ph.ph_main()
    t_run = time.time() - t0
    assert np.isfinite(eobj) and np.isfinite(triv)
    print(f"PH on refined SOC batch: Eobj={eobj:.2f} "
          f"trivial_bound={triv:.2f} conv={conv:.2e}")
    print(f"DRIVER_WALL build={t_build:.2f}s run={t_run:.2f}s")


if __name__ == "__main__":
    main(sys.argv[1:])

"""afew — the quick example subset (analog of the reference's
examples/afew.py:41-50: farmer cylinders, farmer L-shaped, sizes).

    python examples/afew.py
"""

import run_all

if __name__ == "__main__":
    run_all.main(["--fast"])

"""aircond_cylinders — multistage production/inventory cylinders
(analog of the reference's examples/aircond/aircond_cylinders.py).

    python examples/aircond_cylinders.py --branching-factors 3,2 \\
        --lagrangian --xhatshuffle --max-iterations 40
"""

import sys

from _driver import cylinders_main
from mpisppy_tpu.models import aircond


def main(args=None):
    return cylinders_main(aircond, "aircond_cylinders", args=args)


if __name__ == "__main__":
    main(sys.argv[1:])

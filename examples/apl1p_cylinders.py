"""apl1p_cylinders — the APL1P generator-expansion fixture (analog of
the reference's mpisppy/tests/examples/apl1p.py usage).

    python examples/apl1p_cylinders.py --num-scens 4 --lagrangian \\
        --xhatshuffle --max-iterations 30
"""

import sys

from _driver import cylinders_main
from mpisppy_tpu.models import apl1p


def main(args=None):
    return cylinders_main(apl1p, "apl1p_cylinders", args=args)


if __name__ == "__main__":
    main(sys.argv[1:])

"""battery_cylinders — battery arbitrage under price/solar uncertainty
(analog of the reference's examples/battery driver).

    python examples/battery_cylinders.py --num-scens 8 --lagrangian \\
        --xhatshuffle --max-iterations 30
"""

import sys

from _driver import cylinders_main
from mpisppy_tpu.models import battery


def main(args=None):
    return cylinders_main(battery, "battery_cylinders", args=args)


if __name__ == "__main__":
    main(sys.argv[1:])

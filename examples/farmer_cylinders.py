"""farmer_cylinders — the canonical CLI driver (analog of the
reference's examples/farmer/farmer_cylinders.py, using the same
cfg -> vanilla -> WheelSpinner pipeline).

    python examples/farmer_cylinders.py --num-scens 3 --lagrangian \\
        --xhatshuffle --rel-gap 1e-4 --max-iterations 100
"""

from _driver import standard_cfg  # noqa: F401  (sys.path + CPU guard)
from mpisppy_tpu.models import farmer
from mpisppy_tpu.spin_the_wheel import WheelSpinner
from mpisppy_tpu.utils import config, vanilla


def _parse_args(args=None):
    cfg = standard_cfg()
    farmer.inparser_adder(cfg)
    cfg.parse_command_line("farmer_cylinders", args=args)
    return cfg


def main(args=None):
    import time as _time
    t0 = _time.time()
    cfg = _parse_args(args)
    num_scens = cfg.num_scens
    names = farmer.scenario_names_creator(num_scens)
    batch = farmer.build_batch(
        num_scens,
        crops_multiplier=cfg.get("crops_multiplier", 1),
        use_integer=cfg.get("farmer_with_integers", False))

    hub = vanilla.ph_hub(cfg, farmer.scenario_creator, None, names,
                         batch=batch)
    if cfg.get("fixer"):
        vanilla.add_fixer(hub, cfg)
    if cfg.get("use_norm_rho_updater"):
        vanilla.add_norm_rho(hub, cfg)
    if cfg.get("mult_rho"):
        vanilla.add_multi_rho(hub, cfg)
    spokes = vanilla.build_spokes(cfg, farmer.scenario_creator, None,
                                  names, batch=batch)
    t1 = _time.time()

    ws = WheelSpinner(hub, spokes).spin()
    print(f"BestInnerBound = {ws.BestInnerBound}")
    print(f"BestOuterBound = {ws.BestOuterBound}")
    print(f"DRIVER_WALL build={t1 - t0:.2f}s "
          f"run={_time.time() - t1:.2f}s")
    if cfg.get("solution_base_name") and \
            ws.best_nonant_solution() is not None:
        ws.write_first_stage_solution(cfg["solution_base_name"] + ".csv")
    return ws


if __name__ == "__main__":
    main()

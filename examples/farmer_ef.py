"""farmer_ef — one-call extensive-form solve (analog of the
reference's examples/farmer/farmer_ef.py: build the EF, one monolithic
solve; here one batched consensus solve).

    python examples/farmer_ef.py --num-scens 3 --EF
"""

import sys

from _driver import cylinders_main
from mpisppy_tpu.models import farmer


def main(args=None):
    args = list(args or [])
    if "--EF" not in args:
        args.append("--EF")
    return cylinders_main(farmer, "farmer_ef", args=args)


if __name__ == "__main__":
    main(sys.argv[1:])

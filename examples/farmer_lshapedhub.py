"""farmer_lshapedhub — L-shaped (Benders) hub with an xhat spoke
(analog of the reference's examples/farmer/farmer_lshapedhub.py).

    python examples/farmer_lshapedhub.py --num-scens 3 --xhatlshaped \\
        --max-iterations 50
"""

import sys

from _driver import standard_cfg
from mpisppy_tpu.models import farmer
from mpisppy_tpu.spin_the_wheel import WheelSpinner
from mpisppy_tpu.utils import vanilla


def main(args=None):
    cfg = standard_cfg()
    farmer.inparser_adder(cfg)
    cfg.parse_command_line("farmer_lshapedhub", args=args)

    num_scens = cfg.num_scens
    names = farmer.scenario_names_creator(num_scens)
    batch = farmer.build_batch(
        num_scens, crops_multiplier=cfg.get("crops_multiplier", 1))

    hub = vanilla.lshaped_hub(cfg, farmer.scenario_creator, None, names,
                              batch=batch)
    spokes = []
    if cfg.get("xhatlshaped"):
        spokes.append(vanilla.xhatlshaped_spoke(
            cfg, farmer.scenario_creator, None, names, batch=batch))
    ws = WheelSpinner(hub, spokes).spin()
    print(f"BestInnerBound = {ws.BestInnerBound}")
    print(f"BestOuterBound = {ws.BestOuterBound}")
    return ws


if __name__ == "__main__":
    main(sys.argv[1:])

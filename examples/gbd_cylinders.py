"""gbd_cylinders — Ferguson-Dantzig aircraft allocation (analog of
the reference's gbd usage in the sequential-sampling tests).

    python examples/gbd_cylinders.py --num-scens 10 --lagrangian \\
        --xhatshuffle --max-iterations 30
"""

import sys

from _driver import cylinders_main
from mpisppy_tpu.models import gbd


def main(args=None):
    return cylinders_main(gbd, "gbd_cylinders", args=args)


if __name__ == "__main__":
    main(sys.argv[1:])

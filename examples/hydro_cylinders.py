"""hydro_cylinders — multistage hydro scheduling cylinders (analog of
the reference's examples/hydro/hydro_cylinders.py; 3-stage tree via
--branching-factors).

    python examples/hydro_cylinders.py --branching-factors 3,3 \\
        --lagrangian --xhatshuffle --max-iterations 40
"""

import sys

from _driver import cylinders_main
from mpisppy_tpu.models import hydro


def main(args=None):
    return cylinders_main(hydro, "hydro_cylinders", args=args)


if __name__ == "__main__":
    main(sys.argv[1:])

"""netdes_cylinders — stochastic network design cylinders (analog of
the reference's examples/netdes/netdes_cylinders.py, the
cross-scenario-cuts showcase).

    python examples/netdes_cylinders.py --num-scens 5 --lagrangian \\
        --xhatshuffle --cross-scenario-cuts --max-iterations 30
"""

import sys

from _driver import cylinders_main
from mpisppy_tpu.models import netdes


def _extra(cfg):
    cfg.add_to_config("cross_scenario_cuts",
                      "add the cross-scenario cut spoke", bool, False)


def main(args=None):
    return cylinders_main(netdes, "netdes_cylinders", args=args,
                          extraargs_fct=_extra)


if __name__ == "__main__":
    main(sys.argv[1:])

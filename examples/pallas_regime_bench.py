"""Pallas-vs-XLA kernel comparison in the Pallas kernel's CLAIMED
regime (VERDICT r3 item 3): large per-scenario problems where one
scenario's (A, x, y) tile approaches VMEM capacity and the fused chunk
kernel's VMEM residency should pay off — farmer with
crops_multiplier >= 100 (N ~ 1.2k, M ~ 0.4k per scenario at mult=100).

Runs the same solver-space PDHG chunk through BOTH paths and reports
sec/iter each way plus the ratio.  One JSON line per configuration.

    python examples/pallas_regime_bench.py            # on TPU
    PALLAS_BENCH_INTERPRET=1 ... (CPU, correctness only — timing
    meaningless in interpret mode)

On CPU without interpret mode the Pallas path is skipped (the kernel
is TPU-only); the XLA path still prints, so the artifact records the
comparison baseline either way.
"""

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main():
    from mpisppy_tpu.utils.platform import ensure_cpu_backend
    ensure_cpu_backend()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.ops import pdhg

    on_tpu = jax.devices()[0].platform != "cpu"
    interpret = bool(os.environ.get("PALLAS_BENCH_INTERPRET"))
    mult = int(os.environ.get("PALLAS_BENCH_MULT", 100))
    S = int(os.environ.get("PALLAS_BENCH_SCENS", 64))
    n_steps = int(os.environ.get("PALLAS_BENCH_STEPS", 200))
    tile_s = int(os.environ.get("PALLAS_BENCH_TILE", 1))

    b = farmer.build_batch(S, crops_multiplier=mult,
                           dtype=np.float32 if on_tpu else np.float64)
    prep = pdhg.prepare_batch(b.A, b.row_lo, b.row_hi)
    solver = pdhg.PDHGSolver(max_iters=n_steps, eps=1e-6)
    dt = b.c.dtype
    cs = jnp.asarray(b.c) * prep.d_col
    qs = jnp.asarray(b.qdiag) * prep.d_col * prep.d_col
    lbs = jnp.where(jnp.isfinite(b.lb), b.lb / prep.d_col, b.lb)
    ubs = jnp.where(jnp.isfinite(b.ub), b.ub / prep.d_col, b.ub)
    x = jnp.zeros_like(cs)
    y = jnp.zeros((S, b.num_rows), dt)
    omega = jnp.ones((S,), dt)
    sigma = 0.9 * omega / prep.anorm
    tau = 0.9 / (omega * prep.anorm + 0.9 * jnp.max(qs, axis=1))

    vmem_tile_mb = (b.num_rows * b.num_vars * tile_s
                    * np.dtype(dt).itemsize) / 1e6
    out = {"metric": "pallas_vs_xla_sec_per_iter",
           "scens": S, "crops_multiplier": mult,
           "rows": b.num_rows, "vars": b.num_vars,
           "tile_A_mb": round(vmem_tile_mb, 2),
           "device": jax.devices()[0].platform, "n_steps": n_steps}

    # XLA path: the solver's own fused while_loop chunk
    import functools

    @functools.partial(jax.jit, static_argnames=("n",))
    def xla_chunk(x, y, n):
        def body(_, carry):
            x, y = carry
            grad = cs + qs * x + pdhg._ATy(prep.A, y)
            xn = jnp.clip(x - tau[:, None] * grad, lbs, ubs)
            xt = 2.0 * xn - x
            v = y + sigma[:, None] * pdhg._Ax(prep.A, xt)
            zc = jnp.clip(v / sigma[:, None], prep.row_lo, prep.row_hi)
            return xn, v - sigma[:, None] * zc
        from jax import lax
        return lax.fori_loop(0, n, body, (x, y))

    r = xla_chunk(x, y, n_steps)
    jax.block_until_ready(r)
    t0 = time.time()
    r = xla_chunk(x, y, n_steps)
    jax.block_until_ready(r)
    out["xla_sec_per_iter"] = round((time.time() - t0) / n_steps, 7)

    if on_tpu or interpret:
        from mpisppy_tpu.ops.pallas_pdhg import fused_chunk
        r2 = fused_chunk(prep.A, cs, qs, lbs, ubs, prep.row_lo,
                         prep.row_hi, x, y, tau, sigma, n_steps,
                         tile_s=tile_s, interpret=interpret)
        jax.block_until_ready(r2)
        t0 = time.time()
        r2 = fused_chunk(prep.A, cs, qs, lbs, ubs, prep.row_lo,
                         prep.row_hi, x, y, tau, sigma, n_steps,
                         tile_s=tile_s, interpret=interpret)
        jax.block_until_ready(r2)
        out["pallas_sec_per_iter"] = round((time.time() - t0) / n_steps,
                                           7)
        out["pallas_speedup"] = round(
            out["xla_sec_per_iter"] / out["pallas_sec_per_iter"], 3)
        # agreement check on the final iterates
        out["max_dx"] = float(jnp.max(jnp.abs(r[0] - r2[0])))
    else:
        out["pallas_sec_per_iter"] = None
        out["note"] = "Pallas path skipped (TPU-only kernel; CPU host)"
    print(json.dumps(out))


if __name__ == "__main__":
    main()

"""run_all — execute every example driver with real command lines and
collect failures + timings (analog of the reference's
examples/run_all.py: runs each family under mpiexec, records `badguys`
and emits a timing CSV as a side effect).

Here every driver is one process (scenario parallelism is inside the
batched kernel; multi-device runs shard the same code over a mesh), so
the runner shells out plain `python <driver> <args>` lines.

    python examples/run_all.py            # full corpus (CPU backend)
    python examples/run_all.py --fast     # afew-style quick subset
    python examples/run_all.py --medium   # + non-toy rows (padding/
                                          #   sharding at scale)
    python examples/run_all.py --tpu      # keep the ambient platform
"""

from __future__ import annotations

import csv
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))

# (driver, argstring) — mirrors the reference's do_one lines
CORPUS = [
    ("farmer_cylinders.py",
     "--num-scens 3 --max-iterations 50 --default-rho 1 "
     "--lagrangian --xhatshuffle --use-norm-rho-updater"),
    ("farmer_ef.py", "--num-scens 3"),
    ("farmer_lshapedhub.py",
     "--num-scens 3 --max-iterations 50 --xhatlshaped"),
    ("sizes_cylinders.py",
     "--num-scens 3 --max-iterations 5 --default-rho 1 "
     "--lagrangian --xhatshuffle"),
    ("sizes_ef_mip.py", "--num-scens 3 --solver-eps 1e-6"),
    ("sslp_cylinders.py",
     "--num-scens 10 --max-iterations 20 --default-rho 1 "
     "--lagrangian --xhatshuffle"),
    ("hydro_cylinders.py",
     "--branching-factors 3,3 --max-iterations 40 --default-rho 1 "
     "--lagrangian --xhatshuffle"),
    ("netdes_cylinders.py",
     "--num-scens 5 --max-iterations 30 --default-rho 1 "
     "--lagrangian --xhatshuffle"),
    ("uc_cylinders.py",
     "--num-scens 5 --max-iterations 20 --default-rho 1 "
     "--lagrangian --xhatshuffle"),
    # the reference's REAL UC data (WECC-240, examples/uc/3scenarios_r1)
    ("uc_wecc_cylinders.py",
     "--num-scens 3 --uc-hours 6 --uc-max-units 20 "
     "--max-iterations 10 --default-rho 50 "
     "--lagrangian --xhatxbar"),
    ("aircond_cylinders.py",
     "--branching-factors 3,2 --max-iterations 30 --default-rho 1 "
     "--lagrangian --xhatshuffle"),
    ("battery_cylinders.py",
     "--num-scens 8 --max-iterations 30 --default-rho 1 "
     "--lagrangian --xhatshuffle"),
    ("apl1p_cylinders.py",
     "--num-scens 4 --max-iterations 30 --default-rho 1 "
     "--lagrangian --xhatshuffle"),
    ("gbd_cylinders.py",
     "--num-scens 10 --max-iterations 30 --default-rho 1 "
     "--lagrangian --xhatshuffle"),
    ("usar_cylinders.py",
     "--num-scens 3 --max-iterations 25 --default-rho 1 "
     "--lagrangian --xhatshuffle"),
    ("acopf3_cylinders.py",
     "--branching-factors 2,2 --max-iterations 30 --default-rho 5 "
     "--lagrangian --xhatshuffle"),
    # AC fidelity: Jabr SOC relaxation + cone-cut refinement, then PH
    ("acopf3_soc.py",
     "--branching-factors 2,2 --rounds 4 --max-iterations 8"),
]

FAST = {"farmer_cylinders.py", "farmer_lshapedhub.py",
        "sizes_cylinders.py"}    # the reference's afew.py subset

# --medium: a non-toy tier that exercises padding/sharding at scale
# (VERDICT r3: the corpus never left --num-scens 3..10); sizes chosen
# to finish in minutes each on the 1-core CPU smoke box
MEDIUM = [
    ("farmer_cylinders.py",
     "--num-scens 256 --crops-multiplier 4 --max-iterations 10 "
     "--default-rho 1 --lagrangian --xhatshuffle"),
    ("sslp_cylinders.py",
     "--num-scens 50 --max-iterations 10 --default-rho 1 "
     "--lagrangian --xhatshuffle"),
    ("uc_cylinders.py",
     "--num-scens 100 --max-iterations 5 --default-rho 50 "
     "--lagrangian --xhatshuffle"),
    # (hydro's published branch data caps its tree at 3 children per
    # node, so the multistage medium row is aircond's sampled tree)
    ("aircond_cylinders.py",
     "--branching-factors 4,3,2 --max-iterations 10 --default-rho 1 "
     "--lagrangian --xhatshuffle"),
    # real-network fidelity row: the embedded IEEE 14-bus case
    ("acopf3_cylinders.py",
     "--branching-factors 3,2,2 --max-iterations 10 --default-rho 5 "
     "--case ieee14 --lagrangian --xhatshuffle"),
]


def _wall_split(stdout):
    """Parse the drivers' `DRIVER_WALL build=..s run=..s` line."""
    for ln in reversed(stdout.splitlines()):
        if ln.startswith("DRIVER_WALL"):
            try:
                parts = dict(tok.split("=") for tok in ln.split()[1:])
                return (float(parts["build"].rstrip("s")),
                        float(parts["run"].rstrip("s")))
            except (ValueError, KeyError):
                return None, None
    return None, None


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    fast = "--fast" in argv
    medium = "--medium" in argv
    rows = []
    badguys = []
    env = dict(os.environ)
    # smoke tier runs on CPU regardless of the ambient platform (the
    # drivers themselves run on whatever jax picks when launched
    # directly); pass --tpu to keep the ambient JAX_PLATFORMS
    if "--tpu" not in argv:
        env["JAX_PLATFORMS"] = "cpu"
    root = os.path.dirname(HERE)
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    corpus = list(CORPUS) + (MEDIUM if medium else [])
    for prog, argstring in corpus:
        if fast and prog not in FAST:
            continue
        cmd = [sys.executable, os.path.join(HERE, prog)] + argstring.split()
        print(f"** running: {prog} {argstring}", flush=True)
        t0 = time.time()
        r = subprocess.run(cmd, cwd=HERE, env=env,
                           capture_output=True, text=True)
        dt = time.time() - t0
        ok = r.returncode == 0
        build_s, run_s = _wall_split(r.stdout)
        rows.append({"program": prog, "args": argstring,
                     "seconds": round(dt, 2),
                     "build_s": build_s, "run_s": run_s, "ok": ok})
        if not ok:
            badguys.append((prog, r.returncode))
            print(r.stdout[-2000:])
            print(r.stderr[-2000:])
        print(f"   -> {'ok' if ok else 'FAILED'} in {dt:.1f}s",
              flush=True)

    csv_path = os.path.join(HERE, "run_all_timings.csv")
    with open(csv_path, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=["program", "args", "seconds",
                                          "build_s", "run_s", "ok"])
        w.writeheader()
        w.writerows(rows)
    print(f"timings written to {csv_path}")

    if badguys:
        print("badguys:")
        for prog, rc in badguys:
            print(f"  {prog}: rc={rc}")
        sys.exit(1)
    print(f"all {len(rows)} examples passed")


if __name__ == "__main__":
    main()

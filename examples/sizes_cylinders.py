"""sizes_cylinders — hub-and-spokes on the SIZES MIP (analog of the
reference's examples/sizes/sizes_cylinders.py).

    python examples/sizes_cylinders.py --num-scens 3 --lagrangian \\
        --xhatshuffle --max-iterations 20 --default-rho 1
"""

import sys

from _driver import cylinders_main
from mpisppy_tpu.models import sizes


def main(args=None):
    return cylinders_main(sizes, "sizes_cylinders", args=args)


if __name__ == "__main__":
    main(sys.argv[1:])

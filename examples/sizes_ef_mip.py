"""sizes_ef_mip — solve the SIZES extensive form to an integer-feasible
solution with a certified gap via the LP-diving MIP driver
(opt/mip.ExtensiveFormMIP; the reference hands the same EF to a
commercial branch-and-cut solver, reference opt/ef.py:66).

    python examples/sizes_ef_mip.py --num-scens 3
"""

import sys

from _driver import standard_cfg  # noqa: F401  (sys.path + CPU guard)
from mpisppy_tpu.models import sizes
from mpisppy_tpu.opt.mip import ExtensiveFormMIP
from mpisppy_tpu.utils import config


def main(args=None):
    cfg = config.Config()
    cfg.popular_args()
    sizes.inparser_adder(cfg)
    cfg.parse_command_line("sizes_ef_mip", args=args)
    num_scens = cfg.num_scens
    batch = sizes.build_batch(num_scens,
                              num_sizes=cfg.get("num_sizes", 10))
    names = sizes.scenario_names_creator(num_scens)
    ef = ExtensiveFormMIP(
        {"pdhg_eps": cfg.get("solver_eps", 1e-6),
         "pdhg_max_iters": cfg.get("solver_max_iters", 200000)},
        names, batch=batch)
    out = ef.solve_mip(verbose=cfg.get("verbose", False))
    print(f"incumbent = {out['incumbent']}")
    print(f"bound     = {out['bound']}")
    print(f"gap       = {out['gap']:.4%}  "
          f"({out['lp_solves']} LP solves)")
    return out


if __name__ == "__main__":
    main(sys.argv[1:])

"""sslp_cylinders — hub-and-spokes on stochastic server location
(analog of the reference's examples/sslp/sslp_cylinders.py).

    python examples/sslp_cylinders.py --num-scens 10 --lagrangian \\
        --xhatshuffle --max-iterations 20
"""

import sys

from _driver import cylinders_main
from mpisppy_tpu.models import sslp


def main(args=None):
    return cylinders_main(sslp, "sslp_cylinders", args=args)


if __name__ == "__main__":
    main(sys.argv[1:])

"""Strong-scaling protocol: PH iters/sec vs device count at fixed
problem size — the shape of the reference's scaling study
(reference paperruns/scripts/farmer/scaledlw.bash: 2048 scenarios,
np = 3*{32,16,...,1}), re-cast for a device mesh: the scenario batch is
FIXED and sharded over 1/2/4/8 mesh devices; each run times the fused
PH superstep after compile warmup and reports iters/sec.

Writes examples/scaling.csv:
    devices,scens,scens_per_device,warm_iters,timed_iters,sec_per_iter,
    iters_per_sec,trivial_bound

Run on the 8-virtual-device CPU mesh (conftest env):
    env JAX_PLATFORMS=cpu JAX_ENABLE_X64=1 \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/strong_scaling.py
On real hardware the available device counts are used (a single TPU
chip records the 1-device row).

NOTE on the virtual-CPU numbers: all virtual devices share the host's
cores, so CPU rows measure SPMD-partitioning overhead (a flat profile
= sharding adds no cost), not hardware speedup; speedup curves need
real chips (BASELINE.md targets v5e-8).
"""

import csv
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def run(out_path=None):
    from mpisppy_tpu.utils.platform import ensure_cpu_backend
    ensure_cpu_backend()
    import jax

    from mpisppy_tpu.models import farmer
    from mpisppy_tpu.opt.ph import PH
    from mpisppy_tpu.parallel.mesh import ScenarioMesh

    S = int(os.environ.get("SCALING_SCENS", 2048))
    mult = int(os.environ.get("SCALING_MULT", 1))
    timed = int(os.environ.get("SCALING_ITERS", 3))
    ndev_all = len(jax.devices())
    counts = [n for n in (1, 2, 4, 8) if n <= ndev_all]

    rows = []
    for n in counts:
        mesh = ScenarioMesh(devices=jax.devices()[:n])
        b = farmer.build_batch(S, crops_multiplier=mult)
        opts = {"defaultPHrho": 1.0, "PHIterLimit": timed,
                "convthresh": 0.0, "pdhg_eps": 1e-5,
                "superstep_eps": 1e-4, "pdhg_max_iters": 5000}
        ph = PH(opts, [f"scen{i}" for i in range(S)], batch=b, mesh=mesh)
        ph.Iter0()
        ph.ph_iteration()          # compile warmup
        t0 = time.time()
        for _ in range(timed):
            ph.ph_iteration()
        jax.block_until_ready(ph.state.x)
        dt = (time.time() - t0) / timed
        rows.append({
            "devices": n, "scens": S,
            "scens_per_device": S // n,
            "warm_iters": 1, "timed_iters": timed,
            "sec_per_iter": round(dt, 4),
            "iters_per_sec": round(1.0 / dt, 4),
            "trivial_bound": round(ph.trivial_bound, 2),
        })
        print(f"[scaling] {n} device(s): {dt:.3f} s/iter "
              f"({1.0/dt:.3f} iters/s)")

    out = Path(out_path or Path(__file__).parent / "scaling.csv")
    with out.open("w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=list(rows[0].keys()))
        w.writeheader()
        w.writerows(rows)
    print(f"[scaling] wrote {out}")
    return rows


if __name__ == "__main__":
    sys.exit(0 if run() else 1)

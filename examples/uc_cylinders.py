"""uc_cylinders — stochastic unit commitment cylinders (analog of the
reference's examples/uc/uc_cylinders.py and paperruns/larger_uc).

    python examples/uc_cylinders.py --num-scens 10 --lagrangian \\
        --xhatshuffle --max-iterations 30
"""

import sys

from _driver import cylinders_main
from mpisppy_tpu.models import uc


def main(args=None):
    return cylinders_main(uc, "uc_cylinders", args=args)


if __name__ == "__main__":
    main(sys.argv[1:])

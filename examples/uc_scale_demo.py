"""uc_scale_demo — the full UC commitment-recovery pipeline at scale
(analog of the reference's paperruns/larger_uc protocol, BASELINE.md
stretch axis).

Pipeline (every stage batched kernel launches):
  1. PH consensus over S wind scenarios (one fused superstep each),
  2. certificate-free Lagrangian outer bound tracked at its best
     across iterations (uc's finite boxes),
  3. threshold-commitment candidates screened in ONE stacked launch,
  4. batched 1-opt flip search over ALL unit-hour slots on the winner
     (bounded chunks; fractional-only sweeps stall well above the
     optimum — measured vs a HiGHS oracle at S=50),
  5. one consensus-EF LP solve whose dual objective is a second,
     much tighter, valid outer bound,
  6. report incumbent, valid outer bound, and the gap.

Note the bound caveat measured against a scipy/HiGHS oracle (S=50,
fleet_multiplier=2): this instance family has an inherent LP-MIP
integrality gap (~2.8%), so the LP-based certificate cannot reach
1% — the incumbent is the number to compare against a MIP oracle
(the full-slot 1-opt lands on the oracle optimum there).

    python examples/uc_scale_demo.py --num-scens 100 --max-iterations 10
    python examples/uc_scale_demo.py --num-scens 1000 \\
        --uc-fleet-multiplier 3          # the larger_uc-style size
"""

import sys

import numpy as np

from _driver import standard_cfg
from mpisppy_tpu.models import uc
from mpisppy_tpu.opt.ph import PH


def main(args=None):
    cfg = standard_cfg()
    uc.inparser_adder(cfg)
    cfg.parse_command_line("uc_scale_demo", args=args)
    S = cfg.num_scens
    b = uc.build_batch(
        S, H=cfg.get("uc_hours", 6),
        fleet_multiplier=cfg.get("uc_fleet_multiplier", 1))
    ph = PH({"defaultPHrho": cfg.get("default_rho", 50.0),
             "PHIterLimit": cfg.get("max_iterations", 10),
             "convthresh": 0.0,
             "pdhg_eps": cfg.get("solver_eps", 1e-6),
             "superstep_eps": 1e-4, "lagrangian_eps": 1e-5,
             "pdhg_max_iters": cfg.get("solver_max_iters", 200000)},
            [f"s{i}" for i in range(S)], batch=b)
    ph.Iter0()
    outer = ph.trivial_bound
    iters = int(cfg.get("max_iterations", 10))
    for k in range(iters):
        ph.ph_iteration()
        if (k + 1) % 5 == 0:     # best-seen, not just final-W
            outer = max(outer, ph.lagrangian_bound())
    if iters == 0 or iters % 5:
        outer = max(outer, ph.lagrangian_bound())

    xbar = np.asarray(ph.state.xbar)[0]
    cands = uc.commitment_candidates(b, xbar)
    objs, feas = ph.evaluate_candidates(cands)
    ok = np.flatnonzero(feas)
    if ok.size == 0:
        print("no feasible threshold candidate")
        return 1
    best = int(ok[np.argmin(objs[ok])])
    cand, inner = uc.one_opt_commitment(ph, b, cands[best],
                                        max_sweeps=3)

    # second outer bound: the consensus-EF LP's dual objective (valid
    # at any iterate — all boxes finite) is far tighter than the
    # W-path Lagrangian at small iteration counts; same protocol as
    # bench.py worker_uc
    from mpisppy_tpu.opt.ef import ef_dual_bound
    ef_b, _ = ef_dual_bound(b, [f"s{i}" for i in range(S)])
    outer = max(outer, ef_b)
    stats = ph.solve_stats()
    gap = abs(inner - outer) / max(abs(inner), 1e-9)
    print(f"incumbent (integer commitment) = {inner:.6g}")
    print(f"valid outer bound              = {outer:.6g}")
    print(f"certified gap                  = {gap:.2%} "
          f"(includes the LP-MIP integrality gap)")
    print(f"kernel work: {stats['flops'] / 1e12:.2f} TFLOP on "
          f"{stats['device']}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

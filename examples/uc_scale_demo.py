"""uc_scale_demo — the full UC commitment-recovery pipeline at scale
(analog of the reference's paperruns/larger_uc protocol, BASELINE.md
stretch axis).

Pipeline (every stage one batched kernel launch):
  1. PH consensus over S wind scenarios (one fused superstep each),
  2. certificate-free Lagrangian outer bound (uc's finite boxes),
  3. threshold-commitment candidates screened in ONE stacked launch,
  4. batched 1-opt flip search on the winner,
  5. report incumbent, valid outer bound, and the gap.

Note the bound caveat measured in tests/test_uc_scale.py: this
instance family has an inherent LP-MIP integrality gap (~6% at
S=100), so the LP-based certificate cannot reach 1% — the incumbent
is the number to compare against a MIP oracle.

    python examples/uc_scale_demo.py --num-scens 100 --max-iterations 10
    python examples/uc_scale_demo.py --num-scens 1000 \\
        --uc-fleet-multiplier 3          # the larger_uc-style size
"""

import sys

import numpy as np

from _driver import standard_cfg
from mpisppy_tpu.models import uc
from mpisppy_tpu.opt.ph import PH


def main(args=None):
    cfg = standard_cfg()
    uc.inparser_adder(cfg)
    cfg.parse_command_line("uc_scale_demo", args=args)
    S = cfg.num_scens
    b = uc.build_batch(
        S, H=cfg.get("uc_hours", 6),
        fleet_multiplier=cfg.get("uc_fleet_multiplier", 1))
    ph = PH({"defaultPHrho": cfg.get("default_rho", 50.0),
             "PHIterLimit": cfg.get("max_iterations", 10),
             "convthresh": 0.0,
             "pdhg_eps": cfg.get("solver_eps", 1e-6),
             "superstep_eps": 1e-4, "lagrangian_eps": 1e-5,
             "pdhg_max_iters": cfg.get("solver_max_iters", 200000)},
            [f"s{i}" for i in range(S)], batch=b)
    ph.Iter0()
    outer = ph.trivial_bound
    for _ in range(int(cfg.get("max_iterations", 10))):
        ph.ph_iteration()
    outer = max(outer, ph.lagrangian_bound())

    xbar = np.asarray(ph.state.xbar)[0]
    cands = uc.commitment_candidates(b, xbar)
    objs, feas = ph.evaluate_candidates(cands)
    ok = np.flatnonzero(feas)
    if ok.size == 0:
        print("no feasible threshold candidate")
        return 1
    best = int(ok[np.argmin(objs[ok])])
    GH = cands.shape[1] // 2
    frac = np.flatnonzero(
        np.abs(xbar[:GH] - np.round(xbar[:GH])) > 1e-3)
    cand, inner = uc.one_opt_commitment(ph, b, cands[best],
                                        max_sweeps=3, flip_slots=frac)
    stats = ph.solve_stats()
    gap = abs(inner - outer) / max(abs(inner), 1e-9)
    print(f"incumbent (integer commitment) = {inner:.6g}")
    print(f"valid outer bound              = {outer:.6g}")
    print(f"certified gap                  = {gap:.2%} "
          f"(includes the LP-MIP integrality gap)")
    print(f"kernel work: {stats['flops'] / 1e12:.2f} TFLOP on "
          f"{stats['device']}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

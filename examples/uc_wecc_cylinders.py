"""uc_wecc_cylinders — the reference's ACTUAL UC instances (WECC-240
data under reference examples/uc/<k>scenarios_r1/) through the
cylinders stack (analog of the reference's examples/uc/uc_cylinders.py
driving the same files through egret).

    python examples/uc_wecc_cylinders.py --num-scens 3 \\
        --uc-hours 6 --uc-max-units 20 --max-iterations 10 \\
        --default-rho 50 --lagrangian --xhatxbar
"""

import sys

from _driver import cylinders_main
from mpisppy_tpu.models import uc_wecc


def main(args=None):
    return cylinders_main(uc_wecc, "uc_wecc_cylinders", args=args)


if __name__ == "__main__":
    main(sys.argv[1:])

"""usar_cylinders — urban search and rescue deployment (analog of the
reference's examples/usar/wheel_spinner.py).

    python examples/usar_cylinders.py --num-scens 3 --lagrangian \\
        --xhatshuffle --max-iterations 25
"""

import sys

from _driver import cylinders_main
from mpisppy_tpu.models import usar


def main(args=None):
    return cylinders_main(usar, "usar_cylinders", args=args)


if __name__ == "__main__":
    main(sys.argv[1:])

"""mpisppy_tpu — a TPU-native stochastic-programming framework.

A ground-up re-design of the capabilities of mpi-sppy (scenario-based
stochastic programming with Progressive Hedging and hub-and-spoke
"cylinders") for TPU hardware: scenarios are a batch axis, per-scenario
LP/QP subproblems are solved by a vmapped first-order PDHG kernel on the
MXU, and MPI collectives become XLA collectives (`psum` over a named
scenario mesh axis under `shard_map`).

Reference parity: mirrors the layer map of mpi-sppy (see SURVEY.md §1);
the bootstrap/timing layer here corresponds to mpisppy/__init__.py:4-13
in the reference.
"""

import time as _time

__version__ = "0.1.0"

_T0 = _time.time()
_TOC_ENABLED = True
_TOC_SINKS = []


def global_toc(msg, cond=True):
    """Timestamped trace line (reference: mpisppy/__init__.py:11 global_toc).

    `cond` is typically `rank == 0`; in the single-controller JAX world it
    defaults to True (one python process drives all devices).
    """
    if cond and _TOC_ENABLED:
        print(f"[{_time.time() - _T0:10.2f}] {msg}", flush=True)
        for sink in _TOC_SINKS:
            sink(msg)


def add_toc_sink(fn):
    """Register an extra consumer of the trace (log.global_toc_logger
    routes it into the logging tree for headless runs)."""
    _TOC_SINKS.append(fn)


def disable_tictoc_output():
    """Reference: sputils.disable_tictoc_output (sputils.py:914)."""
    global _TOC_ENABLED
    _TOC_ENABLED = False


def reenable_tictoc_output():
    """Reference: sputils.reenable_tictoc_output (sputils.py:918)."""
    global _TOC_ENABLED
    _TOC_ENABLED = True


tt_timer = global_toc  # name-compat with the reference's tt_timer
haveMPI = False  # we never have MPI; the collective layer is XLA

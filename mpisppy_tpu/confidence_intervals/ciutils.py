"""ciutils — shared confidence-interval machinery (reference:
mpisppy/confidence_intervals/ciutils.py, 427 LoC).

Provides seed discipline, xhat (de)serialization, batch sampling
through the amalgamator module contract, and the central
`gap_estimators` (reference ciutils.py:208-427): for a candidate xhat
and a fresh scenario sample, the bias-corrected point estimate G and
sample standard deviation s of the optimality gap.

Sampling protocol: the model module's build_batch is called with a
seed-bearing kwarg (`seed` or `seedoffset`, whichever its signature
takes) so each batch of scenarios is an independent draw — the analog
of the reference's `scenario_names_creator(n, start=seed)` convention
where the scenario NUMBER is the random seed.
"""

from __future__ import annotations

import inspect

import numpy as np

from ..opt.ef import ExtensiveForm
from ..utils.xhat_eval import Xhat_Eval

try:
    from scipy.stats import t as _t_dist
    HAVE_SCIPY = True
except ImportError:                                    # pragma: no cover
    HAVE_SCIPY = False


def t_quantile(confidence_level, dof):
    """One-sided t quantile (reference uses scipy.stats.t.ppf)."""
    if HAVE_SCIPY:
        return float(_t_dist.ppf(confidence_level, dof))
    return 1.96  # normal fallback


# -- xhat (de)serialization (reference ciutils.py:135-165) -----------------

def write_xhat(xhat, path="xhat.npy"):
    np.save(path, np.asarray(xhat))


def read_xhat(path="xhat.npy"):
    return np.load(path)


def writetxt_xhat(xhat, path="xhat.txt"):
    np.savetxt(path, np.asarray(xhat))


def readtxt_xhat(path="xhat.txt"):
    return np.loadtxt(path)


# -- sampling through the module contract ----------------------------------

def sample_batch(module, num_scens, seed, cfg=None, extra_kw=None):
    """Build a batch of scenarios drawn with `seed`.  For MULTISTAGE
    modules, build_batch's first argument is branching_factors (from
    kw_creator), not a scenario count — num_scens is ignored there."""
    kw = dict(module.kw_creator(cfg or {})) if hasattr(
        module, "kw_creator") else {}
    kw.pop("num_scens", None)
    kw.update(extra_kw or {})
    sig = inspect.signature(module.build_batch)
    if "seed" in sig.parameters:
        kw["seed"] = seed
    elif "seedoffset" in sig.parameters:
        kw["seedoffset"] = seed
    elif "start_seed" in sig.parameters:
        kw["start_seed"] = seed
    if getattr(module, "MULTISTAGE", False):
        return module.build_batch(**kw)
    return module.build_batch(num_scens, **kw)


def _solver_opts(cfg):
    cfg = cfg or {}
    return {"pdhg_eps": cfg.get("solver_eps", 1e-7),
            "pdhg_max_iters": cfg.get("solver_max_iters", 100000)}


# -- the gap estimator (reference ciutils.py:208 gap_estimators) -----------

def gap_estimators(xhat_one, mname_or_module, solving_type="EF_2stage",
                   scenario_names=None, sample_options=None,
                   num_scens=None, seed=0, cfg=None, objective_gap=False,
                   ArRP=1):
    """Estimate the optimality gap of candidate `xhat_one` on a fresh
    sample: returns {"G": point estimate, "std" (alias "s"): sample std
    of the per-scenario gap terms, "zhats": E[f(xhat)], "zstar": sampled
    EF value, "seed": next seed}.

    Two-stage: G_n = (1/n) sum_s [ f_s(xhat) - f_s(x*_n) ] with x*_n
    the sampled-EF optimizer — the downward-biased MMW estimator; std
    is the (n-1)-dof sample std of those terms (reference
    ciutils.py:208-330).

    ArRP > 1 pools G and s from ArRP disjoint sub-estimators of
    num_scens/ArRP scenarios each: G = mean(G_i),
    s = ||(s_i)||_2 / sqrt(n/ArRP) (reference ciutils.py:286-313).
    """
    import importlib
    m = (importlib.import_module(mname_or_module)
         if isinstance(mname_or_module, str) else mname_or_module)
    if num_scens is None:
        num_scens = len(scenario_names) if scenario_names else 10
    if solving_type not in ("EF_2stage", "EF-2stage", "EF_mstage"):
        raise ValueError(f"unknown solving_type {solving_type}")

    if ArRP > 1:
        if solving_type == "EF_mstage":
            raise NotImplementedError(
                "pooled (ArRP) estimators are not supported for "
                "multistage problems (reference ciutils.py:288)")
        n = num_scens - num_scens % ArRP
        npool = n // ArRP
        if npool < 2:
            # npool=0 would estimate on empty samples (nan/0 G) and
            # hand callers a stopping certificate that was never
            # computed; npool=1 has no sample std
            raise ValueError(
                f"gap_estimators: num_scens={num_scens} too small for "
                f"ArRP={ArRP} pooling (need >= 2 per pool)")
        Gs, ss, zhs, zss, gobjs = [], [], [], [], []
        sub_seed = seed
        for _ in range(ArRP):
            tmp = gap_estimators(
                xhat_one, m, solving_type=solving_type,
                num_scens=npool, seed=sub_seed, cfg=cfg, ArRP=1,
                objective_gap=objective_gap)
            sub_seed = tmp["seed"]
            Gs.append(tmp["G"])
            ss.append(tmp["std"])
            zhs.append(tmp["zhats"])
            zss.append(tmp["zstar"])
            if objective_gap:
                gobjs.append(tmp["Gobj"])
        G = float(np.mean(Gs))
        s = float(np.linalg.norm(ss) / np.sqrt(npool))
        out = {"G": G, "std": s, "s": s,
               "zhats": float(np.mean(zhs)),
               "zstar": float(np.mean(zss)), "seed": sub_seed}
        if objective_gap:
            out["Gobj"] = float(np.mean(gobjs))
        return out

    batch = sample_batch(m, num_scens, seed, cfg)
    num_scens = min(num_scens, batch.num_scens)   # multistage trees
    names = list(batch.tree.scen_names)[:num_scens]
    opts = _solver_opts(cfg)

    # sampled EF solve -> zstar and the sampled-optimal solution
    ef = ExtensiveForm(dict(opts), names, batch=batch)
    res = ef.solve_extensive_form()
    zstar = ef.get_objective_value()
    # per-scenario f_s(x*_n): recompute UNWEIGHTED (the consensus solve
    # reports p_s-weighted objectives, ef.py folds prob into c)
    fs_star = np.asarray(ef.batch.objective(res.x))[:num_scens]

    # evaluate the candidate on the same sample
    ev = Xhat_Eval(dict(opts), names, batch=batch)
    lb, ub = ev.fixed_nonant_bounds(
        np.asarray(xhat_one), upto_stage=1 if solving_type == "EF_mstage"
        else None)
    evres = ev.solve_loop(lb=lb, ub=ub, warm=False)
    # an infeasible candidate's objectives are junk — fail loudly (the
    # reference checks solver status and raises)
    if ev.feas_prob(evres) < 1.0 - 1e-6:
        raise RuntimeError(
            "gap_estimators: candidate xhat infeasible on the sample "
            f"(feasible mass {ev.feas_prob(evres):.4f})")
    fs_hat = np.asarray(evres.obj)[:num_scens]
    prob = np.asarray(batch.prob)[:num_scens]
    prob = prob / prob.sum()
    zhat = float(prob @ fs_hat)

    gaps = fs_hat - fs_star                       # per-scenario gap terms
    G = float(prob @ gaps)
    # classic MMW uses the iid sample std (uniform probabilities)
    std = float(np.std(gaps, ddof=1)) if num_scens > 1 else 0.0
    out = {"G": G, "std": std, "s": std, "zhats": zhat, "zstar": zstar,
           "seed": seed + num_scens}
    if objective_gap:
        out["Gobj"] = zhat - zstar
    return out


def debit_quarantined_mass(est, frac):
    """Debit lost scenario mass into a gap estimate, in place.

    When a shard store quarantines unreadable shards
    (streaming/store.py), `frac` of the scenario universe was replaced
    by resampled draws from the healthy remainder.  The sampled-gap
    point estimate is then conditioned on the readable sub-universe;
    the unread mass could hide up to `frac * |z|` of objective, so the
    certificate must widen by that much rather than silently claim the
    healthy-corpus verdict.  Scales by the LARGEST objective magnitude
    in the estimate (floored at 1.0 for near-zero objectives), adds
    the debit to est["G"], records it under est["quarantine_debit"],
    and returns the debit.  frac <= 0 is a no-op returning 0.0 — a
    healthy run's estimate is bit-untouched."""
    frac = float(frac)
    if frac <= 0.0:
        return 0.0
    scale = max(abs(float(est.get("zhats", 0.0))),
                abs(float(est.get("zstar", 0.0))), 1.0)
    debit = frac * scale
    est["G"] = float(est["G"]) + debit
    est["quarantine_debit"] = debit
    return debit

"""Config groups for the confidence-interval layer (reference:
mpisppy/confidence_intervals/confidence_config.py:3-85)."""

from __future__ import annotations


def confidence_config(cfg):
    cfg.add_to_config("confidence_level", "CI confidence level",
                      float, 0.95)


def sequential_config(cfg):
    confidence_config(cfg)
    cfg.add_to_config("sample_size_ratio", "growth factor", float, 1.5)
    cfg.add_to_config("xhat1_option", "candidate source", str, "xhat_xbar")
    cfg.add_to_config("n0min", "initial sample size", int, 10)


def BM_config(cfg):
    sequential_config(cfg)
    cfg.add_to_config("BM_h", "BM h parameter", float, 2.0)
    cfg.add_to_config("BM_hprime", "BM h' parameter", float, 0.1)
    cfg.add_to_config("BM_eps", "BM eps", float, 1e-2)
    cfg.add_to_config("BM_eps_prime", "BM eps'", float, 1e-3)
    cfg.add_to_config("BM_p", "BM p", float, 0.1)
    cfg.add_to_config("BM_q", "BM q", float, 1.2)


def BPL_config(cfg):
    sequential_config(cfg)
    cfg.add_to_config("BPL_eps", "BPL fixed width", float, 1.0)
    cfg.add_to_config("BPL_c0", "BPL initial sample", int, 20)
    cfg.add_to_config("BPL_n0min", "BPL minimal n0", int, 0)


def zhat_config(cfg):
    confidence_config(cfg)
    cfg.add_to_config("num_samples", "evaluation batches", int, 5)
    cfg.add_to_config("sample_size", "scenarios per batch", int, 10)

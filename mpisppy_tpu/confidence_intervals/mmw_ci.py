"""MMW confidence intervals (reference:
mpisppy/confidence_intervals/mmw_ci.py:31-189 — Mak, Morton & Wood
gap confidence interval around a given xhat).

`num_batches` independent samples of `batch_size` scenarios each yield
gap estimates G_i with stds s_i; the one-sided (1-alpha) CI on the true
gap is  [0, Gbar + t_{alpha, nB-1} * sbar / sqrt(nB)]  where Gbar and
sbar aggregate over batches (reference mmw_ci.py:120-170).
"""

from __future__ import annotations

import importlib

import numpy as np

from .. import global_toc
from . import ciutils


class MMWConfidenceIntervals:
    def __init__(self, mname, options, xhat_one, num_batches,
                 batch_size=None, start=None, verbose=False,
                 mname_is_module=None):
        self.module = (mname if mname_is_module or not isinstance(
            mname, str) else importlib.import_module(mname))
        self.options = dict(options or {})
        self.xhat_one = np.asarray(xhat_one)
        self.num_batches = int(num_batches)
        self.batch_size = int(batch_size or
                              self.options.get("batch_size", 10))
        # start: first sampling seed; the reference uses num_scens of
        # the original problem so samples never overlap the training
        # scenarios (mmw_ci.py:87)
        self.start = int(start if start is not None
                         else self.options.get("start", 1000))
        self.verbose = verbose
        self.result = None

    def run(self, confidence_level=0.95, objective_gap=False):
        Gs, stds, zhats, zstars = [], [], [], []
        seed = self.start
        for i in range(self.num_batches):
            est = ciutils.gap_estimators(
                self.xhat_one, self.module,
                solving_type=self.options.get("solving_type",
                                              "EF_2stage"),
                num_scens=self.batch_size, seed=seed,
                cfg=self.options, objective_gap=objective_gap)
            seed = est["seed"]
            Gs.append(est["G"])
            stds.append(est["std"])
            zhats.append(est["zhats"])
            zstars.append(est["zstar"])
            if self.verbose:
                global_toc(f"MMW batch {i}: G={est['G']:.6g} "
                           f"std={est['std']:.6g}")
        nB = self.num_batches
        Gbar = float(np.mean(Gs))
        # aggregate std over batches (reference mmw_ci.py:150): the
        # batch-mean estimator's std
        if nB > 1:
            sbar = float(np.std(Gs, ddof=1))
        else:
            sbar = float(stds[0] / np.sqrt(self.batch_size))
        tq = ciutils.t_quantile(confidence_level, max(nB - 1, 1))
        Gmax = Gbar + tq * sbar / np.sqrt(nB)
        self.result = {
            "gap_inner_bound": max(Gmax, 0.0),
            "gap_outer_bound": 0.0,
            "Gbar": Gbar, "std": sbar, "Glist": Gs,
            "zhat_bar": float(np.mean(zhats)),
            "zstar_bar": float(np.mean(zstars)),
        }
        global_toc(f"MMW: gap in [0, {Gmax:.6g}] at "
                   f"{confidence_level:.0%} (Gbar={Gbar:.6g})")
        return self.result

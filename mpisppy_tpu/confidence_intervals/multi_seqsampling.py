"""IndepScens_SeqSampling — multistage sequential sampling with
independent scenario resampling (reference:
mpisppy/confidence_intervals/multi_seqsampling.py:29-339).

The multistage variant of SeqSampling: candidates come from a sampled
TREE (branching factors), and gap estimation evaluates the stage-1
candidate on independently resampled trees (sample_tree fans).
"""

from __future__ import annotations

import numpy as np

from .. import global_toc
from ..opt.ef import ExtensiveForm
from . import ciutils
from .sample_tree import walking_tree_xhats
from .seqsampling import SeqSampling


class IndepScens_SeqSampling(SeqSampling):
    def __init__(self, mname, optionsdict, seed=0,
                 stopping_criterion="BM"):
        super().__init__(mname, optionsdict, seed=seed,
                         stopping_criterion=stopping_criterion,
                         solving_type="EF_mstage")
        bf = self.options.get("branching_factors", [3, 3])
        from ..utils.config import parse_branching_factors
        self.branching_factors = parse_branching_factors(bf)

    def _candidate(self, n, seed):
        """Sampled-tree EF -> stage-1 xhat.  `n` scales the FIRST
        branching factor (the independent-scenarios axis)."""
        bf = list(self.branching_factors)
        bf[0] = max(bf[0], int(np.ceil(n / int(np.prod(bf[1:]) or 1))))
        batch = self._tree_batch(bf, seed)
        names = list(batch.tree.scen_names)
        ef = ExtensiveForm(
            {"pdhg_eps": self.options.get("solver_eps", 1e-7)},
            names, batch=batch)
        ef.solve_extensive_form()
        sol = np.asarray(ef.get_root_solution())
        # root nonants only (stage-1 slots)
        stage_of = np.asarray(batch.tree.stage_of)
        return sol[stage_of == 1]

    def _tree_batch(self, bf, seed):
        import inspect
        kw = dict(self.module.kw_creator(self.options)) if hasattr(
            self.module, "kw_creator") else {}
        kw["branching_factors"] = tuple(bf)
        sig = inspect.signature(self.module.build_batch)
        for s in ("seed", "seedoffset", "start_seed"):
            if s in sig.parameters:
                kw[s] = seed
                break
        return self.module.build_batch(**kw)

    def run(self):
        n = None
        seed = self.seed
        history = []
        xhat = None
        G = s = None
        # candidate-padding metadata depends only on the branching
        # factors, not the sample seed — compute once, not per
        # iteration (each _tree_batch materializes the full tensor)
        meta_batch = self._tree_batch(self.branching_factors, self.seed)
        K = meta_batch.num_nonants
        stage_of = np.asarray(meta_batch.tree.stage_of)
        for k in range(1, self.max_iters + 1):
            # the reference forces kf_Gs = kf_xhat = 1 for multistage
            # (seqsampling.py:233-241): every sample is a fresh tree;
            # sizes follow the BM/BPL schedules
            n = self._sample_size(k, G, s, n)
            xhat1 = self._candidate(n, seed)
            seed += n
            # pad the stage-1 candidate to the full nonant layout for
            # evaluation (later stages stay free via upto_stage=1)
            xhat = np.zeros(K)
            xhat[stage_of == 1] = xhat1
            vals = walking_tree_xhats(
                self.module, xhat, self.branching_factors, seed=seed,
                options=self.options,
                num_samples=int(self.options.get("num_eval_samples", 3)))
            seed += 7919
            if not vals:
                global_toc("IndepScens: no feasible evaluation; "
                           "resampling at the schedule's next size")
                G = s = None
                continue
            zhat = float(np.mean(vals))
            # gap vs the sampled-tree optimum at this iteration
            est_batch = self._tree_batch(self.branching_factors,
                                         seed + 13)
            names = list(est_batch.tree.scen_names)
            ef = ExtensiveForm(
                {"pdhg_eps": self.options.get("solver_eps", 1e-7)},
                names, batch=est_batch)
            ef.solve_extensive_form()
            zstar = ef.get_objective_value()
            G = max(zhat - zstar, 0.0)
            s = float(np.std(vals, ddof=1)) if len(vals) > 1 else 0.0
            history.append((n, G, s))
            stop = not self._continue(G, s, max(len(vals), 2))
            global_toc(f"IndepScens iter {k}: n={n} G={G:.6g} "
                       f"s={s:.6g} stop={stop}")
            if stop:
                upper = (self.h * s + self.eps
                         if self.stopping_criterion == "BM"
                         else self.bpl_eps)
                return {"xhat_one": xhat, "G": G, "std": s, "s": s,
                        "num_scens": n, "T": k,
                        "CI": [0.0, float(upper)], "history": history}
        return {"xhat_one": xhat, "G": G, "std": s, "s": s,
                "num_scens": n, "T": self.max_iters, "history": history,
                "stopped": False}

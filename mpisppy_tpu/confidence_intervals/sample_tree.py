"""Sampled subtrees for multistage evaluation (reference:
mpisppy/confidence_intervals/sample_tree.py:18-313 SampleSubtree +
walking_tree_xhats).

For multistage CI estimation, candidates must be evaluated on FRESH
subtrees: given a multistage module (MULTISTAGE = True, build_batch
over branching_factors), `SampleSubtree` builds a new batch whose
stage-1..t decisions are pinned to the candidate and whose later-stage
branches are resampled via the module's seed kwarg.
"""

from __future__ import annotations

import inspect

import numpy as np

from ..utils.xhat_eval import Xhat_Eval


class SampleSubtree:
    def __init__(self, module, xhats, root_scen_inputs=None,
                 starting_stage=1, branching_factors=None, seed=0,
                 options=None):
        self.module = module
        self.xhats = np.asarray(xhats)
        self.stage = int(starting_stage)
        self.branching_factors = list(branching_factors or [3, 3])
        self.seed = int(seed)
        self.options = dict(options or {})
        self.EF_obj = None

    def _build(self):
        kw = dict(self.module.kw_creator(self.options)) if hasattr(
            self.module, "kw_creator") else {}
        kw["branching_factors"] = tuple(self.branching_factors)
        sig = inspect.signature(self.module.build_batch)
        for s in ("seed", "seedoffset", "start_seed"):
            if s in sig.parameters:
                kw[s] = self.seed
                break
        return self.module.build_batch(**kw)

    def run(self):
        """Pin stages <= self.stage to the candidate, solve the
        remaining tree, return E[obj] (the reference solves the
        sub-EF; here it is one batched pinned solve)."""
        batch = self._build()
        names = list(batch.tree.scen_names)
        ev = Xhat_Eval(
            {"pdhg_eps": self.options.get("solver_eps", 1e-7),
             "pdhg_max_iters":
                 self.options.get("solver_max_iters", 100000)},
            names, batch=batch)
        eobj, feas = ev.evaluate(self.xhats, upto_stage=self.stage)
        self.EF_obj = eobj
        return eobj, feas


def walking_tree_xhats(module, xhat_one, branching_factors, seed=0,
                       options=None, num_samples=3):
    """Evaluate a stage-1 candidate over several independently sampled
    trees (reference walking_tree_xhats builds xhats for every node;
    the fan-resampling here serves the same estimator role).  Returns
    the list of sampled-tree expected objectives."""
    vals = []
    for i in range(num_samples):
        st = SampleSubtree(module, xhat_one, starting_stage=1,
                           branching_factors=branching_factors,
                           seed=seed + 1000 * i, options=options)
        eobj, feas = st.run()
        if feas:
            vals.append(eobj)
    return vals

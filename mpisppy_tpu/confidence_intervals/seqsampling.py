"""Sequential sampling (reference:
mpisppy/confidence_intervals/seqsampling.py:110-585) — produce a
candidate xhat together with a confidence interval on its optimality
gap by solving sampled problems of growing size.

Implements both stopping rules of the reference, with the full
parameterization:

* **BM** [Bayraksan & Morton 2011, "A Sequential Sampling Procedure
  for Stochastic Programming"]: continue while
  ``G_k > BM_hprime * s_k + BM_eps_prime``; the deterministic sample
  size schedule is eq. (5)/(14) of the paper,
      n_k >= (c + 2 p ln^2 k) / (h - h')^2          (BM_q is None)
      n_k >= (c + 2 p k^{2q/r}) / (h - h')^2        (BM_q given, r=2)
  with c = max(1, 2 ln( sum_j exp(-p ln^2 j) / (sqrt(2 pi) (1-alpha))))
  (resp. sum_j exp(-p j^{2q/r})).  Final CI: [0, BM_h*s_k + BM_eps].
* **BPL** [Bayraksan & Pierre-Louis 2012, "Fixed-Width Sequential
  Stopping Rules"]: continue while
  ``G_k + t_{alpha,n_k-1} s_k / sqrt(n_k) + 1/sqrt(n_k) > BPL_eps``;
  sample sizes either deterministic
  ``n_k = BPL_c0 + BPL_c1 * growth_function(k)`` (growth_function
  defaults to k-1) or **stochastic** (sec. 5 of the paper,
  `stochastic_sampling=True`): n_1 = max(BPL_n0min, ln(1/eps)), then
  n_k solves the quadratic  -eps n + (1 + t s) sqrt(n) + n_{k-1} G = 0
  in sqrt(n).  Final CI: [0, BPL_eps].

The rule arithmetic lives in the standalone `SamplingRule` class so
consumers with their OWN gap estimate (streaming.AdaptiveSampler feeds
it G/s from a sampled-PH trajectory) can drive the schedule without
inheriting SeqSampling's solve loop.  SeqSampling composes a rule and
mirrors its knobs as instance attributes for back-compat
(multi_seqsampling and user code read `self.h` / `self.bpl_eps` etc.).

Shared options (reference cfg knobs, same names):
  sample_size_ratio — m_k = ratio * n_k scenarios for the xhat solve
  ArRP              — pool G/s from ArRP disjoint sub-estimators
  kf_Gs, kf_xhat    — resampling frequencies: at iterations where
                      k % kf != 0 the previous sample is EXTENDED
                      (same seed, more scenarios) instead of redrawn
  confidence_level  — alpha for quantiles and the c constant
  n0min             — floor on every n_k (this build's extension; the
                      reference has it only for stochastic sampling)

Candidate solves use the batched consensus-EF kernel; evaluation uses
the batched fixed-nonant solve (ciutils.gap_estimators) — both one
kernel launch per sample rather than per scenario.
"""

from __future__ import annotations

import importlib

import numpy as np

from .. import global_toc
from ..opt.ef import ExtensiveForm
from . import ciutils


def _bm_constant(p, q, confidence_level, r=2):
    """The c_p / c_pq constant of [bm2011] eqs. (5)/(14)."""
    j = np.arange(1, 1000)
    if q is None:
        ssum = np.sum(np.power(j.astype(float), -p * np.log(j)))
    else:
        if q < 1:
            raise ValueError("BM_q must be >= 1")
        ssum = np.sum(np.exp(-p * np.power(j.astype(float), 2 * q / r)))
    return max(1.0, 2 * np.log(
        ssum / (np.sqrt(2 * np.pi) * (1 - confidence_level))))


class SamplingRule:
    """Standalone BM/BPL stopping rule + sample-size schedule.

    Stateless between calls: every method takes the current gap
    estimate (G, s) and sample size, so any driver that can produce a
    gap estimate — SeqSampling's sampled-EF loop, the streaming
    AdaptiveSampler, user code — can ask `should_continue` /
    `sample_size` without subclassing anything.  Knob names and
    defaults are exactly SeqSampling's options-dict surface.
    """

    def __init__(self, options=None, stochastic_sampling=False,
                 stopping_criterion="BM"):
        o = dict(options or {})
        if stopping_criterion not in ("BM", "BPL"):
            raise ValueError("Only BM and BPL criteria are supported")
        self.stopping_criterion = stopping_criterion
        self.stochastic_sampling = bool(
            o.get("stochastic_sampling", stochastic_sampling))

        # shared knobs
        self.confidence = float(o.get("confidence_level", 0.95))
        self.n0 = int(o.get("n0min", o.get("nn0min", 10)))

        # BM knobs [bm2011]
        self.h = float(o.get("BM_h", 2.0))
        self.hprime = float(o.get("BM_hprime", 0.0))
        self.eps = float(o.get("BM_eps", 1e-2))
        self.eps_prime = float(o.get("BM_eps_prime", self.eps))
        self.p = float(o.get("BM_p", 0.191))
        self.q = o.get("BM_q", None)
        if self.q is not None:
            self.q = float(self.q)

        # BPL knobs [bpl2012]
        bpl_eps = o.get("BPL_eps", o.get("eps"))
        self.bpl_eps = float(1.0 if bpl_eps is None else bpl_eps)
        self.bpl_c0 = int(o.get("BPL_c0", self.n0))
        self.bpl_c1 = float(o.get("BPL_c1", 2))
        self.growth_function = o.get("growth_function", lambda k: k - 1)
        self.bpl_n0min = int(o.get("BPL_n0min", max(self.n0, 50)))

        self._c = (_bm_constant(self.p, self.q, self.confidence)
                   if stopping_criterion == "BM" else None)

    # -- stopping rules (True = CONTINUE, as in the reference) ------------
    def bm_continue(self, G, s, nk):
        return G > self.hprime * s + self.eps_prime

    def bpl_continue(self, G, s, nk):
        t = ciutils.t_quantile(self.confidence, max(nk - 1, 1))
        return (G + t * s / np.sqrt(nk) + 1.0 / np.sqrt(nk)
                > self.bpl_eps)

    def should_continue(self, G, s, nk):
        if self.stopping_criterion == "BM":
            return self.bm_continue(G, s, nk)
        return self.bpl_continue(G, s, nk)

    # -- sample-size schedules --------------------------------------------
    def bm_sampsize(self, k, G, s, nk_m1, r=2):
        if self.q is None:
            lower = ((self._c + 2 * self.p * np.log(k) ** 2)
                     / (self.h - self.hprime) ** 2)
        else:
            lower = ((self._c + 2 * self.p * k ** (2 * self.q / r))
                     / (self.h - self.hprime) ** 2)
        return int(np.ceil(lower))

    def bpl_fsp_sampsize(self, k, G, s, nk_m1):
        return int(np.ceil(self.bpl_c0
                           + self.bpl_c1 * self.growth_function(k)))

    def stochastic_sampsize(self, k, G, s, nk_m1):
        """[bpl2012] sec. 5: solve -eps*n + (1+t*s)*sqrt(n) + n_{k-1}G
        = 0 for sqrt(n).  Falls back to the initialization size when no
        (G, s) estimate exists yet (e.g. a multistage iteration whose
        evaluation produced no feasible sample)."""
        if k == 1 or G is None or s is None or nk_m1 is None:
            return int(np.ceil(max(self.bpl_n0min,
                                   np.log(1.0 / self.bpl_eps))))
        t = ciutils.t_quantile(self.confidence, max(nk_m1 - 1, 1))
        a = -self.bpl_eps
        bq = 1.0 + t * s
        cq = nk_m1 * G
        disc = max(bq * bq - 4 * a * cq, 0.0)
        maxroot = -(np.sqrt(disc) + bq) / (2 * a)
        return int(np.ceil(maxroot ** 2))

    def sample_size(self, k, G, s, nk_m1):
        if self.stochastic_sampling:
            n = self.stochastic_sampsize(k, G, s, nk_m1)
        elif self.stopping_criterion == "BM":
            n = self.bm_sampsize(k, G, s, nk_m1)
        else:
            n = self.bpl_fsp_sampsize(k, G, s, nk_m1)
        n = max(n, self.n0)
        if nk_m1 is not None:
            n = max(n, nk_m1)      # sample sizes must not shrink
        return n

    # -- the certified interval -------------------------------------------
    def ci_upper(self, s):
        """Upper end of the [0, u] gap CI once should_continue says
        stop: h*s + eps (BM) or the fixed width (BPL)."""
        if self.stopping_criterion == "BM":
            return float(self.h * s + self.eps)
        return float(self.bpl_eps)


# Attributes mirrored from the rule onto SeqSampling instances
# (multi_seqsampling and user code read them there).
_RULE_ATTRS = ("stochastic_sampling", "confidence", "n0",
               "h", "hprime", "eps", "eps_prime", "p", "q",
               "bpl_eps", "bpl_c0", "bpl_c1", "growth_function",
               "bpl_n0min", "_c")


class SeqSampling:
    def __init__(self, mname, optionsdict, seed=0,
                 stochastic_sampling=False,
                 stopping_criterion="BM", solving_type="EF_2stage"):
        self.module = (mname if not isinstance(mname, str)
                       else importlib.import_module(mname))
        self.options = dict(optionsdict or {})
        self.seed = int(seed)
        self.stopping_criterion = stopping_criterion
        self.solving_type = solving_type
        self.rule = SamplingRule(
            self.options, stochastic_sampling=stochastic_sampling,
            stopping_criterion=stopping_criterion)
        for a in _RULE_ATTRS:
            setattr(self, a, getattr(self.rule, a))
        o = self.options

        # loop-only knobs (not part of the rule arithmetic)
        self.sample_size_ratio = float(o.get("sample_size_ratio", 1))
        self.ArRP = int(o.get("ArRP", 1))
        self.kf_Gs = int(o.get("kf_Gs", 1))
        self.kf_xhat = int(o.get("kf_xhat", 1))
        self.max_iters = int(o.get("max_seq_iters", 200))

    # -- delegation to the rule (back-compat method names) ----------------
    def _bm_continue(self, G, s, nk):
        return self.rule.bm_continue(G, s, nk)

    def _bpl_continue(self, G, s, nk):
        return self.rule.bpl_continue(G, s, nk)

    def _continue(self, G, s, nk):
        return self.rule.should_continue(G, s, nk)

    def _bm_sampsize(self, k, G, s, nk_m1, r=2):
        return self.rule.bm_sampsize(k, G, s, nk_m1, r=r)

    def _bpl_fsp_sampsize(self, k, G, s, nk_m1):
        return self.rule.bpl_fsp_sampsize(k, G, s, nk_m1)

    def _stochastic_sampsize(self, k, G, s, nk_m1):
        return self.rule.stochastic_sampsize(k, G, s, nk_m1)

    def _sample_size(self, k, G, s, nk_m1):
        return self.rule.sample_size(k, G, s, nk_m1)

    # -- candidate solve ---------------------------------------------------
    def _candidate(self, n, seed):
        """Solve a sampled EF -> root xhat (reference xhat_generator_*
        helpers: sampled-amalgamator EF solve)."""
        batch = ciutils.sample_batch(self.module, n, seed, self.options)
        names = list(batch.tree.scen_names)[:n]
        ef = ExtensiveForm(
            {"pdhg_eps": self.options.get("solver_eps", 1e-7),
             "pdhg_max_iters":
                 self.options.get("solver_max_iters", 100000)},
            names, batch=batch)
        ef.solve_extensive_form()
        return np.asarray(ef.get_root_solution())

    # -- main loop (reference seqsampling.py:330-527 run) ------------------
    def run(self, maxit=None):
        maxit = maxit or self.max_iters
        mult = self.sample_size_ratio
        nk = None
        # xhat and estimator samples live in DISJOINT seed regions so a
        # kf-driven sample EXTENSION (same seed, larger n) can never
        # grow into scenarios the other side has drawn — overlap would
        # evaluate the candidate on its own training scenarios and bias
        # G downward, voiding the BM/BPL guarantee.  (The reference
        # keeps disjointness through a single ScenCount because its
        # extensions append NEW scenario names; seed-block sampling
        # needs the region split instead.)
        _REGION = 10_000_000
        xhat_seed = self.seed              # current xhat sample seed
        xhat_next = self.seed              # next unused seed, region A
        est_seed = self.seed + _REGION     # current estimator seed
        est_next = self.seed + _REGION     # next unused seed, region B
        history = []
        xhat = G = s = None
        stopped = False
        for k in range(1, maxit + 1):
            nk_m1 = nk
            nk = self._sample_size(k, G, s, nk_m1)
            nk = self.ArRP * int(np.ceil(nk / self.ArRP))
            mk = max(int(np.floor(mult * nk)), 1)

            # xhat sample: redraw at k % kf_xhat == 0, else extend
            # (same seed, larger n = previous draws plus new ones)
            if k == 1 or k % self.kf_xhat == 0:
                xhat_seed = xhat_next
            xhat_next = max(xhat_next, xhat_seed + mk)
            xhat = self._candidate(mk, xhat_seed)

            # estimator sample: redraw at k % kf_Gs == 0, else extend
            if k == 1 or k % self.kf_Gs == 0:
                est_seed = est_next
            est_next = max(est_next, est_seed + nk)
            est = ciutils.gap_estimators(
                xhat, self.module, solving_type=self.solving_type,
                num_scens=nk, seed=est_seed, cfg=self.options,
                ArRP=self.ArRP)
            G, s = est["G"], est["std"]
            history.append((nk, G, s))
            cont = self._continue(G, s, nk)
            global_toc(f"SeqSampling iter {k}: n={nk} m={mk} "
                       f"G={G:.6g} s={s:.6g} continue={cont}")
            if not cont:
                stopped = True
                break

        upper = self.rule.ci_upper(s)
        out = {"xhat_one": xhat, "G": G, "std": s, "s": s,
               "num_scens": nk, "T": k, "CI": [0.0, float(upper)],
               "Candidate_solution": xhat,
               "history": history, "seed": est_next}
        if not stopped:
            out["stopped"] = False
        return out

"""Sequential sampling (reference:
mpisppy/confidence_intervals/seqsampling.py:110-585 — Bayraksan &
Morton (BM) and Bayraksan & Pierre-Louis (BPL) stopping rules that
produce an xhat with a gap guarantee).

Loop (reference :265-330): at iteration k, draw n_k scenarios, solve
the sampled EF for a candidate xhat_k, estimate (G_k, s_k) on an
independent sample, and stop when the rule fires:
    BM :  G_k <= h * s_k + eps
    BPL:  G_k + t * s_k / sqrt(n_k) <= eps'   (fixed-width)
growing n_k geometrically otherwise.
"""

from __future__ import annotations

import importlib

import numpy as np

from .. import global_toc
from ..opt.ef import ExtensiveForm
from . import ciutils


class SeqSampling:
    def __init__(self, mname, optionsdict, seed=0,
                 stopping_criterion="BM", solving_type="EF_2stage"):
        self.module = (mname if not isinstance(mname, str)
                       else importlib.import_module(mname))
        self.options = dict(optionsdict or {})
        self.seed = int(seed)
        self.stopping_criterion = stopping_criterion
        self.solving_type = solving_type
        # rule parameters (reference defaults)
        self.n0 = int(self.options.get("n0min",
                                       self.options.get("nn0min", 10)))
        self.growth = float(self.options.get("growth_factor", 1.5))
        self.max_iters = int(self.options.get("kf_Gs",
                             self.options.get("max_seq_iters", 10)))
        self.h = float(self.options.get("BM_h", 2.0))
        self.eps = float(self.options.get("BM_eps", 1e-2))
        eps_prime = self.options.get("BPL_eps")
        if eps_prime is None:
            eps_prime = self.options.get("eps")
        self.eps_prime = float(1.0 if eps_prime is None else eps_prime)
        self.confidence = float(self.options.get("confidence_level",
                                                 0.95))

    def _candidate(self, n, seed):
        """Solve a sampled EF -> root xhat (reference run():
        approximate_solve)."""
        batch = ciutils.sample_batch(self.module, n, seed, self.options)
        names = list(batch.tree.scen_names)[:n]
        ef = ExtensiveForm(
            {"pdhg_eps": self.options.get("solver_eps", 1e-7),
             "pdhg_max_iters":
                 self.options.get("solver_max_iters", 100000)},
            names, batch=batch)
        ef.solve_extensive_form()
        return np.asarray(ef.get_root_solution())

    def run(self):
        n = self.n0
        seed = self.seed
        history = []
        for k in range(1, self.max_iters + 1):
            xhat = self._candidate(n, seed)
            seed += n
            est = ciutils.gap_estimators(
                xhat, self.module, solving_type=self.solving_type,
                num_scens=n, seed=seed, cfg=self.options)
            seed = est["seed"]
            G, s = est["G"], est["std"]
            history.append((n, G, s))
            if self.stopping_criterion == "BM":
                stop = G <= self.h * s + self.eps
            else:   # BPL fixed-width
                tq = ciutils.t_quantile(self.confidence, max(n - 1, 1))
                stop = G + tq * s / np.sqrt(n) <= self.eps_prime
            global_toc(f"SeqSampling iter {k}: n={n} G={G:.6g} "
                       f"s={s:.6g} stop={stop}")
            if stop:
                return {"xhat_one": xhat, "G": G, "std": s,
                        "num_scens": n, "T": k, "history": history,
                        "seed": seed}
            n = int(np.ceil(n * self.growth))
        return {"xhat_one": xhat, "G": G, "std": s, "num_scens": n,
                "T": self.max_iters, "history": history, "seed": seed,
                "stopped": False}

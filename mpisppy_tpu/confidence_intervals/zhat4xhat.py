"""zhat4xhat — t-interval on z(xhat) for a fixed candidate (reference:
mpisppy/confidence_intervals/zhat4xhat.py:15-200).

Evaluates xhat on `num_samples` independent scenario batches and
returns the mean and a symmetric t confidence interval.
"""

from __future__ import annotations

import importlib

import numpy as np

from .. import global_toc
from ..utils.xhat_eval import Xhat_Eval
from . import ciutils


def evaluate_sample(module, xhat, num_scens, seed, options=None):
    batch = ciutils.sample_batch(module, num_scens, seed, options)
    names = list(batch.tree.scen_names)[:num_scens]
    ev = Xhat_Eval(
        {"pdhg_eps": (options or {}).get("solver_eps", 1e-7)},
        names, batch=batch)
    eobj, feas = ev.evaluate(np.asarray(xhat))
    if not feas:
        raise RuntimeError(
            "zhat4xhat: candidate infeasible on the sampled batch")
    return eobj


def zhat4xhat(mname, xhat, num_samples=5, sample_size=10, seed=0,
              confidence_level=0.95, options=None):
    m = (mname if not isinstance(mname, str)
         else importlib.import_module(mname))
    zhats = []
    for i in range(num_samples):
        zhats.append(evaluate_sample(m, xhat, sample_size,
                                     seed + i * sample_size, options))
    zhat_bar = float(np.mean(zhats))
    s = float(np.std(zhats, ddof=1)) if num_samples > 1 else 0.0
    tq = ciutils.t_quantile(
        0.5 + confidence_level / 2.0, max(num_samples - 1, 1))
    half = tq * s / np.sqrt(num_samples)
    global_toc(f"zhat4xhat: {zhat_bar:.6g} +/- {half:.6g} "
               f"({confidence_level:.0%})")
    return zhat_bar, s, (zhat_bar - half, zhat_bar + half)

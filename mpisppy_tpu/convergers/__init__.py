from .converger import Converger  # noqa: F401

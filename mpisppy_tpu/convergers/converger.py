"""Converger base class (reference: mpisppy/convergers/converger.py:18).

A converger is constructed with the optimizer and polled once per PH
iteration (phbase.iterk_loop); `is_converged()` returning True stops
the loop.  `convergence_value` holds the last computed metric for
reporting.
"""

from __future__ import annotations


class Converger:
    def __init__(self, opt):
        self.opt = opt
        self.conv = None
        self.convergence_value = None

    def is_converged(self) -> bool:
        raise NotImplementedError

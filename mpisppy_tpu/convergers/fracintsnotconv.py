"""FractionalConverger — fraction of integer nonants not yet agreed
(reference: mpisppy/convergers/fracintsnotconv.py:13).

An integer slot "agrees" when every scenario's value is within
`options["fracintsnotconv_tol"]` (default 1e-4) of the slot's rounded
xbar.  Converged when the not-agreed fraction drops below
options["fracintsnotconv_thresh"] (default 0, i.e. all agree).
"""

from __future__ import annotations

import numpy as np

from .. import global_toc
from .converger import Converger


class FractionalConverger(Converger):
    def __init__(self, opt):
        super().__init__(opt)
        o = opt.options
        self.tol = float(o.get("fracintsnotconv_tol", 1e-4))
        self.thresh = float(o.get("fracintsnotconv_thresh", 0.0))
        b = opt.batch
        self._int_slot = np.asarray(
            b.integer_mask)[:, np.asarray(b.nonant_idx)]
        self._n_int = max(int(self._int_slot.any(axis=0).sum()), 1)

    def is_converged(self):
        st = self.opt.state
        if st is None:
            return False
        x_na = np.asarray(self.opt.batch.nonants(st.x))
        xbar = np.asarray(st.xbar)
        target = np.round(xbar)
        # a slot disagrees if ANY scenario's integer value strays
        bad = self._int_slot & (np.abs(x_na - target) > self.tol)
        frac = bad.any(axis=0).sum() / self._n_int
        self.convergence_value = float(frac)
        if frac <= self.thresh:
            global_toc(f"FractionalConverger: {frac:.3f} <= {self.thresh}")
            return True
        return False

"""NormRhoConverger (reference: mpisppy/convergers/norm_rho_converger.py:12).

Declares convergence when the rho-weighted primal residual
    sum_s p_s || rho * (x_s - xbar) ||_1 / K
drops below options["norm_rho_converger_tol"] (default 1e-4) — the dual
step size PH is about to take.
"""

from __future__ import annotations

import numpy as np

from .. import global_toc
from .converger import Converger


class NormRhoConverger(Converger):
    def __init__(self, opt):
        super().__init__(opt)
        self.tol = float(opt.options.get("norm_rho_converger_tol", 1e-4))

    def is_converged(self):
        st = self.opt.state
        if st is None:
            return False
        b = self.opt.batch
        x_na = np.asarray(b.nonants(st.x))
        xbar = np.asarray(st.xbar)
        rho = np.asarray(self.opt.rho)
        p = np.asarray(b.prob)[:, None]
        val = float(np.sum(p * np.abs(rho * (x_na - xbar)))
                    / max(x_na.shape[1], 1))
        self.convergence_value = val
        if val < self.tol:
            global_toc(f"NormRhoConverger: {val:.3e} < {self.tol}")
            return True
        return False

"""PrimalDualConverger (reference:
mpisppy/convergers/primal_dual_converger.py:9-161).

Tracks  ||primal residual|| + ||dual residual||  where
    primal = sum_s p_s ||x_s - xbar||_1
    dual   = sum_s p_s ||rho*(xbar - xbar_prev)||_1
and converges below options["primal_dual_converger_options"]["tol"]
(default 1e-4).  Optionally appends the history to a CSV
("tracking_csv") — the reference plots; a CSV is the headless analog.
"""

from __future__ import annotations

import csv
import os

import numpy as np

from .. import global_toc
from .converger import Converger


class PrimalDualConverger(Converger):
    def __init__(self, opt):
        super().__init__(opt)
        o = opt.options.get("primal_dual_converger_options") or {}
        self.tol = float(o.get("tol", 1e-4))
        self.csv_path = o.get("tracking_csv")
        self._xbar_prev = None
        self.history = []

    def is_converged(self):
        st = self.opt.state
        if st is None:
            return False
        b = self.opt.batch
        x_na = np.asarray(b.nonants(st.x))
        xbar = np.asarray(st.xbar)
        p = np.asarray(b.prob)[:, None]
        prim = float(np.sum(p * np.abs(x_na - xbar)))
        if self._xbar_prev is None:
            dual = float("inf")
        else:
            rho = np.asarray(self.opt.rho)
            dual = float(np.sum(p * np.abs(rho * (xbar - self._xbar_prev))))
        self._xbar_prev = xbar
        val = prim + dual
        self.convergence_value = val
        self.history.append((int(st.it), prim, dual))
        if self.csv_path:
            new = not os.path.exists(self.csv_path)
            with open(self.csv_path, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["iteration", "primal", "dual"])
                w.writerow([int(st.it), prim, dual])
        if val < self.tol:
            global_toc(f"PrimalDualConverger: {prim:.3e}+{dual:.3e} "
                       f"< {self.tol}")
            return True
        return False

"""Cross-scenario cut spoke (reference:
mpisppy/cylinders/cross_scen_spoke.py:45-296).

Receives the hub's nonant candidate, solves every scenario with
nonants pinned (one batched call), and ships back an AGGREGATE
optimality cut of the expected value function at that candidate:

    E[f](x)  >=  Eq + Egrad . (x_na - xhat)

where Eq = sum_s p_s q_s(xhat) and Egrad = sum_s p_s dq_s/dxhat (the
reduced costs at the pinned slots — free from the first-order solver,
SURVEY.md §2.9).  The reference ships an (nscen x (nonants+2)) per-
scenario coefficient matrix; on TPU the aggregation happens spoke-side
(one psum) and the hub-side extension installs one cut per pass.

Wire format to hub: [Eq | Egrad (K,) | xhat (K,)] (length 2K+1).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from .spoke import ConvergerSpokeType, _BoundNonantSpoke


class CrossScenarioCutSpoke(_BoundNonantSpoke):
    converger_spoke_types = (ConvergerSpokeType.NONANT_GETTER,)
    converger_spoke_char = "C"
    provides_cuts = True      # hub auto-wires attach_spoke extensions

    def send_length(self):
        K = self.opt.batch.num_nonants
        return 2 * K + 1

    def step(self):
        nonants, is_new = self.fresh_nonants()
        if self._killed or not is_new:
            return False
        b = self.opt.batch
        S = self.opt.n_real_scens
        K = b.num_nonants
        # candidate = prob-weighted average of the hub's per-scenario
        # nonants (they agree at consensus; early on this is xbar)
        p = np.asarray(b.prob)[:, None]
        xhat = (p * np.asarray(nonants)).sum(axis=0) / p.sum()

        lb, ub = self.opt.fixed_nonant_bounds(jnp.asarray(xhat))
        res = self.opt.solve_loop(lb=lb, ub=ub, warm=True)
        q = np.asarray(res.obj)[:S]
        aty = jnp.einsum("smn,sm->sn", b.A, res.y)
        rc = np.asarray(b.c + b.qdiag * res.x + aty)[:S]
        grad = rc[:, np.asarray(b.nonant_idx)]
        pr = np.asarray(b.prob)[:S]
        pr = pr / pr.sum()
        Eq = float(pr @ q)
        Egrad = pr @ grad
        self.spoke_to_hub(np.concatenate([[Eq], Egrad, xhat]))
        return True

    def finalize(self):
        return None

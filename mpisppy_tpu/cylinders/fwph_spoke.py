"""FrankWolfeOuterBound spoke (reference:
mpisppy/cylinders/fwph_spoke.py:5-33).

Wraps an FWPH optimizer as an outer-bound cylinder: each step runs one
FWPH outer pass and posts the newest dual bound.  Consumes nothing from
the hub (the reference spoke likewise runs fwph_main independently).
"""

from __future__ import annotations

from .spoke import ConvergerSpokeType, _BoundSpoke


class FrankWolfeOuterBound(_BoundSpoke):
    converger_spoke_types = (ConvergerSpokeType.OUTER_BOUND,)
    converger_spoke_char = "F"

    def receive_length(self):
        return 1   # hub pushes nothing this spoke consumes

    def main(self):
        """Threaded-mode loop WITHOUT the serial-number gate of the
        base class: this spoke consumes nothing from the hub (its
        write_id never advances), it just produces bounds until
        killed — like the reference's independent fwph_main cylinder."""
        while not self.got_kill_signal():
            self.step()

    def step(self):
        opt = self.opt
        if not getattr(opt, "_prepped", False):
            opt.fw_prep()
        opt.fwph_iteration()
        if opt.dual_bound is not None:
            self.update_if_improving(opt.dual_bound)
        return True

    def finalize(self):
        return self.bound

"""Hub classes (reference: mpisppy/cylinders/hub.py).

The hub runs the main algorithm (PH/APH/L-shaped), pushes W / nonant /
bound vectors to spokes, pulls bounds back, tracks BestInnerBound /
BestOuterBound, and decides gap-based termination
(rel_gap / abs_gap / max_stalled_iters — reference hub.py:125-161).
"""

from __future__ import annotations

import collections

import numpy as np

from .. import global_toc
from ..resilience.bounds import BoundGuard
from .spcommunicator import SPCommunicator, WindowPair
from .spoke import ConvergerSpokeType


class Hub(SPCommunicator):
    def __init__(self, spbase_object, spokes=(), options=None):
        super().__init__(spbase_object, options=options)
        self.spokes = list(spokes)     # Spoke instances (wired later)
        self.pairs = []                # WindowPair per spoke
        # bound state (reference hub.py:229-239 initialize_bound_values)
        if self.opt.is_minimizing:
            self.BestInnerBound = np.inf
            self.BestOuterBound = -np.inf
            self._ib_better = lambda new, old: new < old
            self._ob_better = lambda new, old: new > old
        else:
            self.BestInnerBound = -np.inf
            self.BestOuterBound = np.inf
            self._ib_better = lambda new, old: new > old
            self._ob_better = lambda new, old: new < old
        # screen trace state (reference hub.py:36-40, 111-123)
        self.print_init = True
        self.latest_ib_char = None
        self.latest_ob_char = None
        # stall tracking (reference hub.py:41-42)
        self.stalled_iter_cnt = 0
        self.last_gap = float("inf")
        self.best_nonant_solution = None   # incumbent (K,) or (S,K)
        # interleaved mode: the hub drives spoke.step() inline during
        # sync() (single-program scheduling, SURVEY.md §7.6); threaded
        # mode clears this and spokes loop in their own threads
        self.drive_spokes_inline = True
        # graceful degradation (beyond the reference, where a lost MPI
        # rank aborts the job): a spoke whose step raises is REMOVED
        # from the wheel — its wiring indices are pruned so the hub
        # neither feeds it nor accepts anything further from it — and
        # the run completes on the hub's own valid bounds.  Threaded
        # spokes report failures through a queue drained on the hub
        # thread (the index sets must not be mutated concurrently).
        self.failed_spokes = []
        # deque: appends from spoke threads race the hub-thread drain
        self._failed_queue = collections.deque()
        # multiproc-mode process supervision (resilience/supervisor.py);
        # set by the wheel, polled from sync()
        self.supervisor = None
        self.spoke_exit_reports = []
        # bound hygiene at the window-read boundary
        # (resilience/bounds.py): a sick spoke degrades (rejected
        # messages + eventual pruning) instead of corrupting
        # BestInnerBound/BestOuterBound
        self._bound_guard = (
            BoundGuard(rtol=self.options.get("bound_cross_rtol", 1e-2))
            if self.options.get("bound_guard", True) else None)
        self._max_bound_rejects = int(
            self.options.get("max_bound_rejects", 25))
        # payload-level integrity budget (read_checked rejections —
        # checksum mismatch / write_id regression) per spoke; past it
        # the spoke is pruned like a crashed one
        self._max_corrupt_reads = int(
            self.options.get("max_corrupt_reads", 10))
        # bound-progression + reject telemetry (null no-ops when off)
        self._c_rejects = self.telemetry.counter("window.bound_rejects")
        self._c_corrupt = self.telemetry.counter("wheel.corrupt_reads")
        self._g_outer = self.telemetry.gauge("hub.best_outer")
        self._g_inner = self.telemetry.gauge("hub.best_inner")

    def _mark_spoke_failed(self, i, exc):
        """Prune spoke i out of every wiring set (hub thread only)."""
        sp = self.spokes[i]
        if getattr(sp, "_failed", False):
            return                      # already pruned (racing reports)
        sp._failed = True
        for idx_set in (self.outerbound_idx, self.innerbound_idx,
                        self.w_idx, self.nonant_idx_set):
            idx_set.discard(i)
        self.has_outerbound_spokes = bool(self.outerbound_idx)
        self.has_innerbound_spokes = bool(self.innerbound_idx)
        # multiproc SpokeHandles carry the real spoke class in
        # spoke_name (the handle type itself would be meaningless)
        name = getattr(sp, "spoke_name", type(sp).__name__)
        self.failed_spokes.append((name, str(exc)))
        global_toc(f"WARNING: spoke {name} failed and "
                   f"was removed from the wheel: {exc}")

    def report_spoke_failure(self, spoke, exc):
        """Thread-safe failure report (threaded-mode spoke threads);
        applied by _drain_failures on the hub thread."""
        self._failed_queue.append((spoke, exc))

    def _drain_failures(self):
        while True:
            try:
                spoke, exc = self._failed_queue.popleft()
            except IndexError:
                break
            try:
                i = self.spokes.index(spoke)
            except ValueError:
                continue                # unknown reporter; nothing to prune
            if not getattr(spoke, "_failed", False):
                self._mark_spoke_failed(i, exc)

    def _step_spokes(self):
        for i, sp in enumerate(self.spokes):
            if getattr(sp, "_failed", False):
                continue
            try:
                # in-process Spokes expose the traced step; multiproc
                # SpokeHandles only a bare no-op step()
                getattr(sp, "timed_step", sp.step)()
            except Exception as e:
                self._mark_spoke_failed(i, e)

    # -- wiring (reference hub.py:297-368 initialize_spoke_indices +
    #    make_windows) ----------------------------------------------------
    def wire_spokes(self):
        self.outerbound_idx, self.innerbound_idx = set(), set()
        self.w_idx, self.nonant_idx_set = set(), set()
        self.spoke_chars = {}
        self.pairs = []
        for i, sp in enumerate(self.spokes):
            for cst in sp.converger_spoke_types:
                if cst == ConvergerSpokeType.OUTER_BOUND:
                    self.outerbound_idx.add(i)
                elif cst == ConvergerSpokeType.INNER_BOUND:
                    self.innerbound_idx.add(i)
                elif cst == ConvergerSpokeType.W_GETTER:
                    self.w_idx.add(i)
                elif cst == ConvergerSpokeType.NONANT_GETTER:
                    self.nonant_idx_set.add(i)
            self.spoke_chars[i] = sp.converger_spoke_char
            prefix = self.options.get("window_path_prefix")
            # per-spoke backend kwargs (keyed by spoke index) stay
            # opaque here: the mpmd wheel passes device placements for
            # its "device" pairs; the hub never learns mpmd specifics
            bkw = self.options.get("window_backend_kwargs") or {}
            pair = WindowPair(
                hub_length=sp.receive_length(),
                spoke_length=sp.send_length(),
                backend=self.options.get("window_backend", "python"),
                path_prefix=None if prefix is None else f"{prefix}{i}",
                backend_kwargs=bkw.get(i))
            sp.pair = pair
            self.pairs.append(pair)
        self._spoke_read_ids = np.zeros(len(self.spokes), np.int64)
        self.bound_rejects = np.zeros(len(self.spokes), np.int64)
        self.corrupt_reads = np.zeros(len(self.spokes), np.int64)
        self.has_outerbound_spokes = bool(self.outerbound_idx)
        self.has_innerbound_spokes = bool(self.innerbound_idx)
        # auto-wire extensions that consume a spoke's feed (the
        # cross-scenario cut extension reads its spoke's window)
        ext = getattr(self.opt, "extobject", None)
        if ext is not None:
            targets = [ext] + list(getattr(ext, "extensions", []))
            for e in targets:
                if hasattr(e, "attach_spoke"):
                    for sp in self.spokes:
                        # spokes advertise a feed via this class attr
                        # (CrossScenarioCutSpoke and subclasses)
                        if getattr(sp, "provides_cuts", False):
                            e.attach_spoke(sp)

    # -- gap machinery (reference hub.py:77-161) --------------------------
    def compute_gaps(self):
        if self.opt.is_minimizing:
            abs_gap = self.BestInnerBound - self.BestOuterBound
        else:
            abs_gap = self.BestOuterBound - self.BestInnerBound
        if (np.isfinite(abs_gap) and np.isfinite(self.BestOuterBound)
                and self.BestOuterBound != 0):
            rel_gap = abs_gap / abs(self.BestOuterBound)
        else:
            rel_gap = float("inf")
        return abs_gap, rel_gap

    def determine_termination(self):
        o = self.options
        if not any(k in o for k in
                   ("rel_gap", "abs_gap", "max_stalled_iters")):
            return False
        abs_gap, rel_gap = self.compute_gaps()
        rel_ok = "rel_gap" in o and rel_gap <= o["rel_gap"]
        abs_ok = "abs_gap" in o and abs_gap <= o["abs_gap"]
        stalled = False
        if "max_stalled_iters" in o:
            if abs_gap < self.last_gap:
                self.last_gap = abs_gap
                self.stalled_iter_cnt = 0
            else:
                self.stalled_iter_cnt += 1
                stalled = self.stalled_iter_cnt >= o["max_stalled_iters"]
        if abs_ok:
            global_toc(f"Terminating: abs gap {abs_gap:12.4f}")
        if rel_ok:
            global_toc(f"Terminating: rel gap {rel_gap*100:12.3f}%")
        if stalled:
            global_toc(f"Terminating: stalled {self.stalled_iter_cnt} iters")
        return abs_ok or rel_ok or stalled

    def screen_trace(self):
        abs_gap, rel_gap = self.compute_gaps()
        src = ((self.latest_ob_char or " ")
               + " " + (self.latest_ib_char or " "))
        if self.print_init:
            global_toc(f'{"Iter.":>5s}  {"   "}  {"Best Bound":>14s}  '
                       f'{"Best Incumbent":>14s}  {"Rel. Gap":>12s}  '
                       f'{"Abs. Gap":>14s}')
            self.print_init = False
        global_toc(f"{self.current_iteration():5d}  {src}  "
                   f"{self.BestOuterBound:14.4f}  "
                   f"{self.BestInnerBound:14.4f}  "
                   f"{rel_gap*100:12.3f}%  {abs_gap:14.4f}")
        self.latest_ib_char = None
        self.latest_ob_char = None

    # -- bound intake (reference hub.py:174-227) --------------------------
    def _accept_bound(self, kind, value, i):
        """Window-read hygiene: screen one incoming bound; on reject,
        count it and (past the budget) prune the spoke.  Returns True
        iff the bound may enter Best{Inner,Outer}Bound."""
        if self._bound_guard is None:
            return True
        ok, reason = self._bound_guard.check(
            kind, value, inner=self.BestInnerBound,
            outer=self.BestOuterBound,
            minimizing=self.opt.is_minimizing)
        if ok:
            return True
        self.bound_rejects[i] += 1
        self._c_rejects.inc()
        self.telemetry.event("hub.bound_reject", spoke=i, kind=kind,
                             reason=str(reason))
        n = int(self.bound_rejects[i])
        if n == 1 or n % 10 == 0:       # don't spam a steady NaN stream
            name = getattr(self.spokes[i], "spoke_name",
                           type(self.spokes[i]).__name__)
            global_toc(f"WARNING: rejected bound from spoke {i} "
                       f"({name}): {reason} "
                       f"[{n} rejected so far]")
        if (n >= self._max_bound_rejects
                and not getattr(self.spokes[i], "_failed", False)):
            self._mark_spoke_failed(i, RuntimeError(
                f"{n} rejected bounds (last: {reason})"))
        return False

    def _read_spoke_checked(self, i):
        """Integrity-guarded window read of spoke i's to_hub mailbox:
        (data, write_id, ok).  Backends without read_checked (the
        multiproc SpokeHandle / NativeWindow path) fall back to the
        plain read and are always ok.  A rejected snapshot counts into
        the per-spoke corrupt-read budget — past it the spoke is pruned
        exactly like a crashed one (and the MPMD supervisor reslices)."""
        win = self.pairs[i].to_hub
        rc = getattr(win, "read_checked", None)
        if rc is None:
            data, wid = win.read()
            return data, wid, True
        data, wid, ok, reason = rc()
        if ok:
            return data, wid, True
        self.corrupt_reads[i] += 1
        self._c_corrupt.inc()
        self.telemetry.event("hub.corrupt_read", spoke=i,
                             reason=str(reason))
        n = int(self.corrupt_reads[i])
        name = getattr(self.spokes[i], "spoke_name",
                       type(self.spokes[i]).__name__)
        if n == 1 or n % 10 == 0:
            global_toc(f"WARNING: rejected corrupt window read from "
                       f"spoke {i} ({name}): {reason} "
                       f"[{n} rejected so far]")
        if (n >= self._max_corrupt_reads
                and not getattr(self.spokes[i], "_failed", False)):
            self._mark_spoke_failed(i, RuntimeError(
                f"{n} corrupt window reads (last: {reason})"))
        return data, wid, False

    def receive_outerbounds(self):
        for i in list(self.outerbound_idx):
            data, wid, ok = self._read_spoke_checked(i)
            self._c_reads.inc()
            if not ok:
                continue
            if wid > self._spoke_read_ids[i]:
                self._spoke_read_ids[i] = wid
                if self._accept_bound("outer", float(data[0]), i):
                    self.OuterBoundUpdate(float(data[0]), i)
            else:
                self._c_stale.inc()

    def receive_innerbounds(self):
        for i in list(self.innerbound_idx):
            data, wid, ok = self._read_spoke_checked(i)
            self._c_reads.inc()
            if not ok:
                continue
            if wid > self._spoke_read_ids[i]:
                self._spoke_read_ids[i] = wid
                if not self._accept_bound("inner", float(data[0]), i):
                    continue
                self.InnerBoundUpdate(float(data[0]), i)
                sol = getattr(self.spokes[i], "best_solution", None)
                if sol is not None and self.BestInnerBound == float(data[0]):
                    self.best_nonant_solution = sol
            else:
                self._c_stale.inc()

    def _record_bound(self, kind, value, gauge):
        """Bound-progression telemetry: a gauge for snapshots plus a
        Chrome counter sample so Perfetto graphs hub.bounds over the
        run (finite values only — Chrome counters are numeric JSON)."""
        if self.telemetry.enabled and np.isfinite(value):
            gauge.set(value)
            self.telemetry.tracer.counter("hub.bounds", {kind: value})

    def OuterBoundUpdate(self, new_bound, idx=None, char="*"):
        if self._ob_better(new_bound, self.BestOuterBound):
            self.latest_ob_char = (self.spoke_chars.get(idx, char)
                                   if idx is not None else char)
            self.BestOuterBound = new_bound
            self._record_bound("outer", new_bound, self._g_outer)
        return self.BestOuterBound

    def InnerBoundUpdate(self, new_bound, idx=None, char="*"):
        if self._ib_better(new_bound, self.BestInnerBound):
            self.latest_ib_char = (self.spoke_chars.get(idx, char)
                                   if idx is not None else char)
            self.BestInnerBound = new_bound
            self._record_bound("inner", new_bound, self._g_inner)
        return self.BestInnerBound

    # -- outbound (reference hub.py:370-436) ------------------------------
    def send_terminate(self):
        for pair in self.pairs:
            pair.to_spoke.send_kill()
            self._c_kills.inc()

    def hub_finalize(self):
        self._drain_failures()
        self.receive_outerbounds()
        self.receive_innerbounds()
        # surface nonzero spoke exits + their log tails (multiproc
        # mode; collected by the supervisor) instead of discarding them
        for rep in self.spoke_exit_reports:
            how = "hung" if rep.get("hung") else f"rc={rep['rc']}"
            tail = rep.get("log_tail") or ""
            global_toc(
                f"WARNING: spoke {rep['spoke']} ({rep['name']}) "
                f"incarnation {rep['incarnation']} {how}"
                + (f"; log tail:\n{tail}" if tail.strip() else ""))
        global_toc("Statistics at termination")
        self.print_init = True
        self.screen_trace()

    def current_iteration(self):
        raise NotImplementedError

    def main(self):
        raise NotImplementedError


class PHHub(Hub):
    """PH as hub (reference hub.py:453-598): sync() sends Ws + nonants,
    receives bounds; is_converged() seeds the outer bound with PH's
    trivial bound and applies gap termination."""

    def setup_hub(self):
        self.wire_spokes()
        self._iter_for_trace = 0

    def sync(self):
        with self.telemetry.span("hub.sync"):
            self._drain_failures()
            if self.supervisor is not None:
                self.supervisor.poll()
                # elastic recovery barrier: a supervisor that reslices
                # (SliceSupervisor.on_sync) does it here, between the
                # failure drain and this superstep's sends — the next
                # W/nonant push already reflects the new plan
                on_sync = getattr(self.supervisor, "on_sync", None)
                if on_sync is not None:
                    on_sync()
            self.send_ws()
            self.send_nonants()
            if self.drive_spokes_inline:
                self._step_spokes()
            self.receive_outerbounds()
            self.receive_innerbounds()
            if self.supervisor is not None:
                # ensemble checkpoint hook: end-of-sync is the wheel's
                # consistent cut (hub state committed, spokes stepped,
                # bounds received)
                end = getattr(self.supervisor, "on_sync_end", None)
                if end is not None:
                    end()

    def is_converged(self):
        # seed outer bound with the trivial bound once (reference
        # hub.py:519-547)
        if (not np.isfinite(self.BestOuterBound)
                and self.opt.trivial_bound is not None):
            self.OuterBoundUpdate(self.opt.trivial_bound, char="B")
        if not self.has_innerbound_spokes:
            if self.opt.conv is not None and \
                    self.opt.conv < self.options.get("convthresh", -1):
                return True
            return False
        self.screen_trace()
        return self.determine_termination()

    def current_iteration(self):
        st = self.opt.state
        return int(st.it) if st is not None else 0

    def main(self):
        return self.opt.ph_main(finalize=False)

    def send_nonants(self):
        """Push current per-scenario nonant values (reference
        hub.py:562)."""
        st = self.opt.state
        if st is None:
            return
        x_na = np.asarray(self.opt.batch.nonants(st.x)).reshape(-1)
        for i in self.nonant_idx_set:
            self.pairs[i].to_spoke.write(x_na)
            self._c_writes.inc()

    def send_ws(self):
        """Push current W (reference hub.py:590)."""
        st = self.opt.state
        if st is None:
            return
        W = np.asarray(st.W).reshape(-1)
        for i in self.w_idx:
            self.pairs[i].to_spoke.write(W)
            self._c_writes.inc()


class APHHub(PHHub):
    """APH as hub (reference hub.py:691-771): same wire protocol as
    PHHub; main() runs APH_main.  The reference skips the pre-Put
    barrier for asynchrony — moot here (single-program scheduling)."""

    def main(self):
        return self.opt.APH_main(spcomm=self, finalize=False)


class LShapedHub(Hub):
    """L-shaped as hub (reference hub.py:600-689): sends nonant
    candidates (no W spokes), receives bounds, gap termination."""

    def setup_hub(self):
        self.wire_spokes()
        if self.w_idx:
            raise RuntimeError(
                "LShapedHub cannot feed W spokes (reference hub.py:628)")

    def sync(self, send_nonants=True):
        with self.telemetry.span("hub.sync"):
            self._drain_failures()
            if self.supervisor is not None:
                self.supervisor.poll()
                on_sync = getattr(self.supervisor, "on_sync", None)
                if on_sync is not None:
                    on_sync()
            if send_nonants:
                self.send_nonants()
            if self.drive_spokes_inline:
                self._step_spokes()
            self.receive_outerbounds()
            self.receive_innerbounds()
            if self.supervisor is not None:
                end = getattr(self.supervisor, "on_sync_end", None)
                if end is not None:
                    end()

    def is_converged(self):
        # the hub's own loop provides both bounds; spokes may improve
        # the inner one
        ob = self.opt.outer_bound
        if np.isfinite(ob):
            self.OuterBoundUpdate(ob, char="B")
        ib = self.opt.inner_bound
        if np.isfinite(ib):
            self.InnerBoundUpdate(ib, char="B")
        self.screen_trace()
        return self.determine_termination()

    def current_iteration(self):
        return self.opt.iter

    def main(self):
        return self.opt.lshaped_algorithm()

    def send_nonants(self):
        """Push the current candidate x̂, replicated per scenario so
        nonant-spokes see the usual (S*K,) layout."""
        xhat = getattr(self.opt, "best_xhat", None)
        if xhat is None:
            return
        b = self.opt.batch
        flat = np.tile(np.asarray(xhat), (b.num_scens, 1)).reshape(-1)
        for i in self.nonant_idx_set:
            self.pairs[i].to_spoke.write(flat)
            self._c_writes.inc()

"""Lagranger outer-bound spoke (reference:
mpisppy/cylinders/lagranger_bounder.py): an INDEPENDENT Lagrangian that
takes the hub's nonant values (not its Ws), maintains its own W via
xbar/dual updates at its own rho, and reports the resulting dual
bounds.  Optional per-iteration rho rescale factors
(lagranger_rho_rescale_factors_json, reference :55-75) — scalings
accumulate.
"""

from __future__ import annotations

import json

import jax.numpy as jnp
import numpy as np

from ..phbase import compute_xbar, update_W
from .spoke import OuterBoundNonantSpoke


class LagrangerOuterBound(OuterBoundNonantSpoke):
    converger_spoke_char = "A"

    def __init__(self, spbase_object, options=None):
        super().__init__(spbase_object, options=options)
        b = self.opt.batch
        rho0 = float(self.opt.options.get("defaultPHrho", 1.0))
        self.rho = jnp.full((b.num_scens, b.num_nonants), rho0, b.c.dtype)
        self.W = jnp.zeros((b.num_scens, b.num_nonants), b.c.dtype)
        self._iter = 0
        path = self.opt.options.get("lagranger_rho_rescale_factors_json")
        self.rho_rescale_factors = None
        if path is not None:
            with open(path) as f:
                din = json.load(f)
            self.rho_rescale_factors = {int(i): float(v)
                                        for i, v in din.items()}

    def step(self):
        x_na, is_new = self.fresh_nonants()
        if self._killed or not is_new:
            return False
        return self._solve_pass(x_na)

    def _solve_pass(self, x_na):
        if self.rho_rescale_factors is not None and \
                self._iter in self.rho_rescale_factors:
            # scalings accumulate (reference lagranger_bounder.py:57)
            self.rho = self.rho * self.rho_rescale_factors[self._iter]
        b = self.opt.batch
        x_na = jnp.asarray(np.asarray(x_na), b.c.dtype)
        xbar, _ = compute_xbar(b, x_na)
        self.W = update_W(self.W, self.rho, x_na, xbar)
        c_eff = b.c.at[:, b.nonant_idx].add(self.W)
        self.opt.check_W_bound_supported()
        res = self.opt.solve_loop(c=c_eff, warm=True)
        # valid_Ebound: see cylinders/lagrangian_bounder.py
        self.update_if_improving(float(self.opt.valid_Ebound(res)))
        self._iter += 1
        return True

    def finalize(self):
        """Final bound pass with the last nonants, run AFTER the kill
        signal (reference lagranger_bounder.py:106-116 finalize)."""
        x_na, _ = self.fresh_nonants()
        self._solve_pass(x_na)
        return self.bound

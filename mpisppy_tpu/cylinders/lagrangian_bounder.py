"""Lagrangian outer-bound spoke (reference:
mpisppy/cylinders/lagrangian_bounder.py).

Receives PH's W vectors from the hub, re-solves every scenario with the
W-modified objective (NO prox term), and reports the probability-
weighted dual bound.  Valid because the probability-weighted W sums to
zero within each tree node by construction of the PH dual update.

On TPU this spoke is nearly free: same batched PDHG kernel as the hub,
different (c_eff) arrays, own warm-start cache (SURVEY.md §2.10).
"""

from __future__ import annotations

import jax.numpy as jnp

from .spoke import _BoundWSpoke


class LagrangianOuterBound(_BoundWSpoke):
    converger_spoke_char = "L"

    def _solve_pass(self, W):
        """W-only re-solve + dual bound (reference
        lagrangian_bounder.py:44-60 lagrangian())."""
        self.opt.check_W_bound_supported()
        b = self.opt.batch
        c_eff = b.c.at[:, b.nonant_idx].add(jnp.asarray(W, b.c.dtype))
        res = self.opt.solve_loop(c=c_eff, warm=True)
        # valid_Ebound: finite-box LPs are valid at any iterate;
        # otherwise uncertified scenarios mask the bound to -inf rather
        # than publishing a polluted bound to the hub
        self.update_if_improving(float(self.opt.valid_Ebound(res)))

    def step(self):
        W, is_new = self.fresh_Ws()
        if self._killed or not is_new:
            return False
        self._solve_pass(W)
        return True

    def finalize(self):
        """One final pass with the last Ws (reference
        lagrangian_bounder.py:84-95)."""
        W, _ = self.fresh_Ws()
        self._solve_pass(W)
        return self.bound

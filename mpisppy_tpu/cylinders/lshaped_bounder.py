"""XhatLShapedInnerBound — evaluate the L-shaped hub's candidate x̂
(reference: mpisppy/cylinders/lshaped_bounder.py:15).

Fixes the received nonants and does one batched solve; reports E[obj]
as an inner bound when feasible.
"""

from __future__ import annotations

import numpy as np

from .spoke import ConvergerSpokeType, InnerBoundNonantSpoke


class XhatLShapedInnerBound(InnerBoundNonantSpoke):
    converger_spoke_char = "X"

    def step(self):
        nonants, is_new = self.fresh_nonants()
        if self._killed or not is_new:
            return False
        xhat = np.asarray(nonants)[0]   # hub replicates x̂ per scenario
        eobj, feasible = self.opt.evaluate_xhat(xhat)
        if feasible:
            self.update_if_improving(eobj, solution=xhat)
        return True

    def finalize(self):
        return self.bound

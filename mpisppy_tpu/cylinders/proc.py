"""Separate-process cylinder deployment over the native seqlock exchange.

Reference counterpart: `WheelSpinner._make_comms` + `sputils.spin_the_wheel`
launching hub and spokes as distinct MPI programs on a strata_comm
(reference mpisppy/spin_the_wheel.py:219-237); the cylinders exchange
through one-sided RMA windows.

Here each spoke runs as its own OS process (its own Python/JAX runtime)
and dials into the hub's mmap-file windows (runtime/exchange.cpp — the
same seqlock protocol the in-process modes use, see
cylinders/spcommunicator.py).  This is the single-box stand-in for the
multi-host DCN layout: process boundary + shared-memory gateway instead
of host boundary + network gateway, with identical wire semantics
(write_id freshness, kill = write_id -1, torn reads impossible by
seqlock retry).

Because a live jitted optimizer cannot cross an exec boundary, a spoke
process reconstructs its problem from a declarative spec:

    spec = {
      "batch": {"module": "mpisppy_tpu.models.farmer",
                "builder": "build_batch",
                "kwargs": {"num_scens": 30}},
      "opt_class":   "mpisppy_tpu.utils.xhat_eval:Xhat_Eval",
      "spoke_class": "mpisppy_tpu.cylinders.lagrangian_bounder:"
                     "LagrangianOuterBound",
      "opt_options": {...}, "spoke_options": {...},
      "scenario_names": [...],
      "windows": {"prefix": "/tmp/run/pair0",
                  "hub_length": N, "spoke_length": M},
    }

The hub process creates (and owns/resets) the window files BEFORE
spawning, so attachers never race the initialization.
"""

from __future__ import annotations

import importlib
import json
import os
import subprocess
import sys

import numpy as np


def _resolve(path: str):
    mod, _, name = path.partition(":")
    return getattr(importlib.import_module(mod), name)


class SpokeHandle:
    """Hub-side stand-in for a spoke that lives in another process.

    Carries only the wiring metadata the hub needs (spoke type, display
    char, window lengths); `step()` is a no-op because the real work
    happens across the process boundary.  The incumbent solution of an
    inner-bound spoke comes back through a side file written at spoke
    finalize (`<prefix>.sol.npy`) — scalar bounds travel through the
    window itself.
    """

    def __init__(self, spoke_class, send_length: int, receive_length: int,
                 sol_path: str | None = None):
        self.converger_spoke_types = spoke_class.converger_spoke_types
        self.converger_spoke_char = spoke_class.converger_spoke_char
        self.provides_cuts = getattr(spoke_class, "provides_cuts", False)
        self.spoke_name = spoke_class.__name__
        self._send_length = int(send_length)
        self._receive_length = int(receive_length)
        self._sol_path = sol_path
        self.pair = None
        self.proc = None

    def send_length(self):
        return self._send_length

    def receive_length(self):
        return self._receive_length

    def step(self):
        return False

    @property
    def best_solution(self):
        if self._sol_path and os.path.exists(self._sol_path):
            # the spoke writes via tmp-file + os.replace, so the file
            # is never torn; a malformed file (disk full, manual edit)
            # degrades to "no solution" rather than crashing finalize
            try:
                return np.load(self._sol_path)
            except (OSError, ValueError, EOFError):
                return None
        return None

    def finalize(self):
        return None


def spawn_spoke(spec: dict, workdir: str, tag: str,
                env_overrides: dict | None = None) -> subprocess.Popen:
    """Launch `python -m mpisppy_tpu.cylinders.proc <specfile>`.

    The child inherits the parent's environment; by default it is pinned
    to the CPU backend so spoke processes never contend for the single
    accelerator (on a real multi-host pod each process owns its chips
    and this override is dropped)."""
    specfile = os.path.join(workdir, f"spoke_{tag}.json")
    with open(specfile, "w") as f:
        json.dump(spec, f)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_overrides or {})
    # child needs the package importable exactly as the parent sees it
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    log_path = os.path.join(workdir, f"spoke_{tag}.log")
    with open(log_path, "w") as log:
        # Popen dups the fd; closing the parent-side handle immediately
        # avoids leaking one fd per spoke in long-lived hub processes
        p = subprocess.Popen(
            [sys.executable, "-m", "mpisppy_tpu.cylinders.proc",
             specfile],
            env=env, cwd=workdir, stdout=log, stderr=subprocess.STDOUT)
    p.log_path = log_path
    return p


def run_spoke_from_spec(specfile: str) -> int:
    """Worker entry: reconstruct the spoke and serve until killed."""
    from ..utils.platform import ensure_cpu_backend
    ensure_cpu_backend()

    with open(specfile) as f:
        spec = json.load(f)

    from .spcommunicator import WindowPair

    # activate this child's telemetry BEFORE building the optimizer so
    # every configure_from_options(None) call below picks it up; the
    # spoke's spans/metrics land in its own files (real pid = own trace
    # row) which the hub merges after shutdown (spin_the_wheel.py)
    from .. import telemetry as _telemetry
    tel_cfg = spec.get("telemetry")
    tel = _telemetry.configure(tel_cfg) if tel_cfg else _telemetry.get()

    bs = spec["batch"]
    builder = getattr(importlib.import_module(bs["module"]), bs["builder"])
    batch = builder(**bs.get("kwargs", {}))
    pad_to = bs.get("pad_to")
    if pad_to and pad_to > batch.num_scens:
        # match the hub's device-padded scenario count so the flattened
        # W/nonant window vectors reshape identically on both sides
        from ..ir import pad_scenarios
        batch = pad_scenarios(batch, pad_to)
    opt_cls = _resolve(spec["opt_class"])
    spoke_cls = _resolve(spec["spoke_class"])
    opt = opt_cls(spec.get("opt_options", {}),
                  spec["scenario_names"], batch=batch)
    spoke = spoke_cls(opt, options=spec.get("spoke_options"))
    w = spec["windows"]
    spoke.pair = WindowPair(w["hub_length"], w["spoke_length"],
                            backend="native", path_prefix=w["prefix"],
                            attach=True)
    spoke.main()
    sol = getattr(spoke, "best_solution", None)
    if sol is not None:
        # atomic publish: the hub may read at any moment (spoke-exit
        # re-pairing), so it must never observe a half-written file.
        # np.save on a FILE OBJECT keeps the name verbatim (the path
        # form would append .npy to the .tmp suffix).
        import io

        from ..resilience.checkpoint import atomic_write
        final = w["prefix"] + ".sol.npy"
        buf = io.BytesIO()
        np.save(buf, np.asarray(sol))
        atomic_write(final, buf.getvalue())
    spoke.finalize()
    if tel.enabled:
        tp = tel.config.get("trace_path")
        if tp:
            tel.write_trace(tp)
        mp = tel.config.get("metrics_path")
        if mp:
            tel.write_metrics(mp)
    return 0


if __name__ == "__main__":
    sys.exit(run_spoke_from_spec(sys.argv[1]))

"""Slam heuristics (reference: mpisppy/cylinders/slam_heuristic.py):
"slam" every nonant to the elementwise max (or min) across the
scenarios of its tree node — the reference's Allreduce(MAX/MIN) becomes
a per-node segment max/min — then round integers and evaluate."""

from __future__ import annotations

import numpy as np

from ..utils.xhat_utils import node_members, round_integer_nonants
from .spoke import InnerBoundNonantSpoke


class _SlamHeuristic(InnerBoundNonantSpoke):
    _reduce = None  # np.max or np.min

    def __init__(self, spbase_object, options=None):
        super().__init__(spbase_object, options=options)
        n_real = self.opt.n_real_scens
        self._node_of = np.asarray(
            self.opt.batch.tree.node_of)[:n_real]
        self._members = node_members(self._node_of)

    def step(self):
        x_na, is_new = self.fresh_nonants()
        if self._killed or not is_new:
            return False
        x_na = np.asarray(x_na)[: self.opt.n_real_scens]
        # per-(node, slot) reduce over member scenarios, broadcast back;
        # all members of a node carry it at the same slots (stage-major
        # layout), so the slot set comes from any one member
        cand = np.empty_like(x_na)
        for n, mem in self._members.items():
            slots = np.where(self._node_of[mem[0]] == n)[0]
            sub = np.ix_(mem, slots)
            cand[sub] = type(self)._reduce(x_na[sub], axis=0,
                                           keepdims=True)
        # pad rows: replicate scenario 0's candidate (probability 0)
        S = self.opt.batch.num_scens
        if S > cand.shape[0]:
            cand = np.vstack([cand] + [cand[:1]] * (S - cand.shape[0]))
        cand = round_integer_nonants(self.opt.batch, cand)
        obj, feas = self.opt.evaluate_xhat(cand)
        if feas:
            self.update_if_improving(obj, solution=cand)
        return True


class SlamMaxHeuristic(_SlamHeuristic):
    converger_spoke_char = "M"
    _reduce = staticmethod(np.max)


class SlamMinHeuristic(_SlamHeuristic):
    converger_spoke_char = "m"
    _reduce = staticmethod(np.min)

"""Inter-cylinder communication layer.

Reference counterpart: mpisppy/cylinders/spcommunicator.py — one-sided
MPI RMA windows per hub<->spoke pair; the writer Puts into its own
buffer, the reader Gets the remote buffer, and the LAST slot of every
buffer carries a monotonically increasing write_id that readers use to
detect fresh vs. stale vs. torn data (spcommunicator.py:93-120,
spoke.py:93-118, hub.py:411-431).  The kill signal is write_id = -1
(hub.py:438-450).

TPU-native redesign: cylinders are concurrent *algorithms* sharing one
single-controller JAX process (interleaved on the device queue) or
running in host threads; the exchange is therefore a host-side
double-buffered mailbox with the same write_id semantics.  The
`Window` interface below is deliberately identical in contract to the
RMA pair so the multi-process DCN backend (C++ shared-memory exchange,
runtime/exchange.cpp) can slot in behind it unchanged.
"""

from __future__ import annotations

import threading

import numpy as np

from .. import telemetry as _telemetry
from ..resilience.bounds import PayloadGuard, payload_checksum


class Window:
    """One direction of a hub<->spoke pair: a (length+1,) float64
    buffer whose last slot is the write_id.

    Contract (mirrors the reference RMA protocol):
      * writes are atomic and carry a strictly increasing write_id
      * `read()` returns (data_copy, write_id); the reader decides
        freshness by comparing ids (reference spoke.py:99-118)
      * write_id == -1 means terminate (reference hub.py:438)
      * every write is stamped with a payload checksum;
        `read_checked()` additionally validates the snapshot
        (checksum + write_id monotonicity, resilience/bounds.py)
    """

    KILL = -1

    def __init__(self, length: int):
        self.length = int(length)
        self._buf = np.zeros(self.length + 1, dtype=np.float64)
        self._lock = threading.Lock()
        self._checksum = payload_checksum(self._buf[:-1])
        self._corrupt_next = False
        self._pguard = PayloadGuard()

    @property
    def write_id(self):
        with self._lock:
            return int(self._buf[-1])

    def corrupt_next_write(self):
        """Chaos hook (corrupt_window mode): the next write stores a
        perturbed payload under the checksum of the TRUE values, so
        only payload validation — not value hygiene — can catch it."""
        self._corrupt_next = True

    def write(self, values, write_id=None):
        """Post `values` with the next (or given) write_id."""
        values = np.asarray(values, dtype=np.float64)
        if values.shape != (self.length,):
            raise ValueError(
                f"window expects shape ({self.length},), got {values.shape}")
        chk = payload_checksum(values)
        if self._corrupt_next:
            self._corrupt_next = False
            values = values.copy()
            values[0] += 1.0
        with self._lock:
            new_id = int(self._buf[-1]) + 1 if write_id is None else write_id
            self._buf[:-1] = values
            self._buf[-1] = new_id
            self._checksum = chk
            return new_id

    def read(self):
        """(data copy, write_id) — one atomic snapshot."""
        with self._lock:
            return self._buf[:-1].copy(), int(self._buf[-1])

    def read_checked(self):
        """(data, write_id, ok, reason) — one snapshot, integrity
        validated against the writer's checksum and this reader's
        high-water write_id.  Readers drop not-ok snapshots."""
        with self._lock:
            data = self._buf[:-1].copy()
            wid = int(self._buf[-1])
            chk = self._checksum
        ok, reason = self._pguard.check(data, wid, chk)
        return data, wid, ok, reason

    def send_kill(self):
        with self._lock:
            self._buf[-1] = self.KILL

    def close(self):
        """Interface parity with runtime.NativeWindow (which must
        unmap its file): the in-memory window has nothing to release,
        but callers may close any backend uniformly."""


# Exchange-backend registry — the seam through which alternative
# window implementations (the mpmd device-mailbox exchange) plug in
# WITHOUT this package importing them: mpisppy_tpu.mpmd registers its
# "device" factory on import, and cylinders stay ignorant of jax and of
# mpmd internals (guarded by tests/test_mpmd_wheel.py AST checks).
_WINDOW_BACKENDS: dict = {}


def register_window_backend(name, pair_factory):
    """Register `pair_factory(hub_length, spoke_length, **kwargs) ->
    (to_spoke, to_hub)` under `name` for WindowPair(backend=name)."""
    _WINDOW_BACKENDS[name] = pair_factory


class WindowPair:
    """The two windows of one hub<->spoke stratum: hub-owned (spoke
    reads) and spoke-owned (hub reads) — the analog of the two
    MPI.Win.Allocate buffers per pair (reference spcommunicator.py:93).

    backend="python" (alias "seqlock") is the host mailbox above;
    backend="native" uses the C++ seqlock exchange
    (runtime/exchange.cpp): identical contract, lock-free reads, and
    mmap-file support for cross-process (DCN gateway) pairs via
    `path_prefix`.  Any other name resolves through the registered
    backend factories (register_window_backend) with `backend_kwargs`
    passed through opaquely — the "device" backend registered by
    mpisppy_tpu.mpmd takes per-slice device placements this way, and
    its "collective" backend takes the wheel's shared fabric object.

    The registered on-device backends (doc/src/mpmd.md has the full
    matrix):

      * "device"     — one device-resident mailbox per direction
                       (mpmd/exchange.py): each write is its own
                       device_put + sync;
      * "collective" — every pair is one lane row of two shared
                       (K, header+V_pad) slabs (mpmd/collective.py):
                       writes stage host-side, and the first read of a
                       staged generation moves the WHOLE direction
                       with one fused all-gather / broadcast.  The
                       seqlock metadata (write_id, CRC32, payload
                       length) rides in the slab's three header
                       columns, so read_checked validates the same
                       contract on both.
    """

    def __init__(self, hub_length: int, spoke_length: int,
                 backend: str = "python", path_prefix: str | None = None,
                 attach: bool = False, backend_kwargs: dict | None = None):
        if backend == "native":
            from ..runtime import NativeWindow
            pth = (lambda tag: None if path_prefix is None
                   else f"{path_prefix}.{tag}")
            # the pair's creator OWNS the windows: reset any stale file
            # (leftover kill flag / write_id from a previous run);
            # attach=True joins EXISTING files (a spoke process dialing
            # into the hub's windows) and must not reset them
            self.to_spoke = NativeWindow(hub_length, path=pth("to_spoke"),
                                         reset=not attach)
            self.to_hub = NativeWindow(spoke_length, path=pth("to_hub"),
                                       reset=not attach)
        elif backend in ("python", "seqlock"):
            self.to_spoke = Window(hub_length)
            self.to_hub = Window(spoke_length)
        else:
            factory = _WINDOW_BACKENDS.get(backend)
            if factory is None:
                raise RuntimeError(
                    f"window backend {backend!r} is not registered "
                    "(the 'device' backend registers on "
                    "`import mpisppy_tpu.mpmd` — the WheelSpinner "
                    "seam does this when it selects it)")
            self.to_spoke, self.to_hub = factory(
                hub_length, spoke_length, **(backend_kwargs or {}))


class SPCommunicator:
    """Base for Hub and Spoke wrappers: owns an optimization object
    (`opt`, an SPOpt subclass) and its window endpoints (reference
    spcommunicator.py:24-92)."""

    def __init__(self, spbase_object, options=None):
        self.opt = spbase_object
        self.options = dict(options or {})
        self.opt.spcomm = self
        # window-traffic telemetry: handles are bound once here and
        # shared by every hub/spoke subclass; all of them are null
        # no-ops when telemetry is off (telemetry/metrics.py)
        self.telemetry = _telemetry.configure_from_options(
            self.options.get("telemetry"))
        # the spans/rows of this cylinder land on this trace track
        # (None = the hub/main row; WheelSpinner names spoke tracks)
        self.telemetry_track = None
        tel = self.telemetry
        self._c_writes = tel.counter("window.writes")
        self._c_reads = tel.counter("window.reads")
        self._c_stale = tel.counter("window.stale_reads")
        self._c_kills = tel.counter("window.kill_signals")

    # lengths of the vectors this cylinder sends/receives; subclasses
    # override (reference: Spoke.make_windows sends its 2 lengths)
    def send_length(self) -> int:
        return 1

    def receive_length(self) -> int:
        return 1

    def free_windows(self):
        pass

    def finalize(self):
        """Last chance to do work after the kill signal (reference
        spcommunicator.py finalize + spoke finalize passes)."""
        return None

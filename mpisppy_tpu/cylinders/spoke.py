"""Spoke base classes (reference: mpisppy/cylinders/spoke.py).

Every spoke exposes ONE unit of work as `step()` — read fresh hub data,
do a batched solve pass, post results.  The threaded driver loops
`main()` = `while not killed: step()`; the interleaved (single-program)
driver calls `step()` directly between hub iterations.  Both modes
share all algorithm code.

The spoke-type registry (`converger_spoke_types` /
`converger_spoke_char`) drives hub buffer wiring exactly like the
reference (spoke.py:18-33).
"""

from __future__ import annotations

import enum
import os
import time

import numpy as np

from ..resilience.chaos import ChaosInjector
from .spcommunicator import SPCommunicator, Window


class ConvergerSpokeType(enum.Enum):
    OUTER_BOUND = 1
    INNER_BOUND = 2
    W_GETTER = 3
    NONANT_GETTER = 4


class Spoke(SPCommunicator):
    converger_spoke_types = ()
    converger_spoke_char = "?"

    def __init__(self, spbase_object, options=None):
        super().__init__(spbase_object, options=options)
        self.pair = None           # WindowPair, set by the wheel
        self.last_hub_id = 0
        self._killed = False
        # fault injection (resilience/chaos.py): inert unless the
        # options carry a "chaos" dict or MPISPPY_TPU_CHAOS is set
        self.chaos = ChaosInjector.from_options(
            self.options.get("chaos"))
        # liveness: the multiproc supervisor reads this spoke's to_hub
        # write_id as its heartbeat; bound spokes re-post their current
        # bound at this cadence so the id advances even when the bound
        # has stopped improving
        self.heartbeat_interval = float(
            self.options.get("heartbeat_interval", 1.0))
        self._last_heartbeat = 0.0

    # -- hub traffic (reference spoke.py:60-118) --------------------------
    def spoke_to_hub(self, values):
        """Post this spoke's vector (reference spoke.py:60)."""
        values = self.chaos.poison(values)
        self.chaos.pre_write()
        fate = self.chaos.write_fate()
        if fate == "drop":
            return                     # partition_slice: the wire eats it
        if fate == "corrupt":
            corrupt = getattr(self.pair.to_hub, "corrupt_next_write", None)
            if corrupt is not None:
                corrupt()
        self.pair.to_hub.write(values)
        self._c_writes.inc()

    def spoke_from_hub(self):
        """(data, is_new): latest hub vector; is_new iff the write_id
        advanced since our last read AND the snapshot passed payload
        validation (reference spoke.py:93-118 + read_checked)."""
        self.chaos.step_tick()
        win = self.pair.to_spoke
        rc = getattr(win, "read_checked", None)
        if rc is None:                 # backend without integrity guard
            data, wid = win.read()
            ok = True
        else:
            data, wid, ok, _reason = rc()
        self._c_reads.inc()
        if wid == Window.KILL:
            self._killed = True
            return data, False
        if not ok:                     # corrupt snapshot == stale
            self._c_stale.inc()
            return data, False
        is_new = wid > self.last_hub_id
        if not is_new:
            self._c_stale.inc()
        self.last_hub_id = max(self.last_hub_id, wid)
        return data, is_new

    def got_kill_signal(self):
        if not self._killed:
            self._killed = self.pair.to_spoke.write_id == Window.KILL
        return self._killed

    def get_serial_number(self):
        wid = self.pair.to_spoke.write_id
        return 0 if wid == Window.KILL else wid

    # -- work unit --------------------------------------------------------
    def step(self):
        """One unit of spoke work; subclasses implement.  Returns
        truthy iff work was done (fresh data was consumed) — the
        threaded loop backs off when a step was a no-op."""
        raise NotImplementedError

    def timed_step(self):
        """step() under a tracer span on this spoke's own trace track,
        so each spoke renders as its own row in the merged timeline
        (telemetry/export.py).  Identical to step() when telemetry is
        off."""
        tel = self.telemetry
        if not tel.enabled:
            return self.step()
        tr = tel.tracer
        with tr.track(self.telemetry_track):
            with tr.span(f"{type(self).__name__}.step"):
                return self.step()

    def _heartbeat(self):
        """Keep the to_hub write_id advancing so the supervisor can
        tell a slow spoke from a hung one; bound spokes override with
        a real re-post, the base is a no-op."""

    # -- ensemble checkpoint hooks (resilience/checkpoint.py) -------------
    def algo_state(self):
        """npz-safe dict of this spoke's algorithm state for the wheel
        ensemble checkpoint.  Subclasses extend; values must be
        np.asarray-able (scalars/arrays) or None."""
        state = {"last_hub_id": self.last_hub_id}
        opt = self.opt
        if getattr(opt, "_x_warm", None) is not None:
            state["x_warm"] = np.asarray(opt._x_warm)
        if getattr(opt, "_y_warm", None) is not None:
            state["y_warm"] = np.asarray(opt._y_warm)
        for k, (xw, yw) in (getattr(opt, "_named_warm", None) or {}).items():
            state[f"named_warm.{k}.x"] = np.asarray(xw)
            state[f"named_warm.{k}.y"] = np.asarray(yw)
        return state

    def restore_algo_state(self, state):
        """Inverse of algo_state (missing keys keep defaults, so old
        checkpoints restore what they have)."""
        if "last_hub_id" in state:
            self.last_hub_id = int(state["last_hub_id"])
        opt = self.opt
        if "x_warm" in state and hasattr(opt, "_x_warm"):
            opt._x_warm = state["x_warm"]
        if "y_warm" in state and hasattr(opt, "_y_warm"):
            opt._y_warm = state["y_warm"]
        named = {}
        for k in state:
            if k.startswith("named_warm.") and k.endswith(".x"):
                name = k[len("named_warm."):-len(".x")]
                yk = f"named_warm.{name}.y"
                if yk in state:
                    named[name] = (state[k], state[yk])
        if named and hasattr(opt, "_named_warm"):
            opt._named_warm.update(named)

    def main(self):
        """Threaded-mode driver loop (reference: each spoke's main)."""
        while not self.got_kill_signal():
            did = False
            if self.get_serial_number() != 0:
                did = self.timed_step()
            now = time.time()
            if now - self._last_heartbeat >= self.heartbeat_interval:
                self._last_heartbeat = now
                self._heartbeat()
            if not did:
                # nothing fresh from the hub yet — don't busy-spin
                time.sleep(1e-3)


class _BoundSpoke(Spoke):
    """A spoke that sends a scalar bound (reference spoke.py:147-208).
    Supports the per-spoke bound trace CSV via options["trace_prefix"].
    """

    def __init__(self, spbase_object, options=None):
        super().__init__(spbase_object, options=options)
        self.bound = (np.inf if self._is_inner_like()
                      else -np.inf) * (1 if self.opt.is_minimizing else -1)
        self._got_bound = False
        self._trace_path = None
        prefix = self.options.get("trace_prefix")
        if prefix is not None:
            self._trace_path = (
                f"{prefix}_{type(self).__name__}.csv")
            os.makedirs(os.path.dirname(self._trace_path) or ".",
                        exist_ok=True)
            with open(self._trace_path, "w") as f:
                f.write("time,bound\n")
            self._t0 = time.time()

    def _is_inner_like(self):
        return ConvergerSpokeType.INNER_BOUND in self.converger_spoke_types

    def send_length(self):
        return 1

    def update_if_improving(self, candidate):
        """Keep + send the bound if it improves (reference
        spoke.py:186-202)."""
        if not self._improves(candidate):
            return False
        better = self._strictly_better(candidate)
        if better or not self._got_bound:
            self.bound = float(candidate)
            self._got_bound = True
            self.spoke_to_hub([self.bound])
            self._append_trace(self.bound)
            return bool(better)
        return False

    def _improves(self, candidate):
        return candidate is not None and np.isfinite(candidate)

    def _strictly_better(self, candidate):
        if self.opt.is_minimizing:
            return (candidate < self.bound if self._is_inner_like()
                    else candidate > self.bound)
        return (candidate > self.bound if self._is_inner_like()
                else candidate < self.bound)

    def _heartbeat(self):
        """Re-post the current bound (same value, fresh write_id): the
        hub's update is idempotent and the advancing id doubles as the
        multiproc supervisor's liveness signal."""
        if self._got_bound:
            self.spoke_to_hub([self.bound])

    def spoke_to_hub(self, values):
        """Bound posts also feed the per-slice bound-progression gauge
        (wheel.slice_bound.<track> — telemetry.wheel_counters), keyed
        by this cylinder's trace track so every slice of an MPMD wheel
        gets its own series.  Recorded pre-poison: the gauge reflects
        the bound the spoke computed, chaos corrupts only the wire."""
        super().spoke_to_hub(values)
        if self.telemetry.enabled and len(values) \
                and np.isfinite(values[0]):
            track = self.telemetry_track or type(self).__name__
            self.telemetry.gauge(
                f"wheel.slice_bound.{track}").set(float(values[0]))

    def _append_trace(self, value):
        """Reference spoke.py:204 _append_trace."""
        if self._trace_path is None:
            return
        with open(self._trace_path, "a") as f:
            f.write(f"{time.time() - self._t0},{value}\n")

    def algo_state(self):
        state = super().algo_state()
        state["bound"] = float(self.bound)
        state["got_bound"] = bool(self._got_bound)
        return state

    def restore_algo_state(self, state):
        super().restore_algo_state(state)
        if "bound" in state:
            self.bound = float(state["bound"])
        if "got_bound" in state:
            self._got_bound = bool(state["got_bound"])


class _BoundWSpoke(_BoundSpoke):
    """Bound spoke that receives the hub's W vector (flattened (S*K,))
    (reference spoke.py:254-270 localWs)."""

    converger_spoke_types = (ConvergerSpokeType.OUTER_BOUND,
                             ConvergerSpokeType.W_GETTER)

    def receive_length(self):
        b = self.opt.batch
        return b.num_scens * b.num_nonants

    def _reshape_SK(self, data):
        """(S, K) view of a flattened hub vector.  After an elastic
        reslice the hub's batch may carry MORE pad rows than this
        spoke's (pads always append at the end), so truncate to the
        local scenario count instead of requiring an exact match."""
        b = self.opt.batch
        return np.asarray(data).reshape(-1, b.num_nonants)[:b.num_scens]

    @property
    def localWs(self):
        """Pure read of the hub's latest W — does NOT consume the
        freshness flag (use fresh_Ws in step loops)."""
        data, _ = self.pair.to_spoke.read()
        return self._reshape_SK(data)

    def fresh_Ws(self):
        """(W (S,K), is_new)"""
        data, is_new = self.spoke_from_hub()
        return self._reshape_SK(data), is_new


class _BoundNonantSpoke(_BoundSpoke):
    """Bound spoke that receives the hub's nonant values (flattened
    (S*K,)) (reference spoke.py:288-303 localnonants)."""

    def receive_length(self):
        b = self.opt.batch
        return b.num_scens * b.num_nonants

    def _reshape_SK(self, data):
        """(S, K) view, truncating extra post-reslice pad rows (see
        _BoundWSpoke._reshape_SK)."""
        b = self.opt.batch
        return np.asarray(data).reshape(-1, b.num_nonants)[:b.num_scens]

    def fresh_nonants(self):
        data, is_new = self.spoke_from_hub()
        return self._reshape_SK(data), is_new

    @property
    def localnonants(self):
        """Pure read — does NOT consume the freshness flag."""
        data, _ = self.pair.to_spoke.read()
        return self._reshape_SK(data)


class InnerBoundNonantSpoke(_BoundNonantSpoke):
    """Inner-bound spoke consuming hub nonants; tracks the incumbent
    first-stage solution (reference spoke.py:306-363)."""

    converger_spoke_types = (ConvergerSpokeType.INNER_BOUND,
                             ConvergerSpokeType.NONANT_GETTER)

    def __init__(self, spbase_object, options=None):
        super().__init__(spbase_object, options=options)
        self.best_solution = None      # (K,) or (S, K) incumbent nonants

    def update_if_improving(self, candidate, solution=None):
        # record the incumbent BEFORE posting the bound: in threaded
        # mode the hub may read the window between the post and a
        # later assignment, pairing the new bound with a stale solution
        if (solution is not None and self._improves(candidate)
                and (self._strictly_better(candidate)
                     or not self._got_bound)):
            self.best_solution = np.asarray(solution)
        return super().update_if_improving(candidate)

    def algo_state(self):
        state = super().algo_state()
        if self.best_solution is not None:
            state["best_solution"] = np.asarray(self.best_solution)
        return state

    def restore_algo_state(self, state):
        super().restore_algo_state(state)
        if "best_solution" in state:
            self.best_solution = np.asarray(state["best_solution"])


class OuterBoundNonantSpoke(_BoundNonantSpoke):
    converger_spoke_types = (ConvergerSpokeType.OUTER_BOUND,
                             ConvergerSpokeType.NONANT_GETTER)

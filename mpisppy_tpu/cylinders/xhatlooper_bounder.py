"""Xhat sequential-looper inner-bound spoke (reference:
mpisppy/cylinders/xhatlooper_bounder.py): like the shuffler but walks
scenarios in their given order, up to `scen_limit` per pass."""

from __future__ import annotations

import numpy as np

from ..utils.xhat_utils import (candidate_from_sources, full_source_map,
                                node_members, round_integer_nonants)
from .spoke import InnerBoundNonantSpoke


class XhatLooperInnerBound(InnerBoundNonantSpoke):
    converger_spoke_char = "X"

    def __init__(self, spbase_object, options=None):
        super().__init__(spbase_object, options=options)
        self.scen_limit = int(self.options.get("scen_limit", 3))
        self._next = 0
        n_real = self.opt.n_real_scens
        self._members = node_members(
            np.asarray(self.opt.batch.tree.node_of)[:n_real])

    def step(self):
        x_na, is_new = self.fresh_nonants()
        if self._killed or not is_new:
            return False
        x_na = np.asarray(x_na)
        node_of = np.asarray(self.opt.batch.tree.node_of)
        n_real = self.opt.n_real_scens
        for _ in range(self.scen_limit):
            base = self._next % n_real
            self._next += 1
            srcs = full_source_map(node_of, base, members=self._members)
            cand = candidate_from_sources(x_na, node_of, srcs)
            cand = round_integer_nonants(self.opt.batch, cand)
            obj, feas = self.opt.evaluate_xhat(cand)
            if feas:
                self.update_if_improving(obj, solution=cand)
        return True

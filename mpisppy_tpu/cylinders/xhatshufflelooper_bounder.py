"""Xhat shuffle-looper inner-bound spoke (reference:
mpisppy/cylinders/xhatshufflelooper_bounder.py).

The incumbent finder: takes the hub's latest per-scenario nonant
values, cycles through candidate source scenarios in a deterministic
shuffled order (seed 42, reference :58-61), builds an implementable
candidate per tree node, fixes the nonants and evaluates all scenarios
in one batched solve.  Multistage candidates assign a source scenario
to every non-leaf node (the reference's node-scenario dicts,
ScenarioCycler :158-299); epochs optionally reverse.
"""

from __future__ import annotations

import random

import numpy as np

from ..utils.xhat_utils import (candidate_from_sources, full_source_map,
                                node_members, round_integer_nonants)
from .spoke import InnerBoundNonantSpoke


class ScenarioCycler:
    """Deterministic candidate cycler (reference ScenarioCycler):
    walks a shuffled scenario list in epochs, reversing direction each
    epoch when `reverse` is set."""

    def __init__(self, shuffled, reverse=True):
        self._shuffled = list(shuffled)
        self._reverse = reverse
        self._pos = 0
        self._direction = 1
        self.best = None

    def get_next(self):
        if not self._shuffled:
            return None
        if self._pos >= len(self._shuffled) or self._pos < 0:
            self.begin_epoch()
        s = self._shuffled[self._pos]
        self._pos += self._direction
        return s

    def begin_epoch(self):
        if self._reverse:
            self._direction *= -1
        self._pos = (0 if self._direction > 0
                     else len(self._shuffled) - 1)


class XhatShuffleInnerBound(InnerBoundNonantSpoke):
    converger_spoke_char = "X"

    def __init__(self, spbase_object, options=None):
        super().__init__(spbase_object, options=options)
        self.random_seed = 42  # reference hard-wires 42 (:58)
        rs = random.Random()
        rs.seed(self.random_seed)
        n_real = self.opt.n_real_scens
        shuffled = rs.sample(list(range(n_real)), n_real)
        self.cycler = ScenarioCycler(
            shuffled, reverse=self.options.get("reverse", True))
        self._members = node_members(
            np.asarray(self.opt.batch.tree.node_of)[:n_real])
        self._last_nonants = None

    def step(self):
        x_na, is_new = self.fresh_nonants()
        if self._killed:
            return False
        if is_new:
            self._last_nonants = np.asarray(x_na)
        if self._last_nonants is None:
            return False
        base = self.cycler.get_next()
        if base is None:
            return False
        srcs = full_source_map(
            np.asarray(self.opt.batch.tree.node_of),
            base, members=self._members)
        cand = candidate_from_sources(self._last_nonants,
                                      self.opt.batch.tree.node_of, srcs)
        cand = round_integer_nonants(self.opt.batch, cand)
        obj, feas = self.opt.evaluate_xhat(cand)
        if feas and self.update_if_improving(obj, solution=cand):
            self.cycler.best = base
        return True

    # -- ensemble checkpoint (resilience/checkpoint.py) -------------------
    def algo_state(self):
        state = super().algo_state()
        state["cycler_pos"] = int(self.cycler._pos)
        state["cycler_direction"] = int(self.cycler._direction)
        if self.cycler.best is not None:
            state["cycler_best"] = int(self.cycler.best)
        if self._last_nonants is not None:
            state["last_nonants"] = np.asarray(self._last_nonants)
        return state

    def restore_algo_state(self, state):
        super().restore_algo_state(state)
        if "cycler_pos" in state:
            self.cycler._pos = int(state["cycler_pos"])
        if "cycler_direction" in state:
            self.cycler._direction = int(state["cycler_direction"])
        if "cycler_best" in state:
            self.cycler.best = int(state["cycler_best"])
        if "last_nonants" in state:
            self._last_nonants = np.asarray(state["last_nonants"])

"""Xhat-specific inner-bound spoke (reference:
mpisppy/cylinders/xhatspecific_bounder.py): repeatedly evaluates ONE
user-specified node->scenario dict against the hub's latest nonants.
"""

from __future__ import annotations

import numpy as np

from ..utils.xhat_utils import candidate_from_sources, round_integer_nonants
from .spoke import InnerBoundNonantSpoke


class XhatSpecificInnerBound(InnerBoundNonantSpoke):
    converger_spoke_char = "S"

    def __init__(self, spbase_object, options=None):
        super().__init__(spbase_object, options=options)
        # {"ROOT": scen_index, "ROOT_0": ...} by node NAME or id
        spec = self.options.get("xhat_scenario_dict")
        if spec is None:
            raise ValueError(
                "XhatSpecificInnerBound needs options['xhat_scenario_dict']"
                " (reference xhatspecific_bounder.py:19)")
        self.node_to_src = {}
        names = list(getattr(self.opt, "all_nodenames", None) or [])
        scen_names = list(self.opt.all_scenario_names)
        for k, v in spec.items():
            if isinstance(k, str):
                if k not in names:
                    raise ValueError(
                        f"node name {k!r} not in all_nodenames {names}")
                node = names.index(k)
            else:
                node = int(k)
            snum = (scen_names.index(v) if isinstance(v, str)
                    else int(v))
            self.node_to_src[node] = snum
        # the dict must cover every real tree node — a partial spec
        # would silently evaluate the wrong candidate (the reference
        # errors on incomplete scenario dicts too)
        from ..utils.xhat_utils import node_members
        real_nodes = set(node_members(np.asarray(
            self.opt.batch.tree.node_of)[: self.opt.n_real_scens]))
        missing = real_nodes - set(self.node_to_src)
        if missing:
            raise ValueError(
                f"xhat_scenario_dict misses tree nodes {sorted(missing)}")

    def step(self):
        x_na, is_new = self.fresh_nonants()
        if self._killed or not is_new:
            return False
        cand = candidate_from_sources(
            np.asarray(x_na), self.opt.batch.tree.node_of, self.node_to_src)
        cand = round_integer_nonants(self.opt.batch, cand)
        obj, feas = self.opt.evaluate_xhat(cand)
        if feas:
            self.update_if_improving(obj, solution=cand)
        return True

"""Xhat-xbar inner-bound spoke (reference:
mpisppy/cylinders/xhatxbar_bounder.py): the candidate is the consensus
average x̄ itself (rounded on integer slots)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..phbase import compute_xbar
from ..utils.xhat_utils import round_integer_nonants
from .spoke import InnerBoundNonantSpoke


class XhatXbarInnerBound(InnerBoundNonantSpoke):
    converger_spoke_char = "B"

    def step(self):
        x_na, is_new = self.fresh_nonants()
        if self._killed or not is_new:
            return False
        b = self.opt.batch
        xbar, _ = compute_xbar(b, jnp.asarray(np.asarray(x_na), b.c.dtype))
        cand = round_integer_nonants(b, np.asarray(xbar))
        obj, feas = self.opt.evaluate_xhat(cand)
        if feas:
            self.update_if_improving(obj, solution=cand)
        return True

from .extension import Extension, MultiExtension  # noqa: F401

"""MinMaxAvg — print avg/min/max of a per-scenario quantity each
iteration (reference: mpisppy/extensions/avgminmaxer.py).

options["avgminmax_name"] selects what to track: "objective" (default),
"conv" (per-scenario nonant deviation), or a nonant slot index.
"""

from __future__ import annotations

import numpy as np

from .. import global_toc
from .extension import Extension


class MinMaxAvg(Extension):
    def __init__(self, ph):
        super().__init__(ph)
        self.compstr = ph.options.get("avgminmax_name", "objective")

    def _values(self):
        st = self.opt.state
        b = self.opt.batch
        if self.compstr == "objective":
            return np.asarray(st.obj)
        if self.compstr == "conv":
            x_na = np.asarray(b.nonants(st.x))
            return np.abs(x_na - np.asarray(st.xbar)).sum(axis=1)
        k = int(self.compstr)
        return np.asarray(b.nonants(st.x))[:, k]

    def _report(self, when):
        if self.opt.state is None:
            return
        avg, lo, hi = self.opt.avg_min_max(self._values())
        global_toc(f"MinMaxAvg[{self.compstr}] {when}: "
                   f"avg {avg:.6g}  min {lo:.6g}  max {hi:.6g}")

    def post_iter0(self):
        self._report("iter0")

    def enditer(self):
        self._report(f"iter {int(self.opt.state.it)}")

"""CrossScenarioExtension — hub-side half of cross-scenario cuts
(reference: mpisppy/extensions/cross_scen_extension.py:16-283).

Requires the hub optimizer to be built over a batch augmented with
`utils.cross_scenario.add_cross_scenario_capacity` (an epigraph
variable `eta` approximating E[f](x) plus a buffer of inactive cut
rows; each scenario's objective is blended
(1-w) f_s + w eta, which equals E[f] at consensus with tight cuts).

Each sync, the extension drains the CrossScenarioCutSpoke's window and
installs the aggregate cut

    eta - Egrad . x_na >= Eq - Egrad . xhat

into the next free cut row of EVERY scenario, then re-prepares the
constraint data (same shapes — no recompilation; the PH superstep
takes prep as a traced argument).
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax.numpy as jnp

from .. import global_toc
from ..ops.pdhg import prepare_batch
from .extension import Extension


class CrossScenarioExtension(Extension):
    def __init__(self, ph):
        super().__init__(ph)
        if not getattr(ph.batch, "var_names", ()) or \
                ph.batch.var_names[-1] != "_eta_cross":
            raise RuntimeError(
                "CrossScenarioExtension needs a batch augmented by "
                "add_cross_scenario_capacity (eta column missing)")
        self._spoke = None          # wired via attach_spoke
        self._read_id = 0
        self.n_cuts = 0

    def attach_spoke(self, spoke):
        self._spoke = spoke

    def post_iter0(self):
        """Seed eta with a VALID constant cut so early bounds aren't
        polluted by eta's -BIG box (the reference initializes eta with
        a computed valid lower bound): one W-free solve of the BASE
        objective gives the wait-and-see bound WS <= min E[f], and
        eta >= WS is valid everywhere.  Also repairs the trivial bound
        the blended Iter0 computed."""
        from ..utils.cross_scenario import cross_meta
        opt = self.opt
        b = opt.batch
        meta = cross_meta(b)
        # the eta column's objective coefficient IS the blend weight w;
        # base c = c_blend/(1-w) with the eta column zeroed
        w = float(np.asarray(b.c)[0, meta["eta_col"]])
        c_base = np.array(np.asarray(b.c)) / max(1.0 - w, 1e-12)
        c_base[:, meta["eta_col"]] = 0.0
        res = opt.solver.solve(opt.prep, jnp.asarray(c_base),
                               b.qdiag, b.lb, b.ub,
                               obj_const=b.obj_const / max(1.0 - w, 1e-12))
        ws = float(jnp.sum(b.prob * res.dual_obj))
        self._install_cut(ws, np.zeros(b.num_nonants),
                          np.zeros(b.num_nonants))
        opt.trivial_bound = ws
        opt.best_bound = ws

    def _install_cut(self, Eq, Egrad, xhat):
        from ..utils.cross_scenario import cross_meta
        opt = self.opt
        b = opt.batch
        N = b.num_vars            # eta is column N-1
        meta = cross_meta(b)
        if self.n_cuts >= meta["max_cuts"]:
            global_toc("CrossScenario: cut buffer full; skipping")
            return
        r = meta["first_cut_row"] + self.n_cuts
        na = np.asarray(b.nonant_idx)
        Arow = np.zeros(N)
        Arow[na] = -np.asarray(Egrad)
        Arow[N - 1] = 1.0
        A = np.array(np.asarray(b.A))
        A[:, r, :] = Arow
        lo = np.array(np.asarray(b.row_lo))
        lo[:, r] = Eq - float(np.asarray(Egrad) @ np.asarray(xhat))
        opt.batch = dataclasses.replace(
            b, A=jnp.asarray(A), row_lo=jnp.asarray(lo))
        opt.prep = prepare_batch(opt.batch.A, opt.batch.row_lo,
                                 opt.batch.row_hi)
        self.n_cuts += 1

    def miditer(self):
        if self._spoke is None or self._spoke.pair is None:
            return
        data, wid = self._spoke.pair.to_hub.read()
        if wid <= self._read_id or wid < 0:
            return
        self._read_id = wid
        K = self.opt.batch.num_nonants
        Eq = float(data[0])
        Egrad = np.asarray(data[1:1 + K])
        xhat = np.asarray(data[1 + K:1 + 2 * K])
        self._install_cut(Eq, Egrad, xhat)

"""Diagnoser — per-iteration scenario dumps (reference:
mpisppy/extensions/diagnoser.py).

Writes one CSV per call under options["diagnoser_options"]["diagnoser_outdir"]
with per-scenario objective, convergence contribution and solve status.
"""

from __future__ import annotations

import csv
import os

import numpy as np

from .extension import Extension


class Diagnoser(Extension):
    def __init__(self, ph):
        super().__init__(ph)
        o = ph.options.get("diagnoser_options") or {}
        self.outdir = o.get("diagnoser_outdir", "diagnoser_out")

    def _dump(self, tag):
        st = self.opt.state
        if st is None:
            return
        os.makedirs(self.outdir, exist_ok=True)
        b = self.opt.batch
        obj = np.asarray(st.obj)
        prob = np.asarray(b.prob)
        x_na = np.asarray(b.nonants(st.x))
        xbar = np.asarray(st.xbar)
        dev = np.abs(x_na - xbar).sum(axis=1)
        path = os.path.join(self.outdir, f"diag_iter{int(st.it)}_{tag}.csv")
        with open(path, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["scenario", "prob", "objective", "nonant_dev_l1"])
            names = b.tree.scen_names or [
                str(i) for i in range(b.num_scens)]
            for i in range(self.opt.n_real_scens):
                w.writerow([names[i], prob[i], obj[i], dev[i]])

    def post_iter0(self):
        self._dump("iter0")

    def enditer(self):
        self._dump("enditer")

"""Extension hook API (reference: mpisppy/extensions/extension.py:12-169).

An Extension object is constructed with the optimizer (`ph`) and gets
called at the reference's hook points: pre_iter0 / post_iter0 /
post_iter0_after_sync / miditer / enditer / enditer_after_sync /
post_everything / pre_solve_loop / post_solve_loop.  `MultiExtension`
fans a hook out to an ordered list of extensions (reference
extension.py:63-169).

Here the "solve loop" is one batched jitted superstep, so per-scenario
pre_solve/post_solve hooks collapse into the loop-level pair.
"""

from __future__ import annotations


class Extension:
    """Base class: every hook is a no-op."""

    def __init__(self, ph):
        self.opt = ph
        # alias matching the reference attribute name
        self.ph = ph

    def pre_iter0(self):
        pass

    def post_iter0(self):
        pass

    def post_iter0_after_sync(self):
        pass

    def miditer(self):
        pass

    def enditer(self):
        pass

    def enditer_after_sync(self):
        pass

    def post_everything(self):
        pass

    def pre_solve_loop(self):
        pass

    def post_solve_loop(self):
        pass


class MultiExtension(Extension):
    """Compose several extensions; hooks fire in list order (reference
    extension.py:63).  Construct with the class list in `ext_classes`."""

    def __init__(self, ph, ext_classes=()):
        super().__init__(ph)
        self.extdict = {}
        self.extensions = []
        for cls in ext_classes:
            ext = cls(ph)
            self.extdict[cls.__name__] = ext
            self.extensions.append(ext)

    def add_extension(self, ext):
        self.extdict[type(ext).__name__] = ext
        self.extensions.append(ext)

    def _fan(self, hook):
        for ext in self.extensions:
            getattr(ext, hook)()

    def pre_iter0(self):
        self._fan("pre_iter0")

    def post_iter0(self):
        self._fan("post_iter0")

    def post_iter0_after_sync(self):
        self._fan("post_iter0_after_sync")

    def miditer(self):
        self._fan("miditer")

    def enditer(self):
        self._fan("enditer")

    def enditer_after_sync(self):
        self._fan("enditer_after_sync")

    def post_everything(self):
        self._fan("post_everything")

    def pre_solve_loop(self):
        self._fan("pre_solve_loop")

    def post_solve_loop(self):
        self._fan("post_solve_loop")

"""Fixer — progressive variable fixing (reference:
mpisppy/extensions/fixer.py:20-330).

The reference fixes integer variables whose value has stayed near a
bound or near its converged value for `nb` consecutive iterations,
using the xbar/xsqbar variance test.  The TPU version is the same test
vectorized: a slot (scenario s, nonant k) is "ripe" when the cross-
scenario spread  xsqbar - xbar^2  is below `boundtol` AND (for integer
slots) xbar is within `boundtol` of an integer; after `nb` consecutive
ripe iterations the slot is pinned via PHBase.fix_nonants (bounds
tightening — no recompilation).

Options (under options["fixeroptions"], mirroring the reference's
fixer_tol / id_fix_list_fct indirection with flat knobs):
    boundtol     : ripeness tolerance (default 1e-2)
    nb           : consecutive-iteration count to fix (default 3)
    fix_integers : fix integer-marked slots by rounding xbar (default True)
    fix_continuous : also fix continuous slots to xbar (default False)
    unfix_on_drift : unfix slots under dual pressure (default False).
                     Once a slot is pinned (lb=ub) neither its xbar nor
                     its W can move, so the live release signal is the
                     REDUCED COST of the pinned slot in the PH
                     subproblem, r = c_eff + q_eff*x + A'y — the
                     objective pressure against the pin.  Released when
                     |r| > drift_W_factor * (1 + |c|) at the slot.
    drift_W_factor : see above (default 10.0)
    verbose
"""

from __future__ import annotations

import numpy as np

from .. import global_toc
from .extension import Extension


class Fixer(Extension):
    def __init__(self, ph):
        super().__init__(ph)
        o = (ph.options.get("fixeroptions") or {})
        self.boundtol = float(o.get("boundtol", 1e-2))
        self.nb = int(o.get("nb", 3))
        self.fix_integers = bool(o.get("fix_integers", True))
        self.fix_continuous = bool(o.get("fix_continuous", False))
        self.unfix_on_drift = bool(o.get("unfix_on_drift", False))
        self.drift_W_factor = float(o.get("drift_W_factor", 10.0))
        self.verbose = bool(o.get("verbose", False))
        b = ph.batch
        S, K = b.num_scens, b.num_nonants
        self._count = np.zeros((S, K), np.int32)
        self._fixed = np.zeros((S, K), bool)
        self._fixed_vals = np.zeros((S, K), float)  # targets at fix time
        # which slots are integer-typed (per scenario x slot)
        self._int_slot = np.asarray(b.integer_mask)[:, np.asarray(b.nonant_idx)]

    def _ripe_and_target(self):
        st = self.opt.state
        xbar = np.asarray(st.xbar)
        spread = np.asarray(st.xsqbar) - xbar * xbar
        tight = spread < self.boundtol
        target = xbar.copy()
        ripe = np.zeros_like(tight)
        if self.fix_integers:
            rounded = np.round(xbar)
            near_int = np.abs(xbar - rounded) < self.boundtol
            m = self._int_slot & tight & near_int
            ripe |= m
            target = np.where(self._int_slot, rounded, target)
        if self.fix_continuous:
            ripe |= (~self._int_slot) & tight
        return ripe, target

    def iter0(self):
        # reference applies a (usually stricter) iter0 pass; here the
        # same test runs once with no count requirement relaxation
        self.miditer(first=True)

    def post_iter0(self):
        self.iter0()

    def miditer(self, first=False):
        if self.opt.state is None:
            return
        ripe, target = self._ripe_and_target()
        self._count = np.where(ripe, self._count + 1, 0)
        newly = (self._count >= self.nb) & ~self._fixed
        if newly.any():
            # pin ONLY the newly ripe slots: re-pinning already-fixed
            # slots to a target recomputed from a drifted xbar would
            # silently move a "fixed" variable
            self._fixed |= newly
            self._fixed_vals = np.where(newly, target, self._fixed_vals)
            self.opt.fix_nonants(newly, target)
            if self.verbose:
                global_toc(f"Fixer: fixed {int(newly.sum())} new slots "
                           f"({int(self._fixed.sum())} total)")
        elif self.unfix_on_drift and self._fixed.any():
            r_na = self._pinned_reduced_costs()
            c_na = np.abs(np.asarray(self.opt.batch.c))[
                :, np.asarray(self.opt.batch.nonant_idx)]
            drift = self._fixed & (
                np.abs(r_na) > self.drift_W_factor * (1.0 + c_na))
            if drift.any():
                self._fixed &= ~drift
                self._count = np.where(drift, 0, self._count)
                self.opt.unfix_nonants(drift)
                if self.verbose:
                    global_toc(f"Fixer: unfixed {int(drift.sum())} slots")

    def _pinned_reduced_costs(self):
        """Reduced cost of each nonant slot in the PH subproblem at the
        current iterate: r = c_eff + q_eff*x + A'y, restricted to nonant
        columns.  At a pinned slot this is the objective pressure the
        pin resists (KKT multiplier of lb=ub)."""
        import jax.numpy as jnp
        opt = self.opt
        b = opt.batch
        st = opt.state
        na = b.nonant_idx
        rho = opt.rho
        c_eff = b.c.at[:, na].add(st.W - rho * st.xbar)
        q_eff = b.qdiag.at[:, na].add(jnp.broadcast_to(rho, st.W.shape))
        aty = jnp.einsum("smn,sm->sn", b.A, st.y)
        r = c_eff + q_eff * st.x + aty
        return np.asarray(r[:, na])

    def post_everything(self):
        global_toc(f"Fixer: {int(self._fixed.sum())} slots fixed at end "
                   f"(of {self._fixed.size})")

"""Gradient_extension — dynamic gradient-based rho (reference:
mpisppy/extensions/gradient_extension.py:18-111, delegating to
utils/gradient.py + utils/find_rho.py).

Sets rho from gradient order statistics after Iter0 (when the nonant
spread is known) and optionally refreshes it every
`grad_rho_update_interval` iterations.

Options under options["gradient_extension_options"]:
    grad_order_stat (default 0.5), grad_rho_relative_bound (1e3),
    grad_rho_update_interval (0 = iter0 only)
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import global_toc
from ..utils.gradient import find_rho
from .extension import Extension


class Gradient_extension(Extension):
    def __init__(self, ph):
        super().__init__(ph)
        o = ph.options.get("gradient_extension_options") or {}
        self.order_stat = float(o.get("grad_order_stat", 0.5))
        self.rel_bound = float(o.get("grad_rho_relative_bound", 1e3))
        self.interval = int(o.get("grad_rho_update_interval", 0))

    def _apply(self):
        rho = find_rho(self.opt, order_stat=self.order_stat,
                       rel_bound=self.rel_bound)
        b = self.opt.batch
        self.opt.rho = jnp.broadcast_to(
            jnp.asarray(rho, b.c.dtype)[None, :],
            (b.num_scens, b.num_nonants))
        global_toc(f"Gradient rho set: mean {float(np.mean(rho)):.4g} "
                   f"max {float(np.max(rho)):.4g}")

    def post_iter0(self):
        self._apply()

    def miditer(self):
        if self.interval and self.opt.state is not None and \
                int(self.opt.state.it) % self.interval == 0:
            self._apply()

"""Gapper — per-iteration solver-tolerance schedule (reference:
mpisppy/extensions/mipgapper.py:11-57).

The reference sets the MIP solver's mipgap from a {iteration: gap}
dict.  Here the inner solver is the batched PDHG kernel, whose
relative-KKT tolerance `eps` is a traced argument (ops/pdhg.py), so the
schedule tightens/loosens the solve without recompiling.

Options: options["gapperoptions"] = {"verbose": ..., "mipgapdict":
{iter: eps}} — iteration 0 applies from Iter0 onward.
"""

from __future__ import annotations

import jax.numpy as jnp

from .. import global_toc
from .extension import Extension


class Gapper(Extension):
    def __init__(self, ph):
        super().__init__(ph)
        o = ph.options.get("gapperoptions") or {}
        self.verbose = bool(o.get("verbose", False))
        self.mipgapdict = dict(o.get("mipgapdict") or {})

    def _apply(self, it):
        if it in self.mipgapdict:
            eps = float(self.mipgapdict[it])
            self.opt.solver_eps = jnp.asarray(eps, self.opt.batch.c.dtype)
            if self.verbose:
                global_toc(f"Gapper: iter {it} -> solver eps {eps:g}")

    def pre_iter0(self):
        self._apply(0)

    def miditer(self):
        if self.opt.state is not None:
            self._apply(int(self.opt.state.it))

"""MultRhoUpdater — multiply rho when convergence stalls (reference:
mpisppy/extensions/mult_rho_updater.py:29-106).

Options under options["mult_rho_options"]:
    convergence_tolerance (default 1e-4): only update while conv above it
    rho_update_stop_iteration / rho_update_start_iteration
    rho_multiplier (default 2.0)
"""

from __future__ import annotations

from .. import global_toc
from .extension import Extension


class MultRhoUpdater(Extension):
    def __init__(self, ph):
        super().__init__(ph)
        o = ph.options.get("mult_rho_options") or {}
        self.conv_tol = float(o.get("convergence_tolerance", 1e-4))
        self.stop_iter = o.get("rho_update_stop_iteration")
        self.start_iter = int(o.get("rho_update_start_iteration", 1) or 1)
        self.mult = float(o.get("rho_multiplier", 2.0))
        self._last_conv = None

    def miditer(self):
        st = self.opt.state
        if st is None:
            return
        it = int(st.it)
        if it < self.start_iter:
            return
        if self.stop_iter is not None and it > int(self.stop_iter):
            return
        conv = float(st.conv)
        if conv <= self.conv_tol:
            return
        if self._last_conv is not None and conv >= self._last_conv:
            self.opt.rho = self.opt.rho * self.mult
            global_toc(f"MultRhoUpdater iter {it}: conv stalled at "
                       f"{conv:.3e}, rho *= {self.mult}")
        self._last_conv = conv

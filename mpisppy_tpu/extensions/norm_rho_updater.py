"""NormRhoUpdater — adaptive rho from primal/dual residual norms
(reference: mpisppy/extensions/norm_rho_updater.py:33-164).

Standard ADMM-style residual balancing on PH's consensus split:
    primal residual  r = sum_s p_s ||x_s - xbar||_1
    dual residual    d = rho * ||xbar - xbar_prev||_1
rho is scaled up when the primal residual dominates (consensus lagging)
and down when the dual residual dominates, exactly the balancing logic
the reference applies per-variable; we apply it per nonant slot with
prob-weighted norms, vectorized.

Options under options["norm_rho_options"]:
    ratio (default 10.0), step (default 2.0 multiply/divide factor),
    rho_update_stop_iter, verbose
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import global_toc
from .extension import Extension


class NormRhoUpdater(Extension):
    def __init__(self, ph):
        super().__init__(ph)
        o = ph.options.get("norm_rho_options") or {}
        self.ratio = float(o.get("ratio", 10.0))
        self.step = float(o.get("step", 2.0))
        self.stop_iter = o.get("rho_update_stop_iter")
        self.verbose = bool(o.get("verbose", False))
        self._xbar_prev = None

    def miditer(self):
        st = self.opt.state
        if st is None:
            return
        it = int(st.it)
        if self.stop_iter is not None and it > int(self.stop_iter):
            return
        b = self.opt.batch
        xbar = np.asarray(st.xbar)
        if self._xbar_prev is None:
            self._xbar_prev = xbar
            return
        p = np.asarray(b.prob)[:, None]
        x_na = np.asarray(b.nonants(st.x))
        # per-slot prob-weighted residuals (K,)
        prim = np.sum(p * np.abs(x_na - xbar), axis=0)
        rho_np = np.asarray(self.opt.rho)
        dual = np.mean(rho_np, axis=0) * np.sum(
            p * np.abs(xbar - self._xbar_prev), axis=0)
        self._xbar_prev = xbar

        up = prim > self.ratio * dual
        dn = dual > self.ratio * prim
        if up.any() or dn.any():
            factor = np.where(up, self.step,
                              np.where(dn, 1.0 / self.step, 1.0))
            new_rho = rho_np * factor[None, :]
            self.opt.rho = jnp.asarray(new_rho, b.c.dtype)
            if self.verbose:
                global_toc(f"NormRhoUpdater iter {it}: "
                           f"{int(up.sum())} slots up, "
                           f"{int(dn.sum())} down; "
                           f"mean rho {float(new_rho.mean()):.4g}")

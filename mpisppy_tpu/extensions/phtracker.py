"""PHTracker — per-iteration tracking to CSVs (reference:
mpisppy/extensions/phtracker.py:14-510: bounds, gaps, xbars, duals,
nonants, scenario costs as pandas DataFrames in per-cylinder folders).

Options under options["phtracker_options"]:
    results_folder (default "phtracker_results")
    track_bounds / track_xbars / track_duals / track_nonants /
    track_scen_costs (all default True)
"""

from __future__ import annotations

import csv
import os

import numpy as np

from .extension import Extension


class PHTracker(Extension):
    def __init__(self, ph):
        super().__init__(ph)
        o = ph.options.get("phtracker_options") or {}
        self.folder = o.get("results_folder", "phtracker_results")
        self.track = {k: bool(o.get(f"track_{k}", True))
                      for k in ("bounds", "xbars", "duals", "nonants",
                                "scen_costs")}
        os.makedirs(self.folder, exist_ok=True)
        self._files = {}

    def _w(self, name, header):
        if name not in self._files:
            path = os.path.join(self.folder, f"{name}.csv")
            # one file per run ("w"): appending across runs would
            # interleave iteration rows from different runs
            f = open(path, "w", newline="")
            w = csv.writer(f)
            w.writerow(header)
            self._files[name] = (f, w)
        return self._files[name][1]

    def _iteration_row(self):
        opt = self.opt
        st = opt.state
        it = int(st.it)
        K = opt.batch.num_nonants
        if self.track["bounds"]:
            hub = getattr(opt, "spcomm", None)
            ob = getattr(hub, "BestOuterBound", float("nan"))
            ib = getattr(hub, "BestInnerBound", float("nan"))
            conv = float(st.conv)
            self._w("bounds", ["iteration", "outer", "inner", "conv"]
                    ).writerow([it, ob, ib, conv])
        if self.track["xbars"]:
            self._w("xbars", ["iteration"] + [f"x{k}" for k in range(K)]
                    ).writerow([it] + np.asarray(st.xbar[0]).tolist())
        if self.track["duals"]:
            Wbar = np.abs(np.asarray(st.W)).mean(axis=0)
            self._w("duals", ["iteration"] + [f"W{k}" for k in range(K)]
                    ).writerow([it] + Wbar.tolist())
        if self.track["nonants"]:
            x_na = np.asarray(opt.batch.nonants(st.x))
            row = [it] + x_na[: opt.n_real_scens].reshape(-1).tolist()
            self._w("nonants", ["iteration"] + [
                f"s{s}_x{k}" for s in range(opt.n_real_scens)
                for k in range(K)]).writerow(row)
        if self.track["scen_costs"]:
            obj = np.asarray(st.obj)[: opt.n_real_scens]
            self._w("scen_costs", ["iteration"] + [
                f"s{s}" for s in range(opt.n_real_scens)]
                ).writerow([it] + obj.tolist())
        for f, _ in self._files.values():
            f.flush()

    def post_iter0(self):
        self._iteration_row()

    def enditer(self):
        self._iteration_row()

    def post_everything(self):
        for f, _ in self._files.values():
            f.close()
        self._files = {}

"""PHTracker — per-iteration tracking to CSVs and plots (reference:
mpisppy/extensions/phtracker.py:14-510: bounds, gaps, xbars, duals,
nonants, scenario costs as pandas DataFrames in per-cylinder folders,
with optional matplotlib plots per tracked quantity).

Options under options["phtracker_options"]:
    results_folder (default "phtracker_results")
    cylinder_name  (default from the hub/spoke class when running
                    under a WheelSpinner, else "hub") — each cylinder
    writes into results_folder/<cylinder_name>/ like the reference
    track_bounds / track_gaps / track_xbars / track_duals /
    track_nonants / track_scen_costs       (all default True)
    plot_bounds / plot_gaps / plot_xbars / plot_duals /
    plot_scen_costs                        (all default False) —
    written as PNGs at post_everything via matplotlib when available
"""

from __future__ import annotations

import csv
import os

import numpy as np

from .extension import Extension


class PHTracker(Extension):
    def __init__(self, ph):
        super().__init__(ph)
        o = ph.options.get("phtracker_options") or {}
        self._root = o.get("results_folder", "phtracker_results")
        self._name = o.get("cylinder_name")
        self._folder = None
        self.track = {k: bool(o.get(f"track_{k}", True))
                      for k in ("bounds", "gaps", "xbars", "duals",
                                "nonants", "scen_costs")}
        self.plot = {k: bool(o.get(f"plot_{k}", False))
                     for k in ("bounds", "gaps", "xbars", "duals",
                               "scen_costs")}
        self._files = {}

    @property
    def folder(self):
        """Resolved lazily: extensions are constructed inside the opt
        object's __init__, BEFORE the WheelSpinner attaches spcomm —
        resolving the cylinder name there would put every cylinder in
        the same 'hub' subfolder and interleave their CSVs."""
        if self._folder is None:
            name = self._name
            if name is None:
                spcomm = getattr(self.opt, "spcomm", None)
                name = (type(spcomm).__name__ if spcomm is not None
                        else "hub")
            self._folder = os.path.join(self._root, str(name))
            os.makedirs(self._folder, exist_ok=True)
        return self._folder

    def _w(self, name, header):
        if name not in self._files:
            path = os.path.join(self.folder, f"{name}.csv")
            # one file per run ("w"): appending across runs would
            # interleave iteration rows from different runs
            f = open(path, "w", newline="")
            w = csv.writer(f)
            w.writerow(header)
            self._files[name] = (f, w)
        return self._files[name][1]

    def _hub_bounds(self):
        hub = getattr(self.opt, "spcomm", None)
        ob = getattr(hub, "BestOuterBound", float("nan"))
        ib = getattr(hub, "BestInnerBound", float("nan"))
        return float(ob), float(ib)

    def _iteration_row(self):
        opt = self.opt
        st = opt.state
        it = int(st.it)
        K = opt.batch.num_nonants
        if self.track["bounds"]:
            ob, ib = self._hub_bounds()
            conv = float(st.conv)
            self._w("bounds", ["iteration", "outer", "inner", "conv"]
                    ).writerow([it, ob, ib, conv])
        if self.track["gaps"]:
            ob, ib = self._hub_bounds()
            if np.isfinite(ob) and np.isfinite(ib):
                abs_gap = abs(ib - ob)
                rel_gap = (abs_gap / abs(ib) if abs(ib) > 0
                           else float("nan"))
            else:
                abs_gap = rel_gap = float("nan")
            self._w("gaps", ["iteration", "abs_gap", "rel_gap"]
                    ).writerow([it, abs_gap, rel_gap])
        if self.track["xbars"]:
            self._w("xbars", ["iteration"] + [f"x{k}" for k in range(K)]
                    ).writerow([it] + np.asarray(st.xbar[0]).tolist())
        if self.track["duals"]:
            Wbar = np.abs(np.asarray(st.W)).mean(axis=0)
            self._w("duals", ["iteration"] + [f"W{k}" for k in range(K)]
                    ).writerow([it] + Wbar.tolist())
        if self.track["nonants"]:
            x_na = np.asarray(opt.batch.nonants(st.x))
            row = [it] + x_na[: opt.n_real_scens].reshape(-1).tolist()
            self._w("nonants", ["iteration"] + [
                f"s{s}_x{k}" for s in range(opt.n_real_scens)
                for k in range(K)]).writerow(row)
        if self.track["scen_costs"]:
            obj = np.asarray(st.obj)[: opt.n_real_scens]
            self._w("scen_costs", ["iteration"] + [
                f"s{s}" for s in range(opt.n_real_scens)]
                ).writerow([it] + obj.tolist())
        for f, _ in self._files.values():
            f.flush()

    def post_iter0(self):
        self._iteration_row()

    def enditer(self):
        self._iteration_row()

    # -- plotting (reference phtracker.py plot_* methods) ----------------
    def _plot_csv(self, name, ylabel, series_limit=12):
        try:
            import matplotlib
            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
        except ImportError:                            # pragma: no cover
            return
        path = os.path.join(self.folder, f"{name}.csv")
        if not os.path.exists(path):
            return
        with open(path) as f:
            rows = list(csv.reader(f))
        if len(rows) < 2:
            return
        header, data = rows[0], np.array(
            [[float(v) for v in r] for r in rows[1:]])
        fig, ax = plt.subplots(figsize=(7, 4))
        for j in range(1, min(data.shape[1], series_limit + 1)):
            ax.plot(data[:, 0], data[:, j], label=header[j])
        ax.set_xlabel("iteration")
        ax.set_ylabel(ylabel)
        ax.legend(fontsize=7, ncol=2)
        fig.tight_layout()
        fig.savefig(os.path.join(self.folder, f"{name}.png"), dpi=100)
        plt.close(fig)

    def post_everything(self):
        for f, _ in self._files.values():
            f.close()
        self._files = {}
        for name, ylabel in (("bounds", "bound"), ("gaps", "gap"),
                             ("xbars", "xbar"), ("duals", "|W| mean"),
                             ("scen_costs", "scenario cost")):
            if self.plot.get(name, False):
                self._plot_csv(name, ylabel)

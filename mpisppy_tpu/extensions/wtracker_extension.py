"""Wtracker_extension — wraps utils.wtracker.WTracker into the hook API
(reference: mpisppy/extensions/wtracker_extension.py).

Options under options["wtracker_options"]:
    wlen (window length, default 10), reportlen, stdevthresh,
    report_interval (report every k iterations; default only at end)
"""

from __future__ import annotations

from ..utils.wtracker import WTracker
from .extension import Extension


class Wtracker_extension(Extension):
    def __init__(self, ph):
        super().__init__(ph)
        o = ph.options.get("wtracker_options") or {}
        self.wtracker = WTracker(ph, wlen=o.get("wlen", 10))
        self.stdevthresh = o.get("stdevthresh")
        self.report_interval = o.get("report_interval")

    def enditer(self):
        self.wtracker.grab_local_Ws()
        if self.report_interval and self.opt.state is not None:
            if int(self.opt.state.it) % int(self.report_interval) == 0:
                self.wtracker.report_by_moving_stats(self.stdevthresh)

    def post_everything(self):
        self.wtracker.report_by_moving_stats(self.stdevthresh)

"""WXBarReader — warm-start PH from a W/xbar checkpoint (reference:
mpisppy/utils/wxbarreader.py:36-97).

options["init_W_fname"]: .npz written by WXBarWriter; installed right
after Iter0 (the reference also loads at init).
"""

from __future__ import annotations

from ..utils.wxbarutils import read_W_and_xbar
from .extension import Extension


class WXBarReader(Extension):
    def __init__(self, ph):
        super().__init__(ph)
        self.fname = ph.options.get("init_W_fname")

    def post_iter0(self):
        if self.fname:
            read_W_and_xbar(self.fname, self.opt)

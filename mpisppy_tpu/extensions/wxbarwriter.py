"""WXBarWriter — checkpoint W/xbar during PH (reference:
mpisppy/utils/wxbarwriter.py:36-102 extension wrapper).

Options (cfg group wxbar_read_write_args): options["W_fname"] — write
an .npz checkpoint at every iteration (atomic: tmp file + os.replace)
and at post_everything.

For FULL crash-resumable checkpoints (the whole PHState plus hub
bounds and incumbent, restored via options["resume_from"] or
WheelSpinner(resume_from=...)), use options["run_checkpoint"] —
see mpisppy_tpu/resilience/checkpoint.py and doc/src/resilience.md.
"""

from __future__ import annotations

from ..utils.wxbarutils import write_W_and_xbar
from .extension import Extension


class WXBarWriter(Extension):
    def __init__(self, ph):
        super().__init__(ph)
        self.fname = ph.options.get("W_fname")

    def enditer(self):
        if self.fname and self.opt.state is not None:
            write_W_and_xbar(self.fname, self.opt)

    def post_everything(self):
        self.enditer()

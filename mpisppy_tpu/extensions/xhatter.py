"""In-hub xhat extension family (reference: mpisppy/extensions/
xhatbase.py:38-230, xhatclosest.py, xhatxbar.py).

The reference evaluates candidate first-stage solutions INSIDE the hub
via extensions (in addition to the dedicated xhat spokes): an
XhatBase-derived extension picks candidates at `miditer` /
`post_everything`, fixes nonants, solves all scenarios, and — when the
candidate is feasible — publishes the expected objective as an inner
(upper) bound and records the incumbent.

TPU-native: candidate evaluation is the reduced second-stage stacked
solve (spopt.evaluate_candidates — ONE kernel launch for k candidates x
S scenarios), and the winner's bound is certified through
spopt.evaluate_xhat.  Publication goes to the hub's
InnerBoundUpdate when the optimizer runs as a hub cylinder, and to
`opt.best_inner_bound` always.
"""

from __future__ import annotations

import numpy as np

from .. import global_toc
from .extension import Extension


class XhatBase(Extension):
    """Shared candidate-evaluation machinery (reference
    xhatbase.py:38-230 `_try_one` / solve-loop-restore dance; here the
    evaluation is side-effect-free so there is nothing to restore)."""

    #: evaluate every `cycle` PH iterations (reference runs per-iter)
    cycle = 1

    def __init__(self, ph, options=None):
        super().__init__(ph)
        self.options = dict(options or {})
        self.cycle = int(self.options.get("cycle", self.cycle))
        self.best_inner_bound = np.inf if ph.is_minimizing else -np.inf
        self.best_nonants = None
        # mirror onto the optimizer for writers/wheel access
        ph.best_inner_bound = self.best_inner_bound
        ph.best_inner_nonants = None

    # -- candidate supply (subclasses) -----------------------------------
    def candidates(self):
        """Return a (k, K) array of candidate nonant vectors (root-node
        candidates; multistage callers use evaluate_xhat directly with
        per-scenario values)."""
        raise NotImplementedError

    # -- evaluation ------------------------------------------------------
    def _try_candidates(self):
        opt = self.opt
        if opt.state is None:
            return
        cands = np.atleast_2d(np.asarray(self.candidates()))
        if cands.size == 0:
            return
        from ..utils.xhat_eval import calculate_incumbent
        i, obj = calculate_incumbent(opt, cands)
        if i is None:
            return
        better = (obj < self.best_inner_bound if opt.is_minimizing
                  else obj > self.best_inner_bound)
        if better:
            self.best_inner_bound = obj
            self.best_nonants = cands[i]
            opt.best_inner_bound = obj
            opt.best_inner_nonants = cands[i]
            if opt.spcomm is not None and hasattr(opt.spcomm,
                                                 "InnerBoundUpdate"):
                opt.spcomm.InnerBoundUpdate(obj, char=self.char)

    char = "E"

    def miditer(self):
        if int(self.opt.state.it) % self.cycle == 0:
            self._try_candidates()

    def post_everything(self):
        self._try_candidates()
        if self.best_nonants is not None:
            global_toc(f"{type(self).__name__}: best inner bound "
                       f"{self.best_inner_bound:.6g}")


class XhatClosest(XhatBase):
    """Evaluate the scenario solution CLOSEST to xbar (reference
    extensions/xhatclosest.py: `_vb` sorted squared distance to the
    root average, then `_try_one` on the winner).

    options: {"keep_solution": bool, "cycle": int}.
    """

    char = "C"

    def candidates(self):
        opt = self.opt
        st = opt.state
        x_na = np.asarray(opt.batch.nonants(st.x))[: opt.n_real_scens]
        xbar = np.asarray(st.xbar)[0]
        d = np.sum((x_na - xbar[None, :]) ** 2, axis=1)
        order = np.argsort(d)
        k = int(self.options.get("n_candidates", 1))
        return x_na[order[:k]]


class XhatXbar(XhatBase):
    """Evaluate the consensus average itself (reference
    extensions/xhatxbar.py; integer slots are rounded the way the
    reference's xhat_xbar rounds)."""

    char = "X"

    def candidates(self):
        opt = self.opt
        xbar = np.asarray(opt.state.xbar)[0].copy()
        imask = np.asarray(opt.batch.integer_mask)[
            0, np.asarray(opt.batch.nonant_idx)]
        if imask.any():
            xbar[imask] = np.round(xbar[imask])
        return xbar[None, :]


class XhatLooper(XhatBase):
    """Loop over the scenarios' own solutions as candidates (reference
    extensions/xhatlooper.py: xhat_looper walks scenarios in order,
    trying each scenario's nonant vector, up to scen_limit per pass).

    TPU-native: one pass = ONE stacked evaluation of the next
    `scen_limit` scenario solutions (spopt.evaluate_candidates), with
    the walk position carried across calls so successive passes cover
    the whole scenario set cyclically — the batched equivalent of the
    reference's sequential first-feasible loop (its `_try_one` per
    scenario becomes k rows of one kernel launch).

    options: {"scen_limit": int (default 3), "cycle": int}.
    """

    char = "L"

    def __init__(self, ph, options=None):
        super().__init__(ph, options=options)
        self._pos = 0

    def candidates(self):
        opt = self.opt
        n = opt.n_real_scens
        k = min(int(self.options.get("scen_limit", 3)), n)
        x_na = np.asarray(opt.batch.nonants(opt.state.x))[:n]
        idx = (self._pos + np.arange(k)) % n
        self._pos = int((self._pos + k) % n)
        return x_na[idx]


class XhatSpecific(XhatBase):
    """Evaluate one named scenario's solution (reference
    extensions analog of cylinders/xhatspecific_bounder.py).
    options: {"xhat_scenario_name": str}."""

    char = "S"

    def candidates(self):
        opt = self.opt
        name = self.options.get("xhat_scenario_name",
                                opt.all_scenario_names[0])
        idx = opt.all_scenario_names.index(name)
        x_na = np.asarray(opt.batch.nonants(opt.state.x))
        return x_na[idx][None, :]

from .fwph import FWPH  # noqa: F401

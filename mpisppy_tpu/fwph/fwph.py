"""FWPH — Frank-Wolfe Progressive Hedging (reference:
mpisppy/fwph/fwph.py, 1045 LoC; Boland, Christiansen, Dandurand,
Eberhard, Linderoth, Luedtke, Oliveira 2018).

The reference keeps, per scenario, a growing convex-hull ("simplicial
decomposition") approximation: an inner SDM loop alternates a MIP solve
(new vertex/column) with a QP solve over the hull (fwph.py:210-303
`SDM`, `_add_QP_column:305`), producing a SEQUENCE of valid dual
(outer) bounds alongside the PH updates.

TPU-native restructuring:

  * The column bank is a dense (S, T, N) tensor with an active mask —
    fixed capacity T keeps shapes static; when full, the column with
    the smallest hull weight is overwritten (least-used eviction).
  * The **vertex solve** is the batched PDHG LP kernel with the
    linearized objective (for integer problems this is the LP
    relaxation — SURVEY.md §2.9's MIP stance).
  * The **hull QP** min_{lam in simplex} f_s(V lam) + W.(V lam)_na
    + rho/2 ||(V lam)_na - xbar||^2 has a dense Hessian in lam, which
    the diagonal-Q kernel can't express — so it is solved in LIFTED
    (x, lam) space:  x - V lam = 0 rows + one simplex row, diagonal
    prox on x.  One batched solve for all scenarios.
  * The first vertex solve of each outer pass uses the PURE Lagrangian
    objective c + W (no prox linearization), so its dual objective is
    exactly the Lagrangian dual bound — the reference's per-iteration
    outer bound (fwph.py:142-208) for free.

API mirror: FWPH(options, ...).fwph_main() -> (conv, Eobj, dual_bound).
Options: FW_iter_limit (SDM rounds/outer pass, default 2), FW_eps
(Frank-Wolfe gap tolerance ending an SDM pass early, default 1e-6 —
the reference SDM's Gamma stopping test, fwph.py:268-287), column_bank
(capacity T, default 16), plus PH options.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from .. import global_toc
from ..ops.pdhg import PDHGSolver, prepare_batch
from ..phbase import PHBase, compute_xbar, convergence_metric, update_W


class FWPH(PHBase):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        o = self.options
        self.fw_iter_limit = int(o.get("FW_iter_limit", 2))
        self.fw_eps = float(o.get("FW_eps", 1e-6))
        self.T = int(o.get("column_bank", 16))
        b = self.batch
        S, N = b.num_scens, b.num_vars
        # column bank: V (S, T, N), active mask, hull weights
        self._V = np.zeros((S, self.T, N))
        self._active = np.zeros((S, self.T), bool)
        self._lam = np.zeros((S, self.T))
        self._qp_solver = PDHGSolver(
            max_iters=int(o.get("pdhg_max_iters", 20000)),
            eps=float(o.get("pdhg_eps", 1e-6)))
        self.dual_bound = None         # best (max for min-problems) so far
        self._dual_bounds = []         # sequence, one per outer pass
        self.sdm_early_stops = 0       # SDM passes ended by the Gamma test
        # Gamma test is only a valid FW certificate for linear models
        self._qdiag_zero = not bool(np.any(np.asarray(b.qdiag) != 0))

    # -- column management -------------------------------------------------
    def _add_columns(self, x_new):
        """Insert (S, N) vertices; evict the least-used column if full."""
        x_new = np.asarray(x_new)
        for s in range(x_new.shape[0]):
            free = np.where(~self._active[s])[0]
            if free.size:
                t = free[0]
            else:
                t = int(np.argmin(self._lam[s]))
            self._V[s, t] = x_new[s]
            self._active[s, t] = True
            self._lam[s, t] = 0.0   # weight assigned by the next hull QP

    # -- hull QP in lifted (x, lam) space ---------------------------------
    def _hull_qp(self, W, xbar):
        """min c.x + W.x_na + rho/2||x_na - xbar||^2
        s.t. x = V lam, sum lam = 1, lam >= 0 (active cols only).
        Returns (x (S,N), lam (S,T), obj (S,))."""
        b = self.batch
        S, N, T = b.num_scens, b.num_vars, self.T
        K = b.num_nonants
        na = np.asarray(b.nonant_idx)

        # variables [x (N) | lam (T)]; rows: N coupling + 1 simplex
        M = N + 1
        A = np.zeros((S, M, N + T))
        A[:, :N, :N] = np.eye(N)[None]
        A[:, :N, N:] = -np.transpose(self._V, (0, 2, 1))
        A[:, N, N:] = self._active.astype(float)
        row_lo = np.zeros((S, M))
        row_hi = np.zeros((S, M))
        row_lo[:, N] = 1.0
        row_hi[:, N] = 1.0

        lb = np.full((S, N + T), -np.inf)
        ub = np.full((S, N + T), np.inf)
        lb[:, :N] = np.asarray(b.lb)
        ub[:, :N] = np.asarray(b.ub)
        lb[:, N:] = 0.0
        ub[:, N:] = np.where(self._active, 1.0, 0.0)

        rho = np.asarray(self.rho)
        c = np.zeros((S, N + T))
        c[:, :N] = np.asarray(b.c)
        c[:, na] += np.asarray(W) - rho * np.asarray(xbar)
        q = np.zeros((S, N + T))
        q[:, na] = rho

        prep = prepare_batch(jnp.asarray(A), jnp.asarray(row_lo),
                             jnp.asarray(row_hi))
        res = self._qp_solver.solve(
            prep, jnp.asarray(c), jnp.asarray(q),
            jnp.asarray(lb), jnp.asarray(ub))
        # np.array (copy): jax arrays viewed via asarray are read-only,
        # and _lam must stay writable for the eviction bookkeeping
        x = np.array(res.x[:, :N])
        lam = np.array(res.x[:, N:])
        return x, lam

    # -- lifecycle pieces (spoke-steppable) -------------------------------
    def fw_prep(self):
        """Iter0 + seed the column banks with the wait-and-see vertices
        (reference fwph.py:142-160 initialization)."""
        self.Iter0()
        self._add_columns(np.asarray(self.state.x))
        self._prepped = True

    def fwph_iteration(self):
        """One outer FWPH pass: SDM inner loop + PH updates.  Returns
        the convergence metric (reference fwph.py:161-208 loop body)."""
        b = self.batch
        na = b.nonant_idx
        st = self.state
        W, xbar = st.W, st.xbar
        x_qp = np.asarray(st.x)

        for t in range(self.fw_iter_limit):
            if t == 0:
                # pure Lagrangian objective -> valid dual bound
                c_eff = b.c.at[:, na].add(W)
                res = self.solver.solve(
                    self.prep, c_eff, b.qdiag, self.lb_eff,
                    self.ub_eff, obj_const=b.obj_const,
                    x0=st.x, y0=st.y)
                self.check_W_bound_supported()
                db = float(self.valid_Ebound(res))
                self._dual_bounds.append(db)
                if self.dual_bound is None or db > self.dual_bound:
                    self.dual_bound = db
            else:
                # linearize the prox QP at the current hull point
                x_na = b.nonants(jnp.asarray(x_qp))
                c_eff = b.c.at[:, na].add(W + self.rho * (x_na - xbar))
                res = self.solver.solve(
                    self.prep, c_eff, b.qdiag, self.lb_eff,
                    self.ub_eff, obj_const=b.obj_const)
                # SDM Gamma test (reference fwph.py:268-287): the
                # Frank-Wolfe gap c_lin.(x_hull - x_vertex) bounds the
                # hull QP's remaining improvement; when the expected
                # gap is below FW_eps no vertex can improve the hull
                # and the SDM pass ends early.  Valid only for LINEAR
                # subproblems: with a model quadratic (qdiag != 0) the
                # solve above includes b.qdiag, so res.x is not the
                # linear-subproblem minimizer and the quantity is not a
                # Frank-Wolfe gap — skip the early stop there.
                if self._qdiag_zero:
                    gap_s = np.einsum(
                        "sn,sn->s", np.asarray(c_eff),
                        x_qp - np.asarray(res.x))
                    fw_gap = float(np.asarray(b.prob) @ gap_s)
                    scale = 1.0 + abs(float(self.Eobjective(
                        b.objective(jnp.asarray(x_qp)))))
                    if fw_gap <= self.fw_eps * scale:
                        self.sdm_early_stops += 1
                        break
            self._add_columns(np.asarray(res.x))
            x_qp, lam = self._hull_qp(W, xbar)
            self._lam = lam

        # PH updates from the hull point
        x_na = b.nonants(jnp.asarray(x_qp))
        xbar, xsqbar = compute_xbar(b, x_na)
        W = update_W(W, self.rho, x_na, xbar)
        conv = float(convergence_metric(b, x_na, xbar))
        obj = b.objective(jnp.asarray(x_qp))
        self.state = self.state.__class__(
            x=jnp.asarray(x_qp), y=st.y, W=W, xbar=xbar,
            xsqbar=xsqbar, obj=obj, dual_obj=st.dual_obj,
            conv=jnp.asarray(conv), it=st.it + 1)
        self.conv = conv
        return conv

    # -- main loop (reference fwph.py:142-208) ----------------------------
    def fwph_main(self, finalize=True):
        if not getattr(self, "_prepped", False):
            self.fw_prep()
        max_iters = int(self.options.get("PHIterLimit", 50))
        convthresh = float(self.options.get("convthresh", 1e-4))
        conv = float("inf")
        for k in range(1, max_iters + 1):
            conv = self.fwph_iteration()
            self._ext("miditer")
            if k % 5 == 0 or k == 1:
                global_toc(f"FWPH iter {k:3d} conv={conv:.4e} "
                           f"dual_bound={self.dual_bound:.6g}")
            self._ext("enditer")
            if self.spcomm is not None:
                self.spcomm.sync()
                if self.spcomm.is_converged():
                    break
            if conv < convthresh:
                global_toc(f"FWPH converged at iter {k}")
                break
        self._ext("post_everything")
        if finalize:
            eobj = float(self.Eobjective(self.state.obj))
            return conv, eobj, self.dual_bound
        return conv, None, self.dual_bound

"""Array problem IR — the TPU-native replacement for the Pyomo scenario layer.

In the reference, a scenario is a Pyomo ConcreteModel produced by a user
`scenario_creator` callback, with tree metadata attached as
`_mpisppy_node_list` / `_mpisppy_probability`
(reference: mpisppy/spbase.py:505-522, mpisppy/scenario_tree.py:44).
Solvers then consume the Pyomo model out-of-process.

Here a scenario is lowered ONCE at creation time to dense arrays

    minimize   c @ x + 0.5 * x @ diag(qdiag) @ x + obj_const
    subject to row_lo <= A @ x <= row_hi
               lb <= x <= ub

and N scenarios are stacked into a `ScenarioBatch` pytree with a leading
scenario axis — the "DP axis" of stochastic programming
(SURVEY.md §2.10).  Everything downstream (PH, bounds, xhat evaluation)
is a vmapped/sharded computation over that axis.

Shapes must agree across scenarios in one batch (pad rows with free
bounds if a scenario has fewer constraints).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


def _register(cls, data_fields, meta_fields):
    jax.tree_util.register_dataclass(
        cls, data_fields=data_fields, meta_fields=meta_fields
    )
    return cls


@dataclasses.dataclass(frozen=True)
class TreeInfo:
    """Scenario-tree metadata for one batch (reference: scenario_tree.py:44
    ScenarioNode + sputils._ScenTree at sputils.py:745).

    Nonanticipative ("nonant") variables are the per-scenario slots that
    must agree across scenarios sharing a tree node.  They are laid out
    stage-major inside each scenario's x-vector via `nonant_idx`.

    node_of[s, j] = global node id owning nonant slot j of scenario s.
    For a two-stage problem every entry is 0 (the ROOT node).
    Per-node consensus (Compute_Xbar) is a segment-sum over node ids —
    the TPU analog of the reference's per-tree-node MPI communicators
    (spbase.py:333-375).
    """

    # (S, K) int32: global node id per scenario per nonant slot
    node_of: Any
    # (S,) float: unconditional scenario probability
    prob: Any
    # number of distinct nodes (static, for segment_sum sizing)
    num_nodes: int = 1
    # (K,) int32 stage (1-based) of each nonant slot; static metadata
    stage_of: Any = None
    # names for reporting (static)
    nonant_names: tuple = ()
    scen_names: tuple = ()


_register(
    TreeInfo,
    data_fields=("node_of", "prob"),
    meta_fields=("num_nodes", "stage_of", "nonant_names", "scen_names"),
)


@dataclasses.dataclass(frozen=True)
class ScenarioBatch:
    """A batch of S lowered scenario subproblems (leading axis = scenario).

    The lowering replaces the reference's per-iteration Pyomo objective
    mutation (phbase.py:585-699 attach_Ws_and_prox/attach_PH_to_objective):
    PH's W and prox terms enter as pure array arguments to the solver
    kernel, never touching this static problem data.
    """

    c: Any          # (S, N) linear objective
    qdiag: Any      # (S, N) diagonal quadratic objective (0 for LP)
    A: Any          # (S, M, N) constraints
    row_lo: Any     # (S, M)
    row_hi: Any     # (S, M)
    lb: Any         # (S, N)
    ub: Any         # (S, N)
    obj_const: Any  # (S,)
    nonant_idx: Any  # (K,) int32 — same layout for all scenarios
    integer_mask: Any  # (S, N) bool
    tree: TreeInfo
    # (n_stages, S, N): per-stage objective coefficient split, for
    # FirstStageCost-style reporting (reference cost_expression per node);
    # optional — None when not provided.
    stage_cost_c: Any = None
    # (S, K) per-(scenario, nonant-slot) probabilities for consensus
    # averaging — the reference's variable_probability feature
    # (spbase.py:394 _mpisppy_variable_probability); None = use the
    # scenario probabilities uniformly across slots.
    var_prob: Any = None
    var_names: tuple = ()   # static, length N (reporting only)
    # model-specific static metadata (e.g. UC's min-up/down window
    # tables) — carried so helpers never re-derive structure baked
    # into A; preserved by pad/densify (dataclasses.replace)
    model_meta: Any = None

    @property
    def num_scens(self):
        return self.c.shape[0]

    @property
    def shared_A(self):
        """True when ONE constraint matrix serves every scenario (the
        uncertainty lives in row bounds / objective only): A is stored
        (1, M, N) and ops use ir.bmatvec's matmul fast path."""
        return self.A.shape[0] == 1 and self.c.shape[0] > 1

    @property
    def split_A(self):
        """True when A is stored split-native (ir.SplitA: shared part +
        per-scenario sparse delta) — the representation for instances
        too large to ever materialize (S, M, N) densely (true-size
        farmer: crops_multiplier=1000 is ~288 GB dense f32)."""
        return isinstance(self.A, SplitA)

    def densify(self):
        """Materialize a per-scenario A from a shared or split one (for
        code paths that index A by scenario, e.g. the MIP dive)."""
        if self.split_A:
            S, M, N = self.A.shape
            if S * M * N > 500_000_000:
                raise MemoryError(
                    f"densify() of a split-native batch would build a "
                    f"{S}x{M}x{N} tensor; this code path (dense "
                    f"per-scenario A) does not support instances of "
                    f"this size")
            return dataclasses.replace(self, A=self.A.to_dense())
        if not self.shared_A:
            return self
        A = jnp.broadcast_to(self.A[0][None],
                             (self.num_scens,) + self.A.shape[1:])
        return dataclasses.replace(self, A=A)

    @property
    def num_vars(self):
        return self.c.shape[1]

    @property
    def num_rows(self):
        return self.A.shape[1]

    @property
    def num_nonants(self):
        return self.nonant_idx.shape[0]

    @property
    def prob(self):
        return self.tree.prob

    def nonants(self, x):
        """Extract nonant slots from a (..., N) solution -> (..., K)."""
        return jnp.take(x, self.nonant_idx, axis=-1)

    def objective(self, x):
        """Per-scenario objective value of a (S, N) primal point -> (S,)."""
        return (
            jnp.sum(self.c * x, axis=-1)
            + 0.5 * jnp.sum(self.qdiag * x * x, axis=-1)
            + self.obj_const
        )


_register(
    ScenarioBatch,
    data_fields=(
        "c", "qdiag", "A", "row_lo", "row_hi", "lb", "ub", "obj_const",
        "nonant_idx", "integer_mask", "tree", "stage_cost_c", "var_prob",
        "model_meta",
    ),
    meta_fields=("var_names",),
)


@dataclasses.dataclass(frozen=True)
class SplitA:
    """Constraint batch in shared + sparse-delta form:

        A(s) = shared  +  scatter((rows, cols) -> vals[s])

    The TPU-native representation for families whose MATRIX uncertainty
    touches only a few coordinates per scenario (farmer: the per-crop
    yield coefficients — 2*n_crops entries out of M*N).  The batched
    matvec then runs as ONE (S, N) x (N, M) matmul on the MXU plus an
    nnz-sized scatter, instead of an (S, M, N) batched GEMV: per-
    iteration HBM traffic drops from S*M*N to M*N + S*nnz — the same
    trick as ScenarioBatch.shared_A (row-bound uncertainty), extended
    to matrix uncertainty.  `shared` stores ZEROS at the delta
    positions, so the scatter ADD needs no masking.

    Models declare the delta coordinate set via
    model_meta["A_delta_idx"] = (rows, cols); SPOpt then builds the
    split PreparedBatch (ops/pdhg.prepare_batch_split) while batch.A
    itself stays dense for the code paths that index it by scenario
    (MIP dives, Benders cuts, Schur assembly).
    """

    shared: Any   # (M, N) scenario-independent part (0 at delta slots)
    rows: Any     # (nnz,) int32 row of each per-scenario entry
    cols: Any     # (nnz,) int32 column of each per-scenario entry
    vals: Any     # (S, nnz) per-scenario values at (rows, cols)

    @property
    def shape(self):
        return (self.vals.shape[0],) + tuple(self.shared.shape)

    @property
    def ndim(self):
        return 3

    @property
    def dtype(self):
        return self.shared.dtype

    def to_dense(self):
        S = self.vals.shape[0]
        A = jnp.broadcast_to(self.shared[None],
                             (S,) + tuple(self.shared.shape))
        return A.at[:, self.rows, self.cols].add(self.vals)

    def astype(self, dt):
        """Cast shared + per-scenario values (the mixed-precision hot
        loop's storage cast, ops/pdhg hot_dtype); the int coordinate
        arrays are untouched.  Subclass-preserving."""
        return dataclasses.replace(
            self, shared=self.shared.astype(dt),
            vals=self.vals.astype(dt))

    def scale_shared(self, row_mult, col_mult):
        """shared <- diag(row_mult) @ shared @ diag(col_mult), in
        whatever representation `shared` uses (dense here; coordinate
        data in SparseSplitA)."""
        return self.shared * row_mult[:, None] * col_mult[None, :]


_register(SplitA, data_fields=("shared", "rows", "cols", "vals"),
          meta_fields=())


@dataclasses.dataclass(frozen=True)
class SparseSplitA(SplitA):
    """SplitA whose SHARED block is a `jax.experimental.sparse.BCOO`
    matrix instead of a dense (M, N) array.

    When the shared block itself is sparse (UC/network families: each
    row touches a handful of variables), the dense (S, N) x (N, M)
    matmul of the SplitA fast path still pays M*N FLOPs per scenario
    for mostly-zero entries.  Storing the shared block as BCOO routes
    `bmatvec`/`bmatvec_t` through the sparse dot_general rules, so the
    per-iteration cost drops from O(S*M*N) to O(S*nnz(shared) +
    S*nnz(delta)).  The per-scenario delta stays in (rows, cols, vals)
    scatter form, identical to SplitA — gather/compaction
    (`ops/pdhg._gather_prep`, `solve_compacted`) and scenario padding
    touch only `vals` and work unchanged.

    Built by `sparsify_split` when the shared density is below the
    solver's `sparse_threshold` knob (dense fallback above it, and
    whenever jax.experimental.sparse is unavailable)."""

    def to_dense(self):
        S = self.vals.shape[0]
        sh = self.shared.todense()
        A = jnp.broadcast_to(sh[None], (S,) + tuple(sh.shape))
        return A.at[:, self.rows, self.cols].add(self.vals)

    def astype(self, dt):
        from jax.experimental import sparse as jsparse
        sh = jsparse.BCOO((self.shared.data.astype(dt),
                           self.shared.indices),
                          shape=self.shared.shape)
        return dataclasses.replace(self, shared=sh,
                                   vals=self.vals.astype(dt))

    def scale_shared(self, row_mult, col_mult):
        from jax.experimental import sparse as jsparse
        i = self.shared.indices
        data = self.shared.data * row_mult[i[:, 0]] * col_mult[i[:, 1]]
        return jsparse.BCOO((data, i), shape=self.shared.shape)

    @property
    def shared_nnz_frac(self):
        """Stored-element fraction of the shared block (the density the
        sparse_threshold knob gates on; bench JSON `shared_nnz_frac`)."""
        M, N = self.shared.shape
        return float(self.shared.nse) / float(max(M * N, 1))


_register(SparseSplitA, data_fields=("shared", "rows", "cols", "vals"),
          meta_fields=())


def shared_density(A):
    """Nonzero fraction of a SplitA's shared block (1.0 for non-split
    operators — dense batched A never routes sparse)."""
    if isinstance(A, SparseSplitA):
        return A.shared_nnz_frac
    if not isinstance(A, SplitA):
        return 1.0
    sh = np.asarray(A.shared)
    return float(np.count_nonzero(sh)) / float(max(sh.size, 1))


def sparsify_split(A, threshold):
    """Convert a dense-shared SplitA to a SparseSplitA when its shared
    block's density is below `threshold` (host-side, once per prep —
    never inside a trace).  Returns `A` unchanged when the threshold is
    off (<= 0), the density is at/above it, `A` is not a SplitA, or
    jax.experimental.sparse is unavailable (the dense fallback the
    mixed-precision docs promise)."""
    if threshold is None or float(threshold) <= 0.0:
        return A
    if not isinstance(A, SplitA) or isinstance(A, SparseSplitA):
        return A
    dens = shared_density(A)
    if dens >= float(threshold):
        return A
    try:
        from jax.experimental import sparse as jsparse
    except ImportError:        # pragma: no cover - jax always has it
        return A
    sh = np.asarray(A.shared)
    nse = max(int(np.count_nonzero(sh)), 1)
    bcoo = jsparse.BCOO.fromdense(jnp.asarray(A.shared), nse=nse)
    return SparseSplitA(shared=bcoo, rows=A.rows, cols=A.cols,
                        vals=A.vals)


class Static:
    """Wrap a non-array value (string, tuple of names, ...) so it can
    ride inside `model_meta` (a DATA pytree field): the wrapper
    registers as a pytree node with NO array leaves — the value is
    auxiliary data, invisible to tree_map / jit tracing / sharding."""

    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value

    def __repr__(self):
        return f"Static({self.value!r})"

    def __eq__(self, other):
        return isinstance(other, Static) and self.value == other.value

    def __hash__(self):
        return hash(self.value)


jax.tree_util.register_pytree_node(
    Static, lambda s: ((), s.value), lambda aux, _: Static(aux))


def delta_idx(batch):
    """The batch's declared sparse matrix-uncertainty coordinates
    (model_meta["A_delta_idx"] -> (rows, cols) numpy int arrays), or
    None.  ONE accessor for the contract so every consumer (SPOpt prep,
    the xhat reduced-system builder, bundling's remap) reads it the
    same way."""
    meta = batch.model_meta
    if not isinstance(meta, dict):
        return None
    return meta.get("A_delta_idx")


def bmatvec(A, x):
    """Batched A @ x: A (SA, M, N) with SA == S or SA == 1 (shared
    constraint matrix), or a SplitA; x (S, N) -> (S, M).

    The shared-A case is the TPU-native fast path for model families
    whose uncertainty lives in the ROW BOUNDS only (UC wind, many
    two-stage demand models): one (M, N) matrix turns the batched
    matvec into a real (S, N) x (N, M) matmul on the MXU and cuts the
    constraint-tensor memory by S.  SplitA extends the same fast path
    to sparse MATRIX uncertainty (shared matmul + nnz scatter).  With
    a SparseSplitA the shared product routes through
    jax.experimental.sparse's dot_general rules (the `@` below
    dispatches on the BCOO type), dropping the dense M*N FLOPs per
    scenario to nnz(shared)."""
    if isinstance(A, SplitA):
        out = x @ A.shared.T
        return out.at[:, A.rows].add(A.vals * jnp.take(x, A.cols, axis=1))
    if A.shape[0] == 1:
        return x @ A[0].T
    return jnp.einsum("smn,sn->sm", A, x)


def bmatvec_t(A, y):
    """Batched A^T @ y: A (SA, M, N) or SplitA, y (S, M) -> (S, N)."""
    if isinstance(A, SplitA):
        out = y @ A.shared
        return out.at[:, A.cols].add(A.vals * jnp.take(y, A.rows, axis=1))
    if A.shape[0] == 1:
        return y @ A[0]
    return jnp.einsum("smn,sm->sn", A, y)


def node_segment_sum(node_of, num_nodes):
    """Per-(tree node, nonant slot) segment reduction.

    This is THE consensus primitive: the (node, slot) pair is one
    segment key (flatid = node_of * K + slot), and a reduction over a
    (S, K) array scatter-adds into the nn*K segments then gathers back
    to scenario layout.  Used by both PH's xbar averaging
    (phbase.compute_xbar — the analog of the reference's per-tree-node
    Allreduce, phbase.py:27-107) and the EF consensus solver's
    shared-variable adjoint (ops/pdhg.ConsensusSpec).

    Returns (flatid (S, K) int32, segsum) where segsum(v: (S, K))
    -> (S, K) holds each element's segment total.
    """
    K = node_of.shape[1]
    cols = jnp.broadcast_to(jnp.arange(K)[None, :], node_of.shape)
    flatid = node_of * K + cols
    fl = flatid.reshape(-1)
    size = num_nodes * K

    def segsum(v):
        z = jnp.zeros((size,), v.dtype).at[fl].add(v.reshape(-1))
        return z[flatid]

    return flatid, segsum


def stack_scenarios(scens, scen_names=None):
    """Stack a list of single-scenario dicts/batches (S=1 each) into one
    ScenarioBatch.  Mirrors SPBase._create_scenarios looping the user's
    scenario_creator (reference spbase.py:255-273), then normalizes
    probabilities the way _compute_unconditional_node_probabilities does
    (spbase.py:378-392).
    """
    if not scens:
        raise ValueError("no scenarios to stack")
    first = scens[0]
    if any(s.num_vars != first.num_vars or s.num_rows != first.num_rows
           for s in scens):
        raise ValueError(
            "all scenarios in a batch must share (num_rows, num_vars); "
            "pad constraint rows with free bounds to equalize"
        )
    # nonant layout must be identical — the consensus average pairs slot
    # j across scenarios (reference counterpart: _verify_nonant_lengths,
    # spbase.py:150)
    ref_idx = np.asarray(first.nonant_idx)
    for s in scens[1:]:
        if not np.array_equal(np.asarray(s.nonant_idx), ref_idx):
            raise ValueError(
                "all scenarios must declare the same nonant variable "
                "layout (indices and order)")

    def cat(field):
        return jnp.concatenate([getattr(s, field) for s in scens], axis=0)

    prob = jnp.concatenate([s.tree.prob for s in scens])
    total = jnp.sum(prob)
    prob = prob / total
    node_of = jnp.concatenate([s.tree.node_of for s in scens], axis=0)
    num_nodes = max(s.tree.num_nodes for s in scens)
    names = tuple(scen_names) if scen_names is not None else tuple(
        n for s in scens for n in (s.tree.scen_names or ("?",) * s.num_scens)
    )
    tree = TreeInfo(
        node_of=node_of,
        prob=prob,
        num_nodes=num_nodes,
        stage_of=first.tree.stage_of,
        nonant_names=first.tree.nonant_names,
        scen_names=names,
    )
    stage_cost_c = None
    if first.stage_cost_c is not None:
        stage_cost_c = jnp.concatenate(
            [s.stage_cost_c for s in scens], axis=1)
    var_prob = None
    if first.var_prob is not None:
        var_prob = cat("var_prob")
    return ScenarioBatch(
        c=cat("c"), qdiag=cat("qdiag"), A=cat("A"),
        row_lo=cat("row_lo"), row_hi=cat("row_hi"),
        lb=cat("lb"), ub=cat("ub"), obj_const=cat("obj_const"),
        nonant_idx=first.nonant_idx,
        integer_mask=cat("integer_mask"),
        tree=tree,
        stage_cost_c=stage_cost_c,
        var_prob=var_prob,
        var_names=first.var_names,
    )


def pad_scenarios(batch: ScenarioBatch, to: int) -> ScenarioBatch:
    """Pad a batch with zero-probability dummy scenarios so S divides the
    device count.  The sharding layer requires equal shards per device —
    the analog of the reference's contiguous scenario slices per rank
    (sputils.py:804-812), which tolerate ragged slice sizes; we instead
    pad and let probability-0 entries vanish from every reduction.
    """
    S = batch.num_scens
    if to <= S:
        return batch
    padn = to - S

    def padfield(v, fill=0.0):
        pad_shape = (padn,) + v.shape[1:]
        return jnp.concatenate([v, jnp.full(pad_shape, fill, v.dtype)], axis=0)

    tree = batch.tree
    # pads get their own dummy tree node: probability-0 keeps them out
    # of every xbar average, and a distinct node id keeps them out of
    # EF consensus groups (where membership is structural, not
    # probability-weighted — a pad in ROOT would drag its tiny [0,1]
    # pad box into the shared first-stage variable)
    new_tree = TreeInfo(
        node_of=padfield(tree.node_of, tree.num_nodes),
        prob=padfield(tree.prob, 0.0),
        num_nodes=tree.num_nodes + 1,
        stage_of=tree.stage_of,
        nonant_names=tree.nonant_names,
        scen_names=tree.scen_names + tuple(
            f"_pad{i}" for i in range(padn)),
    )
    # Dummy scenarios: feasible-by-construction (free rows, unit box).
    # A shared constraint matrix needs no padding — pads reuse it under
    # free row bounds (any box point satisfies free rows).  The same
    # free-row argument keeps a model_meta["A_delta_idx"] declaration
    # sound: split prep gives a zero-padded scenario the SHARED matrix
    # instead of its literal zero matrix, which only free rows (and
    # prob 0) make harmless — pad_scenarios must never emit pads with
    # finite row bounds.
    if isinstance(batch.A, SplitA):
        # a zero-padded scenario gets the SHARED matrix plus ZERO
        # deltas — harmless under the free row bounds + prob 0 below
        # (same argument as the shared-A case); dataclasses.replace
        # keeps a SparseSplitA sparse
        A_pad = dataclasses.replace(batch.A,
                                    vals=padfield(batch.A.vals))
    else:
        A_pad = batch.A if batch.shared_A else padfield(batch.A)
    return ScenarioBatch(
        c=padfield(batch.c),
        qdiag=padfield(batch.qdiag),
        A=A_pad,
        row_lo=padfield(batch.row_lo, -np.inf),
        row_hi=padfield(batch.row_hi, np.inf),
        lb=padfield(batch.lb),
        ub=padfield(batch.ub, 1.0),
        obj_const=padfield(batch.obj_const),
        nonant_idx=batch.nonant_idx,
        integer_mask=padfield(batch.integer_mask, False),
        tree=new_tree,
        stage_cost_c=None if batch.stage_cost_c is None else jnp.pad(
            batch.stage_cost_c, ((0, 0), (0, padn), (0, 0))),
        var_prob=None if batch.var_prob is None
        else padfield(batch.var_prob, 0.0),
        var_names=batch.var_names,
        model_meta=batch.model_meta,
    )

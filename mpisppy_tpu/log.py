"""Logging configuration (reference: mpisppy/log.py:43-67
`setup_logger`).

The reference exposes one helper that configures a named logger with a
level, an optional file target, and a console fallback, so each module
(`mpisppy.cylinders.hub`, ...) can be tuned independently.  Same
contract here, stdlib-only; plus `global_toc_logger` to mirror the
timestamped screen trace (mpisppy_tpu.global_toc) into the logging
tree when a file target is wanted.
"""

from __future__ import annotations

import logging
import sys

_FORMAT = "%(asctime)s - %(levelname)s - %(name)s: %(message)s"


def setup_logger(name: str, out: str | None = None,
                 level=logging.INFO, fmt: str = _FORMAT,
                 mode: str = "w") -> logging.Logger:
    """Configure and return logger `name` (reference log.py:43-67).

    out: file path, or None / "-" / "stdout" / "stderr" for console.
    Calling again with the same name replaces the handlers (idempotent
    reconfiguration, matching the reference's behavior of one handler
    per named logger).
    """
    logger = logging.getLogger(name)
    logger.setLevel(level)
    logger.propagate = False
    for h in list(logger.handlers):
        logger.removeHandler(h)
        try:
            h.close()
        except Exception:
            pass
    if out in (None, "-", "stdout"):
        handler = logging.StreamHandler(sys.stdout)
    elif out == "stderr":
        handler = logging.StreamHandler(sys.stderr)
    else:
        handler = logging.FileHandler(out, mode=mode)
    handler.setLevel(level)
    handler.setFormatter(logging.Formatter(fmt))
    logger.addHandler(handler)
    return logger


def global_toc_logger(out: str | None = None, level=logging.INFO):
    """Route the package's global_toc screen trace into a logger as
    well (the reference prints via tt_timer only; file capture of the
    trace is this build's addition for headless TPU runs)."""
    from mpisppy_tpu import add_toc_sink

    logger = setup_logger("mpisppy_tpu.toc", out=out, level=level,
                          fmt="%(message)s")
    add_toc_sink(lambda msg: logger.log(level, msg))
    return logger

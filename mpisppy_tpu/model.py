"""Declarative linear-model builder — the user-facing replacement for Pyomo.

Reference `scenario_creator`s build a Pyomo ConcreteModel and attach
`_mpisppy_node_list` (reference: mpisppy/tests/examples/farmer.py:77-86).
Here a creator builds a `LinearModel`, declares variable blocks,
constraints and per-stage costs, then calls `lower()` to produce the
dense-array `ScenarioBatch` IR (ir.py) that the batched TPU kernels
consume.  Model build happens once, on the host, in numpy; nothing here
is traced by JAX.

Design notes (TPU-first): constraints accumulate into a scipy-free COO
triple and densify at the end — models in the target corpus are small
per scenario (tens..thousands of vars), and the batch axis over
scenarios is where the scale is, so a dense (M, N) block per scenario
feeds the MXU well.
"""

from __future__ import annotations

import numpy as np

from .ir import ScenarioBatch, TreeInfo

INF = float("inf")


class _VarBlock:
    __slots__ = ("name", "offset", "size", "shape")

    def __init__(self, name, offset, size, shape):
        self.name = name
        self.offset = offset
        self.size = size
        self.shape = shape

    def __getitem__(self, idx):
        flat = np.ravel_multi_index(idx if isinstance(idx, tuple) else (idx,),
                                    self.shape)
        return self.offset + int(flat)

    def indices(self):
        return np.arange(self.offset, self.offset + self.size)


class LinearExpr:
    """Tiny linear expression: {var_index: coeff} + const."""

    __slots__ = ("terms", "const")

    def __init__(self, terms=None, const=0.0):
        self.terms = dict(terms or {})
        self.const = const

    def add(self, idx, coeff):
        self.terms[idx] = self.terms.get(idx, 0.0) + coeff
        return self


class LinearModel:
    """Build one scenario's LP/QP.

    Usage (see models/farmer.py for a full example):
        m = LinearModel()
        x = m.add_vars("DevotedAcreage", 3, lb=0, ub=500)
        m.add_constr({x[0]: 1, x[1]: 1, x[2]: 1}, hi=500)
        m.add_cost(stage=1, terms={x[0]: 150.0, ...})
        m.set_nonants([x], stage=1)
        spec = m.lower(prob=1/3, name="scen0")
    """

    def __init__(self, sense=1):
        # sense: +1 minimize, -1 maximize (objective is negated on lowering
        # so the kernels always minimize; mirrors SPBase._set_sense
        # at spbase.py:122)
        self.sense = sense
        self._blocks = {}
        self._n = 0
        self._lb = []
        self._ub = []
        self._integer = []
        self._rows = []        # list of (terms_dict, lo, hi)
        self._stage_costs = {}  # stage -> {idx: coeff}
        self._obj_const = 0.0
        self._nonant_blocks = []  # list of (block, stage)
        self._var_names = []

    # ---- variables -----------------------------------------------------
    def add_vars(self, name, shape, lb=0.0, ub=INF, integer=False):
        if isinstance(shape, int):
            shape = (shape,)
        size = int(np.prod(shape))
        blk = _VarBlock(name, self._n, size, shape)
        self._blocks[name] = blk
        self._n += size
        self._lb.extend(np.broadcast_to(lb, (size,)).astype(float).tolist())
        self._ub.extend(np.broadcast_to(ub, (size,)).astype(float).tolist())
        self._integer.extend(np.broadcast_to(integer, (size,)).tolist())
        if size == 1:
            self._var_names.append(name)
        else:
            self._var_names.extend(f"{name}[{i}]" for i in range(size))
        return blk

    def add_var(self, name, lb=0.0, ub=INF, integer=False):
        return self.add_vars(name, 1, lb=lb, ub=ub, integer=integer)[0]

    # ---- constraints ---------------------------------------------------
    def add_constr(self, terms, lo=-INF, hi=INF):
        """terms: {var_index: coeff} (or LinearExpr).  lo <= a@x <= hi."""
        if isinstance(terms, LinearExpr):
            lo = lo - terms.const if lo != -INF else lo
            hi = hi - terms.const if hi != INF else hi
            terms = terms.terms
        self._rows.append((dict(terms), float(lo), float(hi)))

    def add_constr_rows(self, A_rows, idx_cols, lo, hi):
        """Vectorized: A_rows (R, k) coeffs hitting columns idx_cols (R, k)."""
        A_rows = np.asarray(A_rows, dtype=float)
        idx_cols = np.asarray(idx_cols)
        lo = np.broadcast_to(lo, (A_rows.shape[0],))
        hi = np.broadcast_to(hi, (A_rows.shape[0],))
        for r in range(A_rows.shape[0]):
            self._rows.append(
                (dict(zip(idx_cols[r].tolist(), A_rows[r].tolist())),
                 float(lo[r]), float(hi[r])))

    # ---- objective -----------------------------------------------------
    def add_cost(self, stage, terms, const=0.0):
        """Attach per-stage cost (reference: ScenarioNode.cost_expression,
        scenario_tree.py:44).  terms: {var_index: coeff}."""
        d = self._stage_costs.setdefault(stage, {})
        if isinstance(terms, LinearExpr):
            const = const + terms.const
            terms = terms.terms
        for i, cf in terms.items():
            d[i] = d.get(i, 0.0) + cf
        self._obj_const += const

    # ---- nonanticipativity --------------------------------------------
    def set_nonants(self, blocks, stage=1):
        """Declare nonant variable blocks for a stage, in order
        (reference: nonant_list on ScenarioNode)."""
        for b in blocks:
            self._nonant_blocks.append((b, stage))

    # ---- lowering ------------------------------------------------------
    def lower(self, prob, name="scen", node_ids=None, num_nodes=1,
              dtype=np.float64, pad_rows_to=None):
        """Produce a single-scenario ScenarioBatch (S=1).

        node_ids: optional (K,) array of global tree-node ids per nonant
        slot (multistage); default all-ROOT (two-stage).
        """
        n = self._n
        m = len(self._rows)
        mpad = max(m, pad_rows_to or 0)
        A = np.zeros((mpad, n), dtype=dtype)
        row_lo = np.full((mpad,), -INF, dtype=dtype)
        row_hi = np.full((mpad,), INF, dtype=dtype)
        for r, (terms, lo, hi) in enumerate(self._rows):
            for i, cf in terms.items():
                A[r, i] += cf
            row_lo[r] = lo
            row_hi[r] = hi

        c = np.zeros((n,), dtype=dtype)
        stages = sorted(self._stage_costs)
        n_stages = max(stages) if stages else 1
        stage_cost_c = np.zeros((n_stages, n), dtype=dtype)
        for st, d in self._stage_costs.items():
            for i, cf in d.items():
                stage_cost_c[st - 1, i] += cf
                c[i] += cf
        if self.sense < 0:
            c = -c
            stage_cost_c = -stage_cost_c

        nonant_idx = np.concatenate(
            [b.indices() for b, _st in self._nonant_blocks]
        ).astype(np.int32) if self._nonant_blocks else np.zeros(
            (0,), np.int32)
        stage_of = np.concatenate(
            [np.full((b.size,), st, np.int32)
             for b, st in self._nonant_blocks]
        ) if self._nonant_blocks else np.zeros((0,), np.int32)
        K = nonant_idx.shape[0]
        if node_ids is None:
            node_ids = np.zeros((K,), np.int32)
        node_ids = np.asarray(node_ids, np.int32).reshape(1, K)

        tree = TreeInfo(
            node_of=node_ids,
            prob=np.asarray([prob], dtype=dtype),
            num_nodes=num_nodes,
            stage_of=tuple(stage_of.tolist()),
            nonant_names=tuple(self._var_names[i] for i in nonant_idx),
            scen_names=(name,),
        )
        return ScenarioBatch(
            c=c[None], qdiag=np.zeros((1, n), dtype=dtype),
            A=A[None], row_lo=row_lo[None], row_hi=row_hi[None],
            lb=np.asarray(self._lb, dtype=dtype)[None],
            ub=np.asarray(self._ub, dtype=dtype)[None],
            obj_const=np.asarray(
                [self._obj_const * (1 if self.sense > 0 else -1)],
                dtype=dtype),
            nonant_idx=nonant_idx,
            integer_mask=np.asarray(self._integer, dtype=bool)[None],
            tree=tree,
            stage_cost_c=stage_cost_c[:, None, :],
            var_names=tuple(self._var_names),
        )

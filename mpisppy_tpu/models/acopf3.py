"""ACOPF3 — multistage optimal power flow with random line outages
(reference: examples/acopf3/ccopf_multistage.py + ACtree.py, which
builds chance-constrained AC-OPF instances over an outage scenario
tree via egret/matpower and per-stage repair processes).

TPU-native analog: the **DC** approximation (the standard convex
relaxation of the reference's `convex_relaxation=True` mode) over the
same kind of outage tree, lowered directly to batched arrays — no
external power-systems stack.  Per scenario and stage t:

    g[t, i]      generator dispatch            (nonant for t < T)
    th[t, b]     bus voltage angle (slack bus pinned to 0)
    f[t, l]      line flow
    mp/mn[t, b]  load-mismatch slacks (cost `load_mismatch_cost`,
                 the reference's default 1000, ccopf_multistage.py:77)

Rows:
    f[t, l] - alive[t, l] * B_l (th_from - th_to) == 0   (DC flow; an
        OUTAGE sets alive=0, forcing the flow to zero)
    sum_in f - sum_out f + gen_at_bus + mp - mn == load[t, b]
    -ramp <= g[t, i] - g[t-1, i] <= ramp                 (ramping)
Boxes: |f| <= cap, |th| <= pi, 0 <= g <= gmax, 0 <= m <= total load —
all finite, so PDHG dual objectives are valid bounds at any iterate
(spopt.valid_Ebound).

Generator cost is c1*g + c2*g^2 via the batch's diagonal quadratic
term — this model family exercises the QP path of the kernel.

Outage process: at each non-root tree node, the node's branch digit d
selects line d-1 to fail for that stage (digit 0 = no new outage);
outages persist down the tree (no repair — the reference's FixNever;
its FixGaussian repair corresponds to clearing alive bits, hookable
via `repair`).  The grid is a seeded ring-plus-chords synthetic case.
"""

from __future__ import annotations

import numpy as np

from ..ir import ScenarioBatch, TreeInfo
from ..scenario_tree import MultistageTree

INF = float("inf")


# IEEE 14-bus test case — standard public benchmark data (the
# matpower/PGLib `case14`): bus loads (MW), branch endpoints and
# series reactances (p.u.), generator buses, limits (MW), and
# polynomial costs.  This is the kind of real network the reference
# feeds egret (examples/acopf3/ccopf_multistage.py builds instances
# from matpower case files); embedding the published case data mirrors
# how sizes/sslp embed SIZES/SIPLIB instance data.  Branch thermal
# limits: case14 publishes none (rateA=0 = unlimited); we use a
# uniform finite `line_cap` (default 160 MW — non-binding in the
# nominal dispatch, binding under outages) because the kernel's
# bound-validity rule wants all-finite boxes.
_IEEE14_LOAD = [0.0, 21.7, 94.2, 47.8, 7.6, 11.2, 0.0, 0.0, 29.5,
                9.0, 3.5, 6.1, 13.5, 14.9]
_IEEE14_LINES = [
    (0, 1, 0.05917), (0, 4, 0.22304), (1, 2, 0.19797),
    (1, 3, 0.17632), (1, 4, 0.17388), (2, 3, 0.17103),
    (3, 4, 0.04211), (3, 6, 0.20912), (3, 8, 0.55618),
    (4, 5, 0.25202), (5, 10, 0.19890), (5, 11, 0.25581),
    (5, 12, 0.13027), (6, 7, 0.17615), (6, 8, 0.11001),
    (8, 9, 0.08450), (8, 13, 0.27038), (9, 10, 0.19207),
    (11, 12, 0.19988), (12, 13, 0.34802)]
_IEEE14_GEN_BUS = [0, 1, 2, 5, 7]
_IEEE14_GMAX = [332.4, 140.0, 100.0, 100.0, 100.0]
_IEEE14_C1 = [20.0, 20.0, 40.0, 40.0, 40.0]
_IEEE14_C2 = [0.0430292599, 0.25, 0.01, 0.01, 0.01]


def _grid_ieee14(line_cap=160.0):
    lines = [(a, b) for a, b, _ in _IEEE14_LINES]
    # reactances are per-unit on the 100 MVA system base; loads/flows
    # here are MW, so B[MW/rad] = 100 / x_pu
    susceptance = np.array([100.0 / x for _, _, x in _IEEE14_LINES])
    cap = np.full(len(lines), float(line_cap))
    gen_bus = np.array(_IEEE14_GEN_BUS)
    return (lines, susceptance, cap, gen_bus,
            np.array(_IEEE14_GMAX), np.array(_IEEE14_C1),
            np.array(_IEEE14_C2), np.array(_IEEE14_LOAD))


def _grid(n_bus, n_line, n_gen, seed):
    rng = np.random.RandomState(seed)
    # ring + random chords; at most C(n_bus, 2) distinct lines exist,
    # so cap the request or the chord loop would never terminate
    n_line = min(n_line, n_bus * (n_bus - 1) // 2)
    lines = [(b, (b + 1) % n_bus) for b in range(n_bus)]
    while len(lines) < n_line:
        a, b = rng.randint(0, n_bus, 2)
        if a != b and (a, b) not in lines and (b, a) not in lines:
            lines.append((a, b))
    lines = lines[:n_line]
    susceptance = 5.0 + 10.0 * rng.rand(len(lines))
    cap = 60.0 + 40.0 * rng.rand(len(lines))
    gen_bus = rng.choice(n_bus, size=n_gen, replace=False)
    gmax = 80.0 + 40.0 * rng.rand(n_gen)
    c1 = 10.0 + 10.0 * rng.rand(n_gen)
    c2 = 0.05 + 0.1 * rng.rand(n_gen)
    base_load = 20.0 + 20.0 * rng.rand(n_bus)
    return (lines, susceptance, cap, gen_bus, gmax, c1, c2, base_load)


def build_batch(branching_factors=(2, 2), n_bus=5, n_line=6, n_gen=3,
                ramp=None, load_mismatch_cost=1000.0, seed=3301,
                repair=False, case=None, line_cap=160.0,
                dtype=np.float64) -> ScenarioBatch:
    """case=None: seeded synthetic ring-plus-chords grid (n_bus /
    n_line / n_gen sized).  case="ieee14": the embedded IEEE 14-bus
    benchmark network (n_bus/n_line/n_gen ignored; `line_cap` sets the
    uniform thermal limit).  ramp=None resolves per case: 40 MW on the
    synthetic grid, a third of each unit's Pmax on ieee14."""
    tree = MultistageTree(list(branching_factors))
    T = tree.n_stages
    S = tree.num_scens
    if case == "ieee14":
        (lines, B, cap, gen_bus, gmax, c1, c2, base_load) = \
            _grid_ieee14(line_cap)
        n_bus, n_gen = len(base_load), len(gen_bus)
        if ramp is None:
            ramp = gmax / 3.0
    elif case is not None:
        raise ValueError(f"unknown case {case!r} (None or 'ieee14')")
    else:
        (lines, B, cap, gen_bus, gmax, c1, c2, base_load) = _grid(
            n_bus, n_line, n_gen, seed)
        if ramp is None:
            ramp = 40.0
    nL, nG, nB = len(lines), n_gen, n_bus
    ramp_arr = np.broadcast_to(np.asarray(ramp, float), (nG,))

    # outage mask per scenario per stage: branch digit d at stage t>=2
    # fails line d-1 (0 = none); persists unless repair
    alive = np.ones((S, T, nL))
    for s in range(S):
        digits = tree.scen_digits(s)
        out = set()
        for t in range(1, T):
            d = digits[t - 1] % (nL + 1)
            if d > 0:
                out.add(d - 1)
            if repair and len(out) > 1:
                out.pop()
            for l_ in out:
                alive[s, t, l_] = 0.0

    # per-stage layout: [g (nG) | th (nB) | f (nL) | mp (nB) | mn (nB)]
    per = nG + nB + nL + 2 * nB
    N = T * per

    def vg(t, i):
        return t * per + i

    def vth(t, b):
        return t * per + nG + b

    def vf(t, l_):
        return t * per + nG + nB + l_

    def vmp(t, b):
        return t * per + nG + nB + nL + b

    def vmn(t, b):
        return t * per + nG + nB + nL + nB + b

    # loads grow slightly by stage
    load = np.stack([base_load * (1.0 + 0.1 * t) for t in range(T)])

    M = T * nL + T * nB + (T - 1) * nG
    A = np.zeros((S, M, N), dtype=dtype)
    row_lo = np.full((S, M), -INF, dtype=dtype)
    row_hi = np.full((S, M), INF, dtype=dtype)
    r = 0
    for t in range(T):                 # DC flow definition
        for l_, (a, b) in enumerate(lines):
            A[:, r, vf(t, l_)] = 1.0
            A[:, r, vth(t, a)] = -alive[:, t, l_] * B[l_]
            A[:, r, vth(t, b)] = alive[:, t, l_] * B[l_]
            row_lo[:, r] = row_hi[:, r] = 0.0
            r += 1
    for t in range(T):                 # bus balance
        for b in range(nB):
            for l_, (x, y) in enumerate(lines):
                if y == b:
                    A[:, r, vf(t, l_)] = 1.0
                elif x == b:
                    A[:, r, vf(t, l_)] = -1.0
            for i, gb in enumerate(gen_bus):
                if gb == b:
                    A[:, r, vg(t, i)] = 1.0
            A[:, r, vmp(t, b)] = 1.0
            A[:, r, vmn(t, b)] = -1.0
            row_lo[:, r] = row_hi[:, r] = load[t, b]
            r += 1
    for t in range(1, T):              # ramping
        for i in range(nG):
            A[:, r, vg(t, i)] = 1.0
            A[:, r, vg(t - 1, i)] = -1.0
            row_lo[:, r] = -ramp_arr[i]
            row_hi[:, r] = ramp_arr[i]
            r += 1
    assert r == M

    lb = np.zeros((S, N), dtype=dtype)
    ub = np.zeros((S, N), dtype=dtype)
    tot = float(load.max(axis=0).sum())
    for t in range(T):
        for i in range(nG):
            ub[:, vg(t, i)] = gmax[i]
        for b in range(nB):
            lb[:, vth(t, b)] = -np.pi if b else 0.0
            ub[:, vth(t, b)] = np.pi if b else 0.0   # slack bus pinned
            ub[:, vmp(t, b)] = tot
            ub[:, vmn(t, b)] = tot
        for l_ in range(nL):
            lb[:, vf(t, l_)] = -cap[l_]
            ub[:, vf(t, l_)] = cap[l_]

    c = np.zeros((S, N), dtype=dtype)
    qdiag = np.zeros((S, N), dtype=dtype)
    stage_cost_c = np.zeros((T, S, N), dtype=dtype)
    for t in range(T):
        for i in range(nG):
            c[:, vg(t, i)] = c1[i]
            qdiag[:, vg(t, i)] = 2.0 * c2[i]
            stage_cost_c[t, :, vg(t, i)] = c1[i]
        for b in range(nB):
            c[:, vmp(t, b)] = load_mismatch_cost
            c[:, vmn(t, b)] = load_mismatch_cost
            stage_cost_c[t, :, vmp(t, b)] = load_mismatch_cost
            stage_cost_c[t, :, vmn(t, b)] = load_mismatch_cost

    # nonants: dispatch for stages 1..T-1, stage-major (the leaf stage
    # is pure recourse), matching the reference's per-node dispatch
    nonant_idx = np.array(
        [vg(t, i) for t in range(T - 1) for i in range(nG)], np.int32)
    stage_of = tuple(t + 1 for t in range(T - 1) for _ in range(nG))
    node_of = np.stack([
        tree.node_of_slots(s, stage_of) for s in range(S)
    ]).astype(np.int32)

    var_names = tuple(
        f"{nm}[{t+1},{k}]"
        for t in range(T)
        for nm, n in (("g", nG), ("th", nB), ("f", nL), ("mp", nB),
                      ("mn", nB))
        for k in range(n))
    treeinfo = TreeInfo(
        node_of=node_of,
        prob=np.array([tree.scen_probability(s) for s in range(S)],
                      dtype=dtype),
        num_nodes=tree.num_nodes,
        stage_of=stage_of,
        nonant_names=tuple(var_names[i] for i in nonant_idx),
        scen_names=tuple(f"Scenario{s+1}" for s in range(S)),
    )
    return ScenarioBatch(
        c=c, qdiag=qdiag,
        A=A, row_lo=row_lo, row_hi=row_hi, lb=lb, ub=ub,
        obj_const=np.zeros((S,), dtype=dtype),
        nonant_idx=nonant_idx,
        integer_mask=np.zeros((S, N), dtype=bool),
        tree=treeinfo, stage_cost_c=stage_cost_c, var_names=var_names)


MULTISTAGE = True


def scenario_names_creator(num_scens, start=0):
    start = start or 0
    return [f"Scenario{i+1}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    cfg.add_branching_factors()
    cfg.add_to_config("n_bus", description="buses", domain=int,
                      default=5)
    cfg.add_to_config("n_line", description="lines", domain=int,
                      default=6)
    cfg.add_to_config("n_gen", description="generators", domain=int,
                      default=3)
    cfg.add_to_config("case", description="network case (ieee14 or "
                      "empty for the synthetic grid)", domain=str,
                      default="")
    cfg.add_to_config("line_cap", description="uniform thermal limit "
                      "(MW) for case networks", domain=float,
                      default=160.0)


def kw_creator(options):
    from ..utils.config import parse_branching_factors
    return {"branching_factors": parse_branching_factors(
        options.get("branching_factors", (2, 2))),
        "n_bus": options.get("n_bus", 5),
        "n_line": options.get("n_line", 6),
        "n_gen": options.get("n_gen", 3),
        "case": options.get("case") or None,
        "line_cap": options.get("line_cap", 160.0)}


def scenario_denouement(rank, scenario_name, result):
    pass


# ====================================================================
# AC fidelity: Jabr SOC relaxation (VERDICT r4 missing item 5).
#
# The reference's acopf3 is AC via egret with a convex_relaxation mode
# (examples/acopf3/ccopf_multistage.py); the DC model above is its
# first-order cut.  This section is the LP/QP-kernel-shaped step to AC:
# the Jabr second-order-cone relaxation in lifted variables
#
#     u_i  = v_i^2,   cc_l = v_i v_j cos(th_i - th_j),
#     ss_l = v_i v_j sin(th_i - th_j)          (line l: i -> j)
#
# in which the FULL AC branch-flow equations are LINEAR:
#
#     P_ij = g(u_i - cc) - b ss      Q_ij = -b(u_i - cc) - g ss
#     P_ji = g(u_j - cc) + b ss      Q_ji = -b(u_j - cc) + g ss
#
# (series admittance y = g + jb = 1/(r + jx); shunt charging and taps
# ignored).  The one nonlinearity is the rotated cone
#
#     cc^2 + ss^2 <= u_i * u_j,
#
# enforced by OUTER-APPROXIMATION: supporting-hyperplane cuts written
# into a fixed-capacity row buffer (the opt/lshaped.py pattern — rows
# activate in place, shapes never change, nothing recompiles).  Each
# refine round solves the current LP/QP relaxation with the same
# batched PDHG kernel as every other family, measures cone violation,
# and linearizes at the incumbent.  All boxes stay finite, so dual
# objectives remain valid outer bounds at any iterate.
#
# Everything is per-unit on the 100 MVA system base; cost coefficients
# are scaled by 100 (and 1e4 for the quadratic) so objectives stay in
# $/h, directly comparable with the DC model above.
# ====================================================================

# IEEE 14-bus AC data (same public matpower/PGLib case14 the DC section
# embeds): series resistance per branch (same order as _IEEE14_LINES),
# reactive loads (MVAr), generator reactive limits (MVAr), voltage
# band.  Branches with r=0 are the case's transformers.
_IEEE14_R = [0.01938, 0.05403, 0.04699, 0.05811, 0.05695, 0.06701,
             0.01335, 0.0, 0.0, 0.0, 0.09498, 0.12291, 0.06615,
             0.0, 0.0, 0.03181, 0.12711, 0.08205, 0.22092, 0.17093]
_IEEE14_QLOAD = [0.0, 12.7, 19.0, -3.9, 1.6, 7.5, 0.0, 0.0, 16.6,
                 5.8, 1.8, 1.6, 5.8, 5.0]
_IEEE14_QMIN = [0.0, -40.0, 0.0, -6.0, -6.0]
_IEEE14_QMAX = [10.0, 50.0, 40.0, 24.0, 24.0]
_IEEE14_VMIN, _IEEE14_VMAX = 0.94, 1.06


def _grid_soc(n_bus, n_line, n_gen, seed):
    """Seeded synthetic AC grid in per-unit (the p.u.-sane analog of
    `_grid` — that generator's MW-per-radian susceptances don't map to
    a physical AC case): ring + chords, x in [0.05, 0.2] p.u.,
    r = 0.3 x (lossy), loads 0.2-0.4 p.u., thermal caps sized so the
    nominal dispatch is feasible without shed."""
    rng = np.random.RandomState(seed)
    n_line = min(n_line, n_bus * (n_bus - 1) // 2)
    lines = [(b, (b + 1) % n_bus) for b in range(n_bus)]
    while len(lines) < n_line:
        a, b = rng.randint(0, n_bus, 2)
        if a != b and (a, b) not in lines and (b, a) not in lines:
            lines.append((a, b))
    lines = lines[:n_line]
    x = 0.05 + 0.15 * rng.rand(len(lines))
    r = 0.3 * x
    cap = 0.8 + 0.4 * rng.rand(len(lines))
    gen_bus = rng.choice(n_bus, size=n_gen, replace=False)
    gmax = 0.8 + 0.4 * rng.rand(n_gen)
    qmin = -0.3 * np.ones(n_gen)
    qmax = 0.5 * np.ones(n_gen)
    c1 = 10.0 + 10.0 * rng.rand(n_gen)
    c2 = 0.05 + 0.1 * rng.rand(n_gen)
    pload = 0.2 + 0.2 * rng.rand(n_bus)
    qload = 0.3 * pload
    return (lines, r, x, cap, gen_bus, gmax, qmin, qmax, c1, c2,
            pload, qload)


def build_soc_batch(branching_factors=(2, 2), case=None, n_bus=5,
                    n_line=6, n_gen=3, ramp=None,
                    load_mismatch_cost=1000.0, seed=3301,
                    repair=False, line_cap=160.0, soc_cut_slots=6,
                    dtype=np.float64) -> ScenarioBatch:
    """Jabr SOC relaxation over the same outage tree as `build_batch`.

    Per stage t the layout is
        [pg nG | qg nG | u nB | cc nL | ss nL |
         P nL | Pr nL | Q nL | Qr nL | mp nB | mn nB | rp nB | rn nB]
    (P/Pr = active power entering the line at its from/to bus; Q/Qr
    reactive; mp/mn active-mismatch slacks, rp/rn reactive — slacks
    keep every instance structurally feasible, the reference's
    load_mismatch_cost recourse).

    soc_cut_slots: cone-cut buffer capacity per (stage, line).  Cut
    rows start inactive (all-zero, free bounds) and are activated in
    place by `add_soc_cuts`; shapes never change across refine rounds.

    model_meta carries the cone index tables (soc_*) consumed by
    soc_violation / add_soc_cuts / soc_refine."""
    tree = MultistageTree(list(branching_factors))
    T = tree.n_stages
    S = tree.num_scens
    if case == "ieee14":
        lines = [(a, b) for a, b, _ in _IEEE14_LINES]
        r_pu = np.array(_IEEE14_R)
        x_pu = np.array([x for _, _, x in _IEEE14_LINES])
        cap = np.full(len(lines), float(line_cap) / 100.0)   # p.u.
        gen_bus = np.array(_IEEE14_GEN_BUS)
        gmax = np.array(_IEEE14_GMAX) / 100.0
        qmin = np.array(_IEEE14_QMIN) / 100.0
        qmax = np.array(_IEEE14_QMAX) / 100.0
        c1 = np.array(_IEEE14_C1)
        c2 = np.array(_IEEE14_C2)
        pload = np.array(_IEEE14_LOAD) / 100.0
        qload = np.array(_IEEE14_QLOAD) / 100.0
        vmin, vmax = _IEEE14_VMIN, _IEEE14_VMAX
        n_bus = len(pload)
    elif case is not None:
        raise ValueError(f"unknown case {case!r} (None or 'ieee14')")
    else:
        (lines, r_pu, x_pu, cap, gen_bus, gmax, qmin, qmax, c1, c2,
         pload, qload) = _grid_soc(n_bus, n_line, n_gen, seed)
        vmin, vmax = 0.94, 1.06
    nL, nG, nB = len(lines), len(gen_bus), n_bus
    if ramp is None:
        ramp_arr = gmax / 3.0
    else:
        ramp_arr = np.broadcast_to(np.asarray(ramp, float) / 100.0
                                   if case == "ieee14"
                                   else np.asarray(ramp, float), (nG,))
    # series admittance y = 1/(r+jx) = g + jb
    z2 = r_pu * r_pu + x_pu * x_pu
    g_l = r_pu / z2
    b_l = -x_pu / z2

    alive = np.ones((S, T, nL))
    for s in range(S):
        digits = tree.scen_digits(s)
        out = set()
        for t in range(1, T):
            d = digits[t - 1] % (nL + 1)
            if d > 0:
                out.add(d - 1)
            if repair and len(out) > 1:
                out.pop()
            for l_ in out:
                alive[s, t, l_] = 0.0

    per = 2 * nG + 5 * nB + 6 * nL
    N = T * per

    def vpg(t, i):
        return t * per + i

    def vqg(t, i):
        return t * per + nG + i

    def vu(t, b):
        return t * per + 2 * nG + b

    def vcc(t, l_):
        return t * per + 2 * nG + nB + l_

    def vss(t, l_):
        return t * per + 2 * nG + nB + nL + l_

    def vP(t, l_):
        return t * per + 2 * nG + nB + 2 * nL + l_

    def vPr(t, l_):
        return t * per + 2 * nG + nB + 3 * nL + l_

    def vQ(t, l_):
        return t * per + 2 * nG + nB + 4 * nL + l_

    def vQr(t, l_):
        return t * per + 2 * nG + nB + 5 * nL + l_

    def vmp(t, b):
        return t * per + 2 * nG + nB + 6 * nL + b

    def vmn(t, b):
        return t * per + 2 * nG + 2 * nB + 6 * nL + b

    def vrp(t, b):
        return t * per + 2 * nG + 3 * nB + 6 * nL + b

    def vrn(t, b):
        return t * per + 2 * nG + 4 * nB + 6 * nL + b

    pload_t = np.stack([pload * (1.0 + 0.1 * t) for t in range(T)])
    qload_t = np.stack([qload * (1.0 + 0.1 * t) for t in range(T)])

    n_cut = soc_cut_slots * T * nL
    M = T * (4 * nL + 2 * nB) + (T - 1) * nG + n_cut
    A = np.zeros((S, M, N), dtype=dtype)
    row_lo = np.full((S, M), -INF, dtype=dtype)
    row_hi = np.full((S, M), INF, dtype=dtype)
    r = 0
    for t in range(T):          # branch-flow definitions (4 per line)
        for l_, (a, b) in enumerate(lines):
            al = alive[:, t, l_]
            # P - alive*(g u_a - g cc - b ss) = 0
            A[:, r, vP(t, l_)] = 1.0
            A[:, r, vu(t, a)] = -al * g_l[l_]
            A[:, r, vcc(t, l_)] = al * g_l[l_]
            A[:, r, vss(t, l_)] = al * b_l[l_]
            row_lo[:, r] = row_hi[:, r] = 0.0
            r += 1
            # Q - alive*(-b u_a + b cc - g ss) = 0
            A[:, r, vQ(t, l_)] = 1.0
            A[:, r, vu(t, a)] = al * b_l[l_]
            A[:, r, vcc(t, l_)] = -al * b_l[l_]
            A[:, r, vss(t, l_)] = al * g_l[l_]
            row_lo[:, r] = row_hi[:, r] = 0.0
            r += 1
            # Pr - alive*(g u_b - g cc + b ss) = 0
            A[:, r, vPr(t, l_)] = 1.0
            A[:, r, vu(t, b)] = -al * g_l[l_]
            A[:, r, vcc(t, l_)] = al * g_l[l_]
            A[:, r, vss(t, l_)] = -al * b_l[l_]
            row_lo[:, r] = row_hi[:, r] = 0.0
            r += 1
            # Qr - alive*(-b u_b + b cc + g ss) = 0
            A[:, r, vQr(t, l_)] = 1.0
            A[:, r, vu(t, b)] = al * b_l[l_]
            A[:, r, vcc(t, l_)] = -al * b_l[l_]
            A[:, r, vss(t, l_)] = -al * g_l[l_]
            row_lo[:, r] = row_hi[:, r] = 0.0
            r += 1
    for t in range(T):          # bus balances (P then Q per bus)
        for b in range(nB):
            for i, gb in enumerate(gen_bus):
                if gb == b:
                    A[:, r, vpg(t, i)] = 1.0
                    A[:, r + 1, vqg(t, i)] = 1.0
            for l_, (xx, yy) in enumerate(lines):
                if xx == b:
                    A[:, r, vP(t, l_)] = -1.0
                    A[:, r + 1, vQ(t, l_)] = -1.0
                elif yy == b:
                    A[:, r, vPr(t, l_)] = -1.0
                    A[:, r + 1, vQr(t, l_)] = -1.0
            A[:, r, vmp(t, b)] = 1.0
            A[:, r, vmn(t, b)] = -1.0
            row_lo[:, r] = row_hi[:, r] = pload_t[t, b]
            A[:, r + 1, vrp(t, b)] = 1.0
            A[:, r + 1, vrn(t, b)] = -1.0
            row_lo[:, r + 1] = row_hi[:, r + 1] = qload_t[t, b]
            r += 2
    for t in range(1, T):       # ramping on active dispatch
        for i in range(nG):
            A[:, r, vpg(t, i)] = 1.0
            A[:, r, vpg(t - 1, i)] = -1.0
            row_lo[:, r] = -ramp_arr[i]
            row_hi[:, r] = ramp_arr[i]
            r += 1
    cut_base = r
    assert r + n_cut == M       # remaining rows: inactive cut buffer

    lb = np.zeros((S, N), dtype=dtype)
    ub = np.zeros((S, N), dtype=dtype)
    totp = float(pload_t.max(axis=0).sum()) + float(np.sum(gmax))
    totq = float(np.abs(qload_t).max(axis=0).sum()) \
        + float(np.abs(qmax).sum()) + float(np.abs(qmin).sum())
    for t in range(T):
        for i in range(nG):
            ub[:, vpg(t, i)] = gmax[i]
            lb[:, vqg(t, i)] = qmin[i]
            ub[:, vqg(t, i)] = qmax[i]
        for b in range(nB):
            lb[:, vu(t, b)] = vmin * vmin
            ub[:, vu(t, b)] = vmax * vmax
            ub[:, vmp(t, b)] = totp
            ub[:, vmn(t, b)] = totp
            ub[:, vrp(t, b)] = totq
            ub[:, vrn(t, b)] = totq
        for l_ in range(nL):
            al = alive[:, t, l_]
            # dead line: flows AND lifted products pinned to zero
            lb[:, vcc(t, l_)] = 0.0
            ub[:, vcc(t, l_)] = al * vmax * vmax
            lb[:, vss(t, l_)] = -al * vmax * vmax
            ub[:, vss(t, l_)] = al * vmax * vmax
            for vv in (vP, vPr, vQ, vQr):
                lb[:, vv(t, l_)] = -al * cap[l_]
                ub[:, vv(t, l_)] = al * cap[l_]

    # $/h costs: pg is p.u. -> c1[$/MWh]*100*pg; quadratic 2*c2*1e4
    c = np.zeros((S, N), dtype=dtype)
    qdiag = np.zeros((S, N), dtype=dtype)
    stage_cost_c = np.zeros((T, S, N), dtype=dtype)
    shed_cost = load_mismatch_cost * 100.0
    for t in range(T):
        for i in range(nG):
            c[:, vpg(t, i)] = c1[i] * 100.0
            qdiag[:, vpg(t, i)] = 2.0 * c2[i] * 1e4
            stage_cost_c[t, :, vpg(t, i)] = c1[i] * 100.0
        for b in range(nB):
            for vv in (vmp, vmn, vrp, vrn):
                c[:, vv(t, b)] = shed_cost
                stage_cost_c[t, :, vv(t, b)] = shed_cost

    nonant_idx = np.array(
        [vpg(t, i) for t in range(T - 1) for i in range(nG)], np.int32)
    stage_of = tuple(t + 1 for t in range(T - 1) for _ in range(nG))
    node_of = np.stack([
        tree.node_of_slots(s, stage_of) for s in range(S)
    ]).astype(np.int32)

    var_names = tuple(
        f"{nm}[{t+1},{k}]"
        for t in range(T)
        for nm, n in (("pg", nG), ("qg", nG), ("u", nB), ("cc", nL),
                      ("ss", nL), ("P", nL), ("Pr", nL), ("Q", nL),
                      ("Qr", nL), ("mp", nB), ("mn", nB), ("rp", nB),
                      ("rn", nB))
        for k in range(n))
    treeinfo = TreeInfo(
        node_of=node_of,
        prob=np.array([tree.scen_probability(s) for s in range(S)],
                      dtype=dtype),
        num_nodes=tree.num_nodes,
        stage_of=stage_of,
        nonant_names=tuple(var_names[i] for i in nonant_idx),
        scen_names=tuple(f"Scenario{s+1}" for s in range(S)),
    )
    meta = {
        "soc_cc": np.array([[vcc(t, l_) for l_ in range(nL)]
                            for t in range(T)], np.int32),
        "soc_ss": np.array([[vss(t, l_) for l_ in range(nL)]
                            for t in range(T)], np.int32),
        "soc_ua": np.array([[vu(t, a) for a, _ in lines]
                            for t in range(T)], np.int32),
        "soc_ub": np.array([[vu(t, b) for _, b in lines]
                            for t in range(T)], np.int32),
        "soc_alive": alive.astype(dtype),
        "soc_cut_base": int(cut_base),
        "soc_cut_slots": int(soc_cut_slots),
    }
    return ScenarioBatch(
        c=c, qdiag=qdiag,
        A=A, row_lo=row_lo, row_hi=row_hi, lb=lb, ub=ub,
        obj_const=np.zeros((S,), dtype=dtype),
        nonant_idx=nonant_idx,
        integer_mask=np.zeros((S, N), dtype=bool),
        tree=treeinfo, stage_cost_c=stage_cost_c,
        var_names=var_names, model_meta=meta)


def soc_violation(batch, x):
    """Cone violation cc^2 + ss^2 - u_a*u_b per (scenario, stage, line)
    for a (S, N) primal point, masked to live lines -> (S, T, nL)."""
    m = batch.model_meta
    x = np.asarray(x)[:batch.num_scens]    # drop mesh padding rows
    cc = x[:, np.asarray(m["soc_cc"])]
    ss = x[:, np.asarray(m["soc_ss"])]
    ua = x[:, np.asarray(m["soc_ua"])]
    ub_ = x[:, np.asarray(m["soc_ub"])]
    return np.asarray(m["soc_alive"]) * (cc * cc + ss * ss - ua * ub_)


def add_soc_cuts(batch, x, round_idx, tol=1e-7):
    """Activate one supporting-hyperplane cut per violated
    (scenario, stage, line) cone at the incumbent `x`.

    Rotated cone cc^2+ss^2 <= ua*ub == ||(2cc, 2ss, ua-ub)|| <= ua+ub;
    at a violating point p = (2c, 2s, ua-ub) with rho = ||p||, the
    supporting hyperplane is (p/rho).(2cc, 2ss, ua-ub) - ua - ub <= 0.
    Round k writes slot k mod soc_cut_slots of each (stage, line) —
    the oldest cut is recycled once the buffer wraps (bounded memory,
    static shapes; the opt/lshaped.py buffer discipline).

    Returns (new_batch, max_violation, n_cuts_added)."""
    import dataclasses as _dc

    m = batch.model_meta
    S = batch.num_scens
    T, nL = np.asarray(m["soc_cc"]).shape
    slots = int(m["soc_cut_slots"])
    base = int(m["soc_cut_base"])
    viol = soc_violation(batch, x)
    A = np.array(batch.A)
    row_lo = np.array(batch.row_lo)
    row_hi = np.array(batch.row_hi)
    x = np.asarray(x)
    n_added = 0
    k = round_idx % slots
    cc_i = np.asarray(m["soc_cc"])
    ss_i = np.asarray(m["soc_ss"])
    ua_i = np.asarray(m["soc_ua"])
    ub_i = np.asarray(m["soc_ub"])
    for s in range(S):
        for t in range(T):
            for l_ in range(nL):
                if viol[s, t, l_] <= tol:
                    continue
                ic, is_, ia, ib = (cc_i[t, l_], ss_i[t, l_],
                                   ua_i[t, l_], ub_i[t, l_])
                p = np.array([2 * x[s, ic], 2 * x[s, is_],
                              x[s, ia] - x[s, ib]])
                rho = float(np.linalg.norm(p))
                if rho < 1e-12:
                    continue
                rr = base + k * T * nL + t * nL + l_
                A[s, rr, :] = 0.0
                A[s, rr, ic] = 2 * p[0] / rho
                A[s, rr, is_] = 2 * p[1] / rho
                A[s, rr, ia] = p[2] / rho - 1.0
                A[s, rr, ib] = -p[2] / rho - 1.0
                row_lo[s, rr] = -INF
                row_hi[s, rr] = 0.0
                n_added += 1
    nb = _dc.replace(batch, A=A, row_lo=row_lo, row_hi=row_hi)
    return nb, float(viol.max(initial=0.0)), n_added


def soc_refine(batch, opts=None, rounds=8, tol=1e-5, solve=None):
    """Outer-approximation loop: solve the current relaxation, cut the
    violated cones, repeat.  `solve(batch) -> (S, N) x` defaults to the
    consensus-mode ExtensiveForm solve (the same batched kernel PH
    uses); pass a custom callable to refine around PH/xhat incumbents
    instead.  Returns (batch, history) where history rows are
    (round, objective, max_violation, n_cuts)."""
    from ..opt.ef import ExtensiveForm

    opts = dict(opts or {})
    opts.setdefault("pdhg_eps", 1e-6)
    opts.setdefault("pdhg_max_iters", 60000)
    warm = {"x": None, "y": None}

    def _ef_solve(b):
        ef = ExtensiveForm(dict(opts), list(b.tree.scen_names), batch=b)
        # warm-start from the previous round: a new cut only nudges
        # the optimum, so the previous iterates are a near-solution
        # (the persistent-solver analog, reference spopt.py:877).
        # certify=False: a supporting hyperplane of the cone is a
        # VALID cut wherever it is generated — driving intermediate
        # rounds to the KKT floor buys nothing (the caller certifies
        # its own final solve)
        ef.solve_extensive_form(certify=False,
                                x0=warm["x"], y0=warm["y"])
        warm["x"], warm["y"] = ef._result.x, ef._result.y
        # EF pads the batch to a device multiple (mesh.shard_batch);
        # cut bookkeeping runs on the REAL scenarios only
        return (np.asarray(ef._result.x)[:b.num_scens],
                float(ef.get_objective_value()))

    history = []
    for rd in range(rounds):
        if solve is None:
            x, obj = _ef_solve(batch)
        else:
            out = solve(batch)
            x, obj = (out if isinstance(out, tuple)
                      else (out, float("nan")))
        batch, mv, n = add_soc_cuts(batch, x, rd)
        history.append((rd, obj, mv, n))
        if mv <= tol:
            break
    return batch, history

"""ACOPF3 — multistage optimal power flow with random line outages
(reference: examples/acopf3/ccopf_multistage.py + ACtree.py, which
builds chance-constrained AC-OPF instances over an outage scenario
tree via egret/matpower and per-stage repair processes).

TPU-native analog: the **DC** approximation (the standard convex
relaxation of the reference's `convex_relaxation=True` mode) over the
same kind of outage tree, lowered directly to batched arrays — no
external power-systems stack.  Per scenario and stage t:

    g[t, i]      generator dispatch            (nonant for t < T)
    th[t, b]     bus voltage angle (slack bus pinned to 0)
    f[t, l]      line flow
    mp/mn[t, b]  load-mismatch slacks (cost `load_mismatch_cost`,
                 the reference's default 1000, ccopf_multistage.py:77)

Rows:
    f[t, l] - alive[t, l] * B_l (th_from - th_to) == 0   (DC flow; an
        OUTAGE sets alive=0, forcing the flow to zero)
    sum_in f - sum_out f + gen_at_bus + mp - mn == load[t, b]
    -ramp <= g[t, i] - g[t-1, i] <= ramp                 (ramping)
Boxes: |f| <= cap, |th| <= pi, 0 <= g <= gmax, 0 <= m <= total load —
all finite, so PDHG dual objectives are valid bounds at any iterate
(spopt.valid_Ebound).

Generator cost is c1*g + c2*g^2 via the batch's diagonal quadratic
term — this model family exercises the QP path of the kernel.

Outage process: at each non-root tree node, the node's branch digit d
selects line d-1 to fail for that stage (digit 0 = no new outage);
outages persist down the tree (no repair — the reference's FixNever;
its FixGaussian repair corresponds to clearing alive bits, hookable
via `repair`).  The grid is a seeded ring-plus-chords synthetic case.
"""

from __future__ import annotations

import numpy as np

from ..ir import ScenarioBatch, TreeInfo
from ..scenario_tree import MultistageTree

INF = float("inf")


# IEEE 14-bus test case — standard public benchmark data (the
# matpower/PGLib `case14`): bus loads (MW), branch endpoints and
# series reactances (p.u.), generator buses, limits (MW), and
# polynomial costs.  This is the kind of real network the reference
# feeds egret (examples/acopf3/ccopf_multistage.py builds instances
# from matpower case files); embedding the published case data mirrors
# how sizes/sslp embed SIZES/SIPLIB instance data.  Branch thermal
# limits: case14 publishes none (rateA=0 = unlimited); we use a
# uniform finite `line_cap` (default 160 MW — non-binding in the
# nominal dispatch, binding under outages) because the kernel's
# bound-validity rule wants all-finite boxes.
_IEEE14_LOAD = [0.0, 21.7, 94.2, 47.8, 7.6, 11.2, 0.0, 0.0, 29.5,
                9.0, 3.5, 6.1, 13.5, 14.9]
_IEEE14_LINES = [
    (0, 1, 0.05917), (0, 4, 0.22304), (1, 2, 0.19797),
    (1, 3, 0.17632), (1, 4, 0.17388), (2, 3, 0.17103),
    (3, 4, 0.04211), (3, 6, 0.20912), (3, 8, 0.55618),
    (4, 5, 0.25202), (5, 10, 0.19890), (5, 11, 0.25581),
    (5, 12, 0.13027), (6, 7, 0.17615), (6, 8, 0.11001),
    (8, 9, 0.08450), (8, 13, 0.27038), (9, 10, 0.19207),
    (11, 12, 0.19988), (12, 13, 0.34802)]
_IEEE14_GEN_BUS = [0, 1, 2, 5, 7]
_IEEE14_GMAX = [332.4, 140.0, 100.0, 100.0, 100.0]
_IEEE14_C1 = [20.0, 20.0, 40.0, 40.0, 40.0]
_IEEE14_C2 = [0.0430292599, 0.25, 0.01, 0.01, 0.01]


def _grid_ieee14(line_cap=160.0):
    lines = [(a, b) for a, b, _ in _IEEE14_LINES]
    # reactances are per-unit on the 100 MVA system base; loads/flows
    # here are MW, so B[MW/rad] = 100 / x_pu
    susceptance = np.array([100.0 / x for _, _, x in _IEEE14_LINES])
    cap = np.full(len(lines), float(line_cap))
    gen_bus = np.array(_IEEE14_GEN_BUS)
    return (lines, susceptance, cap, gen_bus,
            np.array(_IEEE14_GMAX), np.array(_IEEE14_C1),
            np.array(_IEEE14_C2), np.array(_IEEE14_LOAD))


def _grid(n_bus, n_line, n_gen, seed):
    rng = np.random.RandomState(seed)
    # ring + random chords; at most C(n_bus, 2) distinct lines exist,
    # so cap the request or the chord loop would never terminate
    n_line = min(n_line, n_bus * (n_bus - 1) // 2)
    lines = [(b, (b + 1) % n_bus) for b in range(n_bus)]
    while len(lines) < n_line:
        a, b = rng.randint(0, n_bus, 2)
        if a != b and (a, b) not in lines and (b, a) not in lines:
            lines.append((a, b))
    lines = lines[:n_line]
    susceptance = 5.0 + 10.0 * rng.rand(len(lines))
    cap = 60.0 + 40.0 * rng.rand(len(lines))
    gen_bus = rng.choice(n_bus, size=n_gen, replace=False)
    gmax = 80.0 + 40.0 * rng.rand(n_gen)
    c1 = 10.0 + 10.0 * rng.rand(n_gen)
    c2 = 0.05 + 0.1 * rng.rand(n_gen)
    base_load = 20.0 + 20.0 * rng.rand(n_bus)
    return (lines, susceptance, cap, gen_bus, gmax, c1, c2, base_load)


def build_batch(branching_factors=(2, 2), n_bus=5, n_line=6, n_gen=3,
                ramp=None, load_mismatch_cost=1000.0, seed=3301,
                repair=False, case=None, line_cap=160.0,
                dtype=np.float64) -> ScenarioBatch:
    """case=None: seeded synthetic ring-plus-chords grid (n_bus /
    n_line / n_gen sized).  case="ieee14": the embedded IEEE 14-bus
    benchmark network (n_bus/n_line/n_gen ignored; `line_cap` sets the
    uniform thermal limit).  ramp=None resolves per case: 40 MW on the
    synthetic grid, a third of each unit's Pmax on ieee14."""
    tree = MultistageTree(list(branching_factors))
    T = tree.n_stages
    S = tree.num_scens
    if case == "ieee14":
        (lines, B, cap, gen_bus, gmax, c1, c2, base_load) = \
            _grid_ieee14(line_cap)
        n_bus, n_gen = len(base_load), len(gen_bus)
        if ramp is None:
            ramp = gmax / 3.0
    elif case is not None:
        raise ValueError(f"unknown case {case!r} (None or 'ieee14')")
    else:
        (lines, B, cap, gen_bus, gmax, c1, c2, base_load) = _grid(
            n_bus, n_line, n_gen, seed)
        if ramp is None:
            ramp = 40.0
    nL, nG, nB = len(lines), n_gen, n_bus
    ramp_arr = np.broadcast_to(np.asarray(ramp, float), (nG,))

    # outage mask per scenario per stage: branch digit d at stage t>=2
    # fails line d-1 (0 = none); persists unless repair
    alive = np.ones((S, T, nL))
    for s in range(S):
        digits = tree.scen_digits(s)
        out = set()
        for t in range(1, T):
            d = digits[t - 1] % (nL + 1)
            if d > 0:
                out.add(d - 1)
            if repair and len(out) > 1:
                out.pop()
            for l_ in out:
                alive[s, t, l_] = 0.0

    # per-stage layout: [g (nG) | th (nB) | f (nL) | mp (nB) | mn (nB)]
    per = nG + nB + nL + 2 * nB
    N = T * per

    def vg(t, i):
        return t * per + i

    def vth(t, b):
        return t * per + nG + b

    def vf(t, l_):
        return t * per + nG + nB + l_

    def vmp(t, b):
        return t * per + nG + nB + nL + b

    def vmn(t, b):
        return t * per + nG + nB + nL + nB + b

    # loads grow slightly by stage
    load = np.stack([base_load * (1.0 + 0.1 * t) for t in range(T)])

    M = T * nL + T * nB + (T - 1) * nG
    A = np.zeros((S, M, N), dtype=dtype)
    row_lo = np.full((S, M), -INF, dtype=dtype)
    row_hi = np.full((S, M), INF, dtype=dtype)
    r = 0
    for t in range(T):                 # DC flow definition
        for l_, (a, b) in enumerate(lines):
            A[:, r, vf(t, l_)] = 1.0
            A[:, r, vth(t, a)] = -alive[:, t, l_] * B[l_]
            A[:, r, vth(t, b)] = alive[:, t, l_] * B[l_]
            row_lo[:, r] = row_hi[:, r] = 0.0
            r += 1
    for t in range(T):                 # bus balance
        for b in range(nB):
            for l_, (x, y) in enumerate(lines):
                if y == b:
                    A[:, r, vf(t, l_)] = 1.0
                elif x == b:
                    A[:, r, vf(t, l_)] = -1.0
            for i, gb in enumerate(gen_bus):
                if gb == b:
                    A[:, r, vg(t, i)] = 1.0
            A[:, r, vmp(t, b)] = 1.0
            A[:, r, vmn(t, b)] = -1.0
            row_lo[:, r] = row_hi[:, r] = load[t, b]
            r += 1
    for t in range(1, T):              # ramping
        for i in range(nG):
            A[:, r, vg(t, i)] = 1.0
            A[:, r, vg(t - 1, i)] = -1.0
            row_lo[:, r] = -ramp_arr[i]
            row_hi[:, r] = ramp_arr[i]
            r += 1
    assert r == M

    lb = np.zeros((S, N), dtype=dtype)
    ub = np.zeros((S, N), dtype=dtype)
    tot = float(load.max(axis=0).sum())
    for t in range(T):
        for i in range(nG):
            ub[:, vg(t, i)] = gmax[i]
        for b in range(nB):
            lb[:, vth(t, b)] = -np.pi if b else 0.0
            ub[:, vth(t, b)] = np.pi if b else 0.0   # slack bus pinned
            ub[:, vmp(t, b)] = tot
            ub[:, vmn(t, b)] = tot
        for l_ in range(nL):
            lb[:, vf(t, l_)] = -cap[l_]
            ub[:, vf(t, l_)] = cap[l_]

    c = np.zeros((S, N), dtype=dtype)
    qdiag = np.zeros((S, N), dtype=dtype)
    stage_cost_c = np.zeros((T, S, N), dtype=dtype)
    for t in range(T):
        for i in range(nG):
            c[:, vg(t, i)] = c1[i]
            qdiag[:, vg(t, i)] = 2.0 * c2[i]
            stage_cost_c[t, :, vg(t, i)] = c1[i]
        for b in range(nB):
            c[:, vmp(t, b)] = load_mismatch_cost
            c[:, vmn(t, b)] = load_mismatch_cost
            stage_cost_c[t, :, vmp(t, b)] = load_mismatch_cost
            stage_cost_c[t, :, vmn(t, b)] = load_mismatch_cost

    # nonants: dispatch for stages 1..T-1, stage-major (the leaf stage
    # is pure recourse), matching the reference's per-node dispatch
    nonant_idx = np.array(
        [vg(t, i) for t in range(T - 1) for i in range(nG)], np.int32)
    stage_of = tuple(t + 1 for t in range(T - 1) for _ in range(nG))
    node_of = np.stack([
        tree.node_of_slots(s, stage_of) for s in range(S)
    ]).astype(np.int32)

    var_names = tuple(
        f"{nm}[{t+1},{k}]"
        for t in range(T)
        for nm, n in (("g", nG), ("th", nB), ("f", nL), ("mp", nB),
                      ("mn", nB))
        for k in range(n))
    treeinfo = TreeInfo(
        node_of=node_of,
        prob=np.array([tree.scen_probability(s) for s in range(S)],
                      dtype=dtype),
        num_nodes=tree.num_nodes,
        stage_of=stage_of,
        nonant_names=tuple(var_names[i] for i in nonant_idx),
        scen_names=tuple(f"Scenario{s+1}" for s in range(S)),
    )
    return ScenarioBatch(
        c=c, qdiag=qdiag,
        A=A, row_lo=row_lo, row_hi=row_hi, lb=lb, ub=ub,
        obj_const=np.zeros((S,), dtype=dtype),
        nonant_idx=nonant_idx,
        integer_mask=np.zeros((S, N), dtype=bool),
        tree=treeinfo, stage_cost_c=stage_cost_c, var_names=var_names)


MULTISTAGE = True


def scenario_names_creator(num_scens, start=0):
    start = start or 0
    return [f"Scenario{i+1}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    cfg.add_branching_factors()
    cfg.add_to_config("n_bus", description="buses", domain=int,
                      default=5)
    cfg.add_to_config("n_line", description="lines", domain=int,
                      default=6)
    cfg.add_to_config("n_gen", description="generators", domain=int,
                      default=3)
    cfg.add_to_config("case", description="network case (ieee14 or "
                      "empty for the synthetic grid)", domain=str,
                      default="")
    cfg.add_to_config("line_cap", description="uniform thermal limit "
                      "(MW) for case networks", domain=float,
                      default=160.0)


def kw_creator(options):
    from ..utils.config import parse_branching_factors
    return {"branching_factors": parse_branching_factors(
        options.get("branching_factors", (2, 2))),
        "n_bus": options.get("n_bus", 5),
        "n_line": options.get("n_line", 6),
        "n_gen": options.get("n_gen", 3),
        "case": options.get("case") or None,
        "line_cap": options.get("line_cap", 160.0)}


def scenario_denouement(rank, scenario_name, result):
    pass

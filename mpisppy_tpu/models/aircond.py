"""AIRCOND — multistage production/inventory model (parameter parity
with the reference's aircond, mpisppy/tests/examples/aircond.py:15-34
`parms` table — the CI-interval and proper-bundle workhorse).

T stages (T = len(branching_factors) + 1).  Per stage t: regular
production p_t in [0, Capacity] at RegularProdCost, overtime o_t >= 0
at OvertimeProdCost, and inventory split into its positive and
negative parts (reference aircond.py:146-151 doleInventory):
Ipos_t >= 0 at InventoryCost (LastInventoryCost < 0 at the terminal
stage — end-of-horizon salvage), Ineg_t >= 0 (backlog) at
NegInventoryCost plus an optional QUADRATIC shortage penalty
QuadShortCoeff * Ineg^2 — expressed natively through the batch's
diagonal quadratic (`qdiag`), where the reference needs a QP solver.

    (Ipos_t - Ineg_t) = (Ipos_{t-1} - Ineg_{t-1}) + p_t + o_t - d_t
    (BeginInventory enters the t=1 balance)

start_ups=True adds a per-stage binary u_t with the big-M forcing row
p_t + o_t <= bigM * u_t and StartUpCost * u_t (reference
aircond.py:142-144; bigM = Capacity * max_T) — the integer variant.

Demand is the reference's per-NODE seeded random walk
(aircond.py:37-67 _demands_creator): d_1 = starting_d and
d_{t+1} = clip(d_t + Normal(mu_dev, sigma_dev), min_d, max_d), the
normal draw seeded by start_seed + node index so scenarios sharing a
tree node share the demand path — which is also what makes resampled
trees (confidence_intervals.sample_tree) reproducible from a seed.

Nonants per stage t < T: [p_t, o_t, Ipos_t, Ineg_t (, u_t)]
(stage-major, matching the reference's per-node nonant lists).
"""

from __future__ import annotations

import numpy as np

from ..ir import ScenarioBatch, TreeInfo
from ..scenario_tree import MultistageTree

INF = float("inf")

# reference aircond.py:17-34 `parms` defaults ("Do not edit")
PARMS = {
    "mu_dev": 0.0,
    "sigma_dev": 40.0,
    "start_ups": False,
    "StartUpCost": 300.0,
    "start_seed": 1134,
    "min_d": 0.0,
    "max_d": 400.0,
    "starting_d": 200.0,
    "BeginInventory": 200.0,
    "InventoryCost": 0.5,
    "LastInventoryCost": -0.8,
    "Capacity": 200.0,
    "RegularProdCost": 1.0,
    "OvertimeProdCost": 3.0,
    "NegInventoryCost": 5.0,
    "QuadShortCoeff": 0.0,
}
MAX_T = 25            # reference aircond.py:113 (bigM horizon bound)


def _node_demands(branching_factors, start_seed, mu_dev, sigma_dev,
                  min_d, max_d, starting_d):
    """(S, T) demand array from the per-node seeded random walk."""
    tree = MultistageTree(list(branching_factors))
    S = tree.num_scens
    T = len(branching_factors) + 1
    dem = np.zeros((S, T))
    dem[:, 0] = starting_d
    for s in range(S):
        digits = tree.scen_digits(s)
        path_idx = 0
        d = starting_d
        for t in range(1, T):
            path_idx = path_idx * branching_factors[t - 1] \
                + digits[t - 1]
            rng = np.random.RandomState(
                (start_seed + t * 9176 + path_idx) % (2**31))
            d = min(max_d, max(min_d, d + rng.normal(mu_dev, sigma_dev)))
            dem[s, t] = d
    return dem, tree


def build_batch(branching_factors=(3, 2), start_seed=None,
                dtype=np.float64, **params):
    kw = dict(PARMS)
    kw.update(params)
    if start_seed is not None:
        kw["start_seed"] = start_seed
    unknown = set(kw) - set(PARMS)
    if unknown:
        raise ValueError(f"unknown aircond parameter(s): {unknown}")
    start_ups = bool(kw["start_ups"])
    cap = float(kw["Capacity"])
    bigM = cap * MAX_T

    dem, tree = _node_demands(
        branching_factors, int(kw["start_seed"]), kw["mu_dev"],
        kw["sigma_dev"], kw["min_d"], kw["max_d"], kw["starting_d"])
    S = tree.num_scens
    T = len(branching_factors) + 1
    if T > MAX_T:
        raise RuntimeError(f"number of stages exceeds {MAX_T}")

    # layout: stage-major [p, o, Ipos, Ineg] blocks, then u_t columns
    N = 4 * T + (T if start_ups else 0)
    ip = lambda t: 4 * t
    io = lambda t: 4 * t + 1
    ii = lambda t: 4 * t + 2
    ib = lambda t: 4 * t + 3
    iu = lambda t: 4 * T + t

    # rows: T balance equalities (+ T start-up forcing rows)
    M = T + (T if start_ups else 0)
    A = np.zeros((S, M, N), dtype=dtype)
    row_lo = np.full((S, M), -INF, dtype=dtype)
    row_hi = np.full((S, M), INF, dtype=dtype)

    for t in range(T):
        # Ipos_t - Ineg_t - Ipos_{t-1} + Ineg_{t-1} - p_t - o_t = -d_t
        A[:, t, ii(t)] = 1.0
        A[:, t, ib(t)] = -1.0
        A[:, t, ip(t)] = -1.0
        A[:, t, io(t)] = -1.0
        if t > 0:
            A[:, t, ii(t - 1)] = -1.0
            A[:, t, ib(t - 1)] = 1.0
        rhs = -dem[:, t] + (kw["BeginInventory"] if t == 0 else 0.0)
        row_lo[:, t] = rhs
        row_hi[:, t] = rhs
    if start_ups:
        for t in range(T):
            r = T + t                       # p + o - bigM u <= 0
            A[:, r, ip(t)] = 1.0
            A[:, r, io(t)] = 1.0
            A[:, r, iu(t)] = -bigM
            row_hi[:, r] = 0.0

    lb = np.zeros((S, N), dtype=dtype)
    ub = np.full((S, N), INF, dtype=dtype)
    for t in range(T):
        ub[:, ip(t)] = cap
        ub[:, io(t)] = bigM               # reference box (0, bigM)
        ub[:, ii(t)] = bigM
        ub[:, ib(t)] = bigM
    if start_ups:
        ub[:, 4 * T:] = 1.0

    c = np.zeros((S, N), dtype=dtype)
    qdiag = np.zeros((S, N), dtype=dtype)
    stage_cost_c = np.zeros((T, S, N), dtype=dtype)
    for t in range(T):
        last = (t == T - 1)
        inv_cost = kw["LastInventoryCost"] if last else kw["InventoryCost"]
        c[:, ip(t)] = kw["RegularProdCost"]
        c[:, io(t)] = kw["OvertimeProdCost"]
        c[:, ii(t)] = inv_cost
        c[:, ib(t)] = kw["NegInventoryCost"]
        if kw["QuadShortCoeff"] > 0 and not last:
            # native diagonal QP: 0.5*qdiag*x^2, so qdiag = 2*coeff
            qdiag[:, ib(t)] = 2.0 * kw["QuadShortCoeff"]
        if start_ups:
            c[:, iu(t)] = kw["StartUpCost"]
        for j in (ip(t), io(t), ii(t), ib(t)):
            stage_cost_c[t, :, j] = c[:, j]
        if start_ups:
            stage_cost_c[t, :, iu(t)] = c[:, iu(t)]

    integer_mask = np.zeros((S, N), dtype=bool)
    if start_ups:
        integer_mask[:, 4 * T:] = True

    # nonants: stages 1..T-1, stage-major groups
    per_stage = (lambda t: (ip(t), io(t), ii(t), ib(t), iu(t))
                 if start_ups else (ip(t), io(t), ii(t), ib(t)))
    nonant_idx = np.array(
        [j for t in range(T - 1) for j in per_stage(t)], np.int32)
    width = 5 if start_ups else 4
    stage_of = tuple(t + 1 for t in range(T - 1)
                     for _ in range(width))
    node_of = np.stack([
        tree.node_of_slots(s, stage_of) for s in range(S)
    ]).astype(np.int32)

    var_names = tuple(
        f"{nm}[{t+1}]" for t in range(T)
        for nm in ("RegularProd", "OvertimeProd", "posInventory",
                   "negInventory")) + (tuple(
                       f"StartUp[{t+1}]" for t in range(T))
                       if start_ups else ())
    tree_info = TreeInfo(
        node_of=node_of,
        prob=np.array([tree.scen_probability(s) for s in range(S)],
                      dtype=dtype),
        num_nodes=tree.num_nodes,
        stage_of=stage_of,
        nonant_names=tuple(var_names[i] for i in nonant_idx),
        scen_names=tuple(f"Scenario{s+1}" for s in range(S)),
    )
    return ScenarioBatch(
        c=c, qdiag=qdiag,
        A=A, row_lo=row_lo, row_hi=row_hi, lb=lb, ub=ub,
        obj_const=np.zeros((S,), dtype=dtype),
        nonant_idx=nonant_idx,
        integer_mask=integer_mask,
        tree=tree_info, stage_cost_c=stage_cost_c, var_names=var_names)


def scenario_source(num_scens, cfg=None):
    """streaming.ScenarioSource for aircond.  The scenario universe is
    one coupled multistage tree — node demands are conditional on the
    ancestor path, so scenarios cannot be materialized independently
    from their global index.  Build the tree ONCE (sized by
    cfg["branching_factors"]; num_scens is ignored, tree-sized like
    every MULTISTAGE entry point) and serve gathered blocks out of the
    host-resident batch (streaming.BatchSource).  Note StreamingPH
    itself rejects multistage consensus; this source exists for the
    protocol satellite (block materialization, xhat evaluation, EF
    sub-solves over leaf blocks)."""
    cfg = dict(cfg or {})
    from ..utils.config import parse_branching_factors
    bf = tuple(parse_branching_factors(
        cfg.get("branching_factors", "3,2")))
    kw = {k: cfg[k] for k in PARMS if k in cfg}
    if "start_seed" in cfg:
        kw["start_seed"] = cfg["start_seed"]
    batch = build_batch(branching_factors=bf, **kw)
    from ..streaming import BatchSource
    return BatchSource(batch, name="aircond")


def scenario_names_creator(num_scens, start=0):
    return [f"Scenario{i+1}" for i in range(start, start + num_scens)]


MULTISTAGE = True


def xhat_generator_aircond(scenario_names, branching_factors=None,
                           solver_options=None, **params):
    """Sequential-sampling candidate generator (reference
    aircond.py:465 xhat_generator_aircond): solve the EF of the
    sampled tree the given scenario names imply and return the root
    (stage-1) decisions."""
    from ..opt.ef import ExtensiveForm
    assert branching_factors is not None, \
        "branching factors must be supplied to xhat_generator_aircond"
    prod = int(np.prod(branching_factors))
    if len(scenario_names) != prod:
        raise ValueError(
            f"{len(scenario_names)} scenario names for a "
            f"{prod}-leaf tree {tuple(branching_factors)}")
    # the NAMES select the sample (reference aircond.py:47-55 derives
    # node seeds from the scenario numbers): advance the demand-walk
    # seed by the first scenario's number so successive name blocks
    # draw different trees
    first = scenario_names[0]
    scennum = int("".join(ch for ch in first if ch.isdigit()) or 0)
    params = dict(params)
    params["start_seed"] = (params.get("start_seed", PARMS["start_seed"])
                            + scennum)
    b = build_batch(branching_factors=tuple(branching_factors),
                    **params)
    opts = dict(solver_options or {})
    opts.setdefault("pdhg_eps", 1e-6)
    ef = ExtensiveForm(opts, list(b.tree.scen_names), batch=b)
    ef.solve_extensive_form()
    xhat = np.asarray(ef.get_root_solution())
    stage1 = np.asarray(b.tree.stage_of) == 1
    return xhat[stage1[:xhat.size]] if xhat.size > stage1.sum() \
        else xhat


def inparser_adder(cfg):
    """Reference aircond.py:387-419 flag set (same names)."""
    cfg.add_branching_factors()
    cfg["branching_factors"] = "3,2"
    for name, default in PARMS.items():
        if name == "start_ups":
            cfg.add_to_config("start_ups",
                              description="per-stage start-up binaries",
                              domain=bool, default=False)
        else:
            dom = int if name == "start_seed" else float
            cfg.add_to_config(name, description=f"aircond {name}",
                              domain=dom, default=default)


def kw_creator(options):
    from ..utils.config import parse_branching_factors
    bf = options.get("branching_factors", "3,2")
    kw = {"branching_factors": tuple(parse_branching_factors(bf))}
    for name in PARMS:
        if options.get(name) is not None:
            kw[name] = options[name]
    return kw

"""AIRCOND — multistage production/inventory model (structure parity
with the reference's aircond, mpisppy/tests/examples/aircond.py, the
CI-interval and proper-bundle workhorse).

T stages (T = len(branching_factors) + 1).  Per stage t: regular
production p_t in [0, cap] at unit cost cp, overtime o_t >= 0 at cost
co > cp, inventory I_t >= 0 at holding cost ch, backlog b_t >= 0 at
penalty cb.  Demand d_t is stochastic from stage 2 on (branch-indexed
around a base seasonal profile):

    I_t - b_t = I_{t-1} - b_{t-1} + p_t + o_t - d_t      (balance)
    min E[ sum_t cp*p_t + co*o_t + ch*I_t + cb*b_t ]

Nonants per stage t < T: [p_t, o_t, I_t, b_t] (stage-major layout,
matching the reference's per-node nonant lists).

Demand decoding: stage-1 demand is the base; the stage-(t+1) branch
digit k (0-based over bf) maps to base * (0.6 + 0.8 * k / (bf - 1)),
so the middle child reproduces the base profile.
"""

from __future__ import annotations

import numpy as np

from ..ir import ScenarioBatch, TreeInfo
from ..scenario_tree import MultistageTree

INF = float("inf")

_CAP = 200.0
_CP = 1.0
_CO = 3.0
_CH = 0.5
_CB = 5.0
_BASE_DEMAND = 180.0
_START_INV = 20.0


def stage_demand(t, digit, bf):
    """Demand at stage t (1-based) given the branch digit taken to
    reach it (digit=None for stage 1)."""
    base = _BASE_DEMAND * (1.0 + 0.1 * np.sin(1.0 + t))
    if digit is None or bf <= 1:
        return base
    return base * (0.6 + 0.8 * digit / (bf - 1))


def build_batch(branching_factors=(3, 2), start_seed=0,
                dtype=np.float64):
    tree = MultistageTree(list(branching_factors))
    S = tree.num_scens
    T = len(branching_factors) + 1
    # layout: stage-major [p_t, o_t, I_t, b_t] for t = 1..T
    N = 4 * T
    M = T
    ip = lambda t: 4 * t
    io = lambda t: 4 * t + 1
    ii = lambda t: 4 * t + 2
    ib = lambda t: 4 * t + 3

    A = np.zeros((S, M, N), dtype=dtype)
    row_lo = np.full((S, M), -INF, dtype=dtype)
    row_hi = np.full((S, M), INF, dtype=dtype)

    dem = np.zeros((S, T))
    for s in range(S):
        digits = tree.scen_digits(s)
        dem[s, 0] = stage_demand(1, None, 1)
        for t in range(1, T):
            d = stage_demand(t + 1, digits[t - 1],
                             branching_factors[t - 1])
            # per-NODE seeded perturbation (same for all scenarios
            # through the node — resampling trees for CI estimation,
            # sample_tree.SampleSubtree, needs start_seed to matter)
            path_idx = 0
            for j in range(t):
                path_idx = path_idx * branching_factors[j] + digits[j]
            rng = np.random.RandomState(
                (start_seed * 1000003 + t * 9176 + path_idx) % (2**31))
            dem[s, t] = d * (0.9 + 0.2 * rng.rand())

    for t in range(T):
        # I_t - b_t - I_{t-1} + b_{t-1} - p_t - o_t = -d_t (+start inv)
        A[:, t, ii(t)] = 1.0
        A[:, t, ib(t)] = -1.0
        A[:, t, ip(t)] = -1.0
        A[:, t, io(t)] = -1.0
        if t > 0:
            A[:, t, ii(t - 1)] = -1.0
            A[:, t, ib(t - 1)] = 1.0
        rhs = -dem[:, t] + (_START_INV if t == 0 else 0.0)
        row_lo[:, t] = rhs
        row_hi[:, t] = rhs

    lb = np.zeros((S, N), dtype=dtype)
    ub = np.full((S, N), INF, dtype=dtype)
    for t in range(T):
        ub[:, ip(t)] = _CAP

    c = np.zeros((S, N), dtype=dtype)
    stage_cost_c = np.zeros((T, S, N), dtype=dtype)
    for t in range(T):
        c[:, ip(t)] = _CP
        c[:, io(t)] = _CO
        c[:, ii(t)] = _CH
        c[:, ib(t)] = _CB
        stage_cost_c[t, :, ip(t)] = _CP
        stage_cost_c[t, :, io(t)] = _CO
        stage_cost_c[t, :, ii(t)] = _CH
        stage_cost_c[t, :, ib(t)] = _CB

    # nonants: stages 1..T-1, stage-major
    nonant_idx = np.array(
        [j for t in range(T - 1) for j in (ip(t), io(t), ii(t), ib(t))],
        np.int32)
    stage_of = tuple(t + 1 for t in range(T - 1) for _ in range(4))
    node_of = np.stack([
        tree.node_of_slots(s, stage_of) for s in range(S)
    ]).astype(np.int32)

    var_names = tuple(
        f"{nm}[{t+1}]" for t in range(T)
        for nm in ("RegularProd", "OvertimeProd", "Inventory", "Backlog"))
    # var_names above is stage-major per t in order p,o,I,b
    tree_info = TreeInfo(
        node_of=node_of,
        prob=np.array([tree.scen_probability(s) for s in range(S)],
                      dtype=dtype),
        num_nodes=tree.num_nodes,
        stage_of=stage_of,
        nonant_names=tuple(var_names[i] for i in nonant_idx),
        scen_names=tuple(f"Scenario{s+1}" for s in range(S)),
    )
    return ScenarioBatch(
        c=c, qdiag=np.zeros((S, N), dtype=dtype),
        A=A, row_lo=row_lo, row_hi=row_hi, lb=lb, ub=ub,
        obj_const=np.zeros((S,), dtype=dtype),
        nonant_idx=nonant_idx,
        integer_mask=np.zeros((S, N), dtype=bool),
        tree=tree_info, stage_cost_c=stage_cost_c, var_names=var_names)


def scenario_names_creator(num_scens, start=0):
    return [f"Scenario{i+1}" for i in range(start, start + num_scens)]


MULTISTAGE = True


def inparser_adder(cfg):
    cfg.add_branching_factors()
    # keep the CLI default aligned with build_batch's (3, 2)
    cfg["branching_factors"] = "3,2"


def kw_creator(options):
    from ..utils.config import parse_branching_factors
    bf = options.get("branching_factors", "3,2")
    return {"branching_factors": tuple(parse_branching_factors(bf))}

"""aircondB — the pickle-bundle variant of aircond (reference:
mpisppy/tests/examples/aircondB.py — "PICKLE BUNDLE VERSION": proper
bundles that consume entire second-stage subtrees are built once,
dill-pickled to disk, and later runs unpickle them instead of
rebuilding; aircondB.py:106-172).

TPU-native: a proper bundle is one row of utils.bundles.bundle_batch's
multistage bundling (in-bundle chain rows for stage>=2 nodes make each
bundle a two-stage subproblem — the same construction as the
reference's bundle EF), and pickling is the array-native npz
round-trip (utils/pickle_bundle.py).  Per-bundle files follow the
reference's "Bundle_first_last" naming (aircondB.py:146,171)."""

from __future__ import annotations

import os

import numpy as np

from ..utils import pickle_bundle
from ..utils.bundles import bundle_batch
from . import aircond

MULTISTAGE = False   # bundled: two-stage by construction


def bundle_names(num_scens, scenarios_per_bundle, start=0):
    """Reference naming: Bundle_first_last over ORIGINAL scenario
    numbers (aircondB.py:146)."""
    m = int(scenarios_per_bundle)
    return [f"Bundle_{i}_{i + m - 1}"
            for i in range(start, start + num_scens, m)]


def build_batch(branching_factors=(3, 2), scenarios_per_bundle=None,
                pickle_bundles_dir=None, unpickle_bundles_dir=None,
                start_seed=None, dtype=np.float64, **params):
    """Bundled aircond batch.  scenarios_per_bundle defaults to one
    full stage-2 subtree (prod of the non-root branching factors — the
    smallest proper bundle).  pickle_bundles_dir: also write each
    bundle as its own npz.  unpickle_bundles_dir: skip the model build
    entirely and load the bundle files (the reference's split
    write-then-solve workflow, aircondB.py:145-147)."""
    bf = tuple(branching_factors)
    m = int(scenarios_per_bundle or int(np.prod(bf[1:])) or 1)
    S = int(np.prod(bf))
    if unpickle_bundles_dir is not None:
        from ..ir import stack_scenarios
        names = bundle_names(S, m)
        rows = [pickle_bundle.dill_unpickle(
            os.path.join(unpickle_bundles_dir, nm)) for nm in names]
        return stack_scenarios(rows, scen_names=[r.tree.scen_names[0]
                                                 for r in rows])
    base = aircond.build_batch(bf, start_seed=start_seed, dtype=dtype,
                               **params)
    bb = bundle_batch(base, m)
    if pickle_bundles_dir is not None:
        os.makedirs(pickle_bundles_dir, exist_ok=True)
        for i, nm in enumerate(bundle_names(S, m)):
            pickle_bundle.dill_pickle(
                _slice_bundle(bb, i),
                os.path.join(pickle_bundles_dir, nm))
    return bb


def _slice_bundle(bb, i):
    """One bundle row as an S=1 ScenarioBatch (the per-file unit of the
    reference's pickled-bundle directory)."""
    import dataclasses

    from ..ir import TreeInfo
    sl = slice(i, i + 1)
    tree = bb.tree
    return dataclasses.replace(
        bb,
        c=bb.c[sl], qdiag=bb.qdiag[sl],
        A=bb.A if bb.A.shape[0] == 1 else bb.A[sl],
        row_lo=bb.row_lo[sl], row_hi=bb.row_hi[sl],
        lb=bb.lb[sl], ub=bb.ub[sl], obj_const=bb.obj_const[sl],
        integer_mask=bb.integer_mask[sl],
        stage_cost_c=None,
        tree=TreeInfo(
            node_of=np.asarray(tree.node_of)[sl],
            prob=np.asarray(tree.prob)[sl],
            num_nodes=1,
            stage_of=tree.stage_of,
            nonant_names=tree.nonant_names,
            scen_names=(tree.scen_names[i],)))


def scenario_names_creator(num_scens, start=0, bundles_per_rank=None,
                           scenarios_per_bundle=None):
    """Names are BUNDLE names (the reference's aircondB
    scenario_names_creator yields bundle names too)."""
    m = int(scenarios_per_bundle or 1)
    return bundle_names(num_scens, m, start=start)


def inparser_adder(cfg):
    aircond.inparser_adder(cfg)
    pickle_bundle.pickle_bundle_parser(cfg)


def kw_creator(options):
    kw = aircond.kw_creator(options)
    for key in ("pickle_bundles_dir", "unpickle_bundles_dir",
                "scenarios_per_bundle"):
        if options.get(key) is not None:
            kw[key] = options[key]
    return kw


def scenario_denouement(rank, scenario_name, scenario):
    pass

"""APL1P — classic 2-stage power-expansion planning fixture (structure
parity with the reference's apl1p test model,
mpisppy/tests/examples/apl1p.py; Infanger's APL1P).

First stage: install capacity w_g >= 0 of G generator types
(investment cost inv_g per MW), with a minimum total capacity.
Second stage: generator availability alpha_g^s and demands D_d^s
realize; dispatch x_gd serves demand level d from generator g at
operating cost op_gd; unserved demand penalized.

    min  sum_g inv_g w_g + E[ sum_gd op_gd x_gd + pen * sum_d un_d ]
    s.t. sum_g w_g >= Wmin
         sum_d x_gd <= alpha_g^s * w_g          (availability)
         sum_g x_gd + un_d >= D_d^s             (demand levels)
Nonants: w (continuous).

Scenarios enumerate an independent discrete grid: each generator's
availability in {0.9, 1.0} and a demand scale in {0.8, 1.0, 1.2}
(scenario index decodes mixed-radix), probabilities uniform.
"""

from __future__ import annotations

import numpy as np

from ..ir import ScenarioBatch, TreeInfo

INF = float("inf")

_G = 2          # generator types
_D = 3          # demand levels
_INV = np.array([4.0, 2.5])
_OP = np.array([[4.3, 2.0, 0.5],
                [8.7, 4.0, 1.0]])
_DEMAND = np.array([900.0, 1000.0, 750.0])
_WMIN = 1000.0
_PEN = 10.0
_AVAIL_CHOICES = np.array([0.9, 1.0])
_SCALE_CHOICES = np.array([0.8, 1.0, 1.2])


def max_num_scens():
    return len(_AVAIL_CHOICES) ** _G * len(_SCALE_CHOICES)


def scenario_outcome(scennum):
    """Decode mixed-radix scenario index -> (alpha (G,), demand (D,))."""
    na = len(_AVAIL_CHOICES)
    digits = []
    k = scennum
    for _ in range(_G):
        digits.append(k % na)
        k //= na
    scale = _SCALE_CHOICES[k % len(_SCALE_CHOICES)]
    alpha = _AVAIL_CHOICES[np.array(digits)]
    return alpha, _DEMAND * scale


def build_batch(num_scens=None, dtype=np.float64):
    S = max_num_scens() if num_scens is None else num_scens
    if S > max_num_scens():
        raise ValueError(f"apl1p has at most {max_num_scens()} scenarios")
    G, D = _G, _D
    # layout: [w (G) | x (G*D) | un (D)]
    iw, ix, iu = 0, G, G + G * D
    N = G + G * D + D
    M = 1 + G + D
    A = np.zeros((S, M, N), dtype=dtype)
    row_lo = np.full((S, M), -INF, dtype=dtype)
    row_hi = np.full((S, M), INF, dtype=dtype)

    alphas = np.zeros((S, G))
    dems = np.zeros((S, D))
    for s in range(S):
        alphas[s], dems[s] = scenario_outcome(s)

    A[:, 0, iw:iw + G] = 1.0                 # min total capacity
    row_lo[:, 0] = _WMIN
    for g in range(G):                       # availability
        r = 1 + g
        A[:, r, ix + g * D: ix + (g + 1) * D] = 1.0
        A[:, r, iw + g] = -alphas[:, g]
        row_hi[:, r] = 0.0
    for d in range(D):                       # demand levels
        r = 1 + G + d
        for g in range(G):
            A[:, r, ix + g * D + d] = 1.0
        A[:, r, iu + d] = 1.0
        row_lo[:, r] = dems[:, d]

    lb = np.zeros((S, N), dtype=dtype)
    ub = np.full((S, N), INF, dtype=dtype)

    c = np.zeros((S, N), dtype=dtype)
    c[:, iw:iw + G] = _INV
    c[:, ix:iu] = _OP.reshape(-1)
    c[:, iu:] = _PEN

    stage_cost_c = np.zeros((2, S, N), dtype=dtype)
    stage_cost_c[0, :, iw:iw + G] = _INV
    stage_cost_c[1] = c.copy()
    stage_cost_c[1, :, iw:iw + G] = 0.0

    nonant_idx = np.arange(G, dtype=np.int32)
    var_names = (
        tuple(f"CapExp[{g}]" for g in range(G))
        + tuple(f"Gen[{g},{d}]" for g in range(G) for d in range(D))
        + tuple(f"Unserved[{d}]" for d in range(D)))
    tree = TreeInfo(
        node_of=np.zeros((S, G), np.int32),
        prob=np.full((S,), 1.0 / S, dtype=dtype),
        num_nodes=1,
        stage_of=(1,) * G,
        nonant_names=var_names[:G],
        scen_names=tuple(f"Scenario{i+1}" for i in range(S)),
    )
    return ScenarioBatch(
        c=c, qdiag=np.zeros((S, N), dtype=dtype),
        A=A, row_lo=row_lo, row_hi=row_hi, lb=lb, ub=ub,
        obj_const=np.zeros((S,), dtype=dtype),
        nonant_idx=nonant_idx,
        integer_mask=np.zeros((S, N), dtype=bool),
        tree=tree, stage_cost_c=stage_cost_c, var_names=var_names)


def scenario_names_creator(num_scens, start=0):
    return [f"Scenario{i+1}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    cfg.num_scens_required()


def kw_creator(options):
    return {}

"""BATTERY — 2-stage battery sizing + operation under price/solar
uncertainty (structure parity with the reference's battery example,
examples/battery/battery.py).

First stage: battery energy capacity B (continuous, cost cB per kWh).
Second stage, per scenario over H hours: charge ch_h, discharge dis_h,
grid purchase g_h >= 0, state of charge soc_h in [0, B]:

    soc_h = soc_{h-1} + eta*ch_h - dis_h        (soc_0 = 0)
    g_h + solar^s_h + dis_h - ch_h >= load_h    (power balance)
    ch_h <= rmax, dis_h <= rmax                 (rate limits)
    min cB*B + E[ sum_h price^s_h * g_h ]
Nonants: B.
"""

from __future__ import annotations

import numpy as np

from ..ir import ScenarioBatch, TreeInfo

INF = float("inf")

_ETA = 0.92
_RMAX = 20.0
_CB = 8.0


def _profiles(scennum, H, seed=77):
    rng = np.random.RandomState(seed + scennum)
    hours = np.arange(H)
    solar = np.maximum(
        0.0, 30.0 * np.sin(np.pi * (hours + 0.5) / H)) * (
        0.6 + 0.8 * rng.rand())
    price = 5.0 + 10.0 * rng.rand(H) + 10.0 * (hours >= H * 2 // 3)
    load = 25.0 + 10.0 * np.cos(np.pi * hours / H) * rng.rand()
    return solar, price, load


def build_batch(num_scens, H=12, seed=77, dtype=np.float64):
    S = num_scens
    # layout: [B | ch (H) | dis (H) | g (H) | soc (H)]
    iB, ich, idis, ig, isoc = 0, 1, 1 + H, 1 + 2 * H, 1 + 3 * H
    N = 1 + 4 * H
    M = 3 * H            # soc dynamics (H), balance (H), soc<=B (H)
    A = np.zeros((S, M, N), dtype=dtype)
    row_lo = np.full((S, M), -INF, dtype=dtype)
    row_hi = np.full((S, M), INF, dtype=dtype)

    solar = np.zeros((S, H))
    price = np.zeros((S, H))
    load = np.zeros((S, H))
    for s in range(S):
        solar[s], price[s], load[s] = _profiles(s, H, seed)

    for h in range(H):
        # soc_h - soc_{h-1} - eta*ch_h + dis_h = 0
        A[:, h, isoc + h] = 1.0
        if h > 0:
            A[:, h, isoc + h - 1] = -1.0
        A[:, h, ich + h] = -_ETA
        A[:, h, idis + h] = 1.0
        row_lo[:, h] = 0.0
        row_hi[:, h] = 0.0
        # g + dis - ch >= load - solar
        r = H + h
        A[:, r, ig + h] = 1.0
        A[:, r, idis + h] = 1.0
        A[:, r, ich + h] = -1.0
        row_lo[:, r] = load[:, h] - solar[:, h]
        # soc_h - B <= 0
        r2 = 2 * H + h
        A[:, r2, isoc + h] = 1.0
        A[:, r2, iB] = -1.0
        row_hi[:, r2] = 0.0

    lb = np.zeros((S, N), dtype=dtype)
    ub = np.full((S, N), INF, dtype=dtype)
    ub[:, ich:ich + H] = _RMAX
    ub[:, idis:idis + H] = _RMAX

    c = np.zeros((S, N), dtype=dtype)
    c[:, iB] = _CB
    c[:, ig:ig + H] = price

    stage_cost_c = np.zeros((2, S, N), dtype=dtype)
    stage_cost_c[0, :, iB] = _CB
    stage_cost_c[1, :, ig:ig + H] = price

    nonant_idx = np.array([iB], np.int32)
    var_names = (("B",)
                 + tuple(f"ch[{h}]" for h in range(H))
                 + tuple(f"dis[{h}]" for h in range(H))
                 + tuple(f"g[{h}]" for h in range(H))
                 + tuple(f"soc[{h}]" for h in range(H)))
    tree = TreeInfo(
        node_of=np.zeros((S, 1), np.int32),
        prob=np.full((S,), 1.0 / S, dtype=dtype),
        num_nodes=1,
        stage_of=(1,),
        nonant_names=("B",),
        scen_names=tuple(f"Scenario{i+1}" for i in range(S)),
    )
    return ScenarioBatch(
        c=c, qdiag=np.zeros((S, N), dtype=dtype),
        A=A, row_lo=row_lo, row_hi=row_hi, lb=lb, ub=ub,
        obj_const=np.zeros((S,), dtype=dtype),
        nonant_idx=nonant_idx,
        integer_mask=np.zeros((S, N), dtype=bool),
        tree=tree, stage_cost_c=stage_cost_c, var_names=var_names)


def scenario_names_creator(num_scens, start=0):
    return [f"Scenario{i+1}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    cfg.num_scens_required()
    cfg.add_to_config("battery_hours", description="operation horizon",
                      domain=int, default=12)


def kw_creator(options):
    return {"H": options.get("battery_hours", 12)}

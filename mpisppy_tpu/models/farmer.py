"""Farmer — the canonical scalable 2-stage stress model.

Same mathematical model and stochastic data as the reference
(mpisppy/tests/examples/farmer.py:93-232): a farmer allocates
`500 * crops_multiplier` acres among 3*crops_multiplier crops
(first stage), then after the random yield realizes, buys/sells to meet
cattle-feed requirements (second stage).  Scenario `scen{i}` uses base
yields for i%3 in {below, average, above}, plus a U[0,1) perturbation
from RandomState(i + seedoffset) when i >= 3 (matching the reference's
`farmerstream` seeding at farmer.py:60,159-165 so golden objective
values carry over).

Known golden value: the classic 3-scenario continuous farmer EF
objective is -108390 (Birge & Louveaux; asserted at 2 sig figs in the
reference test suite, mpisppy/tests/test_ef_ph.py).

Variable layout per scenario (N = 4 * ncrops):
    [0:ncrops)            DevotedAcreage      (nonant, stage 1)
    [ncrops:2*ncrops)     QuantitySubQuotaSold
    [2*ncrops:3*ncrops)   QuantitySuperQuotaSold
    [3*ncrops:4*ncrops)   QuantityPurchased

Rows (M = 2*ncrops + 1): cattle-feed requirement (>=), limit-sold (<=),
total acreage (<=).  The quota bound is a variable box bound (the
reference's EnforceQuotas range constraint, farmer.py:207-210).
"""

from __future__ import annotations

import numpy as np

from ..ir import ScenarioBatch, TreeInfo
from ..model import LinearModel

INF = float("inf")

_BASE_YIELD = {
    "below": np.array([2.0, 2.4, 16.0]),
    "average": np.array([2.5, 3.0, 20.0]),
    "above": np.array([3.0, 3.6, 24.0]),
}
_YIELD_BY_MOD3 = [_BASE_YIELD["below"], _BASE_YIELD["average"],
                  _BASE_YIELD["above"]]

_PLANTING_COST = np.array([150.0, 230.0, 260.0])
_SUB_PRICE = np.array([170.0, 150.0, 36.0])
_SUPER_PRICE = np.array([0.0, 0.0, 10.0])
_CATTLE_REQ = np.array([200.0, 240.0, 0.0])
_PURCHASE_PRICE = np.array([238.0, 210.0, 100000.0])
_QUOTA = np.array([100000.0, 100000.0, 6000.0])
_CROP_NAMES = ["WHEAT", "CORN", "SUGAR_BEETS"]


def scenario_yields(scennum, crops_multiplier=1, seedoffset=0):
    """Per-crop yields for scenario `scennum`, matching the reference's
    RNG protocol (farmer.py:60,159-165): base by scennum%3, plus one
    rand() per crop (CROPS iteration order WHEAT_i, CORN_i, BEETS_i
    interleaved per multiplier group) when scennum // 3 != 0."""
    base = np.tile(_YIELD_BY_MOD3[scennum % 3], crops_multiplier)
    if scennum // 3 != 0:
        rng = np.random.RandomState(scennum + seedoffset)
        base = base + rng.rand(3 * crops_multiplier)
    return base


def scenario_block(indices, crops_multiplier=1, use_integer=False,
                   seedoffset=0, sense=1, dtype=np.float64,
                   split="auto") -> ScenarioBatch:
    """Vectorized batch builder over an ARBITRARY index set: constructs
    exactly the scenarios named by `indices` (the host-side
    'scenario_creator loop' collapsed — reference spbase.py:255-273
    builds models one-by-one; here model build is a numpy broadcast).
    Scenario i's data depends only on its GLOBAL index (yields from
    RandomState(i + seedoffset)), so blocks are pure functions of their
    index set — the `streaming.GeneratorSource` contract.  Block
    probabilities are block-uniform (each block is a valid sampled
    batch on its own); `build_batch` is the contiguous full-universe
    special case.

    split: store A split-native (ir.SplitA — one shared (M, N) matrix
    plus the 2*nc per-scenario yield coefficients) instead of the dense
    (S, M, N) tensor.  "auto" switches when the dense tensor would
    exceed ~1 GB: the TRUE baseline-size instance (S=1000,
    crops_multiplier=1000 — reference
    paperruns/scripts/farmer/ef_1000_1000.out) is ~288 GB dense f32 and
    only exists split-native."""
    idx = np.asarray(indices, dtype=np.int64)
    nc = 3 * crops_multiplier
    N = 4 * nc
    M = 2 * nc + 1
    S = idx.size
    if split == "auto":
        split = S * M * N * np.dtype(dtype).itemsize > 1 << 30

    yields = np.stack([
        scenario_yields(int(i), crops_multiplier, seedoffset) for i in idx
    ]).astype(dtype)                                      # (S, nc)

    iac = np.arange(nc)
    isub = nc + iac
    isup = 2 * nc + iac
    ipur = 3 * nc + iac

    row_lo = np.full((S, M), -INF, dtype=dtype)
    row_hi = np.full((S, M), INF, dtype=dtype)
    r = np.arange(nc)
    r2 = nc + r
    # cattle feed: yield*x + purchased - sub - super >= req (rows 0..nc)
    row_lo[:, r] = np.tile(_CATTLE_REQ, crops_multiplier)
    # limit sold: sub + super - yield*x <= 0   (rows nc..2nc)
    row_hi[:, r2] = 0.0
    # total acreage  (last row)
    row_hi[:, -1] = 500.0 * crops_multiplier
    delta_rows = np.concatenate([r, r2]).astype(np.int32)
    delta_cols = np.concatenate([iac, iac]).astype(np.int32)
    if split:
        from ..ir import SplitA
        shared = np.zeros((M, N), dtype=dtype)
        shared[r, ipur] = 1.0
        shared[r, isub] = -1.0
        shared[r, isup] = -1.0
        shared[r2, isub] = 1.0
        shared[r2, isup] = 1.0
        shared[-1, iac] = 1.0
        # the yield slots (r x iac, r2 x iac) stay ZERO in shared; the
        # per-scenario values live in vals at (delta_rows, delta_cols)
        A = SplitA(shared=shared, rows=delta_rows, cols=delta_cols,
                   vals=np.concatenate([yields, -yields], axis=1))
    else:
        A = np.zeros((S, M, N), dtype=dtype)
        A[:, r, iac] = yields
        A[:, r, ipur] = 1.0
        A[:, r, isub] = -1.0
        A[:, r, isup] = -1.0
        A[:, r2, isub] = 1.0
        A[:, r2, isup] = 1.0
        A[:, r2, iac] = -yields
        A[:, -1, iac] = 1.0

    lb = np.zeros((S, N), dtype=dtype)
    ub = np.full((S, N), INF, dtype=dtype)
    total_acreage = 500.0 * crops_multiplier
    ub[:, iac] = total_acreage
    # Implied (presolve-style) finite bounds — provably inactive at some
    # optimum, so objective values are unchanged, and they make EVERY
    # variable box finite, which turns the PDHG dual objective into an
    # exact Lagrangian value for any dual iterate (spopt.Ebound validity
    # without certification):
    #  * sales: the limit-sold row gives sub+sup <= yield*x <= yield*total
    #  * purchases: sub+sup <= yield*x implies the feed row stays
    #    satisfied when purchases are lowered to the requirement, and
    #    purchase cost > 0, so an optimal purchase never exceeds req
    # The 2x margin keeps the boxes STRICTLY inactive (never degenerate
    # with the rows they were derived from), so dual solutions — and
    # everything built on them (cross-scenario cuts, reduced-cost
    # fixing) — are unchanged.
    sale_cap = 2.0 * yields * total_acreage                # (S, nc)
    ub[:, isub] = np.minimum(np.tile(_QUOTA, crops_multiplier), sale_cap)
    ub[:, isup] = sale_cap
    ub[:, ipur] = 2.0 * np.tile(_CATTLE_REQ + 1.0, crops_multiplier)

    c = np.zeros((S, N), dtype=dtype)
    c[:, iac] = np.tile(_PLANTING_COST, crops_multiplier)
    c[:, isub] = -np.tile(_SUB_PRICE, crops_multiplier)
    c[:, isup] = -np.tile(_SUPER_PRICE, crops_multiplier)
    c[:, ipur] = np.tile(_PURCHASE_PRICE, crops_multiplier)
    stage_cost_c = np.zeros((2, S, N), dtype=dtype)
    stage_cost_c[0][:, iac] = np.tile(_PLANTING_COST, crops_multiplier)
    stage_cost_c[1] = c.copy()
    stage_cost_c[1][:, iac] = 0.0
    if sense < 0:
        c = -c
        stage_cost_c = -stage_cost_c

    integer_mask = np.zeros((S, N), dtype=bool)
    if use_integer:
        integer_mask[:, iac] = True

    crop_names = [f"{nm}{g}" for g in range(crops_multiplier)
                  for nm in _CROP_NAMES]
    var_names = (
        tuple(f"DevotedAcreage[{n}]" for n in crop_names)
        + tuple(f"QuantitySubQuotaSold[{n}]" for n in crop_names)
        + tuple(f"QuantitySuperQuotaSold[{n}]" for n in crop_names)
        + tuple(f"QuantityPurchased[{n}]" for n in crop_names))

    tree = TreeInfo(
        node_of=np.zeros((S, nc), np.int32),
        prob=np.full((S,), 1.0 / S, dtype=dtype),
        num_nodes=1,
        stage_of=(1,) * nc,
        nonant_names=var_names[:nc],
        scen_names=tuple(f"scen{int(i)}" for i in idx),
    )
    # the ONLY scenario-varying matrix entries are the 2*nc yield
    # coefficients (feed rows r x iac, limit-sold rows r2 x iac);
    # declaring them (model_meta below) lets SPOpt build the ir.SplitA
    # fast path (shared matmul + nnz scatter instead of an (S, M, N)
    # batched GEMV) even when A is stored dense
    return ScenarioBatch(
        c=c, qdiag=np.zeros((S, N), dtype=dtype),
        A=A, row_lo=row_lo, row_hi=row_hi, lb=lb, ub=ub,
        obj_const=np.zeros((S,), dtype=dtype),
        nonant_idx=iac.astype(np.int32),
        integer_mask=integer_mask,
        tree=tree,
        stage_cost_c=stage_cost_c,
        var_names=var_names,
        model_meta={"A_delta_idx": (delta_rows, delta_cols)},
    )


def build_batch(num_scens, crops_multiplier=1, use_integer=False,
                seedoffset=0, sense=1, dtype=np.float64,
                split="auto") -> ScenarioBatch:
    """The full scenario universe [0, num_scens) — `scenario_block`
    over the contiguous index range (bit-identical to the historical
    builder: scenario data is a function of the global index only)."""
    return scenario_block(np.arange(num_scens),
                          crops_multiplier=crops_multiplier,
                          use_integer=use_integer, seedoffset=seedoffset,
                          sense=sense, dtype=dtype, split=split)


def scenario_source(num_scens, cfg=None):
    """streaming.ScenarioSource over the farmer universe — blocks are
    built split-native by default so the shared constraint matrix is
    never replicated per block (override with cfg["split"])."""
    cfg = dict(cfg or {})
    kw = {
        "crops_multiplier": int(cfg.get("crops_multiplier", 1)),
        "use_integer": bool(cfg.get(
            "use_integer", cfg.get("farmer_with_integers", False))),
        "seedoffset": int(cfg.get("start_seed", cfg.get("seedoffset", 0))),
        "sense": int(cfg.get("sense", 1)),
        "split": cfg.get("split", True),
    }
    from ..streaming import GeneratorSource
    return GeneratorSource(
        "farmer", int(num_scens),
        lambda idx: scenario_block(idx, **kw),
        name_fn=lambda i: f"scen{i}")


def export_corpus(path, num_scens, shard_width=64, cfg=None):
    """Persist the farmer scenario universe as a durable shard corpus
    (streaming/store.py): checksummed fixed-width shard files a
    `ShardSource` can stream back without this module's generator.
    Returns the corpus path."""
    from ..streaming import write_corpus
    return write_corpus(
        scenario_source(num_scens, cfg), path, shard_width,
        meta={"name_format": "scen{i}"})


def scenario_creator(scenario_name, use_integer=False, sense=1,
                     crops_multiplier=1, num_scens=None, seedoffset=0):
    """Single-scenario creator through the declarative LinearModel API —
    the exact analog of the reference's scenario_creator contract
    (farmer.py:25-91).  `build_batch` is the fast path; this exists for
    API parity and to exercise the modeling layer."""
    scennum = int("".join(ch for ch in scenario_name if ch.isdigit()) or 0)
    nc = 3 * crops_multiplier
    y = scenario_yields(scennum, crops_multiplier, seedoffset)
    m = LinearModel(sense=sense)
    total = 500.0 * crops_multiplier
    ac = m.add_vars("DevotedAcreage", nc, lb=0.0, ub=total,
                    integer=use_integer)
    # same implied finite bounds as build_batch (see there for the
    # optimality argument)
    sub = m.add_vars("QuantitySubQuotaSold", nc, lb=0.0,
                     ub=np.minimum(np.tile(_QUOTA, crops_multiplier),
                                   2.0 * y * total))
    sup = m.add_vars("QuantitySuperQuotaSold", nc, lb=0.0,
                     ub=2.0 * y * total)
    pur = m.add_vars("QuantityPurchased", nc, lb=0.0,
                     ub=2.0 * np.tile(_CATTLE_REQ + 1.0, crops_multiplier))
    req = np.tile(_CATTLE_REQ, crops_multiplier)
    for i in range(nc):
        m.add_constr({ac[i]: y[i], pur[i]: 1.0, sub[i]: -1.0,
                      sup[i]: -1.0}, lo=req[i])
    for i in range(nc):
        m.add_constr({sub[i]: 1.0, sup[i]: 1.0, ac[i]: -y[i]}, hi=0.0)
    m.add_constr({ac[i]: 1.0 for i in range(nc)}, hi=total)
    m.add_cost(1, {ac[i]: np.tile(_PLANTING_COST, crops_multiplier)[i]
                   for i in range(nc)})
    m.add_cost(2, {
        **{pur[i]: np.tile(_PURCHASE_PRICE, crops_multiplier)[i]
           for i in range(nc)},
        **{sub[i]: -np.tile(_SUB_PRICE, crops_multiplier)[i]
           for i in range(nc)},
        **{sup[i]: -np.tile(_SUPER_PRICE, crops_multiplier)[i]
           for i in range(nc)},
    })
    m.set_nonants([ac], stage=1)
    prob = 1.0 / num_scens if num_scens else 1.0
    return m.lower(prob=prob, name=scenario_name)


# ---- amalgamator-contract helpers (reference farmer.py:237-268) ----------

def scenario_names_creator(num_scens, start=None):
    start = start or 0
    return [f"scen{i}" for i in range(start, start + num_scens)]


def kw_creator(options):
    return {
        # CLI flag name is farmer_with_integers (inparser_adder);
        # programmatic callers may pass use_integer directly
        "use_integer": options.get(
            "use_integer", options.get("farmer_with_integers", False)),
        "crops_multiplier": options.get("crops_multiplier", 1),
        "num_scens": options.get("num_scens", None),
    }


def inparser_adder(cfg):
    cfg.num_scens_required()
    cfg.add_to_config("crops_multiplier",
                      description="number of crops is 3x this", domain=int,
                      default=1)
    cfg.add_to_config("farmer_with_integers",
                      description="integer acreage variant", domain=bool,
                      default=False)


def batch_creator(cfg_or_kwargs, num_scens=None):
    """Build the full ScenarioBatch from kwargs (fast vectorized path)."""
    kw = dict(cfg_or_kwargs)
    n = num_scens or kw.pop("num_scens", None)
    kw.pop("num_scens", None)
    return build_batch(n, **kw)


def scenario_denouement(rank, scenario_name, result):
    pass

"""GBD — the Ferguson & Dantzig (1956) aircraft-allocation problem
(reference: mpisppy/tests/examples/gbd/gbd.py, used by the sequential-
sampling tests following Bayraksan & Morton).

Allocate 4 aircraft types across 5 routes (first stage, nonant) before
route passenger demand realizes; recourse is pure simple-recourse
slack: excess demand loses revenue, excess capacity flies empty.

Per scenario (N = 34):
    x[a, r]  (20)  aircraft of type a on route r      (nonant)
             x[1,0], x[2,0], x[2,2] are structurally impossible
             (fixed to 0 via the box, reference gbd.py:34-36)
    sa[a]    (4)   idle aircraft of type a
    sp[r]    (5)   unserved demand (hundreds of passengers)
    sn[r]    (5)   over-capacity slack
Rows (9 equalities):
    sum_r x[a, r] + sa[a]              == fleet[a]
    sum_a p[a, r] x[a, r] + sp[r] - sn[r] == demand_s[r]
Objective: sum c[a, r] x[a, r] + sum lost[r] * sp[r].

Data: the published 1956 tables (capacities p, costs c, fleet) and the
demand distributions — either the ORIGINAL 1956 5-point distributions
or the EXTENDED distributions used by the reference's sequential-
sampling experiments (gbd_data/gbd_extended_data.json; embedded
below).  Scenario demands follow the reference's RNG protocol exactly
(gbd.py:18-21, :122-126): RandomState(scennum).rand(5), inverse-CDF
lookup via reversed cumulative probabilities — so sampled-problem
trajectories carry over.
"""

from __future__ import annotations

import numpy as np

from ..ir import ScenarioBatch, TreeInfo

INF = float("inf")

# ---- published 1956 tables (aircraft x route) ----------------------------
FLEET = np.array([10.0, 19.0, 25.0, 15.0])
# passengers (hundreds) hauled per month, aircraft type a on route r
P = np.array([
    [16.0, 15.0, 28.0, 23.0, 81.0],
    [0.0, 10.0, 14.0, 15.0, 57.0],
    [0.0, 5.0, 0.0, 7.0, 29.0],
    [9.0, 11.0, 22.0, 17.0, 55.0],
])
# operating cost (thousands) per month
C = np.array([
    [18.0, 21.0, 18.0, 16.0, 10.0],
    [0.0, 15.0, 16.0, 14.0, 9.0],
    [0.0, 10.0, 0.0, 9.0, 6.0],
    [17.0, 16.0, 17.0, 15.0, 10.0],
])
LOST_REVENUE = np.array([13.0, 13.0, 7.0, 7.0, 1.0])
# routes an aircraft type cannot fly (reference gbd.py:34-36)
FORBIDDEN = [(1, 0), (2, 0), (2, 2)]

# original 1956 demand distributions (gbd.py:100-110 comment block)
DEMANDS_1956 = ([20, 22, 25, 27, 30], [5, 15], [14, 16, 18, 20, 22],
                [1, 5, 8, 10, 34], [58, 60, 62])
PROBS_1956 = ([.2, .05, .35, .2, .2], [.3, .7], [.1, .2, .4, .2, .1],
              [.2, .2, .3, .2, .1], [.1, .8, .1])

# extended distributions (reference gbd_data/gbd_extended_data.json)
DEMANDS_EXT = (
    [175., 185., 195., 200., 210., 220., 250., 270., 280., 290., 300.,
     305., 310., 312., 314.],
    [40., 45., 50., 55., 134., 138., 142., 146., 150., 154., 158.,
     160., 162.],
    [138., 140., 156., 158., 160., 162., 164., 170., 175., 180., 185.,
     188., 200., 205., 210., 220., 222.],
    [5., 10., 30., 37., 50., 57., 80., 85., 100., 110., 300., 320.,
     340., 360., 380.],
    [570., 580., 590., 600., 602., 604., 606., 610., 612., 614., 616.,
     618., 620.])
PROBS_EXT = (
    [.04, .04, .04, .04, .04, .05, .35, .1, .05, .05, .04, .04, .04,
     .04, .04],
    [.05, .05, .05, .05, .1, .1, .1, .1, .1, .1, .1, .05, .05],
    [.05, .05, .02, .04, .1, .02, .02, .1, .1, .1, .1, .06, .06, .04,
     .04, .07, .03],
    [.1, .1, .05, .05, .05, .05, .15, .15, .1, .1, .02, .02, .02, .02,
     .02],
    [.03, .04, .03, .05, .05, .1, .1, .2, .1, .1, .1, .05, .05])


def scenario_demand(scennum, extended=True):
    """(5,) demand vector, matching the reference's sampling protocol
    (gbd.py:122-126): one rand() per route, inverse CDF on the
    reversed cumulative probabilities."""
    dmds = DEMANDS_EXT if extended else DEMANDS_1956
    prbs = PROBS_EXT if extended else PROBS_1956
    rng = np.random.RandomState(scennum)
    rd = rng.rand(5)
    out = np.zeros(5)
    for r in range(5):
        cum = np.flip(np.cumsum(np.flip(prbs[r])))
        j = np.searchsorted(np.flip(cum), rd[r])
        out[r] = dmds[r][len(cum) - 1 - j]
    return out


def build_batch(num_scens, extended=True, seed=0,
                dtype=np.float64) -> ScenarioBatch:
    S = num_scens
    A_, R_ = 4, 5
    ix = 0                      # x[a, r] row-major (a * R + r)
    isa = A_ * R_               # 20
    isp = isa + A_              # 24
    isn = isp + R_              # 29
    N = isn + R_                # 34
    M = A_ + R_                 # 9 equality rows

    dem = np.stack([scenario_demand(seed + s, extended)
                    for s in range(S)]).astype(dtype)   # (S, 5)

    A = np.zeros((S, M, N), dtype=dtype)
    row_lo = np.zeros((S, M), dtype=dtype)
    row_hi = np.zeros((S, M), dtype=dtype)
    for a in range(A_):                      # fleet equalities
        A[:, a, ix + a * R_: ix + (a + 1) * R_] = 1.0
        A[:, a, isa + a] = 1.0
        row_lo[:, a] = row_hi[:, a] = FLEET[a]
    for r in range(R_):                      # demand equalities
        m = A_ + r
        for a in range(A_):
            A[:, m, ix + a * R_ + r] = P[a, r]
        A[:, m, isp + r] = 1.0
        A[:, m, isn + r] = -1.0
        row_lo[:, m] = row_hi[:, m] = dem[:, r]

    lb = np.zeros((S, N), dtype=dtype)
    # implied finite boxes (Ebound validity without certificates):
    # x and the idle slack are fleet-bounded by their equality row;
    # sp <= demand; sn <= max capacity deliverable minus min demand
    ub = np.zeros((S, N), dtype=dtype)
    for a in range(A_):
        ub[:, ix + a * R_: ix + (a + 1) * R_] = FLEET[a]
        ub[:, isa + a] = FLEET[a]
    ub[:, isp:isp + R_] = dem
    cap_max = (P * FLEET[:, None]).sum(axis=0)          # (5,)
    ub[:, isn:isn + R_] = 2.0 * cap_max[None, :]
    for a, r in FORBIDDEN:
        ub[:, ix + a * R_ + r] = 0.0

    c = np.zeros((S, N), dtype=dtype)
    c[:, :isa] = C.reshape(-1)
    c[:, isp:isp + R_] = LOST_REVENUE

    stage_cost_c = np.zeros((2, S, N), dtype=dtype)
    stage_cost_c[0, :, :isa] = C.reshape(-1)
    stage_cost_c[1, :, isp:isp + R_] = LOST_REVENUE

    nonant_idx = np.arange(A_ * R_, dtype=np.int32)
    var_names = (
        tuple(f"x[{a},{r}]" for a in range(A_) for r in range(R_))
        + tuple(f"aircraftSlack[{a}]" for a in range(A_))
        + tuple(f"passengerSlack_pos[{r}]" for r in range(R_))
        + tuple(f"passengerSlack_neg[{r}]" for r in range(R_)))
    tree = TreeInfo(
        node_of=np.zeros((S, A_ * R_), np.int32),
        prob=np.full((S,), 1.0 / S, dtype=dtype),
        num_nodes=1,
        stage_of=(1,) * (A_ * R_),
        nonant_names=var_names[:A_ * R_],
        scen_names=tuple(f"scen{i}" for i in range(S)),
    )
    return ScenarioBatch(
        c=c, qdiag=np.zeros((S, N), dtype=dtype),
        A=A, row_lo=row_lo, row_hi=row_hi, lb=lb, ub=ub,
        obj_const=np.zeros((S,), dtype=dtype),
        nonant_idx=nonant_idx,
        integer_mask=np.zeros((S, N), dtype=bool),
        tree=tree, stage_cost_c=stage_cost_c, var_names=var_names)


def scenario_names_creator(num_scens, start=0):
    start = start or 0
    return [f"scen{i}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    cfg.num_scens_required()
    cfg.add_to_config("gbd_original_demands",
                      description="use the 1956 5-point distributions "
                      "instead of the extended ones", domain=bool,
                      default=False)


def kw_creator(options):
    return {"extended": not options.get("gbd_original_demands", False)}


def scenario_denouement(rank, scenario_name, result):
    pass

"""Hydro — the canonical 3-stage multistage test model.

Same mathematics and data as the reference's hydro ("elec3") model
(reference: mpisppy/tests/examples/hydro/hydro.py and its
PySP/scenariodata/Scen*.dat files): a hydro-thermal scheduling problem
over 3 periods.  Per period t: thermal generation Pgt[t] in [0,100],
hydro generation Pgh[t] in [0,100], unserved demand PDns[t] in
[0, D[t]], reservoir volume Vol[t] in [0,100]; terminal future-cost
slack sl >= 0.

    min  sum_t r[t] * (betaGt*Pgt[t] + betaGh*Pgh[t] + betaDns*PDns[t]) + sl
    s.t. Pgt[t] + Pgh[t] + PDns[t]        = D[t]            (demand)
         Vol[t] - Vol[t-1] + u[t]*Pgh[t] <= u[t]*A_s[t]     (conservation,
                                                             Vol[0] = V0)
         sl >= 4166.67 * (V0 - Vol[3])                      (future cost)

with discount r[t] = (1/1.1)^(duracion[t]/T).  Scenario s's only
stochastic data is the inflow A_s: A[1] = 50 for all; A[2] in
{10, 50, 90} chosen by the stage-2 branch; A[3] in {40, 50, 60} by the
stage-3 branch (read from the reference's Scen1..Scen9.dat).

Tree: branching factors [3, 3] by default, 9 scenarios; nonants are
[Pgt[t], Pgh[t], PDns[t], Vol[t]] at stage t for t = 1, 2 (reference
hydro.py MakeNodesforScen).

Reference golden values (2 sig figs, test_ef_ph.py Test_hydro):
PH trivial bound = 180, E[objective] at consensus = 190.
"""

from __future__ import annotations

import numpy as np

from ..ir import ScenarioBatch, TreeInfo
from ..model import LinearModel
from ..scenario_tree import MultistageTree

INF = float("inf")

_D = np.array([90.0, 160.0, 110.0])
_U = np.array([0.6048, 0.6048, 1.2096])
_DURACION = np.array([168.0, 168.0, 336.0])
_T_HOURS = 8760.0
_V0 = 60.48
_VMAX = 100.0
_PMAX = 100.0
_BETA_GT = 1.0
_BETA_GH = 0.0
_BETA_DNS = 10.0
_FCFE = 4166.67
_A2_BY_BRANCH = np.array([10.0, 50.0, 90.0])   # stage-2 inflow
_A3_BY_BRANCH = np.array([40.0, 50.0, 60.0])   # stage-3 inflow

_R = (1.0 / 1.1) ** (_DURACION / _T_HOURS)     # discount factors


def _inflows(scennum, tree: MultistageTree):
    """(3,) inflow vector A for scenario scennum (0-based)."""
    d = tree.scen_digits(scennum)
    return np.array([50.0, _A2_BY_BRANCH[d[0]], _A3_BY_BRANCH[d[1]]])


def build_batch(branching_factors=(3, 3), dtype=np.float64):
    """Vectorized batch builder for the full hydro tree.

    Variable layout per scenario (N = 13):
        [0:3)   Pgt[t]       [3:6)  Pgh[t]
        [6:9)   PDns[t]      [9:12) Vol[t]
        [12]    sl
    Rows (M = 7): 3 demand equalities, 3 conservation <=, 1 future-cost.
    Nonant slots (K = 8, stage-major): stage-1 [Pgt1,Pgh1,PDns1,Vol1]
    then stage-2 [Pgt2,Pgh2,PDns2,Vol2].
    """
    tree = MultistageTree(list(branching_factors))
    S = tree.num_scens
    N, M = 13, 7
    iPgt, iPgh, iPDns, iVol, isl = 0, 3, 6, 9, 12

    A = np.zeros((S, M, N), dtype=dtype)
    row_lo = np.full((S, M), -INF, dtype=dtype)
    row_hi = np.full((S, M), INF, dtype=dtype)
    inflow = np.stack([_inflows(s, tree) for s in range(S)])   # (S, 3)

    for t in range(3):
        # demand equality
        A[:, t, iPgt + t] = 1.0
        A[:, t, iPgh + t] = 1.0
        A[:, t, iPDns + t] = 1.0
        row_lo[:, t] = _D[t]
        row_hi[:, t] = _D[t]
        # conservation: Vol[t] - Vol[t-1] + u[t]*Pgh[t] <= u[t]*A[t] (+V0)
        r = 3 + t
        A[:, r, iVol + t] = 1.0
        if t > 0:
            A[:, r, iVol + t - 1] = -1.0
        A[:, r, iPgh + t] = _U[t]
        row_hi[:, r] = _U[t] * inflow[:, t] + (_V0 if t == 0 else 0.0)
    # future cost: sl + FCFE*Vol[3] >= FCFE*V0
    A[:, 6, isl] = 1.0
    A[:, 6, iVol + 2] = _FCFE
    row_lo[:, 6] = _FCFE * _V0

    lb = np.zeros((S, N), dtype=dtype)
    ub = np.full((S, N), INF, dtype=dtype)
    ub[:, iPgt:iPgt + 3] = _PMAX
    ub[:, iPgh:iPgh + 3] = _PMAX
    ub[:, iPDns:iPDns + 3] = _D[None, :]
    ub[:, iVol:iVol + 3] = _VMAX

    c = np.zeros((S, N), dtype=dtype)
    stage_cost_c = np.zeros((3, S, N), dtype=dtype)
    for t in range(3):
        c[:, iPgt + t] = _R[t] * _BETA_GT
        c[:, iPgh + t] = _R[t] * _BETA_GH
        c[:, iPDns + t] = _R[t] * _BETA_DNS
        stage_cost_c[t, :, iPgt + t] = _R[t] * _BETA_GT
        stage_cost_c[t, :, iPgh + t] = _R[t] * _BETA_GH
        stage_cost_c[t, :, iPDns + t] = _R[t] * _BETA_DNS
    c[:, isl] = 1.0
    stage_cost_c[2, :, isl] = 1.0

    # nonants: stage-major [stage1 vars | stage2 vars]
    nonant_idx = np.array(
        [iPgt, iPgh, iPDns, iVol, iPgt + 1, iPgh + 1, iPDns + 1, iVol + 1],
        np.int32)
    stage_of = (1, 1, 1, 1, 2, 2, 2, 2)
    node_of = np.stack([
        tree.node_of_slots(s, stage_of) for s in range(S)
    ]).astype(np.int32)

    var_names = tuple(
        [f"Pgt[{t+1}]" for t in range(3)]
        + [f"Pgh[{t+1}]" for t in range(3)]
        + [f"PDns[{t+1}]" for t in range(3)]
        + [f"Vol[{t+1}]" for t in range(3)]
        + ["sl"])
    treeinfo = TreeInfo(
        node_of=node_of,
        prob=np.array([tree.scen_probability(s) for s in range(S)],
                      dtype=dtype),
        num_nodes=tree.num_nodes,
        stage_of=stage_of,
        nonant_names=tuple(var_names[i] for i in nonant_idx),
        scen_names=tuple(f"Scen{s+1}" for s in range(S)),
    )
    return ScenarioBatch(
        c=c, qdiag=np.zeros((S, N), dtype=dtype),
        A=A, row_lo=row_lo, row_hi=row_hi, lb=lb, ub=ub,
        obj_const=np.zeros((S,), dtype=dtype),
        nonant_idx=nonant_idx,
        integer_mask=np.zeros((S, N), dtype=bool),
        tree=treeinfo,
        stage_cost_c=stage_cost_c,
        var_names=var_names,
    )


def scenario_creator(scenario_name, branching_factors=None):
    """Single-scenario creator via the declarative LinearModel API —
    the analog of the reference's scenario_creator contract
    (reference hydro.py scenario_creator).  Scenario names are
    one-based: "Scen1".."Scen9"."""
    if branching_factors is None:
        raise ValueError(
            "hydro scenario_creator requires branching_factors "
            "(reference raises here too)")
    tree = MultistageTree(list(branching_factors))
    snum = int("".join(ch for ch in scenario_name if ch.isdigit())) - 1
    inflow = _inflows(snum, tree)

    m = LinearModel()
    Pgt = m.add_vars("Pgt", 3, lb=0.0, ub=_PMAX)
    Pgh = m.add_vars("Pgh", 3, lb=0.0, ub=_PMAX)
    PDns = m.add_vars("PDns", 3, lb=0.0, ub=_D)
    Vol = m.add_vars("Vol", 3, lb=0.0, ub=_VMAX)
    sl = m.add_var("sl", lb=0.0)

    for t in range(3):
        m.add_constr({Pgt[t]: 1.0, Pgh[t]: 1.0, PDns[t]: 1.0},
                     lo=_D[t], hi=_D[t])
        m.add_cost(t + 1, {Pgt[t]: _R[t] * _BETA_GT,
                           Pgh[t]: _R[t] * _BETA_GH,
                           PDns[t]: _R[t] * _BETA_DNS})
    for t in range(3):
        terms = {Vol[t]: 1.0, Pgh[t]: _U[t]}
        if t > 0:
            terms[Vol[t - 1]] = -1.0
        m.add_constr(terms,
                     hi=_U[t] * inflow[t] + (_V0 if t == 0 else 0.0))
    m.add_constr({sl: 1.0, Vol[2]: _FCFE}, lo=_FCFE * _V0)
    m.add_cost(3, {sl: 1.0})

    # hydro's nonants are per-index slices of the var blocks (stage t
    # owns index t-1 of each block), finer-grained than block-level
    # set_nonants — lower first, then attach explicit slot metadata:
    spec = m.lower(prob=tree.scen_probability(snum), name=scenario_name)
    # Rebuild nonant metadata to the stage-major slice layout
    nonant_idx = np.array([Pgt[0], Pgh[0], PDns[0], Vol[0],
                           Pgt[1], Pgh[1], PDns[1], Vol[1]], np.int32)
    stage_of = (1, 1, 1, 1, 2, 2, 2, 2)
    node_of = tree.node_of_slots(snum, stage_of)[None, :]
    treeinfo = TreeInfo(
        node_of=node_of,
        prob=spec.tree.prob,
        num_nodes=tree.num_nodes,
        stage_of=stage_of,
        nonant_names=tuple(spec.var_names[i] for i in nonant_idx),
        scen_names=(scenario_name,),
    )
    import dataclasses
    return dataclasses.replace(spec, nonant_idx=nonant_idx, tree=treeinfo)


def scenario_denouement(rank, scenario_name, result):
    pass


# ---- amalgamator-contract helpers ----------------------------------------

def scenario_names_creator(num_scens, start=None):
    start = start or 0
    return [f"Scen{i+1}" for i in range(start, start + num_scens)]


MULTISTAGE = True


def kw_creator(options):
    from ..utils.config import parse_branching_factors
    bf = options.get("branching_factors", [3, 3])
    return {"branching_factors": parse_branching_factors(bf)}


def inparser_adder(cfg):
    cfg.add_branching_factors()

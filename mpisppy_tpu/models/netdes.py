"""NETDES — 2-stage stochastic network design (structure parity with
the reference's netdes model, examples/netdes/netdes.py — the
cross-scenario-cuts showcase).

First stage: open arc a (binary x_a, fixed cost f_a).  Second stage:
route single-commodity flows from a source to a sink under a random
demand D^s; flow on a closed arc is forbidden (flow_a <= cap * x_a);
unserved demand is penalized so recourse is complete.

    min  sum_a f_a x_a + E[ sum_a c_a flow_a + pen * short ]
    s.t. flow balance at each node (source injects D^s - short)
         flow_a - cap_a * x_a <= 0
Nonants: x (binary).

The network is a seeded random layered digraph (n_nodes, arc density),
mirroring the scale of the SIPLIB-style netdes instances without
copying their data files.
"""

from __future__ import annotations

import numpy as np

from ..ir import ScenarioBatch, TreeInfo

INF = float("inf")


def _network(n_nodes, seed=2077):
    """Layered digraph: node 0 = source, n-1 = sink, plus all 'forward'
    random arcs; returns arc list [(u, v)], costs, caps, fixed costs."""
    rng = np.random.RandomState(seed)
    arcs = []
    for u in range(n_nodes - 1):
        for v in range(u + 1, n_nodes):
            if v == u + 1 or rng.rand() < 0.5:
                arcs.append((u, v))
    arcs = np.array(arcs)
    nA = len(arcs)
    f = np.round(20.0 + 60.0 * rng.rand(nA))
    cv = np.round(1.0 + 9.0 * rng.rand(nA))
    cap = np.round(30.0 + 40.0 * rng.rand(nA))
    return arcs, f, cv, cap


def scenario_demand(scennum, num_scens, seed=2077):
    rng = np.random.RandomState(seed + 5000 + scennum)
    return float(np.round(20.0 + 30.0 * rng.rand()))


def build_batch(num_scens, n_nodes=6, overflow_penalty=200.0, seed=2077,
                dtype=np.float64):
    arcs, f, cv, cap = _network(n_nodes, seed)
    nA = len(arcs)
    S = num_scens
    # layout: [x (nA) | flow (nA) | short (1)]
    ix, ifl, ish = 0, nA, 2 * nA
    N = 2 * nA + 1
    M = n_nodes + nA
    A = np.zeros((S, M, N), dtype=dtype)
    row_lo = np.full((S, M), -INF, dtype=dtype)
    row_hi = np.full((S, M), INF, dtype=dtype)

    D = np.array([scenario_demand(s, S, seed) for s in range(S)])
    for node in range(n_nodes):
        out_arcs = np.where(arcs[:, 0] == node)[0]
        in_arcs = np.where(arcs[:, 1] == node)[0]
        A[:, node, ifl + out_arcs] = 1.0
        A[:, node, ifl + in_arcs] = -1.0
        if node == 0:
            A[:, node, ish] = 1.0        # out - in + short = D
            row_lo[:, node] = D
            row_hi[:, node] = D
        elif node == n_nodes - 1:
            A[:, node, ish] = -1.0       # out - in - short = -D
            row_lo[:, node] = -D
            row_hi[:, node] = -D
        else:
            row_lo[:, node] = 0.0
            row_hi[:, node] = 0.0
    for a in range(nA):                  # flow_a - cap_a x_a <= 0
        r = n_nodes + a
        A[:, r, ifl + a] = 1.0
        A[:, r, ix + a] = -cap[a]
        row_hi[:, r] = 0.0

    lb = np.zeros((S, N), dtype=dtype)
    ub = np.full((S, N), INF, dtype=dtype)
    ub[:, ix:ix + nA] = 1.0

    c = np.zeros((S, N), dtype=dtype)
    c[:, ix:ix + nA] = f
    c[:, ifl:ifl + nA] = cv
    c[:, ish] = overflow_penalty

    integer_mask = np.zeros((S, N), dtype=bool)
    integer_mask[:, ix:ix + nA] = True

    stage_cost_c = np.zeros((2, S, N), dtype=dtype)
    stage_cost_c[0, :, ix:ix + nA] = f
    stage_cost_c[1] = c.copy()
    stage_cost_c[1, :, ix:ix + nA] = 0.0

    nonant_idx = np.arange(nA, dtype=np.int32)
    var_names = (
        tuple(f"x[{u}->{v}]" for u, v in arcs)
        + tuple(f"flow[{u}->{v}]" for u, v in arcs)
        + ("short",))
    tree = TreeInfo(
        node_of=np.zeros((S, nA), np.int32),
        prob=np.full((S,), 1.0 / S, dtype=dtype),
        num_nodes=1,
        stage_of=(1,) * nA,
        nonant_names=var_names[:nA],
        scen_names=tuple(f"Scenario{i+1}" for i in range(S)),
    )
    return ScenarioBatch(
        c=c, qdiag=np.zeros((S, N), dtype=dtype),
        A=A, row_lo=row_lo, row_hi=row_hi, lb=lb, ub=ub,
        obj_const=np.zeros((S,), dtype=dtype),
        nonant_idx=nonant_idx, integer_mask=integer_mask,
        tree=tree, stage_cost_c=stage_cost_c, var_names=var_names)


def scenario_names_creator(num_scens, start=0):
    return [f"Scenario{i+1}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    cfg.num_scens_required()
    cfg.add_to_config("netdes_nodes", description="network nodes",
                      domain=int, default=6)


def kw_creator(options):
    return {"n_nodes": options.get("netdes_nodes", 6)}

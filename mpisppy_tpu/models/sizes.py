"""SIZES — 2-stage production-sizes MIP (structure parity with the
reference's sizes model, mpisppy/tests/examples/sizes/sizes.py, the
Jorjani-Scott-Woodruff product-sizes problem).

A manufacturer produces a product in `num_sizes` sizes over two
periods.  A size-i unit can be cut down to serve demand for any size
j <= i at a cutting cost.  Producing any amount of size i in a period
incurs a setup (binary).  First-period demand is known; second-period
demand is random.

Per scenario, variables (stage-major; F = num_sizes):
    z1[i]  in {0,1}  setup, period 1            (nonant)
    x1[i]  >= 0      production, period 1       (nonant)
    y1[i,j] (i>=j)   cut i->j, period 1         (nonant)
    z2[i], x2[i], y2[i,j]                       (recourse)
Constraints:
    x_t[i] <= M * z_t[i]                        (setup forcing)
    sum_j y1[i,j] <= x1[i]                      (cut from period-1 prod)
    sum_j y2[i,j] <= x1[i] - sum_j y1[i,j] + x2[i]   (leftover + new)
    sum_{i>=j} y1[i,j] >= d1[j]                 (period-1 demand)
    sum_{i>=j} y2[i,j] >= d2_s[j]               (period-2 demand, random)
    sum_i x_t[i] <= cap                         (capacity per period)
Objective: setup + production + cutting-penalty costs, both periods.

Data is generated from a fixed seed (documented synthetic instance —
the reference ships literal data tables; we generate the same SHAPE of
instance parametrically).  NOTE the model-structure parity is what the
tests pin down (EF == scipy linprog on the relaxation).

`rho_setter` mirrors the reference's sizes rho_setter example
(examples/sizes/sizes_demo.py): rho proportional to the cost
coefficient of each nonant.
"""

from __future__ import annotations

import numpy as np

from ..ir import ScenarioBatch, TreeInfo

INF = float("inf")


def _instance_data(num_sizes, seed=1134):
    rng = np.random.RandomState(seed)
    F = num_sizes
    setup_cost = 200.0 + 50.0 * rng.rand(F) * np.arange(1, F + 1)
    prod_cost = 2.0 + rng.rand(F)
    cut_cost = 0.2
    d1 = np.round(100.0 + 100.0 * rng.rand(F))
    d2_base = np.round(100.0 + 100.0 * rng.rand(F))
    cap = float(np.ceil(1.75 * max(d1.sum(), d2_base.sum())))
    return dict(setup_cost=setup_cost, prod_cost=prod_cost,
                cut_cost=cut_cost, d1=d1, d2_base=d2_base, cap=cap)


def scenario_demand(scennum, num_scens, num_sizes, seed=1134):
    """Period-2 demand for scenario scennum: the base vector scaled by
    an equally-spaced factor in [0.7, 1.3] (3 scenarios reproduce the
    classic low/mid/high pattern)."""
    data = _instance_data(num_sizes, seed)
    if num_scens == 1:
        f = 1.0
    else:
        f = 0.7 + 0.6 * scennum / (num_scens - 1)
    return np.round(data["d2_base"] * f)


def build_batch(num_scens, num_sizes=3, seed=1134, dtype=np.float64):
    F = num_sizes
    data = _instance_data(F, seed)
    S = num_scens
    pairs = [(i, j) for i in range(F) for j in range(F) if i >= j]
    P = len(pairs)

    # layout: [z1 | x1 | y1 | z2 | x2 | y2]
    iz1, ix1, iy1 = 0, F, 2 * F
    iz2, ix2, iy2 = 2 * F + P, 3 * F + P, 4 * F + P
    N = 4 * F + 2 * P

    # rows: forcing (2F), cut-avail p1 (F), cut-avail p2 (F),
    # demand p1 (F), demand p2 (F), capacity (2)
    M = 6 * F + 2
    A = np.zeros((S, M, N), dtype=dtype)
    row_lo = np.full((S, M), -INF, dtype=dtype)
    row_hi = np.full((S, M), INF, dtype=dtype)
    r = 0
    capM = data["cap"]
    for i in range(F):                      # x1 - M z1 <= 0
        A[:, r, ix1 + i] = 1.0
        A[:, r, iz1 + i] = -capM
        row_hi[:, r] = 0.0
        r += 1
    for i in range(F):                      # x2 - M z2 <= 0
        A[:, r, ix2 + i] = 1.0
        A[:, r, iz2 + i] = -capM
        row_hi[:, r] = 0.0
        r += 1
    for i in range(F):                      # sum_j y1[i,.] - x1 <= 0
        for p, (pi, pj) in enumerate(pairs):
            if pi == i:
                A[:, r, iy1 + p] = 1.0
        A[:, r, ix1 + i] = -1.0
        row_hi[:, r] = 0.0
        r += 1
    for i in range(F):    # sum_j y2[i,.] + sum_j y1[i,.] - x1 - x2 <= 0
        for p, (pi, pj) in enumerate(pairs):
            if pi == i:
                A[:, r, iy2 + p] = 1.0
                A[:, r, iy1 + p] = 1.0
        A[:, r, ix1 + i] = -1.0
        A[:, r, ix2 + i] = -1.0
        row_hi[:, r] = 0.0
        r += 1
    for j in range(F):                      # sum_{i>=j} y1[.,j] >= d1
        for p, (pi, pj) in enumerate(pairs):
            if pj == j:
                A[:, r, iy1 + p] = 1.0
        row_lo[:, r] = data["d1"][j]
        r += 1
    d2 = np.stack([scenario_demand(s, S, F, seed) for s in range(S)])
    for j in range(F):                      # sum_{i>=j} y2[.,j] >= d2_s
        for p, (pi, pj) in enumerate(pairs):
            if pj == j:
                A[:, r, iy2 + p] = 1.0
        row_lo[:, r] = d2[:, j]
        r += 1
    A[:, r, ix1:ix1 + F] = 1.0              # capacity p1
    row_hi[:, r] = data["cap"]
    r += 1
    A[:, r, ix2:ix2 + F] = 1.0              # capacity p2
    row_hi[:, r] = data["cap"]
    r += 1
    assert r == M

    lb = np.zeros((S, N), dtype=dtype)
    ub = np.full((S, N), INF, dtype=dtype)
    ub[:, iz1:iz1 + F] = 1.0
    ub[:, iz2:iz2 + F] = 1.0

    c = np.zeros((S, N), dtype=dtype)
    c[:, iz1:iz1 + F] = data["setup_cost"]
    c[:, iz2:iz2 + F] = data["setup_cost"]
    c[:, ix1:ix1 + F] = data["prod_cost"]
    c[:, ix2:ix2 + F] = data["prod_cost"]
    for p, (pi, pj) in enumerate(pairs):    # cutting penalty ~ distance
        c[:, iy1 + p] = data["cut_cost"] * (pi - pj)
        c[:, iy2 + p] = data["cut_cost"] * (pi - pj)

    integer_mask = np.zeros((S, N), dtype=bool)
    integer_mask[:, iz1:iz1 + F] = True
    integer_mask[:, iz2:iz2 + F] = True

    stage_cost_c = np.zeros((2, S, N), dtype=dtype)
    stage_cost_c[0, :, : 2 * F + P] = c[:, : 2 * F + P]
    stage_cost_c[1, :, 2 * F + P:] = c[:, 2 * F + P:]

    nonant_idx = np.arange(0, 2 * F + P, dtype=np.int32)
    var_names = (
        tuple(f"z1[{i}]" for i in range(F))
        + tuple(f"x1[{i}]" for i in range(F))
        + tuple(f"y1[{i},{j}]" for i, j in pairs)
        + tuple(f"z2[{i}]" for i in range(F))
        + tuple(f"x2[{i}]" for i in range(F))
        + tuple(f"y2[{i},{j}]" for i, j in pairs))
    tree = TreeInfo(
        node_of=np.zeros((S, len(nonant_idx)), np.int32),
        prob=np.full((S,), 1.0 / S, dtype=dtype),
        num_nodes=1,
        stage_of=(1,) * len(nonant_idx),
        nonant_names=tuple(var_names[i] for i in nonant_idx),
        scen_names=tuple(f"Scenario{i+1}" for i in range(S)),
    )
    return ScenarioBatch(
        c=c, qdiag=np.zeros((S, N), dtype=dtype),
        A=A, row_lo=row_lo, row_hi=row_hi, lb=lb, ub=ub,
        obj_const=np.zeros((S,), dtype=dtype),
        nonant_idx=nonant_idx, integer_mask=integer_mask,
        tree=tree, stage_cost_c=stage_cost_c, var_names=var_names)


def rho_setter(batch, rho_scale_factor=1.0):
    """Cost-proportional rho (reference: examples/sizes rho_setter):
    rho_k = scale * |c_k| / 2 at each nonant slot, floored at scale."""
    c_na = np.abs(np.asarray(batch.c))[:, np.asarray(batch.nonant_idx)]
    return np.maximum(rho_scale_factor * c_na / 2.0, rho_scale_factor)


def scenario_names_creator(num_scens, start=0):
    return [f"Scenario{i+1}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    cfg.num_scens_required()
    cfg.add_to_config("num_sizes", description="number of product sizes",
                      domain=int, default=3)


def kw_creator(options):
    return {"num_sizes": options.get("num_sizes", 3)}

"""SIZES — 2-stage production-sizes MIP (reference:
mpisppy/tests/examples/sizes/ReferenceModel.py + SIZES3/SIZES10 data;
the two-period version of Lokketangen & Woodruff's product-sizes
problem, Journal of Heuristics 1996).

This module carries the PUBLISHED instance data of the reference's
SIZES3/SIZES10 `.dat` files (demands, costs, capacity — problem data,
not code): 10 product sizes, capacity 200000, setup cost 453 per size
per period, unit production cost 0.748 + 0.0104*(i-1), flat unit
reduction (cut-down) cost 0.008, first-period demand
[2500 7500 12500 10000 35000 25000 15000 12500 12500 5000], and
second-period demand = factor * first-period demand with factors
  3 scenarios:  0.7, 1.0, 1.3      (SIZES3/Scenario{1,2,3}.dat)
  10 scenarios: 0.5, 1.5, 0.6, 0.7, 0.8, 0.9, 1.1, 1.2, 1.3, 1.4
                                   (SIZES10/Scenario{1..10}.dat)
Golden value: the 3-scenario EF optimum rounds to 220000 at 2
significant figures (reference mpisppy/tests/test_ef_ph.py:137), with
NumProducedFirstStage[5] == 1134 at the optimum (test_ef_ph.py:155).

A size-i unit can be cut down to serve demand for any size j <= i at
the flat reduction cost.  Producing any units of size i in a period
incurs a setup (binary, big-M forcing).  Per scenario, variables
(F = num_sizes, P = F(F+1)/2 ordered pairs i >= j):

    z1[i] in {0,1}   setup, period 1        (derived — NOT nonant,
                     matching the reference's StageDerivedVariables)
    x1[i] int [0,cap]  production, period 1   (nonant)
    y1[i,j] int [0,cap] cut i->j, period 1    (nonant)
    z2[i], x2[i], y2[i,j]                     (recourse)

Constraints (reference ReferenceModel.py:94-140):
    x_t[i] - cap * z_t[i] <= 0                 (setup forcing)
    sum_{j<=i} y1[i,j] - x1[i] <= 0            (inventory, period 1)
    sum_{j<=i} (y1[i,j] + y2[i,j]) - x1[i] - x2[i] <= 0   (period 2)
    sum_{i>=j} y1[i,j] >= d1[j]                (period-1 demand)
    sum_{i>=j} y2[i,j] >= d2_s[j]              (period-2 demand, random)
    sum_i x_t[i] <= cap                        (capacity per period)
Objective: sum_t [ setup*z_t + unitcost*x_t + 0.008 * y_t[i,j] (i!=j) ].

All variable boxes are finite ([0,1] / [0,cap]), so the PDHG dual
objective is a valid Lagrangian bound at any iterate (spopt.Ebound).

`rho_setter` mirrors the reference's sizes _rho_setter
(tests/examples/sizes/sizes.py:37-58): rho = 0.001 * cost coefficient
of each nonant (unit production cost for x1, reduction cost for y1).
"""

from __future__ import annotations

import numpy as np

from ..ir import ScenarioBatch, TreeInfo

INF = float("inf")

# ---- published instance data (reference SIZES3/SIZES10 .dat files) -------
NUM_SIZES = 10
CAPACITY = 200000.0
SETUP_COST = 453.0
UNIT_COST = 0.748 + 0.0104 * np.arange(NUM_SIZES)
CUT_COST = 0.008
DEMAND1 = np.array([2500., 7500., 12500., 10000., 35000.,
                    25000., 15000., 12500., 12500., 5000.])
_FACTORS3 = np.array([0.7, 1.0, 1.3])
_FACTORS10 = np.array([0.5, 1.5, 0.6, 0.7, 0.8, 0.9, 1.1, 1.2, 1.3, 1.4])


def demand_factors(num_scens):
    """Second-period demand factors: exact reference data for 3 and 10
    scenarios; evenly spaced in [0.5, 1.5] otherwise (scalable
    extension for stress runs)."""
    if num_scens == 3:
        return _FACTORS3
    if num_scens == 10:
        return _FACTORS10
    if num_scens == 1:
        return np.array([1.0])
    return 0.5 + np.arange(num_scens) / (num_scens - 1)


def scenario_demand(scennum, num_scens, num_sizes=NUM_SIZES):
    """Period-2 demand vector for one scenario (rounded to integers,
    exactly as the .dat files carry them)."""
    f = demand_factors(num_scens)[scennum]
    return np.round(DEMAND1[:num_sizes] * f)


def build_batch(num_scens, num_sizes=NUM_SIZES, dtype=np.float64,
                seed=None, tighten=True) -> ScenarioBatch:
    """tighten: replace the reference's loose forcing big-M (the
    Capacity, ReferenceModel.py:106 "simple upper bound for M") by the
    presolve-tight value
        M_i = min(cap, total demand servable by size i over the
                  horizon, at the scenario's worst case)
    — a standard MIP-equivalent strengthening (production beyond
    servable demand is pure cost, so no optimum exceeds M_i); the LP
    relaxation bound tightens and big-M diving (opt/mip.py) gets honest
    setup amortization.  tighten=False reproduces the reference's
    relaxation exactly."""
    F = num_sizes
    S = num_scens
    d1 = DEMAND1[:F]
    cap = CAPACITY
    pairs = [(i, j) for i in range(F) for j in range(F) if i >= j]
    P = len(pairs)

    # layout: [z1 | x1 | y1 | z2 | x2 | y2]
    iz1, ix1, iy1 = 0, F, 2 * F
    iz2, ix2, iy2 = 2 * F + P, 3 * F + P, 4 * F + P
    N = 4 * F + 2 * P

    # rows: forcing (2F), inventory p1 (F), inventory p2 (F),
    # demand p1 (F), demand p2 (F), capacity (2)
    M = 6 * F + 2
    A = np.zeros((S, M, N), dtype=dtype)
    row_lo = np.full((S, M), -INF, dtype=dtype)
    row_hi = np.full((S, M), INF, dtype=dtype)
    d2all = np.stack([scenario_demand(s, S, F) for s in range(S)])
    if tighten:
        # servable demand by size i: sizes j <= i, both periods (x1 may
        # pre-produce for period 2 through the p2 inventory row).  x1
        # is SHARED across scenarios, so its M must cover the
        # worst-case scenario (max over s) or valid pre-production for
        # a high-demand scenario would be cut off; x2/z2 are
        # scenario-local so the scenario's own demand bounds them.
        cum1 = np.cumsum(d1)
        cum2 = np.cumsum(d2all, axis=1)                    # (S, F)
        M1 = np.minimum(
            cap, cum1[None, :] + np.max(cum2, axis=0)[None, :]
        ) * np.ones((S, 1))                                # (S, F)
        M2 = np.minimum(cap, cum2)
    else:
        M1 = np.full((S, F), cap)
        M2 = np.full((S, F), cap)
    r = 0
    for i in range(F):                      # x1 - M1 z1 <= 0
        A[:, r, ix1 + i] = 1.0
        A[:, r, iz1 + i] = -M1[:, i]
        row_hi[:, r] = 0.0
        r += 1
    for i in range(F):                      # x2 - M2 z2 <= 0
        A[:, r, ix2 + i] = 1.0
        A[:, r, iz2 + i] = -M2[:, i]
        row_hi[:, r] = 0.0
        r += 1
    for i in range(F):                      # sum_{j<=i} y1[i,.] - x1 <= 0
        for p, (pi, pj) in enumerate(pairs):
            if pi == i:
                A[:, r, iy1 + p] = 1.0
        A[:, r, ix1 + i] = -1.0
        row_hi[:, r] = 0.0
        r += 1
    for i in range(F):  # sum_{j<=i} (y1[i,.]+y2[i,.]) - x1 - x2 <= 0
        for p, (pi, pj) in enumerate(pairs):
            if pi == i:
                A[:, r, iy2 + p] = 1.0
                A[:, r, iy1 + p] = 1.0
        A[:, r, ix1 + i] = -1.0
        A[:, r, ix2 + i] = -1.0
        row_hi[:, r] = 0.0
        r += 1
    for j in range(F):                      # sum_{i>=j} y1[.,j] >= d1
        for p, (pi, pj) in enumerate(pairs):
            if pj == j:
                A[:, r, iy1 + p] = 1.0
        row_lo[:, r] = d1[j]
        r += 1
    d2 = d2all
    for j in range(F):                      # sum_{i>=j} y2[.,j] >= d2_s
        for p, (pi, pj) in enumerate(pairs):
            if pj == j:
                A[:, r, iy2 + p] = 1.0
        row_lo[:, r] = d2[:, j]
        r += 1
    A[:, r, ix1:ix1 + F] = 1.0              # capacity p1
    row_hi[:, r] = cap
    r += 1
    A[:, r, ix2:ix2 + F] = 1.0              # capacity p2
    row_hi[:, r] = cap
    r += 1
    assert r == M

    lb = np.zeros((S, N), dtype=dtype)
    ub = np.full((S, N), cap, dtype=dtype)
    ub[:, iz1:iz1 + F] = 1.0
    ub[:, iz2:iz2 + F] = 1.0

    c = np.zeros((S, N), dtype=dtype)
    c[:, iz1:iz1 + F] = SETUP_COST
    c[:, iz2:iz2 + F] = SETUP_COST
    c[:, ix1:ix1 + F] = UNIT_COST[:F]
    c[:, ix2:ix2 + F] = UNIT_COST[:F]
    for p, (pi, pj) in enumerate(pairs):    # flat reduction cost, i != j
        if pi != pj:
            c[:, iy1 + p] = CUT_COST
            c[:, iy2 + p] = CUT_COST

    # every variable is integer in the reference model (z binary; x, y
    # NonNegativeIntegers, ReferenceModel.py:70-83)
    integer_mask = np.ones((S, N), dtype=bool)

    stage_cost_c = np.zeros((2, S, N), dtype=dtype)
    stage_cost_c[0, :, : 2 * F + P] = c[:, : 2 * F + P]
    stage_cost_c[1, :, 2 * F + P:] = c[:, 2 * F + P:]

    # nonants = x1 and y1 (the reference's varlist,
    # tests/examples/sizes/sizes.py:27); z1 is stage-derived
    nonant_idx = np.arange(F, 2 * F + P, dtype=np.int32)
    var_names = (
        tuple(f"ProduceSizeFirstStage[{i+1}]" for i in range(F))
        + tuple(f"NumProducedFirstStage[{i+1}]" for i in range(F))
        + tuple(f"NumUnitsCutFirstStage[{i+1},{j+1}]" for i, j in pairs)
        + tuple(f"ProduceSizeSecondStage[{i+1}]" for i in range(F))
        + tuple(f"NumProducedSecondStage[{i+1}]" for i in range(F))
        + tuple(f"NumUnitsCutSecondStage[{i+1},{j+1}]" for i, j in pairs))
    tree = TreeInfo(
        node_of=np.zeros((S, len(nonant_idx)), np.int32),
        prob=np.full((S,), 1.0 / S, dtype=dtype),
        num_nodes=1,
        stage_of=(1,) * len(nonant_idx),
        nonant_names=tuple(var_names[i] for i in nonant_idx),
        scen_names=tuple(f"Scenario{i+1}" for i in range(S)),
    )
    return ScenarioBatch(
        c=c, qdiag=np.zeros((S, N), dtype=dtype),
        A=A, row_lo=row_lo, row_hi=row_hi, lb=lb, ub=ub,
        obj_const=np.zeros((S,), dtype=dtype),
        nonant_idx=nonant_idx, integer_mask=integer_mask,
        tree=tree, stage_cost_c=stage_cost_c, var_names=var_names)


def rho_setter(batch, rho_scale_factor=0.001):
    """Cost-proportional rho (reference tests/examples/sizes/sizes.py:37
    _rho_setter: rho = RF * unit production cost for NumProduced slots,
    RF * reduction cost for NumUnitsCut slots, RF = 0.001)."""
    c_na = np.abs(np.asarray(batch.c))[:, np.asarray(batch.nonant_idx)]
    return rho_scale_factor * np.maximum(c_na, CUT_COST)


def scenario_names_creator(num_scens, start=0):
    return [f"Scenario{i+1}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    cfg.num_scens_required()
    cfg.add_to_config("num_sizes", description="number of product sizes",
                      domain=int, default=NUM_SIZES)


def kw_creator(options):
    return {"num_sizes": options.get("num_sizes", NUM_SIZES)}


def batch_creator(cfg_or_kwargs, num_scens=None):
    kw = dict(cfg_or_kwargs)
    n = num_scens or kw.pop("num_scens", None)
    kw.pop("num_scens", None)
    kw.pop("use_integer", None)
    kw.pop("crops_multiplier", None)
    return build_batch(n, **kw)


def scenario_denouement(rank, scenario_name, result):
    pass

"""SSLP — 2-stage stochastic server location (structure parity with the
reference's sslp model, examples/sslp/sslp.py, from Ntaimo & Sen's
SIPLIB instances sslp_m_n_S).

First stage: open server at site j (binary x_j, cost cs_j), at most
`max_servers` open.  Second stage: client i is PRESENT with scenario
indicator h_i^s in {0,1}; present clients are assigned to open sites
(y_ij in [0,1], relaxed binaries), earning revenue q_ij (negative
cost); site capacity u limits the assigned load sum_i d_i y_ij; an
overflow variable o_j (penalty) keeps recourse complete.

    min  sum_j cs_j x_j - sum_ij q_ij y_ij + pen * sum_j o_j
    s.t. sum_j y_ij  = h_i^s                 (assign present clients)
         sum_i d_i y_ij - u x_j - o_j <= 0   (capacity if open)
         sum_j x_j <= max_servers
Nonants: x (binary).

Instance data generated from a fixed seed: d_i ~ U{5..20},
q_ij ~ U{10..40}, cs_j ~ U{40..80}, u = ceil(1.5 * sum d / m).
Naming mirrors SIPLIB: build_batch(num_scens, m_sites, n_clients).
"""

from __future__ import annotations

import numpy as np

from ..ir import ScenarioBatch, TreeInfo

INF = float("inf")


def _instance(m, n, seed=365):
    rng = np.random.RandomState(seed)
    d = rng.randint(5, 21, size=n).astype(float)
    q = rng.randint(10, 41, size=(n, m)).astype(float)
    cs = rng.randint(40, 81, size=m).astype(float)
    u = float(np.ceil(1.5 * d.sum() / m))
    return d, q, cs, u


def client_presence(scennum, num_scens, n_clients, seed=365):
    """(n,) 0/1 presence vector; each client present w.p. 0.5 (the
    SIPLIB convention), scenario-seeded."""
    rng = np.random.RandomState(seed + 1000 + scennum)
    return (rng.rand(n_clients) < 0.5).astype(float)


def build_batch(num_scens, m_sites=5, n_clients=10, max_servers=None,
                overflow_penalty=1000.0, seed=365, dtype=np.float64):
    m, n, S = m_sites, n_clients, num_scens
    d, q, cs, u = _instance(m, n, seed)
    if max_servers is None:
        max_servers = m

    # layout: [x (m) | y (n*m, client-major) | o (m)]
    ix, iy, io = 0, m, m + n * m
    N = m + n * m + m
    # rows: n assignment equalities + m capacity + 1 cardinality
    M = n + m + 1
    A = np.zeros((S, M, N), dtype=dtype)
    row_lo = np.full((S, M), -INF, dtype=dtype)
    row_hi = np.full((S, M), INF, dtype=dtype)

    h = np.stack([client_presence(s, S, n, seed) for s in range(S)])
    for i in range(n):                       # sum_j y_ij = h_i
        A[:, i, iy + i * m: iy + (i + 1) * m] = 1.0
        row_lo[:, i] = h[:, i]
        row_hi[:, i] = h[:, i]
    for j in range(m):                       # sum_i d_i y_ij - u x_j - o_j <= 0
        r = n + j
        for i in range(n):
            A[:, r, iy + i * m + j] = d[i]
        A[:, r, ix + j] = -u
        A[:, r, io + j] = -1.0
        row_hi[:, r] = 0.0
    A[:, n + m, ix:ix + m] = 1.0             # cardinality
    row_hi[:, n + m] = float(max_servers)

    lb = np.zeros((S, N), dtype=dtype)
    ub = np.full((S, N), INF, dtype=dtype)
    ub[:, ix:ix + m] = 1.0
    ub[:, iy:io] = 1.0

    c = np.zeros((S, N), dtype=dtype)
    c[:, ix:ix + m] = cs
    c[:, iy:io] = -q.reshape(-1)
    c[:, io:] = overflow_penalty

    integer_mask = np.zeros((S, N), dtype=bool)
    integer_mask[:, ix:ix + m] = True

    stage_cost_c = np.zeros((2, S, N), dtype=dtype)
    stage_cost_c[0, :, ix:ix + m] = cs
    stage_cost_c[1] = c.copy()
    stage_cost_c[1, :, ix:ix + m] = 0.0

    nonant_idx = np.arange(m, dtype=np.int32)
    var_names = (
        tuple(f"x[{j}]" for j in range(m))
        + tuple(f"y[{i},{j}]" for i in range(n) for j in range(m))
        + tuple(f"o[{j}]" for j in range(m)))
    tree = TreeInfo(
        node_of=np.zeros((S, m), np.int32),
        prob=np.full((S,), 1.0 / S, dtype=dtype),
        num_nodes=1,
        stage_of=(1,) * m,
        nonant_names=var_names[:m],
        scen_names=tuple(f"Scenario{i+1}" for i in range(S)),
    )
    return ScenarioBatch(
        c=c, qdiag=np.zeros((S, N), dtype=dtype),
        A=A, row_lo=row_lo, row_hi=row_hi, lb=lb, ub=ub,
        obj_const=np.zeros((S,), dtype=dtype),
        nonant_idx=nonant_idx, integer_mask=integer_mask,
        tree=tree, stage_cost_c=stage_cost_c, var_names=var_names)


def scenario_names_creator(num_scens, start=0):
    return [f"Scenario{i+1}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    cfg.num_scens_required()
    cfg.add_to_config("m_sites", description="candidate server sites",
                      domain=int, default=5)
    cfg.add_to_config("n_clients", description="clients", domain=int,
                      default=10)


def kw_creator(options):
    return {"m_sites": options.get("m_sites", 5),
            "n_clients": options.get("n_clients", 10)}

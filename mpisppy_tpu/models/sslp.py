"""SSLP — 2-stage stochastic server location (structure parity with the
reference's sslp model, examples/sslp/sslp.py, from Ntaimo & Sen's
SIPLIB instances sslp_m_n_S).

Two instance sources:
  * synthetic, seed-generated (default) — scalable m/n/S;
  * the PUBLISHED SIPLIB instance sslp_5_25_50
    (instance="sslp_5_25": 5 sites, 25 clients, up to 50 scenarios;
    data from the reference's examples/sslp/data/sslp_5_25_50 .dat
    files — benchmark problem data, not code): FixedCost
    [40,60,47,68,60], Capacity 188, the 25x5 Revenue==Demand matrix,
    binary allocations, penalty 1000, and the 50 published
    client-presence vectors (packed as 25-bit integers below).
    SIPLIB's published optimum for sslp_5_25_50 is -121.6.

First stage: open server at site j (binary x_j, cost cs_j), at most
`max_servers` open.  Second stage: client i is PRESENT with scenario
indicator h_i^s in {0,1}; present clients are assigned to open sites
(y_ij in [0,1], relaxed binaries), earning revenue q_ij (negative
cost); site capacity u limits the assigned load sum_i d_i y_ij; an
overflow variable o_j (penalty) keeps recourse complete.

    min  sum_j cs_j x_j - sum_ij q_ij y_ij + pen * sum_j o_j
    s.t. sum_j y_ij  = h_i^s                 (assign present clients)
         sum_i d_i y_ij - u x_j - o_j <= 0   (capacity if open)
         sum_j x_j <= max_servers
Nonants: x (binary).

Instance data generated from a fixed seed: d_i ~ U{5..20},
q_ij ~ U{10..40}, cs_j ~ U{40..80}, u = ceil(1.5 * sum d / m).
Naming mirrors SIPLIB: build_batch(num_scens, m_sites, n_clients).
"""

from __future__ import annotations

import numpy as np

from ..ir import ScenarioBatch, TreeInfo

INF = float("inf")


def _instance(m, n, seed=365):
    rng = np.random.RandomState(seed)
    d = rng.randint(5, 21, size=n).astype(float)
    q = rng.randint(10, 41, size=(n, m)).astype(float)
    cs = rng.randint(40, 81, size=m).astype(float)
    u = float(np.ceil(1.5 * d.sum() / m))
    return d, q, cs, u


# ---- published SIPLIB sslp_5_25_50 data ----------------------------------
SIPLIB_5_25_FIXED_COST = np.array([40.0, 60.0, 47.0, 68.0, 60.0])
SIPLIB_5_25_CAPACITY = 188.0
SIPLIB_5_25_REVENUE = np.array([   # (25 clients, 5 sites); == Demand
    [0, 22, 18, 14, 22], [15, 11, 20, 8, 14], [4, 22, 10, 0, 25],
    [14, 23, 23, 5, 22], [8, 23, 14, 5, 11], [18, 5, 2, 23, 6],
    [6, 8, 22, 3, 15], [14, 21, 6, 16, 14], [21, 6, 1, 8, 3],
    [16, 14, 13, 12, 22], [8, 20, 15, 15, 12], [11, 4, 9, 15, 11],
    [2, 19, 13, 2, 9], [15, 20, 17, 0, 16], [6, 1, 21, 23, 1],
    [11, 21, 2, 15, 17], [17, 17, 3, 13, 3], [15, 5, 14, 19, 7],
    [10, 8, 0, 8, 14], [22, 24, 23, 14, 15], [14, 13, 8, 2, 23],
    [21, 12, 10, 12, 17], [2, 10, 13, 10, 9], [20, 21, 9, 20, 21],
    [23, 18, 2, 9, 23]], dtype=float)
# per-scenario ClientPresent vectors, packed MSB-first as 25-bit ints
SIPLIB_5_25_PRESENCE = [
    20993912, 9960662, 7363960, 24339278, 9109504, 29602284, 1319906,
    10106138, 4046399, 4624107, 709021, 31316171, 8568690, 24379175,
    25755796, 28888391, 11091660, 31149044, 30174143, 2178029,
    13892334, 5272943, 14864160, 4486218, 14990610, 29994912,
    27939587, 29855491, 22570151, 1630004, 918378, 10689346, 14884763,
    27127282, 10444694, 1718028, 626212, 10917971, 5014440, 32786963,
    27330641, 10525162, 32990958, 23749576, 26983959, 23481858,
    18431288, 910631, 24749425, 8684607]


def siplib_presence(scennum):
    """(25,) 0/1 ClientPresent vector of SIPLIB scenario scennum+1."""
    bits = SIPLIB_5_25_PRESENCE[scennum]
    return np.array([(bits >> (24 - i)) & 1 for i in range(25)],
                    dtype=float)


def client_presence(scennum, num_scens, n_clients, seed=365):
    """(n,) 0/1 presence vector; each client present w.p. 0.5 (the
    SIPLIB convention), scenario-seeded."""
    rng = np.random.RandomState(seed + 1000 + scennum)
    return (rng.rand(n_clients) < 0.5).astype(float)


def build_batch(num_scens, m_sites=5, n_clients=10, max_servers=None,
                overflow_penalty=1000.0, seed=365, dtype=np.float64,
                instance=None):
    """instance="sslp_5_25": the published SIPLIB sslp_5_25_50 data
    (num_scens <= 50, binary allocations, per-PAIR demand == revenue);
    default: the synthetic seed-generated family."""
    if instance == "sslp_5_25":
        return _build_siplib_5_25(num_scens, dtype=dtype)
    m, n, S = m_sites, n_clients, num_scens
    d, q, cs, u = _instance(m, n, seed)
    if max_servers is None:
        max_servers = m

    # layout: [x (m) | y (n*m, client-major) | o (m)]
    ix, iy, io = 0, m, m + n * m
    N = m + n * m + m
    # rows: n assignment equalities + m capacity + 1 cardinality
    M = n + m + 1
    A = np.zeros((S, M, N), dtype=dtype)
    row_lo = np.full((S, M), -INF, dtype=dtype)
    row_hi = np.full((S, M), INF, dtype=dtype)

    h = np.stack([client_presence(s, S, n, seed) for s in range(S)])
    for i in range(n):                       # sum_j y_ij = h_i
        A[:, i, iy + i * m: iy + (i + 1) * m] = 1.0
        row_lo[:, i] = h[:, i]
        row_hi[:, i] = h[:, i]
    for j in range(m):                       # sum_i d_i y_ij - u x_j - o_j <= 0
        r = n + j
        for i in range(n):
            A[:, r, iy + i * m + j] = d[i]
        A[:, r, ix + j] = -u
        A[:, r, io + j] = -1.0
        row_hi[:, r] = 0.0
    A[:, n + m, ix:ix + m] = 1.0             # cardinality
    row_hi[:, n + m] = float(max_servers)

    lb = np.zeros((S, N), dtype=dtype)
    ub = np.full((S, N), INF, dtype=dtype)
    ub[:, ix:ix + m] = 1.0
    ub[:, iy:io] = 1.0

    c = np.zeros((S, N), dtype=dtype)
    c[:, ix:ix + m] = cs
    c[:, iy:io] = -q.reshape(-1)
    c[:, io:] = overflow_penalty

    integer_mask = np.zeros((S, N), dtype=bool)
    integer_mask[:, ix:ix + m] = True

    stage_cost_c = np.zeros((2, S, N), dtype=dtype)
    stage_cost_c[0, :, ix:ix + m] = cs
    stage_cost_c[1] = c.copy()
    stage_cost_c[1, :, ix:ix + m] = 0.0

    nonant_idx = np.arange(m, dtype=np.int32)
    var_names = (
        tuple(f"x[{j}]" for j in range(m))
        + tuple(f"y[{i},{j}]" for i in range(n) for j in range(m))
        + tuple(f"o[{j}]" for j in range(m)))
    tree = TreeInfo(
        node_of=np.zeros((S, m), np.int32),
        prob=np.full((S,), 1.0 / S, dtype=dtype),
        num_nodes=1,
        stage_of=(1,) * m,
        nonant_names=var_names[:m],
        scen_names=tuple(f"Scenario{i+1}" for i in range(S)),
    )
    return ScenarioBatch(
        c=c, qdiag=np.zeros((S, N), dtype=dtype),
        A=A, row_lo=row_lo, row_hi=row_hi, lb=lb, ub=ub,
        obj_const=np.zeros((S,), dtype=dtype),
        nonant_idx=nonant_idx, integer_mask=integer_mask,
        tree=tree, stage_cost_c=stage_cost_c, var_names=var_names)


def _build_siplib_5_25(num_scens, dtype=np.float64) -> ScenarioBatch:
    """The published SIPLIB sslp_5_25_50 instance (reference
    examples/sslp/model/ReferenceModel.py + data/sslp_5_25_50):

        min  FixedCost @ x - Revenue @ y + 1000 * sum_j o_j
        s.t. sum_j y_ij = present_i^s          (client assignment)
             sum_i Demand_ij y_ij - o_j <= Capacity * x_j
             x_j, y_ij binary; o_j >= 0
    """
    if num_scens > 50:
        raise ValueError("sslp_5_25 has 50 published scenarios")
    m, n, S = 5, 25, num_scens
    q = SIPLIB_5_25_REVENUE                       # (n, m); == demand
    cs = SIPLIB_5_25_FIXED_COST
    u = SIPLIB_5_25_CAPACITY

    ix, iy, io = 0, m, m + n * m
    N = m + n * m + m
    M = n + m
    A = np.zeros((S, M, N), dtype=dtype)
    row_lo = np.full((S, M), -INF, dtype=dtype)
    row_hi = np.full((S, M), INF, dtype=dtype)

    h = np.stack([siplib_presence(s) for s in range(S)])
    for i in range(n):                       # sum_j y_ij = h_i
        A[:, i, iy + i * m: iy + (i + 1) * m] = 1.0
        row_lo[:, i] = h[:, i]
        row_hi[:, i] = h[:, i]
    for j in range(m):       # sum_i d_ij y_ij - u x_j - o_j <= 0
        r = n + j
        for i in range(n):
            A[:, r, iy + i * m + j] = q[i, j]
        A[:, r, ix + j] = -u
        A[:, r, io + j] = -1.0
        row_hi[:, r] = 0.0

    lb = np.zeros((S, N), dtype=dtype)
    ub = np.full((S, N), INF, dtype=dtype)
    ub[:, ix:ix + m] = 1.0
    ub[:, iy:io] = 1.0
    # implied finite box for the overflow: o_j <= total demand of
    # present clients at j (provably inactive beyond it)
    ub[:, io:] = float(q.sum())

    c = np.zeros((S, N), dtype=dtype)
    c[:, ix:ix + m] = cs
    c[:, iy:io] = -q.reshape(-1)
    c[:, io:] = 1000.0

    integer_mask = np.zeros((S, N), dtype=bool)
    integer_mask[:, ix:ix + m] = True
    integer_mask[:, iy:io] = True            # Allocation is binary

    stage_cost_c = np.zeros((2, S, N), dtype=dtype)
    stage_cost_c[0, :, ix:ix + m] = cs
    stage_cost_c[1] = c.copy()
    stage_cost_c[1, :, ix:ix + m] = 0.0

    nonant_idx = np.arange(m, dtype=np.int32)
    var_names = (
        tuple(f"FacilityOpen[{j+1}]" for j in range(m))
        + tuple(f"Allocation[{i+1},{j+1}]"
                for i in range(n) for j in range(m))
        + tuple(f"Dummy[{j+1}]" for j in range(m)))
    tree = TreeInfo(
        node_of=np.zeros((S, m), np.int32),
        prob=np.full((S,), 1.0 / S, dtype=dtype),
        num_nodes=1,
        stage_of=(1,) * m,
        nonant_names=var_names[:m],
        scen_names=tuple(f"Scenario{i+1}" for i in range(S)),
    )
    return ScenarioBatch(
        c=c, qdiag=np.zeros((S, N), dtype=dtype),
        A=A, row_lo=row_lo, row_hi=row_hi, lb=lb, ub=ub,
        obj_const=np.zeros((S,), dtype=dtype),
        nonant_idx=nonant_idx, integer_mask=integer_mask,
        tree=tree, stage_cost_c=stage_cost_c, var_names=var_names)


def scenario_names_creator(num_scens, start=0):
    return [f"Scenario{i+1}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    cfg.num_scens_required()
    cfg.add_to_config("m_sites", description="candidate server sites",
                      domain=int, default=5)
    cfg.add_to_config("n_clients", description="clients", domain=int,
                      default=10)
    cfg.add_to_config("sslp_instance",
                      description="named instance (sslp_5_25) or "
                      "empty for synthetic", domain=str, default="")


def kw_creator(options):
    kw = {"m_sites": options.get("m_sites", 5),
          "n_clients": options.get("n_clients", 10)}
    inst = options.get("sslp_instance") or options.get("instance")
    if inst:
        kw["instance"] = inst
    return kw

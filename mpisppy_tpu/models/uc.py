"""UC — stochastic unit commitment (structure parity with the
reference's uc model family, examples/uc/uc_funcs.py, which wraps
egret; here a self-contained DC-less UC with the same stochastic
shape: first-stage commitment, per-scenario wind).

G thermal units, H hours.  First stage: commitment u_gh in {0,1} and
startup s_gh >= 0.  Second stage, per wind scenario w: dispatch
p_gh >= 0 and load shed sh_h >= 0:

    p_gh <= Pmax_g * u_gh ;  p_gh >= Pmin_g * u_gh
    sum_g p_gh + wind^s_h + sh_h >= demand_h        (balance)
    s_gh >= u_gh - u_g,h-1                          (startup def)
    |p_gh - p_g,h-1| <= ramp_g                      (ramping)
    u_g,tau >= u_gh - u_g,h-1   for tau in (h, h+UT_g)   (min up)
    u_g,tau <= 1 - (u_g,h-1 - u_gh) for tau in (h, h+DT_g)  (min down)
    min sum_gh (cNL_g u_gh + cSU_g s_gh) +
        E[ sum_gh cV_g p_gh + pen * sum_h sh_h ]
Nonants: u, s (first stage).  Min-up/min-down times (UT/DT per unit,
the reference egret UC's uptime/downtime constraints) activate with
min_up_down=True — big units carry the longer windows.

Unit data is a fixed small fleet; wind is a seeded hourly profile per
scenario (the reference's 3..1000 wind-scenario instances).
"""

from __future__ import annotations

import numpy as np

from ..ir import ScenarioBatch, TreeInfo

INF = float("inf")

# fleet: Pmin, Pmax, ramp, cNL (no-load), cSU (startup), cV (variable)
_FLEET = np.array([
    # Pmin  Pmax  ramp  cNL   cSU    cV
    [100.0, 400.0, 150.0, 500.0, 800.0, 15.0],    # big coal-ish
    [50.0, 200.0, 100.0, 300.0, 400.0, 25.0],     # mid gas
    [10.0, 100.0, 100.0, 100.0, 100.0, 40.0],     # peaker
])
# min-up / min-down hours per base unit (big units cycle slowly)
_UT = np.array([3, 2, 1])
_DT = np.array([3, 2, 1])
_PEN = 1000.0


def demand_profile(H):
    hours = np.arange(H)
    return 350.0 + 150.0 * np.sin(np.pi * (hours + 2) / (H / 1.5))


def wind_profile(scennum, H, seed=91):
    rng = np.random.RandomState(seed + 17 * scennum)
    base = 80.0 + 60.0 * rng.rand()
    wiggle = 40.0 * rng.rand(H)
    return np.maximum(0.0, base + wiggle - 20.0)


def build_batch(num_scens, H=6, n_units=None, seed=91,
                fleet_multiplier=1, dtype=np.float64, shared_A=True,
                min_up_down=False, reserve_factor=0.0, scens=None):
    """fleet_multiplier k replicates the 3-unit fleet k times with
    seeded parameter jitter and scales demand to match — the scaling
    axis of the reference's larger_uc instances (paperruns/larger_uc:
    3..1000 wind scenarios on bigger systems).

    shared_A (default True): UC's uncertainty lives entirely in the
    balance-row BOUNDS (wind offsets demand) — the constraint matrix is
    scenario-independent.  Storing it once, (1, M, N), turns every
    batched matvec into a real (S, N) x (N, M) matmul on the MXU
    (ir.bmatvec) and cuts the constraint-tensor memory by S, which is
    what makes the 1000-wind-scenario, 20+-unit, 24 h instances of the
    reference's larger_uc study fit on one chip.

    reserve_factor r > 0 adds the egret-style spinning-reserve rows
    (one per hour), in capacity-adequacy form: committed capacity
    sum_g Pmax_g u_gh must cover net load plus r * demand_h.  Neither
    dispatch nor load shed appears in the row, so shedding cannot
    satisfy reserve — an under-committed hour is infeasible, not
    merely expensive — which is what makes reserve bind the
    commitment the way the reference's egret UC reserves do.  Wind
    enters the row bound per scenario (like the balance rows), so
    shared_A is preserved.

    scens: optional GLOBAL scenario index set; default the contiguous
    universe [0, num_scens).  Scenario i's wind depends only on i
    (wind_profile seeds RandomState(seed + 17*i)), so an arbitrary
    index set yields exactly those scenarios' data — the streaming
    block contract (`scenario_block` wraps this)."""
    if reserve_factor < 0:
        raise ValueError(
            f"reserve_factor must be >= 0, got {reserve_factor}")
    scens = (np.arange(num_scens, dtype=np.int64) if scens is None
             else np.asarray(scens, dtype=np.int64))
    fleet = _FLEET if n_units is None else _FLEET[:n_units]
    if fleet_multiplier > 1:
        rng = np.random.RandomState(seed + 5)
        reps = []
        for k in range(fleet_multiplier):
            jit = 1.0 + 0.1 * (rng.rand(len(fleet), 6) - 0.5)
            reps.append(fleet * jit)
        fleet = np.concatenate(reps, axis=0)
    G = len(fleet)
    S = scens.size
    Pmin, Pmax, ramp, cNL, cSU, cV = fleet.T

    # layout: [u (G*H) | s (G*H) | p (G*H) | sh (H)], unit-major blocks
    iu, isu, ip, ish = 0, G * H, 2 * G * H, 3 * G * H
    N = 3 * G * H + H

    def uidx(g, h):
        return iu + g * H + h

    def sidx(g, h):
        return isu + g * H + h

    def pidx(g, h):
        return ip + g * H + h

    # min-up/min-down windows per unit: tile the base table to however
    # many units the fleet actually has (n_units trims the base fleet,
    # fleet_multiplier replicates it — both change G).  These tables
    # are also stored on the batch (model_meta) so candidate repair
    # uses EXACTLY what A encodes, never a re-derivation.
    nb = min(len(_FLEET) if n_units is None else n_units, len(_FLEET))
    ut = np.tile(_UT[:nb], (G + nb - 1) // nb)[:G]
    dt_ = np.tile(_DT[:nb], (G + nb - 1) // nb)[:G]
    mud_rows = []
    if min_up_down:
        for g in range(G):
            for h in range(1, H):
                for tau in range(h + 1, min(h + int(ut[g]), H)):
                    mud_rows.append(("up", g, h, tau))
                for tau in range(h + 1, min(h + int(dt_[g]), H)):
                    mud_rows.append(("dn", g, h, tau))

    # rows: pmax (GH), pmin (GH), balance (H), startup (GH),
    # ramp up (G(H-1)), ramp down (G(H-1)), min up/down windows,
    # spinning reserve (H, if reserve_factor > 0)
    n_res = H if reserve_factor > 0 else 0
    M = 3 * G * H + H + 2 * G * (H - 1) + len(mud_rows) + n_res
    SA = 1 if shared_A else S   # matrix is scenario-independent
    A = np.zeros((SA, M, N), dtype=dtype)
    row_lo = np.full((S, M), -INF, dtype=dtype)
    row_hi = np.full((S, M), INF, dtype=dtype)
    r = 0
    for g in range(G):
        for h in range(H):
            A[:, r, pidx(g, h)] = 1.0      # p - Pmax u <= 0
            A[:, r, uidx(g, h)] = -Pmax[g]
            row_hi[:, r] = 0.0
            r += 1
    for g in range(G):
        for h in range(H):
            A[:, r, pidx(g, h)] = 1.0      # p - Pmin u >= 0
            A[:, r, uidx(g, h)] = -Pmin[g]
            row_lo[:, r] = 0.0
            r += 1
    dem = demand_profile(H) * fleet_multiplier
    wind = np.stack([wind_profile(int(s), H, seed)
                     for s in scens]) * fleet_multiplier
    for h in range(H):                     # balance
        for g in range(G):
            A[:, r, pidx(g, h)] = 1.0
        A[:, r, ish + h] = 1.0
        row_lo[:, r] = dem[h] - wind[:, h]
        r += 1
    for g in range(G):                     # s_gh >= u_gh - u_g,h-1
        for h in range(H):
            A[:, r, sidx(g, h)] = 1.0
            A[:, r, uidx(g, h)] = -1.0
            if h > 0:
                A[:, r, uidx(g, h - 1)] = 1.0
            row_lo[:, r] = 0.0
            r += 1
    for g in range(G):                     # ramping
        for h in range(1, H):
            A[:, r, pidx(g, h)] = 1.0
            A[:, r, pidx(g, h - 1)] = -1.0
            row_hi[:, r] = ramp[g]
            r += 1
    for g in range(G):
        for h in range(1, H):
            A[:, r, pidx(g, h)] = -1.0
            A[:, r, pidx(g, h - 1)] = 1.0
            row_hi[:, r] = ramp[g]
            r += 1
    # min-up: u_tau >= u_h - u_{h-1}  ->  u_h - u_{h-1} - u_tau <= 0
    # min-down: (u_{h-1} - u_h) + u_tau <= 1
    for kind, g, h, tau in mud_rows:
        if kind == "up":
            A[:, r, uidx(g, h)] = 1.0
            A[:, r, uidx(g, h - 1)] = -1.0
            A[:, r, uidx(g, tau)] = -1.0
            row_hi[:, r] = 0.0
        else:
            A[:, r, uidx(g, h - 1)] = 1.0
            A[:, r, uidx(g, h)] = -1.0
            A[:, r, uidx(g, tau)] = 1.0
            row_hi[:, r] = 1.0
        r += 1
    # spinning reserve, capacity-adequacy form: committed capacity
    # sum_g Pmax_g u_gh >= net load + r * demand.  Neither p nor shed
    # appears in the row — a headroom form (sum Pmax u - p >= R) leaks
    # through shedding, because raising shed lets p drop and frees
    # headroom one-for-one; the capacity form is what actually forces
    # commitment.  Wind sits in the row BOUND, per scenario, exactly
    # like the balance rows — shared_A is preserved.
    if n_res:
        for h in range(H):
            for g in range(G):
                A[:, r, uidx(g, h)] = Pmax[g]
            row_lo[:, r] = (dem[h] - wind[:, h]
                            + reserve_factor * dem[h])
            r += 1
    assert r == M

    lb = np.zeros((S, N), dtype=dtype)
    # implied finite boxes (farmer-style, provably inactive at some
    # optimum): p <= Pmax follows from the forcing row with u <= 1;
    # shedding beyond demand is pure cost.  All-finite boxes make the
    # PDHG dual objective a valid Lagrangian bound at ANY iterate
    # (spopt.valid_Ebound), so Lagrangian spokes need no certificates.
    ub = np.full((S, N), INF, dtype=dtype)
    ub[:, iu:isu] = 1.0
    ub[:, isu:ip] = 1.0
    for g in range(G):
        ub[:, ip + g * H: ip + (g + 1) * H] = Pmax[g]
    ub[:, ish:] = 2.0 * dem.max()

    c = np.zeros((S, N), dtype=dtype)
    for g in range(G):
        c[:, iu + g * H: iu + (g + 1) * H] = cNL[g]
        c[:, isu + g * H: isu + (g + 1) * H] = cSU[g]
        c[:, ip + g * H: ip + (g + 1) * H] = cV[g]
    c[:, ish:] = _PEN

    integer_mask = np.zeros((S, N), dtype=bool)
    integer_mask[:, iu:isu] = True

    stage_cost_c = np.zeros((2, S, N), dtype=dtype)
    stage_cost_c[0, :, : 2 * G * H] = c[:, : 2 * G * H]
    stage_cost_c[1, :, 2 * G * H:] = c[:, 2 * G * H:]

    nonant_idx = np.arange(2 * G * H, dtype=np.int32)
    var_names = (
        tuple(f"u[{g},{h}]" for g in range(G) for h in range(H))
        + tuple(f"su[{g},{h}]" for g in range(G) for h in range(H))
        + tuple(f"p[{g},{h}]" for g in range(G) for h in range(H))
        + tuple(f"shed[{h}]" for h in range(H)))
    tree = TreeInfo(
        node_of=np.zeros((S, 2 * G * H), np.int32),
        prob=np.full((S,), 1.0 / S, dtype=dtype),
        num_nodes=1,
        stage_of=(1,) * (2 * G * H),
        nonant_names=var_names[: 2 * G * H],
        scen_names=tuple(f"Scenario{int(i)+1}" for i in scens),
    )
    return ScenarioBatch(
        c=c, qdiag=np.zeros((S, N), dtype=dtype),
        A=A, row_lo=row_lo, row_hi=row_hi, lb=lb, ub=ub,
        obj_const=np.zeros((S,), dtype=dtype),
        nonant_idx=nonant_idx, integer_mask=integer_mask,
        tree=tree, stage_cost_c=stage_cost_c, var_names=var_names,
        model_meta={"uc_H": H, "uc_G": G,
                    "uc_ut": ut, "uc_dt": dt_,
                    "uc_min_up_down": bool(min_up_down),
                    "uc_reserve_factor": float(reserve_factor)})


def scenario_block(indices, num_scens=None, **kwargs):
    """Build exactly the scenarios named by `indices` (global ids) —
    the streaming block contract.  num_scens is accepted and ignored
    (the universe size lives on the ScenarioSource); all other kwargs
    are build_batch's."""
    idx = np.asarray(indices, dtype=np.int64)
    return build_batch(idx.size, scens=idx, **kwargs)


def scenario_source(num_scens, cfg=None):
    """streaming.ScenarioSource over the UC wind universe.  The
    constraint matrix is scenario-independent (shared_A), so every
    streamed block reuses the one shared (1, M, N) matrix — and the
    driver's shared-A fast path rescales row bounds instead of
    re-running Ruiz per block."""
    cfg = dict(cfg or {})
    kw = {k: cfg[k] for k in
          ("H", "n_units", "seed", "fleet_multiplier", "shared_A",
           "min_up_down", "reserve_factor") if k in cfg}
    from ..streaming import GeneratorSource
    return GeneratorSource(
        "uc", int(num_scens),
        lambda idx: scenario_block(idx, **kw),
        name_fn=lambda i: f"Scenario{i+1}")


def export_corpus(path, num_scens, shard_width=64, cfg=None):
    """Persist the UC wind universe as a durable shard corpus
    (streaming/store.py).  shared_A blocks stay shared on disk — the
    corpus stores one (1, M, N) matrix per shard, never a per-scenario
    replica.  Returns the corpus path."""
    from ..streaming import write_corpus
    return write_corpus(
        scenario_source(num_scens, cfg), path, shard_width,
        meta={"name_format": "Scenario{i1}"})


def scenario_names_creator(num_scens, start=0):
    return [f"Scenario{i+1}" for i in range(start, start + num_scens)]


def repair_min_up_down(u, ut, dt_, H):
    """Repair a (G*H,) rounded commitment to honor per-unit min-up/
    min-down windows: every on-run is extended forward to >= UT hours,
    then every off-run to >= DT hours (extension over-commits — the
    cheap direction; shedding at the penalty price is the expensive
    one).  Idempotent on window-feasible commitments."""
    u = np.asarray(u, float).copy()
    G = u.size // H
    for g in range(G):
        blk = u[g * H:(g + 1) * H]
        # extend on-runs to UT
        h = 0
        while h < H:
            if blk[h] == 1.0 and (h == 0 or blk[h - 1] == 0.0):
                run = 0
                while h + run < H and blk[h + run] == 1.0:
                    run += 1
                need = int(ut[g]) - run
                for k in range(h + run, min(h + run + max(need, 0), H)):
                    blk[k] = 1.0
                h += max(run, 1)
            else:
                h += 1
        # merge off-runs shorter than DT (turn them on)
        h = 0
        while h < H:
            if blk[h] == 0.0 and h > 0 and blk[h - 1] == 1.0:
                run = 0
                while h + run < H and blk[h + run] == 0.0:
                    run += 1
                ends_inside = h + run < H      # off-run then back on
                if ends_inside and run < int(dt_[g]):
                    blk[h:h + run] = 1.0
                h += max(run, 1)
            else:
                h += 1
        u[g * H:(g + 1) * H] = blk
    return u


def commitment_candidate(batch, xbar_row, threshold=0.5):
    """Integer-feasible first-stage candidate from a consensus vector:
    commit unit-hours whose consensus weight exceeds `threshold`, then
    DERIVE the startup s from the rounded u (s_h = max(0,
    u_h - u_{h-1})) — fixing s at its averaged value alongside a
    rounded u violates the startup-definition rows whenever rounding
    flips a commitment.

    Round-to-nearest (threshold 0.5) is usually terrible for UC: a
    0.4-committed unit rounds OFF and its lost capacity is bought back
    as load shedding at the penalty price.  Thresholds below 0.5
    over-commit (cost: no-load + startup) instead of shedding; use
    `commitment_candidates` to screen several thresholds in one
    batched evaluation."""
    vals = np.asarray(xbar_row, float).copy()
    K = vals.size
    GH = K // 2
    u = (np.clip(vals[:GH], 0, 1) > threshold).astype(float)
    # when the batch carries min-up/min-down rows, a bare rounding is
    # usually window-infeasible; repair by extending runs (over-commit
    # — the cheap direction vs shedding).  The window tables come from
    # the batch's own metadata, i.e. exactly what A encodes.
    meta = batch.model_meta or {}
    if meta.get("uc_min_up_down"):
        u = repair_min_up_down(u, np.asarray(meta["uc_ut"]),
                               np.asarray(meta["uc_dt"]),
                               int(meta["uc_H"]))
    return np.concatenate([u, _derive_startups(batch, u)])


def commitment_candidates(batch, xbar_row,
                          thresholds=(0.02, 0.1, 0.25, 0.5, 0.75)):
    """(k, K) stack of threshold-commitment candidates — feed to
    SPOpt.evaluate_candidates for one-launch speculative screening
    (SURVEY.md §2.10)."""
    return np.stack([commitment_candidate(batch, xbar_row, t)
                     for t in thresholds])


def _derive_startups(batch, u):
    GH = u.size
    H = _infer_H(batch, GH)
    G = GH // H
    s = np.zeros_like(u)
    for g in range(G):
        blk = slice(g * H, (g + 1) * H)
        ub_ = u[blk]
        s[blk][0] = ub_[0]
        s[blk][1:] = np.maximum(0.0, ub_[1:] - ub_[:-1])
    return s


def one_opt_commitment(evaluator, batch, candidate, max_sweeps=4,
                       flip_slots=None, chunk=64, screen_eps=None,
                       screen_cap=None, verify_k=3):
    """Batched 1-opt local search on the commitment: each sweep
    evaluates single unit-hour flips of the incumbent commitment in
    stacked launches (up to `chunk` candidates x S scenarios each,
    SPOpt.evaluate_candidates) and keeps the best improving flip.
    Returns (candidate, value).  This is how the reference's slam/xhat
    heuristics earn UC incumbents near the MIP optimum without a MIP
    solver in the loop.

    flip_slots: restrict the search to these u-slot indices (the
    default sweeps ALL slots — measured at S=50 vs a MIP oracle, the
    wrongly-committed slots are usually NOT the fractional-consensus
    ones, so restricted sweeps stall at the threshold incumbent).

    chunk: flips per stacked launch.  A reference-scale fleet has
    GH ~ 500 slots; one (GH*S)-scenario stack of the (1536-var,
    2500-row) subproblem arrays would run to tens of GB, so sweeps
    launch bounded chunks instead.

    screen_eps / screen_cap: when either is set, sweep launches run
    as a cheap RANKING pass (loose tolerance, bounded PDHG
    iterations) and flips are certified in screened rank order with
    the accurate evaluator, keeping the first genuinely improving one
    — the same two-stage screen/verify protocol as opt/mip.py's
    refinement.  Per sweep at most `verify_k` ranks are certified
    (3*verify_k on a would-be-terminating FULL sweep), so the
    termination criterion under screening is "no flip among the top
    3*verify_k screened ranks of a full sweep improves" — a bounded
    relaxation of the exhaustive criterion, traded for ~10x cheaper
    launches.  Screening also enables full/restricted sweep
    alternation: a FULL sweep ranks every slot (len/chunk launches);
    later sweeps re-rank only the top-`chunk` hot slots (1 launch),
    and any stall triggers a full refresh, so only a full sweep can
    terminate the search.  At reference scale (504 slots x 8 sweeps
    x S=1000) full-accuracy sweeps are ~64 launches of a
    64k-scenario stack; screening is what makes the full-slot search
    affordable on one chip.  Without screen_*, behavior is the
    original exhaustive protocol: every sweep scans all flip_slots
    at full accuracy and only the argmin flip is certified."""
    cand = np.asarray(candidate, float).copy()
    GH = cand.size // 2
    if flip_slots is None:
        flip_slots = np.arange(GH)
    flip_slots = np.asarray(flip_slots, int)
    screening = screen_eps is not None or screen_cap is not None
    if screening and screen_eps is None:
        # cap-only screening: a capped solve can't reach the
        # full-accuracy tolerance, so derive a loose one from the
        # evaluator's eps instead of screening everything infeasible
        screen_eps = 10 * float(np.asarray(evaluator.solver_eps))
    # a capped/loose screen can't reach the full-accuracy residual
    # tolerance — widen the feasibility screen; certify restores rigor
    screen_tol = 10 * float(screen_eps) if screening else None
    # every launch is padded to one canonical candidate count, so the
    # evaluator's one-live-stack cache and the jit shape survive
    # across chunks, sweeps, and full/restricted alternation
    kfix = min(chunk, len(flip_slots)) or 1
    val, feas = evaluator.evaluate_xhat(cand)
    if not feas:
        return cand, np.inf
    hot_slots = None
    # a failed RESTRICTED sweep schedules a full-sweep refresh; that
    # refresh runs outside the max_sweeps budget, so the search always
    # ends on a terminating full sweep (accept -> budget resumes;
    # reject -> break), never on a stalled restricted sweep — the
    # documented termination criterion even at small max_sweeps
    sweeps_done = 0
    pending_refresh = False
    while sweeps_done < max_sweeps or pending_refresh:
        if pending_refresh:
            pending_refresh = False
        else:
            sweeps_done += 1
        full = hot_slots is None
        slots = flip_slots if full else hot_slots
        flips = []
        for j in slots:
            u = cand[:GH].copy()
            u[j] = 1.0 - u[j]
            flips.append(np.concatenate([u, _derive_startups(batch, u)]))
        if not flips:
            break
        objs = np.empty(len(flips))
        feas_m = np.zeros(len(flips), bool)
        for lo in range(0, len(flips), kfix):
            sl = slice(lo, min(lo + kfix, len(flips)))
            block = flips[sl]
            k = len(block)
            if k < kfix:
                block = block + [cand] * (kfix - k)
            o, f = evaluator.evaluate_candidates(
                np.stack(block), eps=screen_eps, iters_cap=screen_cap,
                tol=screen_tol)
            objs[sl], feas_m[sl] = o[:k], f[:k]
        ok = np.flatnonzero(feas_m)
        if full and screening and len(flip_slots) > chunk:
            # hot set = best-ranked feasible slots; spuriously-
            # infeasible ones (screen stragglers) fill the tail so
            # restricted sweeps can still revisit them.  (When all
            # slots fit one launch, a "restricted" sweep would be the
            # same launch — stay in all-full mode.)
            bad = np.setdiff1d(np.arange(len(flips)), ok)
            order_all = np.concatenate([ok[np.argsort(objs[ok])], bad])
            hot_slots = np.asarray(slots)[order_all[:chunk]]
        if ok.size == 0:
            if full:
                break
            hot_slots = None
            pending_refresh = True
            continue
        # certify candidates in screened rank order with the accurate
        # evaluator; keep the first genuine improvement.  A full sweep
        # about to terminate digs deeper (3x) before giving up.
        order = ok[np.argsort(objs[ok])]
        if screening:
            tries = order[:(3 * verify_k if full else verify_k)]
        else:
            tries = order[:1]
        accepted = False
        for j in map(int, tries):
            v2, f2 = evaluator.evaluate_xhat(flips[j])
            if f2 and v2 < val - 1e-7 * (1 + abs(val)):
                cand, val = flips[j], v2
                accepted = True
                break
        if not accepted:
            if full:
                break
            hot_slots = None   # refresh with a full sweep next
            pending_refresh = True
    return cand, val


def _infer_H(batch, GH):
    # nonant names are u[g,h] blocks, unit-major; recover H from names
    names = batch.tree.nonant_names
    hs = [int(n.split(",")[1].rstrip("]")) for n in names[:GH]
          if n.startswith("u[")]
    return (max(hs) + 1) if hs else GH


def inparser_adder(cfg):
    cfg.num_scens_required()
    cfg.add_to_config("uc_hours", description="commitment horizon",
                      domain=int, default=6)
    cfg.add_to_config("uc_fleet_multiplier",
                      description="replicate the 3-unit fleet this "
                      "many times (jittered)", domain=int, default=1)
    cfg.add_to_config("uc_min_up_down",
                      description="enforce per-unit minimum up/down "
                      "times", domain=bool, default=False)
    cfg.add_to_config("uc_reserve_factor",
                      description="spinning-reserve requirement as a "
                      "fraction of hourly demand (0 disables)",
                      domain=float, default=0.0)


def kw_creator(options):
    return {"H": options.get("uc_hours", 6),
            "fleet_multiplier": options.get("uc_fleet_multiplier", 1),
            "min_up_down": options.get("uc_min_up_down", False),
            "reserve_factor": options.get("uc_reserve_factor", 0.0)}

"""uc_wecc — lowerer for the reference's ACTUAL stochastic UC data
(reference: examples/uc/{3,5,10,25,50,100}scenarios_r1/ — the
WECC-240 instances of Staid et al with scaled ISO-NE demand;
examples/uc/uc_funcs.py loads them through egret's prescient dat
parser and builds egret's tight UC MIP with UnitOn as the ONLY nonant,
ScenarioStructure.dat StageVariables).

This module parses the same .dat files directly (no Pyomo/egret) and
lowers them into a shared-A ScenarioBatch: the scenario uncertainty is
the hourly DEMAND (Node<k>.dat), which lives entirely in the balance /
reserve ROW BOUNDS, so one (M, N) constraint matrix serves all
scenarios (ir.ScenarioBatch.shared_A) and every batched matvec is a
real matmul on the MXU.

Formulation (3-bin LP/MIP, Rajan-Takriti + Carrion-Arroyo pieces):
  vars  u,v,w in [0,1]^(G,H)  commitment / startup / shutdown
        suc >= 0              startup-cost epigraph (per g,h)
        p in [0, Pmax]        total generation
        seg_{g,k,h}           piecewise production segments,
                              0 <= seg <= width_gk
        shed_h, over_h >= 0   load mismatch slacks (LoadMismatchPenalty)
  rows  p <= Pmax u ; p >= Pmin u
        p = point0_g * u + sum_k seg_k          (piecewise adapter)
        sum_g p + shed - over = demand^s_h      (balance; per-scen rhs)
        u_t - u_{t-1} = v_t - w_t               (3-bin logic; T0 rhs)
        sum_{i in (t-UT, t]} v_i <= u_t         (min-up, RT form)
        sum_{i in (t-DT, t]} w_i <= 1 - u_t     (min-down)
        p_t - p_{t-1} <= RU u_{t-1} + SUramp v_t   (+ T0 row)
        p_{t-1} - p_t <= RD u_t + SDramp w_t       (+ T0 row)
        sum_g Pmax_g u_gh >= demand^s_h + R_h   (reserve, capacity form)
        suc >= C_l (v_t - sum_{n<lag_l} w_{t-n} - hist)  (startup tiers)
  cost  sum_gh [ suc + value0_g u + sum_k slope_gk seg ]
        + pen * sum_h (shed + over)
T0 conditions (UnitOnT0State / PowerGeneratedT0) enter as row bounds
and as initial commitment fixings (a unit on for tau < UT hours stays
on, off for tau < DT stays off — lb/ub on the first hours).

Deliberate divergences from egret's tight model (documented, small):
quick-start units earn no reserve credit while off (our reserve is
committed-capacity only; R_h is ~2.5% of demand in these instances),
and the piecewise production cost uses the instance's
CostPiecewisePoints/Values verbatim (convex segments).
"""

from __future__ import annotations

import os
import re

import numpy as np

from ..ir import ScenarioBatch, Static, TreeInfo

INF = float("inf")
# default instance lookup root; override for checkouts elsewhere
REFERENCE_DIR = os.environ.get("MPISPPY_TPU_UC_DATA",
                               "/root/reference/examples/uc")


# --------------------------------------------------------------------------
# .dat parsing (AMPL-format subset the instances use)
# --------------------------------------------------------------------------

def parse_root(path):
    """Parse RootNode.dat -> dict of fleet/system parameters."""
    txt = open(path).read()
    out = {}
    m = re.search(r"param NumTimePeriods := (\d+)", txt)
    out["H"] = int(m.group(1))
    m = re.search(r"param LoadMismatchPenalty := ([0-9.eE+-]+)", txt)
    out["penalty"] = float(m.group(1)) if m else 1e6
    gens = re.search(r"set ThermalGenerators := ([^;]+);", txt)
    out["gens"] = gens.group(1).split()
    qs = re.search(r"set QuickStartGenerators := ([^;]+);", txt)
    out["quickstart"] = set(qs.group(1).split()) if qs else set()

    tab = re.search(
        r"param: PowerGeneratedT0 UnitOnT0State MinimumPowerOutput "
        r"MaximumPowerOutput MinimumUpTime MinimumDownTime "
        r"NominalRampUpLimit NominalRampDownLimit StartupRampLimit "
        r"ShutdownRampLimit FuelCost :=\s*([^;]+);", txt)
    rows = {}
    for line in tab.group(1).strip().splitlines():
        f = line.split()
        rows[f[0]] = [float(x) for x in f[1:]]
    out["table"] = rows

    rr = re.search(r"param: ReserveRequirement :=\s*([^;]+);", txt)
    res = np.zeros(out["H"])
    if rr:
        for line in rr.group(1).strip().splitlines():
            h, v = line.split()
            res[int(h) - 1] = float(v)
    out["reserve"] = res

    def curves(name):
        d = {}
        for g, v in re.findall(
                rf"set {name}\[([^\]]+)\] := ([^;]*);", txt):
            d[g] = [float(x) for x in v.split()]
        return d

    out["pw_points"] = curves("CostPiecewisePoints")
    out["pw_values"] = curves("CostPiecewiseValues")
    out["su_costs"] = curves("StartupCosts")
    out["su_lags"] = curves("StartupLags")
    return out


def parse_demand(path, H):
    txt = open(path).read()
    m = re.search(r"param: Demand :=\s*([^;]+);", txt)
    d = np.zeros(H)
    for line in m.group(1).strip().splitlines():
        _, h, v = line.split()
        d[int(h) - 1] = float(v)
    return d


def available_instances(base_dir=REFERENCE_DIR):
    out = {}
    if not os.path.isdir(base_dir):
        return out
    for nm in os.listdir(base_dir):
        m = re.match(r"(\d+)scenarios_r1$", nm)
        if m:
            out[int(m.group(1))] = os.path.join(base_dir, nm)
    return out


# --------------------------------------------------------------------------
# lowering
# --------------------------------------------------------------------------

def build_batch(data_dir=None, num_scens=3, hours=None, max_units=None,
                reserve=True, dtype=np.float64):
    """Lower a reference UC instance directory into a shared-A batch.

    data_dir: an instance dir (contains RootNode.dat + Node<k>.dat);
    default picks the smallest reference instance with >= num_scens
    scenarios.  hours / max_units truncate the horizon / fleet (the
    full 85-unit 48 h system lowers to a ~6 GB f32 shared matrix —
    TPU-sized; CPU test tiers trim).  Truncating hours also scales
    each unit's min-up/down and startup lags down proportionally so
    the shortened instance keeps binding commitment dynamics."""
    if data_dir is None:
        inst = available_instances()
        cands = sorted(s for s in inst if s >= num_scens)
        if not cands:
            raise FileNotFoundError(
                f"no reference UC instance with >= {num_scens} "
                f"scenarios under {REFERENCE_DIR}")
        data_dir = inst[cands[0]]
    root = parse_root(os.path.join(data_dir, "RootNode.dat"))
    H_full = root["H"]
    H = int(hours or H_full)
    scale = H / H_full
    gens = root["gens"]
    if max_units:
        gens = gens[: int(max_units)]
    G = len(gens)
    S = int(num_scens)

    demand = np.stack([
        parse_demand(os.path.join(data_dir, f"Node{k + 1}.dat"),
                     H_full)[:H]
        for k in range(S)])                                  # (S, H)
    if max_units:
        # trim demand to the trimmed fleet's capacity scale so the
        # instance stays feasible-without-shed at comparable margins
        cap_full = sum(root["table"][g][3] for g in root["gens"])
        cap_trim = sum(root["table"][g][3] for g in gens)
        demand = demand * (cap_trim / cap_full)
    reserve_req = root["reserve"][:H] if reserve else np.zeros(H)

    tab = np.array([root["table"][g] for g in gens])
    P0, T0, Pmin, Pmax = tab[:, 0], tab[:, 1], tab[:, 2], tab[:, 3]
    UT = np.maximum(1, np.round(tab[:, 4] * scale)).astype(int)
    DT = np.maximum(1, np.round(tab[:, 5] * scale)).astype(int)
    RU, RD, SUr, SDr = tab[:, 6], tab[:, 7], tab[:, 8], tab[:, 9]
    on0 = (T0 > 0).astype(float)
    # remaining initial up/down obligation under the scaled windows
    init_hold_on = np.maximum(
        0, UT - np.round(np.maximum(T0, 0) * scale)).astype(int) \
        * (T0 > 0)
    init_hold_off = np.maximum(
        0, DT - np.round(np.maximum(-T0, 0) * scale)).astype(int) \
        * (T0 < 0)

    pw_pts = [np.asarray(root["pw_points"].get(g, [Pmin[i], Pmax[i]]))
              for i, g in enumerate(gens)]
    pw_val = [np.asarray(root["pw_values"].get(g, [0.0, 0.0]))
              for i, g in enumerate(gens)]
    nseg = np.array([max(len(p) - 1, 0) for p in pw_pts])
    seg_off = np.concatenate([[0], np.cumsum(nseg * H)])[:-1]
    su_costs = [np.asarray(root["su_costs"].get(g, [0.0]))
                for g in gens]
    su_lags = [np.maximum(1, np.round(np.asarray(
        root["su_lags"].get(g, [1])) * scale)).astype(int)
        for g in gens]

    # ---- layout ----------------------------------------------------------
    GH = G * H
    iu, iv, iw, isuc, ip = 0, GH, 2 * GH, 3 * GH, 4 * GH
    iseg = 5 * GH
    nsegtot = int((nseg * H).sum())
    ish = iseg + nsegtot
    iov = ish + H
    N = iov + H

    def uidx(g, h):
        return iu + g * H + h

    def vidx(g, h):
        return iv + g * H + h

    def widx(g, h):
        return iw + g * H + h

    def sucidx(g, h):
        return isuc + g * H + h

    def pidx(g, h):
        return ip + g * H + h

    def segidx(g, k, h):
        return iseg + seg_off[g] + k * H + h

    n_tier = int(sum(max(len(c) - 1, 0) for c in su_costs))
    M = (3 * GH            # pmax, pmin, piecewise adapter
         + H               # balance
         + GH              # 3-bin logic
         + 2 * GH          # min-up / min-down
         + 2 * GH          # ramps (incl. T0 rows)
         + (H if reserve else 0)
         + GH              # startup tier 1
         + n_tier * H)     # deeper startup tiers

    A = np.zeros((1, M, N), dtype=dtype)
    row_lo = np.full((S, M), -INF, dtype=dtype)
    row_hi = np.full((S, M), INF, dtype=dtype)
    r = 0
    for g in range(G):
        for h in range(H):
            A[0, r, pidx(g, h)] = 1.0
            A[0, r, uidx(g, h)] = -Pmax[g]
            row_hi[:, r] = 0.0
            r += 1
    for g in range(G):
        for h in range(H):
            A[0, r, pidx(g, h)] = 1.0
            A[0, r, uidx(g, h)] = -Pmin[g]
            row_lo[:, r] = 0.0
            r += 1
    for g in range(G):           # p = point0 u + sum_k seg
        for h in range(H):
            A[0, r, pidx(g, h)] = 1.0
            A[0, r, uidx(g, h)] = -pw_pts[g][0]
            for k in range(nseg[g]):
                A[0, r, segidx(g, k, h)] = -1.0
            row_lo[:, r] = 0.0
            row_hi[:, r] = 0.0
            r += 1
    for h in range(H):           # balance (per-scenario rhs)
        for g in range(G):
            A[0, r, pidx(g, h)] = 1.0
        A[0, r, ish + h] = 1.0
        A[0, r, iov + h] = -1.0
        row_lo[:, r] = demand[:, h]
        row_hi[:, r] = demand[:, h]
        r += 1
    for g in range(G):           # u_t - u_{t-1} - v_t + w_t = [T0]
        for h in range(H):
            A[0, r, uidx(g, h)] = 1.0
            A[0, r, vidx(g, h)] = -1.0
            A[0, r, widx(g, h)] = 1.0
            if h > 0:
                A[0, r, uidx(g, h - 1)] = -1.0
                rhs = 0.0
            else:
                rhs = on0[g]
            row_lo[:, r] = rhs
            row_hi[:, r] = rhs
            r += 1
    for g in range(G):           # min-up (Rajan-Takriti)
        for h in range(H):
            for i in range(max(0, h - UT[g] + 1), h + 1):
                A[0, r, vidx(g, i)] = 1.0
            A[0, r, uidx(g, h)] = -1.0
            row_hi[:, r] = 0.0
            r += 1
    for g in range(G):           # min-down
        for h in range(H):
            for i in range(max(0, h - DT[g] + 1), h + 1):
                A[0, r, widx(g, i)] = 1.0
            A[0, r, uidx(g, h)] = 1.0
            row_hi[:, r] = 1.0
            r += 1
    for g in range(G):           # ramp up (h=0 row uses T0 power)
        for h in range(H):
            A[0, r, pidx(g, h)] = 1.0
            A[0, r, vidx(g, h)] = -SUr[g]
            if h > 0:
                A[0, r, pidx(g, h - 1)] = -1.0
                A[0, r, uidx(g, h - 1)] = -RU[g]
                row_hi[:, r] = 0.0
            else:
                row_hi[:, r] = P0[g] + RU[g] * on0[g]
            r += 1
    for g in range(G):           # ramp down
        for h in range(H):
            A[0, r, pidx(g, h)] = -1.0
            A[0, r, uidx(g, h)] = -RD[g]
            A[0, r, widx(g, h)] = -SDr[g]
            if h > 0:
                A[0, r, pidx(g, h - 1)] = 1.0
                row_hi[:, r] = 0.0
            else:
                row_hi[:, r] = -P0[g]
            r += 1
    if reserve:                  # committed capacity >= demand + R
        for h in range(H):
            for g in range(G):
                A[0, r, uidx(g, h)] = Pmax[g]
            row_lo[:, r] = demand[:, h] + reserve_req[h]
            r += 1
    for g in range(G):           # startup cost tier 1 (hottest)
        c1 = su_costs[g][0]
        for h in range(H):
            A[0, r, sucidx(g, h)] = 1.0
            A[0, r, vidx(g, h)] = -c1
            row_lo[:, r] = 0.0
            r += 1
    for g in range(G):           # deeper tiers: suc >= C_l (v_t -
        for li in range(1, len(su_costs[g])):   # recent shutdowns)
            cl = su_costs[g][li]
            lag = int(su_lags[g][li])
            for h in range(H):
                A[0, r, sucidx(g, h)] = 1.0
                A[0, r, vidx(g, h)] = -cl
                hist = 0.0
                for n in range(1, lag):
                    if h - n >= 0:
                        A[0, r, widx(g, h - n)] = cl
                    elif T0[g] < 0 and (n - h) == round(
                            -T0[g] * scale) + 1:
                        hist += cl   # pre-horizon shutdown credit
                row_lo[:, r] = -hist
                r += 1
    assert r == M, (r, M)

    lb = np.zeros((S, N), dtype=dtype)
    ub = np.full((S, N), INF, dtype=dtype)
    ub[:, iu:ip] = 1.0                  # u, v, w boxes
    # suc is bounded by the coldest startup cost (implied; keeps every
    # box finite so the dual objective is a valid Lagrangian bound at
    # any iterate — spopt.valid_Ebound)
    for g in range(G):
        ub[:, sucidx(g, 0):sucidx(g, 0) + H] = float(su_costs[g][-1]) \
            + 1.0
        ub[:, pidx(g, 0):pidx(g, 0) + H] = Pmax[g]
        for k in range(nseg[g]):
            ub[:, segidx(g, k, 0):segidx(g, k, 0) + H] = (
                pw_pts[g][k + 1] - pw_pts[g][k])
    dmax = float(demand.max())
    ub[:, ish:] = 2.0 * dmax
    # initial commitment obligations from T0 state
    for g in range(G):
        for h in range(int(init_hold_on[g])):
            lb[:, uidx(g, h)] = 1.0
        for h in range(int(init_hold_off[g])):
            ub[:, uidx(g, h)] = 0.0

    c = np.zeros((S, N), dtype=dtype)
    c[:, isuc:ip] = 1.0                 # epigraph carries startup cost
    for g in range(G):
        c[:, uidx(g, 0):uidx(g, 0) + H] = pw_val[g][0]
        for k in range(nseg[g]):
            width = pw_pts[g][k + 1] - pw_pts[g][k]
            slope = ((pw_val[g][k + 1] - pw_val[g][k]) / width
                     if width > 0 else 0.0)
            c[:, segidx(g, k, 0):segidx(g, k, 0) + H] = slope
    c[:, ish:] = root["penalty"]

    integer_mask = np.zeros((S, N), dtype=bool)
    integer_mask[:, iu:ip] = True       # u, v, w

    nonant_idx = np.arange(iu, iu + GH, dtype=np.int32)   # UnitOn only
    var_names = (
        tuple(f"UnitOn[{g},{h}]" for g in gens for h in range(H))
        + tuple(f"UnitStart[{g},{h}]" for g in gens for h in range(H))
        + tuple(f"UnitStop[{g},{h}]" for g in gens for h in range(H))
        + tuple(f"StartupCost[{g},{h}]" for g in gens for h in range(H))
        + tuple(f"PowerGenerated[{g},{h}]" for g in gens
                for h in range(H))
        + tuple(f"seg{i}" for i in range(nsegtot))
        + tuple(f"LoadShed[{h}]" for h in range(H))
        + tuple(f"OverGen[{h}]" for h in range(H)))
    tree = TreeInfo(
        node_of=np.zeros((S, GH), np.int32),
        prob=np.full((S,), 1.0 / S, dtype=dtype),
        num_nodes=1,
        stage_of=(1,) * GH,
        nonant_names=var_names[:GH],
        scen_names=tuple(f"Scenario{k + 1}" for k in range(S)),
    )
    return ScenarioBatch(
        c=c, qdiag=np.zeros((S, N), dtype=dtype),
        A=A, row_lo=row_lo, row_hi=row_hi, lb=lb, ub=ub,
        obj_const=np.zeros((S,), dtype=dtype),
        nonant_idx=nonant_idx,
        integer_mask=integer_mask,
        tree=tree,
        var_names=var_names,
        model_meta={"G": G, "H": H,
                    "gens": Static(tuple(gens)),
                    "data_dir": Static(data_dir)},
    )


# ---- amalgamator-contract helpers ----------------------------------------

def scenario_names_creator(num_scens, start=0):
    return [f"Scenario{i + 1}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    cfg.num_scens_required()
    cfg.add_to_config("uc_data_dir",
                      description="reference UC instance directory",
                      domain=str, default=None)
    cfg.add_to_config("uc_hours", description="truncate horizon",
                      domain=int, default=None)
    cfg.add_to_config("uc_max_units", description="truncate fleet",
                      domain=int, default=None)


def kw_creator(options):
    return {"data_dir": options.get("uc_data_dir"),
            "num_scens": options.get("num_scens"),
            "hours": options.get("uc_hours"),
            "max_units": options.get("uc_max_units")}


def batch_creator(cfg_or_kwargs, num_scens=None):
    kw = dict(cfg_or_kwargs)
    n = num_scens or kw.pop("num_scens", None)
    kw.pop("num_scens", None)
    return build_batch(num_scens=n, **kw)


def scenario_denouement(rank, scenario_name, scenario):
    pass

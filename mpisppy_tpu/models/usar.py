"""USAR — urban search and rescue team deployment (reference:
examples/usar/abstract.py, after Chen & Miller-Hooks 2012).

Choose which depots to activate (first stage, binary, nonant), then
route rescue teams from depots to incident sites over a discrete time
horizon; a site rescue saves its (scenario-random) lives when a team
ARRIVES.  Teams travel depot->site and site->site with time-dependent
travel times, each site is serviced at most once, and a started rescue
occupies the team for the site's rescue time.  Objective: maximize
expected lives saved (minimize the negative).

Per scenario, T times, D depots, G sites (all binary; reference
abstract.py:52-65):
    act[d]                   activate depot d          (nonant)
    dd[t, d, g]              team departs depot d at t toward site g
    sd[t, g1, g2]            team departs g1 at t toward g2 (g1 != g2)
    st[t, g]                 team stays at g during t
    ita[t, tau, g]           a team is tau steps from arriving at g

Rows (reference abstract.py:67-131):
    sum_d act[d] == num_active_depots
    dd[t, d, g] <= act[d]
    sum_{d,g} dd[t, d, g] <= inflow[t]
    ita[t, tau, g] == ita[t-1, tau+1, g]
                      + sum_{d: travel_dg(t)==tau} dd[t, d, g]
                      + sum_{g': travel_g'g(t)==tau} sd[t, g', g]
    ita[t, 0, g] + st[t-1, g] == sum_{g'} sd[t, g, g'] + st[t, g]
    sum_t ita[t, 0, g] <= 1
    st[t, g] >= (1/T) * sum_{t'<=t, t'+rescue > t} ita[t', 0, g]

Data is generated like the reference's generate_data.py: uniform
coordinates on the unit square, travel time = ceil(distance / speed)
(>= 1), lives ~ 1 + Poisson(2) per site-time, constant rescue times
and depot inflows; per-scenario randomness re-draws the lives map.
"""

from __future__ import annotations

import numpy as np

from ..ir import ScenarioBatch, TreeInfo

INF = float("inf")


def _coords(rng, n):
    return rng.rand(n, 2)


def _travel_times(c1, c2, speed):
    d = np.linalg.norm(c1[:, None, :] - c2[None, :, :], axis=2)
    return np.maximum(1, np.ceil(d / speed)).astype(int)


def build_batch(num_scens, time_horizon=6, num_depots=2, num_sites=4,
                num_active_depots=1, rescue_time=1, depot_inflow=2,
                travel_speed=0.5, seed=1234,
                dtype=np.float64) -> ScenarioBatch:
    T, D, G, S = time_horizon, num_depots, num_sites, num_scens
    rng = np.random.RandomState(seed)
    dep_xy = _coords(rng, D)
    site_xy = _coords(rng, G)
    tt_dg = _travel_times(dep_xy, site_xy, travel_speed)    # (D, G)
    tt_gg = _travel_times(site_xy, site_xy, travel_speed)   # (G, G)

    # lives to be saved: scenario-random, decaying over time (later
    # arrival saves fewer) — the reference draws per (time, site)
    lives = np.zeros((S, T, G))
    for s in range(S):
        r = np.random.RandomState(seed + 7919 * (s + 1))
        base = 1 + r.poisson(2.0, size=G)
        decay = np.clip(1.0 - 0.1 * np.arange(T), 0.1, None)
        lives[s] = np.round(base[None, :] * decay[:, None])

    # variable layout
    iact = 0
    idd = D                                   # dd[t, d, g]
    n_dd = T * D * G
    isd = idd + n_dd                          # sd[t, g1, g2]
    n_sd = T * G * G
    ist = isd + n_sd                          # st[t, g]
    n_st = T * G
    iita = ist + n_st                         # ita[t, tau, g]
    n_ita = T * T * G
    N = iita + n_ita

    def v_dd(t, d, g):
        return idd + (t * D + d) * G + g

    def v_sd(t, g1, g2):
        return isd + (t * G + g1) * G + g2

    def v_st(t, g):
        return ist + t * G + g

    def v_ita(t, tau, g):
        return iita + (t * T + tau) * G + g

    rows = []       # (coef dict, lo, hi) built per scenario-shared part

    def add(coefs, lo, hi):
        rows.append((coefs, lo, hi))

    add({iact + d: 1.0 for d in range(D)},
        float(num_active_depots), float(num_active_depots))
    for t in range(T):
        for d in range(D):
            for g in range(G):
                add({v_dd(t, d, g): 1.0, iact + d: -1.0}, -INF, 0.0)
    for t in range(T):
        add({v_dd(t, d, g): 1.0 for d in range(D) for g in range(G)},
            -INF, float(depot_inflow))
    for t in range(T):
        for tau in range(T):
            for g in range(G):
                coefs = {v_ita(t, tau, g): 1.0}
                if t > 0 and tau + 1 < T:
                    coefs[v_ita(t - 1, tau + 1, g)] = \
                        coefs.get(v_ita(t - 1, tau + 1, g), 0.0) - 1.0
                for d in range(D):
                    if tt_dg[d, g] == tau:
                        coefs[v_dd(t, d, g)] = \
                            coefs.get(v_dd(t, d, g), 0.0) - 1.0
                for g2 in range(G):
                    if g2 != g and tt_gg[g2, g] == tau:
                        coefs[v_sd(t, g2, g)] = \
                            coefs.get(v_sd(t, g2, g), 0.0) - 1.0
                add(coefs, 0.0, 0.0)
    for t in range(T):
        for g in range(G):
            coefs = {v_ita(t, 0, g): 1.0, v_st(t, g): -1.0}
            if t > 0:
                coefs[v_st(t - 1, g)] = 1.0
            for g2 in range(G):
                if g2 != g:
                    coefs[v_sd(t, g, g2)] = -1.0
            add(coefs, 0.0, 0.0)
    for g in range(G):
        add({v_ita(t, 0, g): 1.0 for t in range(T)}, -INF, 1.0)
    for t in range(T):
        for g in range(G):
            coefs = {v_st(t, g): 1.0}
            for t2 in range(t + 1):
                if t2 + rescue_time > t:
                    coefs[v_ita(t2, 0, g)] = \
                        coefs.get(v_ita(t2, 0, g), 0.0) - 1.0 / T
            add(coefs, 0.0, INF)

    M = len(rows)
    A = np.zeros((S, M, N), dtype=dtype)
    row_lo = np.zeros((S, M), dtype=dtype)
    row_hi = np.zeros((S, M), dtype=dtype)
    for m, (coefs, lo, hi) in enumerate(rows):
        for j, v in coefs.items():
            A[:, m, j] = v
        row_lo[:, m] = lo
        row_hi[:, m] = hi

    lb = np.zeros((S, N), dtype=dtype)
    ub = np.ones((S, N), dtype=dtype)        # everything binary
    for g in range(G):                       # self loops forbidden
        for t in range(T):
            ub[:, v_sd(t, g, g)] = 0.0

    # minimize negative lives saved (reference maximizes lives_saved)
    c = np.zeros((S, N), dtype=dtype)
    for t in range(T):
        for g in range(G):
            c[:, v_ita(t, 0, g)] = -lives[:, t, g]

    integer_mask = np.ones((S, N), dtype=bool)

    stage_cost_c = np.zeros((2, S, N), dtype=dtype)
    stage_cost_c[1] = c.copy()               # first-stage cost is 0

    nonant_idx = np.arange(D, dtype=np.int32)
    var_names = tuple(
        [f"is_active_depot[{d}]" for d in range(D)]
        + [f"depot_departures[{t},{d},{g}]" for t in range(T)
           for d in range(D) for g in range(G)]
        + [f"site_departures[{t},{g1},{g2}]" for t in range(T)
           for g1 in range(G) for g2 in range(G)]
        + [f"stays_at_site[{t},{g}]" for t in range(T) for g in range(G)]
        + [f"is_time_from_arrival[{t},{tau},{g}]" for t in range(T)
           for tau in range(T) for g in range(G)])
    tree = TreeInfo(
        node_of=np.zeros((S, D), np.int32),
        prob=np.full((S,), 1.0 / S, dtype=dtype),
        num_nodes=1,
        stage_of=(1,) * D,
        nonant_names=var_names[:D],
        scen_names=tuple(f"scen{i}" for i in range(S)),
    )
    return ScenarioBatch(
        c=c, qdiag=np.zeros((S, N), dtype=dtype),
        A=A, row_lo=row_lo, row_hi=row_hi, lb=lb, ub=ub,
        obj_const=np.zeros((S,), dtype=dtype),
        nonant_idx=nonant_idx, integer_mask=integer_mask,
        tree=tree, stage_cost_c=stage_cost_c, var_names=var_names)


def scenario_names_creator(num_scens, start=0):
    start = start or 0
    return [f"scen{i}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    cfg.num_scens_required()
    cfg.add_to_config("time_horizon", description="time steps",
                      domain=int, default=6)
    cfg.add_to_config("num_depots", description="depots", domain=int,
                      default=2)
    cfg.add_to_config("num_sites", description="incident sites",
                      domain=int, default=4)


def kw_creator(options):
    return {"time_horizon": options.get("time_horizon", 6),
            "num_depots": options.get("num_depots", 2),
            "num_sites": options.get("num_sites", 4)}


def scenario_denouement(rank, scenario_name, result):
    pass

"""mpmd — the wheel as a multi-chip MPMD program (doc/src/mpmd.md).

The pieces:

  * `SlicePlan` (slice_plan.py) — partition the global device list
    into disjoint per-cylinder submeshes (hub large, spokes small);
  * `DeviceWindow` / `device_window_pair` (exchange.py) — versioned
    device-resident mailboxes with the seqlock's write_id contract,
    registered below as the "device" window backend;
  * `CollectiveFabric` / `collective_window_pair` (collective.py) —
    the fused exchange: every pair is one lane row of two shared
    slabs, moved with ONE jitted all-gather (spokes->hub) plus one
    broadcast (hub->spokes) per superstep, registered below as the
    "collective" backend;
  * `MPMDWheel` + `SliceSupervisor` (wheel.py) — one controller thread
    per slice, spoke supersteps overlapping hub supersteps, per-slice
    supervision and telemetry;
  * `ReslicePlanner` (reslice.py) — successor plans after a slice
    dies: the supervisor live-applies them, returning a pruned spoke's
    devices to the hub (elastic recovery, doc/src/mpmd.md).

Importing this package is what makes WindowPair(backend="device") and
WindowPair(backend="collective") resolvable — the WheelSpinner seam
imports it lazily when it selects an on-device exchange; cylinders/
itself never imports mpmd (AST-guarded by tests/test_mpmd_wheel.py).
jax stays lazy throughout: importing mpisppy_tpu.mpmd does not
initialize the accelerator runtime.
"""

from ..cylinders.spcommunicator import register_window_backend
from .collective import (CollectiveFabric, CollectiveWindow,
                         collective_window_pair)
from .exchange import DeviceWindow, device_window_pair
from .reslice import ReslicePlanner
from .slice_plan import CylinderSlice, SlicePlan, slab_width
from .wheel import MPMDWheel, SliceSupervisor

register_window_backend("device", device_window_pair)
register_window_backend("collective", collective_window_pair)

__all__ = [
    "CollectiveFabric",
    "CollectiveWindow",
    "CylinderSlice",
    "DeviceWindow",
    "MPMDWheel",
    "ReslicePlanner",
    "SlicePlan",
    "SliceSupervisor",
    "collective_window_pair",
    "device_window_pair",
    "slab_width",
]

"""CollectiveExchange — one fused collective per superstep instead of
K per-pair mailbox hops.

The device-mailbox backend (exchange.DeviceWindow) moves every
hub<->spoke vector through its own `device_put`: K transfers plus K
blocking syncs per superstep.  Here ALL cylinder outbound vectors pack
into two pre-allocated `(K_pad, H + V_pad)` slabs — one per direction —
laid out over a `cyl` lane axis of a parallel.mesh.ScenarioMesh (one
lane row per hub<->spoke pair), and each superstep moves each slab with
ONE fused device program:

  * spokes->hub: the staged slab is placed lane-sharded (each lane's
    rows land on that spoke's device) and a single jitted
    `shard_map(all_gather)` over the `cyl` axis replicates the full
    slab everywhere — `mesh.fused_cyl_all_gather`, with the staged
    input buffer donated so XLA reuses it in place of a fresh
    allocation (the exchange itself never round-trips through the
    host);
  * hub->spokes: one replicated placement of the staged slab — the
    broadcast — through the `parallel.distributed.lane_transport` seam
    (plain device_put single-process; per-process shard materialization
    once a multihost PR wires DCN lanes in).

Slab layout (header lane).  Row j of a slab is lane j's mailbox:

    [ write_id | crc32 | payload_len | payload ... zero pad to V_pad ]

The three header columns carry the seqlock metadata IN the slab, so
PR 10's `read_checked` integrity contract — monotone write-id, CRC32
over the float64 payload bytes, corrupt-read prune budget — survives
the fused transport bit-for-bit: a reader recomputes the CRC on the
payload it sliced out of the gathered slab and validates it against
the header, exactly as it would against a DeviceWindow's stamped
checksum.  (Write-ids and CRC32 values are exact in float64: both are
< 2**53.)

Commit discipline (lazy flush-on-read): `write()` only stages into the
host slab under a lock and bumps the slab's staged generation — cheap,
and safe from any controller thread.  The FIRST read that observes a
staged generation beyond the committed one triggers the one fused
exchange for the whole direction; every other read in that generation
is a local slice of the committed replicated slab.  Double buffering
falls out of immutability: the previously committed device slab stays
readable while the next exchange builds its successor, and the
reference swaps under the slab lock only after the new slab is
resident.  A fabric-level exchange lock serializes the two directions'
device programs — two multi-device collectives must never be in
flight concurrently from different threads (the XLA rendezvous
deadlock the SolverService backend lock exists for).

Latency accounting: the measured region is `block_until_ready` on the
exchange's output slab ONLY — staging, placement dispatch and the
post-exchange host materialization all happen outside the timed
window, so `wheel.exchange_seconds` reports the collective itself, not
hidden host syncs.

Kill/termination polls (`write_id`, `got_kill_signal`) read a host-side
mirror and never touch the device — same rule as DeviceWindow.

jax stays import-lazy here (AST-guarded by tests): importing
mpisppy_tpu.mpmd to register the backend must not initialize the
accelerator runtime.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from .. import telemetry as _telemetry
from ..resilience.bounds import PayloadGuard, payload_checksum
from .slice_plan import slab_width

HEADER_LANES = 3                   # [write_id, crc32, payload_len]
_H_WID, _H_CRC, _H_LEN = 0, 1, 2

KILL = -1


class _Slab:
    """One direction's slab: host staging buffer + committed device /
    host snapshots + generation counters.  `kind` picks the device
    program: "gather" (spokes->hub all-gather) or "bcast" (hub->spokes
    replicated placement)."""

    def __init__(self, fabric, name, kind):
        self.fabric = fabric
        self.name = name
        self.kind = kind
        self.lens = []             # payload length per lane
        self.windows = []          # CollectiveWindow per lane
        self.lock = threading.Lock()
        self.stage = None          # (K_pad, HEADER_LANES + v_pad) host
        self.v_pad = 0
        self.wid = []              # host write_id mirror per lane
        self.staged_gen = 0
        self.committed_gen = 0
        self.dev = None            # committed device slab (replicated)
        self.host = None           # committed host copy of `dev`
        self.traces = 0            # device-program trace count

    # -- geometry ---------------------------------------------------------
    def alloc(self):
        """Build the staging buffer for the current lane lengths
        (called under the slab lock at the first write; the row count
        is padded to a lane-device multiple at exchange time).  Headers
        are initialized to the pre-first-write contract (id 0, CRC of
        the zero payload), so a read before any write validates exactly
        like a fresh Window."""
        self.v_pad = slab_width(self.lens, self.fabric.pad_to)
        stage = np.zeros((len(self.lens), HEADER_LANES + self.v_pad))
        for lane, n in enumerate(self.lens):
            stage[lane, _H_CRC] = payload_checksum(np.zeros(n))
            stage[lane, _H_LEN] = n
        self.stage = stage

    @property
    def nbytes(self):
        return 0 if self.stage is None else int(self.stage.nbytes)


class CollectiveWindow:
    """Drop-in for cylinders.spcommunicator.Window backed by one lane
    row of a CollectiveFabric slab.  The full Window surface — write /
    read / read_checked / read_device / write_id / send_kill /
    corrupt_next_write / close — with the seqlock's id semantics, so
    nothing above the WindowPair seam can tell the backends apart."""

    KILL = KILL

    def __init__(self, fabric, slab, lane, length, tag=None):
        self.fabric = fabric
        self.lane = int(lane)
        self.length = int(length)
        self.tag = tag
        self._slab = slab
        self._last_read_wid = 0
        self._corrupt_next = False
        self._pguard = PayloadGuard()

    @property
    def write_id(self):
        with self._slab.lock:
            return self._slab.wid[self.lane]

    def write(self, values, write_id=None):
        """Stage `values` under the next (or given) write_id.  No
        device traffic here — the fused exchange runs at the first
        read of this staged generation (module docstring)."""
        values = np.asarray(values, dtype=np.float64)
        if values.shape != (self.length,):
            raise ValueError(
                f"window expects shape ({self.length},), "
                f"got {values.shape}")
        chk = payload_checksum(values)
        if self._corrupt_next:
            # chaos corrupt_window: ship a perturbed payload under the
            # checksum of the true values (read_checked must catch it)
            self._corrupt_next = False
            values = values.copy()
            values[0] += 1.0
        slab = self._slab
        with slab.lock:
            if slab.stage is None:
                slab.alloc()
            new_id = (slab.wid[self.lane] + 1 if write_id is None
                      else int(write_id))
            row = slab.stage[self.lane]
            row[HEADER_LANES:HEADER_LANES + self.length] = values
            row[_H_WID] = new_id
            row[_H_CRC] = chk
            row[_H_LEN] = self.length
            slab.wid[self.lane] = new_id
            slab.staged_gen += 1
        self.fabric._c_writes.inc()
        return new_id

    def _snapshot(self):
        """(payload copy, mirror wid, header wid, header crc) — fused
        exchange first if this lane's slab has staged traffic.  The
        KILL sentinel lives in the host mirror only (the seqlock
        contract: kill overwrites the id, the payload stays the last
        one written) — staged generations still flush, so a reader's
        final pass sees the writer's final payload, not the last one
        somebody happened to read."""
        slab = self._slab
        with slab.lock:
            wid = slab.wid[self.lane]
        self.fabric.ensure_fresh(slab)
        with slab.lock:
            host = slab.host
            if host is None:
                data = np.zeros(self.length)
                return data, wid, 0, payload_checksum(data)
            row = host[self.lane]
            data = row[HEADER_LANES:HEADER_LANES + self.length].copy()
            return data, wid, int(row[_H_WID]), int(row[_H_CRC])

    def _account(self, wid, ok=True):
        if wid != self.KILL:
            if not ok or (wid == self._last_read_wid and wid > 0):
                self.fabric._c_stale.inc()
            self._last_read_wid = wid

    def read(self):
        """(host data copy, write_id) — one committed snapshot, with
        the window-level stale-read accounting of DeviceWindow.read."""
        data, wid, hdr_wid, _ = self._snapshot()
        wid = wid if wid == self.KILL else hdr_wid
        self._account(wid)
        return data, wid

    def read_checked(self):
        """(data, write_id, ok, reason) — one snapshot validated
        against the slab's header lane (checksum + monotone write_id
        via PayloadGuard); corrupt snapshots also count as stale, like
        DeviceWindow.read_checked."""
        data, wid, hdr_wid, crc = self._snapshot()
        wid = wid if wid == self.KILL else hdr_wid
        ok, reason = self._pguard.check(data, wid, crc)
        self._account(wid, ok=ok)
        return data, wid, ok, reason

    def read_device(self):
        """(device-resident payload, write_id) without a host copy —
        a lane slice of the committed replicated slab, for consumers
        that feed the vector straight into a jitted program."""
        slab = self._slab
        with slab.lock:
            wid = slab.wid[self.lane]
        self.fabric.ensure_fresh(slab)
        with slab.lock:
            if slab.dev is None:
                import jax
                return jax.numpy.zeros(self.length), wid
            return (slab.dev[self.lane,
                             HEADER_LANES:HEADER_LANES + self.length],
                    wid)

    def corrupt_next_write(self):
        """Chaos hook (corrupt_window mode) — see Window."""
        self._corrupt_next = True

    def send_kill(self):
        with self._slab.lock:
            self._slab.wid[self.lane] = self.KILL

    def close(self):
        """Interface parity with Window/DeviceWindow; slab buffers are
        shared fabric state and die with the fabric."""


class CollectiveFabric:
    """The shared exchange fabric of one wheel: all hub<->spoke pairs
    as lane rows of two slabs (module docstring).

    `devices` — one lane-mesh device per row; the MPMD wheel passes
    each spoke slice's first device (so the gather input rows land on
    the slices that produced them), the shared-mesh WheelSpinner passes
    a prefix of the hub mesh.  More lanes than devices wrap: K_pad
    rounds the row count up to a device multiple.  `pad_multiple`
    rounds the slab payload width (slice_plan.slab_width), keeping the
    regrown width aligned with the plan's padding quantum after a
    reslice."""

    def __init__(self, devices=None, pad_multiple=1, tag="fabric"):
        self.devices = None if devices is None else list(devices)
        self.pad_to = max(int(pad_multiple), 1)
        self.tag = tag
        tel = _telemetry.get()
        self._c_writes = tel.counter("wheel.exchange_writes")
        self._c_bytes = tel.counter("wheel.exchange_bytes")
        self._c_stale = tel.counter("wheel.stale_reads")
        self._c_coll = tel.counter("wheel.collective_exchanges")
        self._h_latency = tel.histogram("wheel.exchange_seconds")
        # serializes the fused device programs across directions and
        # threads: two in-flight multi-device collectives can deadlock
        # in the XLA rendezvous (the SolverService backend-lock rule)
        self._xlock = threading.Lock()
        self._down = _Slab(self, "to_spoke", kind="bcast")
        self._up = _Slab(self, "to_hub", kind="gather")
        self._mesh = None
        self._transport = None
        self._gather = None
        self._sealed = False

    # -- wiring -----------------------------------------------------------
    @property
    def n_lanes(self):
        return len(self._down.lens)

    @property
    def trace_count(self):
        """Total device-program traces (the single-compile assertion:
        one per slab geometry — regrow retraces, steady state never)."""
        return self._up.traces + self._down.traces

    def add_pair(self, hub_length, spoke_length, tag=None):
        """Register one hub<->spoke pair as lane row `n_lanes` of both
        slabs; returns (to_spoke, to_hub) CollectiveWindows.  All pairs
        must be wired before the first exchange seals the geometry."""
        if self._sealed or self._down.stage is not None \
                or self._up.stage is not None:
            raise RuntimeError(
                "collective fabric is sealed: all pairs must be wired "
                "before the first write fixes the slab geometry")
        lane = self.n_lanes
        down, up = self._down, self._up
        down.lens.append(int(hub_length))
        up.lens.append(int(spoke_length))
        down.wid.append(0)
        up.wid.append(0)
        t = tag if tag is not None else f"{self.tag}.lane{lane}"
        to_spoke = CollectiveWindow(self, down, lane, hub_length,
                                    tag=f"{t}.to_spoke")
        to_hub = CollectiveWindow(self, up, lane, spoke_length,
                                  tag=f"{t}.to_hub")
        down.windows.append(to_spoke)
        up.windows.append(to_hub)
        return to_spoke, to_hub

    # -- geometry / device programs --------------------------------------
    def _seal(self):
        """First-exchange geometry fix: trim the lane device list and
        build the 2-D (cyl x scen) lane mesh + transport."""
        if self._sealed:
            return
        if self.n_lanes == 0:
            raise RuntimeError("collective fabric has no lanes")
        import jax

        from ..parallel.distributed import lane_transport
        from ..parallel.mesh import ScenarioMesh

        devs = self.devices if self.devices is not None else jax.devices()
        devs = list(devs)[:max(1, min(len(list(devs)), self.n_lanes))]
        self.devices = devs
        self._mesh = ScenarioMesh(devices=devs, n_cyl=len(devs))
        self._transport = lane_transport(self._mesh)
        self._sealed = True

    def _run_program(self, slab, snap):
        """Dispatch the slab's fused device program on a staged
        snapshot; returns the committed replicated device slab.  The
        jitted gather is built once per geometry (slab.traces counts
        retraces); the bcast is the transport seam's replicated
        placement and traces nothing."""
        if slab.kind == "gather":
            if self._gather is None:
                def on_trace():
                    slab.traces += 1
                self._gather = self._mesh.fused_cyl_all_gather(
                    on_trace=on_trace)
            x = self._transport.sharded(snap)      # lane rows -> lanes
            return self._gather(x)                 # donates x
        slab.traces = max(slab.traces, 1)          # geometry "trace"
        return self._transport.replicated(snap)    # the broadcast

    def ensure_fresh(self, slab):
        """Commit any staged generation of `slab` with ONE fused
        exchange.  Reads in an already-committed generation return
        immediately; concurrent readers serialize on the exchange lock
        and the loser finds the winner's commit."""
        with slab.lock:
            if slab.staged_gen <= slab.committed_gen:
                return
        with self._xlock:
            with slab.lock:
                gen = slab.staged_gen
                if gen <= slab.committed_gen:
                    return
                self._seal()
                # snapshot under the lock: writers may stage into the
                # buffer while the async transfer below still reads it
                snap = slab.stage.copy()
            # the lane mesh shards slab rows over `cyl`: pad the row
            # count to a device multiple (zero rows, write_id 0)
            r = len(self.devices)
            k = snap.shape[0]
            k_pad = ((k + r - 1) // r) * r
            if k_pad != k:
                snap = np.concatenate(
                    [snap, np.zeros((k_pad - k, snap.shape[1]))])
            out = self._run_program(slab, snap)
            t0 = time.perf_counter()
            out.block_until_ready()
            self._h_latency.observe(time.perf_counter() - t0)
            self._c_coll.inc()
            self._c_bytes.inc(snap.nbytes)
            host = np.asarray(out)    # host mirror, outside the timing
            with slab.lock:
                if gen > slab.committed_gen:
                    # the OLD slab.dev stays alive (and readable) until
                    # the last reader drops it — the double buffer
                    slab.dev = out
                    slab.host = host
                    slab.committed_gen = gen

    # -- reslice support --------------------------------------------------
    def staged_payload(self, win):
        """(last staged payload, mirror wid) for one window, straight
        from the staging buffer — no device work, safe even when the
        fused program is broken (the fallback path reads through
        this)."""
        slab = win._slab
        with slab.lock:
            wid = slab.wid[win.lane]
            if slab.stage is None:
                return np.zeros(win.length), wid
            row = slab.stage[win.lane]
            n = min(win.length, int(row[_H_LEN]) or win.length)
            out = np.zeros(win.length)
            out[:n] = row[HEADER_LANES:HEADER_LANES + n]
            return out, wid

    def regrow_to_spoke(self, new_len):
        """Regrow the hub->spoke slab to the post-reslice `(S*K,)`
        width: every lane's last staged payload is re-staged — CRC
        recomputed for the truncated/zero-extended bytes — under its
        OLD write_id (a fresh id would regress below the spoke's
        last_hub_id and freeze its freshness check), and the next read
        commits the new geometry with one exchange.  All-or-nothing:
        the new stage is built on the side and swapped in at the end,
        so a failure leaves the old slab intact for the device-mailbox
        fallback."""
        new_len = int(new_len)
        down = self._down
        with self._xlock, down.lock:
            k_rows = down.stage.shape[0] if down.stage is not None \
                else len(down.lens)
            v_pad = slab_width([new_len] * max(1, len(down.lens)),
                               self.pad_to)
            stage = np.zeros((k_rows, HEADER_LANES + v_pad))
            for lane, old_n in enumerate(down.lens):
                wid = down.wid[lane]
                payload = np.zeros(new_len)
                if down.stage is not None and wid not in (0, KILL):
                    row = down.stage[lane]
                    n = min(new_len, int(row[_H_LEN]) or old_n)
                    payload[:n] = row[HEADER_LANES:HEADER_LANES + n]
                stage[lane, _H_WID] = 0 if wid == KILL else wid
                stage[lane, _H_CRC] = payload_checksum(payload)
                stage[lane, _H_LEN] = new_len
                stage[lane, HEADER_LANES:HEADER_LANES + new_len] = payload
            # commit the new geometry
            down.lens = [new_len] * len(down.lens)
            down.v_pad = v_pad
            down.stage = stage
            down.dev = None
            down.host = None
            for win in down.windows:
                win.length = new_len
                # DeviceWindow regrow swaps in FRESH windows, so the
                # re-read of a re-posted id is not stale there either
                win._last_read_wid = 0
            down.committed_gen = down.staged_gen
            down.staged_gen += 1

    def describe(self):
        """JSON-safe summary for logs / bench output."""
        return {"backend": "collective", "lanes": self.n_lanes,
                "devices": [str(d) for d in (self.devices or [])],
                "slab_bytes": {"to_spoke": self._down.nbytes,
                               "to_hub": self._up.nbytes},
                "traces": self.trace_count}


def collective_window_pair(hub_length, spoke_length, fabric=None,
                           tag=None):
    """WindowPair factory for the "collective" backend (registered by
    mpisppy_tpu.mpmd): each pair becomes one lane row of the wheel's
    shared CollectiveFabric, passed through `backend_kwargs` — the
    wheel builds ONE fabric and hands every pair the same instance."""
    if fabric is None:
        raise RuntimeError(
            "the 'collective' backend needs a shared CollectiveFabric: "
            "pass window_backend_kwargs={i: {'fabric': fabric}} per "
            "spoke (spin_the_wheel.WheelSpinner and mpmd.MPMDWheel "
            "wire this automatically)")
    return fabric.add_pair(hub_length, spoke_length, tag=tag)

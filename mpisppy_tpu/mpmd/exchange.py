"""DeviceExchange — versioned device-resident mailboxes.

The host seqlock (cylinders/spcommunicator.Window, runtime/exchange.cpp)
keeps every bound/xhat/W vector in host memory; each exchange is a
device->host copy on the writer and a host read on the reader.  Here
the mailbox payload LIVES on a device of the READER's slice: the writer
pays one `jax.device_put` (a cross-slice ICI/DCN hop when writer and
reader occupy different submeshes — arXiv:2412.14374's MPMD transfer
pattern), and the reader's consumption is a local device read.  The
seqlock's atomicity falls out of immutability: a write materializes a
fresh committed array and swaps the (payload, write_id) reference pair
under a lock, so a concurrent `read()` sees either the old or the new
snapshot, never a torn one.

Versioning is EXACTLY the seqlock contract (monotone write_ids,
`write_id == -1` means terminate), so hubs/spokes detect stale reads
with the same id comparisons they use against the host windows —
nothing above the WindowPair seam can tell the backends apart.

This module keeps jax imports lazy (guarded by the AST check in
tests/test_mpmd_wheel.py): importing mpisppy_tpu.mpmd to register the
backend must not initialize the accelerator runtime.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from .. import telemetry as _telemetry
from ..resilience.bounds import PayloadGuard, payload_checksum


class DeviceWindow:
    """Drop-in for cylinders.spcommunicator.Window whose payload is a
    committed device array.

    `device=None` lets jax pick (single-slice wheels); an explicit
    device pins the mailbox onto the reader's slice so writes carry the
    data across the slice boundary and reads stay local."""

    KILL = -1

    def __init__(self, length: int, device=None, tag: str | None = None):
        self.length = int(length)
        self.device = device
        self.tag = tag
        self._lock = threading.Lock()
        self._wid = 0                  # host-side mirror: write_id
        # polls (got_kill_signal every loop tick) must not sync the device
        tel = _telemetry.get()
        self._c_writes = tel.counter("wheel.exchange_writes")
        self._c_bytes = tel.counter("wheel.exchange_bytes")
        self._c_stale = tel.counter("wheel.stale_reads")
        self._h_latency = tel.histogram("wheel.exchange_seconds")
        self._last_read_wid = 0
        self._corrupt_next = False
        self._pguard = PayloadGuard()
        # pre-first-write reads must match Window: zeros with id 0
        self._checksum = payload_checksum(np.zeros(self.length))
        self._payload = self._put(np.zeros(self.length, dtype=np.float64))

    def _put(self, values):
        import jax
        return jax.device_put(values, self.device)

    @property
    def write_id(self):
        with self._lock:
            return self._wid

    def write(self, values, write_id=None):
        """Post `values` with the next (or given) write_id.  The
        transfer is timed into wheel.exchange_seconds and blocks until
        the payload is resident — the reference-swap below must never
        publish an array whose transfer can still fail."""
        values = np.asarray(values, dtype=np.float64)
        if values.shape != (self.length,):
            raise ValueError(
                f"window expects shape ({self.length},), "
                f"got {values.shape}")
        chk = payload_checksum(values)
        if self._corrupt_next:
            # chaos corrupt_window: ship a perturbed payload under the
            # checksum of the true values (read_checked must catch it)
            self._corrupt_next = False
            values = values.copy()
            values[0] += 1.0
        t0 = time.perf_counter()
        arr = self._put(values)
        arr.block_until_ready()
        self._h_latency.observe(time.perf_counter() - t0)
        self._c_writes.inc()
        self._c_bytes.inc(values.nbytes)
        with self._lock:
            new_id = self._wid + 1 if write_id is None else int(write_id)
            self._payload = arr
            self._wid = new_id
            self._checksum = chk
            return new_id

    def read(self):
        """(host data copy, write_id) — one atomic snapshot, with
        window-level stale-read accounting (a repeat of the id last
        handed out here counts into wheel.stale_reads)."""
        with self._lock:
            arr, wid = self._payload, self._wid
        if wid != self.KILL:
            if wid == self._last_read_wid and wid > 0:
                self._c_stale.inc()
            self._last_read_wid = wid
        return np.asarray(arr, dtype=np.float64), wid

    def read_checked(self):
        """(data, write_id, ok, reason) — one snapshot, integrity
        validated (checksum + monotone write_id, PayloadGuard).
        Corrupt snapshots are also counted as stale for the window's
        own traffic accounting."""
        with self._lock:
            arr, wid, chk = self._payload, self._wid, self._checksum
        data = np.asarray(arr, dtype=np.float64)
        ok, reason = self._pguard.check(data, wid, chk)
        if wid != self.KILL:
            if not ok or (wid == self._last_read_wid and wid > 0):
                self._c_stale.inc()
            self._last_read_wid = wid
        return data, wid, ok, reason

    def corrupt_next_write(self):
        """Chaos hook (corrupt_window mode) — see Window."""
        self._corrupt_next = True

    def read_device(self):
        """(device-resident payload, write_id) without a host copy —
        for consumers that feed the vector straight into a jitted
        program on the reader's slice."""
        with self._lock:
            return self._payload, self._wid

    def send_kill(self):
        with self._lock:
            self._wid = self.KILL

    def close(self):
        """Interface parity with Window/NativeWindow; the device buffer
        is garbage-collected with the last reference."""


def device_window_pair(hub_length, spoke_length, hub_device=None,
                       spoke_device=None, tag=None):
    """WindowPair factory for the "device" backend (registered by
    mpisppy_tpu.mpmd): each direction's mailbox sits on the RECEIVING
    slice — to_spoke on the spoke's device, to_hub on the hub's — so
    every write is the cross-slice hop and every read is local."""
    to_spoke = DeviceWindow(hub_length, device=spoke_device,
                            tag=None if tag is None else f"{tag}.to_spoke")
    to_hub = DeviceWindow(spoke_length, device=hub_device,
                          tag=None if tag is None else f"{tag}.to_hub")
    return to_spoke, to_hub

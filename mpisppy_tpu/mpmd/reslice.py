"""ReslicePlanner — successor SlicePlans after a slice dies.

PR 9 made every cylinder a fault domain on its own device slice, but a
pruned spoke's devices were simply lost.  Elastic recovery treats
slice membership as mutable (the MPMD-pipelining systems of
arXiv:2412.14374 do the same for pipeline stages): when the
SliceSupervisor prunes a spoke past its restart budget — or a chaos
device_loss hits its slice — the planner computes a successor plan
with the dead slice removed and its devices merged into a surviving
slice, and the wheel live-applies it behind the hub's sync barrier
(SliceSupervisor.on_sync -> apply_reslice):

  1. the hub optimizer reshards onto the grown submesh
     (PHBase.reshard: re-pad to the new plan's pad_multiple, carry
     PHState over row-for-row — the hub never restarts);
  2. hub->spoke mailboxes whose (S*K,) length changed are rebuilt and
     the last committed payload is re-posted under its OLD write_id,
     so surviving spokes' freshness comparisons stay monotone;
  3. the next send_ws/send_nonants — the very next statements of the
     same sync — already flow through the new plan, which is how a
     reslice completes "within 2 supersteps" of the prune.

Randomized-PH convergence theory (PAPERS.md) tolerates exactly the
stale/missing spoke contributions this transition produces, so the
wheel's certified verdict is unchanged by a mid-run reslice.

This module is jax-free (AST-guarded with the rest of mpmd): plans are
pure device-list bookkeeping.
"""

from __future__ import annotations

from .slice_plan import CylinderSlice, SlicePlan


class ReslicePlanner:
    """Compute successor plans when a slice dies.

    target="hub" (the default, and the only target the supervisor
    live-applies) returns the dead slice's devices to the hub — they
    are APPENDED after the hub's existing devices, so the hub's first
    device (where every to_hub mailbox lives) is unchanged and
    existing spoke->hub wiring survives the transition.

    target="starved" grows the smallest surviving spoke slice instead
    — the static-planning policy for building a recovery plan offline
    (e.g. for a checkpoint resume that restarts dead slices on a
    rebalanced fleet)."""

    def __init__(self, target="hub"):
        if target not in ("hub", "starved"):
            raise ValueError(
                f"reslice target must be 'hub' or 'starved', "
                f"got {target!r}")
        self.target = target

    def successor(self, plan: SlicePlan, dead: CylinderSlice):
        """(new_plan, reclaimed_devices) with `dead` removed and its
        devices merged into the target slice.  The surviving slices
        keep their identity (same CylinderSlice objects) except the
        grown one, which is rebuilt with the extended device tuple."""
        if dead == plan.hub:
            raise ValueError("the hub slice cannot be resliced away")
        survivors = [s for s in plan.slices if s is not dead]
        if len(survivors) == len(plan.slices):
            # not the same object — fall back to equality (a plan
            # round-tripped through describe()/rebuild)
            survivors = [s for s in plan.slices if s != dead]
        if len(survivors) == len(plan.slices):
            raise ValueError(
                f"slice {dead.name!r} is not part of this plan")
        reclaimed = tuple(dead.devices)
        if self.target == "starved" and len(survivors) > 1:
            k = min(range(1, len(survivors)),
                    key=lambda j: survivors[j].n_devices)
        else:
            k = 0
        grown = survivors[k]
        survivors[k] = CylinderSlice(
            grown.name, grown.index, tuple(grown.devices) + reclaimed)
        return SlicePlan(survivors), reclaimed

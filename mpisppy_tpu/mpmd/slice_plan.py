"""SlicePlan — disjoint per-cylinder device slices.

The reference wheel splits COMM_WORLD into a (cylinder x scenario) rank
grid and gives every cylinder its own scenario-sharded communicator
(spin_the_wheel.py:219-237 _make_comms).  The MPMD analog partitions
the GLOBAL device list (parallel.distributed.init_multihost +
jax.devices()) into disjoint submeshes: the hub — which runs the
expensive PH supersteps over all scenarios — gets the large slice, and
each bound/xhat spoke gets a small one (default 1 device), following
the unequal-program placement of the MPMD pipelining work
(arXiv:2412.14374).

Slices expose `.mesh()` (a parallel.mesh.ScenarioMesh over their
devices, built lazily so this module never touches jax at import time)
and the plan exposes `pad_multiple()` — the lcm of the slice sizes —
so ONE host batch padded to that multiple shards evenly on every
slice, keeping the (S*K,) window lengths identical across cylinders.
"""

from __future__ import annotations

import math
from dataclasses import dataclass


def slab_width(lengths, multiple=1):
    """Padded payload width of one collective-exchange slab direction
    (mpmd/collective.py): the max over the lane vector lengths, rounded
    up to `multiple` — the plan's pad_multiple() — so a slab regrown
    after a reslice stays aligned with the padding quantum the batch
    itself was padded to."""
    w = max((int(n) for n in lengths), default=1)
    q = max(int(multiple), 1)
    return max(1, ((w + q - 1) // q) * q)


@dataclass(frozen=True)
class CylinderSlice:
    """One cylinder's share of the fleet: `index` 0 is the hub."""

    name: str
    index: int
    devices: tuple

    @property
    def n_devices(self):
        return len(self.devices)

    def mesh(self, axis_name="scen"):
        from ..parallel.mesh import ScenarioMesh
        return ScenarioMesh(devices=list(self.devices),
                            axis_name=axis_name)


class SlicePlan:
    def __init__(self, slices):
        slices = list(slices)
        if not slices:
            raise ValueError("a SlicePlan needs at least the hub slice")
        seen = []
        for s in slices:
            if not s.devices:
                raise ValueError(f"slice {s.name!r} has no devices")
            for d in s.devices:
                if d in seen:
                    raise ValueError(
                        f"device {d} appears in two slices — cylinder "
                        "slices must be disjoint")
                seen.append(d)
        self.slices = slices
        self.devices = seen            # union, in slice order

    @property
    def hub(self):
        return self.slices[0]

    @property
    def spokes(self):
        return self.slices[1:]

    @property
    def n_slices(self):
        return len(self.slices)

    def pad_multiple(self):
        """lcm of the slice sizes: a batch padded to a multiple of this
        shards evenly on EVERY slice, so no cylinder re-pads and the
        flattened W/nonant window lengths agree across the wheel."""
        return math.lcm(*(s.n_devices for s in self.slices))

    @classmethod
    def partition(cls, n_spokes, devices=None, spoke_devices=1,
                  spoke_names=None):
        """Hub-heavy partition of `devices` (default: the global
        jax.devices() list): the last n_spokes*spoke_devices devices
        become spoke slices, everything before them is the hub's
        scenario slice."""
        if devices is None:
            import jax
            devices = jax.devices()
        devices = list(devices)
        need = n_spokes * spoke_devices + 1
        if len(devices) < need:
            raise ValueError(
                f"{len(devices)} device(s) cannot host a hub plus "
                f"{n_spokes} spoke slice(s) of {spoke_devices} — "
                f"need at least {need}")
        n_hub = len(devices) - n_spokes * spoke_devices
        slices = [CylinderSlice("hub", 0, tuple(devices[:n_hub]))]
        for j in range(n_spokes):
            lo = n_hub + j * spoke_devices
            name = (spoke_names[j] if spoke_names is not None
                    else f"spoke{j}")
            slices.append(CylinderSlice(
                name, j + 1, tuple(devices[lo:lo + spoke_devices])))
        return cls(slices)

    @classmethod
    def from_mesh(cls, mesh, n_spokes, spoke_devices=1, spoke_names=None):
        """Partition an existing ScenarioMesh's device list, validating
        each slice through `mesh.submesh` (membership check) — for a
        2-D cylinder x scenario mesh with equal rows, `uniform` via
        `slice_axis` is the natural alternative."""
        plan = cls.partition(n_spokes, devices=mesh.devices,
                             spoke_devices=spoke_devices,
                             spoke_names=spoke_names)
        for s in plan.slices:
            mesh.submesh(s.devices)    # raises on foreign devices
        return plan

    @classmethod
    def uniform(cls, mesh, spoke_names=None):
        """One slice per cylinder row of a 2-D cylinder x scenario
        ScenarioMesh (mesh.slice_axis) — equal-size slices, row 0 is
        the hub."""
        rows = mesh.slice_axis(mesh.cyl_axis)
        if len(rows) < 2:
            raise ValueError(
                "uniform plans need a 2-D mesh with n_cyl >= 2")
        slices = []
        for r, sub in enumerate(rows):
            name = ("hub" if r == 0 else
                    spoke_names[r - 1] if spoke_names is not None
                    else f"spoke{r - 1}")
            slices.append(CylinderSlice(name, r, tuple(sub.devices)))
        return cls(slices)

    def describe(self):
        """JSON-safe summary for logs / bench output."""
        return [{"slice": s.index, "name": s.name,
                 "devices": [str(d) for d in s.devices]}
                for s in self.slices]

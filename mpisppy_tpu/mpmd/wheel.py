"""MPMDWheel — the hub-and-spoke wheel as a multi-slice MPMD program.

WheelSpinner timeshares ONE mesh: every cylinder's jitted programs
queue on the same devices, so a spoke's Lagrangian pass and the hub's
PH superstep serialize even in `threads` mode.  MPMDWheel instead
gives each cylinder its own disjoint submesh from a SlicePlan (hub
gets the large scenario slice, spokes get small ones) and runs one
controller thread per slice — the single-controller analog of the
multi-program placement in arXiv:2412.14374.  Spoke supersteps then
genuinely overlap hub supersteps (hub_overlap_fraction measures how
much), and bound/xhat/W vectors cross slice boundaries through the
device-resident mailboxes of exchange.DeviceWindow rather than the
host seqlock.

Batch discipline: every cylinder lowers ONE host batch pre-padded to a
multiple of `plan.pad_multiple()` (lcm of slice sizes), so each slice's
ScenarioMesh shards it without further padding and the flattened
(S*K,) window lengths agree across the wheel — the same invariant the
multiproc path enforces with `pad_to` (spin_the_wheel._spin_multiproc).

Supervision: SliceSupervisor is the in-process analog of
resilience.SpokeSupervisor — crashed slice threads restart with the
shared capped backoff (fresh chaos schedule, like a respawned process)
until the restart budget is spent, then prune through the hub's
`report_spoke_failure`/`_mark_spoke_failed` path; write_id staleness
per slice feeds `wheel.slice_heartbeat_age.*` gauges and hang pruning.
Telemetry tracks are per-slice, so the run exports ONE merged
cross-slice trace exactly like the threaded wheel.

jax stays import-lazy here (AST-guarded): the accelerator runtime
initializes when the wheel spins, not when mpmd imports.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from .. import global_toc
from .. import telemetry as _telemetry
from ..resilience.chaos import ChaosInjector, DeviceLossError
from ..resilience.supervisor import restart_delay
from ..spin_the_wheel import WheelSpinner
from .slice_plan import SlicePlan


class SliceSupervisor:
    """Per-slice health for the MPMD wheel's controller threads.

    Shares SpokeSupervisor's option names/defaults (the hub's options
    dict configures either) and its counter attributes
    (`spoke_restarts` / `spokes_failed`), so resilience.wheel_counters
    and the bench JSON read both supervisors identically."""

    def __init__(self, hub, spokes, plan, options=None):
        o = dict(hub.options or {})
        o.update(options or {})
        self.hub = hub
        self.spokes = list(spokes)
        self.plan = plan
        self.interval = float(o.get("supervise_interval", 0.25))
        self.hang_timeout = float(o.get("spoke_hang_timeout", 300.0))
        self.max_restarts = int(o.get("spoke_max_restarts", 2))
        self.backoff = float(o.get("spoke_restart_backoff", 0.5))
        self.backoff_cap = float(o.get("spoke_restart_backoff_cap", 30.0))
        n = len(self.spokes)
        self.threads = [None] * n
        self.restarts = [0] * n
        self.spoke_restarts = 0
        self.spokes_failed = 0
        self.exit_reports = []
        self._busy = [0.0] * n
        self._busy_in_hub = [0.0] * n
        self._last_wid = [None] * n
        self._last_progress = [None] * n
        self._last_poll = 0.0
        self._hung = set()
        self._shutting_down = False
        self.hub_t0 = None
        self.hub_t1 = None
        # elastic recovery (doc/src/mpmd.md "Elastic recovery"):
        # _slice_of maps spoke position -> its CURRENT CylinderSlice
        # (survives earlier reslices, unlike positional plan indexing)
        self._slice_of = {i: plan.spokes[i] for i in range(n)}
        self.reslice_enabled = bool(o.get("reslice", True))
        self._reslice_target = str(o.get("reslice_target", "hub"))
        self._resliced = set()
        self.reslice_log = []
        self.devices_reclaimed = 0
        # wheel-level ensemble checkpoints (resilience/checkpoint.py):
        # written at the end of every checkpoint_every-th hub sync
        self._wheel_ckpt = o.get("wheel_checkpoint")
        self._ckpt_every = int(o.get("checkpoint_every", 1))
        self._last_ckpt_it = 0
        self._tel = getattr(hub, "telemetry", None) or _telemetry.get()
        for i, sp in enumerate(self.spokes):
            self._wrap_step(sp, i)

    def _wrap_step(self, sp, i):
        """Instrument the spoke's step with per-slice busy accounting —
        the raw material of hub_overlap_fraction and the per-slice
        phase_seconds in the bench JSON."""
        orig = sp.timed_step

        def timed_step():
            s = time.monotonic()
            try:
                return orig()
            finally:
                e = time.monotonic()
                self._busy[i] += e - s
                if self.hub_t0 is not None:
                    lo = max(s, self.hub_t0)
                    hi = e if self.hub_t1 is None else min(e, self.hub_t1)
                    if hi > lo:
                        self._busy_in_hub[i] += hi - lo

        sp.timed_step = timed_step

    # -- lifecycle --------------------------------------------------------
    def start(self):
        for i in range(len(self.spokes)):
            self._launch(i)
        return self

    def _launch(self, i):
        th = threading.Thread(target=self._guarded_main, args=(i,),
                              daemon=True, name=f"mpmd-slice{i + 1}")
        self.threads[i] = th
        self._tel.event("wheel.slice_spawn", slice=i + 1,
                        incarnation=self.restarts[i])
        th.start()

    def _guarded_main(self, i):
        try:
            self.spokes[i].main()
        except Exception as e:
            self._on_crash(i, e)

    def _on_crash(self, i, exc):
        sp = self.spokes[i]
        self.exit_reports.append(
            {"slice": i + 1, "name": type(sp).__name__,
             "incarnation": self.restarts[i], "error": str(exc)})
        if self._shutting_down or sp.got_kill_signal():
            return                     # the wheel is over; don't relaunch
        if isinstance(exc, DeviceLossError):
            # the slice's hardware is gone: restarting on it is futile —
            # skip the budget and prune straight into the reslice path
            self.spokes_failed += 1
            self._tel.event("wheel.slice_device_loss", slice=i + 1,
                            reason=str(exc))
            self._tel.counter("wheel.slices_failed").inc()
            self.hub.report_spoke_failure(sp, RuntimeError(
                f"unrestartable: {exc}"))
            return
        if self.restarts[i] < self.max_restarts:
            self.restarts[i] += 1
            self.spoke_restarts += 1
            delay = restart_delay(self.restarts[i], self.backoff,
                                  self.backoff_cap)
            self._tel.event("wheel.slice_restart", slice=i + 1,
                            reason=str(exc),
                            incarnation=self.restarts[i], delay=delay)
            self._tel.counter("wheel.slice_restarts").inc()
            global_toc(f"WARNING: mpmd slice {i + 1} "
                       f"({type(sp).__name__}) crashed: {exc}; restart "
                       f"{self.restarts[i]}/{self.max_restarts} in "
                       f"{delay:.2f}s")
            time.sleep(delay)
            # a restarted incarnation re-runs its fault-injection
            # schedule from scratch, exactly like a respawned process
            sp.chaos = ChaosInjector.from_options(
                sp.options.get("chaos"))
            self._launch(i)
        else:
            self.spokes_failed += 1
            self._tel.event("wheel.slice_prune", slice=i + 1,
                            reason=str(exc), restarts=self.restarts[i])
            self._tel.counter("wheel.slices_failed").inc()
            self.hub.report_spoke_failure(sp, RuntimeError(
                f"{exc} after {self.restarts[i]} restart(s)"))

    # -- supervision (hub thread, called from Hub.sync) -------------------
    def poll(self, force=False):
        now = time.monotonic()
        if self._shutting_down or (not force and
                                   now - self._last_poll < self.interval):
            return
        self._last_poll = now
        for i, sp in enumerate(self.spokes):
            if getattr(sp, "_failed", False) or sp.pair is None:
                continue
            # heartbeat: the slice's to_hub write_id, same liveness
            # signal the multiproc supervisor uses (bound spokes re-post
            # on a timer so the id advances even at a fixed bound)
            wid = sp.pair.to_hub.write_id
            if wid != self._last_wid[i] or self._last_progress[i] is None:
                self._last_wid[i] = wid
                self._last_progress[i] = now
            age = now - self._last_progress[i]
            self._tel.gauge(
                f"wheel.slice_heartbeat_age.slice{i + 1}").set(age)
            if age > self.hang_timeout and i not in self._hung:
                th = self.threads[i]
                if th is not None and th.is_alive():
                    # a thread cannot be killed: prune the slice so the
                    # wheel finishes on the live ones
                    self._hung.add(i)
                    self.spokes_failed += 1
                    self._tel.event("wheel.slice_hang", slice=i + 1,
                                    age=age)
                    self.hub.report_spoke_failure(sp, RuntimeError(
                        f"slice hung: no window write for {age:.1f}s"))

    # -- elastic recovery (hub thread, via Hub.sync getattr hooks) --------
    def on_sync(self):
        """Reslice barrier: runs at the START of every hub sync (after
        the failure drain), so a spoke pruned on ANY path — thread
        crash, device loss, hang, bound-reject or corrupt-read budget —
        gets its devices reclaimed before this superstep's sends."""
        if not self.reslice_enabled or self._shutting_down:
            return
        for i, sp in enumerate(self.spokes):
            if getattr(sp, "_failed", False) and i not in self._resliced:
                self._resliced.add(i)
                try:
                    self.apply_reslice(i)
                except Exception as e:
                    global_toc(f"WARNING: reslice after slice {i + 1} "
                               f"failure failed: {e}")

    def apply_reslice(self, i):
        """Return the dead slice i's devices to the hub: successor
        plan, hub reshard onto the grown submesh, and — when the hub's
        padded scenario count changed — rebuilt hub->spoke mailboxes
        whose last payload is re-posted under its OLD write_id so
        surviving spokes' freshness checks stay monotone."""
        from .reslice import ReslicePlanner

        dead = self._slice_of.pop(i)
        target = self._reslice_target
        if target != "hub":
            # only hub reclamation is safe to live-apply (growing a
            # running spoke's mesh under its controller thread is not);
            # "starved" remains a static-planning policy
            global_toc(f"WARNING: reslice_target={target!r} cannot be "
                       "live-applied; reclaiming to the hub instead")
            target = "hub"
        new_plan, reclaimed = ReslicePlanner(target=target).successor(
            self.plan, dead)
        self.plan = new_plan
        it = self.hub.current_iteration()
        hub_opt = self.hub.opt
        old_S = hub_opt.batch.num_scens
        hub_opt.reshard(new_plan.hub.mesh(),
                        pad_multiple=new_plan.pad_multiple())
        new_S = hub_opt.batch.num_scens
        if new_S != old_S:
            K = hub_opt.batch.num_nonants
            self._regrow_windows(new_S * K)
        self.devices_reclaimed += len(reclaimed)
        event = {"slice": i + 1, "name": dead.name, "iteration": it,
                 "devices_reclaimed": len(reclaimed),
                 "hub_devices": new_plan.hub.n_devices,
                 "padded_scens": new_S}
        self.reslice_log.append(event)
        # "name" would collide with Telemetry.event's own first arg
        self._tel.event("wheel.reslice", **dict(
            {k: v for k, v in event.items() if k != "name"},
            slice_name=dead.name))
        self._tel.counter("wheel.reslice_events").inc()
        self._tel.counter("wheel.devices_reclaimed").inc(len(reclaimed))
        self._tel.gauge("wheel.n_slices").set(new_plan.n_slices)
        global_toc(f"reslice: slice {i + 1} ({dead.name}) pruned at "
                   f"iter {it}; {len(reclaimed)} device(s) returned to "
                   f"the hub ({new_plan.hub.n_devices} total)")

    def _regrow_windows(self, new_len):
        """Rebuild surviving hub->spoke mailboxes at the new (S*K,)
        length.  The last committed payload is carried over (truncated
        readers only consume their own leading rows) and re-posted
        under the OLD write_id: a fresh window would restart ids at 1,
        which is < the spoke's last_hub_id and would freeze its
        freshness check forever.

        Collective pairs regrow as ONE fabric-level slab resize
        (CollectiveFabric.regrow_to_spoke re-stages every lane the same
        way); if that fails the surviving pairs fall back cleanly onto
        device mailboxes at the new width, re-posted under their old
        ids, and the wheel finishes on the per-pair backend."""
        from .collective import CollectiveWindow

        regrown = set()
        for j, sp in enumerate(self.spokes):
            if getattr(sp, "_failed", False) or sp.pair is None:
                continue
            old = sp.pair.to_spoke
            if old.length == new_len:
                continue
            if isinstance(old, CollectiveWindow):
                fab = old.fabric
                if id(fab) in regrown:
                    continue
                regrown.add(id(fab))
                try:
                    fab.regrow_to_spoke(new_len)
                    self._tel.event("wheel.collective_regrow",
                                    width=new_len)
                except Exception as e:
                    global_toc(f"WARNING: collective slab regrow "
                               f"failed ({e}); falling back to device "
                               "mailboxes")
                    self._fallback_to_device_mailboxes(fab, new_len)
                continue
            if hasattr(old, "device"):       # DeviceWindow placement
                new_win = type(old)(new_len, device=old.device,
                                    tag=old.tag)
            else:
                new_win = type(old)(new_len)
            old_data, old_wid = old.read()
            if old_wid not in (0, old.KILL):
                payload = np.zeros(new_len)
                n = min(new_len, old_data.shape[0])
                payload[:n] = old_data[:n]
                new_win.write(payload, write_id=old_wid)
            old.close()
            # sp.pair is the hub's pairs[j] object too — one swap
            # covers both endpoints; readers tolerate either window
            # during the handoff (old stays readable until collected)
            sp.pair.to_spoke = new_win

    def _fallback_to_device_mailboxes(self, fabric, new_len):
        """Swap every surviving pair of `fabric` onto DeviceWindow
        mailboxes: both directions, last staged payloads re-posted
        under their old write_ids (straight from the staging slab —
        no device work through the possibly-broken fused program)."""
        from .collective import CollectiveWindow
        from .exchange import DeviceWindow

        hub_dev = self.plan.hub.devices[0]
        for j, sp in enumerate(self.spokes):
            pair = sp.pair
            if getattr(sp, "_failed", False) or pair is None \
                    or not isinstance(pair.to_spoke, CollectiveWindow) \
                    or pair.to_spoke.fabric is not fabric:
                continue
            spoke_dev = (self._slice_of[j].devices[0]
                         if j in self._slice_of else None)
            for dirn, length, dev in (
                    ("to_spoke", new_len, spoke_dev),
                    ("to_hub", pair.to_hub.length, hub_dev)):
                old = getattr(pair, dirn)
                new_win = DeviceWindow(length, device=dev, tag=old.tag)
                data, wid = fabric.staged_payload(old)
                if wid not in (0, old.KILL):
                    payload = np.zeros(length)
                    n = min(length, data.shape[0])
                    payload[:n] = data[:n]
                    new_win.write(payload, write_id=wid)
                elif wid == old.KILL:
                    new_win.send_kill()
                old.close()
                setattr(pair, dirn, new_win)
        self._tel.event("wheel.collective_fallback", width=new_len)
        self._tel.counter("wheel.collective_fallbacks").inc()

    def on_sync_end(self):
        """Ensemble checkpoint hook: END of hub sync is the wheel's
        consistent cut — hub state committed for this iteration,
        spokes stepped (lockstep) and bounds received — so a resume
        continues at the next iteration with the whole wheel intact."""
        if not self._wheel_ckpt or self._shutting_down:
            return
        it = self.hub.current_iteration()
        if it <= self._last_ckpt_it or it % self._ckpt_every != 0:
            return
        self._last_ckpt_it = it
        from ..resilience.checkpoint import save_wheel_ensemble
        save_wheel_ensemble(self._wheel_ckpt, self.hub,
                            plan=self.plan.describe())
        self._tel.event("wheel.checkpoint", path=str(self._wheel_ckpt),
                        iteration=it)

    # -- shutdown (after hub.send_terminate) ------------------------------
    def shutdown(self, timeout=120.0):
        """Join controller threads against ONE global budget: each
        pending thread gets the remaining time divided by the threads
        still unjoined, so a hung first thread cannot consume the whole
        budget and leak the rest.  A slice still alive past its share
        is escalated through the failure-pruning path and its daemon
        thread dies with the process."""
        self._shutting_down = True
        deadline = time.monotonic() + float(timeout)
        pending = [(i, th) for i, th in enumerate(self.threads)
                   if th is not None and th.is_alive()]
        for k, (i, th) in enumerate(pending):
            remaining = max(0.0, deadline - time.monotonic())
            share = remaining / (len(pending) - k)
            th.join(timeout=share)
            if th.is_alive():
                self.hub.report_spoke_failure(self.spokes[i], TimeoutError(
                    f"slice did not exit within its {share:.1f}s share "
                    f"of the {timeout:.0f}s shutdown budget"))

    # -- accounting -------------------------------------------------------
    def overlap_fraction(self):
        """Fraction of the hub's main() wall-clock covered by spoke
        work on other slices (summed over slices, capped at 1.0 — with
        several spokes the raw sum can exceed the hub window, which
        just means more than one slice was busy at once)."""
        if self.hub_t0 is None or self.hub_t1 is None:
            return 0.0
        dur = self.hub_t1 - self.hub_t0
        if dur <= 0.0:
            return 0.0
        return min(1.0, sum(self._busy_in_hub) / dur)

    def phase_seconds(self):
        """Per-slice busy seconds keyed by trace track ("hub" is filled
        in by the wheel)."""
        return {(sp.telemetry_track or f"slice{i + 1}"):
                round(self._busy[i], 6)
                for i, sp in enumerate(self.spokes)}

    def health(self):
        return [{"slice": i + 1, "name": type(sp).__name__,
                 "alive": bool(self.threads[i] is not None
                               and self.threads[i].is_alive()),
                 "failed": bool(getattr(sp, "_failed", False)),
                 "restarts": self.restarts[i],
                 "busy_seconds": round(self._busy[i], 4),
                 # via _slice_of, not positional plan indexing: after a
                 # reslice the plan no longer carries pruned slices
                 "devices": ([str(d) for d in self._slice_of[i].devices]
                             if i in self._slice_of else [])}
                for i, sp in enumerate(self.spokes)]


class MPMDWheel(WheelSpinner):
    """WheelSpinner whose cylinders run on disjoint mesh slices with
    device-resident exchange.

    lockstep=True drives spokes inline from the hub's sync (the
    deterministic interleaved schedule, for parity runs); the default
    overlaps spoke controller threads with the hub's supersteps."""

    def __init__(self, hub_dict, list_of_spoke_dict=(), plan=None,
                 spoke_devices=1, lockstep=False, keep_workdir=False,
                 resume_from=None):
        super().__init__(hub_dict, list_of_spoke_dict, mode="mpmd",
                         keep_workdir=keep_workdir,
                         resume_from=resume_from)
        self.plan = plan
        self.spoke_devices = spoke_devices
        self.lockstep = lockstep
        self.supervisor = None
        self.fabric = None
        self.hub_main_seconds = 0.0
        self.hub_overlap_fraction = 0.0
        self.slice_phase_seconds = {}

    def spin(self):
        import jax

        from ..ir import pad_scenarios

        hd = self.hub_dict
        plan = self.plan
        if plan is None:
            plan = SlicePlan.partition(len(self.list_of_spoke_dict),
                                       devices=jax.devices(),
                                       spoke_devices=self.spoke_devices)
        self.plan = plan
        global_toc(f"MPMDWheel: {plan.n_slices} slices over "
                   f"{len(plan.devices)} devices (hub: "
                   f"{plan.hub.n_devices})")

        hub_kw = dict(hd["opt_kwargs"])
        batch = hub_kw.get("batch")
        if batch is None:
            raise RuntimeError(
                "MPMDWheel needs opt_kwargs['batch']: every cylinder "
                "lowers the one shared host batch onto its own slice")
        q = plan.pad_multiple()
        Spad = ((batch.num_scens + q - 1) // q) * q
        batch = pad_scenarios(batch, Spad)
        hub_kw["batch"] = batch
        hub_kw["mesh"] = plan.hub.mesh()
        global_toc("MPMDWheel: constructing hub optimizer on its slice")
        hub_opt = hd["opt_class"](**hub_kw)

        spokes = []
        for j, sd in enumerate(self.list_of_spoke_dict):
            kw = dict(sd["opt_kwargs"])
            kw["batch"] = batch        # same host batch, own sharding
            kw["mesh"] = plan.spokes[j].mesh()
            sp_opt = sd["opt_class"](**kw)
            spoke = sd["spoke_class"](
                sp_opt, options=sd.get("spoke_kwargs", {}).get("options"))
            spoke.telemetry_track = (
                f"slice{j + 1}:{type(spoke).__name__}")
            spokes.append(spoke)

        hub_options = dict(hd.get("hub_kwargs", {}).get("options") or {})
        backend = hub_options.get("window_backend")
        if backend is None:
            # ISSUE/ROADMAP auto-selection: the fused collective fabric
            # whenever the hub mesh spans >1 device; a 1-device hub
            # (minimal 3-device fleet) keeps the per-pair mailboxes
            backend = ("collective" if spokes and plan.hub.n_devices > 1
                       else "device")
        if backend == "collective" \
                and "window_backend_kwargs" not in hub_options:
            try:
                from .collective import CollectiveFabric
                # one lane row per spoke, on that spoke slice's first
                # device: the gather input rows land on the slices
                # that stage them, so the all-gather is the real
                # cross-slice hop
                self.fabric = CollectiveFabric(
                    devices=[s.devices[0] for s in plan.spokes],
                    pad_multiple=plan.pad_multiple(), tag="mpmd")
                hub_options["window_backend_kwargs"] = {
                    j: {"fabric": self.fabric, "tag": f"pair{j}"}
                    for j in range(len(spokes))}
            except Exception as e:
                global_toc(f"MPMDWheel: collective fabric unavailable "
                           f"({e}); using device mailboxes")
                backend = "device"
        if backend == "device" \
                and "window_backend_kwargs" not in hub_options:
            # each pair's mailboxes pin to the receiving slice's first
            # device (device_window_pair)
            hub_options["window_backend_kwargs"] = {
                j: {"spoke_device": plan.spokes[j].devices[0],
                    "hub_device": plan.hub.devices[0],
                    "tag": f"pair{j}"}
                for j in range(len(spokes))}
        hub_options["window_backend"] = backend
        self.exchange_backend_used = backend
        global_toc(f"MPMDWheel: {backend!r} exchange backend")
        hub = hd["hub_class"](hub_opt, spokes, options=hub_options)
        hub.setup_hub()
        self._restore_hub_bounds(hub)
        # ensemble resume: the hub optimizer's PH state already rides
        # options["resume_from"] -> load_run_checkpoint (the wheel file
        # is a superset of the run-checkpoint keys); here the SPOKES
        # and window payloads come back, so the spin continues with the
        # whole wheel intact — failed-at-save slices restart fresh
        if self.resume_from is not None:
            from ..resilience.checkpoint import (is_wheel_checkpoint,
                                                 load_wheel_ensemble)
            if is_wheel_checkpoint(self.resume_from):
                load_wheel_ensemble(self.resume_from, hub)
                global_toc(f"MPMDWheel: ensemble restored from "
                           f"{self.resume_from}")
        self.spcomm = hub
        hub.telemetry.gauge("wheel.n_slices").set(plan.n_slices)

        sup = SliceSupervisor(hub, spokes, plan)
        hub.supervisor = sup
        self.supervisor = sup

        if self.lockstep or not spokes:
            hub.drive_spokes_inline = True
            t0 = time.monotonic()
            hub.main()
            self.hub_main_seconds = time.monotonic() - t0
            hub.send_terminate()
        else:
            hub.drive_spokes_inline = False
            sup.start()
            sup.hub_t0 = time.monotonic()
            hub.main()
            sup.hub_t1 = time.monotonic()
            self.hub_main_seconds = sup.hub_t1 - sup.hub_t0
            sup.poll(force=True)
            hub.send_terminate()
            sup.shutdown(timeout=float(hub.options.get(
                "shutdown_join_timeout", 120.0)))
            hub._drain_failures()

        for sp in spokes:
            if getattr(sp, "_failed", False):
                continue
            try:
                sp.finalize()
            except Exception as e:  # a failing final pass must not eat
                global_toc(f"spoke finalize failed: {e}")  # the results
        hub.hub_finalize()
        self.hub_overlap_fraction = sup.overlap_fraction()
        self.slice_phase_seconds = dict(
            {"hub": round(self.hub_main_seconds, 6)},
            **sup.phase_seconds())
        self._flush_telemetry()
        self._ran = True
        return self

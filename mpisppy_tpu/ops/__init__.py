from .pdhg import PDHGSolver, SolveResult, prepare_batch  # noqa: F401

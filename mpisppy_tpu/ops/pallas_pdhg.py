"""Pallas TPU kernel: fused PDHG iteration chunk.

The PDHG hot loop (ops/pdhg.py `steps`) does, per iteration, two
batched matvecs plus elementwise prox updates.  Under plain XLA each
iteration's intermediates round-trip through HBM; this kernel keeps a
TILE of scenarios' (A, x, y, bounds) resident in VMEM and runs the
whole `n_steps` chunk on-chip — matvecs on the MXU via dot_general,
prox math on the VPU — writing back only the chunk's final iterates
and running sums (which the restart logic consumes).

Grid: 1-D over scenario tiles; every ref is a (TILE_S, ...) VMEM
block.  Usable on CPU with interpret=True (that is how the unit tests
pin it against the jnp reference implementation).

See /opt/skills/guides/pallas_guide.md for the API conventions used.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:                                                  # TPU-only module
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except ImportError:                                   # pragma: no cover
    _VMEM = None


def _chunk_kernel(n_steps, A_ref, cs_ref, qs_ref, lb_ref, ub_ref,
                  rlo_ref, rhi_ref, x_ref, y_ref, tau_ref, sig_ref,
                  xo_ref, yo_ref, xs_ref, ys_ref):
    # mixed-precision slabs (hot_dtype="bf16x") STORE A in bf16 — half
    # the VMEM per tile — but all arithmetic runs in the state dtype
    # (f32): the cast up happens once per chunk on the VMEM-resident
    # tile, so accumulation never drops below the compute precision
    A = A_ref[:].astype(cs_ref.dtype)
    cs = cs_ref[:]
    qs = qs_ref[:]
    lb = lb_ref[:]
    ub = ub_ref[:]
    rlo = rlo_ref[:]
    rhi = rhi_ref[:]
    tau = tau_ref[:]          # (T, 1)
    sigma = sig_ref[:]        # (T, 1)

    def body(_, carry):
        x, y, xs, ys = carry
        # per-scenario matvecs as VPU multiply-reduce over the VMEM-
        # resident A tile (Mosaic does not lower batched 3-D
        # dot_general; a matvec is bandwidth-bound so the VPU is the
        # right unit anyway)
        aty = jnp.sum(A * y[:, :, None], axis=1)      # (T, N)
        grad = cs + qs * x + aty
        xn = jnp.clip(x - tau * grad, lb, ub)
        xt = 2.0 * xn - x
        ax = jnp.sum(A * xt[:, None, :], axis=2)      # (T, M)
        v = y + sigma * ax
        zc = jnp.clip(v / sigma, rlo, rhi)
        yn = v - sigma * zc
        return xn, yn, xs + xn, ys + yn

    x0 = x_ref[:]
    y0 = y_ref[:]
    x, y, xs, ys = lax.fori_loop(
        0, n_steps, body,
        (x0, y0, jnp.zeros_like(x0), jnp.zeros_like(y0)))
    xo_ref[:] = x
    yo_ref[:] = y
    xs_ref[:] = xs
    ys_ref[:] = ys


@functools.partial(
    jax.jit,
    static_argnames=("n_steps", "tile_s", "interpret"))
def fused_chunk(A, cs, qs, lbs, ubs, rlo, rhi, x, y, tau, sigma,
                n_steps, tile_s=8, interpret=False):
    """Run `n_steps` PDHG iterations for the whole batch.

    All arrays are SOLVER-SPACE (already Ruiz-scaled) like the inner
    loop of PDHGSolver._solve_impl.  tau/sigma: (S,) per-scenario step
    sizes.  Returns (x, y, x_sum, y_sum) exactly matching the jnp
    `steps` implementation.
    """
    S, M, N = A.shape
    # shrink the tile to the largest divisor of S <= tile_s by halving:
    # compacted slabs (PDHGSolver.solve_compacted) arrive at power-of-
    # two widths, so a pow2 tile_s degrades gracefully (8 -> 4 -> 2)
    # instead of collapsing straight to 1 whenever S % tile_s != 0
    tile_s = max(1, min(int(tile_s), S))
    while S % tile_s:
        tile_s -= 1 if tile_s % 2 else tile_s // 2
    grid = (S // tile_s,)
    t2 = tau[:, None]
    s2 = sigma[:, None]

    def tile_spec(*blk):
        return pl.BlockSpec(blk, lambda i: (i,) + (0,) * (len(blk) - 1))

    kernel = functools.partial(_chunk_kernel, n_steps)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            tile_spec(tile_s, M, N),    # A
            tile_spec(tile_s, N),       # cs
            tile_spec(tile_s, N),       # qs
            tile_spec(tile_s, N),       # lb
            tile_spec(tile_s, N),       # ub
            tile_spec(tile_s, M),       # rlo
            tile_spec(tile_s, M),       # rhi
            tile_spec(tile_s, N),       # x
            tile_spec(tile_s, M),       # y
            tile_spec(tile_s, 1),       # tau
            tile_spec(tile_s, 1),       # sigma
        ],
        out_specs=[
            tile_spec(tile_s, N),
            tile_spec(tile_s, M),
            tile_spec(tile_s, N),
            tile_spec(tile_s, M),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((S, N), x.dtype),
            jax.ShapeDtypeStruct((S, M), y.dtype),
            jax.ShapeDtypeStruct((S, N), x.dtype),
            jax.ShapeDtypeStruct((S, M), y.dtype),
        ],
        interpret=interpret,
    )(A, cs, qs, lbs, ubs, rlo, rhi, x, y, t2, s2)
    return tuple(out)

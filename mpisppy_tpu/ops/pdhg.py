"""Batched first-order LP/QP solver (restarted PDHG, PDLP/MPAX family).

This kernel is the TPU-native replacement for the reference's
out-of-process Gurobi/CPLEX/Xpress calls (reference: mpisppy/spopt.py:85
`solve_one`, :839 `_create_solvers`) — SURVEY.md §2.9.  One scenario =
one batch element; all matvecs are batched (S, M, N) x (S, N) einsums
that land on the MXU; the whole solve is one `lax.while_loop` under
`jit`, so PH's solve_loop becomes a single fused XLA computation instead
of N sequential solver processes.

Problem form (per scenario):

    minimize    c @ x + 0.5 * qdiag @ (x*x)
    subject to  row_lo <= A @ x <= row_hi
                lb <= x <= ub

qdiag >= 0 (diagonal QP — exactly what PH's proximal term produces,
reference phbase.py:617 attach_PH_to_objective).

Method: Chambolle-Pock / Condat-Vu primal-dual iterations with
  * Ruiz equilibration of A (done once per batch in `prepare_batch`),
  * step sizes from a power-iteration estimate of ||A||_2,
  * KKT-progress-triggered ADAPTIVE restarts to the better of
    {current, running average} (the PDLP/MPAX trigger: restart on
    sufficient KKT-score decay or on necessary-decay-plus-stagnation,
    per scenario, with the fixed cadence kept as both the forced
    cycle-length cap and a documented fallback mode —
    `restart_mode="fixed"`),
  * primal-weight (omega) rebalancing at restarts,
  * per-scenario convergence freezing, and (opt-in) host-driven
    COMPACTION of the surviving scenarios into smaller power-of-two
    width buckets once most of the batch has converged
    (`solve_compacted`), so late iterations stop paying matvec FLOPs
    and HBM bandwidth for frozen scenarios.

Termination mirrors PDLP's relative KKT criterion.  Duals: `y` are the
row multipliers; `reduced costs` follow from c + qdiag*x + A^T y, giving
the Lagrangian-bound machinery its inputs (reference
cylinders/lagrangian_bounder.py) for free — see `dual_objective`.
"""

from __future__ import annotations

import dataclasses
import os as _os
import threading as _threading
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

# one shared-A broadcast dispatch rule for the whole package: the
# SA == 1 fast path turns the batched matvec into a real matmul
from ..ir import SplitA
from ..ir import bmatvec as _Ax
from ..ir import bmatvec_t as _ATy

# hot_dtype knob -> (storage dtype, compute dtype) for the inner loop.
#   f32:   everything in float32 — the MPAX/PDLP trade: the hot loop
#          runs ~2x+ faster (CPU SIMD width / MXU rate / HBM traffic)
#          while the certified bound paths stay f64;
#   bf16x: A stored in bfloat16 (halves the constraint tensor's HBM
#          traffic — the dominant bandwidth term), iterates and
#          accumulation in float32 (bf16 @ f32 dot_generals accumulate
#          in f32).
# The knob NEVER upcasts: an f32 batch under hot_dtype="f32" is a
# no-op, and the final KKT verdict + unscaled SolveResult are always
# produced in the CALLER's dtype (see _solve_impl).
HOT_DTYPES = {
    "f32": ("float32", "float32"),
    "bf16x": ("bfloat16", "float32"),
}


def eps_floor(dtype):
    """Tightest tolerance `dtype` arithmetic can express: below
    ~100 ulp the KKT residuals are rounding noise and the loop would
    spin to max_iters (the clamp `_solve_impl` has always applied,
    exposed for the promotion rule)."""
    return 100.0 * float(jnp.finfo(jnp.dtype(dtype)).eps)


def _register(cls, data_fields, meta_fields=()):
    jax.tree_util.register_dataclass(
        cls, data_fields=data_fields, meta_fields=meta_fields)
    return cls


@dataclasses.dataclass(frozen=True)
class PreparedBatch:
    """Scaled constraint data, computed once per ScenarioBatch."""
    A: Any        # (S, M, N) scaled: D_r @ A @ D_c
    row_lo: Any   # (S, M) scaled: D_r * row_lo
    row_hi: Any   # (S, M)
    d_row: Any    # (S, M) row scaling D_r
    d_col: Any    # (S, N) col scaling D_c
    anorm: Any    # (S,) ||A_scaled||_2 estimate


_register(PreparedBatch,
          ("A", "row_lo", "row_hi", "d_row", "d_col", "anorm"))


@dataclasses.dataclass(frozen=True)
class ConsensusSpec:
    """Nonanticipativity structure for EXACT extensive-form solves.

    With a spec, the solver treats each (tree node, nonant slot) as ONE
    shared variable broadcast to its member scenarios: the primal
    gradient is segment-summed over node members before the update (the
    adjoint of the broadcast), so the batched iteration solves the
    monolithic EF — the TPU-native analog of the reference's
    `_create_EF_from_scen_dict` nonant equality constraints
    (reference sputils.py:308-336) without ever materializing the big
    matrix.  Requires prepare_batch(shared_cols=True).
    """
    node_of: Any      # (S, K) node id per scenario per nonant slot
    nonant_idx: Any   # (K,) column indices of nonant slots
    num_nodes: int = 1
    # number of INDEPENDENT stacked EF copies along the scenario axis
    # (opt/mip._lp_multi probes k bound-variants in one launch): every
    # batch-global reduction — power-iteration norm, step sizes, the
    # one-problem KKT verdict, the restart omega — is taken PER COPY,
    # so a degenerate/infeasible variant cannot pollute its siblings'
    # step sizes or convergence verdicts
    num_copies: int = 1


_register(ConsensusSpec, ("node_of", "nonant_idx"),
          ("num_nodes", "num_copies"))


@dataclasses.dataclass(frozen=True)
class SolveResult:
    x: Any          # (S, N) primal solution (unscaled)
    y: Any          # (S, M) row duals (unscaled)
    obj: Any        # (S,) primal objective (incl. obj_const)
    dual_obj: Any   # (S,) dual objective estimate (incl. obj_const)
    pres: Any       # (S,) relative primal residual (inf-norm)
    dres: Any       # (S,) relative dual residual (inf-norm)
    gap: Any        # (S,) relative primal-dual gap
    converged: Any  # (S,) bool
    iters: Any      # () int - iterations used (max across batch)
    restarts: Any = 0  # (S,) int - restart events per scenario


_register(SolveResult,
          ("x", "y", "obj", "dual_obj", "pres", "dres", "gap",
           "converged", "iters", "restarts"))


# --------------------------------------------------------------------------
# scaling
# --------------------------------------------------------------------------

def _ruiz(A, n_iter=10, eps=1e-12, shared_cols=False):
    """Ruiz equilibration: returns (A_scaled, d_row, d_col) with
    A_scaled = diag(d_row) @ A @ diag(d_col), rows/cols ~unit inf-norm.

    shared_cols: use ONE column scaling across all scenarios (the EF
    matrix's column space) — required by consensus solves, where a
    shared variable must see one consistent scaling."""
    S, M, N = A.shape
    d_row = jnp.ones((S, M), A.dtype)
    d_col = jnp.ones((N,) if shared_cols else (S, N), A.dtype)

    def body(_, carry):
        As, dr, dc = carry
        rmax = jnp.max(jnp.abs(As), axis=2)            # (S, M)
        cmax = jnp.max(jnp.abs(As), axis=(0, 1) if shared_cols else 1)
        sr = jnp.where(rmax <= eps, 1.0,
                       1.0 / jnp.sqrt(jnp.maximum(rmax, eps)))
        sc = jnp.where(cmax <= eps, 1.0,
                       1.0 / jnp.sqrt(jnp.maximum(cmax, eps)))
        sc_b = sc[None, None, :] if shared_cols else sc[:, None, :]
        As = As * sr[:, :, None] * sc_b
        return As, dr * sr, dc * sc

    A, d_row, d_col = lax.fori_loop(0, n_iter, body, (A, d_row, d_col))
    if shared_cols:
        d_col = jnp.broadcast_to(d_col[None, :], (S, N))
    return A, d_row, d_col


def _power_iteration(A, iters=40, seed=0):
    """||A||_2 per scenario via power iteration on A^T A."""
    S, M, N = A.shape
    key = jax.random.PRNGKey(seed)
    v = jax.random.normal(key, (S, N), A.dtype)

    def body(_, v):
        v = v / (jnp.linalg.norm(v, axis=1, keepdims=True) + 1e-30)
        av = _Ax(A, v)
        v = _ATy(A, av)
        return v

    v = lax.fori_loop(0, iters, body, v)
    av = _Ax(A, v / (
        jnp.linalg.norm(v, axis=1, keepdims=True) + 1e-30))
    return jnp.linalg.norm(av, axis=1)


@partial(jax.jit, static_argnames=("ruiz_iters", "shared_cols"))
def prepare_batch(A, row_lo, row_hi, ruiz_iters=10, shared_cols=False):
    """One-time per-batch preprocessing (scale + norm estimate)."""
    As, d_row, d_col = _ruiz(A, n_iter=ruiz_iters, shared_cols=shared_cols)
    anorm = _power_iteration(As)
    return PreparedBatch(
        A=As,
        row_lo=jnp.where(jnp.isfinite(row_lo), row_lo * d_row, row_lo),
        row_hi=jnp.where(jnp.isfinite(row_hi), row_hi * d_row, row_hi),
        d_row=d_row,
        d_col=d_col,
        # floor at 1: after Ruiz scaling a real A has ||A|| >= ~1; an
        # all-zero A (zero-probability padding scenario, ir.pad_scenarios)
        # would otherwise yield ~0 and blow up the step sizes
        anorm=jnp.maximum(anorm, 1.0),
    )


@partial(jax.jit, static_argnames=("ruiz_iters",))
def prepare_batch_split(A, rows, cols, row_lo, row_hi, ruiz_iters=10):
    """prepare_batch for a batch whose matrix uncertainty is confined
    to the (rows, cols) coordinate set (ir.SplitA): A is the DENSE
    (S, M, N) tensor the model built; every entry OUTSIDE the delta set
    must be scenario-independent (the model's declaration via
    model_meta["A_delta_idx"] is the contract — tests pin it).

    Ruiz equilibration here uses ONE row/col scaling shared across
    scenarios (norms taken as the max over scenarios), because a
    per-scenario scaling would destroy the shared+sparse structure:
    D_r(s) (A0 + d(s)) D_c(s) splits only when D_r/D_c are shared.
    Shared scalings also satisfy the consensus solver's shared-column
    requirement (prepare_batch(shared_cols=True)) for free.

    Returns a PreparedBatch whose A is a SplitA and whose d_row/d_col
    are (1, M)/(1, N) — the shared-A broadcasting convention.
    """
    rows = jnp.asarray(rows, jnp.int32)
    cols = jnp.asarray(cols, jnp.int32)
    vals = A[:, rows, cols]                          # (S, nnz)
    A0 = A[0].at[rows, cols].set(0.0)                # (M, N) shared part
    return _prepare_split_core(A0, rows, cols, vals, row_lo, row_hi,
                               ruiz_iters=ruiz_iters)


def prepare_split_native(A: "SplitA", row_lo, row_hi, ruiz_iters=10):
    """prepare_batch_split for a batch born split (ir.ScenarioBatch.A
    IS a SplitA — the only representation at sizes where the dense
    (S, M, N) tensor cannot exist, e.g. true-size farmer)."""
    return _prepare_split_core(
        A.shared, jnp.asarray(A.rows, jnp.int32),
        jnp.asarray(A.cols, jnp.int32), A.vals, row_lo, row_hi,
        ruiz_iters=ruiz_iters)


@partial(jax.jit, static_argnames=("ruiz_iters",))
def _prepare_split_core(A0, rows, cols, vals, row_lo, row_hi,
                        ruiz_iters=10):
    M, N = A0.shape
    A0 = A0.at[rows, cols].set(0.0)   # enforce the zeros-at-delta contract
    eps = 1e-12

    def body(_, carry):
        A0s, vs, dr, dc = carry
        vmax = jnp.max(jnp.abs(vs), axis=0)          # (nnz,) over scens
        rmax = jnp.max(jnp.abs(A0s), axis=1).at[rows].max(vmax)
        cmax = jnp.max(jnp.abs(A0s), axis=0).at[cols].max(vmax)
        sr = jnp.where(rmax <= eps, 1.0,
                       1.0 / jnp.sqrt(jnp.maximum(rmax, eps)))
        sc = jnp.where(cmax <= eps, 1.0,
                       1.0 / jnp.sqrt(jnp.maximum(cmax, eps)))
        A0s = A0s * sr[:, None] * sc[None, :]
        vs = vs * sr[rows] * sc[cols]
        return A0s, vs, dr * sr, dc * sc

    A0s, vs, dr, dc = lax.fori_loop(
        0, ruiz_iters, body,
        (A0, vals, jnp.ones((M,), A0.dtype), jnp.ones((N,), A0.dtype)))
    As = SplitA(shared=A0s, rows=rows, cols=cols, vals=vs)
    anorm = _power_iteration(As)
    d_row = dr[None, :]
    d_col = dc[None, :]
    return PreparedBatch(
        A=As,
        row_lo=jnp.where(jnp.isfinite(row_lo), row_lo * d_row, row_lo),
        row_hi=jnp.where(jnp.isfinite(row_hi), row_hi * d_row, row_hi),
        d_row=d_row,
        d_col=d_col,
        anorm=jnp.maximum(anorm, 1.0),
    )


def _gather_prep(prep: PreparedBatch, ii) -> PreparedBatch:
    """Gather a PreparedBatch down to the scenario rows `ii`.

    Shared-A leaves (leading dim 1, the broadcasting convention of
    prepare_batch_split / shared prep) are NOT gathered — they apply
    to every scenario already; a SplitA gathers only its per-scenario
    delta values.  Used by `PDHGSolver.solve_compacted`."""
    def take(a):
        return a if a.shape[0] == 1 else a[ii]

    A = prep.A
    if isinstance(A, SplitA):
        # replace (not the constructor) so a SparseSplitA stays sparse
        A = dataclasses.replace(A, vals=A.vals[ii])
    else:
        A = take(A)
    return PreparedBatch(
        A=A, row_lo=take(prep.row_lo), row_hi=take(prep.row_hi),
        d_row=take(prep.d_row), d_col=take(prep.d_col),
        anorm=take(prep.anorm))


def reprep_row_bounds(prep: PreparedBatch, row_lo, row_hi) -> PreparedBatch:
    """Rebuild a PreparedBatch's scaled row bounds from new RAW bounds.

    Valid exactly when the constraint operator is UNCHANGED — the Ruiz
    scaling and the norm estimate depend only on A, so a batch whose
    uncertainty lives entirely in the row bounds (shared-A families:
    UC wind) can reuse one prep for every scenario block and pay only
    this O(S*M) rescale per block.  The streaming layer's shared-A
    block path is built on this; `_shift_and_widen_rows` (spopt xhat)
    is the same identity for shifted bounds."""
    return dataclasses.replace(
        prep,
        row_lo=jnp.where(jnp.isfinite(row_lo),
                         row_lo * prep.d_row, row_lo),
        row_hi=jnp.where(jnp.isfinite(row_hi),
                         row_hi * prep.d_row, row_hi))


def _unscale_A(A, dr, dc):
    """User-space view of a scaled constraint operator: A / dr / dc,
    dispatching on representation (dense batched / shared / SplitA /
    SparseSplitA — scale_shared keeps BCOO data in coordinate form)."""
    if isinstance(A, SplitA):
        return dataclasses.replace(
            A,
            shared=A.scale_shared(1.0 / dr[0], 1.0 / dc[0]),
            vals=A.vals / (dr[:, A.rows] * dc[:, A.cols]))
    return A / dr[:, :, None] / dc[:, None, :]


def _cast_A(A, dt):
    """Storage-dtype cast of a constraint operator (SplitA.astype is
    subclass-preserving; dense arrays cast directly)."""
    return A.astype(dt)


# --------------------------------------------------------------------------
# core iteration pieces (all batched over leading S axis)
# --------------------------------------------------------------------------


def _proj_box(x, lb, ub):
    return jnp.clip(x, lb, ub)


def _dual_prox(v, sigma, lo, hi):
    """prox of the support function of [lo, hi]:
    v - sigma * proj_[lo,hi](v / sigma), safe with +-inf bounds."""
    z = v / sigma[..., None]
    zc = jnp.clip(z, lo, hi)
    return v - sigma[..., None] * zc


def _residuals(x, y, c, qdiag, A, row_lo, row_hi, lb, ub, cavg=None):
    """KKT residuals + gap, all relative, inf-norms. Batched.

    Follows the PDLP convention: reduced-cost terms whose matching bound
    is infinite are projected out of the dual objective and charged to
    the dual residual instead.

    cavg: optional consensus averaging fn — replaces each nonant slot's
    reduced cost by (segment sum / member count) so per-scenario sums of
    rc terms equal the shared-variable (EF) dual-objective terms.
    """
    Ax = _Ax(A, x)
    # primal violation of row bounds (box is enforced by projection)
    pviol = jnp.maximum(jnp.maximum(row_lo - Ax, Ax - row_hi), 0.0)
    pviol = jnp.where(jnp.isfinite(pviol), pviol, 0.0)
    rhs_scale = 1.0 + jnp.max(
        jnp.where(jnp.isfinite(row_hi), jnp.abs(row_hi), 0.0)
        + jnp.where(jnp.isfinite(row_lo), jnp.abs(row_lo), 0.0), axis=1)
    pres = jnp.max(pviol, axis=1) / rhs_scale

    # dual: r = grad f + A^T y ; must live in normal cone of the box
    grad = c + qdiag * x
    aty = _ATy(A, y)
    r = grad + aty
    if cavg is not None:
        r = cavg(r)
    # split reduced cost by sign; valid part pairs with a finite bound
    rpos = jnp.maximum(r, 0.0)
    rneg = jnp.minimum(r, 0.0)
    lb_fin = jnp.isfinite(lb)
    ub_fin = jnp.isfinite(ub)
    # dual residual: the part of r that cannot be explained by an active
    # finite bound
    dviol = jnp.where(lb_fin, 0.0, rpos) + jnp.where(ub_fin, 0.0, -rneg)
    # plus stationarity leftover for strictly-interior coords:
    at_lb = x <= lb + 1e-9 * (1 + jnp.abs(lb))
    at_ub = x >= ub - 1e-9 * (1 + jnp.abs(ub))
    interior = ~(at_lb | at_ub)
    dviol = jnp.maximum(dviol, jnp.where(interior, jnp.abs(r), 0.0))
    obj_scale = 1.0 + jnp.max(jnp.abs(c), axis=1)
    dres = jnp.max(dviol, axis=1) / obj_scale

    # objectives
    pobj = jnp.sum(c * x, axis=1) + 0.5 * jnp.sum(qdiag * x * x, axis=1)
    # dual objective (PDLP-style estimate):
    #   g(y) = -0.5 x'Qx - sup_{s in [lo,hi]} y's + sum_j rc_j * (lb or ub)
    # with L = f(x) + y'(Ax) - sup_{s in [lo,hi]} y's, the support term
    # is y_i*hi if y_i>0 else y_i*lo.
    ysup = jnp.where(y > 0,
                     jnp.where(jnp.isfinite(row_hi), y * row_hi, 0.0),
                     jnp.where(jnp.isfinite(row_lo), y * row_lo, 0.0))
    rc = jnp.where(lb_fin, rpos * lb, 0.0) + jnp.where(ub_fin, rneg * ub, 0.0)
    dobj = (-0.5 * jnp.sum(qdiag * x * x, axis=1)
            - jnp.sum(ysup, axis=1)
            + jnp.sum(rc, axis=1))
    gap = jnp.abs(pobj - dobj) / (1.0 + jnp.abs(pobj) + jnp.abs(dobj))
    return pres, dres, gap, pobj, dobj


@dataclasses.dataclass(frozen=True)
class _Carry:
    x: Any
    y: Any
    x_sum: Any           # running sums for the restart average
    y_sum: Any
    nsum: Any            # (S,) count in current restart cycle
    x_last: Any          # iterate at last restart (for omega update)
    y_last: Any
    omega: Any           # (S,) primal weight
    k: Any               # iteration counter (outer checks)
    converged: Any       # (S,) bool
    x_best: Any          # frozen solution for converged scenarios
    y_best: Any
    cycle: Any           # (S,) checks since last restart
    score_restart: Any   # (S,) KKT score of the last restart point
    score_cand_prev: Any  # (S,) candidate score at previous check
    restarts: Any        # (S,) restart events taken


_register(_Carry, tuple(f.name for f in dataclasses.fields(_Carry)))


# Per-THREAD solve-jit registry.  Every cylinder of a wheel (hub +
# spokes) and every serve-layer request builds its own PDHGSolver from
# the same options; a per-instance `jax.jit(self._solve_impl)` would
# give each instance a private trace cache and re-compile the identical
# computation.  `_solve_impl` depends only on the construction-time
# scalars in `config_key()`, so instances with equal config share ONE
# wrapper — jit's own cache then buckets on argument shapes/dtypes
# exactly as before.  The registry is thread-local, NOT process-global,
# and `PDHGSolver._solve_jit` resolves through it at CALL time (a
# property), not at construction: threaded cylinder wheels construct
# every cylinder on the main thread and then dispatch hub and spoke
# solves concurrently from worker threads, and concurrent calls into
# one shared jit wrapper deadlock the dispatch path (observed: all
# threads futex-parked under test_cylinders threaded mode).  Call-time
# per-thread scoping keeps the dedup win inside each thread while
# preserving the pre-registry invariant that no two threads ever race
# one wrapper — whichever thread DRIVES a solver gets (and reuses) its
# own wrapper, regardless of which thread built the solver.
_SOLVE_JIT_TLS = _threading.local()


def shared_solve_jit(solver):
    """The thread-shared jitted `_solve_impl` for `solver`'s config."""
    reg = getattr(_SOLVE_JIT_TLS, "registry", None)
    if reg is None:
        reg = _SOLVE_JIT_TLS.registry = {}
    key = solver.config_key()
    fn = reg.get(key)
    if fn is None:
        fn = jax.jit(solver._solve_impl)
        reg[key] = fn
    return fn


class PDHGSolver:
    """Restarted PDHG solver over a ScenarioBatch.

    Stateless/functional: `solve` is jit-compiled; typical use is through
    SPOpt.solve_loop (opt/spopt.py) which supplies PH-modified
    objectives as plain arrays.
    """

    def __init__(self, max_iters=20000, eps=1e-6, check_every=40,
                 restart_every=16, omega0=1.0, use_pallas="auto",
                 pallas_tile=8, pallas_interpret=False,
                 restart_mode="adaptive", restart_beta_sufficient=0.2,
                 restart_beta_necessary=0.8, compact_threshold=0.0,
                 hot_dtype=None, sparse_threshold=0.0):
        # restart_every is in units of `check_every` inner iterations.
        # Under restart_mode="adaptive" it is the FORCED cycle-length
        # cap (a restart fires at the latest every restart_every
        # checks); under restart_mode="fixed" it is the whole policy.
        # Default 16 (=640 inner iterations per restart cycle):
        # measured on the model corpus, every-4 FIXED restarts CYCLE on
        # degenerate duals (unit commitment: 24/40 scenarios stuck at
        # gap ~1 after 300k iters; at 16 all converge in 12k) and are
        # ~2x slower on farmer; sizes/sslp/netdes/battery are
        # insensitive (within ~2x of their small iteration counts).
        # The adaptive trigger restarts EARLIER than the cap only on
        # evidence of sufficient KKT decay, so it cannot reintroduce
        # that cycling.
        self.max_iters = int(max_iters)
        self.eps = float(eps)
        self.check_every = int(check_every)
        self.restart_every = int(restart_every)
        self.omega0 = float(omega0)
        if restart_mode not in ("adaptive", "fixed"):
            raise ValueError(
                f"restart_mode must be 'adaptive' or 'fixed', "
                f"got {restart_mode!r}")
        self.restart_mode = str(restart_mode)
        self.restart_beta_sufficient = float(restart_beta_sufficient)
        self.restart_beta_necessary = float(restart_beta_necessary)
        # active fraction below which solve_compacted gathers the
        # unconverged survivors into a smaller pow2 width bucket;
        # 0.0 disables compaction (solve_compacted == solve)
        self.compact_threshold = float(compact_threshold)
        if use_pallas == "auto":
            # measured on TPU v5e (farmer-64, crops_mult 4): XLA's
            # fused while_loop beats the Pallas chunk kernel ~100x at
            # these batched-small-matvec shapes — Pallas grid programs
            # serialize over scenario tiles while XLA vectorizes the
            # whole batch.  The kernel stays available (explicitly
            # pass use_pallas=True) for very large per-scenario
            # problems where one scenario fills VMEM.
            use_pallas = False
        self.use_pallas = bool(use_pallas)
        self.pallas_tile = int(pallas_tile)
        self.pallas_interpret = bool(pallas_interpret)
        # mixed-precision hot loop (see HOT_DTYPES): None/f64/off keep
        # the historical behavior — the loop runs in the caller's dtype
        if hot_dtype in (None, "", "none", "off", "f64", "float64"):
            hot_dtype = None
        elif hot_dtype not in HOT_DTYPES:
            raise ValueError(
                f"hot_dtype must be one of {sorted(HOT_DTYPES)} (or "
                f"None/'f64' for full precision), got {hot_dtype!r}")
        self.hot_dtype = hot_dtype
        # shared-block density below which a SplitA prep is stored /
        # multiplied as BCOO (ir.SparseSplitA); 0.0 = always dense
        self.sparse_threshold = float(sparse_threshold)

    @property
    def _solve_jit(self):
        # resolved per CALLING thread (see _SOLVE_JIT_TLS above): the
        # thread that runs the solve owns the wrapper, never a thread
        # that merely constructed the solver
        return shared_solve_jit(self)

    @classmethod
    def from_options(cls, options):
        """Build a solver from an SPBase-style options dict (the pdhg_*
        keys).  The one place the option names/defaults are mapped —
        SPOpt and the serve layer's compile cache both route through
        here so a request's bucket is keyed on the exact solver config
        the in-process optimizer would use.

        The MPISPPY_TPU_PDHG environment variable overlays the dict
        (env wins, matching the chaos/telemetry layering): a
        space-separated key=value string of pdhg knobs with or without
        the pdhg_ prefix, e.g.
        ``MPISPPY_TPU_PDHG="restart_mode=fixed compact_threshold=0.25"``.
        """
        o = dict(options or {})
        env = _os.environ.get("MPISPPY_TPU_PDHG")
        if env:
            from ..utils.solver_spec import option_string_to_dict
            for k, v in (option_string_to_dict(env) or {}).items():
                o[k if k.startswith("pdhg_") else f"pdhg_{k}"] = v
        return cls(
            max_iters=int(o.get("pdhg_max_iters", 20000)),
            eps=float(o.get("pdhg_eps", 1e-6)),
            check_every=int(o.get("pdhg_check_every", 40)),
            restart_every=int(o.get("pdhg_restart_every", 16)),
            use_pallas=o.get("pdhg_use_pallas", "auto"),
            pallas_tile=int(o.get("pdhg_pallas_tile", 8)),
            pallas_interpret=bool(o.get("pdhg_pallas_interpret", False)),
            restart_mode=str(o.get("pdhg_restart_mode", "adaptive")),
            restart_beta_sufficient=float(
                o.get("pdhg_restart_beta_sufficient", 0.2)),
            restart_beta_necessary=float(
                o.get("pdhg_restart_beta_necessary", 0.8)),
            compact_threshold=float(o.get("pdhg_compact_threshold", 0.0)),
            hot_dtype=o.get("pdhg_hot_dtype"),
            sparse_threshold=float(o.get("pdhg_sparse_threshold", 0.0)))

    def config_key(self):
        """Hashable construction-time config.  `_solve_impl` reads ONLY
        these attributes, so two solvers with equal keys trace to the
        same computation and may share one jit wrapper.
        (compact_threshold does not enter the trace — solve_compacted
        is a host-side driver — but it is part of the key so configs
        with different compaction policies never alias in caches keyed
        on it, e.g. serve.compile_cache.bucket_key.)"""
        return (self.max_iters, self.eps, self.check_every,
                self.restart_every, self.omega0, self.use_pallas,
                self.pallas_tile, self.pallas_interpret,
                self.restart_mode, self.restart_beta_sufficient,
                self.restart_beta_necessary, self.compact_threshold,
                self.hot_dtype, self.sparse_threshold)

    def clone(self, **overrides):
        """A new solver with this one's full config, selected fields
        overridden — the safe way for callers that re-solve under a
        different budget/precision (spopt._certified_resolve,
        opt.mip._dive_solver) to keep every OTHER knob (restart policy,
        betas, pallas config) in sync with the parent solver."""
        cfg = dict(
            max_iters=self.max_iters, eps=self.eps,
            check_every=self.check_every,
            restart_every=self.restart_every, omega0=self.omega0,
            use_pallas=self.use_pallas, pallas_tile=self.pallas_tile,
            pallas_interpret=self.pallas_interpret,
            restart_mode=self.restart_mode,
            restart_beta_sufficient=self.restart_beta_sufficient,
            restart_beta_necessary=self.restart_beta_necessary,
            compact_threshold=self.compact_threshold,
            hot_dtype=self.hot_dtype,
            sparse_threshold=self.sparse_threshold)
        cfg.update(overrides)
        return type(self)(**cfg)

    # -- mixed precision ---------------------------------------------------
    def hot_eps_floor(self):
        """Tolerance floor of the configured hot dtype's COMPUTE
        precision (0.0 when the hot loop runs full precision — nothing
        to promote from)."""
        if self.hot_dtype is None:
            return 0.0
        return eps_floor(HOT_DTYPES[self.hot_dtype][1])

    def wants_promotion(self, eps=None):
        """True when a solve at tolerance `eps` (default: the
        construction-time eps) needs MORE precision than the hot dtype
        can express — the eps-ladder/KKT promotion rule: drivers
        (spopt.solve_loop, phbase supersteps) switch to the
        full-precision solver + prep instead of letting the hot loop
        clamp eps to its floor and certify at a looser tolerance than
        requested.  Monotone under the PH eps ladder: the ladder only
        tightens, so promotion never reverts within a run."""
        if self.hot_dtype is None:
            return False
        e = self.eps if eps is None else float(eps)
        return e < self.hot_eps_floor()

    def _hot_pair(self, caller_dtype):
        """(storage, compute) jnp dtypes for the hot loop given the
        caller's array dtype, or None when no downcast applies (knob
        off, caller already at/below the hot precision)."""
        if self.hot_dtype is None:
            return None
        store, compute = (jnp.dtype(s)
                          for s in HOT_DTYPES[self.hot_dtype])
        dt = jnp.dtype(caller_dtype)
        if dt == store and dt == compute:
            return None
        if jnp.finfo(dt).bits < jnp.finfo(compute).bits:
            return None     # never upcast the caller's data
        return store, compute

    # -- public ----------------------------------------------------------
    def solve(self, prep: PreparedBatch, c, qdiag, lb, ub,
              obj_const=None, x0=None, y0=None,
              consensus: ConsensusSpec | None = None,
              eps=None, iters_cap=None) -> SolveResult:
        """Solve the batch.  c/qdiag/lb/ub are UNSCALED user-space arrays
        (S, N); x0/y0 optional warm starts in user space.  With a
        ConsensusSpec, solves the monolithic EF (prep must come from
        prepare_batch(shared_cols=True)).  `eps` (a jnp scalar) overrides
        the construction-time tolerance without recompiling — the analog
        of per-iteration solver mipgap schedules (reference
        extensions/mipgapper.py)."""
        S, N = c.shape
        M = prep.A.shape[1]
        if obj_const is None:
            obj_const = jnp.zeros((S,), c.dtype)
        if x0 is None:
            x0 = jnp.zeros((S, N), c.dtype)
        if y0 is None:
            y0 = jnp.zeros((S, M), c.dtype)
        return self._solve_jit(prep, c, qdiag, lb, ub, obj_const, x0, y0,
                               consensus, eps, iters_cap)

    def solve_compacted(self, prep: PreparedBatch, c, qdiag, lb, ub,
                        obj_const=None, x0=None, y0=None,
                        consensus: ConsensusSpec | None = None,
                        eps=None, probs=None, segment_iters=None,
                        on_segment=None) -> SolveResult:
        """`solve`, segmented on the host so converged scenarios stop
        paying matvec FLOPs: run `segment_iters` inner iterations via
        the traced `iters_cap` (no recompile per segment), read the
        converged mask back, and once the active (unconverged, prob>0)
        fraction drops below `compact_threshold`, GATHER the survivors
        into the next smaller power-of-two width bucket
        (serve.compile_cache.width_bucket — pow2 quantization bounds
        the number of distinct compiled widths at log2(S)) and continue
        the hot loop on the compacted slab, scattering results back
        over the frozen full-width buffers.

        Scenarios that converge are NEVER re-entered into a later
        segment, so anything frozen before the first compaction is
        bit-identical to the uncompacted solve (same jit, same shapes,
        same inputs up to its convergence check).  Survivors restart
        each segment from their own warm iterate; their restart average
        and omega re-seed, so they agree with the uncompacted solve
        only up to the KKT tolerance — the compaction parity contract.

        Falls back to plain `solve` when compaction is disabled
        (compact_threshold == 0) or under a ConsensusSpec (consensus
        couples the whole batch; dropping scenarios would change the
        problem).  `probs`: optional (S,) scenario probabilities —
        zero-probability padding rows never count as active.
        `on_segment`: optional callback receiving a dict
        (width/active/iters/seg_iters) after each segment — the
        telemetry hook for the active-fraction trajectory.
        """
        if self.compact_threshold <= 0.0 or consensus is not None:
            return self.solve(prep, c, qdiag, lb, ub,
                              obj_const=obj_const, x0=x0, y0=y0,
                              consensus=consensus, eps=eps)
        import numpy as np

        from ..serve.compile_cache import width_bucket

        S, N = c.shape
        M = prep.A.shape[1]
        if obj_const is None:
            obj_const = jnp.zeros((S,), c.dtype)
        if x0 is None:
            x0 = jnp.zeros((S, N), c.dtype)
        if y0 is None:
            y0 = jnp.zeros((S, M), c.dtype)
        seg = (int(segment_iters) if segment_iters
               else self.check_every * self.restart_every)
        seg = max(seg, self.check_every)

        real = np.arange(S)
        if probs is not None:
            p = np.asarray(probs).reshape(-1)
            real = real[p > 0]

        bufs = None          # full-width result buffers (set by seg 1)
        restarts_f = jnp.zeros((S,), jnp.int32)
        iters_done = 0
        width = S
        cur = None           # None = full width, else gathered indices
        cur_n = S            # how many leading rows of `cur` are real
        while True:
            cap = min(seg, self.max_iters - iters_done)
            if cap < self.check_every and bufs is not None:
                break
            if cur is None:
                res = self.solve(prep, c, qdiag, lb, ub,
                                 obj_const=obj_const, x0=x0, y0=y0,
                                 eps=eps, iters_cap=cap)
            else:
                ii = jnp.asarray(cur, jnp.int32)
                res = self.solve(
                    _gather_prep(prep, ii), c[ii], qdiag[ii], lb[ii],
                    ub[ii], obj_const=obj_const[ii],
                    x0=bufs["x"][ii], y0=bufs["y"][ii],
                    eps=eps, iters_cap=cap)
            iters_done += int(res.iters)
            if bufs is None:
                bufs = {f: getattr(res, f) for f in
                        ("x", "y", "obj", "dual_obj", "pres", "dres",
                         "gap", "converged")}
                restarts_f = res.restarts
            else:
                ri = jnp.asarray(cur[:cur_n], jnp.int32)
                for f in bufs:
                    bufs[f] = bufs[f].at[ri].set(
                        getattr(res, f)[:cur_n])
                restarts_f = restarts_f.at[ri].add(res.restarts[:cur_n])

            conv = np.asarray(bufs["converged"])
            act = real[~conv[real]]
            if on_segment is not None:
                on_segment({"width": int(width),
                            "active": int(act.size),
                            "iters": iters_done,
                            "seg_iters": int(res.iters)})
            if act.size == 0 or iters_done >= self.max_iters:
                break
            target = width_bucket(act.size)
            if target < width and act.size <= self.compact_threshold * width:
                width = target
            # survivors only — converged rows are frozen in `bufs` and
            # must never re-enter a segment (bit-stability contract);
            # pad to the bucket width by repeating survivors (padded
            # duplicates converge with their twins and are dropped at
            # scatter time)
            cur = np.resize(act, width)
            cur[:act.size] = act
            cur_n = int(act.size)

        return SolveResult(
            x=bufs["x"], y=bufs["y"], obj=bufs["obj"],
            dual_obj=bufs["dual_obj"], pres=bufs["pres"],
            dres=bufs["dres"], gap=bufs["gap"],
            converged=bufs["converged"],
            iters=jnp.asarray(iters_done, jnp.int32),
            restarts=restarts_f)

    # -- impl --------------------------------------------------------
    def _solve_impl(self, prep, c, qdiag, lb, ub, obj_const, x0, y0,
                    consensus=None, eps=None, iters_cap=None):
        dc, dr = prep.d_col, prep.d_row
        # scale into solver space (in the caller's precision — the
        # promotion rules of c * dc fix the OUTPUT dtype below)
        cs = c * dc
        qs = qdiag * dc * dc
        lbs = jnp.where(jnp.isfinite(lb), lb / dc, lb)
        ubs = jnp.where(jnp.isfinite(ub), ub / dc, ub)
        xs0 = jnp.clip(jnp.where(jnp.isfinite(x0 / dc), x0 / dc, 0.0),
                       lbs, ubs)
        ys0 = y0 / dr
        A, rlo, rhi = prep.A, prep.row_lo, prep.row_hi
        # mixed precision (hot_dtype): the while_loop below runs in the
        # hot COMPUTE dtype with A held in the hot STORAGE dtype; the
        # final KKT verdict and the returned SolveResult are produced
        # back in the caller's dtype (out_dt), so warm starts, PH state
        # and checkpoints never silently narrow.  The *_f views feed
        # that final verdict — aliases when no downcast applies.
        out_dt = cs.dtype
        hot = self._hot_pair(out_dt)
        cs_f, qs_f, lbs_f, ubs_f = cs, qs, lbs, ubs
        A_f, rlo_f, rhi_f = A, rlo, rhi
        if hot is not None:
            store, compute = hot
            cs, qs = cs.astype(compute), qs.astype(compute)
            lbs, ubs = lbs.astype(compute), ubs.astype(compute)
            xs0, ys0 = xs0.astype(compute), ys0.astype(compute)
            rlo, rhi = rlo.astype(compute), rhi.astype(compute)
            A = _cast_A(A, store)
        S, N = cs.shape
        # clamp the tolerance to what the LOOP dtype can express: in
        # float32 an eps below ~1e-5 can never be met and every solve
        # would spin to max_iters.  (Callers needing a tighter eps than
        # the hot floor promote to full precision instead —
        # wants_promotion.)  The final verdict reuses the same clamped
        # value in the caller's dtype (eps_out).
        floor = 100.0 * float(jnp.finfo(cs.dtype).eps)
        if eps is None:
            eps = max(self.eps, floor)
            eps_out = eps
        else:
            eps_out = jnp.maximum(jnp.asarray(eps, out_dt), floor)
            eps = eps_out.astype(cs.dtype)

        if consensus is not None:
            from ..ir import node_segment_sum
            na = consensus.nonant_idx
            _, segsum = node_segment_sum(consensus.node_of,
                                         consensus.num_nodes)
            counts = segsum(jnp.ones_like(cs[:, na]))

            def csum(g):
                """Adjoint of the shared-variable broadcast: nonant
                slots <- sum over node members, broadcast back."""
                return g.at[:, na].set(segsum(g[:, na]))

            def cavg(g):
                g2 = csum(g)
                return g2.at[:, na].set(g2[:, na] / counts)

            # z-space norm weights: shared coords counted once
            wz = jnp.ones_like(cs).at[:, na].set(1.0 / counts)

            # per-copy reductions over the scenario axis: with
            # num_copies stacked independent EFs (opt/mip._lp_multi),
            # each copy is its own problem and must get its own norm /
            # step size / verdict (nc == 1 degenerates to the plain
            # batch-global reductions)
            nc = max(int(getattr(consensus, "num_copies", 1) or 1), 1)
            S0 = S // nc

            def scen_sum(a):
                """(S,) -> per-copy sum, broadcast back to (S,)."""
                return jnp.repeat(
                    jnp.sum(a.reshape(nc, S0), axis=1), S0)

            def scen_max(a):
                return jnp.repeat(
                    jnp.max(a.reshape(nc, S0), axis=1), S0)

            def znorm(g):
                """(S, ...) -> per-copy z-norm, (S,) broadcast."""
                return jnp.sqrt(scen_sum(
                    jnp.sum(wz * g * g, axis=1))) + 1e-30

            # power iteration for the EF operator  M = blockdiag(A) . B
            key = jax.random.PRNGKey(0)
            v = cavg(jax.random.normal(key, (S, N), cs.dtype))

            def pbody(_, v):
                v = v / znorm(v)[:, None]
                u = _Ax(A, v)
                return csum(_ATy(A, u))

            v = lax.fori_loop(0, 40, pbody, v)
            av = _Ax(A, v / znorm(v)[:, None])
            anorm_c = jnp.sqrt(scen_sum(jnp.sum(av * av, axis=1)))
            anorm = jnp.maximum(anorm_c, 1.0).astype(cs.dtype)
            qmax = scen_max(jnp.max(csum(qs), axis=1)).astype(cs.dtype)
            xs0 = jnp.clip(cavg(xs0), lbs, ubs)  # consistent warm start
        else:
            csum = cavg = None
            # cast, not recompute: the norm estimate from the (possibly
            # low-precision) prep is accurate far beyond step-size needs
            anorm = prep.anorm.astype(cs.dtype)
            qmax = jnp.max(qs, axis=1)

        def steps(x, y, omega, n):
            """n PDHG iterations; returns final + running sums."""
            sigma = 0.9 * omega / anorm
            tau = 0.9 / (omega * anorm + 0.9 * qmax)

            if self.use_pallas and csum is None \
                    and not isinstance(A, SplitA) \
                    and A.shape[0] == x.shape[0]:
                # (the Pallas chunk kernel tiles per-scenario A slabs;
                # shared-A batches use the XLA matmul path)
                from .pallas_pdhg import fused_chunk
                return fused_chunk(
                    A, cs, qs, lbs, ubs, rlo, rhi, x, y,
                    tau, sigma, n, tile_s=self.pallas_tile,
                    interpret=self.pallas_interpret)

            def body(_, carry):
                x, y, xs, ys = carry
                grad = cs + qs * x + _ATy(A, y)
                if csum is not None:
                    grad = csum(grad)
                xn = _proj_box(x - tau[:, None] * grad, lbs, ubs)
                xt = 2.0 * xn - x
                v = y + sigma[:, None] * _Ax(A, xt)
                yn = _dual_prox(v, sigma, rlo, rhi)
                return xn, yn, xs + xn, ys + yn

            zx = jnp.zeros_like(x)
            zy = jnp.zeros_like(y)
            x, y, xs, ys = lax.fori_loop(0, n, body, (x, y, zx, zy))
            return x, y, xs, ys

        def kkt_score(x, y, data=None):
            # data: optional (cs, qs, A, rlo, rhi, lbs, ubs) override —
            # the final verdict passes the caller-precision views
            if data is None:
                data = (cs, qs, A, rlo, rhi, lbs, ubs)
            csk, qsk, Ak, rlok, rhik, lbsk, ubsk = data
            pres, dres, gap, pobj, dobj = _residuals(
                x, y, csk, qsk, Ak, rlok, rhik, lbsk, ubsk, cavg=cavg)
            if consensus is not None:
                # each EF COPY is one problem: its scenarios share one
                # verdict, and only the SUMS of its per-scenario
                # objective pieces are meaningful for the duality gap
                pres = scen_max(pres)
                dres = scen_max(dres)
                ps, ds = scen_sum(pobj), scen_sum(dobj)
                gap = jnp.abs(ps - ds) / (1.0 + jnp.abs(ps)
                                          + jnp.abs(ds))
            return pres + dres + gap, pres, dres, gap

        ne = self.check_every
        n_outer = self.max_iters // ne
        # traced SCREENING cap: callers ranking many speculative
        # candidates (uc.one_opt_commitment sweeps, mip refine) bound
        # the spend per launch without a second solver instance or a
        # recompile per cap value
        if iters_cap is None:
            cap_outer = n_outer
        else:
            cap_outer = jnp.minimum(
                jnp.asarray(n_outer, jnp.int32),
                (jnp.asarray(iters_cap, jnp.int32) + ne - 1) // ne)

        def cond(carry):
            return (carry.k < cap_outer) & (~jnp.all(carry.converged))

        def body(carry):
            x, y, xs, ys = steps(carry.x, carry.y, carry.omega, ne)
            x_sum = carry.x_sum + xs
            y_sum = carry.y_sum + ys
            nsum = carry.nsum + ne
            score_cur, pres, dres, gap = kkt_score(x, y)
            newly = (pres < eps) & (dres < eps) & (gap < eps)
            conv = carry.converged | newly
            x_best = jnp.where(
                (newly & ~carry.converged)[:, None], x, carry.x_best)
            y_best = jnp.where(
                (newly & ~carry.converged)[:, None], y, carry.y_best)

            k = carry.k + 1
            cycle = carry.cycle + 1

            # restart CANDIDATE: the better of {current, cycle average}
            # (PDLP's restart-to-the-best rule).  Computed every check
            # — one extra kkt_score per check_every inner iterations,
            # ~2.5% at the default cadence — so the adaptive trigger
            # can observe the candidate's score.
            xa = x_sum / nsum[:, None]
            ya = y_sum / nsum[:, None]
            score_avg, *_ = kkt_score(xa, ya)
            take_avg = score_avg < score_cur
            xr = jnp.where(take_avg[:, None], xa, x)
            yr = jnp.where(take_avg[:, None], ya, y)
            score_cand = jnp.minimum(score_avg, score_cur)

            if self.restart_mode == "adaptive":
                # PDLP trigger, per scenario: sufficient decay fires
                # immediately; necessary decay fires only once progress
                # WITHIN the cycle stalls (candidate score no longer
                # improving check-over-check); the fixed cadence
                # remains as a forced cap so no cycle runs unbounded.
                # Under consensus every input here is per-copy uniform
                # (kkt_score reduces with scen_max/scen_sum), so the
                # mask is per-copy uniform too and the shared-variable
                # invariant holds.
                suff = (score_cand
                        <= self.restart_beta_sufficient
                        * carry.score_restart)
                nec = ((score_cand
                        <= self.restart_beta_necessary
                        * carry.score_restart)
                       & (score_cand > carry.score_cand_prev))
                do_restart = suff | nec | (cycle >= self.restart_every)
            else:
                do_restart = jnp.broadcast_to(
                    cycle >= self.restart_every, cycle.shape)
            # frozen scenarios take no further restarts (their state is
            # pinned below anyway; keeps the restarts counter honest)
            do_restart = do_restart & ~conv

            # primal weight update (PDLP eq. (10)-style smoothing)
            if consensus is not None:
                # one shared problem PER COPY -> one shared omega
                # per copy (per-scenario omegas would give
                # inconsistent step sizes and break the
                # shared-variable invariant)
                dxv = xr - carry.x_last
                dyv = yr - carry.y_last
                dx = jnp.sqrt(scen_sum(jnp.sum(dxv * dxv, axis=1)))
                dy = jnp.sqrt(scen_sum(jnp.sum(dyv * dyv, axis=1)))
            else:
                dx = jnp.linalg.norm(xr - carry.x_last, axis=1)
                dy = jnp.linalg.norm(yr - carry.y_last, axis=1)
            ok = (dx > 1e-12) & (dy > 1e-12)
            ratio = jnp.where(ok, dy / jnp.maximum(dx, 1e-12), 1.0)
            om_new = jnp.where(
                ok,
                jnp.exp(0.5 * jnp.log(ratio)
                        + 0.5 * jnp.log(carry.omega)),
                carry.omega)
            om_new = jnp.clip(om_new, 1e-4, 1e4)

            # apply the restart per scenario via masks (no batch-global
            # lax.cond: scenarios restart independently)
            m = do_restart
            m2 = m[:, None]
            zx = jnp.zeros_like(x)
            zy = jnp.zeros_like(y)
            xr_ = jnp.where(m2, xr, x)
            yr_ = jnp.where(m2, yr, y)

            # freeze converged scenarios
            cm = carry.converged[:, None]
            return _Carry(
                x=jnp.where(cm, carry.x, xr_),
                y=jnp.where(cm, carry.y, yr_),
                x_sum=jnp.where(m2, zx, x_sum),
                y_sum=jnp.where(m2, zy, y_sum),
                nsum=jnp.where(m, 0.0, nsum),
                x_last=jnp.where(m2, xr, carry.x_last),
                y_last=jnp.where(m2, yr, carry.y_last),
                omega=jnp.where(m, om_new, carry.omega), k=k,
                converged=conv, x_best=x_best, y_best=y_best,
                cycle=jnp.where(m, 0, cycle),
                score_restart=jnp.where(m, score_cand,
                                        carry.score_restart),
                # reset to +inf at a restart so the stagnation test
                # cannot fire on the new cycle's first check
                score_cand_prev=jnp.where(m, jnp.inf, score_cand),
                restarts=carry.restarts + m.astype(jnp.int32))

        S, N = cs.shape
        M = rlo.shape[1]
        inf = jnp.full((S,), jnp.inf, cs.dtype)
        # seed the decay reference with the WARM-START's own KKT score
        # (not +inf, which would read any first check as "sufficient
        # decay" and fire a spurious immediate restart)
        score0, *_ = kkt_score(xs0, ys0)
        init = _Carry(
            x=xs0, y=ys0,
            x_sum=jnp.zeros_like(xs0), y_sum=jnp.zeros_like(ys0),
            nsum=jnp.zeros((S,), cs.dtype),
            x_last=xs0, y_last=ys0,
            omega=jnp.full((S,), self.omega0, cs.dtype),
            k=jnp.asarray(0, jnp.int32),
            converged=jnp.zeros((S,), bool),
            x_best=xs0, y_best=ys0,
            cycle=jnp.zeros((S,), jnp.int32),
            score_restart=score0.astype(cs.dtype),
            score_cand_prev=inf,
            restarts=jnp.zeros((S,), jnp.int32))
        fin = lax.while_loop(cond, body, init)

        x = jnp.where(fin.converged[:, None], fin.x_best, fin.x)
        y = jnp.where(fin.converged[:, None], fin.y_best, fin.y)
        if hot is not None:
            # promote the final iterate to the caller's dtype and
            # recheck the verdict there: frozen scenarios keep their
            # hot-precision certificate (the semantics a native-f32
            # run has always had) and the full-precision recheck can
            # only ADD conversions
            x = x.astype(out_dt)
            y = y.astype(out_dt)
        _, pres, dres, gap = kkt_score(
            x, y, data=(cs_f, qs_f, A_f, rlo_f, rhi_f, lbs_f, ubs_f))
        # unscale
        xu = x * dc
        yu = y * dr
        pobj = (jnp.sum(c * xu, axis=1)
                + 0.5 * jnp.sum(qdiag * xu * xu, axis=1) + obj_const)
        # dual objective in user space (recompute residual pieces unscaled)
        _, _, _, _, dobj = _residuals(
            xu, yu, c, qdiag,
            _unscale_A(prep.A, dr, dc),
            jnp.where(jnp.isfinite(prep.row_lo), prep.row_lo / dr,
                      prep.row_lo),
            jnp.where(jnp.isfinite(prep.row_hi), prep.row_hi / dr,
                      prep.row_hi),
            lb, ub, cavg=cavg)
        return SolveResult(
            x=xu, y=yu, obj=pobj, dual_obj=dobj + obj_const,
            pres=pres, dres=dres, gap=gap,
            converged=fin.converged | ((pres < eps_out)
                                       & (dres < eps_out)
                                       & (gap < eps_out)),
            iters=fin.k * ne, restarts=fin.restarts)

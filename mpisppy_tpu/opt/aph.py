"""APH — Asynchronous Projective Hedging (reference: mpisppy/opt/aph.py,
982 LoC; Eckstein/Watson/Woodruff projective splitting).

The reference hides solver latency behind a listener THREAD doing
continuous Allreduces (utils/listener_util) and dispatches only a
fraction of subproblems per pass (APH_solve_loop, aph.py:554-669).  On
TPU the "solver" is one batched kernel, so the listener disappears
(SURVEY.md §2.3): every reduction is a fused array op inside one jitted
superstep.  What survives — because it changes the ALGORITHM, not just
the schedule — is the **dispatch fraction**: per iteration only the
`dispatch_frac` least-recently-dispatched scenarios refresh their
(x, y); the rest contribute stale values to the averages, exactly the
asynchronous trajectory of the reference.

Per-iteration math (mirrors aph.py:332-530):
    solve:  x_s  <- argmin f_s(x) + W_s.x_na + rho/2 ||x_na - z_s||^2
    y_s   = W_s + rho (x_na - z)                    (Update_y, :151-182)
    xbar  = node-avg x_na ; ybar = node-avg y       (Compute_Averages)
    u_s   = x_na - xbar ;  v = ybar
    tau   = E_s[ ||u_s||^2 + ||v_s||^2 / gamma ]    (side gig, :271-289)
    phi   = E_s[ (z - x_na).(W - y) ]               (compute_phis_summand)
    theta = nu * phi / tau  if phi>0, tau>0 else 0  (Update_theta_zw)
    W    += theta * u ;  z += theta * ybar / gamma
    conv  = ||u||_p/||W||_p + ||v||_p/||z||_p       (Compute_Convergence)

Iteration 1 is special (reference :481-485): z := xbar, y := 0.

Options: APHgamma (>0, default 1), APHnu (in (0,2), default 1),
dispatch_frac (default 1.0 = synchronous), plus the PH options.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from .. import global_toc
from ..ir import node_segment_sum
from ..phbase import PHBase, compute_xbar, convergence_metric


def _register(cls, data_fields, meta_fields=()):
    jax.tree_util.register_dataclass(
        cls, data_fields=data_fields, meta_fields=meta_fields)
    return cls


@dataclasses.dataclass(frozen=True)
class APHState:
    x: Any             # (S, N) last primal solutions (possibly stale)
    y: Any             # (S, M) row duals from last dispatched solve
    y_na: Any          # (S, K) APH subgradients on nonants
    W: Any             # (S, K)
    z: Any             # (S, K) consensus point (node-consistent)
    xbar: Any          # (S, K)
    xsqbar: Any        # (S, K)
    ybar: Any          # (S, K)
    obj: Any           # (S,)
    dual_obj: Any      # (S,)
    conv: Any          # ()
    theta: Any         # ()
    phi: Any           # ()
    tau: Any           # ()
    it: Any            # () int32
    last_dispatch: Any  # (S,) int32 — iteration each scenario last solved


_register(APHState, tuple(f.name for f in dataclasses.fields(APHState)))


def node_average(batch, v):
    """Node-conditional probability-weighted average of a (S, K) array
    (the FirstReduce of the reference, aph.py:394-407)."""
    tree = batch.tree
    p = tree.prob[:, None]
    _, segsum = node_segment_sum(tree.node_of, tree.num_nodes)
    wsum = jnp.maximum(segsum(jnp.broadcast_to(p, v.shape)), 1e-30)
    return segsum(p * v) / wsum


class APH(PHBase):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        o = self.options
        self.APHgamma = float(o.get("APHgamma", 1.0))
        self.APHnu = float(o.get("APHnu", 1.0))
        frac = float(o.get("dispatch_frac", 1.0))
        S = self.batch.num_scens
        self.n_dispatch = max(1, min(S, int(jnp.ceil(frac * S))))
        self.aph_state: APHState | None = None
        self._aph_superstep = jax.jit(self._aph_superstep_impl)

    # -- one APH iteration, fully fused -----------------------------------
    def _aph_superstep_impl(self, st: APHState, rho, lb, ub, eps):
        b = self.batch
        S = b.num_scens
        na = b.nonant_idx

        # dispatch selection: the n least-recently-dispatched scenarios
        # (reference dispatchrecord sort, aph.py:554-669); index breaks
        # ties so the rotation is deterministic
        key = st.last_dispatch * S + jnp.arange(S, dtype=jnp.int32)
        _, idx = jax.lax.top_k(-key, self.n_dispatch)
        mask = jnp.zeros((S,), bool).at[idx].set(True)

        # subproblem objective: W.x + rho/2 ||x - z||^2 (prox against z,
        # NOT xbar — the PH/APH difference; reference aph.py:841-884)
        c_eff = b.c.at[:, na].add(st.W - rho * st.z)
        q_eff = b.qdiag.at[:, na].add(jnp.broadcast_to(rho, st.W.shape))
        res = self.solver._solve_jit(
            self.prep, c_eff, q_eff, lb, ub, b.obj_const, st.x, st.y,
            None, eps)

        m2 = mask[:, None]
        x = jnp.where(m2, res.x, st.x)
        y_rows = jnp.where(m2, res.y, st.y)
        x_na = b.nonants(x)
        # Update_y (reference aph.py:151-182) for dispatched scenarios
        y_na = jnp.where(m2, st.W + rho * (x_na - st.z), st.y_na)

        xbar, xsqbar = compute_xbar(b, x_na)
        ybar = node_average(b, y_na)

        p = b.tree.prob
        u = x_na - xbar
        v = ybar
        pusq = jnp.sum(p * jnp.sum(u * u, axis=1))
        pvsq = jnp.sum(p * jnp.sum(v * v, axis=1))
        tau = pusq + pvsq / self.APHgamma
        phi = jnp.sum(p * jnp.sum((st.z - x_na) * (st.W - y_na), axis=1))
        theta = jnp.where((tau > 0) & (phi > 0),
                          self.APHnu * phi / jnp.maximum(tau, 1e-30), 0.0)

        W = st.W + theta * u
        z = st.z + theta * ybar / self.APHgamma

        pwsq = jnp.sum(p * jnp.sum(W * W, axis=1))
        pzsq = jnp.sum(p * jnp.sum(z * z, axis=1))
        conv = (jnp.sqrt(pusq) / jnp.maximum(jnp.sqrt(pwsq), 1e-30)
                + jnp.sqrt(pvsq) / jnp.maximum(jnp.sqrt(pzsq), 1e-30))

        obj = b.objective(x)
        return APHState(
            x=x, y=y_rows, y_na=y_na, W=W, z=z,
            xbar=xbar, xsqbar=xsqbar, ybar=ybar,
            obj=obj, dual_obj=res.dual_obj, conv=conv,
            theta=theta, phi=phi, tau=tau, it=st.it + 1,
            last_dispatch=jnp.where(mask, st.it + 1, st.last_dispatch))

    # -- driver (reference APH_main, aph.py:820-922) ----------------------
    def APH_main(self, spcomm=None, finalize=True):
        if spcomm is not None:
            self.spcomm = spcomm
        self.Iter0()   # PHBase Iter0: no-penalty solves, trivial bound
        st0 = self.state
        b = self.batch
        S = b.num_scens
        # iteration-1 specials (reference aph.py:481-485): z := xbar,
        # y := 0; W carries Iter0's PH update
        self.aph_state = APHState(
            x=st0.x, y=st0.y, y_na=jnp.zeros_like(st0.W), W=st0.W,
            z=st0.xbar, xbar=st0.xbar, xsqbar=st0.xsqbar,
            ybar=jnp.zeros_like(st0.W), obj=st0.obj,
            dual_obj=st0.dual_obj, conv=jnp.asarray(jnp.inf, b.c.dtype),
            theta=jnp.asarray(0.0, b.c.dtype),
            phi=jnp.asarray(0.0, b.c.dtype),
            tau=jnp.asarray(0.0, b.c.dtype),
            it=jnp.asarray(1, jnp.int32),
            last_dispatch=jnp.zeros((S,), jnp.int32))

        max_iters = int(self.options.get("PHIterLimit", 100))
        convthresh = float(self.options.get("convthresh", 1e-4))
        for k in range(2, max_iters + 2):
            self.aph_state = self._aph_superstep(
                self.aph_state, self.rho, self.lb_eff, self.ub_eff,
                self.solver_eps)
            # mirror into PHState-compatible fields for spokes/extensions
            self.state = dataclasses.replace(
                self.state, x=self.aph_state.x, y=self.aph_state.y,
                W=self.aph_state.W, xbar=self.aph_state.xbar,
                xsqbar=self.aph_state.xsqbar, obj=self.aph_state.obj,
                dual_obj=self.aph_state.dual_obj,
                conv=self.aph_state.conv, it=self.aph_state.it)
            self.conv = float(self.aph_state.conv)
            self._ext("miditer")
            if k % 10 == 0 or k == 2:
                global_toc(f"APH iter {k:4d} conv={self.conv:.6e} "
                           f"theta={float(self.aph_state.theta):.4g} "
                           f"phi={float(self.aph_state.phi):.4g}")
            self._ext("enditer")
            if self.spcomm is not None:
                self.spcomm.sync()
                if self.spcomm.is_converged():
                    global_toc(f"APH terminated by hub at iter {k}")
                    break
            if self.conv < convthresh:
                global_toc(f"APH converged (conv={self.conv:.3e}) "
                           f"at iter {k}")
                break
            self._ext("enditer_after_sync")
        self._ext("post_everything")
        if finalize:
            eobj = self.post_loops()
            return self.conv, eobj, self.trivial_bound
        return self.conv, None, self.trivial_bound

    # lowercase alias matching this package's PH.ph_main style
    def aph_main(self, finalize=True):
        return self.APH_main(finalize=finalize)

    def root_z(self):
        """Root-node consensus point z (the APH candidate solution)."""
        return self.aph_state.z[0]

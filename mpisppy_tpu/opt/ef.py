"""ExtensiveForm — monolithic EF solve (reference: mpisppy/opt/ef.py).

The reference builds one big Pyomo model: scenario sub-blocks, a
probability-weighted summed objective, and explicit nonanticipativity
equality constraints against first-seen reference variables
(reference sputils.py:209-341 _create_EF_from_scen_dict), then makes a
single monolithic solver call (opt/ef.py:66 solve_extensive_form) —
2939 s of Gurobi barrier at farmer-1000 scale (BASELINE.md).

Here the EF is never materialized: the batched PDHG kernel runs in
consensus mode (ops/pdhg.ConsensusSpec) where each (node, nonant-slot)
is one shared variable — the per-scenario matvecs stay batched on the
MXU and the consensus coupling is a segment-sum per iteration.  The
probability weighting moves into the per-scenario objective arrays.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import global_toc
from ..ops.pdhg import ConsensusSpec
from ..spopt import SPOpt


class ExtensiveForm(SPOpt):
    # consensus solves need one column scaling shared by all scenarios;
    # SPOpt.__init__ reads this so the batch is prepared exactly once
    _shared_cols = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        b = self.batch
        self.consensus = ConsensusSpec(
            node_of=b.tree.node_of,
            nonant_idx=b.nonant_idx,
            num_nodes=b.tree.num_nodes)
        self._result = None

    def solve_extensive_form(self, solver_options=None, tee=False,
                             certify=True, x0=None, y0=None):
        """One batched consensus solve == the reference's single
        monolithic solver call (opt/ef.py:66).

        certify: if the fast solve leaves the (single, coupled) EF
        unconverged, re-solve the FULL batch in float64 warm-started —
        the consensus system cannot be subset the way the per-scenario
        fallback (spopt._certified_resolve) does.

        x0/y0: optional warm starts (user space) — sequential-
        relaxation callers (models/acopf3.soc_refine's cut loop) hand
        each round the previous round's iterates, the persistent-
        solver analog."""
        b = self.batch
        p = b.prob[:, None]
        res = self.solver.solve(
            self.prep,
            b.c * p,
            b.qdiag * p,
            b.lb, b.ub,
            obj_const=b.obj_const * b.prob,
            x0=x0, y0=y0,
            consensus=self.consensus)
        if certify and not bool(jnp.all(res.converged)):
            res = self._certified_ef_resolve(res)
        self._result = res
        global_toc(
            f"EF solve: obj={self.get_objective_value():.6g} "
            f"pres={float(jnp.max(res.pres)):.2e} "
            f"gap={float(jnp.max(res.gap)):.2e} "
            f"iters={int(res.iters)}", tee)
        return res

    def _certified_ef_resolve(self, res, c=None, qdiag=None, lb=None,
                              ub=None, obj_const=None):
        """Full-batch float64 consensus re-solve, warm-started from the
        fast result (on the CPU backend when the accelerator lacks
        f64).  The f32 kernel's primal-residual floor (~1e-4 relative)
        applies to the EF exactly as to per-scenario solves.

        c/qdiag/lb/ub/obj_const override the batch's own
        (probability-weighted) arrays — callers solving a MODIFIED EF
        (opt/mip.py dives fix integer boxes) MUST pass their arrays or
        the fallback would silently re-solve the unmodified EF and
        report its solution as the modified one."""
        import dataclasses

        import jax

        from .. import global_toc
        from ..ops.pdhg import prepare_batch

        b = self.batch
        p = np.asarray(b.prob, np.float64)[:, None]
        if c is None:
            c = np.asarray(b.c, np.float64) * p
        if qdiag is None:
            qdiag = np.asarray(b.qdiag, np.float64) * p
        if obj_const is None:
            obj_const = np.asarray(b.obj_const, np.float64) * p[:, 0]
        lb = b.lb if lb is None else lb
        ub = b.ub if ub is None else ub
        try:
            cpu = jax.devices("cpu")[0]
        except RuntimeError:
            cpu = None
        from ..utils.platform import enable_x64_scope
        with enable_x64_scope():
            put = ((lambda a: jax.device_put(np.asarray(a, np.float64),
                                             cpu))
                   if cpu is not None
                   else (lambda a: jnp.asarray(np.asarray(a, np.float64))))
            prep64 = prepare_batch(put(b.A), put(b.row_lo), put(b.row_hi),
                                   shared_cols=True)
            # hot_dtype pinned OFF: this is the certified f64 authority
            # for the coupled EF solve (AST-guarded in
            # tests/test_precision.py)
            s64 = self.solver.clone(
                max_iters=max(self.solver.max_iters, 100000),
                use_pallas=False, hot_dtype=None)
            r64 = s64.solve(
                prep64,
                put(c),
                put(qdiag),
                put(lb), put(ub),
                obj_const=put(obj_const),
                x0=put(res.x), y0=put(res.y),
                consensus=dataclasses.replace(
                    self.consensus,
                    node_of=jax.device_put(
                        np.asarray(self.consensus.node_of, np.int32),
                        cpu),
                    nonant_idx=jax.device_put(
                        np.asarray(self.consensus.nonant_idx, np.int32),
                        cpu)),
                eps=float(self.solver.eps))
            jax.block_until_ready(r64.x)
        if not bool(jnp.all(r64.converged)):
            global_toc("WARNING: EF f64 fallback did not fully converge")
        dt = res.x.dtype
        cast = lambda a: jnp.asarray(np.asarray(a), dt)  # noqa: E731
        return dataclasses.replace(
            res, x=cast(r64.x), y=cast(r64.y), obj=cast(r64.obj),
            dual_obj=cast(r64.dual_obj), pres=cast(r64.pres),
            dres=cast(r64.dres), gap=cast(r64.gap),
            converged=jnp.asarray(np.asarray(r64.converged), bool))

    @property
    def solved(self):
        return self._result is not None

    def get_objective_value(self):
        """EF objective = sum of probability-weighted scenario pieces
        (reference opt/ef.py:97)."""
        if self._result is None:
            raise RuntimeError("call solve_extensive_form first")
        return float(jnp.sum(self._result.obj))

    def get_dual_bound(self):
        """Valid lower bound from the EF dual estimate."""
        if self._result is None:
            raise RuntimeError("call solve_extensive_form first")
        return float(jnp.sum(self._result.dual_obj))

    def get_root_solution(self):
        """Root-node nonant values (K,) (reference opt/ef.py:114)."""
        if self._result is None:
            raise RuntimeError("call solve_extensive_form first")
        x_na = self.batch.nonants(self._result.x)
        # all scenarios agree by construction; read scenario 0
        return np.asarray(x_na[0])

    def nonants(self):
        """Per-scenario nonant values for the REAL scenarios, padding
        excluded (reference sputils.ef_nonants)."""
        if self._result is None:
            raise RuntimeError("call solve_extensive_form first")
        return np.asarray(
            self.batch.nonants(self._result.x))[: self.n_real_scens]


def ef_dual_bound(batch, scenario_names, eps=1e-5, max_iters=100000):
    """(bound, seconds): one consensus-EF LP solve's dual objective —
    a valid outer bound at ANY iterate when the batch is an LP with
    all-finite boxes (spopt valid-Ebound rule #1), and measured (UC
    S=50 vs a HiGHS oracle) much tighter than a W-path Lagrangian
    bound at small PH iteration counts.  Shared by bench.py worker_uc
    and examples/uc_scale_demo.py so the bench artifact and the demo
    certify with the same protocol."""
    import time

    t0 = time.time()
    ef = ExtensiveForm({"pdhg_eps": eps, "pdhg_max_iters": max_iters},
                       scenario_names, batch=batch)
    ef.solve_extensive_form()
    return ef.get_dual_bound(), time.time() - t0

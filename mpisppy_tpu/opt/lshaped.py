"""L-shaped (Benders) method (reference: mpisppy/opt/lshaped.py, 776 LoC).

The reference builds a root problem on rank 0 with per-scenario `eta`
epigraph variables (lshaped.py:139-366), strips first-stage constraints
into it (:380-506), and loops: rank0 root solve -> Bcast x -> all ranks
generate cuts through pyomo.contrib.benders -> add cuts (:590-679).

TPU-native restructuring (SURVEY.md §2.9: "duals come free from
first-order solvers"):

  * A **subproblem** is the scenario LP with nonant slots pinned to
    x̂ via bounds (spopt.fixed_nonant_bounds) — the whole scenario set
    solves as ONE batched PDHG call, and each pinned slot's reduced
    cost  r_j = c_j + (A'y)_j  IS the cut gradient dq_s/dx̂_j.
  * The **root** is a small LP over [x (K,), eta (S,)] with the
    first-stage rows (rows of A whose support is inside the nonant
    columns) plus a FIXED-CAPACITY cut buffer — rows activate as cuts
    arrive, shapes never change, so root solves hit one compiled
    kernel.
  * eta lower bounds come from the wait-and-see duals of the unpinned
    iter-0 solve (valid: q_s(x) >= min_x q_s(x)), replacing the
    reference's "valid_eta_lb" option (lshaped.py:155-170).

Cuts are the multi-cut family (one eta per scenario, matching the
reference's per-scenario eta); `single_cut=True` aggregates them
(the reference's non-multi mode).

Two-stage only, like the reference (lshaped.py asserts two stages).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from .. import global_toc
from ..ops.pdhg import PDHGSolver, prepare_batch
from ..spopt import SPOpt


class LShapedMethod(SPOpt):
    _needs_dense_A = True   # cut generation indexes A by scenario
    def __init__(self, options, all_scenario_names, **kwargs):
        super().__init__(options, all_scenario_names, **kwargs)
        if self.batch.tree.num_nodes > 2:  # ROOT (+ possibly pad node)
            # pad scenarios add one dummy node; real multistage has more
            if int(np.asarray(self.batch.tree.node_of).max()) > 0 and \
               np.any(np.asarray(self.batch.tree.node_of)
                      [: self.n_real_scens] > 0):
                raise RuntimeError(
                    "LShapedMethod is two-stage only (so is the "
                    "reference, opt/lshaped.py)")
        o = self.options
        self.max_iter = int(o.get("max_iter", 50))
        self.tol = float(o.get("tol", 1e-6))
        self.single_cut = bool(o.get("single_cut", False))
        self.verbose = bool(o.get("verbose", False))
        self.root_eps = float(o.get("root_eps", o.get("pdhg_eps", 1e-7)))

        self._build_root_skeleton()
        self.outer_bound = -np.inf if self.is_minimizing else np.inf
        self.inner_bound = np.inf if self.is_minimizing else -np.inf
        self.best_xhat = None
        self.iter = 0
        self.spcomm = None

    # -- root construction -------------------------------------------------
    def _build_root_skeleton(self):
        b = self.batch
        K = b.num_nonants
        S = self.n_real_scens
        na = np.asarray(b.nonant_idx)
        self.n_eta = 1 if self.single_cut else S

        # first-stage rows: support entirely inside nonant columns
        # (the reference's "strip first-stage constraints",
        # lshaped.py:380-506, done structurally on the lowered arrays)
        A0 = np.asarray(b.A[0])
        lo0 = np.asarray(b.row_lo[0])
        hi0 = np.asarray(b.row_hi[0])
        nz = np.abs(A0) > 0
        mask_cols = np.zeros(b.num_vars, bool)
        mask_cols[na] = True
        fs_rows = np.where(
            (nz.any(axis=1)) & (~nz[:, ~mask_cols].any(axis=1)))[0]
        self._fs_rows = fs_rows

        cuts_per_round = self.n_eta
        self.max_cuts = cuts_per_round * (self.max_iter + 1)
        M_root = len(fs_rows) + self.max_cuts
        N_root = K + self.n_eta

        A = np.zeros((1, M_root, N_root))
        row_lo = np.full((1, M_root), -np.inf)
        row_hi = np.full((1, M_root), np.inf)
        A[0, : len(fs_rows), :K] = A0[np.ix_(fs_rows, na)]
        row_lo[0, : len(fs_rows)] = lo0[fs_rows]
        row_hi[0, : len(fs_rows)] = hi0[fs_rows]
        # cut rows start free (inactive): row_lo = -inf

        # objective: min sum_s p_s eta_s (subproblem q includes the
        # first-stage cost because pinned slots keep their c terms)
        c = np.zeros((1, N_root))
        if self.single_cut:
            c[0, K] = 1.0
        else:
            c[0, K:] = np.asarray(b.prob)[:S]
        # x bounds from the batch; eta bounds filled after iter0
        lb = np.full((1, N_root), -np.inf)
        ub = np.full((1, N_root), np.inf)
        lb[0, :K] = np.asarray(b.lb[0])[na]
        ub[0, :K] = np.asarray(b.ub[0])[na]

        self._root = {
            "A": A, "row_lo": row_lo, "row_hi": row_hi,
            "c": c, "lb": lb, "ub": ub,
            "n_cuts": 0, "K": K, "S": S,
        }
        self._root_solver = PDHGSolver(
            max_iters=int(self.options.get("root_max_iters", 50000)),
            eps=self.root_eps)
        self._root_warm = None

    def _root_solve(self):
        r = self._root
        prep = prepare_batch(jnp.asarray(r["A"]),
                             jnp.asarray(r["row_lo"]),
                             jnp.asarray(r["row_hi"]))
        x0 = y0 = None
        if self._root_warm is not None:
            x0, y0 = self._root_warm
        res = self._root_solver.solve(
            prep, jnp.asarray(r["c"]), jnp.zeros_like(jnp.asarray(r["c"])),
            jnp.asarray(r["lb"]), jnp.asarray(r["ub"]), x0=x0, y0=y0)
        self._root_warm = (res.x, res.y)
        xhat = np.asarray(res.x[0, : r["K"]])
        root_obj = float(res.obj[0])
        return xhat, root_obj

    def _add_cuts(self, xhat, q, grad, only=None):
        """q: (S,) subproblem values; grad: (S, K) cut gradients.
        Cut: eta_s >= q_s + grad_s.(x - xhat)  ->
             eta_s - grad_s.x >= q_s - grad_s.xhat
        `only`: optional (S,) bool — add cuts just for those scenarios
        (used when some subproblems failed to converge)."""
        r = self._root
        K, S = r["K"], r["S"]
        if self.single_cut:
            p = np.asarray(self.batch.prob)[:S]
            q = np.array([np.dot(p, q)])
            grad = (p[:, None] * grad).sum(axis=0, keepdims=True)
            only = None
        for j in range(q.shape[0]):
            if only is not None and not only[j]:
                continue
            i = len(self._fs_rows) + r["n_cuts"]
            if r["n_cuts"] >= self.max_cuts:
                global_toc("L-shaped: cut buffer full; dropping cut")
                return
            r["A"][0, i, :K] = -grad[j]
            r["A"][0, i, K + j] = 1.0
            r["row_lo"][0, i] = q[j] - float(grad[j] @ xhat)
            r["n_cuts"] += 1

    # -- main loop (reference lshaped.py:508-679 lshaped_algorithm) --------
    def lshaped_algorithm(self):
        b = self.batch
        S = self.n_real_scens
        na = b.nonant_idx

        # iter0: unpinned wait-and-see solves -> eta lower bounds + x0
        global_toc("L-shaped iter0: wait-and-see solves")
        res = self.solve_loop(warm=False)
        ws_dual = np.asarray(res.dual_obj)[:S]
        K = b.num_nonants
        r = self._root
        if self.single_cut:
            p = np.asarray(b.prob)[:S]
            r["lb"][0, K] = float(p @ ws_dual) - abs(float(p @ ws_dual)) - 1.0
        else:
            r["lb"][0, K:] = ws_dual - np.abs(ws_dual) * 1e-6 - 1.0
        # initial candidate: probability-weighted average of the
        # wait-and-see nonants (what PH iter0 would call xbar)
        p = np.asarray(b.prob)[:, None]
        x_na = np.asarray(b.nonants(res.x))
        xhat = (p * x_na).sum(axis=0) / p.sum()

        for k in range(1, self.max_iter + 1):
            self.iter = k
            # subproblems: pin nonants to xhat, batched solve
            lb, ub = self.fixed_nonant_bounds(jnp.asarray(xhat))
            sub = self.solve_loop(lb=lb, ub=ub, warm=True)
            q = np.asarray(sub.obj)[:S]
            # cut gradient = reduced cost at pinned slots
            grad_full = np.asarray(self._reduced_costs(sub))[:S]
            grad = grad_full[:, np.asarray(na)]

            # trust nothing from a non-converged/infeasible subproblem
            # (models without relatively complete recourse; the
            # reference classifies solver status, spopt.py:175-194)
            feas_tol = 10 * self.solver.eps
            scen_ok = np.asarray(sub.pres)[:S] < feas_tol
            all_ok = bool(scen_ok.all())

            if all_ok:
                ib = float(np.asarray(b.prob)[:S] @ q)
                if self._ib_better(ib, self.inner_bound):
                    self.inner_bound = ib
                    self.best_xhat = xhat.copy()
                self._add_cuts(xhat, q, grad)
            else:
                bad = np.where(~scen_ok)[0]
                global_toc(f"L-shaped iter {k}: {bad.size} subproblem(s) "
                           "infeasible/non-converged at candidate; "
                           "adding cuts from feasible scenarios only")
                if not self.single_cut and scen_ok.any():
                    self._add_cuts(xhat, np.where(scen_ok, q, -np.inf),
                                   grad, only=scen_ok)
            xhat, root_obj = self._root_solve()
            self.outer_bound = root_obj

            gap = abs(self.inner_bound - self.outer_bound) / (
                1e-12 + abs(self.outer_bound))
            if self.verbose or k % 5 == 0 or k == 1:
                global_toc(f"L-shaped iter {k:3d} outer={root_obj:.6g} "
                           f"inner={self.inner_bound:.6g} gap={gap:.3e}")
            if self.spcomm is not None:
                self.spcomm.sync()
                if self.spcomm.is_converged():
                    global_toc(f"L-shaped terminated by hub at iter {k}")
                    break
            if gap <= self.tol:
                global_toc(f"L-shaped converged at iter {k} "
                           f"(gap {gap:.3e})")
                break
        self.first_stage_solution = self.best_xhat
        return self.outer_bound, self.inner_bound, self.best_xhat

    def _ib_better(self, new, old):
        return new < old if self.is_minimizing else new > old

    def _reduced_costs(self, res):
        """r = c + qdiag*x + A'y per scenario (user space)."""
        b = self.batch
        aty = jnp.einsum("smn,sm->sn", b.A, res.y)
        return b.c + b.qdiag * res.x + aty

    # xhat for spokes
    def root_xbar(self):
        return self.best_xhat

"""ExtensiveFormMIP — EF solves with integer variables.

The reference gets MIP optima by handing the EF to a commercial
branch-and-cut solver (reference opt/ef.py:66 solve_extensive_form ->
Gurobi/CPLEX).  There is no branch-and-bound on a TPU; SURVEY.md §7.8
prescribes the alternative this class implements: LP relaxation +
progressive fix-and-round, with every LP a batched PDHG solve so the
whole dive stays on-device.

Method — three-phase LP diving with strong rounding:

  0. solve the consensus LP relaxation -> valid outer bound (the root
     relaxation bound branch-and-bound would start from).
  Phase Z (gating binaries): strong-round the binaries that GATE a
     nonant column — a binary b gates v when raising b loosens a row
     constraining v (the big-M setup-forcing pattern: x - M z <= 0).
     These drive the structural cost tradeoffs, and their LP values
     are the least trustworthy (a big-M relaxation amortizes the
     binary's cost to ~nothing), so they are decided FIRST, by
     cost-weighted fractionality, each by solving the EF with the
     binary fixed 0 and 1 and keeping the cheaper feasible direction.
     Deciding production quantities before setups inverts the
     economics and overspends on setups (measured on sizes-3:
     +1.7% incumbent).
  Phase A (coupled): dive on the INTEGER NONANT columns over the
     consensus EF solve: bulk-fix every one within `int_tol` of an
     integer, then strong-round the most fractional one.  Nonant fixes
     are applied to every scenario through the tree node (the
     ConsensusSpec shared-variable invariant).
  Bridge: pin continuous nonants at their consensus values — the EF
     then separates by scenario.
  Phase B (separable): recover the remaining per-scenario integers
     with BATCHED parallel dives: every scenario bulk-fixes its own
     near-integral variables and strong-rounds its own most fractional
     one, all scenarios at once — two batched independent solves per
     round (floor-batch, ceil-batch), so the round count is
     max-over-scenarios of the fractional depth, not the sum.
  3. final batched solve with all integers fixed = integer-feasible
     incumbent; (incumbent - root bound)/|incumbent| is a TRUE
     optimality gap (bound valid, incumbent feasible).

Degenerate optimal faces are broken by a deterministic relative cost
perturbation (`perturb`) on integer columns so the kernel converges to
a vertex-like point where implicitly-integer variables (network /
transportation structure) come out integral and bulk-fixing does the
work; perturbation is removed from all REPORTED objective values.

Used by the integer-golden tests (sizes-3 EF == 220000 at 2
significant figures, reference mpisppy/tests/test_ef_ph.py:137).
"""

from __future__ import annotations

import dataclasses
import hashlib

import jax.numpy as jnp
import numpy as np

from .. import global_toc
from ..ops.pdhg import ConsensusSpec
from .ef import ExtensiveForm


class ExtensiveFormMIP(ExtensiveForm):
    _needs_dense_A = True   # the dive indexes A by scenario
    _use_split_prep = False  # _lp_multi tiles prep.A as a dense array

    def __init__(self, options, all_scenario_names, **kwargs):
        super().__init__(options, all_scenario_names, **kwargs)
        if not bool(np.any(np.asarray(self.batch.integer_mask))):
            raise ValueError("batch has no integer variables; use "
                             "ExtensiveForm")

    # -- one consensus LP solve under current fixing bounds ---------------
    def _lp(self, c_s, lb, ub, x0=None, y0=None, consensus=True,
            eps=None, certify=True, max_iters=None):
        """eps: loose tolerance for DIVE solves (branch probes need
        comparison-grade accuracy, not bound-grade); certify=False
        skips the f64 fallback — the dive's decisions self-correct via
        the release/retry machinery, and the f64 fallback burning
        max_iters on a loose probe was the dominant cost of the r3
        dive (measured: 80k kernel iters/solve at eps=1e-6 vs ~5k at
        1e-4).  Bound-carrying solves (root, final) keep the default
        tight+certified path."""
        b = self.batch
        p = np.asarray(b.prob)[:, None]
        solver = (self.solver if certify
                  else self._dive_solver(max_iters))
        res = solver.solve(
            self.prep, c_s * p, b.qdiag * p, lb, ub,
            obj_const=b.obj_const * b.prob,
            x0=x0, y0=y0,
            consensus=self.consensus if consensus else None,
            eps=None if eps is None else jnp.asarray(eps, b.c.dtype))
        if not certify:
            return res
        if not bool(np.all(np.asarray(res.converged))):
            if consensus:
                res = self._certified_ef_resolve(
                    res, c=np.asarray(c_s, np.float64) * p,
                    qdiag=np.asarray(b.qdiag, np.float64) * p,
                    lb=lb, ub=ub,
                    obj_const=np.asarray(b.obj_const, np.float64)
                    * np.asarray(b.prob, np.float64))
            else:
                res = self._certified_resolve(
                    res, c=np.asarray(c_s, np.float64) * p,
                    qdiag=np.asarray(b.qdiag, np.float64) * p,
                    lb=lb, ub=ub,
                    obj_const=np.asarray(b.obj_const, np.float64)
                    * np.asarray(b.prob, np.float64))
        return res

    def _dive_solver(self, max_iters=None):
        """Solver for the dive's probe solves: same knobs, capped
        iteration budget — an INFEASIBLE probe never converges, and
        letting it burn the certified solver's max_iters (200k) was
        most of the r3 dive's wall-clock; structural infeasibility
        shows as O(1) row violation long before the cap.  A tighter
        explicit cap serves the refinement probes, where an
        unconverged probe simply counts as not-an-improvement."""
        if max_iters is None:
            max_iters = int(self.options.get("mip_dive_max_iters",
                                             60000))
        key = ("_dive_solver", max_iters)
        s = self._np_cache.get(key)
        if s is None:
            # clone: every knob (restart policy, betas, pallas config)
            # stays in lockstep with the certified solver's config —
            # except hot_dtype, pinned OFF: dive probes feed bound
            # decisions (prune/accept), which must never rest on a
            # low-precision verdict (AST-guarded in
            # tests/test_precision.py)
            s = self.solver.clone(max_iters=max_iters, hot_dtype=None)
            self._np_cache[key] = s
        return s

    # -- k bound-variants of the same EF in ONE stacked launch ------------
    def _lp_multi(self, c_s, bounds, x0=None, y0=None, consensus=True,
                  eps=None, max_iters=None):
        """Solve k variants of the (consensus or separable) EF that
        differ only in their bound arrays, in ONE kernel launch: the
        batch is tiled k-fold along the scenario axis and, for
        consensus solves, each copy's tree nodes are offset so the k
        EFs stay decoupled.  This is the phase-B floor/ceil-batch trick
        applied to the COUPLED phases (VERDICT r3 item 5): a stacked
        launch runs to the max of the variants' iteration counts where
        sequential probes pay the sum.

        bounds: list of (lb, ub) numpy arrays.  x0/y0: one warm start
        shared by every variant (the parent relaxation).  Returns a
        list of k SolveResult views sliced back to (S, ...).
        """
        k = len(bounds)
        if k == 1:
            return [self._lp(c_s, bounds[0][0], bounds[0][1], x0=x0,
                             y0=y0, consensus=consensus, eps=eps,
                             certify=False, max_iters=max_iters)]
        b = self.batch
        S = b.num_scens
        dt = b.c.dtype
        key = ("mip_stack", k, bool(consensus))
        st = self._np_cache.get(key)
        if st is None:
            def tile(a):
                a = jnp.asarray(a)
                return jnp.tile(a, (k,) + (1,) * (a.ndim - 1))
            prep = self.prep
            p = jnp.asarray(b.prob)[:, None]
            st = {
                "prep": dataclasses.replace(
                    prep, A=tile(prep.A), row_lo=tile(prep.row_lo),
                    row_hi=tile(prep.row_hi), d_row=tile(prep.d_row),
                    d_col=tile(prep.d_col), anorm=tile(prep.anorm)),
                "qdiag": tile(b.qdiag * p),
                "obj_const": tile(b.obj_const * b.prob),
                "consensus": None,
            }
            if consensus:
                node_of = np.asarray(b.tree.node_of)
                offs = np.concatenate(
                    [node_of + i * b.tree.num_nodes for i in range(k)],
                    axis=0)
                st["consensus"] = ConsensusSpec(
                    node_of=jnp.asarray(offs),
                    nonant_idx=b.nonant_idx,
                    num_nodes=k * b.tree.num_nodes,
                    # per-copy norms/verdicts: an infeasible probe must
                    # not pollute its siblings' step sizes
                    num_copies=k)
            self._np_cache[key] = st
        p_np = np.asarray(b.prob)[:, None]
        c_t = jnp.asarray(np.tile(np.asarray(c_s * p_np, dt), (k, 1)))
        lb_t = jnp.asarray(np.concatenate(
            [np.asarray(lo, dt) for lo, _ in bounds], axis=0))
        ub_t = jnp.asarray(np.concatenate(
            [np.asarray(hi, dt) for _, hi in bounds], axis=0))
        x0_t = None if x0 is None else jnp.tile(jnp.asarray(x0), (k, 1))
        y0_t = None if y0 is None else jnp.tile(jnp.asarray(y0), (k, 1))
        res = self._dive_solver(max_iters).solve(
            st["prep"], c_t, st["qdiag"], lb_t, ub_t,
            obj_const=st["obj_const"], x0=x0_t, y0=y0_t,
            consensus=st["consensus"],
            eps=None if eps is None else jnp.asarray(eps, dt))

        def view(i):
            sl = slice(i * S, (i + 1) * S)
            return dataclasses.replace(
                res, x=res.x[sl], y=res.y[sl], obj=res.obj[sl],
                dual_obj=res.dual_obj[sl], pres=res.pres[sl],
                dres=res.dres[sl], gap=res.gap[sl],
                converged=res.converged[sl])

        return [view(i) for i in range(k)]

    def _row_viol(self, res):
        """(S,) max PER-ROW relative constraint violation in USER
        space.  The kernel's pres normalizes the max scaled violation
        by the max scaled bound across ALL rows, which can hide a huge
        violation on a small-scale row (measured: a 4999-unit forcing
        violation read as pres 5.6e-5 on sizes-3); dive decisions need
        the honest componentwise check."""
        b = self.batch
        x = np.asarray(res.x, np.float64)
        A = np.asarray(b.A, np.float64)
        Ax = np.einsum("smn,sn->sm", A, x)
        # violation relative to the row's operand magnitude (sum of
        # |a_j x_j|), so a forcing row with bound 0 is judged against
        # its actual flow, not against an absolute unit
        mag = np.einsum("smn,sn->sm", np.abs(A), np.abs(x))
        lo = np.asarray(b.row_lo, np.float64)
        hi = np.asarray(b.row_hi, np.float64)
        vlo = np.where(np.isfinite(lo), np.maximum(lo - Ax, 0.0)
                       / (1.0 + np.abs(lo) + mag), 0.0)
        vhi = np.where(np.isfinite(hi), np.maximum(Ax - hi, 0.0)
                       / (1.0 + np.abs(hi) + mag), 0.0)
        return np.maximum(vlo, vhi).max(axis=1)

    # Branch decisions discriminate STRUCTURAL infeasibility (an
    # unservable demand shows as O(1) relative violation) from solver
    # noise (a converged scaled-eps solve can carry unit-scale
    # violations on big-M rows, ~1e-4 relative); sub-threshold true
    # infeasibilities surface again as the dive's freedom shrinks and
    # are handled by the release/retry machinery.
    VIOL_TOL = 1e-3

    def _feasible(self, res):
        return (bool(np.all(np.asarray(res.converged)))
                and float(np.max(self._row_viol(res))) < self.VIOL_TOL)

    def solve_mip(self, int_tol=1e-4, perturb=1e-7, max_rounds=None,
                  verbose=False, seed=0, dive_eps=None):
        """Two-phase LP-diving MIP solve.  Returns a dict with:
          incumbent  — objective of the integer-feasible solution
          bound      — root LP relaxation bound (valid outer bound)
          gap        — |incumbent - bound| / |incumbent|
          x          — (S, N) solution (integer slots integral)
          rounds, lp_solves — dive statistics
        Raises RuntimeError if no integer-feasible point is found
        (both strong-rounding directions infeasible).

        dive_eps (option "mip_dive_eps", default max(1e-4, solver
        eps)): tolerance of the DIVE solves — branch probes compare
        objectives, they don't publish bounds, so they run loose and
        uncertified; only the root relaxation (outer bound) and the
        final fixed-integer solve (incumbent) run at the certified
        tolerance (VERDICT r3 item 5)."""
        b = self.batch
        imask = np.asarray(b.integer_mask).copy()
        live = np.asarray(b.prob) > 0
        imask[~live] = False          # padding scenarios: don't dive
        lb = np.asarray(b.lb, np.float64).copy()
        ub = np.asarray(b.ub, np.float64).copy()
        dt = b.c.dtype
        S, N = lb.shape
        if dive_eps is None:
            dive_eps = float(self.options.get(
                "mip_dive_eps", max(1e-4, float(self.solver_eps))))

        # deterministic tie-breaking perturbation on integer columns
        # (relative, so scale-free); reported objectives use the TRUE c
        c_s = np.asarray(b.c, np.float64).copy()
        if perturb:
            rng = np.random.RandomState(seed)
            pert = perturb * (1.0 + np.abs(c_s)) * rng.rand(*c_s.shape)
            c_s = c_s + np.where(imask, pert, 0.0)
        c_s = c_s.astype(dt)

        # a nonant column is ONE shared variable per tree node: any fix
        # must cover every member scenario or the kernel's synchronized
        # members would diverge (ops/pdhg.ConsensusSpec invariant)
        na = np.asarray(b.nonant_idx)
        col_to_k = {int(col): k for k, col in enumerate(na)}
        node_of = np.asarray(b.tree.node_of)
        na_cols = np.zeros(N, bool)
        na_cols[na] = True

        def fix_at(lb_a, ub_a, si, vi, val):
            k = col_to_k.get(int(vi))
            if k is None:
                lb_a[si, vi] = ub_a[si, vi] = val
            else:
                members = node_of[:, k] == node_of[si, k]
                lb_a[members, vi] = ub_a[members, vi] = val

        # the REPORTED outer bound comes from the root relaxation under
        # the TRUE c: the perturbed-c dual objective is only valid for
        # the original problem up to O(perturb)*|c.x|, which could in
        # principle exceed the true optimum by that epsilon
        res_true = self._lp(np.asarray(b.c, dt), lb.astype(dt),
                            ub.astype(dt))
        if not self._feasible(res_true):
            raise RuntimeError("EF LP relaxation infeasible/unsolved")
        root_bound = float(np.sum(np.asarray(res_true.dual_obj)))
        # the dive itself runs on the perturbed c_s (tie-breaking);
        # warm-started from the true-c vertex this re-solve is cheap
        res = self._lp(c_s, lb.astype(dt), ub.astype(dt),
                       x0=res_true.x, y0=res_true.y,
                       eps=dive_eps, certify=False)
        if not self._feasible(res):
            res = res_true

        max_rounds = max_rounds or (int(np.sum(imask)) + 20)
        state = {"res": res, "lp_solves": 2, "rounds": 0}

        # gating binaries: binary b loosens row m for other columns when
        # raising b raises the slack (A[s,m,b] < 0 against a finite hi,
        # or > 0 against a finite lo) and the row also touches a nonant
        A_np = np.asarray(b.A)
        hi_fin = np.isfinite(np.asarray(b.row_hi))           # (S, M)
        lo_fin = np.isfinite(np.asarray(b.row_lo))
        row_has_na = np.any(A_np[:, :, na] != 0, axis=2)     # (S, M)
        loosens = ((A_np < 0) & (hi_fin & row_has_na)[:, :, None]) | \
                  ((A_np > 0) & (lo_fin & row_has_na)[:, :, None])
        is_binary = imask & (np.asarray(b.lb) == 0) & (
            np.asarray(b.ub) == 1)
        gating = is_binary & np.any(loosens, axis=1) & ~na_cols[None, :]
        # a positive-cost binary gating a SHARED variable equals that
        # variable's support indicator at any optimum, so its value is
        # common to the gated nonant's whole tree node: map each gating
        # column to the first nonant slot it gates and broadcast fixes
        # over that node's members (cuts the phase-Z round count by S).
        # Soundness requires the loosening rows to couple the binary to
        # nonant columns EXCLUSIVELY — if those rows also involve
        # scenario-local columns, the support-indicator equality is not
        # implied and a broadcast could cut off the optimum, so such a
        # binary is fixed per-scenario instead.  Broadcasting also
        # requires every gated nonant slot to share one node structure
        # (so "the node's members" is well-defined).
        gate_k = {}
        for j in np.flatnonzero(np.any(gating, axis=0)):
            rows_m = np.any(loosens[:, :, j], axis=0)        # (M,)
            cols_touched = np.any(A_np[:, rows_m, :] != 0,
                                  axis=(0, 1))               # (N,)
            cols_touched[j] = False
            if not (cols_touched & na_cols).any():
                continue
            if (cols_touched & ~na_cols).any():
                continue                  # scenario-local coupling
            ks = [col_to_k[int(cc)]
                  for cc in np.flatnonzero(cols_touched & na_cols)]
            if all(np.array_equal(node_of[:, ks[0]], node_of[:, k2])
                   for k2 in ks[1:]):
                gate_k[int(j)] = ks[0]

        def fix_gating(lb_a, ub_a, si, vi, val):
            k = gate_k.get(int(vi))
            if k is None:
                lb_a[si, vi] = ub_a[si, vi] = val
            else:
                members = node_of[:, k] == node_of[si, k]
                lb_a[members, vi] = ub_a[members, vi] = val

        lb0 = np.asarray(b.lb, np.float64)
        ub0 = np.asarray(b.ub, np.float64)
        bulk_fixed = np.zeros_like(imask)

        def near_integral(v, unfixed):
            """Integrality test scaled to SOLVER NOISE: the kernel's
            accuracy on a value of size |v| is ~eps*|v| (plus slack for
            distance-to-vertex exceeding the KKT residual), so a
            14499.99 read of a true 14500 counts as integral without a
            fixed absolute tol strong-branching noise on every
            large-magnitude integer — while a true .5-fractional at
            that magnitude is NOT swallowed (measured: a
            value-relative int_tol*(1+|v|) test fixed genuine
            fractionals and drove the dive into infeasible corners)."""
            r = np.round(v)
            frac = np.abs(v - r)
            # noise scale follows the accuracy the dive ACTUALLY solves
            # at (dive_eps), floored at the certified eps
            noise = max(float(self.solver_eps), 0.1 * dive_eps)
            atol = int_tol + 100.0 * noise * (1.0 + np.abs(v))
            return r, frac, unfixed & (frac <= np.minimum(atol, 0.4))

        def coupled_dive(mask, phase, weight=None, fixer=None):
            """Sequential strong-rounding dive over `mask` columns at
            the consensus level.  weight: optional (S, N) priority
            multiplier on fractionality.  fixer: bound-fixing fn
            (defaults to the nonant-aware fix_at).  Bulk fixes are
            tentative: on a dead end they are released once and
            re-derived around the strong fixes."""
            fixer = fixer or fix_at
            retried = False
            skip_bulk = False
            while True:
                res = state["res"]
                x = np.asarray(res.x, np.float64)
                unfixed = mask & (lb != ub)
                if not unfixed.any():
                    return
                state["rounds"] += 1
                if state["rounds"] > max_rounds:
                    raise RuntimeError(
                        f"dive did not finish in {max_rounds} rounds "
                        f"(phase {phase})")
                v = np.where(unfixed, x, 0.0)
                r, frac, integral = near_integral(v, unfixed)
                if skip_bulk:
                    # a release without suppressing re-bulk-fixing
                    # would just re-derive the same dead end
                    integral &= False
                if integral.any():
                    rv = np.clip(r, lb, ub)
                    lb[integral] = rv[integral]
                    ub[integral] = rv[integral]
                    bulk_fixed[integral] = True
                still = unfixed & ~integral
                if not still.any():
                    state["res"] = self._lp(
                        c_s, lb.astype(dt), ub.astype(dt),
                        x0=res.x, y0=res.y, eps=dive_eps, certify=False)
                    state["lp_solves"] += 1
                    # bulk fixes are only kept if the re-solve stays
                    # feasible — a wrongly swallowed fractional shows
                    # up here, not at the next strong branch
                    if not self._feasible(state["res"]) \
                            and bulk_fixed.any() and not retried:
                        lb[bulk_fixed] = lb0[bulk_fixed]
                        ub[bulk_fixed] = ub0[bulk_fixed]
                        bulk_fixed[:] = False
                        retried = True
                        skip_bulk = True
                        state["res"] = self._lp(
                            c_s, lb.astype(dt), ub.astype(dt),
                            eps=dive_eps, certify=False)
                        state["lp_solves"] += 1
                        if verbose:
                            global_toc(f"MIP dive {phase}: bulk fixes "
                                       f"broke feasibility — released")
                    continue
                score = frac if weight is None else frac * weight
                flat = np.argmax(np.where(still, score, -1.0))
                si, vi = np.unravel_index(flat, frac.shape)
                # both strong-rounding directions probed in ONE stacked
                # launch (the phase-B floor/ceil-batch trick at the
                # consensus level — VERDICT r3 item 5)
                dirs, dbounds = [], []
                for d in (np.floor(x[si, vi]), np.ceil(x[si, vi])):
                    if d < lb[si, vi] - 1e-9 or d > ub[si, vi] + 1e-9:
                        continue
                    lb2, ub2 = lb.copy(), ub.copy()
                    fixer(lb2, ub2, si, vi, d)
                    dirs.append(d)
                    dbounds.append((lb2.astype(dt), ub2.astype(dt)))
                cands = (self._lp_multi(c_s, dbounds, x0=res.x,
                                        y0=res.y, eps=dive_eps)
                         if dbounds else [])
                state["lp_solves"] += len(dbounds)
                best = None
                for d, cand in zip(dirs, cands):
                    feas = self._feasible(cand)
                    if verbose:
                        global_toc(
                            f"  branch ({si},{vi})={d:g}: feas={feas} "
                            f"pres={float(np.max(np.asarray(cand.pres))):.2e} "
                            f"conv={int(np.sum(np.asarray(cand.converged)))} "
                            f"obj={float(np.sum(np.asarray(cand.obj))):.6g}")
                    if not feas:
                        continue
                    obj = float(np.sum(np.asarray(cand.obj)))
                    if best is None or obj < best[0]:
                        best = (obj, d, cand)
                if best is None:
                    if bulk_fixed.any() and not retried:
                        # release tentative bulk fixes, keep strong ones
                        lb[bulk_fixed] = lb0[bulk_fixed]
                        ub[bulk_fixed] = ub0[bulk_fixed]
                        bulk_fixed[:] = False
                        retried = True
                        skip_bulk = True
                        state["res"] = self._lp(
                            c_s, lb.astype(dt), ub.astype(dt),
                            x0=res.x, y0=res.y, eps=dive_eps,
                            certify=False)
                        state["lp_solves"] += 1
                        if verbose:
                            global_toc(f"MIP dive {phase}: dead end — "
                                       f"released bulk fixes")
                        continue
                    if gate_k.pop(int(vi), None) is not None:
                        # the node-broadcast fix was the culprit (the
                        # support-indicator equality held structurally
                        # but the dive's earlier fixes made it binding
                        # scenario-asymmetrically): demote this binary
                        # to per-scenario fixing and re-probe
                        if verbose:
                            global_toc(f"MIP dive {phase}: dead end — "
                                       f"col {vi} demoted to "
                                       f"per-scenario fixing")
                        continue
                    raise RuntimeError(
                        f"both strong-rounding directions infeasible "
                        f"at scenario {si}, col {vi} (phase {phase})")
                retried = False
                skip_bulk = False
                _, d, state["res"] = best
                fixer(lb, ub, si, vi, d)
                if verbose:
                    global_toc(
                        f"MIP dive {phase} round {state['rounds']}: "
                        f"fixed ({si},{vi})={d:g}, "
                        f"{int(np.sum(mask & (lb != ub)))} left, "
                        f"obj~{best[0]:.6g}")

        def refine_binaries(mask, fixer, phase):
            """1-opt / 2-opt re-testing of fixed BINARY decisions with
            all of them integral: the greedy decided each binary while
            later ones were still fractional (their cost amortized to
            ~nothing), so flips and open/close swaps are re-evaluated
            by one warm consensus LP each (the continuous rest
            re-optimizes exactly).  Measured: recovers ~0.7% on
            sizes-3 (setup binaries) and ~11% on sslp_5_25_50
            (facility-open nonants)."""
            cols = np.flatnonzero(np.any(mask, axis=0))
            if cols.size == 0:
                return

            def rep_scen(vi):
                return int(np.flatnonzero(mask[:, vi])[0])

            # accept threshold scaled to the dive solves' accuracy so
            # loose-eps objective noise can't fake an improvement
            accept = max(1e-7, 0.3 * dive_eps)
            # fingerprint digests of the bound-fixing at which a pass
            # proved "no improvement": a pass re-entered at an
            # UNCHANGED fixing (the sweep loop does this after the
            # other pass improves and then cleans) is a pure duplicate
            # — skip it.  Measured: removes ~1/3 of sizes-3 refine
            # wall.  Digest, not raw bytes: lb/ub are S*N*8 bytes.
            clean = {}

            def _fp(tag):
                h = hashlib.sha1(lb.tobytes())
                h.update(ub.tobytes())
                return (tag, h.hexdigest())
            # refinement probes share the dive iteration cap: a flip
            # whose probe can't converge inside it counts as
            # not-improving.  (Tighter caps were measured to reject
            # winning flips on sizes-3 — the golden's 225000 rounding
            # boundary leaves <0.05% slack.)
            refine_cap = int(self.options.get(
                "mip_refine_max_iters",
                self.options.get("mip_dive_max_iters", 60000)))
            screen_cap = max(2000, refine_cap // 10)
            # ranked-chunk verification: candidates are verified at
            # the full cap in screened-rank order, one 8-wide launch
            # at a time, stopping at the first launch that yields an
            # improvement — a mis-ranked winner is never LOST, it
            # just costs another launch.  mip_verify_chunks bounds how
            # many launches a NO-improvement scan pays before trusting
            # the screen's "nothing here" (budget-capped either way);
            # measured on sizes-3, winners rank in the top launch or
            # the second.
            verify_chunks = int(self.options.get("mip_verify_chunks", 3))

            def flip_bounds(flips):
                lb2, ub2 = lb.copy(), ub.copy()
                for si, vi, nv in flips:
                    fixer(lb2, ub2, si, vi, nv)
                return lb2.astype(dt), ub2.astype(dt)

            def _stacked_probe(flips_list, cap):
                """Evaluate flip variants in fixed-width-8 stacked
                launches at iteration cap `cap`; returns [(obj, feas,
                res)] aligned with flips_list.  A stacked launch runs
                to its SLOWEST member, so the cap is the cost lever."""
                out = []
                for i0 in range(0, len(flips_list), 8):
                    chunk = flips_list[i0:i0 + 8]
                    state["lp_solves"] += len(chunk)
                    pads = [flip_bounds(f) for f in chunk]
                    while len(pads) < 8:
                        pads.append(pads[-1])
                    rs = self._lp_multi(
                        c_s, pads,
                        x0=state["res"].x, y0=state["res"].y,
                        eps=dive_eps, max_iters=cap)
                    for r in rs[:len(chunk)]:
                        out.append((float(np.sum(np.asarray(r.obj))),
                                    self._feasible(r), r))
                return out

            def refine_pass(tag, gen_candidates):
                """Shared screen -> ranked-chunk-verify -> apply-best
                body for the 1-opt and 2-opt passes.  Stage 1 ranks
                every candidate with short-cap launches (ranking needs
                relative order only; feasibility at the short cap is
                not trusted either way).  Stage 2 verifies at the full
                refine cap in rank order, 8 per launch, early-stopping
                at the first launch containing an improvement.
                Measured one-stage alternatives on sizes-3: serial
                LP-per-candidate 72 s; full-cap launches of ALL
                candidates ~115 s (a stacked launch runs to its
                slowest member); this pass keeps the same incumbent at
                a fraction of either."""
                nonlocal budget
                improved_any = False
                while budget > 0:
                    if clean.get(_fp(tag)):
                        return improved_any
                    cands = gen_candidates()
                    if not cands:
                        return improved_any
                    cur = float(np.sum(np.asarray(state["res"].obj)))
                    if len(cands) > 8:
                        # screens are the cheap stage: charge budget
                        # per LAUNCH (the full-cap verifies below
                        # charge per candidate)
                        budget -= (len(cands) + 7) // 8
                        screened = _stacked_probe(cands, screen_cap)
                        order = np.argsort([o for o, _, _ in screened])
                        cands = [cands[i] for i in order]
                    best = None
                    for ci in range(0, min(len(cands),
                                           8 * verify_chunks), 8):
                        if budget <= 0 and ci:
                            break
                        chunk = cands[ci:ci + 8]
                        budget -= len(chunk)
                        for f, (obj, feas, r) in zip(
                                chunk,
                                _stacked_probe(chunk, refine_cap)):
                            if not feas:
                                continue
                            if obj < cur - accept * (1 + abs(cur)) \
                                    and (best is None or obj < best[0]):
                                best = (obj, f, r)
                        if best is not None:
                            break   # improvement in this launch
                    if best is None:
                        clean[_fp(tag)] = True
                        return improved_any
                    obj, f, r = best
                    for si, vi, nv in f:
                        fixer(lb, ub, si, vi, nv)
                    state["res"] = r
                    improved_any = True
                    if verbose:
                        global_toc(f"MIP dive {phase} {tag}(batch): "
                                   f"{[(v, nv) for _, v, nv in f]}, "
                                   f"obj~{obj:.6g}")
                return improved_any

            def gen_one_opt():
                """Single flips of every fixed binary."""
                flips = []
                for vi in cols:
                    si = rep_scen(vi)
                    if lb[si, vi] == ub[si, vi]:
                        flips.append([(si, vi, 1.0 - lb[si, vi])])
                return flips

            def gen_two_opt():
                """Open/close swaps single flips cannot reach (closing
                alone is infeasible, opening alone is pure cost; the
                swap can still be net cheaper)."""
                pairs = []
                for vi in cols:
                    si = rep_scen(vi)
                    if lb[si, vi] != ub[si, vi] or lb[si, vi] != 1:
                        continue
                    for vj in cols:
                        sj = rep_scen(vj)
                        if vj == vi or lb[sj, vj] != ub[sj, vj] \
                                or lb[sj, vj] != 0:
                            continue
                        pairs.append([(si, vi, 0.0), (sj, vj, 1.0)])
                return pairs

            improved = True
            sweep = 0
            budget = 12 * max(cols.size, 1)
            while improved and sweep < 4 and budget > 0:
                improved = False
                sweep += 1
                # 1-opt: re-test each decision with all binaries fixed
                if refine_pass("1-opt", gen_one_opt):
                    improved = True
                if not improved and refine_pass("2-opt", gen_two_opt):
                    improved = True

        # ---- Phase Z: gating binaries, costliest first -----------------
        if gating.any():
            coupled_dive(gating, "Z",
                         weight=1.0 + np.abs(np.asarray(b.c, np.float64)),
                         fixer=fix_gating)
            refine_binaries(gating, fix_gating, "Z")
        # ---- Phase A: integer nonants over the consensus EF ------------
        na_int = imask & na_cols[None, :]
        coupled_dive(na_int, "A")
        na_bin = na_int & is_binary
        if na_bin.any():
            refine_binaries(na_bin, fix_at, "A")
        res = state["res"]
        lp_solves = state["lp_solves"]
        rounds = state["rounds"]

        # ---- Bridge: pin continuous nonants at consensus values --------
        cont_na = (~imask) & na_cols[None, :] & live[:, None]
        if cont_na.any():
            # ONE certified tight re-solve before pinning: the dive ran
            # loose (dive_eps), and pins at 1e-4-accurate values can
            # make the fully-fixed final system infeasible at the
            # certified tolerance
            res = self._lp(c_s, lb.astype(dt), ub.astype(dt),
                           x0=res.x, y0=res.y)
            lp_solves += 1
            x = np.asarray(res.x, np.float64)
            pin = np.clip(x, lb, ub)
            lb = np.where(cont_na, pin, lb)
            ub = np.where(cont_na, pin, ub)

        # ---- Phase B: per-scenario integers, batched parallel dives ----
        b_mask = imask & ~na_cols[None, :]
        bx, by = res.x, res.y
        # bulk fixes are TENTATIVE in phase B: rounding a near-integral
        # value pins it to the wrong integer when later strong fixes
        # shift the vertex; on a dead end (both directions infeasible)
        # the affected scenario's bulk fixes are released and re-derived
        bulk_fixed[:] = False           # phase-B scope only
        retried = np.zeros(S, bool)
        # released scenarios skip re-bulk-fixing until a strong fix
        # lands (else a release just re-derives the same dead end)
        no_bulk = np.zeros(S, bool)
        while True:
            unfixed = b_mask & (lb != ub)
            if not unfixed.any():
                break
            rounds += 1
            if rounds > max_rounds:
                raise RuntimeError(f"dive did not finish in "
                                   f"{max_rounds} rounds (phase B)")
            # fresh independent solve under current bounds
            res = self._lp(c_s, lb.astype(dt), ub.astype(dt),
                           x0=bx, y0=by, consensus=False,
                           eps=dive_eps, certify=False)
            lp_solves += 1
            bx, by = res.x, res.y
            # scenarios whose system went infeasible under bulk fixes:
            # release those fixes before branching anything
            scen_bad = ((self._row_viol(res) >= self.VIOL_TOL)
                        | ~np.asarray(res.converged)) & live
            fixable = scen_bad & bulk_fixed.any(axis=1) & ~retried
            if fixable.any():
                rel = fixable[:, None] & bulk_fixed
                lb = np.where(rel, lb0, lb)
                ub = np.where(rel, ub0, ub)
                bulk_fixed &= ~rel
                retried |= fixable
                no_bulk |= fixable
                if verbose:
                    global_toc(f"MIP dive B round {rounds}: "
                               f"{int(np.sum(fixable))} scenario(s) "
                               f"infeasible under bulk fixes — "
                               f"released")
                continue
            if scen_bad.any():
                bad = int(np.flatnonzero(scen_bad)[0])
                xb = np.asarray(res.x, np.float64)[bad]
                Axb = np.asarray(b.A, np.float64)[bad] @ xb
                lo_b = np.asarray(b.row_lo, np.float64)[bad]
                hi_b = np.asarray(b.row_hi, np.float64)[bad]
                vb = np.maximum(
                    np.where(np.isfinite(lo_b), lo_b - Axb, 0),
                    np.where(np.isfinite(hi_b), Axb - hi_b, 0))
                wr = int(np.argmax(vb))
                raise RuntimeError(
                    f"phase-B subproblem infeasible at scenario {bad} "
                    f"(viol={float(self._row_viol(res)[bad]):.3e}, "
                    f"tol={self.VIOL_TOL:.1e}) with no bulk fixes to release; "
                    f"worst row {wr}: Ax={Axb[wr]:.4f} "
                    f"lo={lo_b[wr]:.4f} hi={hi_b[wr]:.4f}")
            x = np.asarray(res.x, np.float64)
            v = np.where(unfixed, x, 0.0)
            r, frac, integral = near_integral(v, unfixed)
            integral &= ~no_bulk[:, None]
            # setups first, quantities second (same reasoning as phase
            # Z): while a scenario still has unfixed binaries, don't
            # bulk-lock its general integers — their relaxation values
            # assume amortized setup costs and overspend on setups
            bin_col = np.any(is_binary, axis=0)
            open_bin = (unfixed & is_binary).any(axis=1)
            integral &= ~(open_bin[:, None] & ~bin_col[None, :])
            # and strong-branch binaries before quantities
            frac = np.where(
                open_bin[:, None] & ~bin_col[None, :], 0.0, frac)
            if integral.any():
                rv = np.clip(r, lb, ub)
                lb = np.where(integral, rv, lb)
                ub = np.where(integral, rv, ub)
                bulk_fixed |= integral
            still = unfixed & ~integral
            if not still.any():
                continue
            # every scenario strong-rounds its own most fractional var
            pick = np.argmax(np.where(still, frac, -1.0), axis=1)  # (S,)
            has = still[np.arange(S), pick]
            vals = x[np.arange(S), pick]
            lo_d, hi_d = np.floor(vals), np.ceil(vals)
            # floor-batch + ceil-batch in ONE stacked launch (the two
            # directions share the while_loop, paying max not sum)
            rows = np.flatnonzero(has)
            dbounds, dvs = [], []
            for dvals in (lo_d, hi_d):
                lb2, ub2 = lb.copy(), ub.copy()
                dv = np.clip(dvals[rows], lb[rows, pick[rows]],
                             ub[rows, pick[rows]])
                lb2[rows, pick[rows]] = dv
                ub2[rows, pick[rows]] = dv
                dbounds.append((lb2.astype(dt), ub2.astype(dt)))
                dvs.append(dv)
            cands = self._lp_multi(c_s, dbounds, x0=bx, y0=by,
                                   consensus=False, eps=dive_eps)
            lp_solves += 2
            branches = []
            for cand, dv in zip(cands, dvs):
                feas = ((self._row_viol(cand) < self.VIOL_TOL)
                        & np.asarray(cand.converged))
                branches.append((np.asarray(cand.obj, np.float64),
                                 feas, dv, rows))
            (obj_lo, feas_lo, dv_lo, rows), (obj_hi, feas_hi, dv_hi, _) \
                = branches
            neither = has & ~(feas_lo | feas_hi)
            if neither.any():
                release = neither & bulk_fixed.any(axis=1) & ~retried
                if not release.any():
                    bad = int(np.flatnonzero(neither)[0])
                    raise RuntimeError(
                        f"both strong-rounding directions infeasible "
                        f"at scenario {bad}, col {int(pick[bad])}: "
                        f"v={vals[bad]:.6f} "
                        f"viol(parent)="
                        f"{float(self._row_viol(res)[bad]):.3e} "
                        f"tol={self.VIOL_TOL:.1e}")
                # release the dead-ended scenarios' bulk fixes and
                # re-derive them around the strong fixes kept so far
                rel = release[:, None] & bulk_fixed
                lb = np.where(rel, lb0, lb)
                ub = np.where(rel, ub0, ub)
                bulk_fixed &= ~rel
                retried |= release
                no_bulk |= release
                if verbose:
                    global_toc(f"MIP dive B round {rounds}: released "
                               f"bulk fixes of "
                               f"{int(np.sum(release))} scenario(s)")
                continue
            retried[:] = False
            take_lo = feas_lo & ((obj_lo <= obj_hi) | ~feas_hi)
            choice = np.where(take_lo, lo_d, hi_d)
            keep = has & ~neither
            rows = np.flatnonzero(keep)
            dv = np.clip(choice[rows], lb[rows, pick[rows]],
                         ub[rows, pick[rows]])
            lb[rows, pick[rows]] = dv
            ub[rows, pick[rows]] = dv
            no_bulk[rows] = False
            if verbose:
                global_toc(f"MIP dive B round {rounds}: fixed "
                           f"{rows.size} scenario vars, "
                           f"{int(np.sum(b_mask & (lb != ub)))} left")

        # ---- final solve under full fixing, TRUE objective -------------
        final = self._lp(np.asarray(b.c, dt), lb.astype(dt),
                         ub.astype(dt), x0=bx, y0=by, consensus=False)
        lp_solves += 1
        # acceptance is the honest user-space row-violation test (the
        # reported `viol` honesty metric); a hard-to-converge but
        # primal-feasible final system is a valid incumbent
        if float(np.max(self._row_viol(final)[live])) >= self.VIOL_TOL:
            raise RuntimeError("fixed-integer final LP infeasible")
        x = np.asarray(final.x, np.float64)
        x = np.where(imask, np.clip(np.round(x), lb, ub), x)
        p = np.asarray(b.prob, np.float64)
        incumbent = float(np.sum(
            p * (np.einsum("sn,sn->s", np.asarray(b.c, np.float64), x)
                 + 0.5 * np.einsum(
                     "sn,sn->s", np.asarray(b.qdiag, np.float64), x * x)
                 + np.asarray(b.obj_const, np.float64))))
        gap = abs(incumbent - root_bound) / max(abs(incumbent), 1e-9)
        self._result = final
        # honesty metric: worst relative row violation of the SNAPPED
        # integer solution (the FeasibilityTol analog; first-order
        # kernel, so looser than a simplex basis would give)
        import dataclasses as _dc
        snapped = _dc.replace(final, x=np.asarray(x, dt))
        viol = float(np.max(self._row_viol(snapped)))
        # the k-fold tiled probe stacks (_lp_multi) are per-run scratch
        # holding k copies of the constraint tensor — release them (the
        # same accretion rule spopt.evaluate_candidates enforces)
        for key in [k2 for k2 in self._np_cache
                    if isinstance(k2, tuple) and k2
                    and k2[0] == "mip_stack"]:
            del self._np_cache[key]
        return {"incumbent": incumbent, "bound": root_bound, "gap": gap,
                "x": x, "viol": viol, "rounds": rounds,
                "lp_solves": lp_solves}

"""ExtensiveFormMIP — EF solves with integer variables.

The reference gets MIP optima by handing the EF to a commercial
branch-and-cut solver (reference opt/ef.py:66 solve_extensive_form ->
Gurobi/CPLEX).  There is no branch-and-bound on a TPU; SURVEY.md §7.8
prescribes the alternative this class implements: LP relaxation +
progressive fix-and-round, with every LP a batched PDHG solve so the
whole dive stays on-device.

Method — three-phase LP diving with strong rounding:

  0. solve the consensus LP relaxation -> valid outer bound (the root
     relaxation bound branch-and-bound would start from).
  Phase Z (gating binaries): strong-round the binaries that GATE a
     nonant column — a binary b gates v when raising b loosens a row
     constraining v (the big-M setup-forcing pattern: x - M z <= 0).
     These drive the structural cost tradeoffs, and their LP values
     are the least trustworthy (a big-M relaxation amortizes the
     binary's cost to ~nothing), so they are decided FIRST, by
     cost-weighted fractionality, each by solving the EF with the
     binary fixed 0 and 1 and keeping the cheaper feasible direction.
     Deciding production quantities before setups inverts the
     economics and overspends on setups (measured on sizes-3:
     +1.7% incumbent).
  Phase A (coupled): dive on the INTEGER NONANT columns over the
     consensus EF solve: bulk-fix every one within `int_tol` of an
     integer, then strong-round the most fractional one.  Nonant fixes
     are applied to every scenario through the tree node (the
     ConsensusSpec shared-variable invariant).
  Bridge: pin continuous nonants at their consensus values — the EF
     then separates by scenario.
  Phase B (separable): recover the remaining per-scenario integers
     with BATCHED parallel dives: every scenario bulk-fixes its own
     near-integral variables and strong-rounds its own most fractional
     one, all scenarios at once — two batched independent solves per
     round (floor-batch, ceil-batch), so the round count is
     max-over-scenarios of the fractional depth, not the sum.
  3. final batched solve with all integers fixed = integer-feasible
     incumbent; (incumbent - root bound)/|incumbent| is a TRUE
     optimality gap (bound valid, incumbent feasible).

Degenerate optimal faces are broken by a deterministic relative cost
perturbation (`perturb`) on integer columns so the kernel converges to
a vertex-like point where implicitly-integer variables (network /
transportation structure) come out integral and bulk-fixing does the
work; perturbation is removed from all REPORTED objective values.

Used by the integer-golden tests (sizes-3 EF == 220000 at 2
significant figures, reference mpisppy/tests/test_ef_ph.py:137).
"""

from __future__ import annotations

import numpy as np

from .. import global_toc
from .ef import ExtensiveForm


class ExtensiveFormMIP(ExtensiveForm):
    def __init__(self, options, all_scenario_names, **kwargs):
        super().__init__(options, all_scenario_names, **kwargs)
        if not bool(np.any(np.asarray(self.batch.integer_mask))):
            raise ValueError("batch has no integer variables; use "
                             "ExtensiveForm")

    # -- one consensus LP solve under current fixing bounds ---------------
    def _lp(self, c_s, lb, ub, x0=None, y0=None, consensus=True):
        b = self.batch
        p = np.asarray(b.prob)[:, None]
        res = self.solver.solve(
            self.prep, c_s * p, b.qdiag * p, lb, ub,
            obj_const=b.obj_const * b.prob,
            x0=x0, y0=y0,
            consensus=self.consensus if consensus else None)
        if not bool(np.all(np.asarray(res.converged))):
            if consensus:
                res = self._certified_ef_resolve(
                    res, c=np.asarray(c_s, np.float64) * p,
                    qdiag=np.asarray(b.qdiag, np.float64) * p,
                    lb=lb, ub=ub,
                    obj_const=np.asarray(b.obj_const, np.float64)
                    * np.asarray(b.prob, np.float64))
            else:
                res = self._certified_resolve(
                    res, c=np.asarray(c_s, np.float64) * p,
                    qdiag=np.asarray(b.qdiag, np.float64) * p,
                    lb=lb, ub=ub,
                    obj_const=np.asarray(b.obj_const, np.float64)
                    * np.asarray(b.prob, np.float64))
        return res

    def _row_viol(self, res):
        """(S,) max PER-ROW relative constraint violation in USER
        space.  The kernel's pres normalizes the max scaled violation
        by the max scaled bound across ALL rows, which can hide a huge
        violation on a small-scale row (measured: a 4999-unit forcing
        violation read as pres 5.6e-5 on sizes-3); dive decisions need
        the honest componentwise check."""
        b = self.batch
        x = np.asarray(res.x, np.float64)
        A = np.asarray(b.A, np.float64)
        Ax = np.einsum("smn,sn->sm", A, x)
        # violation relative to the row's operand magnitude (sum of
        # |a_j x_j|), so a forcing row with bound 0 is judged against
        # its actual flow, not against an absolute unit
        mag = np.einsum("smn,sn->sm", np.abs(A), np.abs(x))
        lo = np.asarray(b.row_lo, np.float64)
        hi = np.asarray(b.row_hi, np.float64)
        vlo = np.where(np.isfinite(lo), np.maximum(lo - Ax, 0.0)
                       / (1.0 + np.abs(lo) + mag), 0.0)
        vhi = np.where(np.isfinite(hi), np.maximum(Ax - hi, 0.0)
                       / (1.0 + np.abs(hi) + mag), 0.0)
        return np.maximum(vlo, vhi).max(axis=1)

    # Branch decisions discriminate STRUCTURAL infeasibility (an
    # unservable demand shows as O(1) relative violation) from solver
    # noise (a converged scaled-eps solve can carry unit-scale
    # violations on big-M rows, ~1e-4 relative); sub-threshold true
    # infeasibilities surface again as the dive's freedom shrinks and
    # are handled by the release/retry machinery.
    VIOL_TOL = 1e-3

    def _feasible(self, res):
        return (bool(np.all(np.asarray(res.converged)))
                and float(np.max(self._row_viol(res))) < self.VIOL_TOL)

    def solve_mip(self, int_tol=1e-4, perturb=1e-7, max_rounds=None,
                  verbose=False, seed=0):
        """Two-phase LP-diving MIP solve.  Returns a dict with:
          incumbent  — objective of the integer-feasible solution
          bound      — root LP relaxation bound (valid outer bound)
          gap        — |incumbent - bound| / |incumbent|
          x          — (S, N) solution (integer slots integral)
          rounds, lp_solves — dive statistics
        Raises RuntimeError if no integer-feasible point is found
        (both strong-rounding directions infeasible)."""
        b = self.batch
        imask = np.asarray(b.integer_mask).copy()
        live = np.asarray(b.prob) > 0
        imask[~live] = False          # padding scenarios: don't dive
        lb = np.asarray(b.lb, np.float64).copy()
        ub = np.asarray(b.ub, np.float64).copy()
        dt = b.c.dtype
        S, N = lb.shape

        # deterministic tie-breaking perturbation on integer columns
        # (relative, so scale-free); reported objectives use the TRUE c
        c_s = np.asarray(b.c, np.float64).copy()
        if perturb:
            rng = np.random.RandomState(seed)
            pert = perturb * (1.0 + np.abs(c_s)) * rng.rand(*c_s.shape)
            c_s = c_s + np.where(imask, pert, 0.0)
        c_s = c_s.astype(dt)

        # a nonant column is ONE shared variable per tree node: any fix
        # must cover every member scenario or the kernel's synchronized
        # members would diverge (ops/pdhg.ConsensusSpec invariant)
        na = np.asarray(b.nonant_idx)
        col_to_k = {int(col): k for k, col in enumerate(na)}
        node_of = np.asarray(b.tree.node_of)
        na_cols = np.zeros(N, bool)
        na_cols[na] = True

        def fix_at(lb_a, ub_a, si, vi, val):
            k = col_to_k.get(int(vi))
            if k is None:
                lb_a[si, vi] = ub_a[si, vi] = val
            else:
                members = node_of[:, k] == node_of[si, k]
                lb_a[members, vi] = ub_a[members, vi] = val

        # the REPORTED outer bound comes from the root relaxation under
        # the TRUE c: the perturbed-c dual objective is only valid for
        # the original problem up to O(perturb)*|c.x|, which could in
        # principle exceed the true optimum by that epsilon
        res_true = self._lp(np.asarray(b.c, dt), lb.astype(dt),
                            ub.astype(dt))
        if not self._feasible(res_true):
            raise RuntimeError("EF LP relaxation infeasible/unsolved")
        root_bound = float(np.sum(np.asarray(res_true.dual_obj)))
        # the dive itself runs on the perturbed c_s (tie-breaking);
        # warm-started from the true-c vertex this re-solve is cheap
        res = self._lp(c_s, lb.astype(dt), ub.astype(dt),
                       x0=res_true.x, y0=res_true.y)
        if not self._feasible(res):
            res = res_true

        max_rounds = max_rounds or (int(np.sum(imask)) + 20)
        state = {"res": res, "lp_solves": 2, "rounds": 0}

        # gating binaries: binary b loosens row m for other columns when
        # raising b raises the slack (A[s,m,b] < 0 against a finite hi,
        # or > 0 against a finite lo) and the row also touches a nonant
        A_np = np.asarray(b.A)
        hi_fin = np.isfinite(np.asarray(b.row_hi))           # (S, M)
        lo_fin = np.isfinite(np.asarray(b.row_lo))
        row_has_na = np.any(A_np[:, :, na] != 0, axis=2)     # (S, M)
        loosens = ((A_np < 0) & (hi_fin & row_has_na)[:, :, None]) | \
                  ((A_np > 0) & (lo_fin & row_has_na)[:, :, None])
        is_binary = imask & (np.asarray(b.lb) == 0) & (
            np.asarray(b.ub) == 1)
        gating = is_binary & np.any(loosens, axis=1) & ~na_cols[None, :]
        # a positive-cost binary gating a SHARED variable equals that
        # variable's support indicator at any optimum, so its value is
        # common to the gated nonant's whole tree node: map each gating
        # column to the first nonant slot it gates and broadcast fixes
        # over that node's members (cuts the phase-Z round count by S).
        # Soundness requires the loosening rows to couple the binary to
        # nonant columns EXCLUSIVELY — if those rows also involve
        # scenario-local columns, the support-indicator equality is not
        # implied and a broadcast could cut off the optimum, so such a
        # binary is fixed per-scenario instead.  Broadcasting also
        # requires every gated nonant slot to share one node structure
        # (so "the node's members" is well-defined).
        gate_k = {}
        for j in np.flatnonzero(np.any(gating, axis=0)):
            rows_m = np.any(loosens[:, :, j], axis=0)        # (M,)
            cols_touched = np.any(A_np[:, rows_m, :] != 0,
                                  axis=(0, 1))               # (N,)
            cols_touched[j] = False
            if not (cols_touched & na_cols).any():
                continue
            if (cols_touched & ~na_cols).any():
                continue                  # scenario-local coupling
            ks = [col_to_k[int(cc)]
                  for cc in np.flatnonzero(cols_touched & na_cols)]
            if all(np.array_equal(node_of[:, ks[0]], node_of[:, k2])
                   for k2 in ks[1:]):
                gate_k[int(j)] = ks[0]

        def fix_gating(lb_a, ub_a, si, vi, val):
            k = gate_k.get(int(vi))
            if k is None:
                lb_a[si, vi] = ub_a[si, vi] = val
            else:
                members = node_of[:, k] == node_of[si, k]
                lb_a[members, vi] = ub_a[members, vi] = val

        lb0 = np.asarray(b.lb, np.float64)
        ub0 = np.asarray(b.ub, np.float64)
        bulk_fixed = np.zeros_like(imask)

        def near_integral(v, unfixed):
            """Integrality test scaled to SOLVER NOISE: the kernel's
            accuracy on a value of size |v| is ~eps*|v| (plus slack for
            distance-to-vertex exceeding the KKT residual), so a
            14499.99 read of a true 14500 counts as integral without a
            fixed absolute tol strong-branching noise on every
            large-magnitude integer — while a true .5-fractional at
            that magnitude is NOT swallowed (measured: a
            value-relative int_tol*(1+|v|) test fixed genuine
            fractionals and drove the dive into infeasible corners)."""
            r = np.round(v)
            frac = np.abs(v - r)
            atol = int_tol + 100.0 * float(self.solver_eps) * (
                1.0 + np.abs(v))
            return r, frac, unfixed & (frac <= np.minimum(atol, 0.4))

        def coupled_dive(mask, phase, weight=None, fixer=None):
            """Sequential strong-rounding dive over `mask` columns at
            the consensus level.  weight: optional (S, N) priority
            multiplier on fractionality.  fixer: bound-fixing fn
            (defaults to the nonant-aware fix_at).  Bulk fixes are
            tentative: on a dead end they are released once and
            re-derived around the strong fixes."""
            fixer = fixer or fix_at
            retried = False
            skip_bulk = False
            while True:
                res = state["res"]
                x = np.asarray(res.x, np.float64)
                unfixed = mask & (lb != ub)
                if not unfixed.any():
                    return
                state["rounds"] += 1
                if state["rounds"] > max_rounds:
                    raise RuntimeError(
                        f"dive did not finish in {max_rounds} rounds "
                        f"(phase {phase})")
                v = np.where(unfixed, x, 0.0)
                r, frac, integral = near_integral(v, unfixed)
                if skip_bulk:
                    # a release without suppressing re-bulk-fixing
                    # would just re-derive the same dead end
                    integral &= False
                if integral.any():
                    rv = np.clip(r, lb, ub)
                    lb[integral] = rv[integral]
                    ub[integral] = rv[integral]
                    bulk_fixed[integral] = True
                still = unfixed & ~integral
                if not still.any():
                    state["res"] = self._lp(
                        c_s, lb.astype(dt), ub.astype(dt),
                        x0=res.x, y0=res.y)
                    state["lp_solves"] += 1
                    # bulk fixes are only kept if the re-solve stays
                    # feasible — a wrongly swallowed fractional shows
                    # up here, not at the next strong branch
                    if not self._feasible(state["res"]) \
                            and bulk_fixed.any() and not retried:
                        lb[bulk_fixed] = lb0[bulk_fixed]
                        ub[bulk_fixed] = ub0[bulk_fixed]
                        bulk_fixed[:] = False
                        retried = True
                        skip_bulk = True
                        state["res"] = self._lp(
                            c_s, lb.astype(dt), ub.astype(dt))
                        state["lp_solves"] += 1
                        if verbose:
                            global_toc(f"MIP dive {phase}: bulk fixes "
                                       f"broke feasibility — released")
                    continue
                score = frac if weight is None else frac * weight
                flat = np.argmax(np.where(still, score, -1.0))
                si, vi = np.unravel_index(flat, frac.shape)
                best = None
                for d in (np.floor(x[si, vi]), np.ceil(x[si, vi])):
                    if d < lb[si, vi] - 1e-9 or d > ub[si, vi] + 1e-9:
                        continue
                    lb2, ub2 = lb.copy(), ub.copy()
                    fixer(lb2, ub2, si, vi, d)
                    cand = self._lp(c_s, lb2.astype(dt), ub2.astype(dt),
                                    x0=res.x, y0=res.y)
                    state["lp_solves"] += 1
                    feas = self._feasible(cand)
                    if verbose:
                        global_toc(
                            f"  branch ({si},{vi})={d:g}: feas={feas} "
                            f"pres={float(np.max(np.asarray(cand.pres))):.2e} "
                            f"conv={int(np.sum(np.asarray(cand.converged)))} "
                            f"obj={float(np.sum(np.asarray(cand.obj))):.6g}")
                    if not feas:
                        continue
                    obj = float(np.sum(np.asarray(cand.obj)))
                    if best is None or obj < best[0]:
                        best = (obj, d, cand)
                if best is None:
                    if bulk_fixed.any() and not retried:
                        # release tentative bulk fixes, keep strong ones
                        lb[bulk_fixed] = lb0[bulk_fixed]
                        ub[bulk_fixed] = ub0[bulk_fixed]
                        bulk_fixed[:] = False
                        retried = True
                        skip_bulk = True
                        state["res"] = self._lp(
                            c_s, lb.astype(dt), ub.astype(dt),
                            x0=res.x, y0=res.y)
                        state["lp_solves"] += 1
                        if verbose:
                            global_toc(f"MIP dive {phase}: dead end — "
                                       f"released bulk fixes")
                        continue
                    raise RuntimeError(
                        f"both strong-rounding directions infeasible "
                        f"at scenario {si}, col {vi} (phase {phase})")
                retried = False
                skip_bulk = False
                _, d, state["res"] = best
                fixer(lb, ub, si, vi, d)
                if verbose:
                    global_toc(
                        f"MIP dive {phase} round {state['rounds']}: "
                        f"fixed ({si},{vi})={d:g}, "
                        f"{int(np.sum(mask & (lb != ub)))} left, "
                        f"obj~{best[0]:.6g}")

        def refine_binaries(mask, fixer, phase):
            """1-opt / 2-opt re-testing of fixed BINARY decisions with
            all of them integral: the greedy decided each binary while
            later ones were still fractional (their cost amortized to
            ~nothing), so flips and open/close swaps are re-evaluated
            by one warm consensus LP each (the continuous rest
            re-optimizes exactly).  Measured: recovers ~0.7% on
            sizes-3 (setup binaries) and ~11% on sslp_5_25_50
            (facility-open nonants)."""
            cols = np.flatnonzero(np.any(mask, axis=0))
            if cols.size == 0:
                return

            def rep_scen(vi):
                return int(np.flatnonzero(mask[:, vi])[0])

            def try_flip(flips):
                cur = float(np.sum(np.asarray(state["res"].obj)))
                lb2, ub2 = lb.copy(), ub.copy()
                for si, vi, nv in flips:
                    fixer(lb2, ub2, si, vi, nv)
                cand = self._lp(c_s, lb2.astype(dt), ub2.astype(dt),
                                x0=state["res"].x, y0=state["res"].y)
                state["lp_solves"] += 1
                if not self._feasible(cand):
                    return False
                obj = float(np.sum(np.asarray(cand.obj)))
                if obj >= cur - 1e-7 * (1 + abs(cur)):
                    return False
                for si, vi, nv in flips:
                    fixer(lb, ub, si, vi, nv)
                state["res"] = cand
                if verbose:
                    global_toc(f"MIP dive {phase} {len(flips)}-opt: "
                               f"{[(v, nv) for _, v, nv in flips]}, "
                               f"obj~{obj:.6g}")
                return True

            improved = True
            sweep = 0
            budget = 12 * max(cols.size, 1)
            while improved and sweep < 4 and budget > 0:
                improved = False
                sweep += 1
                # 1-opt: re-test each decision with all binaries fixed
                for vi in cols:
                    si = rep_scen(vi)
                    if lb[si, vi] != ub[si, vi] or budget <= 0:
                        continue
                    budget -= 1
                    if try_flip([(si, vi, 1.0 - lb[si, vi])]):
                        improved = True
                # 2-opt: open/close swaps single flips cannot reach
                # (closing alone is infeasible, opening alone is pure
                # cost; the swap can still be net cheaper)
                if not improved:
                    for vi in cols:
                        si = rep_scen(vi)
                        if lb[si, vi] != ub[si, vi] or lb[si, vi] != 1:
                            continue
                        for vj in cols:
                            sj = rep_scen(vj)
                            if vj == vi or lb[sj, vj] != ub[sj, vj] \
                                    or lb[sj, vj] != 0 or budget <= 0:
                                continue
                            budget -= 1
                            if try_flip([(si, vi, 0.0),
                                         (sj, vj, 1.0)]):
                                improved = True
                                break
                        if improved:
                            break

        # ---- Phase Z: gating binaries, costliest first -----------------
        if gating.any():
            coupled_dive(gating, "Z",
                         weight=1.0 + np.abs(np.asarray(b.c, np.float64)),
                         fixer=fix_gating)
            refine_binaries(gating, fix_gating, "Z")
        # ---- Phase A: integer nonants over the consensus EF ------------
        na_int = imask & na_cols[None, :]
        coupled_dive(na_int, "A")
        na_bin = na_int & is_binary
        if na_bin.any():
            refine_binaries(na_bin, fix_at, "A")
        res = state["res"]
        lp_solves = state["lp_solves"]
        rounds = state["rounds"]

        # ---- Bridge: pin continuous nonants at consensus values --------
        x = np.asarray(res.x, np.float64)
        cont_na = (~imask) & na_cols[None, :] & live[:, None]
        if cont_na.any():
            pin = np.clip(x, lb, ub)
            lb = np.where(cont_na, pin, lb)
            ub = np.where(cont_na, pin, ub)

        # ---- Phase B: per-scenario integers, batched parallel dives ----
        b_mask = imask & ~na_cols[None, :]
        bx, by = res.x, res.y
        # bulk fixes are TENTATIVE in phase B: rounding a near-integral
        # value pins it to the wrong integer when later strong fixes
        # shift the vertex; on a dead end (both directions infeasible)
        # the affected scenario's bulk fixes are released and re-derived
        bulk_fixed[:] = False           # phase-B scope only
        retried = np.zeros(S, bool)
        # released scenarios skip re-bulk-fixing until a strong fix
        # lands (else a release just re-derives the same dead end)
        no_bulk = np.zeros(S, bool)
        while True:
            unfixed = b_mask & (lb != ub)
            if not unfixed.any():
                break
            rounds += 1
            if rounds > max_rounds:
                raise RuntimeError(f"dive did not finish in "
                                   f"{max_rounds} rounds (phase B)")
            # fresh independent solve under current bounds
            res = self._lp(c_s, lb.astype(dt), ub.astype(dt),
                           x0=bx, y0=by, consensus=False)
            lp_solves += 1
            bx, by = res.x, res.y
            # scenarios whose system went infeasible under bulk fixes:
            # release those fixes before branching anything
            scen_bad = ((self._row_viol(res) >= self.VIOL_TOL)
                        | ~np.asarray(res.converged)) & live
            fixable = scen_bad & bulk_fixed.any(axis=1) & ~retried
            if fixable.any():
                rel = fixable[:, None] & bulk_fixed
                lb = np.where(rel, lb0, lb)
                ub = np.where(rel, ub0, ub)
                bulk_fixed &= ~rel
                retried |= fixable
                no_bulk |= fixable
                if verbose:
                    global_toc(f"MIP dive B round {rounds}: "
                               f"{int(np.sum(fixable))} scenario(s) "
                               f"infeasible under bulk fixes — "
                               f"released")
                continue
            if scen_bad.any():
                bad = int(np.flatnonzero(scen_bad)[0])
                xb = np.asarray(res.x, np.float64)[bad]
                Axb = np.asarray(b.A, np.float64)[bad] @ xb
                lo_b = np.asarray(b.row_lo, np.float64)[bad]
                hi_b = np.asarray(b.row_hi, np.float64)[bad]
                vb = np.maximum(
                    np.where(np.isfinite(lo_b), lo_b - Axb, 0),
                    np.where(np.isfinite(hi_b), Axb - hi_b, 0))
                wr = int(np.argmax(vb))
                raise RuntimeError(
                    f"phase-B subproblem infeasible at scenario {bad} "
                    f"(viol={float(self._row_viol(res)[bad]):.3e}, "
                    f"tol={self.VIOL_TOL:.1e}) with no bulk fixes to release; "
                    f"worst row {wr}: Ax={Axb[wr]:.4f} "
                    f"lo={lo_b[wr]:.4f} hi={hi_b[wr]:.4f}")
            x = np.asarray(res.x, np.float64)
            v = np.where(unfixed, x, 0.0)
            r, frac, integral = near_integral(v, unfixed)
            integral &= ~no_bulk[:, None]
            # setups first, quantities second (same reasoning as phase
            # Z): while a scenario still has unfixed binaries, don't
            # bulk-lock its general integers — their relaxation values
            # assume amortized setup costs and overspend on setups
            bin_col = np.any(is_binary, axis=0)
            open_bin = (unfixed & is_binary).any(axis=1)
            integral &= ~(open_bin[:, None] & ~bin_col[None, :])
            # and strong-branch binaries before quantities
            frac = np.where(
                open_bin[:, None] & ~bin_col[None, :], 0.0, frac)
            if integral.any():
                rv = np.clip(r, lb, ub)
                lb = np.where(integral, rv, lb)
                ub = np.where(integral, rv, ub)
                bulk_fixed |= integral
            still = unfixed & ~integral
            if not still.any():
                continue
            # every scenario strong-rounds its own most fractional var
            pick = np.argmax(np.where(still, frac, -1.0), axis=1)  # (S,)
            has = still[np.arange(S), pick]
            vals = x[np.arange(S), pick]
            lo_d, hi_d = np.floor(vals), np.ceil(vals)
            branches = []
            for dvals in (lo_d, hi_d):
                lb2, ub2 = lb.copy(), ub.copy()
                rows = np.flatnonzero(has)
                dv = np.clip(dvals[rows], lb[rows, pick[rows]],
                             ub[rows, pick[rows]])
                lb2[rows, pick[rows]] = dv
                ub2[rows, pick[rows]] = dv
                cand = self._lp(c_s, lb2.astype(dt), ub2.astype(dt),
                                x0=bx, y0=by, consensus=False)
                lp_solves += 1
                feas = ((self._row_viol(cand) < self.VIOL_TOL)
                        & np.asarray(cand.converged))
                branches.append((np.asarray(cand.obj, np.float64),
                                 feas, dv, rows))
            (obj_lo, feas_lo, dv_lo, rows), (obj_hi, feas_hi, dv_hi, _) \
                = branches
            neither = has & ~(feas_lo | feas_hi)
            if neither.any():
                release = neither & bulk_fixed.any(axis=1) & ~retried
                if not release.any():
                    bad = int(np.flatnonzero(neither)[0])
                    raise RuntimeError(
                        f"both strong-rounding directions infeasible "
                        f"at scenario {bad}, col {int(pick[bad])}: "
                        f"v={vals[bad]:.6f} "
                        f"viol(parent)="
                        f"{float(self._row_viol(res)[bad]):.3e} "
                        f"tol={self.VIOL_TOL:.1e}")
                # release the dead-ended scenarios' bulk fixes and
                # re-derive them around the strong fixes kept so far
                rel = release[:, None] & bulk_fixed
                lb = np.where(rel, lb0, lb)
                ub = np.where(rel, ub0, ub)
                bulk_fixed &= ~rel
                retried |= release
                no_bulk |= release
                if verbose:
                    global_toc(f"MIP dive B round {rounds}: released "
                               f"bulk fixes of "
                               f"{int(np.sum(release))} scenario(s)")
                continue
            retried[:] = False
            take_lo = feas_lo & ((obj_lo <= obj_hi) | ~feas_hi)
            choice = np.where(take_lo, lo_d, hi_d)
            keep = has & ~neither
            rows = np.flatnonzero(keep)
            dv = np.clip(choice[rows], lb[rows, pick[rows]],
                         ub[rows, pick[rows]])
            lb[rows, pick[rows]] = dv
            ub[rows, pick[rows]] = dv
            no_bulk[rows] = False
            if verbose:
                global_toc(f"MIP dive B round {rounds}: fixed "
                           f"{rows.size} scenario vars, "
                           f"{int(np.sum(b_mask & (lb != ub)))} left")

        # ---- final solve under full fixing, TRUE objective -------------
        final = self._lp(np.asarray(b.c, dt), lb.astype(dt),
                         ub.astype(dt), x0=bx, y0=by, consensus=False)
        lp_solves += 1
        if not self._feasible(final):
            raise RuntimeError("fixed-integer final LP infeasible")
        x = np.asarray(final.x, np.float64)
        x = np.where(imask, np.clip(np.round(x), lb, ub), x)
        p = np.asarray(b.prob, np.float64)
        incumbent = float(np.sum(
            p * (np.einsum("sn,sn->s", np.asarray(b.c, np.float64), x)
                 + 0.5 * np.einsum(
                     "sn,sn->s", np.asarray(b.qdiag, np.float64), x * x)
                 + np.asarray(b.obj_const, np.float64))))
        gap = abs(incumbent - root_bound) / max(abs(incumbent), 1e-9)
        self._result = final
        # honesty metric: worst relative row violation of the SNAPPED
        # integer solution (the FeasibilityTol analog; first-order
        # kernel, so looser than a simplex basis would give)
        import dataclasses as _dc
        snapped = _dc.replace(final, x=np.asarray(x, dt))
        viol = float(np.max(self._row_viol(snapped)))
        return {"incumbent": incumbent, "bound": root_bound, "gap": gap,
                "x": x, "viol": viol, "rounds": rounds,
                "lp_solves": lp_solves}

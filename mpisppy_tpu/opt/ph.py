"""PH — the Progressive Hedging driver (reference: mpisppy/opt/ph.py).

ph_main mirrors the reference pipeline (opt/ph.py:25-71):
PH_Prep -> Iter0 -> iterk_loop -> post_loops, returning
(conv, Eobj, trivial_bound).
"""

from __future__ import annotations

import numpy as np

from .. import global_toc
from ..phbase import PHBase


class PH(PHBase):
    def ph_main(self, finalize=True):
        self.trivial_bound = None
        # crash-resume: a checkpoint replaces Iter0 entirely (the full
        # PHState — warm starts included — comes from the file, so the
        # resumed trajectory replays the uninterrupted one); a missing
        # file falls through to a fresh start, letting drivers pass
        # resume_from unconditionally alongside run_checkpoint
        resume = self.options.get("resume_from")
        from ..resilience.checkpoint import checkpoint_exists
        if resume is not None and checkpoint_exists(resume):
            trivial = self.restore_run_checkpoint(resume)
        else:
            trivial = self.Iter0()
        self.iterk_loop()
        if finalize:
            eobj = self.post_loops()
            global_toc(f"PH done: conv={self.conv:.4e} Eobj={eobj:.6g} "
                       f"trivial_bound={trivial:.6g}")
            return self.conv, eobj, trivial
        return self.conv, None, trivial

    def solution_dict(self, finalize=True):
        """The `ph_main` return values as a structured dict — the serve
        layer's response envelope (serve/service.py, doc/src/serve.md).
        `conv`/`eobj`/`trivial_bound` carry exactly the floats ph_main
        would return on this instance's current state."""
        eobj = self.post_loops() if finalize else None
        return {
            "conv": self.conv,
            "eobj": eobj,
            "trivial_bound": self.trivial_bound,
            "xbar": np.asarray(self.root_xbar()),
            "iterations": int(self.state.it),
            "solve_iters": int(self.state.solve_iters),
        }

"""SchurComplement — structured interior-point solve of the two-stage
EF (reference: mpisppy/opt/sc.py:89-106, which delegates to external
parapint + MA27: per-scenario KKT factorizations and an MPI-assembled
Schur complement on the first-stage block; continuous problems only,
sc.py:18-21).

TPU-native replacement (SURVEY.md §2.9: "batched Cholesky/CG on TPU
for per-scenario KKT blocks; Schur complement assembled with psum"):

A primal-dual log-barrier IPM on the consensus EF.  Per scenario s the
barrier Newton step reduces (normal-equations form) to an N x N SPD
system  M_s = H_mu,s + A_s^T D_s A_s ; splitting columns into the
shared first-stage block x (the nonant slots) and the local recourse
block y_s:

    [ Mxx_s  Mxy_s ] [dx ]   [ rx_s ]
    [ Myx_s  Myy_s ] [dy_s] = [ ry_s ]

all scenarios' Myy are Cholesky-factored IN ONE BATCH, and the
first-stage Schur complement

    C = sum_s ( Mxx_s - Mxy_s Myy_s^{-1} Myx_s ),   (K x K)

is a plain sum over the scenario axis — under a sharded mesh XLA lowers
it to a psum, exactly the role of the reference's MPI reduction inside
parapint.  One K x K solve yields dx; back-substitution (batched) gives
every dy_s.

Continuous problems only (raises on integer batches), like the
reference.  Bounds at +-inf get no barrier; equality rows are relaxed
to a tight box (barrier eps) which keeps the operator SPD.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .. import global_toc
from ..spbase import SPBase

BIG = 1e8


class SchurComplement(SPBase):
    _needs_dense_A = True   # KKT assembly indexes A by scenario
    def __init__(self, options, all_scenario_names, **kwargs):
        super().__init__(options, all_scenario_names, **kwargs)
        if bool(np.asarray(self.batch.integer_mask).any()):
            raise RuntimeError(
                "SchurComplement handles continuous problems only "
                "(so does the reference, opt/sc.py:18-21)")
        o = self.options
        self.max_iter = int(o.get("sc_max_iter", 100))
        self.tol = float(o.get("sc_tol", 1e-7))
        self.mu0 = float(o.get("sc_mu0", 10.0))
        self._solve_jit = jax.jit(self._ipm)
        self.first_stage_solution = None
        self.objective = None

    # -- problem massaging -------------------------------------------------
    def _arrays(self):
        b = self.batch
        # finite boxes for barrier terms; huge-but-finite where inf
        lb = jnp.where(jnp.isfinite(b.lb), b.lb, -BIG)
        ub = jnp.where(jnp.isfinite(b.ub), b.ub, BIG)
        rlo = jnp.where(jnp.isfinite(b.row_lo), b.row_lo, -BIG)
        rhi = jnp.where(jnp.isfinite(b.row_hi), b.row_hi, BIG)
        # equality rows: open a tiny box so slack barriers exist
        eq = rhi - rlo < 1e-12
        rlo = jnp.where(eq, rlo - 1e-7, rlo)
        rhi = jnp.where(eq, rhi + 1e-7, rhi)
        p = b.prob[:, None]
        c = b.c * p                 # probability-weighted objective
        q = b.qdiag * p
        return c, q, lb, ub, rlo, rhi

    # -- the IPM (all jitted; shapes static) -------------------------------
    def _ipm(self, c, q, lb, ub, rlo, rhi):
        b = self.batch
        S, N = c.shape
        K = b.num_nonants
        na = b.nonant_idx
        rest = jnp.setdiff1d(jnp.arange(N), na, size=N - K,
                             assume_unique=False)
        A = b.A
        prob_mask = (b.tree.prob > 0)[:, None]   # padding scenarios

        # strictly interior start: z near the "small" corner of its
        # box, s interior of the row box; the coupling Az = s is an
        # EQUALITY handled by the Newton system (linear -> restored in
        # one full step), so s need not start consistent
        z = jnp.clip(jnp.zeros((S, N), c.dtype), lb + 1e-1, ub - 1e-1)
        zx = jnp.mean(z[:, na], axis=0)
        z = z.at[:, na].set(jnp.broadcast_to(zx[None, :], (S, K)))
        s = jnp.clip(jnp.einsum("smn,sn->sm", A, z),
                     rlo + 1e-1, rhi - 1e-1)

        def barrier_grad_hess(v, lo, hi, mu):
            g = -mu / (v - lo) + mu / (hi - v)
            h = mu / (v - lo) ** 2 + mu / (hi - v) ** 2
            return g, h

        def body(carry, _):
            z, s, mu = carry
            gz, hz = barrier_grad_hess(z, lb, ub, mu)
            gs, hs = barrier_grad_hess(s, rlo, rhi, mu)
            # Newton-KKT of  min c.z + q/2 z^2 + B(z) + B(s)
            #               s.t. Az - s = 0
            # eliminating (ds, dlambda):
            #   (Hz + A^T Hs A) dz = -(gz_full + A^T(gs + Hs r_eq))
            #   ds = A dz + r_eq
            r_eq = jnp.einsum("smn,sn->sm", A, z) - s
            grad = (c + q * z + gz
                    + jnp.einsum("smn,sm->sn", A, gs + hs * r_eq))
            M = (A * hs[:, :, None]).swapaxes(1, 2) @ A
            M = M + jnp.eye(N)[None] * 1e-10
            M = M + jnp.zeros_like(M).at[
                :, jnp.arange(N), jnp.arange(N)].set(q + hz)
            # zero out padding scenarios (identity keeps Cholesky happy)
            M = jnp.where(prob_mask[:, :, None], M, jnp.eye(N)[None])
            grad = jnp.where(prob_mask, grad, 0.0)

            Mxx = M[:, na][:, :, na]                    # (S, K, K)
            Mxy = M[:, na][:, :, rest]                  # (S, K, N-K)
            Myy = M[:, rest][:, :, rest]                # (S, n2, n2)
            rx = -grad[:, na]
            ry = -grad[:, rest]

            L = jnp.linalg.cholesky(Myy)
            def chol_solve(Lb, B):
                w = jax.scipy.linalg.solve_triangular(
                    Lb, B, lower=True)
                return jax.scipy.linalg.solve_triangular(
                    Lb.swapaxes(-1, -2), w, lower=False)
            Yinv_yx = jax.vmap(chol_solve)(L, Mxy.swapaxes(1, 2))
            Yinv_ry = jax.vmap(chol_solve)(L, ry[:, :, None])[..., 0]
            # Schur pieces; the sums over S are the psum analog.
            # padding scenarios (prob 0) are excluded — their dummy
            # unit boxes must not constrain the shared step
            pmask3 = prob_mask[:, :, None]
            C = jnp.sum(jnp.where(pmask3, Mxx - Mxy @ Yinv_yx, 0.0),
                        axis=0)
            rhs = jnp.sum(jnp.where(
                prob_mask, rx - jnp.einsum("skn,sn->sk", Mxy, Yinv_ry),
                0.0), axis=0)
            dx = jnp.linalg.solve(C + jnp.eye(K) * 1e-12, rhs)
            dy = Yinv_ry - jnp.einsum(
                "snk,k->sn", Yinv_yx, dx)
            dz = jnp.zeros_like(z)
            dz = dz.at[:, na].set(jnp.broadcast_to(dx[None], (S, K)))
            dz = dz.at[:, rest].set(dy)
            dz = jnp.where(prob_mask, dz, 0.0)   # pads stay put
            ds = jnp.einsum("smn,sn->sm", A, dz) + jnp.where(
                prob_mask, r_eq, 0.0)

            # fraction-to-boundary step
            def max_step(v, dv, lo, hi):
                t_lo = jnp.where(dv < 0, (lo - v) / dv, jnp.inf)
                t_hi = jnp.where(dv > 0, (hi - v) / dv, jnp.inf)
                return jnp.minimum(jnp.min(t_lo), jnp.min(t_hi))

            alpha = jnp.minimum(
                1.0, 0.995 * jnp.minimum(
                    max_step(z, dz, lb, ub), max_step(s, ds, rlo, rhi)))
            z = z + alpha * dz
            s = s + alpha * ds
            # keep strictly interior: compounding 0.995 steps can
            # round an iterate ONTO its bound, and 1/(z-lb) -> NaN
            z = jnp.clip(z, lb + 1e-12 * (1 + jnp.abs(lb)),
                         ub - 1e-12 * (1 + jnp.abs(ub)))
            s = jnp.clip(s, rlo + 1e-12 * (1 + jnp.abs(rlo)),
                         rhi - 1e-12 * (1 + jnp.abs(rhi)))
            # barrier decrease is fast once the (linear) coupling
            # Az = s is restored, slow while infeasible — shrinking mu
            # on an infeasible iterate strands a super-optimal point
            feas = jnp.max(jnp.abs(jnp.where(prob_mask, r_eq, 0.0)))
            rate = jnp.where(feas < 1e-4, 0.5, 0.95)
            mu = jnp.maximum(mu * rate, 1e-10)
            return (z, s, mu), alpha

        (z, s, mu), _ = jax.lax.scan(
            body, (z, s, self.mu0), None, length=self.max_iter)
        obj = jnp.sum(jnp.sum(c * z + 0.5 * q * z * z, axis=1)
                      + b.obj_const * b.tree.prob)
        return z, obj

    def solve(self):
        """Reference API: SchurComplement.solve (opt/sc.py:89)."""
        c, q, lb, ub, rlo, rhi = self._arrays()
        z, obj = self._solve_jit(c, q, lb, ub, rlo, rhi)
        self.objective = float(obj)
        self.first_stage_solution = np.asarray(
            z[0, np.asarray(self.batch.nonant_idx)])
        global_toc(f"SchurComplement IPM: obj = {self.objective:.6g}")
        return self.objective, self.first_stage_solution

from .mesh import ScenarioMesh  # noqa: F401

"""Multi-host initialization — the DCN story of SURVEY.md §2.3 made
real code.

The reference scales across hosts by launching one MPI rank per
cylinder-shard and splitting COMM_WORLD (reference
spin_the_wheel.py:219-237 _make_comms); inter-host traffic is MPI over
the cluster fabric.  Here each HOST PROCESS calls `init_multihost()`
once; jax.distributed wires the processes into one runtime, after
which `jax.devices()` returns the GLOBAL device list, a ScenarioMesh
over it spans every process, and the very same consensus program
(segment-sum + psum under GSPMD) lowers its reductions to
cross-process collectives — ICI within a slice, DCN across slices.
No algorithm code changes between 1 device, 1 host x N devices, and
M hosts x N devices; that is the point of the design.

On TPU pods every argument is auto-detected from the environment.  On
CPU/GPU fleets (and the 2-process CPU test tier,
tests/test_multihost.py) pass coordinator/num/id explicitly or via
MPISPPY_TPU_COORDINATOR / MPISPPY_TPU_NUM_PROCS /
MPISPPY_TPU_PROC_ID.
"""

from __future__ import annotations

import os

import numpy as np

import jax

from .mesh import ScenarioMesh


def init_multihost(coordinator_address=None, num_processes=None,
                   process_id=None):
    """Join this process into the global JAX runtime
    (jax.distributed.initialize).  Idempotent: a second call is a
    no-op so library code may call it defensively.  Must run BEFORE
    any backend-initializing JAX call (jax.devices etc.) — so the
    idempotence check keeps to our own flag, never jax.process_count()
    (which would itself initialize the backend)."""
    if getattr(init_multihost, "_done", False):
        return
    coordinator_address = coordinator_address or os.environ.get(
        "MPISPPY_TPU_COORDINATOR")
    if num_processes is None and "MPISPPY_TPU_NUM_PROCS" in os.environ:
        num_processes = int(os.environ["MPISPPY_TPU_NUM_PROCS"])
    if process_id is None and "MPISPPY_TPU_PROC_ID" in os.environ:
        process_id = int(os.environ["MPISPPY_TPU_PROC_ID"])
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    init_multihost._done = True


def global_mesh(axis_name="scen"):
    """ScenarioMesh over the GLOBAL device list (call after
    init_multihost)."""
    return ScenarioMesh(devices=jax.devices(), axis_name=axis_name)


class LaneTransport:
    """Host->fabric placement seam of the collective exchange
    (mpmd/collective.py): the two ways a staged slab reaches the lane
    mesh.  Single-process this is plain `device_put` through
    ScenarioMesh._put; once a wheel spans hosts, the SAME two calls go
    through `jax.make_array_from_callback` — each process materializes
    only its addressable lane rows and the fused all-gather's
    collectives cross DCN — so a later multihost PR plugs in here
    without touching the fabric above."""

    def __init__(self, mesh):
        self.mesh = mesh

    def sharded(self, slab):
        """Place a (K, V) slab lane-sharded over the `cyl` axis: each
        lane's rows land on the device (process) that owns that lane —
        the input placement of the fused all-gather."""
        return self.mesh._put(np.asarray(slab), self.mesh.lane_sharding())

    def replicated(self, slab):
        """Place a (K, V) slab fully replicated over the lane mesh —
        the hub->spokes broadcast is exactly this one placement."""
        return self.mesh._put(np.asarray(slab), self.mesh.replicated())


def lane_transport(mesh):
    """The LaneTransport for a fabric's 2-D lane ScenarioMesh."""
    return LaneTransport(mesh)


def process_index():
    return jax.process_index()


def is_coordinator():
    """Analog of the reference's rank-0 gating (global_rank == 0)."""
    return jax.process_index() == 0

"""Device mesh + sharding layer — the TPU-native replacement for the
reference's MPI communicator plumbing (SURVEY.md §2.3).

The reference splits COMM_WORLD into per-tree-node communicators
(spbase.py:333-375) and reduces numpy buffers with comm.Allreduce
(phbase.py:83-87).  Here the scenario axis is a named mesh axis: batches
are placed with a NamedSharding over axis "scen", every consensus
reduction is a sum over that axis inside one jit-compiled program, and
XLA lowers the reductions to ICI collectives (psum / reduce-scatter)
automatically under GSPMD.  Multi-host DCN scaling follows the same
code path — jax.distributed initializes the global mesh.

The n_devices=1 case IS the serial mock (reference mpisppy/MPI.py:19-82
_MockMPIComm): the same program compiles to a single-device executable
with the collectives elided.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ir import ScenarioBatch, pad_scenarios


class ScenarioMesh:
    """A 1-D (or 2-D cylinder x scenario) device mesh for scenario
    parallelism — the analog of the reference's rank grid
    (spin_the_wheel.py:219-237 _make_comms)."""

    def __init__(self, devices=None, axis_name="scen"):
        if devices is None:
            devices = jax.devices()
        self.devices = list(devices)
        self.axis_name = axis_name
        self.mesh = Mesh(np.array(self.devices), (axis_name,))

    @property
    def size(self):
        return len(self.devices)

    def batch_sharding(self):
        """Sharding for (S, ...) scenario-leading arrays."""
        return NamedSharding(self.mesh, P(self.axis_name))

    def replicated(self):
        return NamedSharding(self.mesh, P())

    def shard_batch(self, batch: ScenarioBatch) -> ScenarioBatch:
        """Pad S to a device multiple (zero-probability dummies — the
        sharding analog of the reference's ragged rank slices,
        sputils.py:804-812) and place each leaf: scenario-leading arrays
        sharded on "scen", shared metadata replicated."""
        S = batch.num_scens
        n = self.size
        Spad = ((S + n - 1) // n) * n
        batch = pad_scenarios(batch, Spad)
        shard = self.batch_sharding()
        repl = self.replicated()
        # explicit field -> axis-0-sharded map (field names, not shape
        # heuristics: nonant_idx is (K,) and K can equal Spad)
        scen_leading = {
            "c", "qdiag", "A", "row_lo", "row_hi", "lb", "ub",
            "obj_const", "integer_mask", "node_of", "prob", "var_prob",
        }

        def place(path, leaf):
            if leaf is None:
                return None
            arr = jax.numpy.asarray(leaf)
            name = path[-1].name if hasattr(path[-1], "name") else None
            if name == "A" and arr.shape[0] == 1:
                # shared constraint matrix (ir.ScenarioBatch.shared_A):
                # replicated, not sharded — every device multiplies its
                # scenario shard against the same (M, N) matrix
                return jax.device_put(arr, repl)
            if name in scen_leading:
                return jax.device_put(arr, shard)
            if name == "stage_cost_c":  # (n_stages, S, N)
                return jax.device_put(
                    arr, NamedSharding(self.mesh, P(None, self.axis_name)))
            return jax.device_put(arr, repl)

        return jax.tree_util.tree_map_with_path(place, batch)

    def shard_like_batch(self, arr):
        """Place an (S, ...) array with the batch sharding."""
        return jax.device_put(jax.numpy.asarray(arr), self.batch_sharding())

    def replicate(self, arr):
        return jax.device_put(jax.numpy.asarray(arr), self.replicated())


def local_mesh():
    """Mesh over whatever devices are visible (1 TPU chip, or N forced
    CPU devices under XLA_FLAGS=--xla_force_host_platform_device_count)."""
    return ScenarioMesh()

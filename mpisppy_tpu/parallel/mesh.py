"""Device mesh + sharding layer — the TPU-native replacement for the
reference's MPI communicator plumbing (SURVEY.md §2.3).

The reference splits COMM_WORLD into per-tree-node communicators
(spbase.py:333-375) and reduces numpy buffers with comm.Allreduce
(phbase.py:83-87).  Here the scenario axis is a named mesh axis: batches
are placed with a NamedSharding over axis "scen", every consensus
reduction is a sum over that axis inside one jit-compiled program, and
XLA lowers the reductions to ICI collectives (psum / reduce-scatter)
automatically under GSPMD.  Multi-host DCN scaling follows the same
code path — parallel.distributed.init_multihost wires the processes
into one runtime (jax.distributed), after which this mesh spans the
GLOBAL device list and the same program's collectives cross process
boundaries (exercised by tests/test_multihost.py on a 2-process CPU
fleet).

The n_devices=1 case IS the serial mock (reference mpisppy/MPI.py:19-82
_MockMPIComm): the same program compiles to a single-device executable
with the collectives elided.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ir import ScenarioBatch, pad_scenarios


class ScenarioMesh:
    """A 1-D (or 2-D cylinder x scenario) device mesh for scenario
    parallelism — the analog of the reference's rank grid
    (spin_the_wheel.py:219-237 _make_comms).

    Multi-host: after parallel.distributed.init_multihost(),
    jax.devices() returns the GLOBAL device list and this same mesh
    spans every process — placement then goes through
    jax.make_array_from_callback (each process materializes only its
    addressable shards) and XLA lowers the consensus reductions to
    cross-process collectives over DCN, the analog of the reference's
    inter-node MPI traffic (SURVEY.md §2.3)."""

    def __init__(self, devices=None, axis_name="scen", n_cyl=None,
                 cyl_axis="cyl"):
        if devices is None:
            devices = jax.devices()
        self.devices = list(devices)
        self.axis_name = axis_name
        self.n_cyl = int(n_cyl) if n_cyl else None
        self.cyl_axis = cyl_axis if self.n_cyl else None
        if self.n_cyl:
            # 2-D cylinder x scenario grid: one row per cylinder, the
            # scenario axis within each row (the reference's rank grid,
            # spin_the_wheel.py:219-237 _make_comms).  Batches shard on
            # the scenario axis only, so each cylinder row holds a full
            # scenario-sharded copy
            if len(self.devices) % self.n_cyl:
                raise ValueError(
                    f"{len(self.devices)} devices do not split into "
                    f"{self.n_cyl} cylinder rows")
            grid = np.array(self.devices).reshape(self.n_cyl, -1)
            self.mesh = Mesh(grid, (cyl_axis, axis_name))
        else:
            self.mesh = Mesh(np.array(self.devices), (axis_name,))
        # single-process fast path keeps plain device_put
        self.multihost = jax.process_count() > 1

    def _put(self, arr, sharding):
        if not self.multihost:
            return jax.device_put(arr, sharding)
        host = np.asarray(arr)
        return jax.make_array_from_callback(
            host.shape, sharding, lambda idx: host[idx])

    @property
    def size(self):
        return len(self.devices)

    @property
    def scen_size(self):
        """Extent of the scenario axis — the padding quantum for
        shard_batch.  Equals `size` on a 1-D mesh; on a 2-D cylinder x
        scenario mesh each cylinder row holds `size // n_cyl` scenario
        shards."""
        return self.size // self.n_cyl if self.n_cyl else self.size

    def submesh(self, devices, axis_name=None):
        """A fresh 1-D ScenarioMesh over a subset of this mesh's
        devices — the building block of mpmd.SlicePlan (each cylinder
        gets its own disjoint submesh)."""
        devs = list(devices)
        if not devs:
            raise ValueError("submesh needs at least one device")
        missing = [d for d in devs if d not in self.devices]
        if missing:
            raise ValueError(
                f"devices {missing} are not part of this mesh")
        return ScenarioMesh(devs, axis_name=axis_name or self.axis_name)

    def slice_axis(self, axis=None):
        """Split the cylinder axis of a 2-D mesh into one 1-D
        ScenarioMesh per cylinder row.  The returned submeshes are
        pairwise disjoint and together cover this mesh's device list
        (guarded by tests/test_mpmd_wheel.py).  A 1-D mesh is its own
        single slice."""
        if axis is not None and self.cyl_axis is not None \
                and axis != self.cyl_axis:
            raise ValueError(
                f"mesh has cylinder axis {self.cyl_axis!r}, not {axis!r}")
        if not self.n_cyl:
            return [self]
        per_row = len(self.devices) // self.n_cyl
        return [self.submesh(self.devices[r * per_row:(r + 1) * per_row])
                for r in range(self.n_cyl)]

    def batch_sharding(self):
        """Sharding for (S, ...) scenario-leading arrays."""
        return NamedSharding(self.mesh, P(self.axis_name))

    def replicated(self):
        return NamedSharding(self.mesh, P())

    def shard_batch(self, batch: ScenarioBatch) -> ScenarioBatch:
        """Pad S to a device multiple (zero-probability dummies — the
        sharding analog of the reference's ragged rank slices,
        sputils.py:804-812) and place each leaf: scenario-leading arrays
        sharded on "scen", shared metadata replicated."""
        S = batch.num_scens
        n = self.scen_size
        Spad = ((S + n - 1) // n) * n
        batch = pad_scenarios(batch, Spad)
        shard = self.batch_sharding()
        repl = self.replicated()
        # explicit field -> axis-0-sharded map (field names, not shape
        # heuristics: nonant_idx is (K,) and K can equal Spad)
        scen_leading = {
            "c", "qdiag", "A", "row_lo", "row_hi", "lb", "ub",
            "obj_const", "integer_mask", "node_of", "prob", "var_prob",
        }

        def place(path, leaf):
            if leaf is None:
                return None
            arr = jax.numpy.asarray(leaf)
            name = path[-1].name if hasattr(path[-1], "name") else None
            if name == "A" and arr.shape[0] == 1:
                # shared constraint matrix (ir.ScenarioBatch.shared_A):
                # replicated, not sharded — every device multiplies its
                # scenario shard against the same (M, N) matrix
                return self._put(arr, repl)
            if name == "vals":
                # SplitA per-scenario delta values, (S, nnz): the only
                # scenario-leading leaf inside a split-native A (its
                # shared/rows/cols are replicated metadata below)
                return self._put(arr, shard)
            if name in scen_leading:
                return self._put(arr, shard)
            if name == "stage_cost_c":  # (n_stages, S, N)
                return self._put(
                    arr, NamedSharding(self.mesh, P(None, self.axis_name)))
            return self._put(arr, repl)

        return jax.tree_util.tree_map_with_path(place, batch)

    def shard_like_batch(self, arr):
        """Place an (S, ...) array with the batch sharding."""
        return self._put(np.asarray(arr), self.batch_sharding())

    def replicate(self, arr):
        return self._put(np.asarray(arr), self.replicated())

    def lane_sharding(self):
        """Sharding for (K, ...) lane-leading slabs on a 2-D cylinder
        mesh: rows split over the `cyl` axis, one block of lanes per
        cylinder row — the placement of the collective exchange
        fabric's staged slab (mpmd/collective.py)."""
        if not self.n_cyl:
            raise ValueError(
                "lane_sharding needs a 2-D cylinder mesh (n_cyl)")
        return NamedSharding(self.mesh, P(self.cyl_axis))

    def fused_cyl_all_gather(self, on_trace=None, donate=True):
        """ONE jitted collective for the whole exchange: shard_map of
        `jax.lax.all_gather` over the `cyl` axis, turning a
        lane-sharded (K, V) slab into a fully replicated copy on every
        lane device — the spokes->hub direction of the MPMD wheel's
        collective fabric.  `donate=True` donates the staged input
        buffer to the program (the slab never detours through a fresh
        host allocation); `on_trace` fires at trace time only, the hook
        behind the single-compile-per-geometry assertion.
        check_rep=False: with out_specs=P() the all-gather's output IS
        replicated over `cyl`, but shard_map's replication checker
        cannot infer that and would reject the specs."""
        from jax.experimental.shard_map import shard_map

        if not self.n_cyl:
            raise ValueError(
                "fused_cyl_all_gather needs a 2-D cylinder mesh (n_cyl)")
        axis = self.cyl_axis

        def gather(x):
            if on_trace is not None:
                on_trace()
            return jax.lax.all_gather(x, axis, axis=0, tiled=True)

        fn = shard_map(gather, mesh=self.mesh, in_specs=P(axis),
                       out_specs=P(), check_rep=False)
        jfn = jax.jit(fn, in_shardings=self.lane_sharding(),
                      out_shardings=self.replicated(),
                      donate_argnums=(0,) if donate else ())
        if not donate:
            return jfn

        def call(x):
            # a replicated output is larger than any per-device input
            # shard, so XLA may find nothing to alias the donation to
            # (it still frees the staged buffer); silence that per-call
            # compile-time warning, it is expected here
            import warnings
            with warnings.catch_warnings():
                warnings.filterwarnings(
                    "ignore", message="Some donated buffers were not usable")
                return jfn(x)

        return call


def local_mesh():
    """Mesh over whatever devices are visible (1 TPU chip, or N forced
    CPU devices under XLA_FLAGS=--xla_force_host_platform_device_count)."""
    return ScenarioMesh()

"""PHBase — Progressive Hedging machinery (reference: mpisppy/phbase.py).

The reference's per-iteration work is: pack [xbar||xsqbar] vectors
var-by-var into Pyomo Params, one MPI Allreduce per tree node
(phbase.py:27-107 _Compute_Xbar), a Python loop for the dual update
(:293-318 Update_W), mutation of every scenario's Pyomo objective, and
N sequential solver calls.  Here ALL of it is one jitted superstep:

    x  <- argmin_x  c@x + (W - rho*xbar)@x_na + rho/2 ||x_na||^2 + ...
    xbar <- per-node probability-weighted average (segment-sum + psum)
    W  <- W + rho * (x_na - xbar)
    conv <- prob-weighted scaled ||x - xbar||_1

The per-tree-node communicators of the reference (spbase.py:333-375)
become a segment-sum over node ids (ir.TreeInfo.node_of) — identical
code for 2-stage (1 node) and multistage.
"""

from __future__ import annotations

import dataclasses
import functools
import threading
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from . import global_toc
from .ir import ScenarioBatch, SparseSplitA, node_segment_sum
from .resilience.chaos import ChaosInjector
from .spopt import SPOpt
from .utils import mfu as _mfu


def _register(cls, data_fields, meta_fields=()):
    jax.tree_util.register_dataclass(
        cls, data_fields=data_fields, meta_fields=meta_fields)
    return cls


@dataclasses.dataclass(frozen=True)
class PHState:
    """Per-iteration PH state (pytree; scenario-leading arrays sharded)."""
    x: Any        # (S, N) last primal solutions
    y: Any        # (S, M) last duals (warm start + Lagrangian bounds)
    W: Any        # (S, K) dual weights on nonants
    xbar: Any     # (S, K) per-slot consensus values (node-averaged)
    xsqbar: Any   # (S, K) consensus of squares (for Fixer-style variance)
    obj: Any      # (S,) per-scenario objective at x
    dual_obj: Any  # (S,)
    conv: Any     # () convergence metric
    it: Any       # () int iteration count
    solve_iters: Any = 0  # () int kernel iterations of the last solve
    active_frac: Any = 1.0  # () unconverged fraction (prob>0) last solve
    solve_restarts: Any = 0  # () int restart events of the last solve
    # () int 1 when the last solve ran on the promoted full-precision
    # pair (hot_dtype runs only; stays 0 otherwise) — checkpointed so a
    # resumed run knows its precision history (resilience/checkpoint.py)
    promoted: Any = 0


_register(PHState, tuple(f.name for f in dataclasses.fields(PHState)))


@dataclasses.dataclass(frozen=True)
class ScenarioView:
    """One scenario's slice of the solution state — what denouements
    and user callbacks receive in place of the reference's Pyomo
    scenario instance (reference spbase.py:505-522)."""
    index: int
    name: str
    x: Any         # (N,) full primal solution of this scenario
    nonants: Any   # (K,) nonanticipative values
    obj: float     # true objective at x
    prob: float    # scenario probability
    W: Any         # (K,) dual weights
    xbar: Any      # (K,) consensus values seen by this scenario


# ---- pure functional core (all jit-friendly) -----------------------------

def compute_xbar(batch: ScenarioBatch, x_na, extra=None):
    """Per-node probability-weighted averages of nonant values.

    Mirror of _Compute_Xbar (reference phbase.py:27-107): the reference
    packs [xbar||xsqbar] and Allreduces per node comm; here it's a
    segment-sum over node ids, reduced across devices by XLA.

    When the batch carries per-(scenario, slot) probabilities
    (batch.var_prob — the reference's variable_probability feature,
    spbase.py:394), those weights replace the scenario probabilities in
    the average, exactly as the reference's Compute_Xbar consumes
    `_mpisppy_variable_probability` (phbase.py:71-88).

    x_na: (S, K) nonant values.  Returns (xbar, xsqbar), each (S, K),
    gathered back to scenario-slot layout.
    """
    tree = batch.tree
    if batch.var_prob is not None:
        p = jnp.asarray(batch.var_prob, x_na.dtype)      # (S, K)
    else:
        p = jnp.broadcast_to(tree.prob[:, None], x_na.shape)
    _, segsum = node_segment_sum(tree.node_of, tree.num_nodes)
    wsum = segsum(p)
    denom = jnp.maximum(wsum, 1e-30)
    xbar = segsum(p * x_na) / denom
    xsqbar = segsum(p * x_na * x_na) / denom
    return xbar, xsqbar


def ph_objective_arrays(batch: ScenarioBatch, W, rho, xbar,
                        W_on=1.0, prox_on=1.0):
    """Fold PH's W and prox terms into (c_eff, qdiag_eff).

    Replaces attach_Ws_and_prox / attach_PH_to_objective (reference
    phbase.py:585-699): W@x + prox_on * rho/2 (x^2 - 2 xbar x + xbar^2).
    The xbar^2 constant is dropped (doesn't move the argmin; objective
    values reported from c, not c_eff).  W_on/prox_on mirror the
    reference's gate scalars.
    """
    na = batch.nonant_idx
    lin = W_on * W - prox_on * rho * xbar
    c_eff = batch.c.at[:, na].add(lin)
    q_eff = batch.qdiag.at[:, na].add(
        jnp.broadcast_to(prox_on * rho, W.shape))
    return c_eff, q_eff


def update_W(W, rho, x_na, xbar):
    """Dual update (reference phbase.py:293-318 Update_W)."""
    return W + rho * (x_na - xbar)


def _active_fraction(batch, converged):
    """Fraction of prob>0 scenarios the solve left unconverged — the
    adaptive-work observability signal (pdhg.active_fraction)."""
    live = batch.prob > 0
    n = jnp.maximum(jnp.sum(live), 1)
    return jnp.sum((~converged) & live) / n


def convergence_metric(batch: ScenarioBatch, x_na, xbar):
    """Scaled prob-weighted ||x - xbar||_1 (reference phbase.py:321-343
    convergence_diff)."""
    K = max(x_na.shape[1], 1)
    per_scen = jnp.sum(jnp.abs(x_na - xbar), axis=1) / K
    return jnp.sum(batch.prob * per_scen)


def ph_superstep(solver, state: PHState, rho, W_on, prox_on,
                 lb, ub, eps, prep, batch):
    """One fused PH iteration as a pure function of its inputs:
    solve -> xbar consensus -> W update -> convergence metric.

    Everything that varies per run — scenario data, rho, bounds,
    tolerance, prepared matrices — is a traced ARGUMENT, so one lowered
    computation serves every PH instance (and every serve-layer
    request) with the same shapes: the executable is keyed only on the
    solver config (via `fused_superstep`) plus jit's own shape bucket.
    This is also what lets the serve layer vmap the whole superstep
    over a leading request axis."""
    c_eff, q_eff = ph_objective_arrays(
        batch, state.W, rho, state.xbar, W_on=W_on, prox_on=prox_on)
    res = solver._solve_jit(
        prep, c_eff, q_eff, lb, ub, batch.obj_const,
        state.x, state.y, None, eps)
    x_na = batch.nonants(res.x)
    xbar, xsqbar = compute_xbar(batch, x_na)
    W = update_W(state.W, rho, x_na, xbar)
    conv = convergence_metric(batch, x_na, xbar)
    # report the TRUE objective at x (c, not c_eff)
    obj = batch.objective(res.x)
    return PHState(
        x=res.x, y=res.y, W=W, xbar=xbar, xsqbar=xsqbar,
        obj=obj, dual_obj=res.dual_obj, conv=conv, it=state.it + 1,
        solve_iters=res.iters,
        active_frac=_active_fraction(batch, res.converged),
        solve_restarts=jnp.sum(res.restarts))


# Per-THREAD fused-superstep registry, mirroring
# ops.pdhg._SOLVE_JIT_TLS: `ph_superstep` depends on the solver only
# through its config, so every PHBase whose solver shares a config_key
# (within one thread) shares ONE jitted wrapper.  Before this registry
# each instance jitted a bound method and re-traced/re-compiled the
# identical superstep.  Thread-local, not process-global, and resolved
# at CALL time (`PHBase._superstep` is a property), for the same reason
# as the solve-jit registry: threaded cylinder wheels construct every
# cylinder on the main thread but dispatch concurrently from worker
# threads, and concurrent calls into one jit wrapper deadlock —
# call-time per-thread scoping preserves the invariant that no two
# threads race one wrapper.  The serve layer's batch=1 path runs this identical
# lowered computation (same function, same config, same shapes), which
# is what makes its result bitwise equal to a standalone `PH.ph_main`
# (asserted in tests/test_serve.py).
_SUPERSTEP_TLS = threading.local()


def fused_superstep(solver):
    """The thread-shared jitted PH superstep for `solver`'s config."""
    reg = getattr(_SUPERSTEP_TLS, "registry", None)
    if reg is None:
        reg = _SUPERSTEP_TLS.registry = {}
    key = solver.config_key()
    fn = reg.get(key)
    if fn is None:
        fn = jax.jit(functools.partial(ph_superstep, solver))
        reg[key] = fn
    return fn


class PHBase(SPOpt):
    """Shared PH machinery; algorithm drivers (opt/ph.py, opt/aph.py)
    subclass this."""

    def __init__(self, options, all_scenario_names, scenario_creator=None,
                 scenario_denouement=None, all_nodenames=None,
                 extensions=None, extension_kwargs=None,
                 rho_setter=None, variable_probability=None,
                 scenario_creator_kwargs=None, batch=None, mesh=None,
                 prep=None):
        super().__init__(
            options, all_scenario_names,
            scenario_creator=scenario_creator,
            scenario_denouement=scenario_denouement,
            all_nodenames=all_nodenames,
            scenario_creator_kwargs=scenario_creator_kwargs,
            variable_probability=variable_probability,
            batch=batch, mesh=mesh, prep=prep)
        self.rho_setter = rho_setter
        self.extobject = None
        if extensions is not None:
            self.extobject = extensions(self, **(extension_kwargs or {}))
        self.spcomm = None  # set by cylinders.hub when running as hub
        self._iter0_solver_options = self.options.get(
            "iter0_solver_options")
        self.W_on = 1.0
        self.prox_on = 1.0

        # rho: scalar option -> (S, K) array; rho_setter may override
        # per-variable (reference phbase.py:387-406 _use_rho_setter)
        K = self.batch.num_nonants
        S = self.batch.num_scens
        rho_default = float(self.options.get("defaultPHrho", 1.0))
        rho = jnp.full((S, K), rho_default, self.batch.c.dtype)
        if rho_setter is not None:
            vals = np.asarray(rho_setter(self.batch), dtype=float)
            rho = jnp.broadcast_to(jnp.asarray(vals), (S, K)).astype(
                self.batch.c.dtype)
        self.rho = rho

        self.state: PHState | None = None
        self.trivial_bound = None
        self.best_bound = None
        # per-phase jitted pieces of the superstep, built lazily the
        # first time telemetry phase timing runs (telemetry/; the fused
        # _superstep property stays the only path when telemetry is off)
        self._phase_jits = None
        self.conv = None

        # effective bounds: extensions (Fixer, slamming) pin nonants by
        # tightening these; the jitted superstep takes them as ARGS so a
        # fix never triggers recompilation (the reference mutates Pyomo
        # var.fix() instead, spopt.py:592-740)
        self.lb_eff = self.batch.lb
        self.ub_eff = self.batch.ub
        # (solver_eps lives on SPOpt so solve_loop callers — Iter0,
        # spokes, xhat evaluation — honor the Gapper schedule too)
        # superstep tolerance: PH subproblem solves tolerate loose
        # accuracy (PH is itself an approximation until the bounds
        # certify), so the hot loop may run at a looser eps than the
        # certified bound solves — the analog of the reference's
        # iterk mipgap vs bound-solve gap split (extensions/mipgapper.py)
        self._superstep_eps_opt = self.options.get("superstep_eps")
        # inexactness LADDER (options["eps_ladder"]): start the hot-loop
        # solves LOOSE and tighten as PH's own convergence metric
        # shrinks — early PH iterations over-solve subproblems the next
        # W update will invalidate anyway (the adaptive-sampling-PH
        # observation, PAPERS.md).  Config (truthy enables; a dict
        # overrides fields):
        #   start  — iteration-1 tolerance (default max(100*eps, 1e-3))
        #   min    — tightest tolerance (default the solver eps)
        #   couple — eps target = couple * conv (default 0.1): the
        #       tolerance tracks the consensus error geometrically,
        #       clamped to [min, start] and monotone non-increasing
        # When enabled, the ladder REPLACES a static superstep_eps (it
        # IS the dynamic schedule feeding the same traced-eps path, so
        # tightening never recompiles).
        lad = self.options.get("eps_ladder")
        self._ladder = None
        if lad:
            lad = dict(lad) if isinstance(lad, dict) else {}
            self._ladder = {
                "start": float(lad.get(
                    "start", max(100.0 * self.solver.eps, 1e-3))),
                "min": float(lad.get("min", self.solver.eps)),
                "couple": float(lad.get("couple", 0.1)),
            }
            self._ladder_eps = self._ladder["start"]

        # optional converger (reference phbase.py:726-755 PH_Prep wires
        # options["ph_converger"]; convergers/converger.py API)
        self.convobject = None
        conv_cls = self.options.get("ph_converger")
        if conv_cls is not None:
            self.convobject = conv_cls(self)

        # crash-resume + fault injection (resilience/):
        #   options["run_checkpoint"]   — atomic full-state checkpoint
        #       path, written every options["checkpoint_every"] iters
        #   options["resume_from"]      — checkpoint to restore instead
        #       of running Iter0 (missing file => fresh start)
        #   options["chaos"]            — deterministic fault injectors
        self._chaos = ChaosInjector.from_options(self.options.get("chaos"))

    # -- hook plumbing (reference extensions/extension.py API) ------------
    def _ext(self, hook, *args):
        if self.extobject is not None:
            getattr(self.extobject, hook, lambda *a: None)(*args)

    # -- nonant fixing for extensions (reference spopt.py:592-740) --------
    def fix_nonants(self, mask, values):
        """Pin nonant slots where mask (S, K) is True to `values` (S, K)
        by tightening the effective bounds.  Idempotent; unfix_nonants
        reverses."""
        b = self.batch
        na = b.nonant_idx
        vals = jnp.asarray(values, b.c.dtype)
        m = jnp.asarray(mask, bool)
        self.lb_eff = self.lb_eff.at[:, na].set(
            jnp.where(m, vals, self.lb_eff[:, na]))
        self.ub_eff = self.ub_eff.at[:, na].set(
            jnp.where(m, vals, self.ub_eff[:, na]))

    def unfix_nonants(self, mask):
        """Restore original batch bounds where mask (S, K) is True."""
        b = self.batch
        na = b.nonant_idx
        m = jnp.asarray(mask, bool)
        self.lb_eff = self.lb_eff.at[:, na].set(
            jnp.where(m, b.lb[:, na], self.lb_eff[:, na]))
        self.ub_eff = self.ub_eff.at[:, na].set(
            jnp.where(m, b.ub[:, na], self.ub_eff[:, na]))

    def count_fixed(self):
        na = self.batch.nonant_idx
        return int(jnp.sum(self.lb_eff[:, na] == self.ub_eff[:, na]))

    # -- elastic re-slicing (mpmd/reslice.py; doc/src/mpmd.md) ------------
    def reshard(self, mesh, pad_multiple=1):
        """Move this optimizer onto a NEW ScenarioMesh mid-run — the
        hub side of a dynamic reslice: the current batch is re-padded
        to the new plan's pad_multiple (pads always APPEND, so existing
        scenario rows keep their indices and window row semantics),
        every scenario-leading state array is zero-extended onto the
        new rows, all device state is re-placed on the new mesh, and
        the solver prep is rebuilt there.  The hub never restarts:
        PHState — duals, consensus, the iteration counter — carries
        over row-for-row.  Returns the new padded scenario count."""
        from .ir import SplitA, pad_scenarios, shared_density

        S_old = self.batch.num_scens
        q = max(int(pad_multiple), 1)
        Spad = ((S_old + q - 1) // q) * q
        self.mesh = mesh
        self.batch = mesh.shard_batch(pad_scenarios(self.batch, Spad))
        S_new = self.batch.num_scens
        dS = S_new - S_old

        def grow(a, fill=0.0):
            # zero-extend an (S_old, ...) array to (S_new, ...) and
            # commit it to the new mesh (arrays committed to the OLD
            # mesh cannot feed a jit over the new one)
            a = np.asarray(a)
            if dS > 0:
                pad = np.full((dS,) + a.shape[1:], fill, a.dtype)
                a = np.concatenate([a, pad])
            return mesh.shard_like_batch(a)

        def scal(a):
            return mesh.replicate(np.asarray(a))

        st = self.state
        if st is not None:
            self.state = PHState(
                x=grow(st.x), y=grow(st.y), W=grow(st.W),
                xbar=grow(st.xbar), xsqbar=grow(st.xsqbar),
                obj=grow(st.obj), dual_obj=grow(st.dual_obj),
                conv=scal(st.conv), it=scal(st.it),
                solve_iters=scal(st.solve_iters),
                active_frac=scal(st.active_frac),
                solve_restarts=scal(st.solve_restarts),
                promoted=scal(st.promoted))
        self.rho = grow(self.rho,
                        float(self.options.get("defaultPHrho", 1.0)))
        # effective bounds keep their (possibly extension-pinned) rows;
        # the fresh batch supplies the new pad rows
        lb = np.concatenate([np.asarray(self.lb_eff),
                             np.asarray(self.batch.lb)[S_old:]])
        ub = np.concatenate([np.asarray(self.ub_eff),
                             np.asarray(self.batch.ub)[S_old:]])
        self.lb_eff = mesh.shard_like_batch(lb)
        self.ub_eff = mesh.shard_like_batch(ub)
        # every shape/placement-keyed cache is stale now; the next
        # superstep retraces on the new (S, ...) shapes
        self.prep = self._build_prep(hot=self.solver.hot_dtype)
        self._shared_nnz_frac = (float(shared_density(self.prep.A))
                                 if isinstance(self.prep.A, SplitA)
                                 else None)
        self.solver_eps = jnp.asarray(np.asarray(self.solver_eps),
                                      self.batch.c.dtype)
        self._promoted_cache = None
        self._np_cache = {}
        self._phase_jits = None
        self.clear_warmstart()
        global_toc(f"reshard: {S_old} -> {S_new} padded scenarios on "
                   f"{mesh.size} device(s)")
        return S_new

    # -- Iter0 (reference phbase.py:758-872) ------------------------------
    def Iter0(self):
        self._ext("pre_iter0")
        global_toc("Iter0: no-penalty solves")
        # certify="feas": refine (f64) only primal-infeasible scenarios
        # — matching the reference's infeasibility-only iter0 gate; a
        # solve legitimately riding to a big artificial box (epigraph
        # variables pre-cuts) is dual-unconverged but NOT refined.
        # options["iter0_certify"]=False skips the refine entirely —
        # for batches that are feasible by construction (UC load shed)
        # where an f32 stall is solver noise, a large straggler set
        # would route through the CPU-f64 fallback and dominate
        # accelerator wall-clock (the r4 UC-on-TPU timeout); Ebound's
        # mask keeps the published bound valid either way
        res = self.solve_loop(
            lb=self.lb_eff, ub=self.ub_eff, warm=False,
            dtiming=self.options.get("display_timing"),
            certify=("feas" if self.options.get("iter0_certify", True)
                     else False))
        feas = self.feas_prob(res)
        self.iter0_feas_mass = float(feas)   # benchmarks report this
        if feas < 1.0 - 1e-6:
            # reference hard-quits on infeasible iter0 (phbase.py:817
            # "quitting after iter 0 because of infeasibility");
            # set options["iter0_infeasibility_ok"] to downgrade to a
            # warning (and accept -inf bounds from Ebound's mask)
            if self.options.get("iter0_certify", True):
                msg = (f"iter0 feasible mass only {feas} after "
                       f"certified re-solve: infeasible or unsolvable "
                       f"scenario(s)")
            else:
                # no certification ran — an f32 stall is
                # indistinguishable from true infeasibility here
                msg = (f"iter0 feasible mass only {feas} on the "
                       f"UNCERTIFIED fast solve (iter0_certify=False): "
                       f"enable iter0_certify for an f64 re-solve, or "
                       f"set iter0_infeasibility_ok to continue with "
                       f"masked bounds")
            if self.options.get("iter0_infeasibility_ok", False):
                global_toc("WARNING: " + msg)
            else:
                raise RuntimeError(msg)
        x_na = self.batch.nonants(res.x)
        xbar, xsqbar = compute_xbar(self.batch, x_na)
        W = update_W(jnp.zeros_like(x_na), self.rho, x_na, xbar)
        conv = convergence_metric(self.batch, x_na, xbar)
        self.trivial_bound = float(self.valid_Ebound(res))
        self.best_bound = self.trivial_bound
        self.state = PHState(
            x=res.x, y=res.y, W=W, xbar=xbar, xsqbar=xsqbar,
            obj=res.obj, dual_obj=res.dual_obj, conv=conv,
            it=jnp.asarray(0, jnp.int32), solve_iters=res.iters,
            active_frac=_active_fraction(self.batch, res.converged),
            solve_restarts=jnp.sum(res.restarts))
        self.conv = float(conv)
        global_toc(f"Iter0 trivial bound = {self.trivial_bound:.6g}, "
                   f"conv = {float(conv):.6g}")
        if self._tel.enabled:
            self._tel.event("ph.iter0",
                            trivial_bound=self.trivial_bound,
                            feas_mass=self.iter0_feas_mass,
                            conv=self.conv)
        self._ext("post_iter0")
        return self.trivial_bound

    # -- one PH iteration, fully fused ------------------------------------
    # The body lives in the module-level `ph_superstep`: everything
    # that varies per run is a traced ARG (not a closure constant) —
    # multihost meshes forbid closing over arrays that span
    # non-addressable devices, bound-rewriting extensions swap
    # batches/preps without recompiling, and the serve layer executes
    # the same function with swapped-in scenario arrays.  This method
    # stays as the un-jitted entry for callers holding a PH instance.
    def _superstep_impl(self, state: PHState, rho, W_on, prox_on,
                        lb=None, ub=None, eps=None, prep=None,
                        batch=None):
        b = self.batch if batch is None else batch
        return ph_superstep(
            self.solver, state, rho, W_on, prox_on,
            b.lb if lb is None else lb,
            b.ub if ub is None else ub,
            eps, self.prep if prep is None else prep, b)

    @property
    def _superstep(self):
        # resolved per CALLING thread (see _SUPERSTEP_TLS above): in the
        # threaded wheel the hub's driving thread is not the thread
        # that constructed it, and the wrapper must belong to the driver
        return fused_superstep(self.solver)

    @property
    def superstep_eps(self):
        """Tolerance of the hot-loop subproblem solves: the eps-ladder
        schedule when enabled (options["eps_ladder"], updated each
        ph_iteration), else the superstep_eps option when given, else
        the DYNAMIC solver_eps (so the Gapper schedule keeps reaching
        the PH loop)."""
        if self._ladder is not None:
            return jnp.asarray(self._ladder_eps, self.batch.c.dtype)
        if self._superstep_eps_opt is None:
            return self.solver_eps
        return jnp.asarray(self._superstep_eps_opt, self.batch.c.dtype)

    def _run_superstep(self):
        """Advance self.state by one superstep and sync.  Telemetry
        phase timing (when ON) routes through the unfused per-phase
        path; otherwise this is byte-for-byte the pre-telemetry fused
        call — the zero-cost-when-off contract of telemetry/.

        A hot-dtype run promotes here too: once the superstep tolerance
        (ladder or static) crosses the hot dtype's eps floor, the
        promoted full-precision (solver, prep) pair takes over —
        monotone under the ladder, so at most one extra superstep
        compile per run."""
        solver, prep = self.active_solver_prep(self.superstep_eps)
        if self._tel.phase_timing:
            self._superstep_phased(solver, prep)
        else:
            self.state = fused_superstep(solver)(
                self.state, self.rho, self.W_on, self.prox_on,
                self.lb_eff, self.ub_eff, self.superstep_eps, prep,
                self.batch)
            jax.block_until_ready(self.state.x)
        if solver is not self.solver:
            self.state = dataclasses.replace(
                self.state, promoted=jnp.asarray(1, jnp.int32))

    def _phase_impls(self, solver=None):
        """Jitted per-phase cuts of _superstep_impl (solve / xbar-psum
        / W-update / conv), functionally identical to the fused body —
        only the phase boundaries differ, so the phase-timed iteration
        produces the same PHState.  `solver` defaults to the configured
        one; the promoted full-precision solver gets its own cache
        entry (config_key differs)."""
        solver = self.solver if solver is None else solver
        key = solver.config_key()
        cache = self._phase_jits
        if cache is None:
            cache = self._phase_jits = {}
        fns = cache.get(key)
        if fns is not None:
            return fns

        def solve(state, rho, W_on, prox_on, lb, ub, eps, prep, batch):
            c_eff, q_eff = ph_objective_arrays(
                batch, state.W, rho, state.xbar,
                W_on=W_on, prox_on=prox_on)
            return solver._solve_jit(
                prep, c_eff, q_eff, lb, ub, batch.obj_const,
                state.x, state.y, None, eps)

        def xbar(batch, x):
            x_na = batch.nonants(x)
            return (x_na,) + compute_xbar(batch, x_na)

        def w_up(W, rho, x_na, xbar_):
            return update_W(W, rho, x_na, xbar_)

        def conv(batch, x_na, xbar_, x):
            return convergence_metric(batch, x_na, xbar_), \
                batch.objective(x)

        fns = {"solve": jax.jit(solve), "xbar": jax.jit(xbar),
               "w_update": jax.jit(w_up), "conv": jax.jit(conv)}
        cache[key] = fns
        return fns

    def _superstep_phased(self, solver=None, prep=None):
        """One PH iteration with per-phase spans + timing histograms
        (ph.phase.{solve,psum,w_update,conv}_seconds).  Each phase runs
        as its own jitted call with a device sync between phases — the
        observability/fusion trade the telemetry docs call out, which
        is why this path exists ONLY behind tel.phase_timing."""
        tel = self._tel
        st, b = self.state, self.batch
        prep = self.prep if prep is None else prep
        fns = self._phase_impls(solver)
        t0 = time.monotonic()
        with tel.span("ph.phase.solve"):
            res = fns["solve"](st, self.rho, self.W_on, self.prox_on,
                               self.lb_eff, self.ub_eff,
                               self.superstep_eps, prep, b)
            jax.block_until_ready(res.x)
        t1 = time.monotonic()
        with tel.span("ph.phase.psum"):
            x_na, xbar, xsqbar = fns["xbar"](b, res.x)
            jax.block_until_ready(xbar)
        t2 = time.monotonic()
        with tel.span("ph.phase.w_update"):
            W = fns["w_update"](st.W, self.rho, x_na, xbar)
            jax.block_until_ready(W)
        t3 = time.monotonic()
        with tel.span("ph.phase.conv"):
            conv, obj = fns["conv"](b, x_na, xbar, res.x)
            jax.block_until_ready(conv)
        t4 = time.monotonic()
        hist = tel.registry.histogram
        hist("ph.phase.solve_seconds").observe(t1 - t0)
        hist("ph.phase.psum_seconds").observe(t2 - t1)
        hist("ph.phase.w_update_seconds").observe(t3 - t2)
        hist("ph.phase.conv_seconds").observe(t4 - t3)
        self.state = PHState(
            x=res.x, y=res.y, W=W, xbar=xbar, xsqbar=xsqbar,
            obj=obj, dual_obj=res.dual_obj, conv=conv, it=st.it + 1,
            solve_iters=res.iters,
            active_frac=_active_fraction(b, res.converged),
            solve_restarts=jnp.sum(res.restarts))

    def ph_iteration(self):
        self._ext("pre_solve_loop")
        t0 = time.time()
        tel = self._tel
        if tel.enabled:
            with tel.span("ph.iteration"):
                self._run_superstep()
        else:
            self._run_superstep()
        # account the superstep's kernel work (utils/mfu): iters ride
        # along in the state so no extra device sync is needed beyond
        # the conv readback below
        b = self.batch
        it_n = int(self.state.solve_iters)
        rst_n = int(self.state.solve_restarts)
        self._flops += _mfu.pdhg_flops(
            it_n, b.num_scens, b.num_rows,
            b.num_vars, self.solver.check_every,
            density=self._prep_density(self.prep))
        self._kernel_iters += it_n
        self._restarts_total += rst_n
        if isinstance(self.prep.A, SparseSplitA):
            self._sparse_matvecs += 2 * it_n
        self._active_fraction = float(self.state.active_frac)
        wall = time.time() - t0
        self._solve_wall += wall
        self._ext("post_solve_loop")
        self.conv = float(self.state.conv)
        if self._ladder is not None:
            # tighten (never loosen) toward couple*conv, floored at min
            self._ladder_eps = min(
                self._ladder_eps,
                max(self._ladder["min"],
                    self._ladder["couple"] * self.conv))
        if tel.enabled:
            r = tel.registry
            r.counter("ph.iterations").inc()
            r.histogram("ph.iteration_seconds").observe(wall)
            r.gauge("ph.conv").set(self.conv)
            r.counter("pdhg.inner_iters_total").inc(it_n)
            r.counter("pdhg.restarts_total").inc(rst_n)
            r.gauge("pdhg.active_fraction").set(self._active_fraction)
            if isinstance(self.prep.A, SparseSplitA):
                r.counter("pdhg.sparse_matvecs").inc(2 * it_n)
            if self._ladder is not None:
                r.gauge("ph.superstep_eps").set(self._ladder_eps)
        return self.conv

    # -- crash-resume (resilience/checkpoint.py) --------------------------
    def _save_checkpoint(self, path):
        """Write the run checkpoint — the subclass override point:
        StreamingPH routes to the stream checkpoint format (host-
        resident W + sampler RNG state instead of device PHState)."""
        from .resilience.checkpoint import save_run_checkpoint
        save_run_checkpoint(path, self)

    def _maybe_checkpoint(self, k):
        path = self.options.get("run_checkpoint")
        if not path:
            return
        if k % int(self.options.get("checkpoint_every", 1)) == 0:
            self._save_checkpoint(path)

    def restore_run_checkpoint(self, path):
        """Install a full run checkpoint (state, bounds, iter) — the
        Iter0 replacement on a `resume_from=` run."""
        from .resilience.checkpoint import load_run_checkpoint
        load_run_checkpoint(path, self)
        global_toc(f"PH resumed from checkpoint {path} at iter "
                   f"{int(self.state.it)} "
                   f"(trivial_bound={self.trivial_bound})")
        return self.trivial_bound

    # -- main loop (reference phbase.py:875-979 iterk_loop) ---------------
    def iterk_loop(self):
        max_iters = int(self.options.get("PHIterLimit", 100))
        convthresh = float(self.options.get("convthresh", 1e-4))
        verbose = self.options.get("verbose", False)
        # a resumed run continues from the checkpointed iteration so
        # the total iteration budget matches the uninterrupted run
        start = int(self.state.it) if self.state is not None else 0
        for k in range(start + 1, max_iters + 1):
            conv = self.ph_iteration()
            self._ext("miditer")
            if verbose or k % 10 == 0 or k == 1:
                eobj = float(self.Eobjective(self.state.obj))
                global_toc(f"PH iter {k:4d} conv={conv:.6e} "
                           f"E[obj]={eobj:.6g}")
            self._ext("enditer")
            self._maybe_checkpoint(k)
            # chaos crash-at-iter fires AFTER the checkpoint: the test
            # contract is "killed at iter k, resumable from iter k"
            self._chaos.hub_iter_tick(k)
            if self.spcomm is not None:
                self.spcomm.sync()
                if self.spcomm.is_converged():
                    global_toc(f"PH terminated by hub at iter {k}")
                    break
            if self.convobject is not None and self.convobject.is_converged():
                global_toc(f"PH terminated by converger "
                           f"{type(self.convobject).__name__} at iter {k}")
                break
            if conv < convthresh:
                global_toc(f"PH converged (conv={conv:.3e} < "
                           f"{convthresh}) at iter {k}")
                break
            self._ext("enditer_after_sync")
        self._ext("post_everything")
        return self.conv

    def post_loops(self):
        """Final expected objective (reference phbase.py:982).

        The denouement contract is the reference's
        (rank, scenario_name, scenario): each callback receives THAT
        scenario's data — a ScenarioView of its solution row — not the
        global state (reference spbase.py:505-522 usage)."""
        eobj = float(self.Eobjective(self.state.obj))
        if self.scenario_denouement is not None:
            for i, name in enumerate(self.all_scenario_names):
                self.scenario_denouement(0, name, self.scenario_view(i))
        return eobj

    def _host_state(self):
        """Bulk device->host materialization of the solution state
        (ONE gather per array, not one per scenario row)."""
        st = self.state
        return {
            "x": np.asarray(st.x),
            "nonants": np.asarray(st.x[:, self.batch.nonant_idx]),
            "obj": np.asarray(st.obj),
            "prob": np.asarray(self.batch.prob),
            "W": np.asarray(st.W),
            "xbar": np.asarray(st.xbar),
        }

    def scenario_view(self, i):
        """Per-scenario slice of the current state — the analog of the
        reference's Pyomo scenario instance handed to denouements and
        extensions (reference spbase.py:505-522).  The host copy is
        cached per iteration so S denouement calls cost one gather."""
        h = getattr(self, "_host_cache", None)
        if h is None or h["state"] is not self.state:
            # keyed on state identity (PHState is frozen: every update
            # makes a new object), so checkpoint installs and re-solves
            # can never serve a stale view
            h = dict(self._host_state(), state=self.state)
            self._host_cache = h
        return ScenarioView(
            index=i,
            name=self.all_scenario_names[i],
            x=h["x"][i],
            nonants=h["nonants"][i],
            obj=float(h["obj"][i]),
            prob=float(h["prob"][i]),
            W=h["W"][i],
            xbar=h["xbar"][i],
        )

    # -- bounds -----------------------------------------------------------
    def lagrangian_bound(self, W=None, certify="auto", eps=None):
        """Valid outer bound from the current W (reference:
        cylinders/lagrangian_bounder.py — re-solve with W-only objective,
        no prox, then Ebound).  Valid because the prob-weighted W sums to
        zero per node by construction of update_W.

        certify="auto": when the subproblems are LPs with all-finite
        variable boxes, the PDHG dual objective equals the Lagrangian
        g(y) exactly for ANY dual iterate, so the bound is valid without
        a convergence certificate and the solve never needs the f64
        fallback (the bound merely tightens as y converges).  Otherwise
        falls back to certify=True: drive every scenario to the KKT
        tolerance and mask any uncertified scenario out of the published
        bound (-inf).  `eps` optionally loosens this solve alone
        (options key "lagrangian_eps") — a looser y costs bound
        tightness, never validity (in the auto/LP case)."""
        self.check_W_bound_supported()
        self._tel.counter("ph.lagrangian_bound_calls").inc()
        b = self.batch
        W = self.state.W if W is None else W
        c_eff = b.c.at[:, b.nonant_idx].add(W)
        if certify == "auto":
            certify = not (self.is_lp and self.all_bounds_finite)
        if eps is None:
            eps = self.options.get("lagrangian_eps")
        if eps is not None:
            eps = jnp.asarray(eps, b.c.dtype)
        # optional per-solve budget ("lagrangian_iters_cap"): in the
        # auto/LP case a CAPPED solve is still a valid bound (dual
        # objective valid at any iterate) — it only costs tightness.
        # The W-only objective has no prox term, so uncapped bound
        # solves cost ~4x a PH iteration; a cap makes the bound-check
        # cadence affordable.  Never applied when certify is on
        # (capped+certified would mask most scenarios to -inf).
        cap = None if certify else self.options.get(
            "lagrangian_iters_cap")
        res = self.solve_loop(c=c_eff, warm="lagrangian", certify=certify,
                              eps=eps, iters_cap=cap)
        return float(self.Ebound(res.dual_obj,
                                 converged=res.converged if certify
                                 else None))

    # -- spoke support ----------------------------------------------------
    def root_xbar(self):
        """Root-node consensus vector (K,) — candidate first-stage
        solution, for xhat spokes and solution writers."""
        return self.state.xbar[0]

"""Resilience subsystem: spoke supervision, bound hygiene, crash
checkpoints, and fault injection for the cylinder wheel.

The reference mpi-sppy aborts the whole job when any MPI rank dies;
this package is the graceful-degradation layer on top of the wheel:

  * `supervisor.SpokeSupervisor` — multiproc-mode process supervision:
    detects dead children (`Popen.poll`) and hung children (window
    `write_id` staleness — the spoke's bound writes double as the
    heartbeat), escalates SIGTERM -> SIGKILL with deadlines, restarts
    from the declarative spec with capped exponential backoff, and
    permanently prunes a spoke after its restart budget.
  * `bounds.BoundGuard` — NaN/Inf and wrong-direction bound rejection
    at the hub's window-read boundary, so a sick spoke degrades
    instead of corrupting BestInnerBound/BestOuterBound.
  * `checkpoint` — full atomic PH run checkpoints (W, xbar, x, y,
    iter, best bounds, incumbent) with `resume_from=` on
    PH/WheelSpinner.
  * `chaos` — config/env-driven fault injectors (crash-at-step, hang,
    NaN-bound, delayed window write, hub crash-at-iter) backing the
    deterministic `chaos`-marked tests.

See doc/src/resilience.md for the operator-facing story.
"""

from .bounds import BoundGuard
from .chaos import ChaosError, ChaosInjector
from .checkpoint import (atomic_write, checkpoint_exists,
                         load_run_checkpoint, restore_hub,
                         save_run_checkpoint)
from .supervisor import SpokeSupervisor, restart_delay


def wheel_counters(opt_or_hub):
    """Resilience counters for benchmark/report JSON: works on a bare
    optimizer (no wheel -> zeros), a Hub, or a WheelSpinner."""
    hub = opt_or_hub
    for attr in ("spcomm",):
        hub = getattr(hub, attr, hub)
    sup = getattr(hub, "supervisor", None)
    failed = len(getattr(hub, "failed_spokes", ()) or ())
    return {
        "spoke_restarts": int(getattr(sup, "spoke_restarts", 0)),
        "spokes_failed": failed,
    }


__all__ = [
    "BoundGuard", "ChaosError", "ChaosInjector", "SpokeSupervisor",
    "atomic_write", "checkpoint_exists", "load_run_checkpoint",
    "restart_delay", "restore_hub", "save_run_checkpoint",
    "wheel_counters",
]

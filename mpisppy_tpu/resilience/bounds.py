"""Bound hygiene at the hub's window-read boundary.

A spoke's published bound travels through a shared-memory window with
no schema beyond "one float64" — a sick spoke (numerical blow-up,
memory corruption, chaos NaN injector) can post values that would
silently corrupt BestInnerBound/BestOuterBound and with them the gap
termination test.  The hub therefore screens every incoming bound:

  * non-finite values (NaN/Inf) are rejected outright;
  * wrong-direction values — an outer bound that crosses the current
    best inner bound (or vice versa) beyond a relative tolerance —
    are rejected, since a valid outer bound can never exceed a valid
    incumbent (minimization; mirrored for maximization) by more than
    solver noise.

Rejections only increment a per-spoke counter and drop the message —
the spoke keeps running and can recover — until the counter exceeds
its budget, at which point the hub prunes the spoke through the same
`_mark_spoke_failed` path a crashed spoke takes.
"""

from __future__ import annotations

import zlib

import numpy as np


def payload_checksum(values) -> int:
    """CRC32 over the float64 byte image of a window payload.

    Both window backends stamp every write with this checksum and
    `read_checked()` recomputes it on the reader's copy, so a torn
    snapshot or a corrupted mailbox is detected at the read boundary
    instead of flowing into bound/W/nonant state."""
    arr = np.ascontiguousarray(values, dtype=np.float64)
    return zlib.crc32(arr.tobytes()) & 0xFFFFFFFF


class PayloadGuard:
    """Payload-level twin of BoundGuard for one window direction.

    Validates each `(data, write_id, checksum)` snapshot a reader
    takes: the byte image must match the writer's stamped checksum and
    the write_id must never regress below the highest id this reader
    has seen (the kill sentinel -1 is exempt — it carries no payload).
    Rejections drop the message; the hub counts them per spoke into
    the same prune budget that bound rejections feed."""

    KILL = -1

    def __init__(self):
        self.max_wid = 0
        self.corrupt = 0

    def check(self, values, write_id, checksum):
        """(ok, reason) for one window snapshot."""
        wid = int(write_id)
        if wid == self.KILL:
            return True, None
        if wid < self.max_wid:
            self.corrupt += 1
            return False, (f"write_id regressed: {wid} after "
                           f"{self.max_wid}")
        self.max_wid = wid
        if checksum is not None and payload_checksum(values) != int(checksum):
            self.corrupt += 1
            return False, f"payload checksum mismatch at write_id {wid}"
        return True, None


class BoundGuard:
    """Stateless validity check for one incoming scalar bound.

    `rtol` scales the crossing tolerance by the magnitude of the bound
    being compared against (floor 1.0), so legitimate eps-level
    crossings from loose solves are never rejected while grossly
    invalid bounds always are.
    """

    def __init__(self, rtol: float = 1e-2):
        self.rtol = float(rtol)

    def check(self, kind: str, value: float, inner: float, outer: float,
              minimizing: bool):
        """(ok, reason) for one incoming bound.

        kind: "outer" or "inner"; inner/outer are the hub's current
        best bounds (possibly +-inf before first update)."""
        v = float(value)
        if not np.isfinite(v):
            return False, f"non-finite {kind} bound {v!r}"
        other = inner if kind == "outer" else outer
        if not np.isfinite(other):
            return True, None
        tol = self.rtol * max(1.0, abs(other))
        # minimization: valid outer <= opt <= valid inner; a new outer
        # above the incumbent (or inner below the outer bound) by more
        # than tol means one side is corrupt — reject the newcomer
        if minimizing:
            crossed = (v > other + tol if kind == "outer"
                       else v < other - tol)
        else:
            crossed = (v < other - tol if kind == "outer"
                       else v > other + tol)
        if crossed:
            return False, (f"wrong-direction {kind} bound {v:.6g} "
                           f"crosses best {'inner' if kind == 'outer' else 'outer'}"
                           f" bound {other:.6g}")
        return True, None

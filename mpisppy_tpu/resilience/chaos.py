"""Fault injection ("chaos") layer.

Deterministic injectors that exercise every degradation path of the
wheel end to end: a spoke that crashes (softly or via a hard
`os._exit`, the SIGKILL stand-in), hangs, poisons its published bound
with NaN, or delays its window writes; plus a hub-side crash-at-iter
used by the checkpoint/resume tests.

Configuration comes from the owner's options dict under the "chaos"
key (JSON-serializable, so it crosses the multiproc spec boundary in
`cylinders/proc.py` untouched), optionally overridden by the
`MPISPPY_TPU_CHAOS` environment variable (a JSON dict — for manual
chaos runs against an unmodified driver).

Injection points (all no-ops when unconfigured):
  * `Spoke.spoke_from_hub` calls `step_tick()` once per read — the
    spoke-side step clock (crash_at_step / hang_at_step /
    hard_exit).
  * `Spoke.spoke_to_hub` routes outgoing vectors through
    `poison()` (nan_bound) and `pre_write()` (delay_write_s).
  * `PHBase.iterk_loop` calls `hub_iter_tick(k)` after the iter-k
    checkpoint is written (crash_at_iter).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

ENV_VAR = "MPISPPY_TPU_CHAOS"


class ChaosError(RuntimeError):
    """An injected failure (never raised outside chaos runs)."""


class DeviceLossError(ChaosError):
    """An injected loss of the slice's device(s).

    Unlike a soft crash, the hardware is gone: SliceSupervisor treats
    this as unrestartable and prunes the slice immediately (skipping
    the restart budget), which triggers the elastic reslice path."""


class ChaosInjector:
    """One injector instance per owning cylinder; all state local.

    Config keys (all optional):
      crash_at_step: int   raise ChaosError on the N-th step tick
      hard_exit: bool      crash via os._exit(13) instead of raising
                           (no cleanup/atexit — the SIGKILL analog)
      hang_at_step: int    stop making progress on the N-th tick
                           (sleep loop; the process stays alive but
                           its window writes go stale)
      nan_bound: bool      replace every outgoing vector with NaN
      delay_write_s: float sleep before every outgoing write
      crash_at_iter: int   hub-side: raise ChaosError at PH iter N
                           (after that iteration's checkpoint)
      device_loss: int     raise DeviceLossError on the N-th step tick
                           (unrestartable: the supervisor prunes the
                           slice and reslices without burning restarts)
      corrupt_window: int  from the N-th outgoing write on, corrupt
                           the posted payload (checksum stays that of
                           the true values, so read_checked rejects)
      partition_slice: int from the N-th outgoing write on, silently
                           drop every write (the slice looks
                           partitioned away: its write_id goes stale
                           and hang pruning fires)
      block_build_fail: int streaming: fail the first N source block
                           builds (retry/backoff tests)
      io_delay: float      shard store: sleep before every shard read
                           attempt (slow-storage injection — feeds the
                           store.read_wait_seconds telemetry)
      io_error: int        shard store: raise OSError on the first N
                           shard read attempts (TRANSIENT — the
                           store's capped-backoff retry must recover
                           without quarantining anything)
      shard_corrupt: ids   shard store: flip payload bytes of these
                           shard ids after every disk read (int or
                           list).  The stored CRC stays HONEST (it
                           covers the true bytes), so read_checked's
                           checksum validation — not value hygiene —
                           must reject the shard; persistent, so the
                           retry budget exhausts and the shard is
                           quarantined
      shard_missing: ids   shard store: reads of these shard ids raise
                           FileNotFoundError (int or list; persistent
                           -> quarantine, like shard_corrupt)
      replica_crash: int   serve replica: raise ChaosError on EVERY
                           dispatch from the N-th on (exhausts the
                           service's worker-restart budget so the
                           whole replica fails closed — the router's
                           replace-and-replay path)
      slow_replica: float  serve replica: sleep this many seconds
                           before every dispatch (injected dispatch
                           latency — the hedged-retry trigger)
      poison_request: bool serve: a request whose options carry
                           `chaos_poison` crashes whichever replica
                           dispatches it (deterministically, every
                           time) — the router's poison budget must
                           quarantine it instead of hedge-amplifying
                           the crash across the replica set
    """

    HARD_EXIT_CODE = 13

    def __init__(self, config=None):
        self.config = dict(config or {})
        self.steps = 0
        self.writes = 0
        self.builds = 0
        self.shard_reads = 0

    @classmethod
    def from_options(cls, config=None):
        """Merge the options-dict config with the env override (env
        wins; an unset env and empty config yield an inert injector).
        """
        merged = dict(config or {})
        env = os.environ.get(ENV_VAR)
        if env:
            try:
                merged.update(json.loads(env))
            except ValueError:
                pass
        return cls(merged)

    @property
    def active(self):
        return bool(self.config)

    # -- spoke-side -------------------------------------------------------
    def step_tick(self):
        """Advance the spoke step clock; crash or hang on schedule."""
        if not self.config:
            return
        self.steps += 1
        c = self.config
        if c.get("hang_at_step") and self.steps >= int(c["hang_at_step"]):
            # stay alive but stop all progress: the supervisor must
            # notice via write_id staleness, not process death
            while True:          # pragma: no cover - killed externally
                time.sleep(0.25)
        if c.get("device_loss") and self.steps >= int(c["device_loss"]):
            raise DeviceLossError(
                f"injected device loss at step {self.steps}")
        if c.get("crash_at_step") and self.steps >= int(c["crash_at_step"]):
            if c.get("hard_exit"):
                # no cleanup, no atexit, nonzero rc — the in-process
                # stand-in for SIGKILL-ing the spoke
                os._exit(self.HARD_EXIT_CODE)
            raise ChaosError(
                f"injected spoke crash at step {self.steps}")
        if c.get("replica_crash") and self.steps >= int(c["replica_crash"]):
            raise ChaosError(
                f"injected replica crash at dispatch {self.steps}")

    # -- serve-side -------------------------------------------------------
    def pre_dispatch(self):
        """Injected dispatch latency (slow_replica): the serve dispatch
        thread sleeps before executing each group, so queued requests
        age past the router's hedge threshold while the replica stays
        alive and healthy-looking."""
        d = float(self.config.get("slow_replica", 0) or 0)
        if d > 0:
            time.sleep(d)

    def request_tick(self, options):
        """Poison-request injection: when poison_request is armed, a
        request whose options carry `chaos_poison` crashes the
        dispatching worker — every time, on every replica it is
        (re)tried on.  Only a router-level poison budget stops the
        blast radius."""
        if self.config.get("poison_request") \
                and (options or {}).get("chaos_poison"):
            raise ChaosError("injected poison request")

    def poison(self, values):
        """NaN-poison an outgoing vector (bound hygiene tests)."""
        if self.config.get("nan_bound"):
            return np.full_like(np.asarray(values, np.float64), np.nan)
        return values

    def pre_write(self):
        d = float(self.config.get("delay_write_s", 0) or 0)
        if d > 0:
            time.sleep(d)

    def write_fate(self):
        """"ok" | "drop" | "corrupt" for the next outgoing write.

        partition_slice drops writes (the slice goes silent — its
        heartbeat id stops advancing), corrupt_window flips the posted
        payload under an honest checksum so payload validation, not
        value hygiene, must catch it.  Both apply from the N-th write
        on, so heartbeat re-posts keep feeding the corrupt-read budget
        until the hub prunes the slice."""
        if not self.config:
            return "ok"
        self.writes += 1
        c = self.config
        if (c.get("partition_slice")
                and self.writes >= int(c["partition_slice"])):
            return "drop"
        if (c.get("corrupt_window")
                and self.writes >= int(c["corrupt_window"])):
            return "corrupt"
        return "ok"

    # -- streaming-side ---------------------------------------------------
    def block_build_tick(self):
        """Fail the first N scenario-block builds (streaming retry
        tests); the retry wrapper re-enters here on each attempt."""
        n = self.config.get("block_build_fail")
        if not n:
            return
        self.builds += 1
        if self.builds <= int(n):
            raise ChaosError(
                f"injected block build failure {self.builds}/{int(n)}")

    # -- shard-store-side -------------------------------------------------
    @staticmethod
    def _sid_set(v):
        """Normalize an id config value (int or iterable) to a set."""
        if v is None:
            return set()
        if isinstance(v, (int, float)):
            return {int(v)}
        return {int(s) for s in v}

    def shard_read_tick(self, sid):
        """One shard read ATTEMPT (the store's retry loop re-enters
        here per attempt): injected storage latency (io_delay), a
        transient I/O fault for the first `io_error` attempts, and the
        persistent missing-file fault for `shard_missing` ids."""
        if not self.config:
            return
        self.shard_reads += 1
        c = self.config
        d = float(c.get("io_delay", 0) or 0)
        if d > 0:
            time.sleep(d)
        if int(sid) in self._sid_set(c.get("shard_missing")):
            raise FileNotFoundError(
                f"injected missing shard {int(sid)}")
        n = c.get("io_error")
        if n and self.shard_reads <= int(n):
            raise OSError(
                f"injected io error on shard read "
                f"{self.shard_reads}/{int(n)}")

    def corrupt_shard_bytes(self, sid, data):
        """Flip the LAST byte of a shard file image when `sid` is in
        shard_corrupt — always inside the payload region, so the
        header parses but the HONEST stored CRC (computed over the
        true bytes) no longer matches: checksum validation, not value
        hygiene, must catch it."""
        if int(sid) not in self._sid_set(self.config.get("shard_corrupt")):
            return data
        if not data:
            return data
        return data[:-1] + bytes([data[-1] ^ 0xFF])

    # -- hub-side ---------------------------------------------------------
    def hub_iter_tick(self, k):
        """Crash the hub's PH loop at iteration k (checkpoint tests)."""
        at = self.config.get("crash_at_iter")
        if at is not None and int(k) == int(at):
            raise ChaosError(f"injected hub crash at iter {k}")

"""Crash-resumable PH runs: full atomic run checkpoints.

Extends the WXBarWriter W/xbar snapshot (`utils/wxbarutils.py`) into a
complete PH run checkpoint: the whole `PHState` (x, y, W, xbar,
xsqbar, obj, dual_obj, conv, it, solve_iters, active_frac,
solve_restarts, promoted) plus the run-level
scalars (trivial/best bound) and — when the optimizer runs under a
hub — the hub's BestInnerBound/BestOuterBound and incumbent nonant
solution.  Restoring the full state makes the resumed trajectory
bit-replay the uninterrupted one (the superstep is deterministic in
its state), so a run killed at iter k and resumed with `resume_from=`
matches the uninterrupted run's W/xbar/bounds.

Writes are atomic: the .npz is serialized to `<path>.tmp` and
`os.replace`d over the target, so a reader (or a resume after a crash
mid-write) never sees a torn file.
"""

from __future__ import annotations

import io
import os

import numpy as np


def atomic_write(path, data):
    """Atomically write `data` (bytes) to `path` via tmp-file +
    os.replace: a reader — or a resume after a crash mid-write — never
    sees a torn file.  The ONE tmp-rename discipline shared by run/
    wheel/stream checkpoints (`_atomic_savez`), the W/xbar snapshot
    (utils/wxbarutils.py), the spoke solution publish
    (cylinders/proc.py), and the shard corpus (streaming/store.py)."""
    path = str(path)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)
    return path


def _norm_npz(path):
    path = str(path)
    return path if path.endswith(".npz") else path + ".npz"


def checkpoint_exists(path):
    return os.path.exists(_norm_npz(path))


def _opt_float(x):
    """None -> nan for npz storage (and back, in _opt_load)."""
    return np.float64(np.nan if x is None else float(x))


def _opt_load(v):
    v = float(v)
    return None if np.isnan(v) else v


def _atomic_savez(path, payload):
    """Write `payload` as <path>.npz through `atomic_write`.  savez on
    a FILE OBJECT keeps the name verbatim (the path form appends .npz,
    which would break the replace pairing)."""
    buf = io.BytesIO()
    np.savez_compressed(buf, **payload)
    return atomic_write(_norm_npz(path), buf.getvalue())


def _run_payload(opt):
    """The run-checkpoint key set for `opt` (a PHBase with a live
    `state`) — shared by save_run_checkpoint and the wheel ensemble
    (whose file is a strict SUPERSET of this, so load_run_checkpoint /
    restore_hub work unchanged on either format)."""
    st = opt.state
    if st is None:
        raise RuntimeError("cannot checkpoint before Iter0 (no state)")
    hub = getattr(opt, "spcomm", None)
    incumbent = getattr(hub, "best_nonant_solution", None)
    return {
        "x": np.asarray(st.x), "y": np.asarray(st.y),
        "W": np.asarray(st.W), "xbar": np.asarray(st.xbar),
        "xsqbar": np.asarray(st.xsqbar),
        "obj": np.asarray(st.obj), "dual_obj": np.asarray(st.dual_obj),
        "conv": np.float64(st.conv), "it": np.int64(st.it),
        "solve_iters": np.int64(st.solve_iters),
        "active_frac": np.float64(st.active_frac),
        "solve_restarts": np.int64(np.asarray(st.solve_restarts)),
        # precision state (PR 6): whether the last solve ran on the
        # promoted full-precision pair, and the ladder's current
        # tolerance — a resumed hot-dtype run must not silently fall
        # back to the loose start-of-ladder precision
        "promoted": np.int64(np.asarray(st.promoted)),
        "ladder_eps": _opt_float(getattr(opt, "_ladder_eps", None)
                                 if getattr(opt, "_ladder", None)
                                 is not None else None),
        "trivial_bound": _opt_float(getattr(opt, "trivial_bound", None)),
        "best_bound": _opt_float(getattr(opt, "best_bound", None)),
        "nonant_names": (
            np.array(opt.batch.tree.nonant_names, dtype=object)
            if opt.batch.tree.nonant_names else np.array([], dtype=object)),
        "best_inner": _opt_float(getattr(hub, "BestInnerBound", None)),
        "best_outer": _opt_float(getattr(hub, "BestOuterBound", None)),
        "incumbent": (np.asarray(incumbent) if incumbent is not None
                      else np.array([])),
    }


def save_run_checkpoint(path, opt):
    """Atomically persist the full run state of `opt` (a PHBase with a
    live `state`); hub-level bounds ride along when `opt.spcomm` is a
    hub."""
    return _atomic_savez(path, _run_payload(opt))


def load_run_checkpoint(path, opt):
    """Install a saved run state into `opt` (shapes and nonant names
    validated against its batch).  Returns the raw npz dict-like for
    callers that want the hub-level fields too."""
    import jax.numpy as jnp

    from ..phbase import PHState

    z = np.load(_norm_npz(path), allow_pickle=True)
    b = opt.batch
    S, K = b.num_scens, b.num_nonants
    W = np.asarray(z["W"])
    if W.shape != (S, K):
        raise ValueError(
            f"checkpoint W{W.shape} does not match this batch "
            f"(S,K)=({S},{K})")
    if np.asarray(z["x"]).shape[1] != b.num_vars:
        raise ValueError(
            f"checkpoint x has {np.asarray(z['x']).shape[1]} vars, "
            f"batch has {b.num_vars}")
    saved_names = tuple(np.asarray(z["nonant_names"]).tolist())
    cur_names = tuple(b.tree.nonant_names or ())
    if saved_names and cur_names and saved_names != cur_names:
        raise ValueError(
            "checkpoint nonant names do not match this model: "
            f"{saved_names[:3]}... vs {cur_names[:3]}...")
    dt = b.c.dtype
    opt.state = PHState(
        x=jnp.asarray(z["x"], dt), y=jnp.asarray(z["y"], dt),
        W=jnp.asarray(W, dt), xbar=jnp.asarray(z["xbar"], dt),
        xsqbar=jnp.asarray(z["xsqbar"], dt),
        obj=jnp.asarray(z["obj"], dt),
        dual_obj=jnp.asarray(z["dual_obj"], dt),
        conv=jnp.asarray(float(z["conv"]), dt),
        it=jnp.asarray(int(z["it"]), jnp.int32),
        solve_iters=jnp.asarray(int(z["solve_iters"]), jnp.int32),
        # fields added after the original format default when a
        # pre-adaptive-work checkpoint is restored
        active_frac=jnp.asarray(
            float(z["active_frac"]) if "active_frac" in z else 1.0, dt),
        solve_restarts=jnp.asarray(
            int(z["solve_restarts"]) if "solve_restarts" in z else 0,
            jnp.int32),
        # pre-PR-6 checkpoints carry no precision fields: they were
        # written by full-precision (f64-era) runs, so promoted=0
        promoted=jnp.asarray(
            int(z["promoted"]) if "promoted" in z else 0, jnp.int32))
    opt.conv = float(z["conv"])
    opt.trivial_bound = _opt_load(z["trivial_bound"])
    opt.best_bound = _opt_load(z["best_bound"])
    if "ladder_eps" in z and getattr(opt, "_ladder", None) is not None:
        lad_eps = _opt_load(z["ladder_eps"])
        if lad_eps is not None:
            # monotone: the restored tolerance can only tighten the
            # freshly-initialized ladder, never loosen it
            opt._ladder_eps = min(opt._ladder_eps, lad_eps)
    return z


def save_stream_checkpoint(path, sph):
    """Atomically persist a StreamingPH run (streaming/streaming_ph.py).

    The streamed trajectory is a function of (host-resident W, x_na,
    solved mask, consensus xbar, the sampler's RNG state + active
    sample size, the already-drawn next block, and the certification
    cursor) — all host numpy, so the payload never touches jax.
    Restoring every field and re-prefetching the pending block makes
    the resumed trajectory bit-replay the uninterrupted one (asserted
    in tests/test_streaming.py)."""
    if sph.state is None:
        raise RuntimeError("cannot checkpoint before Iter0 (no state)")
    import json

    samp = sph.sampler.state()
    warm = sph._warm_host  # (x_full, y_full) or None
    payload = {
        "stream_format": np.int64(1),
        "W_host": np.asarray(sph.W_host),
        "x_na_host": np.asarray(sph.x_na_host),
        "solved": np.asarray(sph.solved),
        "xbar_host": np.asarray(sph.xbar_host),
        "conv": np.float64(sph.conv),
        "it": np.int64(int(sph.state.it)),
        "active_n": np.int64(samp["active_n"]),
        "est_rounds": np.int64(samp["est_rounds"]),
        "rng_state": np.array(samp["rng_state"]),  # json string
        "pending_indices": np.asarray(sph._pending_indices,
                                      dtype=np.int64),
        "est_seed": np.int64(sph._est_seed),
        "est_history": np.array(json.dumps(sph._est_history)),
        "trivial_bound": _opt_float(getattr(sph, "trivial_bound", None)),
        "best_bound": _opt_float(getattr(sph, "best_bound", None)),
        "ladder_eps": _opt_float(getattr(sph, "_ladder_eps", None)
                                 if getattr(sph, "_ladder", None)
                                 is not None else None),
        "nonant_names": (
            np.array(sph.batch.tree.nonant_names, dtype=object)
            if sph.batch.tree.nonant_names
            else np.array([], dtype=object)),
        "warm_x": (np.asarray(warm[0]) if warm is not None
                   else np.array([])),
        "warm_y": (np.asarray(warm[1]) if warm is not None
                   else np.array([])),
    }
    # storage cursor (shard-backed sources): the quarantine set and
    # retry/resample state — substitution is a pure function of
    # (indices, quarantine set), so restoring this set is what makes
    # the resumed run replay quarantine substitutions bit-equally
    store = getattr(sph, "_shard_store", lambda: None)()
    if store is not None:
        payload["storage_cursor"] = np.array(json.dumps(store.state()))
    return _atomic_savez(path, payload)


def load_stream_checkpoint(path, sph):
    """Install a stream checkpoint into `sph` (a StreamingPH).  Shape/
    name validation mirrors load_run_checkpoint; the pending block is
    NOT prefetched here — the caller re-issues the prefetch so the
    stream worker rebuilds it from the stored indices (blocks are pure
    functions of their index set)."""
    import json

    z = np.load(_norm_npz(path), allow_pickle=True)
    if "stream_format" not in z:
        raise ValueError(
            f"{path} is a plain PH run checkpoint, not a stream "
            "checkpoint (use PH.ph_main resume for it)")
    W = np.asarray(z["W_host"])
    S, K = sph.total_scens, sph.batch.num_nonants
    if W.shape != (S, K):
        raise ValueError(
            f"stream checkpoint W{W.shape} does not match this source "
            f"(S,K)=({S},{K})")
    saved_names = tuple(np.asarray(z["nonant_names"]).tolist())
    cur_names = tuple(sph.batch.tree.nonant_names or ())
    if saved_names and cur_names and saved_names != cur_names:
        raise ValueError(
            "stream checkpoint nonant names do not match this model: "
            f"{saved_names[:3]}... vs {cur_names[:3]}...")
    sph.W_host = W.copy()
    sph.x_na_host = np.asarray(z["x_na_host"]).copy()
    sph.solved = np.asarray(z["solved"]).copy()
    sph.xbar_host = np.asarray(z["xbar_host"]).copy()
    sph.conv = float(z["conv"])
    sph.sampler.restore({
        "active_n": int(z["active_n"]),
        "est_rounds": int(z["est_rounds"]),
        "rng_state": str(z["rng_state"]),
    })
    sph._pending_indices = np.asarray(z["pending_indices"],
                                      dtype=np.int64)
    sph._est_seed = int(z["est_seed"])
    sph._est_history = json.loads(str(z["est_history"]))
    sph.trivial_bound = _opt_load(z["trivial_bound"])
    sph.best_bound = _opt_load(z["best_bound"])
    if getattr(sph, "_ladder", None) is not None:
        lad_eps = _opt_load(z["ladder_eps"])
        if lad_eps is not None:
            sph._ladder_eps = min(sph._ladder_eps, lad_eps)
    wx = np.asarray(z["warm_x"])
    sph._warm_host = ((wx, np.asarray(z["warm_y"])) if wx.size
                      else None)
    store = getattr(sph, "_shard_store", lambda: None)()
    if store is not None and "storage_cursor" in z:
        store.restore(json.loads(str(z["storage_cursor"])))
    sph._install_resumed_state(int(z["it"]))
    return z


def restore_hub(path, hub):
    """Restore hub-level bound state (BestInner/OuterBound, incumbent)
    from a run checkpoint — the hub half of `resume_from=`."""
    z = np.load(_norm_npz(path), allow_pickle=True)
    bi, bo = float(z["best_inner"]), float(z["best_outer"])
    if np.isfinite(bi):
        hub.BestInnerBound = bi
    if np.isfinite(bo):
        hub.BestOuterBound = bo
    inc = np.asarray(z["incumbent"])
    if inc.size:
        hub.best_nonant_solution = inc
    return hub


# -- wheel ensemble checkpoints (MPMD wheel, PR 10) -----------------------
#
# One atomic file for the WHOLE wheel: the hub's run-checkpoint keys
# (a strict superset, so load_run_checkpoint / restore_hub read a
# wheel file unchanged — and a pre-wheel run checkpoint is still a
# valid `resume_from` for the wheel, restoring the hub and starting
# the spokes fresh), plus `wheel_format`, the serialized SlicePlan,
# per-spoke algorithm state from Spoke.algo_state(), the last
# committed payload + write_id of every pair's mailboxes, and the
# hub's per-spoke accounting vectors.  Restoring all of it makes a
# lockstep wheel resume bit-replay the uninterrupted spin; spokes
# marked failed at save time are NOT restored, so a post-failure
# resume restarts only the dead slices.
#
# This module never imports mpmd (AST-guarded): everything here goes
# through the generic hub/spoke/Window interfaces.

def is_wheel_checkpoint(path):
    """True when `path` is an ensemble (wheel_format) checkpoint, not
    a plain PH run checkpoint."""
    if not checkpoint_exists(path):
        return False
    with np.load(_norm_npz(path), allow_pickle=True) as z:
        return "wheel_format" in z


def save_wheel_ensemble(path, hub, plan=None):
    """Atomically persist the full wheel: hub PH state + bounds, every
    live spoke's algorithm state, the last-committed window payloads
    and write-id vector, and the current slice plan (pass
    `plan=SlicePlan.describe()`)."""
    import json

    payload = _run_payload(hub.opt)
    payload["wheel_format"] = np.int64(1)
    payload["wheel_n_spokes"] = np.int64(len(hub.spokes))
    if plan is not None:
        payload["wheel_plan"] = np.array(json.dumps(plan))
    payload["wheel_spoke_read_ids"] = np.asarray(hub._spoke_read_ids)
    payload["wheel_bound_rejects"] = np.asarray(hub.bound_rejects)
    payload["wheel_corrupt_reads"] = np.asarray(
        getattr(hub, "corrupt_reads", np.zeros(len(hub.spokes), np.int64)))
    for j, sp in enumerate(hub.spokes):
        failed = bool(getattr(sp, "_failed", False))
        payload[f"spoke{j}_failed"] = np.int64(failed)
        if failed:
            continue                   # dead slices restart fresh on resume
        for k, v in sp.algo_state().items():
            payload[f"spoke{j}_{k}"] = np.asarray(v)
        pair = hub.pairs[j]
        data, wid = pair.to_spoke.read()
        payload[f"pair{j}_to_spoke"] = np.asarray(data)
        payload[f"pair{j}_to_spoke_id"] = np.int64(wid)
        data, wid = pair.to_hub.read()
        payload[f"pair{j}_to_hub"] = np.asarray(data)
        payload[f"pair{j}_to_hub_id"] = np.int64(wid)
    return _atomic_savez(path, payload)


def load_wheel_ensemble(path, hub):
    """Install the ensemble half of a wheel checkpoint into a wired
    hub (pairs and spokes constructed, setup_hub done).  The hub
    optimizer's PH state is NOT touched here — it rides the normal
    `resume_from` -> load_run_checkpoint path, which reads the same
    file.  Spokes saved as failed are skipped: they restart fresh.
    Window payloads are re-posted under their saved write_ids, so
    freshness comparisons continue exactly where the saved spin
    stopped."""
    z = np.load(_norm_npz(path), allow_pickle=True)
    if "wheel_format" not in z:
        raise ValueError(
            f"{path} is a plain PH run checkpoint, not a wheel "
            "ensemble (it restores the hub only)")
    n = int(z["wheel_n_spokes"])
    if n != len(hub.spokes):
        raise ValueError(
            f"wheel checkpoint has {n} spokes, this wheel has "
            f"{len(hub.spokes)}")
    hub._spoke_read_ids[:] = np.asarray(z["wheel_spoke_read_ids"])
    hub.bound_rejects[:] = np.asarray(z["wheel_bound_rejects"])
    if hasattr(hub, "corrupt_reads") and "wheel_corrupt_reads" in z:
        hub.corrupt_reads[:] = np.asarray(z["wheel_corrupt_reads"])
    for j, sp in enumerate(hub.spokes):
        if int(z[f"spoke{j}_failed"]):
            continue
        prefix = f"spoke{j}_"
        state = {k[len(prefix):]: z[k] for k in z.files
                 if k.startswith(prefix) and k != f"spoke{j}_failed"}
        sp.restore_algo_state(state)
        pair = hub.pairs[j]
        for win, key in ((pair.to_spoke, f"pair{j}_to_spoke"),
                         (pair.to_hub, f"pair{j}_to_hub")):
            wid = int(z[key + "_id"])
            data = np.asarray(z[key])
            # shape guard: a resume under a different plan can carry a
            # different padded length — skip the re-post and let the
            # next superstep publish fresh vectors
            if wid > 0 and data.shape == (win.length,):
                win.write(data, write_id=wid)
    return z


# -- serve drain checkpoints (serve/service.py, PR 10) --------------------

def save_drain_checkpoint(path, requests):
    """Atomically persist the requests a draining SolverService could
    not finish: a list of plain dicts (id, options, scenario_names,
    model, batch with HOST-numpy leaves — the caller converts; device
    buffers do not pickle).  A restarted service warms from this file
    and resubmits them."""
    payload = {
        "drain_format": np.int64(1),
        "requests": np.array(list(requests), dtype=object),
    }
    return _atomic_savez(path, payload)


def load_drain_checkpoint(path):
    """The saved request dicts, in submission order.  A truncated or
    bit-flipped file (np.load / zip / pickle errors) raises ValueError
    with the underlying cause — SolverService.warm_from turns that
    into a structured reject instead of propagating mid-resubmit."""
    try:
        z = np.load(_norm_npz(path), allow_pickle=True)
        if "drain_format" not in z:
            raise ValueError(f"{path} is not a drain checkpoint")
        return list(np.asarray(z["requests"], dtype=object))
    except ValueError:
        raise
    except Exception as exc:
        raise ValueError(
            f"corrupt or truncated drain checkpoint {path}: "
            f"{exc!r}") from exc

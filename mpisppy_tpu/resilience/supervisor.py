"""SpokeSupervisor — process supervision for the multiproc wheel.

The multiproc mode (`cylinders/proc.py`) runs each spoke as its own OS
process dialing into the hub's mmap seqlock windows.  Before this
module the hub had zero supervision: a crashed spoke was never
detected (`SpokeHandle.step()` is a no-op) and a hung one blocked
nothing but produced nothing.  The supervisor closes that gap:

  * **death detection** via `Popen.poll()` each supervision interval
    (the hub calls `poll()` from `sync()` every iteration; a throttle
    keeps the cost bounded);
  * **hang detection** via window `write_id` staleness — the spoke's
    own bound writes are the heartbeat (bound spokes re-post their
    current bound on a timer precisely so the id keeps advancing, see
    `cylinders/spoke.py`), monotone by the seqlock protocol
    (`runtime/exchange.cpp`);
  * **escalated kills** SIGTERM -> SIGKILL with a deadline for hung
    children;
  * **restarts** from the declarative spec with capped exponential
    backoff — the fresh process re-attaches to the existing window
    files and re-acquires warm state from the hub's last W/nonant
    write (attach never resets the files, `cylinders/spcommunicator`);
  * **permanent pruning** into the hub's `_mark_spoke_failed` path
    once the restart budget is exhausted, so the wheel finishes on the
    hub's own valid bounds;
  * **exit reporting** — every nonzero exit code plus the tail of the
    incarnation's log file is kept and surfaced in the hub's final
    report instead of being silently discarded.

Options (read from the hub's options dict):
  supervise_interval        min seconds between polls        (1.0)
  spoke_hang_timeout        stale-window seconds -> hung     (300.0)
  spoke_max_restarts        restarts before pruning          (2)
  spoke_restart_backoff     first backoff seconds, doubling  (0.5)
  spoke_restart_backoff_cap backoff ceiling seconds          (30.0)
  spoke_term_deadline       SIGTERM grace before SIGKILL     (5.0)
"""

from __future__ import annotations

import os
import signal
import time

from .. import global_toc
from .. import telemetry as _telemetry

LIVE, WAITING, STOPPED, FAILED = "live", "waiting", "stopped", "failed"


def restart_delay(n, backoff, cap):
    """Capped exponential backoff before the n-th restart (n >= 1) —
    the single restart-pacing policy, shared by SpokeSupervisor and
    the serve layer's worker supervision (serve/service.py)."""
    return min(backoff * 2.0 ** (n - 1), cap)


def _log_tail(proc, max_lines=15):
    lp = getattr(proc, "log_path", None)
    if lp and os.path.exists(lp):
        try:
            with open(lp) as f:
                return "".join(f.readlines()[-max_lines:])
        except OSError:
            pass
    return ""


class SpokeSupervisor:
    def __init__(self, hub, specs, workdir, options=None, spawn_fn=None):
        if spawn_fn is None:
            from ..cylinders.proc import spawn_spoke as spawn_fn
        self.hub = hub
        self.handles = hub.spokes          # SpokeHandle per spoke
        self.specs = list(specs)
        self.workdir = workdir
        self._spawn = spawn_fn
        o = dict(options or {})
        self.interval = float(o.get("supervise_interval", 1.0))
        self.hang_timeout = float(o.get("spoke_hang_timeout", 300.0))
        self.max_restarts = int(o.get("spoke_max_restarts", 2))
        self.backoff = float(o.get("spoke_restart_backoff", 0.5))
        self.backoff_cap = float(o.get("spoke_restart_backoff_cap", 30.0))
        self.term_deadline = float(o.get("spoke_term_deadline", 5.0))
        n = len(self.specs)
        self.state = [STOPPED] * n
        self.restarts = [0] * n            # incarnations beyond the first
        self._next_restart = [0.0] * n
        self._last_wid = [None] * n
        self._last_progress = [0.0] * n
        self._last_poll = 0.0
        self._shutting_down = False
        self.killed_by_us = set()
        # run-level counters (bench.py JSON; resilience.wheel_counters)
        self.spoke_restarts = 0
        self.spokes_failed = 0
        self.exit_reports = []             # dicts: spoke/rc/log_tail/...
        # lifecycle events land in the shared telemetry event log /
        # metrics (no-ops when telemetry is off); tolerate bare fake
        # hubs in tests that lack a .telemetry attribute
        self._tel = getattr(hub, "telemetry", None) or _telemetry.get()

    # -- lifecycle --------------------------------------------------------
    def start(self):
        for i in range(len(self.specs)):
            self._spawn_incarnation(i, first=True)
        return self

    def _spawn_incarnation(self, i, first=False):
        tag = str(i) if first else f"{i}r{self.restarts[i]}"
        p = self._spawn(self.specs[i], self.workdir, tag)
        self.handles[i].proc = p
        self.state[i] = LIVE
        self._last_wid[i] = None
        self._last_progress[i] = time.monotonic()
        self._tel.event("supervisor.spawn", spoke=i,
                        incarnation=self.restarts[i],
                        pid=getattr(p, "pid", None))

    # -- supervision (hub thread, called from Hub.sync) -------------------
    def poll(self, force=False):
        now = time.monotonic()
        if self._shutting_down or (not force
                                   and now - self._last_poll < self.interval):
            return
        self._last_poll = now
        for i, h in enumerate(self.handles):
            if self.state[i] == WAITING:
                if now >= self._next_restart[i]:
                    self._spawn_incarnation(i)
                continue
            if self.state[i] != LIVE:
                continue
            rc = h.proc.poll()
            if rc is not None:
                if rc == 0:
                    # clean early exit (e.g. the spoke saw a stale kill
                    # flag): not a failure, just out of the wheel
                    self.state[i] = STOPPED
                    continue
                self._record_exit(i, rc)
                self._on_down(i, f"exited rc={rc}")
                continue
            # hang detection: the spoke's to_hub write_id is its
            # heartbeat; no advance within the timeout => hung
            wid = self.hub.pairs[i].to_hub.write_id
            if wid != self._last_wid[i]:
                self._last_wid[i] = wid
                self._last_progress[i] = now
            self._tel.gauge(f"supervisor.heartbeat_age.spoke{i}").set(
                now - self._last_progress[i])
            if wid == self._last_wid[i] \
                    and now - self._last_progress[i] > self.hang_timeout:
                self._kill_escalating(i)
                rc = h.proc.poll()
                self._record_exit(i, rc, hung=True)
                self._on_down(
                    i, f"hung: no window write for "
                       f"{now - self._last_progress[i]:.1f}s")

    def _kill_escalating(self, i):
        """SIGTERM, wait out the deadline, then SIGKILL."""
        p = self.handles[i].proc
        self.killed_by_us.add(p.pid)
        try:
            self._tel.event("supervisor.sigterm", spoke=i, pid=p.pid)
            p.send_signal(signal.SIGTERM)
            p.wait(timeout=self.term_deadline)
        except Exception:
            try:
                self._tel.event("supervisor.sigkill", spoke=i, pid=p.pid)
                p.kill()
                p.wait(timeout=self.term_deadline)
            except Exception:      # pragma: no cover - unkillable child
                pass

    def _record_exit(self, i, rc, hung=False):
        self.exit_reports.append({
            "spoke": i,
            "name": self.handles[i].spoke_name,
            "incarnation": self.restarts[i],
            "rc": rc,
            "hung": hung,
            "log_tail": _log_tail(self.handles[i].proc),
        })

    def _on_down(self, i, reason):
        h = self.handles[i]
        if self.restarts[i] < self.max_restarts:
            self.restarts[i] += 1
            self.spoke_restarts += 1
            delay = restart_delay(self.restarts[i], self.backoff,
                                  self.backoff_cap)
            self._next_restart[i] = time.monotonic() + delay
            self.state[i] = WAITING
            self._tel.event("supervisor.restart", spoke=i, reason=reason,
                            incarnation=self.restarts[i], delay=delay)
            self._tel.counter("supervisor.restarts").inc()
            global_toc(f"WARNING: spoke {i} ({h.spoke_name}) {reason}; "
                       f"restart {self.restarts[i]}/{self.max_restarts} "
                       f"in {delay:.2f}s")
        else:
            self.state[i] = FAILED
            self.spokes_failed += 1
            self._tel.event("supervisor.prune", spoke=i, reason=reason,
                            restarts=self.restarts[i])
            self._tel.counter("supervisor.spokes_failed").inc()
            tail = self.exit_reports[-1]["log_tail"] if self.exit_reports \
                else ""
            self.hub._mark_spoke_failed(i, RuntimeError(
                f"{reason} after {self.restarts[i]} restart(s); "
                f"log tail:\n{tail}"))

    # -- shutdown (after hub.send_terminate) ------------------------------
    def shutdown(self, timeout=120.0):
        """Wait for live children to exit on the kill signal; escalate
        stragglers; collect exit reports for any nonzero rc."""
        self._shutting_down = True
        for i, h in enumerate(self.handles):
            if self.state[i] != LIVE or h.proc is None:
                continue
            try:
                h.proc.wait(timeout=timeout)
            except Exception:
                global_toc(f"spoke {i} still busy {timeout:.0f}s after "
                           "the kill signal; terminating it")
                self._kill_escalating(i)
            rc = h.proc.poll()
            if rc is not None and rc != 0 \
                    and h.proc.pid not in self.killed_by_us:
                self._record_exit(i, rc)
            self.state[i] = STOPPED

    def kill_all(self):
        """Last-resort cleanup: nothing may outlive the wheel."""
        self._shutting_down = True
        for h in self.handles:
            p = getattr(h, "proc", None)
            if p is not None and p.poll() is None:
                self.killed_by_us.add(p.pid)
                p.kill()

from .native import NativeWindow, available  # noqa: F401

from .native import NativeWindow, PySeqlockWindow, available  # noqa: F401

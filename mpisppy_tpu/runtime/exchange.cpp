// Host-side inter-cylinder exchange: seqlock double-buffer windows.
//
// TPU-native counterpart of the reference's one-sided MPI RMA windows
// (reference mpisppy/cylinders/spcommunicator.py:93-120: MPI.Win with
// Lock/Put/Unlock writes, Lock/Get/Unlock reads, and a trailing
// monotonically-increasing write_id slot; kill signal = write_id -1,
// hub.py:438-450).  Here a window is a shared-memory region (mmap'd
// file for cross-process / multi-host-gateway use, heap for in-process
// threads) guarded by a SEQLOCK: the writer increments `seq` to an odd
// value, stores the payload + write_id, then bumps `seq` to the next
// even value; readers snapshot, and retry when `seq` was odd or moved
// — the same torn-read protection the reference gets from the
// write_id consensus check (spoke.py:99-118), without any reader-side
// locking of the writer.
//
// Build: g++ -O3 -shared -fPIC -o libexchange.so exchange.cpp
// (driven by runtime/native.py at import, cached by mtime).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <new>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Header {
    std::atomic<int64_t> seq;       // even = stable, odd = write nobody
    std::atomic<int64_t> write_id;  // -1 == KILL
    int64_t length;                 // payload doubles
};

struct Handle {
    Header* hdr;
    double* data;
    size_t map_bytes;
    int fd;          // -1 => heap-backed
};

size_t region_bytes(int64_t length) {
    return sizeof(Header) + static_cast<size_t>(length) * sizeof(double);
}

}  // namespace

extern "C" {

// path == nullptr -> private in-process window (threads).
// Otherwise an mmap'd file shared across processes.  reset != 0
// reinitializes an existing file's header — a leftover kill flag or
// stale write_id from a previous run must not leak into a new one.
void* exch_create(const char* path, int64_t length, int reset) {
    if (length <= 0) return nullptr;
    const size_t bytes = region_bytes(length);
    Handle* h = new (std::nothrow) Handle();
    if (!h) return nullptr;
    h->map_bytes = bytes;
    h->fd = -1;
    void* mem = nullptr;
    if (path == nullptr) {
        mem = ::mmap(nullptr, bytes, PROT_READ | PROT_WRITE,
                     MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
        if (mem == MAP_FAILED) { delete h; return nullptr; }
    } else {
        int fd = ::open(path, O_RDWR | O_CREAT, 0644);
        if (fd < 0) { delete h; return nullptr; }
        bool fresh = false;
        struct stat st;
        if (::fstat(fd, &st) == 0 &&
            st.st_size < static_cast<off_t>(bytes)) {
            if (::ftruncate(fd, bytes) != 0) {
                ::close(fd); delete h; return nullptr;
            }
            fresh = true;
        }
        mem = ::mmap(nullptr, bytes, PROT_READ | PROT_WRITE, MAP_SHARED,
                     fd, 0);
        if (mem == MAP_FAILED) { ::close(fd); delete h; return nullptr; }
        h->fd = fd;
        if (!fresh) {
            // existing file: sanity-check recorded length
            Header* hdr = reinterpret_cast<Header*>(mem);
            if (hdr->length != 0 && hdr->length != length) {
                ::munmap(mem, bytes); ::close(fd); delete h;
                return nullptr;
            }
        }
    }
    h->hdr = reinterpret_cast<Header*>(mem);
    h->data = reinterpret_cast<double*>(
        reinterpret_cast<char*>(mem) + sizeof(Header));
    // initialize if virgin (length==0) or explicitly reset
    if (h->hdr->length == 0 || reset) {
        h->hdr->seq.store(0, std::memory_order_relaxed);
        h->hdr->write_id.store(0, std::memory_order_relaxed);
        h->hdr->length = length;
    }
    return h;
}

void exch_close(void* vh) {
    if (!vh) return;
    Handle* h = static_cast<Handle*>(vh);
    ::munmap(h->hdr, h->map_bytes);
    if (h->fd >= 0) ::close(h->fd);
    delete h;
}

// write_id < 0 -> auto-increment.  Returns the id written.
int64_t exch_write(void* vh, const double* vals, int64_t n,
                   int64_t write_id) {
    Handle* h = static_cast<Handle*>(vh);
    if (!h || n != h->hdr->length) return -2;
    Header* hdr = h->hdr;
    int64_t s = hdr->seq.load(std::memory_order_relaxed);
    hdr->seq.store(s + 1, std::memory_order_release);   // odd: in write
    std::atomic_thread_fence(std::memory_order_release);
    std::memcpy(h->data, vals, n * sizeof(double));
    int64_t id = write_id >= 0
        ? write_id
        : hdr->write_id.load(std::memory_order_relaxed) + 1;
    hdr->write_id.store(id, std::memory_order_relaxed);
    std::atomic_thread_fence(std::memory_order_release);
    hdr->seq.store(s + 2, std::memory_order_release);   // even: stable
    return id;
}

// Snapshot into out; returns the write_id of the snapshot.
int64_t exch_read(void* vh, double* out, int64_t n) {
    Handle* h = static_cast<Handle*>(vh);
    if (!h || n != h->hdr->length) return -2;
    Header* hdr = h->hdr;
    while (true) {
        int64_t s0 = hdr->seq.load(std::memory_order_acquire);
        if (s0 & 1) continue;                       // write in flight
        std::atomic_thread_fence(std::memory_order_acquire);
        std::memcpy(out, h->data, n * sizeof(double));
        int64_t id = hdr->write_id.load(std::memory_order_relaxed);
        std::atomic_thread_fence(std::memory_order_acquire);
        int64_t s1 = hdr->seq.load(std::memory_order_acquire);
        if (s0 == s1) return id;                    // consistent
    }
}

int64_t exch_write_id(void* vh) {
    Handle* h = static_cast<Handle*>(vh);
    return h ? h->hdr->write_id.load(std::memory_order_acquire) : -2;
}

void exch_kill(void* vh) {
    Handle* h = static_cast<Handle*>(vh);
    if (h) h->hdr->write_id.store(-1, std::memory_order_release);
}

}  // extern "C"

"""Build + ctypes bindings for the native runtime (runtime/exchange.cpp).

Compiles the shared library on first use with g++ (toolchain is part
of the target environment), caching by source mtime.  If no compiler
is available the import still succeeds, `available()` returns False,
and NativeWindow transparently delegates to PySeqlockWindow — a pure
numpy-over-mmap implementation of the SAME memory layout (24-byte
{seq, write_id, length} int64 header + float64 payload), so the wheel
runs on boxes without g++ and the two implementations interoperate on
one mmap file.  The native path stays preferred: the fallback only
engages when the library cannot be built or loaded.
"""

from __future__ import annotations

import ctypes
import mmap
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "exchange.cpp")
_LIB = os.path.join(_HERE, "libexchange.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build():
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           "-o", _LIB, _SRC]
    subprocess.run(cmd, check=True, capture_output=True)


def _load():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if (not os.path.exists(_LIB)
                    or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
                _build()
            try:
                lib = ctypes.CDLL(_LIB)
            except OSError:
                # a checked-out .so may target another toolchain/ABI;
                # one rebuild from source is authoritative
                _build()
                lib = ctypes.CDLL(_LIB)
        except (OSError, subprocess.CalledProcessError):
            return None
        lib.exch_create.restype = ctypes.c_void_p
        lib.exch_create.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                    ctypes.c_int]
        lib.exch_close.argtypes = [ctypes.c_void_p]
        lib.exch_write.restype = ctypes.c_int64
        lib.exch_write.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_double),
                                   ctypes.c_int64, ctypes.c_int64]
        lib.exch_read.restype = ctypes.c_int64
        lib.exch_read.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_double),
                                  ctypes.c_int64]
        lib.exch_write_id.restype = ctypes.c_int64
        lib.exch_write_id.argtypes = [ctypes.c_void_p]
        lib.exch_kill.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available():
    """True iff the COMPILED exchange library is loadable — the
    fallback below keeps NativeWindow working either way, but callers
    that specifically exercise the C++ path (tests) key off this."""
    return _load() is not None


class PySeqlockWindow:
    """Pure-Python mmap seqlock with exchange.cpp's exact memory
    layout: int64 {seq, write_id, length} header then `length`
    float64s.  Writers bump seq to odd, copy the payload, store the
    write_id (auto-increment when None, KILL=-1 from send_kill), and
    bump seq back to even; readers retry while seq is odd or changed
    underneath the copy — so a process using this class and one using
    the C++ library can share a single window file."""

    KILL = -1
    _HDR = 24                       # 3 x int64, matches struct Header

    def __init__(self, length: int, path: str | None = None,
                 reset: bool = False):
        if length <= 0:
            raise ValueError("window length must be positive")
        self.length = int(length)
        nbytes = self._HDR + 8 * self.length
        self._fd = -1
        if path is None:
            self._mm = mmap.mmap(-1, nbytes)
            fresh = True
        else:
            fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
            st = os.fstat(fd).st_size
            fresh = st == 0
            if fresh:
                os.ftruncate(fd, nbytes)
            elif st != nbytes:
                # exchange.cpp's exch_create refuses a file whose size
                # disagrees with the requested length; growing it here
                # would tear a reader already attached at the old size
                os.close(fd)
                raise RuntimeError("exch_create failed: length mismatch")
            self._mm = mmap.mmap(fd, nbytes)
            self._fd = fd
        self._hdr = np.frombuffer(self._mm, dtype=np.int64, count=3)
        self._data = np.frombuffer(self._mm, dtype=np.float64,
                                   count=self.length, offset=self._HDR)
        if not fresh and self._hdr[2] not in (0, self.length):
            raise RuntimeError("exch_create failed: length mismatch")
        if fresh or self._hdr[2] == 0 or reset:
            self._hdr[0] = 0
            self._hdr[1] = 0
            self._hdr[2] = self.length
        self._lock = threading.Lock()

    @property
    def write_id(self):
        return int(self._hdr[1])

    def write(self, values, write_id=None):
        values = np.ascontiguousarray(values, dtype=np.float64)
        if values.shape != (self.length,):
            raise ValueError(
                f"window expects shape ({self.length},), "
                f"got {values.shape}")
        with self._lock:
            s = int(self._hdr[0])
            self._hdr[0] = s + 1
            self._data[:] = values
            wid = (int(self._hdr[1]) + 1 if write_id is None
                   else int(write_id))
            self._hdr[1] = wid
            self._hdr[0] = s + 2
            return wid

    def read(self):
        while True:
            s0 = int(self._hdr[0])
            if s0 & 1:
                continue
            out = self._data.copy()
            wid = int(self._hdr[1])
            if int(self._hdr[0]) == s0:
                return out, wid

    def send_kill(self):
        with self._lock:
            self._hdr[1] = self.KILL

    def close(self):
        if getattr(self, "_mm", None) is not None:
            # drop the numpy views FIRST: mmap.close raises BufferError
            # while buffer exports are alive
            self._hdr = None
            self._data = None
            self._mm.close()
            self._mm = None
            if self._fd >= 0:
                os.close(self._fd)
                self._fd = -1

    def __del__(self):                                  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


class NativeWindow:
    """Drop-in for cylinders.spcommunicator.Window backed by the C++
    seqlock exchange; pass `path` for a cross-process (mmap file)
    window — the DCN-gateway layout."""

    KILL = -1

    def __init__(self, length: int, path: str | None = None,
                 reset: bool = False):
        """reset=True reinitializes an existing mmap file (owners pass
        it; attaching readers must not).  When the compiled library is
        unavailable (no g++, broken ABI) this delegates to the
        layout-compatible PySeqlockWindow instead of raising, so the
        wheel's native-backend paths keep working toolchain-free."""
        lib = _load()
        self.length = int(length)
        if lib is None:
            self._lib = None
            self._h = None
            self._py = PySeqlockWindow(self.length, path=path,
                                       reset=reset)
            return
        self._py = None
        self._lib = lib
        p = path.encode() if path is not None else None
        self._h = lib.exch_create(p, self.length, 1 if reset else 0)
        if not self._h:
            raise RuntimeError("exch_create failed")

    @property
    def write_id(self):
        if self._py is not None:
            return self._py.write_id
        return int(self._lib.exch_write_id(self._h))

    def write(self, values, write_id=None):
        if self._py is not None:
            return self._py.write(values, write_id=write_id)
        values = np.ascontiguousarray(values, dtype=np.float64)
        if values.shape != (self.length,):
            raise ValueError(
                f"window expects shape ({self.length},), "
                f"got {values.shape}")
        wid = -1 if write_id is None else int(write_id)
        out = self._lib.exch_write(
            self._h, values.ctypes.data_as(
                ctypes.POINTER(ctypes.c_double)),
            self.length, wid)
        if out == -2:
            raise RuntimeError("native window length mismatch")
        return int(out)

    def read(self):
        if self._py is not None:
            return self._py.read()
        out = np.empty(self.length, dtype=np.float64)
        wid = self._lib.exch_read(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            self.length)
        if wid == -2:
            raise RuntimeError("native window length mismatch")
        return out, int(wid)

    def send_kill(self):
        if self._py is not None:
            return self._py.send_kill()
        self._lib.exch_kill(self._h)

    def close(self):
        if getattr(self, "_py", None) is not None:
            self._py.close()
            self._py = None
        if getattr(self, "_h", None):
            self._lib.exch_close(self._h)
            self._h = None

    def __del__(self):                                  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass

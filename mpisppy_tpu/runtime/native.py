"""Build + ctypes bindings for the native runtime (runtime/exchange.cpp).

Compiles the shared library on first use with g++ (toolchain is part
of the target environment), caching by source mtime.  If no compiler
is available the import still succeeds and `available()` returns False
— callers fall back to the pure-Python Window.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "exchange.cpp")
_LIB = os.path.join(_HERE, "libexchange.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build():
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           "-o", _LIB, _SRC]
    subprocess.run(cmd, check=True, capture_output=True)


def _load():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if (not os.path.exists(_LIB)
                    or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
                _build()
            try:
                lib = ctypes.CDLL(_LIB)
            except OSError:
                # a checked-out .so may target another toolchain/ABI;
                # one rebuild from source is authoritative
                _build()
                lib = ctypes.CDLL(_LIB)
        except (OSError, subprocess.CalledProcessError):
            return None
        lib.exch_create.restype = ctypes.c_void_p
        lib.exch_create.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                    ctypes.c_int]
        lib.exch_close.argtypes = [ctypes.c_void_p]
        lib.exch_write.restype = ctypes.c_int64
        lib.exch_write.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_double),
                                   ctypes.c_int64, ctypes.c_int64]
        lib.exch_read.restype = ctypes.c_int64
        lib.exch_read.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_double),
                                  ctypes.c_int64]
        lib.exch_write_id.restype = ctypes.c_int64
        lib.exch_write_id.argtypes = [ctypes.c_void_p]
        lib.exch_kill.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available():
    return _load() is not None


class NativeWindow:
    """Drop-in for cylinders.spcommunicator.Window backed by the C++
    seqlock exchange; pass `path` for a cross-process (mmap file)
    window — the DCN-gateway layout."""

    KILL = -1

    def __init__(self, length: int, path: str | None = None,
                 reset: bool = False):
        """reset=True reinitializes an existing mmap file (owners pass
        it; attaching readers must not)."""
        lib = _load()
        if lib is None:
            raise RuntimeError("native exchange library unavailable")
        self._lib = lib
        self.length = int(length)
        p = path.encode() if path is not None else None
        self._h = lib.exch_create(p, self.length, 1 if reset else 0)
        if not self._h:
            raise RuntimeError("exch_create failed")

    @property
    def write_id(self):
        return int(self._lib.exch_write_id(self._h))

    def write(self, values, write_id=None):
        values = np.ascontiguousarray(values, dtype=np.float64)
        if values.shape != (self.length,):
            raise ValueError(
                f"window expects shape ({self.length},), "
                f"got {values.shape}")
        wid = -1 if write_id is None else int(write_id)
        out = self._lib.exch_write(
            self._h, values.ctypes.data_as(
                ctypes.POINTER(ctypes.c_double)),
            self.length, wid)
        if out == -2:
            raise RuntimeError("native window length mismatch")
        return int(out)

    def read(self):
        out = np.empty(self.length, dtype=np.float64)
        wid = self._lib.exch_read(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            self.length)
        if wid == -2:
            raise RuntimeError("native window length mismatch")
        return out, int(wid)

    def send_kill(self):
        self._lib.exch_kill(self._h)

    def close(self):
        if getattr(self, "_h", None):
            self._lib.exch_close(self._h)
            self._h = None

    def __del__(self):                                  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass

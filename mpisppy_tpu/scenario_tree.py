"""Scenario-tree metadata for multistage problems.

Reference counterparts: `ScenarioNode` (mpisppy/scenario_tree.py:44),
`sputils.create_nodenames_from_branching_factors` (sputils.py:934),
`sputils._ScenTree`/`_TreeNode` (sputils.py:675-840) and the per-tree-
node communicator construction (spbase.py:333-375).

TPU-first design: the tree is pure static metadata.  Each nonant slot
of each scenario carries the GLOBAL id of the tree node that owns it
(`ir.TreeInfo.node_of`); consensus reductions are segment-sums over
those ids inside one jitted program, so 2-stage and multistage run the
exact same code.  Nothing here ever touches a device.

Node numbering: breadth-first over non-leaf stages — ROOT = 0, then the
stage-2 nodes left-to-right, then stage-3, ...  Leaf nodes are elided,
exactly like the reference ("mpisppy does not have leaf nodes",
reference hydro.py MakeAllScenarioTreeNodes comment; sputils.py:659).
"""

from __future__ import annotations

import numpy as np


def create_nodenames_from_branching_factors(branching_factors):
    """Non-leaf node names for a balanced tree (reference
    sputils.py:934).  BFs [3,3] (3 stages) -> ["ROOT", "ROOT_0",
    "ROOT_1", "ROOT_2"]; leaves are elided."""
    names = ["ROOT"]
    frontier = ["ROOT"]
    # nodes exist at stages 1..len(BFs); stage t branches BFs[t-1] ways
    for bf in branching_factors[:-1]:
        nxt = []
        for parent in frontier:
            for b in range(bf):
                nxt.append(f"{parent}_{b}")
        names.extend(nxt)
        frontier = nxt
    return names


class MultistageTree:
    """Balanced scenario tree from branching factors.

    branching_factors: list of ints, length = n_stages - 1.  Scenario
    count = prod(BFs).  Scenario i (0-based) follows the digit path of
    i in the mixed-radix system of the BFs.

    Attributes:
        nodenames: non-leaf names, breadth-first (id = index)
        num_nodes: number of non-leaf nodes
        n_stages:  len(BFs) + 1
        num_scens: prod(BFs)
    """

    def __init__(self, branching_factors, cond_probs=None):
        self.branching_factors = list(branching_factors)
        self.n_stages = len(self.branching_factors) + 1
        self.num_scens = int(np.prod(self.branching_factors))
        self.nodenames = create_nodenames_from_branching_factors(
            self.branching_factors)
        self.num_nodes = len(self.nodenames)
        self._id_of = {n: i for i, n in enumerate(self.nodenames)}
        # per-stage node id offsets: stage t (1-based) nodes occupy
        # ids [offset[t-1], offset[t])
        self._stage_counts = [1]
        for bf in self.branching_factors[:-1]:
            self._stage_counts.append(self._stage_counts[-1] * bf)
        self._stage_offsets = np.concatenate(
            [[0], np.cumsum(self._stage_counts)])
        # conditional probability per branch of each stage (uniform
        # unless given); reference ScenarioNode cond_prob
        if cond_probs is None:
            cond_probs = [
                np.full((bf,), 1.0 / bf) for bf in self.branching_factors
            ]
        self.cond_probs = [np.asarray(p, float) for p in cond_probs]

    def node_id(self, name):
        return self._id_of[name]

    def scen_digits(self, scennum):
        """Mixed-radix digits of scenario scennum (0-based), most
        significant (stage-2 branch) first."""
        digits = []
        rem = scennum
        for bf in reversed(self.branching_factors):
            digits.append(rem % bf)
            rem //= bf
        return list(reversed(digits))

    def nodes_for_scen(self, scennum):
        """Global ids of the non-leaf nodes scenario scennum passes
        through, one per stage 1..n_stages-1 (reference hydro.py
        MakeNodesforScen)."""
        digits = self.scen_digits(scennum)
        ids = [0]
        idx = 0  # index of current node within its stage
        for t in range(1, self.n_stages - 1):
            idx = idx * self.branching_factors[t - 1] + digits[t - 1]
            ids.append(int(self._stage_offsets[t] + idx))
        return ids

    def nodenames_for_scen(self, scennum):
        return [self.nodenames[i] for i in self.nodes_for_scen(scennum)]

    def scen_probability(self, scennum):
        """Unconditional probability (reference
        spbase.py:378 _compute_unconditional_node_probabilities)."""
        p = 1.0
        for t, d in enumerate(self.scen_digits(scennum)):
            p *= float(self.cond_probs[t][d])
        return p

    def node_of_slots(self, scennum, stage_of):
        """(K,) global node id per nonant slot, given each slot's stage
        (1-based).  Slots of stage t attach to the scenario's stage-t
        node."""
        ids = self.nodes_for_scen(scennum)
        stage_of = np.asarray(stage_of, np.int32)
        if stage_of.size and stage_of.max() > len(ids):
            raise ValueError(
                f"nonant slot declared at stage {int(stage_of.max())} but "
                f"the tree has only {len(ids)} non-leaf stages")
        return np.array([ids[t - 1] for t in stage_of], np.int32)

    def scens_of_node(self, node_id):
        """List of scenario numbers passing through node_id."""
        return [s for s in range(self.num_scens)
                if node_id in self.nodes_for_scen(s)]

    def stage_of_node(self, node_id):
        """1-based stage of a node id."""
        return int(np.searchsorted(self._stage_offsets, node_id,
                                   side="right"))

    def parent_of(self, node_id):
        """Parent node id (None for ROOT)."""
        if node_id == 0:
            return None
        t = self.stage_of_node(node_id)           # node's stage
        idx = node_id - self._stage_offsets[t - 1]
        pidx = idx // self.branching_factors[t - 2]
        return int(self._stage_offsets[t - 2] + pidx)


def two_stage_tree(num_scens, probs=None):
    """Degenerate 1-node tree for 2-stage problems."""
    t = MultistageTree([num_scens],
                       cond_probs=None if probs is None else [probs])
    return t

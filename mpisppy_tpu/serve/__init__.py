"""Solver-as-a-service: a persistent in-process solver layer.

Modules (doc/src/serve.md is the operator-facing chapter):

  * `compile_cache` — shape-bucketed compile cache: one executable per
    (model, scenario count, stage dims, dtype, backend, solver config)
    bucket, deduplicated through the thread-scoped jit registries
    (phbase.fused_superstep / ops.pdhg.shared_solve_jit), plus AOT
    `jit(vmap(superstep)).lower().compile()` executables for coalesced
    batches;
  * `service` — SolverService: bounded queue, admission control,
    deadline handling (structured timeout results, never a hang), a
    dispatch loop that coalesces same-bucket requests into one
    vmap-batched execution, and SpokeSupervisor-style worker
    supervision (chaos-injectable, capped-backoff restarts);
  * `replica` — Replica/ReplicaSet: N supervised services as isolated
    fault domains (own threads, own compile-cache handle, separately
    drainable), with slot-targeted chaos and replace-and-warm_from;
  * `procpool`/`procworker` — ProcReplica/ProcReplicaSet: the same
    replica surface backed by one OS process per slot
    (`serve_replica_mode="process"`), talking the serve/net wire
    protocol over loopback — device execution parallelizes past the
    in-process `_BACKEND_LOCK`, and workers boot warm by prewarming
    the shared AOT artifact dir;
  * `router` — the replica-set front door: health-probed circuit
    breakers, hedged retries made safe by idempotency keys, per-tenant
    token-bucket quotas, a brownout ladder, and replace-and-replay of
    requests stranded on a dead replica;
  * `api` — submit/poll/result handles + synchronous solve() over a
    process-global router (serve_replicas=1 by default);
  * `request` — jax-free request/result envelope types.

Importing this package (or `serve.api`) never imports jax; the service
machinery loads on first use.  `router`/`replica` are jax-free too —
only a replica's SolverService pulls in the backend.
"""

from .api import (RequestHandle, RouterHandle, get_service,  # noqa: F401
                  poll, result, shutdown_service, solve,
                  start_service, submit)

__all__ = [
    "RequestHandle", "RouterHandle", "SolverService", "CompileCache",
    "bucket_key", "Router", "Replica", "ReplicaSet", "ProcReplica",
    "ProcReplicaSet", "CircuitBreaker", "TokenBucket", "get_service",
    "poll", "result", "shutdown_service", "solve", "start_service",
    "submit",
]


def __getattr__(name):
    # lazy heavyweights: SolverService/CompileCache pull in the full
    # optimizer stack (and jax) — resolved only when actually used.
    # Router/Replica are themselves jax-free but construct services,
    # so they stay lazy for symmetry.
    if name == "SolverService":
        from .service import SolverService
        return SolverService
    if name in ("CompileCache", "bucket_key"):
        from . import compile_cache as _cc
        return getattr(_cc, name)
    if name in ("Router", "CircuitBreaker", "TokenBucket"):
        from . import router as _router
        return getattr(_router, name)
    if name in ("Replica", "ReplicaSet"):
        from . import replica as _replica
        return getattr(_replica, name)
    if name in ("ProcReplica", "ProcReplicaSet"):
        from . import procpool as _procpool
        return getattr(_procpool, name)
    raise AttributeError(name)

"""Solver-as-a-service: a persistent in-process solver layer.

Modules (doc/src/serve.md is the operator-facing chapter):

  * `compile_cache` — shape-bucketed compile cache: one executable per
    (model, scenario count, stage dims, dtype, backend, solver config)
    bucket, deduplicated through the thread-scoped jit registries
    (phbase.fused_superstep / ops.pdhg.shared_solve_jit), plus AOT
    `jit(vmap(superstep)).lower().compile()` executables for coalesced
    batches;
  * `service` — SolverService: bounded queue, admission control,
    deadline handling (structured timeout results, never a hang), a
    dispatch loop that coalesces same-bucket requests into one
    vmap-batched execution, and SpokeSupervisor-style worker
    supervision (chaos-injectable, capped-backoff restarts);
  * `api` — submit/poll/result handles + synchronous solve() over a
    process-global service;
  * `request` — jax-free request/result envelope types.

Importing this package (or `serve.api`) never imports jax; the service
machinery loads on first use.
"""

from .api import (RequestHandle, get_service, poll, result,  # noqa: F401
                  shutdown_service, solve, start_service, submit)

__all__ = [
    "RequestHandle", "SolverService", "CompileCache", "bucket_key",
    "get_service", "poll", "result", "shutdown_service", "solve",
    "start_service", "submit",
]


def __getattr__(name):
    # lazy heavyweights: SolverService/CompileCache pull in the full
    # optimizer stack (and jax) — resolved only when actually used
    if name == "SolverService":
        from .service import SolverService
        return SolverService
    if name in ("CompileCache", "bucket_key"):
        from . import compile_cache as _cc
        return getattr(_cc, name)
    raise AttributeError(name)

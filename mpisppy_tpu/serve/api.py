"""Client API for the serve layer: submit / poll / result handles plus
a synchronous solve() wrapper over a process-global SolverService.

IMPORT CONTRACT: importing this module touches neither jax nor the
service machinery — clients embed it for free (AST-guarded in
tests/test_serve.py, the telemetry-guard pattern).  The heavy imports
happen inside `start_service` on first use.

    from mpisppy_tpu.serve import api

    h = api.submit(batch, {"defaultPHrho": 1.0})  # returns instantly
    api.poll(h)                                    # "queued"/"running"/...
    res = api.result(h, timeout=60)                # structured, never hangs

    res = api.solve(batch, opts)                   # submit+result in one
    # res["conv"], res["eobj"], res["trivial_bound"]: the same values
    # PH.ph_main returns (bitwise identical at batch=1)
"""

from __future__ import annotations

import threading

from .request import RequestHandle  # noqa: F401  (re-export, jax-free)

_service = None
_lock = threading.Lock()


def start_service(options=None):
    """Start (or return) the process-global SolverService.  `options`
    only applies when the service is first created."""
    global _service
    with _lock:
        if _service is None:
            from .service import SolverService
            _service = SolverService(options)
    return _service.start()


def get_service():
    """The process-global service, or None if never started."""
    return _service


def submit(batch, options=None, **kwargs):
    """Enqueue a solve on the global service; returns a RequestHandle."""
    return start_service().submit(batch, options, **kwargs)


def poll(handle):
    s = _service
    if s is None:
        return "unknown"
    return s.poll(handle)


def result(handle, timeout=None):
    s = _service
    if s is None:
        return {"status": "unknown", "request_id": handle.id}
    return s.result(handle, timeout=timeout)


def solve(batch, options=None, **kwargs):
    """Synchronous convenience wrapper: the result dict carries the
    same solution values as `PH.ph_main` (see PH.solution_dict)."""
    return start_service().solve(batch, options, **kwargs)


def shutdown_service(timeout=60.0):
    """Drain and stop the global service (a later call starts a fresh
    one)."""
    global _service
    with _lock:
        s, _service = _service, None
    if s is not None:
        s.shutdown(timeout)

"""Client API for the serve layer: submit / poll / result handles plus
a synchronous solve() wrapper over a process-global front door.

The front door is a `Router` (serve/router.py) over a replica set —
circuit breakers, hedged retries, tenant quotas, and brownout
degradation all live behind these same five calls.  By default the
router runs ONE replica (`serve_replicas=1`), which behaves exactly
like the old direct-SolverService wiring; pass `serve_replicas >= 2`
in options to get real fault isolation.  Replicas are in-process
threads by default; `serve_replica_mode="process"` backs each slot
with its own OS process (serve/procpool.py) so device execution
parallelizes past the in-process `_BACKEND_LOCK` — same five calls,
same results (batch=1 stays bitwise-equal to `PH.ph_main`).

IMPORT CONTRACT: importing this module touches neither jax nor the
service machinery — clients embed it for free (AST-guarded in
tests/test_serve.py, the telemetry-guard pattern).  The heavy imports
happen inside `start_service` on first use.

    from mpisppy_tpu.serve import api

    h = api.submit(batch, {"defaultPHrho": 1.0})  # returns instantly
    api.poll(h)                                    # "queued"/"running"/...
    res = api.result(h, timeout=60)                # structured, never hangs

    res = api.solve(batch, opts)                   # submit+result in one
    # res["conv"], res["eobj"], res["trivial_bound"]: the same values
    # PH.ph_main returns (bitwise identical at batch=1)
"""

from __future__ import annotations

import threading

from .request import (RequestHandle,  # noqa: F401  (re-export, jax-free)
                      RouterHandle)   # noqa: F401

_router = None
_lock = threading.Lock()


def start_service(options=None):
    """Start (or return) the process-global Router.  `options` only
    applies when the router is first created; `serve_replicas`
    defaults to 1 here (the single-replica router is behaviourally the
    old direct service, plus admission/deadline uniformity)."""
    global _router
    with _lock:
        if _router is None:
            from .router import Router
            o = dict(options or {})
            o.setdefault("serve_replicas", 1)
            _router = Router(o)
    return _router.start()


def get_service():
    """The process-global router, or None if never started."""
    return _router


def submit(batch, options=None, **kwargs):
    """Enqueue a solve on the global router; returns a RouterHandle."""
    return start_service().submit(batch, options, **kwargs)


def poll(handle):
    r = _router
    if r is None:
        return "unknown"
    return r.poll(handle)


def result(handle, timeout=None):
    r = _router
    if r is None:
        return {"status": "unknown", "request_id": handle.id}
    return r.result(handle, timeout=timeout)


def solve(batch, options=None, **kwargs):
    """Synchronous convenience wrapper: the result dict carries the
    same solution values as `PH.ph_main` (see PH.solution_dict)."""
    return start_service().solve(batch, options, **kwargs)


def shutdown_service(timeout=60.0):
    """Drain and stop the global router (a later call starts a fresh
    one)."""
    global _router
    with _lock:
        r, _router = _router, None
    if r is not None:
        r.shutdown(timeout)


def start_gateway(options=None, host="127.0.0.1", port=0):
    """Start a network `Gateway` (serve/net/) over the process-global
    router — the socket front door to the same five calls.  The
    gateway does NOT own the router: `shutdown_service()` still owns
    its lifecycle, and a gateway shutdown only closes the socket edge.
    Returns the started Gateway; read `.address` for the bound
    (host, port)."""
    from .net.gateway import Gateway
    return Gateway(options, router=start_service(options),
                   host=host, port=port).start()

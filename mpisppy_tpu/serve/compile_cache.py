"""Shape-bucketed compile cache for the serve layer.

Requests are assigned to a BUCKET — the tuple of everything that
determines the lowered PH superstep computation: model identity
(name + static var/nonant names), scenario count, stage dims, constraint
matrix kind, dtype, backend, and the solver config.  Two requests in
the same bucket differ only in ARRAY VALUES (scenario data, rho,
bounds, tolerance), which are all traced arguments of
`phbase.ph_superstep` — so one compiled executable serves both, and a
group of them can run as one vmap-batched execution.

Per bucket this module holds:
  * the canonical `PDHGSolver` (built once via `from_options`, so its
    shared solve jit — ops.pdhg.shared_solve_jit — is warm for every
    PH constructed for requests in the bucket);
  * `superstep` — the thread-shared jitted superstep
    (`phbase.fused_superstep`): the identical lowered computation a
    standalone `PH.ph_main` runs (same pure function, same solver
    config, same shapes), which makes the serve batch=1 result
    bitwise-identical to a standalone run;
  * per-batch-width AOT executables (`jax.jit(jax.vmap(...)).lower()
    .compile()`) for the coalesced B>1 path.

The cache also counts `serve.compile_cache.{hit,miss}` per REQUEST
(telemetry counters when enabled, plain ints always) — the acceptance
signal "N concurrent same-shape requests, one compilation".  Wire-up
to jax's PERSISTENT compilation cache (warm process restarts skip XLA)
is `utils.platform.enable_compile_cache`, called from
`SolverService.start`.
"""

from __future__ import annotations

import threading

from .. import telemetry as _telemetry


def width_bucket(n, floor=1):
    """Next power-of-two >= max(n, floor): THE width-bucketing rule.

    Used by the serve layer's batch coalescing and by
    `ops.pdhg.PDHGSolver.solve_compacted` when it gathers unconverged
    survivors into a smaller slab — quantizing widths to powers of two
    bounds the number of distinct compiled executables at log2(S) per
    bucket instead of one per observed width."""
    n = max(int(n), int(floor), 1)
    return 1 << (n - 1).bit_length()


def solver_config(options):
    """The bucket's solver-config component: the same hashable key the
    process-wide jit registries use (PDHGSolver.config_key of the
    solver `from_options` would build)."""
    from ..ops.pdhg import PDHGSolver
    return PDHGSolver.from_options(options).config_key()


def bucket_key(batch, options=None, model=None, backend=None):
    """Shape-bucket key for one request.

    `model` defaults to the batch's static var/nonant names — a
    structural fingerprint that separates models which happen to share
    shapes; pass an explicit model name to pin it symbolically."""
    import jax

    if backend is None:
        backend = jax.default_backend()
    ident = model if model is not None else (
        batch.var_names, batch.tree.nonant_names)
    akind = ("split" if batch.split_A
             else "shared" if batch.shared_A else "dense")
    return (
        ident,
        int(batch.num_scens),
        int(batch.num_vars),
        int(batch.num_rows),
        int(batch.num_nonants),
        int(batch.tree.num_nodes),
        akind,
        str(batch.c.dtype),
        str(backend),
        solver_config(options),
        # prep STRUCTURE flag: split-vs-dense prepared matrices change
        # the argument treedef, so they cannot share an executable
        bool((options or {}).get("no_split_prep", False)),
    )


class CompiledBucket:
    """One bucket's executables (see module docstring).  Built lazily
    by the service's single dispatch thread, so `fused_superstep`'s
    thread-local registry resolves to that thread's wrapper; the
    bucket object itself is only ever driven from the dispatch
    thread (sequentially across worker restarts)."""

    def __init__(self, key, options):
        from ..ops.pdhg import PDHGSolver
        from ..phbase import fused_superstep
        self.key = key
        self.solver = PDHGSolver.from_options(options)
        self.superstep = fused_superstep(self.solver)
        self._batched = {}            # B -> AOT-compiled executable
        self._lock = threading.Lock()
        self.aot_compiles = 0

    def batched_superstep(self, example_args):
        """AOT executable of `vmap(ph_superstep)` over a leading
        request axis, lowered+compiled once per batch width B from the
        stacked `example_args` (the superstep's 9 positional args, each
        leaf with a leading B axis)."""
        import functools

        import jax

        from ..phbase import ph_superstep

        B = int(example_args[1].shape[0])     # rho: (B, S, K)
        with self._lock:
            exe = self._batched.get(B)
        if exe is not None:
            return exe
        fn = jax.jit(jax.vmap(functools.partial(ph_superstep, self.solver)))
        exe = fn.lower(*example_args).compile()
        with self._lock:
            if B not in self._batched:
                self._batched[B] = exe
                self.aot_compiles += 1
        return self._batched[B]


class CompileCache:
    """Bucket table + per-request hit/miss accounting."""

    def __init__(self, tel=None):
        self._tel = tel if tel is not None else _telemetry.get()
        self._buckets = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get(self, batch, options=None, model=None):
        """The CompiledBucket for one request (building it on first
        sight of the bucket).  Counts one hit or miss per call — call
        it once per request, not once per dispatch group."""
        key = bucket_key(batch, options, model=model)
        with self._lock:
            entry = self._buckets.get(key)
            if entry is None:
                entry = CompiledBucket(key, options)
                self._buckets[key] = entry
                self.misses += 1
                self._tel.counter("serve.compile_cache.miss").inc()
            else:
                self.hits += 1
                self._tel.counter("serve.compile_cache.hit").inc()
        return entry

    def stats(self):
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "buckets": len(self._buckets)}


def merged_stats(caches):
    """Aggregate `CompileCache.stats()` across a replica set (each
    replica owns its own cache handle, so per-replica stats only tell
    half the story).  `buckets` sums the PER-CACHE bucket counts: the
    same logical shape bucket compiled in two replicas IS two
    compilations — the fault-isolation price the replica split pays,
    and the signal this aggregate exists to expose."""
    out = {"hits": 0, "misses": 0, "buckets": 0, "caches": 0}
    for c in caches:
        s = c.stats()
        out["hits"] += s["hits"]
        out["misses"] += s["misses"]
        out["buckets"] += s["buckets"]
        out["caches"] += 1
    return out

"""Shape-bucketed compile cache for the serve layer.

Requests are assigned to a BUCKET — the tuple of everything that
determines the lowered PH superstep computation: model identity
(name + static var/nonant names), scenario count, stage dims, constraint
matrix kind, dtype, backend, and the solver config.  Two requests in
the same bucket differ only in ARRAY VALUES (scenario data, rho,
bounds, tolerance), which are all traced arguments of
`phbase.ph_superstep` — so one compiled executable serves both, and a
group of them can run as one vmap-batched execution.

Per bucket this module holds:
  * the canonical `PDHGSolver` (built once via `from_options`, so its
    shared solve jit — ops.pdhg.shared_solve_jit — is warm for every
    PH constructed for requests in the bucket);
  * `superstep` — the thread-shared jitted superstep
    (`phbase.fused_superstep`): the identical lowered computation a
    standalone `PH.ph_main` runs (same pure function, same solver
    config, same shapes), which makes the serve batch=1 result
    bitwise-identical to a standalone run;
  * per-batch-width AOT executables of `vmap(ph_superstep)` for the
    coalesced B>1 path, built through `jax.export` (see below).

AOT persistence to disk
-----------------------
When `MPISPPY_TPU_COMPILE_CACHE_DIR` is set, each batched executable
is additionally serialized via `jax.export.export(...).serialize()`
into `$MPISPPY_TPU_COMPILE_CACHE_DIR/aot/<fingerprint>.mtaot`, under a
fingerprint covering the full `bucket_key` PLUS batch width, jax and
jaxlib versions, backend, argument treedef, and the x64 flag — the
things that can silently change the traced program between processes.
A fresh replica (`warm_from` on a new incarnation, a rolling restart,
a cold process) deserializes the artifact instead of re-tracing: the
Python-level trace of `vmap(ph_superstep)` — the dominant cold-start
cost — is skipped entirely.  Validation mirrors the MTSHARD1 shard
discipline (streaming/store.py): magic + header JSON + payload CRC32
checked on every load, and ANY mismatch — torn file, foreign
fingerprint, version skew — falls back silently to tracing, counted in
`cache.aot_load_failures`.  Loads that succeed count
`cache.aot_loads`, saves `cache.aot_saves` (telemetry counters when
enabled, plain ints always — `telemetry.gateway_counters()`).

Both the trace and the warm path execute `jax.jit(exported.call)` over
the SAME exported artifact shape (flat array leaves in, flat leaves
out), so a warm-started replica's batched results are identical to a
freshly-traced one's — the fallback is behaviorally invisible.

The cache also counts `serve.compile_cache.{hit,miss}` per REQUEST —
the acceptance signal "N concurrent same-shape requests, one
compilation".  Wire-up to jax's own persistent XLA cache is
`utils.platform.enable_compile_cache`, called from
`SolverService.start`; the jax.export layer above it persists the
*traced program*, which jax's cache does not.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import threading
import time
import zlib

from .. import global_toc
from .. import telemetry as _telemetry

AOT_MAGIC = b"MTAOTX1\0"
AOT_FORMAT = 1
_AOT_SUFFIX = ".mtaot"


def width_bucket(n, floor=1):
    """Next power-of-two >= max(n, floor): THE width-bucketing rule.

    Used by the serve layer's batch coalescing and by
    `ops.pdhg.PDHGSolver.solve_compacted` when it gathers unconverged
    survivors into a smaller slab — quantizing widths to powers of two
    bounds the number of distinct compiled executables at log2(S) per
    bucket instead of one per observed width."""
    n = max(int(n), int(floor), 1)
    return 1 << (n - 1).bit_length()


def solver_config(options):
    """The bucket's solver-config component: the same hashable key the
    process-wide jit registries use (PDHGSolver.config_key of the
    solver `from_options` would build)."""
    from ..ops.pdhg import PDHGSolver
    return PDHGSolver.from_options(options).config_key()


def bucket_key(batch, options=None, model=None, backend=None):
    """Shape-bucket key for one request.

    `model` defaults to the batch's static var/nonant names — a
    structural fingerprint that separates models which happen to share
    shapes; pass an explicit model name to pin it symbolically."""
    import jax

    if backend is None:
        backend = jax.default_backend()
    ident = model if model is not None else (
        batch.var_names, batch.tree.nonant_names)
    akind = ("split" if batch.split_A
             else "shared" if batch.shared_A else "dense")
    return (
        ident,
        int(batch.num_scens),
        int(batch.num_vars),
        int(batch.num_rows),
        int(batch.num_nonants),
        int(batch.tree.num_nodes),
        akind,
        str(batch.c.dtype),
        str(backend),
        solver_config(options),
        # prep STRUCTURE flag: split-vs-dense prepared matrices change
        # the argument treedef, so they cannot share an executable
        bool((options or {}).get("no_split_prep", False)),
    )


# -- AOT disk layer --------------------------------------------------------

def aot_cache_dir():
    """The on-disk AOT executable directory, or None when persistence
    is off (`MPISPPY_TPU_COMPILE_CACHE_DIR` unset/empty)."""
    root = os.environ.get("MPISPPY_TPU_COMPILE_CACHE_DIR")
    if not root:
        return None
    return os.path.join(root, "aot")


def aot_fingerprint(key, B, treedef_repr):
    """The cache key of one persisted executable: sha256 over the full
    bucket key + batch width + jax/jaxlib versions + backend + argument
    treedef + x64 flag.  Anything that can change the traced program
    between processes is in here — a mismatch means "trace, don't
    load"."""
    import jax
    try:
        import jaxlib.version
        jaxlib_v = jaxlib.version.__version__
    except Exception:                  # pragma: no cover - old layouts
        jaxlib_v = "unknown"
    ident = (repr(key), int(B), jax.__version__, jaxlib_v,
             str(jax.default_backend()), str(treedef_repr),
             bool(jax.config.jax_enable_x64))
    return hashlib.sha256(repr(ident).encode("utf-8")).hexdigest()


def _aot_encode(fingerprint, B, payload):
    """One persisted executable's byte image: magic + header JSON +
    serialized jax.export payload, CRC-stamped like an MTSHARD1
    shard."""
    import jax
    header = {
        "aot_format": AOT_FORMAT,
        "fingerprint": fingerprint,
        "batch_width": int(B),
        "jax_version": jax.__version__,
        "backend": str(jax.default_backend()),
        "payload_len": len(payload),
        "payload_crc32": zlib.crc32(payload) & 0xFFFFFFFF,
    }
    hjson = json.dumps(header, sort_keys=True).encode("utf-8")
    return AOT_MAGIC + struct.pack("<I", len(hjson)) + hjson + payload


def _aot_decode(data, fingerprint):
    """Validate + strip one persisted executable; raises ValueError on
    ANY mismatch (torn, foreign, corrupt, fingerprint/format skew)."""
    if len(data) < len(AOT_MAGIC) + 4:
        raise ValueError("truncated AOT file")
    if data[:len(AOT_MAGIC)] != AOT_MAGIC:
        raise ValueError("bad AOT magic")
    (hlen,) = struct.unpack(
        "<I", data[len(AOT_MAGIC):len(AOT_MAGIC) + 4])
    hstart = len(AOT_MAGIC) + 4
    if hstart + hlen > len(data):
        raise ValueError("truncated AOT header")
    header = json.loads(data[hstart:hstart + hlen].decode("utf-8"))
    if int(header.get("aot_format", -1)) != AOT_FORMAT:
        raise ValueError(f"AOT format {header.get('aot_format')!r}")
    if header.get("fingerprint") != fingerprint:
        raise ValueError("AOT fingerprint mismatch")
    payload = data[hstart + hlen:]
    if len(payload) != int(header.get("payload_len", -1)):
        raise ValueError("AOT payload length mismatch")
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    if crc != int(header.get("payload_crc32", -1)):
        raise ValueError("AOT payload CRC mismatch")
    return payload


# -- boot-time prewarm + artifact lifecycle --------------------------------
#
# A process replica boots, calls `prewarm()`, and every artifact in the
# shared aot/ dir is deserialized ONCE into this fingerprint-keyed
# resident set; `_aot_load` consults it before touching the disk, so
# the first request of every previously-seen (bucket, width) runs warm
# without a per-request open+deserialize.  The registry is process-
# global on purpose — the artifacts are keyed by full fingerprint, so a
# stale entry can never satisfy a lookup it shouldn't.

_PREWARM_LOCK = threading.Lock()
_PREWARMED = {}                        # fingerprint -> jax.export.Exported


def prewarm(directory=None):
    """Load the full AOT artifact set into the resident prewarm
    registry.  `directory` defaults to `aot_cache_dir()` (None → no-op,
    returns 0).  Undecodable/foreign files are skipped and counted in
    `cache.aot_load_failures`.  Returns the number of artifacts
    resident after the sweep."""
    from jax import export as jax_export
    d = directory if directory is not None else aot_cache_dir()
    if not d or not os.path.isdir(d):
        return 0
    tel = _telemetry.get()
    for fname in sorted(os.listdir(d)):
        if not fname.endswith(_AOT_SUFFIX):
            continue
        fp = fname[:-len(_AOT_SUFFIX)]
        with _PREWARM_LOCK:
            if fp in _PREWARMED:
                continue
        try:
            with open(os.path.join(d, fname), "rb") as f:
                payload = _aot_decode(f.read(), fp)
            exported = jax_export.deserialize(payload)
        except Exception as exc:
            tel.counter("cache.aot_load_failures").inc()
            global_toc(f"WARNING: prewarm rejected {fname}: {exc}")
            continue
        with _PREWARM_LOCK:
            _PREWARMED[fp] = exported
    with _PREWARM_LOCK:
        return len(_PREWARMED)


def clear_prewarmed():
    """Drop the resident prewarm registry (tests)."""
    with _PREWARM_LOCK:
        _PREWARMED.clear()


def prune_aot_dir(max_age_s=None, max_total_bytes=None, directory=None):
    """Bound the on-disk aot/ artifact set: evict entries older than
    `max_age_s` (by mtime), then oldest-first until the directory is
    under `max_total_bytes`.  Both limits None → no-op.  Evictions
    count in `cache.aot_evictions`; returns the number removed.
    Concurrent writers are fine — a racing delete is just skipped."""
    d = directory if directory is not None else aot_cache_dir()
    if not d or not os.path.isdir(d):
        return 0
    if max_age_s is None and max_total_bytes is None:
        return 0
    entries = []
    for fname in os.listdir(d):
        if not fname.endswith(_AOT_SUFFIX):
            continue
        path = os.path.join(d, fname)
        try:
            st = os.stat(path)
        except OSError:
            continue
        entries.append((st.st_mtime, st.st_size, path))
    entries.sort()                      # oldest first
    now = time.time()
    doomed = []
    if max_age_s is not None:
        cutoff = now - float(max_age_s)
        doomed = [e for e in entries if e[0] < cutoff]
        entries = [e for e in entries if e[0] >= cutoff]
    if max_total_bytes is not None:
        total = sum(e[1] for e in entries)
        while entries and total > int(max_total_bytes):
            e = entries.pop(0)
            doomed.append(e)
            total -= e[1]
    tel = _telemetry.get()
    removed = 0
    for _, _, path in doomed:
        try:
            os.remove(path)
        except OSError:
            continue
        removed += 1
        tel.counter("cache.aot_evictions").inc()
    if removed:
        global_toc(f"AOT cache pruned: {removed} artifact(s) evicted")
    return removed


class _BatchedRunner:
    """One batch width's executable: flat leaves through the exported
    artifact, pytree structure restored at the edges.  Callable exactly
    like the jitted vmap it replaces (same 9 positional superstep args,
    same PHState out) — `service._run_batched` can't tell warm from
    traced, which is the point."""

    def __init__(self, call, out_treedef):
        self._call = call
        self._out_treedef = out_treedef

    def __call__(self, *args):
        import jax
        leaves = jax.tree_util.tree_leaves(args)
        out = self._call(*leaves)
        return jax.tree_util.tree_unflatten(self._out_treedef,
                                            list(out))


class CompiledBucket:
    """One bucket's executables (see module docstring).  Built lazily
    by the service's single dispatch thread, so `fused_superstep`'s
    thread-local registry resolves to that thread's wrapper; the
    bucket object itself is only ever driven from the dispatch
    thread (sequentially across worker restarts)."""

    def __init__(self, key, options, owner=None):
        from ..ops.pdhg import PDHGSolver
        from ..phbase import fused_superstep
        self.key = key
        self.solver = PDHGSolver.from_options(options)
        self.superstep = fused_superstep(self.solver)
        self._batched = {}            # B -> _BatchedRunner
        self._lock = threading.Lock()
        self._owner = owner
        self.aot_compiles = 0

    def _aot_account(self, what):
        tel = self._owner._tel if self._owner is not None \
            else _telemetry.get()
        tel.counter(f"cache.{what}").inc()
        if self._owner is not None:
            with self._owner._lock:
                setattr(self._owner, what,
                        getattr(self._owner, what) + 1)

    def _aot_load(self, path, fingerprint):
        """Deserialize a persisted executable, or None (counted) when
        the file is absent, torn, corrupt, or fingerprint-skewed —
        the silent-fallback half of the AOT contract.  A boot-time
        `prewarm()` hit short-circuits the disk entirely."""
        from jax import export as jax_export
        with _PREWARM_LOCK:
            exported = _PREWARMED.get(fingerprint)
        if exported is not None:
            self._aot_account("aot_prewarm_hits")
            self._aot_account("aot_loads")
            return exported
        if not os.path.exists(path):
            return None
        try:
            with open(path, "rb") as f:
                payload = _aot_decode(f.read(), fingerprint)
            exported = jax_export.deserialize(payload)
        except Exception as exc:
            self._aot_account("aot_load_failures")
            global_toc("WARNING: AOT cache entry rejected "
                       f"({os.path.basename(path)}): {exc}")
            return None
        self._aot_account("aot_loads")
        return exported

    def _aot_save(self, path, fingerprint, B, exported):
        from ..resilience.checkpoint import atomic_write
        try:
            data = _aot_encode(fingerprint, B, exported.serialize())
            os.makedirs(os.path.dirname(path), exist_ok=True)
            atomic_write(path, data)
        except Exception as exc:       # pragma: no cover - disk full &c
            global_toc(f"WARNING: AOT cache write failed: {exc}")
            return
        self._aot_account("aot_saves")

    def batched_superstep(self, example_args):
        """The executable of `vmap(ph_superstep)` over a leading
        request axis for batch width B (from the stacked
        `example_args`: the superstep's 9 positional args, each leaf
        with a leading B axis) — deserialized from the AOT disk cache
        when a matching artifact exists, traced (once per width) and
        persisted otherwise."""
        B = int(example_args[1].shape[0])     # rho: (B, S, K)
        with self._lock:
            runner = self._batched.get(B)
        if runner is not None:
            return runner
        runner = self._build_runner(B, example_args)
        with self._lock:
            if B not in self._batched:
                self._batched[B] = runner
                self.aot_compiles += 1
        return self._batched[B]

    def _build_runner(self, B, example_args):
        import functools

        import jax
        from jax import export as jax_export

        from ..phbase import ph_superstep

        tu = jax.tree_util
        args = tuple(example_args)
        leaves, in_treedef = tu.tree_flatten(args)
        # superstep out = a PHState shaped like the (stacked) state in
        out_treedef = tu.tree_structure(args[0])
        path = None
        d = aot_cache_dir()
        fp = aot_fingerprint(self.key, B, repr(in_treedef))
        if d is not None:
            path = os.path.join(d, fp + _AOT_SUFFIX)
            exported = self._aot_load(path, fp)
            if exported is not None:
                return _BatchedRunner(jax.jit(exported.call),
                                      out_treedef)

        # trace path: export the flat-leaf wrapper (custom pytrees like
        # PHState/ScenarioBatch don't cross jax.export's serialization
        # boundary — positional array leaves do), then run THROUGH the
        # exported artifact so warm and traced replicas execute the
        # same program shape
        def flat_fn(*flat):
            a = tu.tree_unflatten(in_treedef, list(flat))
            out = jax.vmap(
                functools.partial(ph_superstep, self.solver))(*a)
            return tuple(tu.tree_leaves(out))

        try:
            exported = jax_export.export(jax.jit(flat_fn))(*leaves)
        except Exception as exc:
            # un-exportable program: plain AOT lower+compile, no disk
            # persistence for this bucket (counted so it's visible)
            self._aot_account("aot_export_failures")
            global_toc(f"WARNING: jax.export failed for bucket "
                       f"(B={B}): {exc!r}; falling back to "
                       "lower().compile() without persistence")
            fn = jax.jit(jax.vmap(
                functools.partial(ph_superstep, self.solver)))
            return fn.lower(*args).compile()
        if path is not None:
            self._aot_save(path, fp, B, exported)
        return _BatchedRunner(jax.jit(exported.call), out_treedef)


class CompileCache:
    """Bucket table + per-request hit/miss accounting + the AOT disk
    layer's load/save/failure counts."""

    def __init__(self, tel=None):
        self._tel = tel if tel is not None else _telemetry.get()
        self._buckets = {}
        self._lock = threading.RLock()
        self.hits = 0
        self.misses = 0
        self.aot_loads = 0
        self.aot_load_failures = 0
        self.aot_saves = 0
        self.aot_export_failures = 0
        self.aot_prewarm_hits = 0

    def get(self, batch, options=None, model=None):
        """The CompiledBucket for one request (building it on first
        sight of the bucket).  Counts one hit or miss per call — call
        it once per request, not once per dispatch group."""
        key = bucket_key(batch, options, model=model)
        with self._lock:
            entry = self._buckets.get(key)
            if entry is None:
                entry = CompiledBucket(key, options, owner=self)
                self._buckets[key] = entry
                self.misses += 1
                self._tel.counter("serve.compile_cache.miss").inc()
            else:
                self.hits += 1
                self._tel.counter("serve.compile_cache.hit").inc()
        return entry

    def stats(self):
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "buckets": len(self._buckets),
                    "aot_loads": self.aot_loads,
                    "aot_load_failures": self.aot_load_failures,
                    "aot_saves": self.aot_saves,
                    "aot_export_failures": self.aot_export_failures,
                    "aot_prewarm_hits": self.aot_prewarm_hits}


_MERGE_KEYS = ("hits", "misses", "buckets", "aot_loads",
               "aot_load_failures", "aot_saves",
               "aot_export_failures", "aot_prewarm_hits")


def merged_stats_dicts(stat_dicts):
    """Aggregate already-materialized `CompileCache.stats()` dicts —
    the form process replicas report over the wire (the cache object
    lives in the worker process; only its stats cross the socket)."""
    out = {k: 0 for k in _MERGE_KEYS}
    out["caches"] = 0
    for s in stat_dicts:
        if not s:
            continue
        for k in _MERGE_KEYS:
            out[k] += int(s.get(k, 0))
        out["caches"] += 1
    return out


def merged_stats(caches):
    """Aggregate `CompileCache.stats()` across a replica set (each
    replica owns its own cache handle, so per-replica stats only tell
    half the story).  `buckets` sums the PER-CACHE bucket counts: the
    same logical shape bucket compiled in two replicas IS two
    compilations — the fault-isolation price the replica split pays
    (which the AOT disk layer now refunds: the second replica LOADS
    what the first traced), and the signal this aggregate exists to
    expose."""
    return merged_stats_dicts(c.stats() for c in caches)

"""serve/net — the network front door over the replica-set serve core.

Three modules, all jax-free at module level (the gateway process never
touches a backend; device execution stays behind the Router):

  * `protocol` — versioned length-prefixed wire frames (JSON header +
    npz payload, CRC32-checked) with the submit/poll/result/solve/
    health/drain/roll verbs and the one-namespace error-code matrix;
  * `gateway`  — threaded stdlib-socket server: bearer-token -> tenant
    auth, forwards into serve/router.py (quotas, brownout, hedging,
    idempotency come free), `drain()` and zero-downtime `roll()`;
  * `client`   — blocking client with connect/request timeouts and
    capped-jitter reconnect on `resilience.restart_delay`.

See doc/src/serve.md, "The network edge".
"""

from . import protocol
from .client import Client, ClientError, NetHandle
from .gateway import Gateway

__all__ = ["protocol", "Client", "ClientError", "NetHandle", "Gateway"]

"""Blocking wire-protocol client for the serve gateway.

One `Client` wraps one TCP connection and speaks `protocol`'s framed
request/response exchange: `submit / poll / result / solve / health /
drain / roll`.  Failure handling is deliberately boring:

  * **connect timeout** and **request timeout** bound every socket
    operation (`socket.create_connection(timeout=)`, `settimeout`);
  * a torn connection (ConnectionError / OSError / mid-frame EOF)
    triggers **capped-jitter reconnect** built on the shared
    `resilience.restart_delay` pacing policy, then ONE resend of the
    in-flight request.  Every submit/solve carries an idempotency key
    (auto-generated uuid when the caller gave none), so a resend after
    a half-delivered request is deduplicated server-side — the wire
    half of the exactly-once contract;
  * a `result` wait stretches the socket timeout to the request's own
    timeout plus a grace, so slow solves aren't misread as dead peers.
    With `timeout=None` the server blocks up to ITS cap
    (`gateway_result_cap`, 600 s default), so the socket stretches to
    the client's `result_cap` mirror of that value — leaving it at
    `request_timeout` would misread every solve slower than 60 s as a
    transport failure and burn the reconnect budget on a healthy
    request.

Layering: jax-free, like the rest of `serve/net/` (AST +
fresh-interpreter guarded in tests/test_net_gateway.py).
"""

from __future__ import annotations

import itertools
import random
import socket
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass

from ... import telemetry as _telemetry
from ...resilience import restart_delay
from . import protocol as P


@dataclass(frozen=True)
class NetHandle:
    """A submitted request as seen from the client side: the router's
    handle id plus the idempotency key the client stamped on it (the
    key is what survives a reconnect; the id is what poll/result
    use)."""
    id: int
    idempotency_key: str


class ClientError(RuntimeError):
    """The server answered with ok=False: carries the wire error code
    (protocol.ERROR_CODES) as `.code`."""

    def __init__(self, code, message):
        super().__init__(f"[{code}] {message}")
        self.code = code


class Client:
    """Blocking gateway client (see module docstring)."""

    def __init__(self, host, port, token="", connect_timeout=5.0,
                 request_timeout=60.0, result_cap=600.0,
                 reconnect_backoff=0.05, reconnect_cap=2.0,
                 max_reconnects=8, jitter_seed=None,
                 max_payload=P.DEFAULT_MAX_PAYLOAD):
        self.host = host
        self.port = int(port)
        self.token = token
        self.connect_timeout = float(connect_timeout)
        self.request_timeout = float(request_timeout)
        # mirror of the server's gateway_result_cap: how long a
        # result/solve with timeout=None may legitimately block
        self.result_cap = float(result_cap)
        self.reconnect_backoff = float(reconnect_backoff)
        self.reconnect_cap = float(reconnect_cap)
        self.max_reconnects = int(max_reconnects)
        self.max_payload = int(max_payload)
        self._rng = random.Random(jitter_seed)
        self._sock = None
        self.reconnects = 0            # lifetime count (tests/bench)

    # -- connection management --------------------------------------------
    def _connect(self):
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.connect_timeout)
        sock.settimeout(self.request_timeout)
        self._sock = sock
        return sock

    def _ensure(self):
        return self._sock if self._sock is not None else self._connect()

    def _drop(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self):
        self._drop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- request core ------------------------------------------------------
    def _request(self, header, payload=b"", timeout=None):
        """One framed exchange, with reconnect-and-resend on transport
        failure.  Safe to resend because every mutating verb carries an
        idempotency key.  Returns (response_header, response_payload);
        raises ClientError on an ok=False response, ConnectionError
        when the reconnect budget is spent."""
        attempt = 0
        while True:
            try:
                sock = self._ensure()
                if timeout is not None:
                    sock.settimeout(float(timeout))
                try:
                    P.write_message(sock, header, payload)
                    resp, rpayload = P.read_message(
                        sock, max_payload=self.max_payload)
                finally:
                    if timeout is not None:
                        sock.settimeout(self.request_timeout)
                if resp is None:
                    raise P.ProtocolError("server closed the connection")
            except (ConnectionError, OSError, P.ProtocolError) as exc:
                self._drop()
                attempt += 1
                self.reconnects += 1
                if attempt > self.max_reconnects:
                    raise ConnectionError(
                        f"gateway unreachable after {attempt - 1} "
                        f"reconnect(s): {exc}") from exc
                # capped exponential backoff with full jitter: the
                # shared restart pacing policy scaled by U(0.5, 1)
                delay = restart_delay(attempt, self.reconnect_backoff,
                                      self.reconnect_cap)
                time.sleep(delay * (0.5 + 0.5 * self._rng.random()))
                continue
            if not resp.get("ok", False):
                raise ClientError(resp.get("error_code", P.E_INTERNAL),
                                  resp.get("error", ""))
            return resp, rpayload

    def _header(self, verb, **fields):
        hdr = {"kind": "request", "verb": verb, "token": self.token}
        hdr.update({k: v for k, v in fields.items() if v is not None})
        return hdr

    # -- verbs -------------------------------------------------------------
    def submit(self, batch, options=None, scenario_names=None,
               deadline=None, model=None, priority=None,
               idempotency_key=None):
        """Enqueue one solve; returns a NetHandle immediately.  An
        immediately-rejected request still gets a handle — `result`
        reports the structured rejection."""
        key = idempotency_key or f"net-{uuid.uuid4().hex}"
        hdr = self._header(
            "submit", options=options, scenario_names=scenario_names,
            deadline=deadline, model=model, priority=priority,
            idempotency_key=key)
        resp, _ = self._request(hdr, P.encode_batch(batch))
        return NetHandle(int(resp["result"]["handle"]), key)

    def poll(self, handle):
        resp, _ = self._request(self._header("poll", handle=handle.id))
        return resp["result"]["state"]

    def _wire_timeout(self, timeout):
        """Socket wait for a blocking result exchange: the request's
        own timeout + grace, or — with timeout=None, where the SERVER
        decides when to answer (up to gateway_result_cap) — the
        client's result_cap mirror + grace."""
        cap = self.result_cap if timeout is None else float(timeout)
        return cap + 10.0

    def result(self, handle, timeout=None):
        """Block for the structured result dict (arrays restored
        bit-exact from the npz payload).  The socket wait stretches to
        `timeout` + grace (or `result_cap` + grace when timeout is
        None) so a slow solve isn't misread as a dead peer."""
        wire_timeout = self._wire_timeout(timeout)
        resp, payload = self._request(
            self._header("result", handle=handle.id, timeout=timeout),
            timeout=wire_timeout)
        return P.decode_result(resp["result"], payload)

    def solve(self, batch, options=None, timeout=None, **kwargs):
        """submit + result in one exchange (one frame each way)."""
        key = kwargs.pop("idempotency_key", None) \
            or f"net-{uuid.uuid4().hex}"
        hdr = self._header("solve", options=options, timeout=timeout,
                           idempotency_key=key, **kwargs)
        resp, payload = self._request(hdr, P.encode_batch(batch),
                                      timeout=self._wire_timeout(timeout))
        return P.decode_result(resp["result"], payload)

    def health(self):
        resp, _ = self._request(self._header("health"))
        return resp["result"]

    def drain(self, deadline=5.0):
        resp, _ = self._request(
            self._header("drain", deadline=deadline),
            timeout=float(deadline) + 10.0)
        return resp["result"]

    def roll(self, timeout=120.0):
        """Ask the gateway for a zero-downtime rolling restart of the
        whole replica set; blocks until every slot has been replaced."""
        resp, _ = self._request(self._header("roll"), timeout=timeout)
        return resp["result"]["rolled"]


# -- pooled, pipelined client ----------------------------------------------

class _Pending:
    """One in-flight exchange on a pooled connection: the request (kept
    for resend-after-reconnect), the per-connection sequence number it
    was stamped with, and the slots its response (or transport error)
    lands in."""

    __slots__ = ("header", "payload", "seq", "event", "resp_header",
                 "resp_payload", "error")

    def __init__(self, header, payload):
        self.header = header
        self.payload = payload
        self.seq = None
        self.event = threading.Event()
        self.resp_header = None
        self.resp_payload = b""
        self.error = None


class _PooledConn:
    """One persistent socket carrying multiple in-flight requests.

    The server handles a connection's frames strictly in order
    (gateway._conn_main and procworker loop one frame at a time), so
    responses come back FIFO: a deque of pending exchanges matches them
    without ids.  Each request is additionally stamped with a
    per-connection `seq` that the server echoes — a cheap cross-check
    that the FIFO assumption holds; a mismatch kills the connection
    rather than mis-delivering a frame.

    Thread model: any caller thread may `send` (serialized by `_wlock`);
    ONE reader thread drains responses.  `fail()` is idempotent and
    callable from any of them — it marks the conn dead, errors out
    every pending exchange, and closes the socket (which also unblocks
    the reader)."""

    def __init__(self, host, port, connect_timeout, max_payload,
                 on_dead=None):
        self.sock = socket.create_connection(
            (host, port), timeout=connect_timeout)
        # the reader owns all receives and blocks indefinitely; request
        # timeouts are enforced by the caller's event wait, not the
        # socket, so a slow solve can't tear a shared connection down
        self.sock.settimeout(None)
        self.max_payload = int(max_payload)
        self._on_dead = on_dead
        self._wlock = threading.Lock()
        self._plock = threading.Lock()
        self._pending = deque()
        self._seq = itertools.count(1)
        self.alive = True
        self.last_used = time.monotonic()
        self._reader = threading.Thread(
            target=self._reader_main, name="net-pool-reader", daemon=True)
        self._reader.start()

    def inflight(self):
        with self._plock:
            return len(self._pending)

    def send(self, pending):
        """Stamp, register, and write one exchange.  Raises on a torn
        write (after failing the connection)."""
        err = None
        with self._wlock:
            if not self.alive:
                raise ConnectionError("connection already failed")
            hdr = dict(pending.header)
            pending.seq = hdr["seq"] = next(self._seq)
            with self._plock:
                self._pending.append(pending)
            self.last_used = time.monotonic()
            try:
                P.write_message(self.sock, hdr, pending.payload)
            except (ConnectionError, OSError, P.ProtocolError) as exc:
                err = exc
        if err is not None:
            self.fail(err)
            raise ConnectionError(f"write failed: {err}") from err

    def _reader_main(self):
        try:
            while True:
                resp, payload = P.read_message(
                    self.sock, max_payload=self.max_payload)
                if resp is None:
                    raise P.ProtocolError("server closed the connection")
                with self._plock:
                    if not self._pending:
                        raise P.ProtocolError("unsolicited response")
                    pending = self._pending.popleft()
                if resp.get("seq") not in (None, pending.seq):
                    raise P.ProtocolError(
                        f"response seq {resp.get('seq')} != "
                        f"expected {pending.seq}")
                pending.resp_header = resp
                pending.resp_payload = payload
                self.last_used = time.monotonic()
                pending.event.set()
        except (ConnectionError, OSError, P.ProtocolError) as exc:
            self.fail(exc)

    def fail(self, exc):
        """Tear down: error out every in-flight exchange exactly once."""
        with self._plock:
            if not self.alive:
                return
            self.alive = False
            doomed = list(self._pending)
            self._pending.clear()
        for p in doomed:
            p.error = exc
            p.event.set()
        try:
            self.sock.close()
        except OSError:
            pass
        if self._on_dead is not None:
            self._on_dead(self)

    def close(self):
        self.fail(ConnectionError("client closed"))


class PooledClient:
    """Pooled, pipelined wire-protocol client: up to `pool_size`
    persistent connections, each carrying multiple in-flight requests
    (the Router's per-replica transport — one submit need not wait for
    a neighbor's solve).  Same failure discipline as `Client`:
    transport errors trigger capped-jitter reconnect + resend (safe —
    every mutating verb carries an idempotency key upstream), counted
    in `reconnects`/`resends` and the `client.reconnects` /
    `client.resends` / `client.idle_reaped` telemetry counters.
    Connections idle past `idle_timeout` with nothing in flight are
    reaped at the next checkout."""

    def __init__(self, host, port, token="", pool_size=2,
                 connect_timeout=5.0, request_timeout=60.0,
                 max_retries=4, reconnect_backoff=0.05,
                 reconnect_cap=1.0, idle_timeout=30.0, jitter_seed=None,
                 max_payload=P.DEFAULT_MAX_PAYLOAD):
        self.host = host
        self.port = int(port)
        self.token = token
        self.pool_size = max(1, int(pool_size))
        self.connect_timeout = float(connect_timeout)
        self.request_timeout = float(request_timeout)
        self.max_retries = int(max_retries)
        self.reconnect_backoff = float(reconnect_backoff)
        self.reconnect_cap = float(reconnect_cap)
        self.idle_timeout = float(idle_timeout)
        self.max_payload = int(max_payload)
        self._rng = random.Random(jitter_seed)
        self._lock = threading.Lock()
        self._conns = []
        self._closed = False
        self.reconnects = 0
        self.resends = 0
        self.idle_reaped = 0

    # -- pool management ---------------------------------------------------
    def _on_dead(self, conn):
        with self._lock:
            if conn in self._conns:
                self._conns.remove(conn)

    def _checkout(self):
        """A live connection: reap idle ones, reuse the least-loaded,
        dial when the pool has room (or everything died)."""
        now = time.monotonic()
        with self._lock:
            if self._closed:
                raise ConnectionError("client closed")
            live = [c for c in self._conns if c.alive]
            reap = [c for c in live
                    if c.inflight() == 0
                    and now - c.last_used > self.idle_timeout]
            for c in reap:
                live.remove(c)
                self._conns.remove(c)
                self.idle_reaped += 1
                _telemetry.get().counter("client.idle_reaped").inc()
            self._conns = [c for c in self._conns if c.alive]
            if live and (len(live) >= self.pool_size
                         or min(c.inflight() for c in live) == 0):
                conn = min(live, key=lambda c: c.inflight())
            else:
                # dial INSIDE the lock: concurrent first callers must
                # pipeline onto the one connection being established,
                # not each dial their own past pool_size
                conn = _PooledConn(self.host, self.port,
                                   self.connect_timeout,
                                   self.max_payload,
                                   on_dead=self._on_dead)
                self._conns.append(conn)
        for c in reap:
            c.close()
        return conn

    def close(self):
        with self._lock:
            self._closed = True
            conns = list(self._conns)
            self._conns = []
        for c in conns:
            c.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- request core ------------------------------------------------------
    def call(self, verb, payload=b"", timeout=None, **fields):
        """One pipelined exchange: returns (response_header,
        response_payload).  Raises ClientError on ok=False,
        ConnectionError when the retry budget is spent."""
        header = {"kind": "request", "verb": verb, "token": self.token}
        header.update({k: v for k, v in fields.items() if v is not None})
        wait = float(timeout) if timeout is not None \
            else self.request_timeout
        attempt = 0
        while True:
            pending = _Pending(header, payload)
            try:
                conn = self._checkout()
                conn.send(pending)
            except (ConnectionError, OSError) as exc:
                pending.error = exc
            else:
                if not pending.event.wait(wait):
                    # the conn may be healthy but the server silent
                    # past the deadline: kill it (pipelined neighbors
                    # resend) rather than risk mismatched frames later
                    conn.fail(socket.timeout(
                        f"no response within {wait}s"))
            if pending.error is not None:
                attempt += 1
                self.reconnects += 1
                _telemetry.get().counter("client.reconnects").inc()
                if attempt > self.max_retries:
                    raise ConnectionError(
                        f"peer unreachable after {attempt - 1} "
                        f"retry(ies): {pending.error}") from pending.error
                self.resends += 1
                _telemetry.get().counter("client.resends").inc()
                delay = restart_delay(attempt, self.reconnect_backoff,
                                      self.reconnect_cap)
                time.sleep(delay * (0.5 + 0.5 * self._rng.random()))
                continue
            resp = pending.resp_header
            if not resp.get("ok", False):
                raise ClientError(resp.get("error_code", P.E_INTERNAL),
                                  resp.get("error", ""))
            return resp, pending.resp_payload

"""Gateway — the threaded socket server in front of the serve router.

Everything behind the socket already exists (PR 3/10/11): the gateway
is deliberately a THIN edge — it authenticates a bearer token to a
tenant id, decodes the frame, and forwards into `serve/router.py`'s
`Router`, whose per-tenant quotas, brownout ladder, hedged retries,
idempotency table, circuit breakers and replace-and-replay machinery
all come for free.  The gateway's own job is exactly four things:

  * **wire <-> structured translation** — protocol frames in, router
    calls out; structured rejects come back as wire error codes
    (protocol.ERROR_CODES, one namespace for both layers);
  * **authentication** — `gateway_tokens` maps bearer token -> tenant
    id; with no table configured the gateway runs OPEN and every
    caller is tenant "default" (tests, single-user dev loops).  Open
    mode is LOOPBACK-ONLY: binding a non-loopback host without a token
    table raises unless `gateway_open_non_loopback` is explicitly set.
    The fleet-lifecycle verbs (`drain`/`roll`) are additionally gated
    behind `gateway_admin_tokens` — a tenant bearer token must not be
    able to drain admission or restart the fleet out from under the
    other tenants;
  * **edge accounting** — `gateway.requests`, `gateway.rejects.<code>`,
    `gateway.bytes_in/out` counters and the
    `gateway.active_connections` gauge (telemetry.gateway_counters());
  * **fleet lifecycle** — `drain()` closes admission at the edge, and
    `roll()` performs a zero-downtime rolling restart: one replica at
    a time is condemned through the router's replace-and-replay path
    while its peers absorb traffic, in-flight requests surviving via
    the idempotency table (`Router.roll`).

Threading model: one accept loop thread plus one thread per client
connection, each handling that connection's frames sequentially (the
protocol is strictly request/response per connection; concurrency
comes from concurrent connections).  `result` waits are time-bounded
by the router's own clamps, so a connection thread can never hang
forever on a dead request.

Layering (AST + fresh-interpreter guarded in
tests/test_net_gateway.py): jax-free at module level, like router.py —
the gateway binds, accepts, and authenticates in a process that never
initializes a backend until a replica dispatches.
"""

from __future__ import annotations

import socket
import threading
import time

from ... import global_toc
from ... import telemetry as _telemetry
from ..request import REJECTED, RouterHandle
from . import protocol as P


class Gateway:
    """The network front door (see module docstring).

    Options (all prefixed `gateway_` unless noted):
      gateway_tokens        {bearer token: tenant id} (None = open)
      gateway_admin_tokens  bearer tokens allowed to drain/roll; when
                            unset, drain/roll are open-mode-only (any
                            authenticated deployment refuses them)
      gateway_open_non_loopback  allow open mode (no token table) on
                            a non-loopback bind (default False: raise)
      gateway_max_payload   per-frame payload cap bytes      (256 MiB)
      gateway_idle_timeout  close an idle connection after    (300 s)
      gateway_result_cap    hard cap on one result() wait     (600 s)
      gateway_backlog       listen() backlog                    (64)
    plus every router_*/serve_* key, forwarded to the Router when the
    gateway builds its own (`router=None`)."""

    def __init__(self, options=None, router=None,
                 host="127.0.0.1", port=0):
        o = dict(options or {})
        self.options = o
        self.host = host
        self.port = int(port)
        self.tokens = o.get("gateway_tokens")      # None => open mode
        admins = o.get("gateway_admin_tokens")
        self.admin_tokens = None if admins is None else set(admins)
        if self.tokens is None and not self._loopback(host) \
                and not o.get("gateway_open_non_loopback"):
            raise ValueError(
                f"refusing open (unauthenticated) mode on non-loopback "
                f"bind {host!r}: configure gateway_tokens, or set "
                f"gateway_open_non_loopback=True to override")
        self.max_payload = int(o.get("gateway_max_payload",
                                     P.DEFAULT_MAX_PAYLOAD))
        self.idle_timeout = float(o.get("gateway_idle_timeout", 300.0))
        self.result_cap = float(o.get("gateway_result_cap", 600.0))
        self.backlog = int(o.get("gateway_backlog", 64))
        self._tel = _telemetry.configure_from_options(o.get("telemetry"))
        self._own_router = router is None
        if router is None:
            from ..router import Router
            router = Router(o)
        self.router = router
        self._listener = None
        self._accept_thread = None
        self._conn_threads = []
        self._lock = threading.Lock()
        self._stopped = False
        self._draining = False
        self._active_connections = 0
        self.counts = {}               # plain-int mirror of counters
        self.rolls = 0

    @staticmethod
    def _loopback(host):
        # NB: "" binds INADDR_ANY — emphatically not loopback
        return host in ("localhost", "::1") \
            or str(host).startswith("127.")

    # -- accounting helpers ------------------------------------------------
    def _count(self, name, n=1):
        with self._lock:
            self.counts[name] = self.counts.get(name, 0) + n
        self._tel.counter(f"gateway.{name}").inc(n)

    def _reject(self, code):
        with self._lock:
            by = self.counts.setdefault("rejects_by_code", {})
            by[code] = by.get(code, 0) + 1
        self._tel.counter(f"gateway.rejects.{code}").inc()

    def _set_active(self, delta):
        with self._lock:
            self._active_connections += delta
            n = self._active_connections
        self._tel.gauge("gateway.active_connections").set(n)

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        """Bind + listen + start the accept loop (idempotent).  Binds
        port 0 to an ephemeral port; read `self.address` after."""
        with self._lock:
            if self._listener is not None or self._stopped:
                return self
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind((self.host, self.port))
            sock.listen(self.backlog)
            sock.settimeout(0.25)
            self._listener = sock
            self.port = sock.getsockname()[1]
        self.router.start()
        t = threading.Thread(target=self._accept_main,
                             name="serve-gateway-accept", daemon=True)
        self._accept_thread = t
        t.start()
        self._tel.event("gateway.start", host=self.host, port=self.port)
        global_toc(f"gateway listening on {self.host}:{self.port}")
        return self

    @property
    def address(self):
        return (self.host, self.port)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()

    def shutdown(self, timeout=10.0):
        """Stop accepting, close every connection, and (when the
        gateway built its own router) shut the router down too."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            listener = self._listener
            threads = list(self._conn_threads)
        if listener is not None:
            try:
                listener.close()
            except OSError:
                pass
        at = self._accept_thread
        if at is not None and at.is_alive():
            at.join(timeout)
        for t in threads:
            t.join(max(0.1, timeout / max(len(threads), 1)))
        if self._own_router:
            self.router.shutdown(timeout=timeout)
        self._tel.event("gateway.shutdown")

    def drain(self, deadline=5.0):
        """Close admission at the edge: new submit/solve frames reject
        with code "draining" while poll/result/health keep flowing, and
        the call blocks until the router's open-request table empties
        (or `deadline` passes).  Returns {"drained_open": n} with the
        number of requests still open when the deadline hit."""
        self._draining = True
        self._tel.event("gateway.drain", deadline=deadline)
        end = time.monotonic() + float(deadline)
        while time.monotonic() < end:
            with self.router._lock:
                if not self.router._open:
                    break
            time.sleep(0.02)
        with self.router._lock:
            left = len(self.router._open)
        self._count("drains")
        return {"drained_open": left}

    def roll(self):
        """Zero-downtime rolling restart of the whole replica set, one
        slot at a time through the router's replace-and-replay
        machinery (Router.roll); peers absorb traffic and in-flight
        requests survive via the idempotency table.  Emits a
        `gateway.roll_slot` event per replaced slot (the trail) and
        counts `gateway.rolls` once per completed roll."""
        t0 = time.monotonic()
        rolled = self.router.roll(
            on_slot=lambda slot, name: self._tel.event(
                "gateway.roll_slot", slot=slot, fresh=name))
        self.rolls += 1
        self._count("rolls")
        self._tel.event("gateway.rolled", replicas=rolled,
                        wall_s=round(time.monotonic() - t0, 4))
        return rolled

    # -- connection handling ----------------------------------------------
    def _accept_main(self):
        while True:
            with self._lock:
                if self._stopped:
                    return
            try:
                conn, addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return                 # listener closed under us
            t = threading.Thread(target=self._conn_main,
                                 args=(conn, addr),
                                 name="serve-gateway-conn", daemon=True)
            with self._lock:
                # prune finished handlers so a long-running gateway
                # doesn't hold one Thread object per connection EVER
                # accepted (and shutdown's join budget stays honest)
                self._conn_threads = [
                    c for c in self._conn_threads if c.is_alive()]
                self._conn_threads.append(t)
            t.start()

    def _conn_main(self, conn, addr):
        self._set_active(+1)
        conn.settimeout(self.idle_timeout)
        try:
            while not self._stopped:
                try:
                    header, payload = P.read_message(
                        conn, max_payload=self.max_payload,
                        on_bytes=lambda n: self._count("bytes_in", n))
                except P.ProtocolError as exc:
                    # a torn frame poisons the stream position: answer
                    # once, then close — the client reconnects clean
                    # (_error_frame counts the reject — exactly once)
                    self._safe_send(conn, P.pack_message(
                        self._error_frame(P.E_BAD_FRAME, str(exc))))
                    return
                except socket.timeout:
                    return             # idle connection reaped
                if header is None:
                    return             # clean EOF
                self._count("requests")
                resp_header, resp_payload = self._dispatch(
                    header, payload)
                if "seq" in header:
                    # pipelined clients (net/client.PooledClient) stamp
                    # a per-connection sequence number; echoing it lets
                    # them cross-check FIFO response matching
                    resp_header["seq"] = header["seq"]
                n = self._safe_send(
                    conn, P.pack_message(resp_header, resp_payload))
                self._count("bytes_out", n)
        except (ConnectionError, OSError):
            pass                       # peer went away mid-write
        finally:
            self._set_active(-1)
            try:
                conn.close()
            except OSError:
                pass

    def _safe_send(self, conn, data):
        try:
            conn.sendall(data)
            return len(data)
        except (ConnectionError, OSError):
            return 0

    # -- request dispatch --------------------------------------------------
    def _error_frame(self, code, message, **extra):
        self._reject(code)
        hdr = {"kind": "response", "ok": False, "error_code": code,
               "error": str(message)[:2000]}
        hdr.update(extra)
        return hdr

    def _ok_frame(self, verb, result=None, payload=b"", **extra):
        hdr = {"kind": "response", "ok": True, "verb": verb,
               "error_code": None}
        if result is not None:
            hdr["result"] = result
        hdr.update(extra)
        return hdr, payload

    def _authenticate(self, header):
        """Bearer token -> tenant id, or None when unauthorized.  With
        no token table the gateway is OPEN: every caller is tenant
        "default" (the router's quotas then see one tenant).  An admin
        token authenticates even without a tenant-table row (tenant
        "admin") — operators don't need a quota bucket to drain."""
        tok = header.get("token")
        if self.admin_tokens is not None and tok in self.admin_tokens:
            return (self.tokens or {}).get(tok, "admin")
        if self.tokens is None:
            return "default"
        return self.tokens.get(tok)

    def _is_admin(self, header):
        """May this caller drain/roll the fleet?  With an admin table:
        only its tokens.  Without one: only open mode (dev loop) —
        an authenticated multi-tenant deployment that configured no
        admin tokens has NO wire path to drain/roll (operators hold
        the Gateway object and call .drain()/.roll() directly)."""
        if self.admin_tokens is not None:
            return header.get("token") in self.admin_tokens
        return self.tokens is None

    def _dispatch(self, header, payload):
        verb = header.get("verb")
        if verb not in P.VERBS or not hasattr(self, f"_verb_{verb}"):
            # the second clause: protocol.VERBS also names replica-
            # worker verbs (peek/warm_from/shutdown) the gateway does
            # not serve — a structured reject, not E_INTERNAL
            return self._error_frame(P.E_BAD_VERB,
                                     f"unknown verb {verb!r}"), b""
        tenant = self._authenticate(header)
        if tenant is None:
            return self._error_frame(
                P.E_UNAUTHORIZED, "bearer token not recognized"), b""
        try:
            return getattr(self, f"_verb_{verb}")(header, payload,
                                                  tenant)
        except P.ProtocolError as exc:
            return self._error_frame(P.E_BAD_PAYLOAD, str(exc)), b""
        except Exception as exc:       # pragma: no cover - belt+braces
            global_toc(f"WARNING: gateway handler error: {exc!r}")
            self._tel.event("gateway.handler_error", verb=verb,
                            error=repr(exc))
            return self._error_frame(P.E_INTERNAL, repr(exc)), b""

    # -- verbs -------------------------------------------------------------
    def _submit_inner(self, header, payload, tenant):
        """Shared by submit and solve: decode + forward to the router.
        Returns (handle, reject_code_or_None)."""
        if self._draining:
            return None, P.E_DRAINING
        try:
            batch = P.decode_batch(payload)
        except Exception as exc:
            raise P.ProtocolError(f"undecodable batch payload: {exc!r}")
        h = self.router.submit(
            batch,
            options=header.get("options") or {},
            scenario_names=header.get("scenario_names"),
            deadline=header.get("deadline"),
            model=header.get("model"),
            tenant=tenant,
            priority=int(header.get("priority", 1)),
            idempotency_key=header.get("idempotency_key"))
        # structured rejects surface immediately as wire error codes
        # (resolved-at-submit requests have their result already)
        rreq = self.router._requests.get(h.id)
        if rreq is not None and rreq.done.is_set() \
                and rreq.status == REJECTED:
            code = rreq.result.get("reason", REJECTED)
            self._reject(code)
            return h, code
        return h, None

    def _verb_submit(self, header, payload, tenant):
        h, code = self._submit_inner(header, payload, tenant)
        if h is None:
            return self._error_frame(code, "gateway is draining"), b""
        result = {"handle": h.id}
        if code is not None:
            result["rejected"] = code
        return self._ok_frame("submit", result)

    def _verb_poll(self, header, payload, tenant):
        h = RouterHandle(int(header.get("handle", -1)))
        status = self.router.poll(h)
        if status == "unknown":
            return self._error_frame(
                P.E_UNKNOWN_HANDLE, f"no request {h.id}"), b""
        return self._ok_frame("poll", {"handle": h.id,
                                       "state": status})

    def _verb_result(self, header, payload, tenant):
        h = RouterHandle(int(header.get("handle", -1)))
        if self.router._requests.get(h.id) is None:
            return self._error_frame(
                P.E_UNKNOWN_HANDLE, f"no request {h.id}"), b""
        timeout = header.get("timeout")
        timeout = self.result_cap if timeout is None \
            else min(float(timeout), self.result_cap)
        res = self.router.result(h, timeout=timeout)
        return self._result_frame("result", res)

    def _verb_solve(self, header, payload, tenant):
        h, code = self._submit_inner(header, payload, tenant)
        if h is None:
            return self._error_frame(code, "gateway is draining"), b""
        timeout = header.get("timeout")
        timeout = self.result_cap if timeout is None \
            else min(float(timeout), self.result_cap)
        res = self.router.result(h, timeout=timeout)
        return self._result_frame("solve", res, handle=h.id)

    def _result_frame(self, verb, res, **extra):
        """A terminal result as a wire frame: non-ok statuses carry
        their reject/failure reason as `error_code` (counted), but the
        frame is still ok=True — the REQUEST failed, not the wire."""
        code = None
        if res.get("status") != "ok":
            code = res.get("reason", res.get("status"))
            code = "quarantined" if isinstance(code, str) \
                and code.startswith("quarantined") else code
            self._reject(str(code))
        scalars, payload = P.encode_result(res)
        hdr, payload = self._ok_frame(verb, scalars, payload,
                                      **extra)
        hdr["error_code"] = None if code is None else str(code)
        return hdr, payload

    def _verb_health(self, header, payload, tenant):
        stats = P.jsonable(self.router.stats())
        stats["gateway"] = {
            "active_connections": self._active_connections,
            "draining": self._draining,
            "rolls": self.rolls,
            "counts": P.jsonable(dict(self.counts)),
        }
        return self._ok_frame("health", stats)

    def _verb_drain(self, header, payload, tenant):
        if not self._is_admin(header):
            return self._error_frame(
                P.E_UNAUTHORIZED,
                "drain requires a gateway_admin_tokens token"), b""
        out = self.drain(deadline=float(header.get("deadline", 5.0)))
        return self._ok_frame("drain", out)

    def _verb_roll(self, header, payload, tenant):
        if not self._is_admin(header):
            return self._error_frame(
                P.E_UNAUTHORIZED,
                "roll requires a gateway_admin_tokens token"), b""
        rolled = self.roll()
        return self._ok_frame("roll", {"rolled": rolled})

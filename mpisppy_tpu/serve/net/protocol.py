"""Wire protocol for the serve network edge: versioned length-prefixed
frames with the `MTSHARD1`-style magic/format discipline
(streaming/store.py), spoken by `gateway.Gateway` and `client.Client`.

One MESSAGE on the wire is

    bytes 0..8     magic  b"MTNETP1\\0"
    bytes 8..12    uint32 header length H (little-endian)
    bytes 12..12+H header JSON: proto, kind ("request"/"response"),
                   verb, payload_len, payload_crc32, plus per-verb
                   fields (token, options, handle, result, ...)
    rest           payload bytes (payload_len long): an .npz holding
                   the request's ScenarioBatch (submit/solve) or the
                   result's array fields (result/solve responses);
                   empty for array-free messages

and `read_message` re-validates ALL of it on every read — magic,
header JSON, declared vs received payload length, CRC32 over the
payload bytes — mirroring the shard store's `read_checked` contract:
a torn, foreign, or corrupted frame raises `ProtocolError`, never a
partially-decoded message.

Verbs: ``submit / poll / result / solve / health / drain / roll``.
Error codes are the union of gateway-level frame/auth failures and the
router's structured reject reasons (the gateway maps one onto the
other — see ERROR_CODES and doc/src/serve.md's error-code matrix).

Trust boundary: payload bytes come from the NETWORK, so — unlike the
shard store, whose npz codec reads trusted on-disk data — this module
NEVER unpickles them.  Every `np.load` here passes
`allow_pickle=False`; the object-dtype fields a ScenarioBatch payload
needs (name tuples, `model_meta`) travel as a JSON sidecar plus a
pool of plain numeric arrays (`_meta_encode`/`_meta_decode`), so a
crafted pickle inside a frame is a decode error, not code execution.

Layering (AST + fresh-interpreter guarded in
tests/test_net_gateway.py): this module never imports jax or mpmd at
module level — batch (de)serialization reuses the shard store's
npz payload helpers, which import `ir` lazily inside the call.
"""

from __future__ import annotations

import io
import json
import struct
import zlib

import numpy as np

MAGIC = b"MTNETP1\0"
PROTO_FORMAT = 1

# hard caps: a single corrupt length field must not make the reader
# allocate unbounded memory
MAX_HEADER_BYTES = 1 << 20          # 1 MiB of JSON is already absurd
DEFAULT_MAX_PAYLOAD = 1 << 28       # 256 MiB per frame

VERBS = ("submit", "poll", "result", "solve", "health", "drain", "roll",
         # replica-worker verbs (serve/procworker.py): the gateway
         # rejects these with E_BAD_VERB — it has no handlers for them
         "peek", "peek_many", "statuses", "warm_from", "shutdown")

# -- error-code matrix (doc/src/serve.md) ----------------------------------
# gateway-level codes: the request never reached the router
E_BAD_FRAME = "bad_frame"            # torn/foreign/corrupt frame
E_BAD_VERB = "bad_verb"              # verb outside VERBS
E_BAD_PAYLOAD = "bad_payload"        # frame ok, batch/npz undecodable
E_UNAUTHORIZED = "unauthorized"      # bearer token unknown
E_UNKNOWN_HANDLE = "unknown_handle"  # poll/result for a foreign id
E_PAYLOAD_TOO_LARGE = "payload_too_large"
E_DRAINING = "draining"              # gateway OR replica drain closed
                                     # admission (one code, both layers)
E_INTERNAL = "internal"              # handler raised (bug, not client)

#: every wire error code -> which layer rejects, and why.  Router codes
#: are the structured reject/failure reasons of serve/router.py and
#: serve/service.py, passed through verbatim as `error_code` so a
#: client switch()es on ONE namespace.
ERROR_CODES = {
    E_BAD_FRAME: "gateway: magic/length/CRC/JSON validation failed",
    E_BAD_VERB: "gateway: verb not in protocol.VERBS",
    E_BAD_PAYLOAD: "gateway: payload npz undecodable",
    E_UNAUTHORIZED: "gateway: bearer token not in gateway_tokens",
    E_UNKNOWN_HANDLE: "gateway: handle id this router never issued",
    E_PAYLOAD_TOO_LARGE: "gateway: payload exceeds gateway_max_payload",
    E_DRAINING: "gateway drain() or a replica drain closed admission",
    E_INTERNAL: "gateway: handler error (server-side bug)",
    "over_quota": "router: tenant token bucket empty",
    "brownout_shed": "router: brownout level 3 shed low priority",
    "shutdown": "router/service: shut down",
    "queue_full": "service: bounded queue at capacity",
    "max_inflight": "service: inflight admission cap",
    "service_failed": "service: restart budget spent, failed closed",
    "drained": "service: request was drained to a checkpoint",
    "quarantined": "router: poison budget spent on this request",
    "timeout": "deadline exceeded (queued/dispatch/iteration/wait)",
    "failed": "solver/worker failure after the attempt budget",
}


class ProtocolError(RuntimeError):
    """A frame failed validation (torn, foreign, corrupt, oversized)."""


# -- framing ---------------------------------------------------------------

def pack_message(header, payload=b""):
    """One wire message's byte image: magic + header JSON + payload,
    with payload_len and an honest CRC32 stamped into the header."""
    hdr = dict(header)
    hdr["proto"] = PROTO_FORMAT
    hdr["payload_len"] = len(payload)
    hdr["payload_crc32"] = zlib.crc32(payload) & 0xFFFFFFFF
    hjson = json.dumps(hdr).encode("utf-8")
    if len(hjson) > MAX_HEADER_BYTES:
        raise ProtocolError(f"header too large ({len(hjson)} bytes)")
    return MAGIC + struct.pack("<I", len(hjson)) + hjson + payload


def recv_exact(sock, n):
    """Read exactly n bytes from a socket; raises ProtocolError on a
    mid-message EOF (a clean EOF at a message boundary is the caller's
    to detect via recv_opt)."""
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise ProtocolError(
                f"connection closed mid-message ({got}/{n} bytes)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def read_message(sock, max_payload=DEFAULT_MAX_PAYLOAD, on_bytes=None):
    """Read + validate one message from a socket.  Returns
    (header_dict, payload_bytes); returns (None, None) on a clean EOF
    at a message boundary; raises ProtocolError on anything torn,
    foreign, oversized, or failing CRC.  `on_bytes` (if given) is
    called with the exact frame size on success — the gateway's
    bytes_in accounting tap."""
    first = sock.recv(1)
    if not first:
        return None, None
    head = first + recv_exact(sock, len(MAGIC) + 4 - 1)
    if head[:len(MAGIC)] != MAGIC:
        raise ProtocolError("bad magic (foreign or torn stream)")
    (hlen,) = struct.unpack("<I", head[len(MAGIC):])
    if hlen > MAX_HEADER_BYTES:
        raise ProtocolError(f"header length {hlen} exceeds cap")
    try:
        header = json.loads(recv_exact(sock, hlen).decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise ProtocolError(f"unparseable header JSON: {e}")
    if int(header.get("proto", -1)) != PROTO_FORMAT:
        raise ProtocolError(
            f"unsupported protocol version {header.get('proto')!r}")
    plen = int(header.get("payload_len", 0))
    if plen < 0 or plen > max_payload:
        raise ProtocolError(
            f"payload length {plen} exceeds cap {max_payload}")
    payload = recv_exact(sock, plen) if plen else b""
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    if crc != int(header.get("payload_crc32", -1)):
        raise ProtocolError(
            f"payload CRC mismatch: computed {crc:#010x}, header "
            f"{int(header.get('payload_crc32', -1)):#010x}")
    if on_bytes is not None:
        on_bytes(len(MAGIC) + 4 + hlen + plen)
    return header, payload


def write_message(sock, header, payload=b""):
    """pack_message + sendall; returns the bytes written (the
    gateway's bytes_out accounting input)."""
    data = pack_message(header, payload)
    sock.sendall(data)
    return len(data)


# -- ScenarioBatch payloads ------------------------------------------------
#
# The shard store's payload dict holds object-dtype arrays (the name
# tuples, and model_meta — an arbitrary pytree of dicts/tuples/numpy
# arrays).  Saved as-is those would need allow_pickle=True on load,
# which at a network trust boundary means arbitrary code execution.
# The wire codec therefore splits them: strings and structure go into
# a JSON sidecar (stored as a uint8 array under _WIRE_JSON), numeric
# leaves of model_meta go into the npz array pool under reserved
# _WIRE_META_ARR keys, and decode reassembles with allow_pickle=False.

_WIRE_JSON = "_wire_json"
_WIRE_META_ARR = "_wire_meta_arr_"
_NAME_FIELDS = ("tree_nonant_names", "tree_scen_names", "var_names")
_TAG_ND = "__nd__"
_TAG_TUPLE = "__tuple__"


def _meta_encode(value, arrays):
    """model_meta pytree -> JSON-safe tagged tree.  ndarrays move into
    `arrays` under reserved npz keys (bit-exact); tuples are tagged so
    decode restores tuple-ness (pytree structure survives).  Anything
    not JSON/array-representable is a ProtocolError — the wire carries
    data, never pickled code."""
    if isinstance(value, np.ndarray):
        key = f"{_WIRE_META_ARR}{len(arrays)}"
        arrays[key] = value
        return {_TAG_ND: key}
    if isinstance(value, np.generic):
        return value.item()
    if isinstance(value, dict):
        return {str(k): _meta_encode(v, arrays) for k, v in value.items()}
    if isinstance(value, tuple):
        return {_TAG_TUPLE: [_meta_encode(v, arrays) for v in value]}
    if isinstance(value, list):
        return [_meta_encode(v, arrays) for v in value]
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    raise ProtocolError(
        f"model_meta value of type {type(value).__name__} is not "
        f"wire-encodable (JSON scalars, lists, tuples, dicts and "
        f"numpy arrays only)")


def _meta_decode(node, z):
    """Inverse of _meta_encode against the npz array pool `z`."""
    if isinstance(node, dict):
        if set(node) == {_TAG_ND}:
            key = node[_TAG_ND]
            if not (isinstance(key, str)
                    and key.startswith(_WIRE_META_ARR)):
                raise ProtocolError(f"bad meta array reference {key!r}")
            return np.asarray(z[key])
        if set(node) == {_TAG_TUPLE}:
            return tuple(_meta_decode(v, z) for v in node[_TAG_TUPLE])
        return {k: _meta_decode(v, z) for k, v in node.items()}
    if isinstance(node, list):
        return [_meta_decode(v, z) for v in node]
    return node


def encode_batch(batch):
    """ScenarioBatch -> npz bytes, reusing the shard store's payload
    codec so the A representation (dense / shared / SplitA) survives
    the wire exactly like it survives disk — minus its object arrays,
    which are re-encoded pickle-free (see the section comment)."""
    from ...streaming.store import _batch_payload
    raw = _batch_payload(batch)
    raw.pop("model_meta", None)        # re-encoded from batch below
    arrays, names = {}, {}
    for k, v in raw.items():
        a = np.asarray(v)
        if a.dtype == object:          # the *_names string tuples
            names[k] = [str(s) for s in a.tolist()]
        else:
            arrays[k] = a
    side = {"names": names}
    if batch.model_meta is not None:
        side["model_meta"] = _meta_encode(batch.model_meta, arrays)
    arrays[_WIRE_JSON] = np.frombuffer(
        json.dumps(side).encode("utf-8"), dtype=np.uint8)
    buf = io.BytesIO()
    # uncompressed on purpose: payloads are a few KiB and zlib costs
    # ~40% of the encode on the submit path, which a process-replica
    # parent pays once per request on the loopback wire
    np.savez(buf, **arrays)
    return buf.getvalue()


def decode_batch(data):
    """npz bytes -> ScenarioBatch (inverse of encode_batch).  Network
    bytes: `allow_pickle=False`, so a crafted object array raises
    instead of executing."""
    from ...streaming.store import _batch_from_payload
    z = np.load(io.BytesIO(data), allow_pickle=False)
    payload = {k: np.asarray(z[k]) for k in z.files
               if k != _WIRE_JSON and not k.startswith(_WIRE_META_ARR)}
    if _WIRE_JSON not in z.files:
        raise ProtocolError("batch payload missing wire sidecar")
    side = json.loads(
        np.asarray(z[_WIRE_JSON]).tobytes().decode("utf-8"))
    for k, v in (side.get("names") or {}).items():
        if k not in _NAME_FIELDS:
            raise ProtocolError(f"unexpected sidecar name field {k!r}")
        payload[k] = np.array([str(s) for s in v], dtype=object)
    if "model_meta" in side:
        meta = np.empty(1, dtype=object)
        meta[0] = _meta_decode(side["model_meta"], z)
        payload["model_meta"] = meta
    return _batch_from_payload(payload)


# -- result dicts ----------------------------------------------------------

def jsonable(value):
    """Recursively convert a structured result value to JSON-safe form:
    numpy scalars -> Python scalars, tuples -> lists.  Arrays are NOT
    accepted here — encode_result routes them to the npz payload."""
    if isinstance(value, np.generic):
        return value.item()
    if isinstance(value, np.ndarray):
        raise TypeError("arrays belong in the payload, not the header")
    if isinstance(value, dict):
        return {str(k): jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple, set)):
        return [jsonable(v) for v in value]
    return value


def encode_result(res):
    """Split one structured result dict into (json_header_result,
    payload_bytes): ndarray values move to an npz payload (bit-exact),
    everything else is JSON — CPython's shortest-repr float round-trip
    keeps scalar doubles bitwise too, which is what lets a wire result
    stay bitwise-equal to the in-process one."""
    scalars, arrays = {}, {}
    for k, v in dict(res).items():
        if isinstance(v, np.ndarray):
            if v.dtype == object:      # would need pickle on the wire
                raise TypeError(
                    f"result field {k!r} is an object-dtype array; "
                    f"only numeric/string arrays are wire-encodable")
            arrays[k] = v
        else:
            scalars[k] = jsonable(v)
    payload = b""
    if arrays:
        buf = io.BytesIO()
        # uncompressed for the same reason as encode_batch: the codec
        # CPU, not the byte count, is what the wire path pays for
        np.savez(buf, **arrays)
        payload = buf.getvalue()
    scalars["_array_keys"] = sorted(arrays)
    return scalars, payload


def decode_result(header_result, payload):
    """Inverse of encode_result.  Network bytes: `allow_pickle=False`
    (a malicious or confused peer gets a decode error, not code
    execution in the client)."""
    res = dict(header_result)
    keys = res.pop("_array_keys", [])
    if keys:
        z = np.load(io.BytesIO(payload), allow_pickle=False)
        for k in keys:
            res[k] = np.asarray(z[k])
    return res
